"""Fused ops (ref: python/paddle/incubate/nn/functional/*).

The reference hand-fuses these into single CUDA kernels; on TPU the
same fusion happens in XLA, so each "fused_*" here is the composed jnp
expression (single dispatch under jit) routed through the pallas fast
paths where one exists (rms_norm, flash attention).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def fused_matmul_bias(x, y, bias=None, transpose_x=False, transpose_y=False):
    """ref: incubate/nn/functional/fused_matmul_bias.py."""
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2)
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2)
    out = x @ y
    return out if bias is None else out + bias


fused_linear = fused_matmul_bias


def swiglu(x, y=None):
    """ref: incubate/nn/functional/swiglu.py — silu(x) * y; single-arg
    form splits the last dim in half."""
    if y is None:
        x, y = jnp.split(x, 2, axis=-1)
    return jax.nn.silu(x) * y


def _flatten_norm(x, begin_norm_axis):
    """Paddle norm semantics: normalize over ALL trailing axes from
    begin_norm_axis; returns (flattened x, restore shape) — a no-op view
    for the default last-axis case."""
    axis = begin_norm_axis % x.ndim if begin_norm_axis >= 0 else \
        x.ndim + begin_norm_axis
    if axis == x.ndim - 1:
        return x, None
    shape = x.shape
    return x.reshape(shape[:axis] + (-1,)), shape


def fused_rms_norm(x, norm_weight=None, norm_bias=None, epsilon=1e-6,
                   begin_norm_axis=-1, **kw):
    """ref: fused_rms_norm.py — dispatches to the pallas kernel on TPU."""
    from ...ops import rms_norm as _rms

    xf, shape = _flatten_norm(x, begin_norm_axis)
    out = _rms(xf, norm_weight.reshape(-1) if norm_weight is not None
               else None, epsilon)
    if norm_bias is not None:
        out = out + norm_bias.reshape(-1)
    return out if shape is None else out.reshape(shape)


def fused_layer_norm(x, norm_weight=None, norm_bias=None, epsilon=1e-5,
                     begin_norm_axis=-1, residual=None, **kw):
    """ref: fused_layer_norm.py (residual-add + LN)."""
    from ...nn.functional.norm import layer_norm

    if residual is not None:
        x = x + residual
    xf, shape = _flatten_norm(x, begin_norm_axis)
    out = layer_norm(xf, xf.shape[-1],
                     norm_weight.reshape(-1) if norm_weight is not None
                     else None,
                     norm_bias.reshape(-1) if norm_bias is not None
                     else None, epsilon)
    return out if shape is None else out.reshape(shape)


def fused_dropout_add(x, y, p=0.0, training=True, mode='upscale_in_train',
                      rng_key=None):
    """ref: fused_dropout_add.py — dropout(x) + y."""
    if p == 0.0:
        return x + y
    if not training:
        # downscale_in_infer: train keeps raw activations, infer scales
        if mode == 'downscale_in_infer':
            x = x * (1 - p)
        return x + y
    from ...framework import random as random_mod

    key = rng_key if rng_key is not None else random_mod.split_key()
    keep = jax.random.bernoulli(key, 1 - p, x.shape)
    if mode == 'upscale_in_train':
        x = jnp.where(keep, x / (1 - p), 0.0)
    else:
        x = jnp.where(keep, x, 0.0)
    return x + y


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None, use_neox_rotary_style=True):
    """ref: fused_rotary_position_embedding.py.

    q/k/v: (B, S, H, D). When sin/cos are None they are computed from
    positions with the default 10000 theta. Accepts the reference's
    full-head-dim cos/sin layout ((1, S, 1, D), both halves duplicated)
    or the compact (S, D/2)/(B, S, D/2) tables. use_neox_rotary_style
    selects rotate-half (True) vs GPT-J interleaved pairs (False).
    Returns rotated (q, k, v) — v passes through (rope only mixes q/k,
    the reference accepts it for API parity).
    """
    from ...models.llama import apply_rotary, rope_cos_sin

    B, S, _, D = q.shape
    if cos is None or sin is None:
        if position_ids is None:
            position_ids = jnp.broadcast_to(jnp.arange(S)[None], (B, S))
        cos, sin = rope_cos_sin(position_ids, D, dtype=q.dtype)
    else:
        def canon(t):
            t = jnp.asarray(t)
            if t.ndim == 4:                # reference layout (B|1, S, 1, D)
                t = t[:, :, 0, :]
            if t.ndim == 2:                # (S, Dx) → (1, S, Dx)
                t = t[None]
            if t.shape[-1] == D:
                # full-head-dim table: halves duplicated (neox) or
                # pairwise-duplicated (interleaved)
                t = t[..., ::2] if not use_neox_rotary_style else \
                    t[..., :D // 2]
            if position_ids is not None:
                # gather table rows at the requested positions (decode
                # steps pass the full-length table + position_ids=[[t]])
                t = jnp.broadcast_to(t, (B,) + t.shape[1:])
                t = jnp.take_along_axis(
                    t, jnp.asarray(position_ids)[:, :, None], axis=1)
            return jnp.broadcast_to(t, (B, S, D // 2))

        cos, sin = canon(cos), canon(sin)

    if use_neox_rotary_style:
        rot = lambda x: apply_rotary(x, cos, sin)
    else:
        # GPT-J style: rotate adjacent pairs (2i, 2i+1)
        c = cos[:, :, None, :]
        s = sin[:, :, None, :]

        def rot(x):
            xp = x.reshape(*x.shape[:-1], D // 2, 2)
            xe, xo = xp[..., 0], xp[..., 1]
            re = xe * c - xo * s
            ro = xo * c + xe * s
            return jnp.stack([re, ro], -1).reshape(x.shape).astype(x.dtype)

    out_q = rot(q)
    out_k = rot(k) if k is not None else None
    return out_q, out_k, v


def fused_multi_head_attention(x, qkv_weight, linear_weight, pre_layer_norm=False,
                               pre_ln_scale=None, pre_ln_bias=None,
                               ln_scale=None, ln_bias=None, pre_ln_epsilon=1e-5,
                               qkv_bias=None, linear_bias=None, cache_kv=None,
                               attn_mask=None, dropout_rate=0.0,
                               attn_dropout_rate=0.0, ln_epsilon=1e-5,
                               training=True, num_heads=None):
    """ref: fused_transformer.py::fused_multi_head_attention — packed-QKV
    self-attention block with residual + layer norm, flash-attention fast
    path on TPU.

    x: (B, S, E); qkv_weight: (3, num_heads, head_dim, E) (reference
    layout); linear_weight: (E, E).
    """
    from ...nn.functional.attention import scaled_dot_product_attention
    from ...nn.functional.norm import layer_norm

    B, S, E = x.shape
    three, H, D, _ = qkv_weight.shape
    assert three == 3 and H * D == E

    residual = x
    if pre_layer_norm:
        x = layer_norm(x, E, pre_ln_scale, pre_ln_bias, pre_ln_epsilon)
    qkv = jnp.einsum('bse,thde->bsthd', x, qkv_weight)     # (B,S,3,H,D)
    if qkv_bias is not None:
        qkv = qkv + qkv_bias.reshape(3, H, D)[None, None]
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]     # (B,S,H,D)
    new_cache = None
    if cache_kv is not None:
        # ref layout (2, B, H, S_past, D): append, attend over the
        # full prefix, and return the grown cache alongside the output
        past_k = jnp.swapaxes(cache_kv[0], 1, 2)           # (B,S_past,H,D)
        past_v = jnp.swapaxes(cache_kv[1], 1, 2)
        k = jnp.concatenate([past_k, k], axis=1)
        v = jnp.concatenate([past_v, v], axis=1)
        new_cache = jnp.stack([jnp.swapaxes(k, 1, 2),
                               jnp.swapaxes(v, 1, 2)])
    out = scaled_dot_product_attention(
        q, k, v, attn_mask=attn_mask, dropout_p=attn_dropout_rate,
        training=training)
    out = out.reshape(B, S, E) @ linear_weight
    if linear_bias is not None:
        out = out + linear_bias
    if dropout_rate:
        out = fused_dropout_add(out, residual, dropout_rate, training)
    else:
        out = out + residual
    if not pre_layer_norm:
        out = layer_norm(out, E, ln_scale, ln_bias, ln_epsilon)
    if new_cache is not None:
        return out, new_cache
    return out


def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None,
                      linear2_bias=None, ln1_scale=None, ln1_bias=None,
                      ln2_scale=None, ln2_bias=None, dropout1_rate=0.5,
                      dropout2_rate=0.5, activation='relu',
                      ln1_epsilon=1e-5, ln2_epsilon=1e-5,
                      pre_layer_norm=False, training=True):
    """ref: fused_transformer.py::fused_feedforward — LN + MLP + residual."""
    from ...nn.functional.norm import layer_norm

    E = x.shape[-1]
    residual = x
    if pre_layer_norm:
        x = layer_norm(x, E, ln1_scale, ln1_bias, ln1_epsilon)
    act = {'relu': jax.nn.relu, 'gelu': jax.nn.gelu,
           'silu': jax.nn.silu}[activation]
    h = act(fused_matmul_bias(x, linear1_weight, linear1_bias))
    if dropout1_rate and training:
        h = fused_dropout_add(h, jnp.zeros_like(h), dropout1_rate, training)
    h = fused_matmul_bias(h, linear2_weight, linear2_bias)
    out = fused_dropout_add(h, residual, dropout2_rate, training) \
        if dropout2_rate and training else h + residual
    if not pre_layer_norm:
        out = layer_norm(out, E, ln2_scale, ln2_bias, ln2_epsilon)
    return out


def fused_bias_act(x, bias=None, act_method='gelu'):
    """ref: fused_bias_act.py."""
    if bias is not None:
        x = x + bias
    return {'gelu': jax.nn.gelu, 'relu': jax.nn.relu, 'silu': jax.nn.silu,
            'swiglu': swiglu}[act_method](x)


# ---------------------------------------------------------------------------
# Serving attention primitives (paged + masked decode)
# ---------------------------------------------------------------------------

def _split_qkv(x, num_heads, num_kv_heads, head_dim):
    """(T, (Hq + 2*Hkv) * D) fused qkv -> q (T, Hq, D), k/v (T, Hkv, D)."""
    q_sz = num_heads * head_dim
    kv_sz = num_kv_heads * head_dim
    q = x[..., :q_sz].reshape(*x.shape[:-1], num_heads, head_dim)
    k = x[..., q_sz:q_sz + kv_sz].reshape(*x.shape[:-1], num_kv_heads,
                                          head_dim)
    v = x[..., q_sz + kv_sz:].reshape(*x.shape[:-1], num_kv_heads, head_dim)
    return q, k, v


def _rope_rows(q, k, cos, sin, neox):
    """Rotate one row per sequence: q/k (N, H, D); cos/sin (N, D/2).
    neox=True -> rotate-half; False -> GPT-J interleaved pairs (the
    reference default), mirroring fused_rotary_position_embedding."""
    if neox:
        from ...models.llama import apply_rotary

        return (apply_rotary(q[:, None], cos[:, None], sin[:, None])[:, 0],
                apply_rotary(k[:, None], cos[:, None], sin[:, None])[:, 0])

    def rot(x):
        D = x.shape[-1]
        xp = x.reshape(*x.shape[:-1], D // 2, 2)
        xe, xo = xp[..., 0], xp[..., 1]
        c, sn = cos[:, None, :], sin[:, None, :]
        return jnp.stack([xe * c - xo * sn, xo * c + xe * sn],
                         -1).reshape(x.shape).astype(x.dtype)

    return rot(q), rot(k)


def masked_multihead_attention(x, cache_kv=None, bias=None, src_mask=None,
                               cum_offsets=None, sequence_lengths=None,
                               rotary_tensor=None, beam_cache_offset=None,
                               qkv_out_scale=None, out_shift=None,
                               out_smooth=None, seq_len=1, rotary_emb_dims=0,
                               use_neox_rotary_style=False,
                               compute_dtype='default', out_scale=-1,
                               quant_round_type=1, quant_max_bound=127.0,
                               quant_min_bound=-127.0):
    """Single-token decode MHA over a contiguous cache (ref:
    python/paddle/incubate/nn/functional/masked_multihead_attention.py:74
    — the reference generation loop's fused decode attention).

    x: (B, 3*H*D) fused qkv for ONE new token per row; cache_kv:
    (2, B, H, max_seq, D); sequence_lengths: (B, 1) current per-row
    lengths (write position). rotary_tensor: optional (2, B, S, D/2)
    cos/sin stack applied to q/k at each row's position. Returns
    (out (B, H*D), cache_kv_out).

    TPU-native: the cache row is attended by the paged decode kernel
    (ops/pallas/paged_attention.py, one page per row) when the row fits
    VMEM; the XLA masked path otherwise. The reference's smooth-quant
    int8 GEMM pipeline knobs (qkv_out_scale / out_shift / out_smooth /
    int32 x / out_scale) are CUDA-pipeline-specific and rejected.
    """
    for name, v_ in (('qkv_out_scale', qkv_out_scale),
                     ('out_shift', out_shift), ('out_smooth', out_smooth),
                     ('beam_cache_offset', beam_cache_offset)):
        if v_ is not None:
            raise NotImplementedError(
                f'{name} belongs to the reference CUDA smooth-quant/beam '
                f'pipeline; quantize with paddle_tpu.quantization + '
                f'kv_cache_int8 instead')
    if out_scale != -1:
        raise NotImplementedError('out_scale quantized output unsupported')
    if cache_kv is None:
        raise ValueError(
            'masked_multihead_attention requires cache_kv (the '
            '(2, B, H, max_seq, D) decode cache written at prefill) — '
            'there is no cache-less decode step')
    _, B, H, S, D = cache_kv.shape
    if cache_kv.dtype == jnp.int8:
        raise NotImplementedError(
            'int8 cache_kv is not supported by masked_multihead_attention '
            '(no scale inputs in this API) — use block_multihead_attention '
            'with static dequant scales, or the model-level '
            'generate(kv_cache_int8=True) path')
    q, k, v = _split_qkv(x, H, H, D)                     # (B, H, D) each
    if bias is not None:
        b3 = jnp.asarray(bias).reshape(3, H, D)
        q, k, v = q + b3[0], k + b3[1], v + b3[2]
    if sequence_lengths is None:
        raise ValueError(
            'sequence_lengths is required (per-row cache write position)')
    if not isinstance(sequence_lengths, jax.core.Tracer):
        import numpy as _np

        if (_np.reshape(_np.asarray(sequence_lengths), (-1,)) >= S).any():
            raise ValueError(
                f'cache is full (sequence_length >= max_seq {S}): the new '
                f'token has nowhere to land — grow the cache (JAX would '
                f'silently drop the out-of-bounds write)')
    lens = jnp.reshape(jnp.asarray(sequence_lengths, jnp.int32), (-1,))
    if rotary_tensor is not None:
        rt = jnp.asarray(rotary_tensor)
        if rt.ndim != 4 or rt.shape[0] != 2:
            raise NotImplementedError(
                'rotary_tensor must be a (2, B, S, D/2) cos/sin stack '
                '(the reference CUDA layouts are kernel-internal); or '
                'pre-rotate q/k and pass rotary_tensor=None')
        pos = lens[:, None]                              # (B, 1)
        cos = jnp.take_along_axis(rt[0], pos[:, :, None], axis=1)[:, 0]
        sin = jnp.take_along_axis(rt[1], pos[:, :, None], axis=1)[:, 0]
        q, k = _rope_rows(q, k, cos, sin, use_neox_rotary_style)

    ck, cv = cache_kv[0], cache_kv[1]                    # (B, H, S, D)
    rows = jnp.arange(B)
    ck = ck.at[rows, :, lens].set(k.astype(ck.dtype))
    cv = cv.at[rows, :, lens].set(v.astype(cv.dtype))
    counts = lens + 1

    out = None
    if src_mask is None:
        from ...ops import use_pallas

        if use_pallas() and D % 8 == 0:
            try:
                # head-major contiguous variant of the paged kernel:
                # streams any cache length blockwise, no transpose
                from ...ops.pallas.paged_attention import (
                    decode_attention_headmajor)

                out = decode_attention_headmajor(
                    q[:, None], ck, cv, counts)[:, 0]
            except Exception as e:  # noqa: BLE001
                from ...ops import pallas_failed

                pallas_failed('paged_attention', e)
    if out is None:
        logits = jnp.einsum('bhd,bhsd->bhs', q.astype(jnp.float32),
                            ck.astype(jnp.float32)) / (D ** 0.5)
        mask = jnp.arange(S)[None, None, :] < counts[:, None, None]
        logits = jnp.where(mask, logits, -1e30)
        if src_mask is not None:
            logits = logits + jnp.asarray(src_mask,
                                          jnp.float32).reshape(B, 1, -1)
        p = jax.nn.softmax(logits, axis=-1)
        out = jnp.einsum('bhs,bhsd->bhd', p,
                         cv.astype(jnp.float32)).astype(x.dtype)
    return out.reshape(B, H * D), jnp.stack([ck, cv])


def block_multihead_attention(
        qkv, key_cache, value_cache, seq_lens_encoder, seq_lens_decoder,
        seq_lens_this_time, padding_offsets=None, cum_offsets=None,
        cu_seqlens_q=None, cu_seqlens_k=None, block_tables=None,
        pre_key_cache=None, pre_value_cache=None, cache_k_quant_scales=None,
        cache_v_quant_scales=None, cache_k_dequant_scales=None,
        cache_v_dequant_scales=None, qkv_out_scale=None, qkv_bias=None,
        out_shift=None, out_smooth=None, max_enc_len_this_time=None,
        max_dec_len_this_time=None, rope_emb=None, mask=None, tgt_mask=None,
        max_seq_len=-1, block_size=64, use_neox_style=False,
        use_dynamic_cachekv_quant=False, quant_round_type=1,
        quant_max_bound=127.0, quant_min_bound=-127.0, out_scale=-1,
        compute_dtype='default', num_heads=None, num_kv_heads=None):
    """Paged-KV serving attention (ref:
    python/paddle/incubate/nn/functional/block_multihead_attention.py:30).

    The serving loop's two phases are both supported, per call:
      - PREFILL (seq_lens_encoder > 0): the unpadded token stream
        attends causally within each sequence (varlen segment-id flash
        on TPU) and its K/V rows are scattered into the paged cache via
        block_tables.
      - DECODE (seq_lens_decoder > 0, one token per row): the new K/V
        row lands in its page and the fused paged kernel streams exactly
        the pages the row occupies (ops/pallas/paged_attention.py — the
        block table drives the BlockSpec index map via scalar prefetch).

    Layouts follow the reference: qkv (token_num, (Hq+2*Hkv)*D);
    key_cache/value_cache (max_block_num, Hkv, block_size, D);
    block_tables (B, MAXB); cu_seqlens_q (B+1,) prefix sums of this
    call's tokens. STATIC cache-KV int8 is supported via
    cache_k/v_dequant_scales of shape (Hkv,) or (Hkv, D) with int8
    caches (quantization on write uses the reciprocal). Mode must be
    host-decidable (concrete seq_lens): mixed prefill+decode in one call
    and dynamic per-batch cache quant are rejected with guidance.
    Returns (out, qkv, key_cache, value_cache).
    """
    import numpy as _np

    for name, v_ in (('qkv_out_scale', qkv_out_scale),
                     ('out_shift', out_shift), ('out_smooth', out_smooth),
                     ('pre_key_cache', pre_key_cache),
                     ('pre_value_cache', pre_value_cache)):
        if v_ is not None:
            raise NotImplementedError(
                f'{name} is part of the reference CUDA smooth-quant/'
                f'pre-cache pipeline and is not supported on TPU')
    if use_dynamic_cachekv_quant:
        raise NotImplementedError(
            'dynamic cache-KV quant (per-batch scales) is not supported: '
            'use static dequant scales, or the model-level '
            'generate(kv_cache_int8=True) path which calibrates at '
            'prefill')
    if out_scale != -1:
        raise NotImplementedError('quantized fmha output unsupported')
    if isinstance(seq_lens_encoder, jax.core.Tracer) or isinstance(
            seq_lens_decoder, jax.core.Tracer):
        raise NotImplementedError(
            'block_multihead_attention needs host-known sequence lengths '
            'to pick the prefill/decode phase (the serving loop knows '
            'its phase; call it with concrete seq_lens)')

    NB, Hkv, BS, D = key_cache.shape
    if block_size != BS:
        raise ValueError(f'block_size={block_size} != cache page size {BS}')
    enc = _np.reshape(_np.asarray(seq_lens_encoder), (-1,))
    dec = _np.reshape(_np.asarray(seq_lens_decoder), (-1,))
    B = enc.shape[0]
    if num_kv_heads is None:
        num_kv_heads = Hkv
    if num_heads is None:
        num_heads = qkv.shape[-1] // D - 2 * num_kv_heads
    Hq = num_heads
    q, k, v = _split_qkv(qkv, Hq, num_kv_heads, D)       # (T, H*, D)
    if qkv_bias is not None:
        bq, bk, bv = _split_qkv(jnp.asarray(qkv_bias)[None], Hq,
                                num_kv_heads, D)
        q, k, v = q + bq[0], k + bk[0], v + bv[0]

    prefill = bool((enc > 0).any())
    decode = bool((dec > 0).any()) and not prefill
    if prefill and bool((dec > 0).any()):
        raise NotImplementedError(
            'mixed prefill+decode batches are not supported in one call; '
            'split the batch by phase (the reference serving loop '
            'schedules them separately too)')

    tbl = jnp.clip(jnp.asarray(block_tables, jnp.int32), 0, NB - 1)
    quant_cache = key_cache.dtype == jnp.int8
    if quant_cache:
        def canon_scale(s):
            s = jnp.asarray(s, jnp.float32)
            return jnp.broadcast_to(s[:, None], (Hkv, D)) if s.ndim == 1 \
                else s
        kds = canon_scale(cache_k_dequant_scales)
        vds = canon_scale(cache_v_dequant_scales)

        def quantize_rows(x, ds):
            qx = jnp.round(x.astype(jnp.float32) / ds[None])
            return jnp.clip(qx, quant_min_bound,
                            quant_max_bound).astype(jnp.int8)
    if rope_emb is not None:
        re = jnp.asarray(rope_emb)
        if re.ndim == 5:                                  # (2,B,S,1,D/2)
            re = re[:, :, :, 0, :]
        if re.ndim != 4 or re.shape[0] != 2:
            raise NotImplementedError(
                'rope_emb must be (2, B, max_seq, [1,] D/2) cos/sin')

    if prefill:
        # ---- varlen causal prefill over the unpadded token stream ----
        cu = jnp.reshape(jnp.asarray(cu_seqlens_q, jnp.int32), (-1,))
        T = q.shape[0]
        tok = jnp.arange(T)
        seg = jnp.searchsorted(cu[1:], tok, side='right').astype(jnp.int32)
        pos = tok - cu[seg]                               # position in seq
        if rope_emb is not None:
            cos = re[0][seg, pos]                         # (T, D/2)
            sin = re[1][seg, pos]
            q, k = _rope_rows(q, k, cos, sin, use_neox_style)
        from ...nn.functional.attention import scaled_dot_product_attention

        out = scaled_dot_product_attention(
            q[None], k[None], v[None], is_causal=True,
            segment_ids=seg[None])[0]                     # (T, Hq, D)
        # scatter K/V rows into pages: token t of seq b at position p
        # lands in page tbl[b, p // BS] slot p % BS
        page = tbl[seg, pos // BS]
        slot = pos % BS
        kw, vw = (quantize_rows(k, kds), quantize_rows(v, vds)) \
            if quant_cache else (k.astype(key_cache.dtype),
                                 v.astype(value_cache.dtype))
        key_cache = key_cache.at[page, :, slot].set(kw)
        value_cache = value_cache.at[page, :, slot].set(vw)
        return out.reshape(T, Hq * D), qkv, key_cache, value_cache

    if decode:
        # ---- one token per row: paged fused decode -------------------
        if q.shape[0] != B:
            raise NotImplementedError(
                f'decode expects one qkv row per batch row (got '
                f'{q.shape[0]} tokens for batch {B}); keep finished rows '
                f'in the batch with seq_lens_this_time=0')
        this = _np.reshape(_np.asarray(seq_lens_this_time), (-1,))
        active = jnp.asarray(this > 0)                   # (B,)
        if ((dec + (this > 0)) > tbl.shape[1] * BS).any():
            raise ValueError(
                f'page capacity exceeded: a row needs position '
                f'{int(dec.max())} but block_tables provides only '
                f'{tbl.shape[1]} pages x {BS} slots — allocate another '
                f'page for the row (JAX clamping would silently '
                f'overwrite a live slot)')
        lens = jnp.asarray(dec, jnp.int32)               # context so far
        rows = jnp.arange(B)
        page = tbl[rows, lens // BS]
        slot = lens % BS
        if rope_emb is not None:
            pos = lens[:, None]
            cos = jnp.take_along_axis(re[0], pos[:, :, None], axis=1)[:, 0]
            sin = jnp.take_along_axis(re[1], pos[:, :, None], axis=1)[:, 0]
            q, k = _rope_rows(q, k, cos, sin, use_neox_style)
        kw, vw = (quantize_rows(k, kds), quantize_rows(v, vds)) \
            if quant_cache else (k.astype(key_cache.dtype),
                                 v.astype(value_cache.dtype))
        # finished/inactive rows (seq_lens_this_time == 0) must not
        # scatter their dummy token — keep the existing page contents
        old_k = key_cache[page, :, slot]
        old_v = value_cache[page, :, slot]
        key_cache = key_cache.at[page, :, slot].set(
            jnp.where(active[:, None, None], kw, old_k))
        value_cache = value_cache.at[page, :, slot].set(
            jnp.where(active[:, None, None], vw, old_v))
        counts = lens + 1

        out = None
        from ...ops import use_pallas

        if use_pallas() and D % 8 == 0 and tgt_mask is None:
            try:
                from ...ops.pallas.paged_attention import (
                    paged_decode_attention)

                out = paged_decode_attention(
                    q[:, None], key_cache, value_cache, tbl, counts,
                    k_scale=kds if quant_cache else None,
                    v_scale=vds if quant_cache else None)[:, 0]
            except Exception as e:  # noqa: BLE001
                from ...ops import pallas_failed

                pallas_failed('paged_attention', e)
        if out is None:
            # XLA fallback: gather each row's pages to a contiguous view
            maxb = tbl.shape[1]
            ck = key_cache[tbl]                           # (B,MAXB,Hkv,BS,D)
            cv = value_cache[tbl]
            ck = jnp.swapaxes(ck, 2, 3).reshape(B, maxb * BS, Hkv, D)
            cv = jnp.swapaxes(cv, 2, 3).reshape(B, maxb * BS, Hkv, D)
            if quant_cache:
                ck = ck.astype(jnp.float32) * kds[None, None]
                cv = cv.astype(jnp.float32) * vds[None, None]
            rep = Hq // Hkv
            ckr = jnp.repeat(ck.astype(jnp.float32), rep, axis=2)
            cvr = jnp.repeat(cv.astype(jnp.float32), rep, axis=2)
            logits = jnp.einsum('bhd,bshd->bhs', q.astype(jnp.float32),
                                ckr) / (D ** 0.5)
            msk = jnp.arange(maxb * BS)[None, None, :] < counts[:, None,
                                                                None]
            if tgt_mask is not None:
                tm = jnp.asarray(tgt_mask, jnp.float32).reshape(B, 1, -1)
                logits = logits + jnp.pad(
                    tm, ((0, 0), (0, 0), (0, maxb * BS - tm.shape[-1])))
            logits = jnp.where(msk, logits, -1e30)
            p = jax.nn.softmax(logits, axis=-1)
            out = jnp.einsum('bhs,bshd->bhd', p, cvr).astype(qkv.dtype)
        return out.reshape(B, Hq * D), qkv, key_cache, value_cache

    raise ValueError('neither prefill (seq_lens_encoder) nor decode '
                     '(seq_lens_decoder) rows present')


# ---------------------------------------------------------------------------
# Remaining reference functional surface
# ---------------------------------------------------------------------------

def blha_get_max_len(seq_lens_encoder, seq_lens_decoder, batch_size):
    """ref: incubate/nn/functional/blha_get_max_len.py — the serving
    loop's helper: max encoder/decoder lengths this step (feeds
    block_multihead_attention's max_enc/dec_len_this_time)."""
    enc = jnp.max(jnp.reshape(jnp.asarray(seq_lens_encoder, jnp.int32),
                              (-1,)))
    dec = jnp.max(jnp.reshape(jnp.asarray(seq_lens_decoder, jnp.int32),
                              (-1,)))
    return enc.reshape(1), dec.reshape(1)


def fused_dot_product_attention(query, key, value, attn_mask=None,
                                dropout_p=0.0, is_causal=False,
                                scaling_factor=None, training=True,
                                name=None):
    """ref: incubate/nn/functional/fused_dot_product_attention.py (cuDNN
    fused attention, [B, S, H, D] layout) — on TPU this IS
    scaled_dot_product_attention (flash kernel underneath)."""
    from ...nn.functional.attention import scaled_dot_product_attention

    return scaled_dot_product_attention(
        query, key, value, attn_mask=attn_mask, dropout_p=dropout_p,
        is_causal=is_causal, scale=scaling_factor, training=training)


def variable_length_memory_efficient_attention(query, key, value, seq_lens,
                                               kv_seq_lens, mask=None,
                                               scale=None, causal=False,
                                               pre_cache_length=0):
    """ref: incubate/nn/functional/variable_length_memory_efficient_
    attention.py (CUTLASS varlen attention, [B, H, S, D] layout):
    per-row query/key validity from seq_lens/kv_seq_lens, optional
    additive mask, causal option. The XLA softmax fuses; rows beyond a
    sequence's length contribute nothing and emit zeros."""
    if pre_cache_length:
        raise NotImplementedError(
            'pre_cache_length belongs to the reference CUDA pre-cache '
            'pipeline')
    B, H, Sq, D = query.shape
    Sk = key.shape[2]
    scale = scale if scale is not None else 1.0 / (D ** 0.5)
    ql = jnp.reshape(jnp.asarray(seq_lens, jnp.int32), (-1,))
    kl = jnp.reshape(jnp.asarray(kv_seq_lens, jnp.int32), (-1,))
    logits = jnp.einsum('bhqd,bhkd->bhqk', query.astype(jnp.float32),
                        key.astype(jnp.float32)) * scale
    keep = (jnp.arange(Sk)[None, None, None, :] < kl[:, None, None, None])
    if causal:
        keep = keep & (jnp.arange(Sk)[None, None, None, :]
                       <= jnp.arange(Sq)[None, None, :, None])
    logits = jnp.where(keep, logits, -1e30)
    if mask is not None:
        logits = logits + jnp.asarray(mask, jnp.float32)
    p = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum('bhqk,bhkd->bhqd', p, value.astype(jnp.float32))
    # rows past a sequence's own length are undefined in the reference;
    # zero them so garbage can't leak downstream
    qvalid = (jnp.arange(Sq)[None, None, :, None]
              < ql[:, None, None, None])
    return jnp.where(qvalid, out, 0.0).astype(query.dtype)


def fused_moe(x, gate_weight, ffn1_weight, ffn2_weight, ffn1_bias=None,
              ffn1_scale=None, ffn2_bias=None, ffn2_scale=None,
              quant_method='None', moe_topk=2, norm_topk_prob=True):
    """ref: incubate/nn/functional/fused_moe.py — the fused serving MoE:
    per-token top-k over precomputed gate logits ([B, S, E]), SwiGLU
    experts with fused gate+up ffn1 ([E, d, 2*dff]), optional int8
    weights dequantized by ffn1/2_scale. TPU-native: the dropless
    sort + lax.ragged_dot grouped-GEMM path (distributed.moe)."""
    from ...distributed.moe import F as _moeF  # silu
    from ...distributed.moe import ragged_expert_apply

    if quant_method not in ('None', None, 'weight_only_int8'):
        raise NotImplementedError(f'quant_method={quant_method!r}')
    if quant_method == 'weight_only_int8' and (ffn1_scale is None
                                               or ffn2_scale is None):
        raise ValueError(
            "quant_method='weight_only_int8' requires ffn1_scale and "
            'ffn2_scale — raw int8 codes without scales would silently '
            'produce garbage')
    if ffn1_bias is not None:
        raise NotImplementedError(
            'ffn1_bias (inside the activation) is not supported by the '
            'ragged path; fold it into the checkpoint (the reference '
            'CUTLASS kernel does apply it — fc1_expert_biases)')
    B, S, d = x.shape
    E = gate_weight.shape[-1]
    logits = jnp.asarray(gate_weight, jnp.float32).reshape(B * S, E)
    probs = jax.nn.softmax(logits, axis=-1)
    gate_vals, expert_idx = jax.lax.top_k(probs, moe_topk)
    if norm_topk_prob:
        gate_vals = gate_vals / jnp.maximum(
            gate_vals.sum(-1, keepdims=True), 1e-9)

    w1 = jnp.asarray(ffn1_weight)
    w2 = jnp.asarray(ffn2_weight)
    if ffn1_scale is not None:
        w1 = w1.astype(jnp.float32) * jnp.asarray(ffn1_scale)[:, None, :]
    if ffn2_scale is not None:
        w2 = w2.astype(jnp.float32) * jnp.asarray(ffn2_scale)[:, None, :]
    w1 = w1.astype(x.dtype)
    w2 = w2.astype(x.dtype)
    dff2 = w1.shape[-1]
    # fused gate+up: split [.., 2*dff] -> swiglu halves
    w_gate, w_up = w1[..., :dff2 // 2], w1[..., dff2 // 2:]

    tokens = x.reshape(B * S, d)
    out = ragged_expert_apply(tokens, expert_idx, gate_vals, w_gate, w_up,
                              w2, E, act=_moeF.silu)
    if ffn2_bias is not None:
        # per-expert output bias: gather-free second pass
        oh = jax.nn.one_hot(expert_idx, E, dtype=jnp.float32)  # (T, k, E)
        w = (oh * gate_vals[..., None]).sum(1)                 # (T, E)
        b2 = jnp.asarray(ffn2_bias).reshape(E, d)
        out = out + (w @ b2).astype(out.dtype)
    return out.reshape(B, S, d)


def fused_gate_attention(query, key=None, query_weight=None,
                         key_weight=None, value_weight=None,
                         qkv_weight=None, gate_linear_weight=None,
                         gate_linear_bias=None, out_linear_weight=None,
                         out_linear_bias=None, nonbatched_bias=None,
                         attn_mask=None, has_gating=True, merge_qkv=True,
                         use_flash_attn=False):
    """ref: incubate/nn/functional/fused_gate_attention.py (AlphaFold
    gated self-attention): q/k/v projections, attention with an optional
    nonbatched bias, sigmoid gating, output projection. Layouts follow
    the reference: query (B, M, R, qdim); merged qkv_weight
    (3, H, D, qdim); separate q/k/v weights (qdim, H, D);
    gate/out weights (qdim, H, D) / (H, D, odim)."""
    q_in = jnp.asarray(query)
    if merge_qkv:
        if qkv_weight is None:
            raise ValueError('merge_qkv=True requires qkv_weight')
        qkv = jnp.einsum('bmrc,thdc->tbmrhd', q_in, jnp.asarray(qkv_weight))
        q, k, v = qkv[0], qkv[1], qkv[2]        # (B, M, R, H, D)
    else:
        if key is None:
            key = query
        k_in = jnp.asarray(key)
        q = jnp.einsum('bmrc,chd->bmrhd', q_in, jnp.asarray(query_weight))
        k = jnp.einsum('bmrc,chd->bmrhd', k_in, jnp.asarray(key_weight))
        v = jnp.einsum('bmrc,chd->bmrhd', k_in, jnp.asarray(value_weight))
    D = q.shape[-1]
    logits = jnp.einsum('bmrhd,bmshd->bmhrs', q.astype(jnp.float32),
                        k.astype(jnp.float32)) * (1.0 / (D ** 0.5))
    if nonbatched_bias is not None:
        # reference layout (B, 1, H, R, S): broadcasts over the msa axis
        # directly — no extra axis
        logits = logits + jnp.asarray(nonbatched_bias, jnp.float32)
    if attn_mask is not None:
        logits = logits + jnp.asarray(attn_mask, jnp.float32)
    p = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum('bmhrs,bmshd->bmrhd', p, v.astype(jnp.float32))
    out = out.astype(q_in.dtype)
    if has_gating:
        if gate_linear_weight is None:
            raise ValueError('has_gating=True requires gate_linear_weight')
        gate = jnp.einsum('bmrc,chd->bmrhd', q_in,
                          jnp.asarray(gate_linear_weight))
        if gate_linear_bias is not None:
            gate = gate + jnp.asarray(gate_linear_bias)
        out = out * jax.nn.sigmoid(gate)
    if out_linear_weight is not None:
        out = jnp.einsum('bmrhd,hdc->bmrc', out,
                         jnp.asarray(out_linear_weight))
        if out_linear_bias is not None:
            out = out + jnp.asarray(out_linear_bias)
    return out


def fused_bias_dropout_residual_layer_norm(x, residual, bias=None,
                                           ln_scale=None, ln_bias=None,
                                           dropout_rate=0.5,
                                           ln_epsilon=1e-5, training=True,
                                           mode='upscale_in_train',
                                           name=None):
    """ref: fused_transformer.py::fused_bias_dropout_residual_layer_norm
    — LN(residual + dropout(x + bias))."""
    if bias is not None:
        x = x + bias
    h = fused_dropout_add(x, residual, dropout_rate, training=training,
                          mode=mode)
    return fused_layer_norm(h, ln_scale, ln_bias, ln_epsilon)


def fused_linear_activation(x, y, bias=None, trans_x=False, trans_y=False,
                            activation='gelu'):
    """ref: fused_linear_activation — matmul + bias + activation (the
    cuBLASLt epilogue fusion; XLA fuses the same chain on TPU)."""
    acts = {'gelu': jax.nn.gelu, 'relu': jax.nn.relu, 'none': lambda a: a,
            '': lambda a: a}
    if activation not in acts:
        raise ValueError(f'activation must be one of {list(acts)}')
    out = fused_matmul_bias(x, y, bias, transpose_x=trans_x,
                            transpose_y=trans_y)
    return acts[activation](out)


def fused_multi_transformer(x, ln_scales, ln_biases, qkv_weights,
                            qkv_biases, linear_weights, linear_biases,
                            ffn_ln_scales, ffn_ln_biases, ffn1_weights,
                            ffn1_biases, ffn2_weights, ffn2_biases,
                            pre_layer_norm=True, epsilon=1e-5,
                            residual_alpha=1.0, cache_kvs=None,
                            beam_offset=None, pre_caches=None,
                            seq_lens=None, rotary_embs=None,
                            time_step=None, attn_mask=None,
                            dropout_rate=0.0, rotary_emb_dims=0,
                            activation='gelu', training=False,
                            mode='upscale_in_train', trans_qkvw=True,
                            ring_id=-1, norm_type='layernorm',
                            use_neox_rotary_style=True,
                            gqa_group_size=-1, name=None):
    """ref: fused_transformer.py::fused_multi_transformer — the
    FUNCTIONAL form of the serving decoder stack (per-layer weight
    lists; PaddleNLP's inference path calls this directly). Same math
    as incubate.nn.FusedMultiTransformer: prefill writes the
    (2, B, H, max_seq, D) caches through the flash path, `time_step`
    decode routes the fused head-major kernel. The CUDA-pipeline knobs
    (beam_offset, pre_caches, rotary_embs, gqa) are rejected with
    guidance.
    """
    for nm, v in (('beam_offset', beam_offset), ('pre_caches', pre_caches),
                  ('rotary_embs', rotary_embs)):
        if v is not None:
            raise NotImplementedError(
                f'{nm}: use the Llama-family models for RoPE/beam serving')
    if not trans_qkvw:
        raise NotImplementedError('trans_qkvw=False unsupported')
    if gqa_group_size not in (-1, 0):
        raise NotImplementedError(
            'gqa: use the Llama family (GQA-native) models')
    if residual_alpha != 1.0:
        raise NotImplementedError('residual_alpha != 1 unsupported')
    from ...nn.functional.norm import layer_norm
    from ...ops import rms_norm

    if norm_type == 'layernorm':
        def norm(h, scale, bias_):
            return layer_norm(h, h.shape[-1],
                              scale.reshape(-1) if scale is not None
                              else None,
                              bias_.reshape(-1) if bias_ is not None
                              else None, epsilon)
    elif norm_type == 'rmsnorm':
        def norm(h, scale, bias_):
            out = rms_norm(h, scale.reshape(-1) if scale is not None
                           else None, epsilon)
            return out + bias_ if bias_ is not None else out
    else:
        raise ValueError(f'norm_type must be layernorm|rmsnorm, '
                         f'got {norm_type!r}')
    acts = {'gelu': jax.nn.gelu, 'relu': jax.nn.relu,
            'silu': jax.nn.silu}
    if activation not in acts:
        raise ValueError(f'activation must be one of {list(acts)}')
    act = acts[activation]
    from ...nn.functional.attention import scaled_dot_product_attention

    if time_step is not None and x.shape[1] != 1:
        raise ValueError('time_step decode expects one token per row')
    if time_step is not None and cache_kvs is None:
        raise ValueError(
            'time_step decode requires cache_kvs (the per-layer '
            '(2, B, H, max_seq, D) caches written at prefill)')
    if time_step is not None and attn_mask is not None:
        raise NotImplementedError(
            'attn_mask is not applied on time_step decode steps (the '
            'cache window is positional) — drive padded decode via '
            'seq_lens instead of a mask')

    num_layers = len(qkv_weights)
    new_caches = [] if cache_kvs is not None else None
    for i in range(num_layers):
        qkv_w = jnp.asarray(qkv_weights[i])         # (3, H, D, E)
        _, H, D, _ = qkv_w.shape
        residual = x
        h = norm(x, ln_scales[i], ln_biases[i]) if pre_layer_norm else x
        cache = cache_kvs[i] if cache_kvs is not None else None
        if time_step is not None:
            xt = h[:, 0]
            qkv_flat = jnp.einsum('be,thde->bthd', xt, qkv_w).reshape(
                xt.shape[0], 3 * H * D)
            if qkv_biases[i] is not None:
                qkv_flat = qkv_flat + jnp.asarray(qkv_biases[i]).reshape(-1)
            lens = (jnp.reshape(jnp.asarray(seq_lens, jnp.int32), (-1, 1))
                    if seq_lens is not None
                    else jnp.full((x.shape[0], 1), time_step, jnp.int32))
            attn_out, nc = masked_multihead_attention(
                qkv_flat, cache_kv=cache, sequence_lengths=lens)
            attn_out = attn_out[:, None]
        else:
            qkv = jnp.einsum('bse,thde->bsthd', h, qkv_w)
            if qkv_biases[i] is not None:
                qkv = qkv + jnp.asarray(qkv_biases[i]).reshape(
                    3, H, D)[None, None]
            q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
            attn_out = scaled_dot_product_attention(
                q, k, v, attn_mask=attn_mask,
                is_causal=attn_mask is None).reshape(*h.shape[:2], H * D)
            nc = cache
            if cache is not None:
                S = h.shape[1]
                nc = cache.at[0, :, :, :S].set(
                    jnp.swapaxes(k, 1, 2).astype(cache.dtype))
                nc = nc.at[1, :, :, :S].set(
                    jnp.swapaxes(v, 1, 2).astype(cache.dtype))
        if new_caches is not None:
            new_caches.append(nc)
        attn_out = attn_out @ jnp.asarray(linear_weights[i])
        if linear_biases[i] is not None:
            attn_out = attn_out + jnp.asarray(linear_biases[i])
        x = fused_dropout_add(attn_out, residual, dropout_rate,
                              training=training, mode=mode)
        if not pre_layer_norm:
            x = norm(x, ln_scales[i], ln_biases[i])

        residual = x
        h = norm(x, ffn_ln_scales[i], ffn_ln_biases[i]) \
            if pre_layer_norm else x
        h = h @ jnp.asarray(ffn1_weights[i])
        if ffn1_biases[i] is not None:
            h = h + jnp.asarray(ffn1_biases[i])
        h = act(h) @ jnp.asarray(ffn2_weights[i])
        if ffn2_biases[i] is not None:
            h = h + jnp.asarray(ffn2_biases[i])
        x = fused_dropout_add(h, residual, dropout_rate,
                              training=training, mode=mode)
        if not pre_layer_norm:
            x = norm(x, ffn_ln_scales[i], ffn_ln_biases[i])
    if cache_kvs is not None:
        return x, new_caches
    return x


@functools.partial(
    jax.jit, donate_argnames=('cache_kvs',),
    static_argnames=('pre_layer_norm', 'epsilon', 'activation',
                     'norm_type'))
def _fmt_decode_step(x, ln_scales, ln_biases, qkv_weights, qkv_biases,
                     linear_weights, linear_biases, ffn_ln_scales,
                     ffn_ln_biases, ffn1_weights, ffn1_biases, ffn2_weights,
                     ffn2_biases, cache_kvs, seq_lens, time_step, *,
                     pre_layer_norm, epsilon, activation, norm_type):
    # engine-wide retrace accounting (runs only while tracing)
    from ...inference.engine import _count_trace

    _count_trace('fmt_decode_step')
    return fused_multi_transformer(
        x, ln_scales, ln_biases, qkv_weights, qkv_biases, linear_weights,
        linear_biases, ffn_ln_scales, ffn_ln_biases, ffn1_weights,
        ffn1_biases, ffn2_weights, ffn2_biases,
        pre_layer_norm=pre_layer_norm, epsilon=epsilon,
        cache_kvs=cache_kvs, seq_lens=seq_lens, time_step=time_step,
        activation=activation, training=False, norm_type=norm_type)


def fused_multi_transformer_decode_step(
        x, ln_scales, ln_biases, qkv_weights, qkv_biases, linear_weights,
        linear_biases, ffn_ln_scales, ffn_ln_biases, ffn1_weights,
        ffn1_biases, ffn2_weights, ffn2_biases, cache_kvs, time_step,
        seq_lens=None, pre_layer_norm=True, epsilon=1e-5,
        activation='gelu', norm_type='layernorm'):
    """The fused_multi_transformer time_step path under the
    DecodeEngine's compilation/donation contract (docs/decode_engine.md):
    a MODULE-LEVEL jit (steady-state serving never retraces — the trace
    is keyed on the weight-list pytree structure, cache shapes, and the
    static config) with `cache_kvs` DONATED, so every layer's
    (2, B, H, max_seq, D) cache is updated in place instead of copied
    per token.

    Contract: the cache_kvs buffers passed in are DEAD to the caller
    after this returns — keep only the returned caches (the serving
    loop's natural `caches = step(..., caches)` shape). time_step may be
    a traced/device scalar: one compilation serves every step index.

    Returns (x_out, new_cache_kvs) exactly like
    fused_multi_transformer(time_step=...)."""
    return _fmt_decode_step(
        x, ln_scales, ln_biases, qkv_weights, qkv_biases, linear_weights,
        linear_biases, ffn_ln_scales, ffn_ln_biases, ffn1_weights,
        ffn1_biases, ffn2_weights, ffn2_biases, cache_kvs,
        seq_lens, jnp.asarray(time_step, jnp.int32),
        pre_layer_norm=bool(pre_layer_norm), epsilon=float(epsilon),
        activation=activation, norm_type=norm_type)
