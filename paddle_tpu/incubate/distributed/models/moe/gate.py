"""ref: python/paddle/incubate/distributed/models/moe/gate — gate
variants (fastmoe lineage): naive / switch / gshard."""
from paddle_tpu.distributed.moe import (  # noqa: F401
    BaseGate,
    GShardGate,
    NaiveGate,
    SwitchGate,
)
