"""ref: python/paddle/incubate/distributed/models/moe — re-export of the
TPU-native MoE (paddle_tpu.distributed.moe): GShard dense dispatch +
ragged grouped-GEMM path + gate variants."""
from paddle_tpu.distributed.moe import (  # noqa: F401
    BaseGate,
    ExpertMLP,
    GShardGate,
    MoELayer,
    NaiveGate,
    SwitchGate,
    ragged_expert_apply,
    top_k_gating,
)
from . import gate  # noqa: F401
