"""ref: python/paddle/incubate/distributed/models."""
from . import moe  # noqa: F401
