"""paddle_tpu.incubate (ref: python/paddle/incubate) — experimental /
fused surfaces. LookAhead re-exported for API parity
(paddle.incubate.LookAhead).
"""
from . import nn  # noqa: F401
from ..optimizer.wrappers import LookAhead  # noqa: F401
