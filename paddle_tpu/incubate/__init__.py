"""paddle_tpu.incubate (ref: python/paddle/incubate) — experimental /
fused surfaces. LookAhead re-exported for API parity
(paddle.incubate.LookAhead).
"""
from . import distributed  # noqa: F401
from . import nn  # noqa: F401
from ..optimizer.wrappers import LookAhead  # noqa: F401

from ..geometric import (  # noqa: F401  (ref: incubate graph ops are the
    segment_max,          # geometric segment/message-passing ops)
    segment_mean,
    segment_min,
    segment_sum,
)


def graph_send_recv(x, src_index, dst_index, pool_type='sum',
                    out_size=None):
    """ref: paddle.incubate.graph_send_recv — gather at src, segment-
    reduce at dst (the geometric send_u_recv op)."""
    from ..geometric import send_u_recv

    return send_u_recv(x, src_index, dst_index, reduce_op=pool_type,
                       out_size=out_size)


def softmax_mask_fuse(x, mask):
    """ref: paddle.incubate.softmax_mask_fuse — softmax(x + mask); XLA
    fuses the add into the softmax, which is all the CUDA kernel did."""
    import jax
    import jax.numpy as jnp

    return jax.nn.softmax(x.astype(jnp.float32) + mask.astype(jnp.float32),
                          axis=-1).astype(x.dtype)


def softmax_mask_fuse_upper_triangle(x):
    """ref: paddle.incubate.softmax_mask_fuse_upper_triangle — causal
    masked softmax over the last two axes."""
    import jax
    import jax.numpy as jnp

    s_q, s_k = x.shape[-2], x.shape[-1]
    causal = jnp.tril(jnp.ones((s_q, s_k), bool), k=s_k - s_q)
    logits = jnp.where(causal, x.astype(jnp.float32), -1e30)
    return jax.nn.softmax(logits, axis=-1).astype(x.dtype)


def identity_loss(x, reduction='none'):
    """ref: paddle.incubate.identity_loss (IPU loss anchor; on TPU just
    the requested reduction)."""
    import jax.numpy as jnp

    if reduction in (0, 'sum'):
        return jnp.sum(x)
    if reduction in (1, 'mean'):
        return jnp.mean(x)
    return x


_sampler_rng = []


def _rng():
    # persistent across calls: fresh default_rng(0) per call would make
    # every "random" neighbour draw identical, defeating the sampling
    import numpy as np

    if not _sampler_rng:
        _sampler_rng.append(np.random.default_rng())
    return _sampler_rng[0]


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes,
                       sorted_eids=None, return_eids=False):
    """ref: paddle.incubate.graph_khop_sampler — k-hop neighbourhood
    sampling. Host-side (graph sampling is data-dependent control flow;
    the reference's kernel is also a host-orchestrated gather)."""
    import numpy as np

    row = np.asarray(row)
    colptr = np.asarray(colptr)
    frontier = np.asarray(input_nodes).reshape(-1)
    all_rows, all_cols = [], []
    rng = _rng()
    for size in sample_sizes:
        rs, cs = [], []
        for v in frontier:
            lo, hi = int(colptr[v]), int(colptr[v + 1])
            neigh = row[lo:hi]
            if size >= 0 and len(neigh) > size:
                neigh = rng.choice(neigh, size, replace=False)
            rs.extend(neigh.tolist())
            cs.extend([int(v)] * len(neigh))
        all_rows.extend(rs)
        all_cols.extend(cs)
        frontier = np.unique(np.asarray(rs, np.int64))
    edge_src = np.asarray(all_rows, np.int64)
    edge_dst = np.asarray(all_cols, np.int64)
    nodes = np.unique(np.concatenate([np.asarray(input_nodes).reshape(-1),
                                      edge_src]))
    # relabel to local ids
    lut = {int(n): i for i, n in enumerate(nodes)}
    reindex_src = np.asarray([lut[int(s)] for s in edge_src], np.int64)
    reindex_dst = np.asarray([lut[int(d)] for d in edge_dst], np.int64)
    return reindex_src, reindex_dst, nodes, None


def graph_sample_neighbors(row, colptr, input_nodes, sample_size=-1,
                           eids=None, return_eids=False, perm_buffer=None,
                           flag_perm_buffer=False):
    """ref: paddle.incubate.graph_sample_neighbors — one-hop sampling
    over a CSC graph. With `return_eids`, a third array of sampled edge
    ids (positions, or `eids` mapped through them) is returned."""
    import numpy as np

    row = np.asarray(row)
    colptr = np.asarray(colptr)
    eids_arr = None if eids is None else np.asarray(eids)
    rng = _rng()
    out_neigh, out_count, out_eids = [], [], []
    for v in np.asarray(input_nodes).reshape(-1):
        lo, hi = int(colptr[v]), int(colptr[v + 1])
        pos = np.arange(lo, hi)
        if sample_size >= 0 and len(pos) > sample_size:
            pos = pos[rng.choice(len(pos), sample_size, replace=False)]
        out_neigh.extend(row[pos].tolist())
        out_count.append(len(pos))
        if return_eids:
            chosen = eids_arr[pos] if eids_arr is not None else pos
            out_eids.extend(np.asarray(chosen).tolist())
    result = (np.asarray(out_neigh, np.int64),
              np.asarray(out_count, np.int64))
    if return_eids:
        return result + (np.asarray(out_eids, np.int64),)
    return result


def graph_reindex(x, neighbors, count, value_buffer=None, index_buffer=None,
                  flag_buffer_hashtable=False):
    """ref: paddle.incubate.graph_reindex — relabel a neighbourhood set
    to contiguous local ids."""
    import numpy as np

    x = np.asarray(x).reshape(-1)
    neighbors = np.asarray(neighbors).reshape(-1)
    nodes = list(dict.fromkeys(x.tolist() + neighbors.tolist()))
    lut = {int(n): i for i, n in enumerate(nodes)}
    reindex = np.asarray([lut[int(n)] for n in neighbors], np.int64)
    count = np.asarray(count, np.int64)
    dst = np.repeat(np.arange(len(x), dtype=np.int64), count)
    return reindex, dst, np.asarray(nodes, np.int64)


class ModelAverage:
    """ref: paddle.incubate.ModelAverage — running average of parameters
    applied at eval; the TPU-native EMA wrapper covers the mechanism."""

    def __init__(self, average_window_rate=0.15, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        from ..optimizer.wrappers import ExponentialMovingAverage

        # window-rate ≈ (1 - decay): map onto the EMA machinery
        self._ema = ExponentialMovingAverage(
            decay=1.0 - average_window_rate)
        self._state = None

    def update(self, model):
        if self._state is None:
            self._state = self._ema.init(model)
        self._state = self._ema.update(self._state, model)
        return self._state

    def apply(self, model):
        """Returns a copy of `model` with averaged weights swapped in."""
        return self._ema.apply(model, self._state)

    def restore(self, model):
        """Functional framework: the original model was never mutated."""
        return model


class inference:
    """ref: paddle.incubate.inference namespace (TensorRT wrappers —
    CUDA-only; the TPU path is jit.save -> StableHLO)."""
