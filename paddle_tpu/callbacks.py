"""Training callbacks (ref: python/paddle/hapi/callbacks.py)."""
from __future__ import annotations

import os
import time


class Callback:
    """ref: paddle.callbacks.Callback."""

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = params

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks, model=None, params=None):
        self.callbacks = list(callbacks)
        for c in self.callbacks:
            if model is not None:
                c.set_model(model)
            c.set_params(params or {})

    def __getattr__(self, name):
        if name.startswith('on_'):
            def call(*args, **kw):
                for c in self.callbacks:
                    getattr(c, name)(*args, **kw)

            return call
        raise AttributeError(name)


class ProgBarLogger(Callback):
    """ref: paddle.callbacks.ProgBarLogger — step/epoch console logging."""

    def __init__(self, log_freq=10, verbose=1):
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = (self.params or {}).get('steps')
        self._t0 = time.time()
        if self.verbose:
            print(f'Epoch {epoch + 1}/{self.params.get("epochs", "?")}')

    def on_train_batch_end(self, step, logs=None):
        if self.verbose and step % self.log_freq == 0:
            items = ' - '.join(f'{k}: {v:.4f}' if isinstance(v, float) else f'{k}: {v}'
                               for k, v in (logs or {}).items())
            print(f'step {step}/{self.steps or "?"} - {items}')

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.time() - self._t0
            items = ' - '.join(f'{k}: {v:.4f}' if isinstance(v, float) else f'{k}: {v}'
                               for k, v in (logs or {}).items())
            print(f'epoch {epoch + 1} done in {dt:.1f}s - {items}')


class ModelCheckpoint(Callback):
    """ref: paddle.callbacks.ModelCheckpoint — periodic save."""

    def __init__(self, save_freq=1, save_dir='checkpoint'):
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.model and epoch % self.save_freq == 0:
            os.makedirs(self.save_dir, exist_ok=True)
            self.model.save(os.path.join(self.save_dir, str(epoch)))


class LRSchedulerCallback(Callback):
    """ref: paddle.callbacks.LRScheduler — steps the lr schedule."""

    def __init__(self, by_step=True, by_epoch=False):
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, '_optimizer', None)
        lr = getattr(opt, '_lr', None)
        return lr if hasattr(lr, 'step') else None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s is not None:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if self.by_epoch and s is not None:
            s.step()


LRScheduler = LRSchedulerCallback


class EarlyStopping(Callback):
    """ref: paddle.callbacks.EarlyStopping."""

    def __init__(self, monitor='loss', mode='auto', patience=0, min_delta=0,
                 baseline=None, save_best_model=True):
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        if mode == 'auto':
            mode = 'max' if 'acc' in monitor else 'min'
        self.mode = mode
        self.stopped = False
        self.wait = 0
        self.best = None

    def _better(self, cur):
        if self.best is None:
            return True
        if self.mode == 'min':
            return cur < self.best - self.min_delta
        return cur > self.best + self.min_delta

    def on_eval_end(self, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            return
        if isinstance(cur, (list, tuple)):
            cur = cur[0]
        if self._better(cur):
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.stopped = True
                if self.model is not None:
                    self.model.stop_training = True


class ReduceLROnPlateau(Callback):
    """ref: paddle.callbacks.ReduceLROnPlateau — shrink the lr when a
    monitored metric stops improving."""

    def __init__(self, monitor='loss', factor=0.1, patience=10, verbose=1,
                 mode='auto', min_delta=1e-4, cooldown=0, min_lr=0):
        self.monitor = monitor
        self.factor = factor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = min_delta
        self.cooldown = cooldown
        self.min_lr = min_lr
        if mode == 'auto':
            mode = 'min' if 'loss' in monitor or 'err' in monitor else 'max'
        self.mode = mode
        self._best = None
        self._wait = 0
        self._cooldown_left = 0
        self._saw_eval = False

    def _better(self, cur):
        if self._best is None:
            return True
        if self.mode == 'min':
            return cur < self._best - self.min_delta
        return cur > self._best + self.min_delta

    def on_eval_end(self, logs=None):
        # once eval logs exist they are the single metric stream; the
        # epoch hook stands down (hooking both would double-count
        # patience and mix train/eval values for the same monitor key)
        self._saw_eval = True
        self._check(logs)

    def on_epoch_end(self, epoch, logs=None):
        if not self._saw_eval:
            self._check(logs)

    def _check(self, logs):
        logs = logs or {}
        if self.monitor not in logs:
            return
        cur = float(logs[self.monitor])
        if self._cooldown_left > 0:
            # in cooldown: track the best but do NO patience accounting
            # (otherwise patience drains during the window and the lr
            # collapses once per epoch instead of once per window)
            self._cooldown_left -= 1
            self._wait = 0
            if self._better(cur):
                self._best = cur
            return
        if self._better(cur):
            self._best = cur
            self._wait = 0
            return
        self._wait += 1
        if self._wait >= self.patience:
            opt = getattr(self.model, '_optimizer', None)
            if opt is None:
                return
            lr = opt._lr
            if callable(lr):
                # evaluate the schedule at the CURRENT step, not step 0 —
                # replacing a decayed schedule with lr(0)*factor could
                # INCREASE the rate late in training
                state = getattr(opt, 'state', None)
                step = (int(state['step']) if isinstance(state, dict)
                        and 'step' in state else 0)
                cur_lr = float(lr(step))
            else:
                cur_lr = float(lr)
            new_lr = max(cur_lr * self.factor, self.min_lr)
            if new_lr < cur_lr:
                opt.set_lr(new_lr)
                if self.verbose:
                    print(f'ReduceLROnPlateau: lr -> {new_lr:.3e}')
            self._cooldown_left = self.cooldown
            self._wait = 0


class VisualDL(Callback):
    """ref: paddle.callbacks.VisualDL — metric scalars to a log dir.
    The visualdl package is CUDA-ecosystem tooling not shipped here;
    scalars land in a JSONL file a notebook (or TensorBoard via
    jax.profiler traces) can plot."""

    def __init__(self, log_dir='./vdl_log'):
        import os

        self.log_dir = log_dir
        os.makedirs(log_dir, exist_ok=True)
        self._f = None
        self._step = 0

    def _write(self, tag, logs):
        import json
        import os

        if self._f is None:
            self._f = open(os.path.join(self.log_dir, 'scalars.jsonl'), 'a')
        for k, v in (logs or {}).items():
            try:
                self._f.write(json.dumps(
                    {'tag': f'{tag}/{k}', 'step': self._step,
                     'value': float(v)}) + '\n')
            except (TypeError, ValueError):
                continue
        self._f.flush()

    def on_train_batch_end(self, step, logs=None):
        self._step += 1
        self._write('train', logs)

    def on_eval_end(self, logs=None):
        self._write('eval', logs)

    def on_train_end(self, logs=None):
        if self._f is not None:
            self._f.close()
            self._f = None


class WandbCallback(Callback):
    """ref: paddle.callbacks.WandbCallback — requires the wandb package
    (not shipped); degrades to the VisualDL JSONL logger."""

    def __init__(self, project=None, dir=None, **kwargs):
        try:
            import wandb  # noqa: F401

            self._wandb = wandb
            self._run = wandb.init(project=project, dir=dir, **kwargs)
        except ImportError:
            self._wandb = None
            self._fallback = VisualDL(log_dir=dir or './wandb_log')

    def on_train_batch_end(self, step, logs=None):
        if self._wandb:
            self._wandb.log(dict(logs or {}), step=step)
        else:
            self._fallback.model = getattr(self, 'model', None)
            self._fallback.on_train_batch_end(step, logs)

    def on_train_end(self, logs=None):
        if self._wandb:
            self._run.finish()
        else:
            self._fallback.on_train_end(logs)
