"""Training callbacks (ref: python/paddle/hapi/callbacks.py)."""
from __future__ import annotations

import os
import time


class Callback:
    """ref: paddle.callbacks.Callback."""

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = params

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks, model=None, params=None):
        self.callbacks = list(callbacks)
        for c in self.callbacks:
            if model is not None:
                c.set_model(model)
            c.set_params(params or {})

    def __getattr__(self, name):
        if name.startswith('on_'):
            def call(*args, **kw):
                for c in self.callbacks:
                    getattr(c, name)(*args, **kw)

            return call
        raise AttributeError(name)


class ProgBarLogger(Callback):
    """ref: paddle.callbacks.ProgBarLogger — step/epoch console logging."""

    def __init__(self, log_freq=10, verbose=1):
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = (self.params or {}).get('steps')
        self._t0 = time.time()
        if self.verbose:
            print(f'Epoch {epoch + 1}/{self.params.get("epochs", "?")}')

    def on_train_batch_end(self, step, logs=None):
        if self.verbose and step % self.log_freq == 0:
            items = ' - '.join(f'{k}: {v:.4f}' if isinstance(v, float) else f'{k}: {v}'
                               for k, v in (logs or {}).items())
            print(f'step {step}/{self.steps or "?"} - {items}')

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.time() - self._t0
            items = ' - '.join(f'{k}: {v:.4f}' if isinstance(v, float) else f'{k}: {v}'
                               for k, v in (logs or {}).items())
            print(f'epoch {epoch + 1} done in {dt:.1f}s - {items}')


class ModelCheckpoint(Callback):
    """ref: paddle.callbacks.ModelCheckpoint — periodic save."""

    def __init__(self, save_freq=1, save_dir='checkpoint'):
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.model and epoch % self.save_freq == 0:
            os.makedirs(self.save_dir, exist_ok=True)
            self.model.save(os.path.join(self.save_dir, str(epoch)))


class LRSchedulerCallback(Callback):
    """ref: paddle.callbacks.LRScheduler — steps the lr schedule."""

    def __init__(self, by_step=True, by_epoch=False):
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, '_optimizer', None)
        lr = getattr(opt, '_lr', None)
        return lr if hasattr(lr, 'step') else None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s is not None:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if self.by_epoch and s is not None:
            s.step()


LRScheduler = LRSchedulerCallback


class EarlyStopping(Callback):
    """ref: paddle.callbacks.EarlyStopping."""

    def __init__(self, monitor='loss', mode='auto', patience=0, min_delta=0,
                 baseline=None, save_best_model=True):
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        if mode == 'auto':
            mode = 'max' if 'acc' in monitor else 'min'
        self.mode = mode
        self.stopped = False
        self.wait = 0
        self.best = None

    def _better(self, cur):
        if self.best is None:
            return True
        if self.mode == 'min':
            return cur < self.best - self.min_delta
        return cur > self.best + self.min_delta

    def on_eval_end(self, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            return
        if isinstance(cur, (list, tuple)):
            cur = cur[0]
        if self._better(cur):
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.stopped = True
                if self.model is not None:
                    self.model.stop_training = True
