"""GPT-2 style decoder LM (ref: PaddleNLP GPT / the reference's
incubate transformer stacks): learned position embeddings, pre-LN
blocks, GELU MLP, tied LM head optional.
"""
from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .. import nn
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer.base import Layer, Parameter
from .generation import GenerationMixin
from .llama import cached_attention


@dataclasses.dataclass
class GPTConfig:
    vocab_size: int = 50257
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 1024
    layer_norm_epsilon: float = 1e-5
    dropout: float = 0.1
    initializer_range: float = 0.02
    tie_word_embeddings: bool = True

    @property
    def head_dim(self):
        return self.hidden_size // self.num_attention_heads


def gpt2_tiny(**kw):
    defaults = dict(vocab_size=256, hidden_size=64, num_hidden_layers=2,
                    num_attention_heads=4, intermediate_size=128,
                    max_position_embeddings=128, dropout=0.0)
    defaults.update(kw)
    return GPTConfig(**defaults)


class GPTAttention(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.num_heads = config.num_attention_heads
        self.head_dim = config.head_dim
        init = I.Normal(0.0, config.initializer_range)
        h = config.hidden_size
        self.qkv = Parameter(init((h, 3 * h), 'float32'), spec=P(None, 'tp'))
        self.qkv_bias = Parameter(jnp.zeros((3 * h,)), spec=P('tp'))
        self.out_proj = Parameter(init((h, h), 'float32'), spec=P('tp', None))
        self.out_bias = Parameter(jnp.zeros((h,)))

    def forward(self, x, cache=None, cache_index=None, kvalid=None,
                kv_start=None, kv_write_pos=None):
        """cache: optional (k, v) of (B, max_len, H, D) — same cached-call
        contract as LlamaAttention (ref llama.py), incl. the fused pallas
        decode kernel on single-token steps, left-pad kvalid/kv_start and
        per-row kv_write_pos (batched speculative)."""
        B, S, H = x.shape
        qkv = x @ self.qkv + self.qkv_bias
        q, k, v = jnp.split(qkv, 3, axis=-1)
        shape = (B, S, self.num_heads, self.head_dim)
        q, k, v = q.reshape(shape), k.reshape(shape), v.reshape(shape)
        if cache is None:
            attn_mask = None
            if kvalid is not None:
                # left-pad support on the uncached path (same fold as
                # LlamaAttention): causal & row-validity
                causal = (jnp.arange(S)[None, :]
                          <= jnp.arange(S)[:, None])[None, None]
                attn_mask = causal & (kvalid[:, :S] > 0)[:, None, None, :]
            out = F.scaled_dot_product_attention(
                q, k, v, attn_mask=attn_mask, is_causal=attn_mask is None)
            new_cache = None
        else:
            out, new_cache = cached_attention(q, k, v, cache, cache_index,
                                              kvalid=kvalid,
                                              kv_start=kv_start,
                                              kv_write_pos=kv_write_pos)
        return out.reshape(B, S, H) @ self.out_proj + self.out_bias, new_cache


class GPTBlock(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        h = config.hidden_size
        init = I.Normal(0.0, config.initializer_range)
        self.ln_1 = nn.LayerNorm(h, epsilon=config.layer_norm_epsilon)
        self.attn = GPTAttention(config)
        self.ln_2 = nn.LayerNorm(h, epsilon=config.layer_norm_epsilon)
        self.fc_in = Parameter(init((h, config.intermediate_size), 'float32'),
                               spec=P(None, 'tp'))
        self.fc_in_bias = Parameter(jnp.zeros((config.intermediate_size,)),
                                    spec=P('tp'))
        self.fc_out = Parameter(init((config.intermediate_size, h), 'float32'),
                                spec=P('tp', None))
        self.fc_out_bias = Parameter(jnp.zeros((h,)))
        self.dropout = nn.Dropout(config.dropout)

    def forward(self, x, cache=None, cache_index=None, kvalid=None,
                kv_start=None, kv_write_pos=None):
        attn_out, new_cache = self.attn(self.ln_1(x), cache, cache_index,
                                        kvalid, kv_start, kv_write_pos)
        x = x + attn_out
        # gelu_new (tanh approximation) — GPT-2's canonical activation
        h = F.gelu(self.ln_2(x) @ self.fc_in + self.fc_in_bias,
                   approximate=True)
        return x + self.dropout(h @ self.fc_out + self.fc_out_bias), new_cache


class GPTModel(Layer):
    # wte/wpe are lookup tables (gather; wte.T serves the tied head) —
    # exempt from weight-only PTQ
    no_quantize = ('wte', 'wpe')

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        init = I.Normal(0.0, config.initializer_range)
        self.wte = Parameter(init((config.vocab_size, config.hidden_size),
                                  'float32'), spec=P('tp', None))
        self.wpe = Parameter(init((config.max_position_embeddings,
                                   config.hidden_size), 'float32'))
        self.drop = nn.Dropout(config.dropout)
        self.h = nn.LayerList([GPTBlock(config)
                               for _ in range(config.num_hidden_layers)])
        self.ln_f = nn.LayerNorm(config.hidden_size,
                                 epsilon=config.layer_norm_epsilon)

    def forward(self, input_ids, positions=None, caches=None,
                cache_index=None, kvalid=None, kv_start=None,
                kv_write_pos=None):
        B, S = input_ids.shape
        if cache_index is None and S > self.config.max_position_embeddings:
            raise ValueError(
                f'sequence length {S} exceeds the learned position table '
                f'(max_position_embeddings='
                f'{self.config.max_position_embeddings})')
        if positions is None:
            from .generation import default_positions

            positions = default_positions(B, S, cache_index, kv_write_pos)
        # pad rows clip into the learned table (masked out anyway)
        pos = jnp.clip(positions, 0,
                       self.config.max_position_embeddings - 1)
        x = self.drop(self.wte[input_ids] + self.wpe[pos])
        new_caches = [] if caches is not None else None
        for i, block in enumerate(self.h):
            cache = caches[i] if caches is not None else None
            x, nc = block(x, cache, cache_index, kvalid, kv_start,
                          kv_write_pos)
            if new_caches is not None:
                new_caches.append(nc)
        return self.ln_f(x), new_caches


class GPTForCausalLM(GenerationMixin, Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.transformer = GPTModel(config)
        if config.tie_word_embeddings:
            self.lm_head = None
        else:
            init = I.Normal(0.0, config.initializer_range)
            self.lm_head = Parameter(
                init((config.hidden_size, config.vocab_size), 'float32'),
                spec=P(None, 'tp'))


    def cache_dtype(self):
        return self.transformer.wte.dtype

    def init_cache(self, batch_size, max_len, dtype=None, quantized=False):
        limit = self.config.max_position_embeddings
        if max_len > limit:
            raise ValueError(
                f'prompt + max_new_tokens = {max_len} exceeds the learned '
                f'position table (max_position_embeddings={limit}); the '
                f'gather would silently clamp to the last row. Unlike '
                f'RoPE models, GPT cannot extrapolate positions.')
        return super().init_cache(batch_size, max_len, dtype,
                                  quantized=quantized)

    def forward(self, input_ids, positions=None, caches=None,
                cache_index=None, kvalid=None, kv_start=None,
                kv_write_pos=None):
        hidden, new_caches = self.transformer(
            input_ids, positions, caches, cache_index, kvalid, kv_start,
            kv_write_pos)
        if self.lm_head is None:
            logits = hidden @ self.transformer.wte.T
        else:
            logits = hidden @ self.lm_head
        if caches is not None:
            return logits, new_caches
        return logits

    def loss(self, input_ids, labels=None):
        if labels is None:
            labels = input_ids[:, 1:]
            input_ids = input_ids[:, :-1]
        logits = self(input_ids).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.take_along_axis(logp, labels[..., None], axis=-1).mean()
