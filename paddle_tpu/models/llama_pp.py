"""Pipeline-parallel Llama (ref: fleet/meta_parallel/pipeline_parallel.py
applied to PaddleNLP Llama: PipelineLayer partitions the decoder stack).

Composition story (SURVEY §2.7 hybrid): embedding + head are
tp/replicated as usual; the decoder stack runs under the GPipe
`shard_map` schedule over the 'pp' mesh axis for inference, and the
fused 1F1B schedule (default; 'gpipe'/'interleaved' selectable) for the
training loss, with tp sharding *inside*
each stage handled by GSPMD — dp×tp×pp in one jitted train step.

Stage parameters live in a `nn.LayerList` whose leaves carry a leading
stage axis (sharded over 'pp'), so they are ordinary trainable pytree
state: `value_and_grad` + optimizer updates see them like any weight.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .. import nn
from ..distributed.pipeline import (pipeline_1f1b_loss,
                                    pipeline_apply,
                                    pipeline_interleaved_1f1b_loss,
                                    stack_stage_params)
from ..nn import initializer as I
from ..nn.layer.base import Layer, Parameter
from .llama import LlamaConfig, LlamaDecoderLayer


class LlamaForCausalLMPipelined(Layer):
    """Llama with its decoder stack partitioned into pp stages.

    Requires config.num_hidden_layers % mesh.shape['pp'] == 0 and
    batch % n_microbatches == 0.
    """

    def __init__(self, config: LlamaConfig, mesh, n_microbatches=2,
                 schedule='1f1b', n_virtual=1):
        super().__init__()
        if schedule not in ('gpipe', '1f1b', 'interleaved'):
            raise ValueError(
                f"schedule must be 'gpipe'|'1f1b'|'interleaved', "
                f'got {schedule}')
        if n_virtual > 1 and schedule != 'interleaved':
            raise ValueError("n_virtual > 1 requires schedule='interleaved'")
        self.schedule = schedule
        self.n_virtual = n_virtual if schedule == 'interleaved' else 1
        self.config = config
        n_stages = mesh.shape['pp']
        n_parts = n_stages * self.n_virtual
        if config.num_hidden_layers % n_parts:
            raise ValueError(
                f'{config.num_hidden_layers} layers not divisible into '
                f'{n_parts} pp (virtual) stages')
        self.per_stage = config.num_hidden_layers // n_parts
        self.n_stages = n_stages
        self._mesh = mesh
        self._n_micro = n_microbatches
        init = I.Normal(0.0, config.initializer_range)
        self.embed_tokens = Parameter(
            init((config.vocab_size, config.hidden_size), config.dtype))
        blocks = [LlamaDecoderLayer(config)
                  for _ in range(config.num_hidden_layers)]
        stages = [blocks[s * self.per_stage:(s + 1) * self.per_stage]
                  for s in range(n_parts)]
        # list of `per_stage` block-pytrees, leaves stacked (n_stages, ...)
        self.stage_blocks = nn.LayerList(stack_stage_params(stages))
        self.norm = nn.RMSNorm(config.hidden_size, epsilon=config.rms_norm_eps)
        self.lm_head = Parameter(
            init((config.hidden_size, config.vocab_size), config.dtype))

    def forward(self, input_ids):
        """input_ids: (batch, S); batch % n_microbatches == 0."""
        B, S = input_ids.shape
        n = self._n_micro
        assert B % n == 0, f'batch {B} % microbatches {n} != 0'
        x = self.embed_tokens[input_ids]                     # (B, S, H)
        mbs = x.reshape(n, B // n, S, -1)

        if self.schedule == 'interleaved':
            # inference path: chunks applied in virtual-stage order
            stage_fn = self._stage_fn()
            out = mbs
            V = self.n_stages * self.n_virtual
            for vs in range(V):
                chunk = jax.tree.map(lambda a: a[vs],
                                     list(self.stage_blocks))
                out = jax.vmap(lambda mb, c=chunk: stage_fn(c, mb))(out)
        else:
            out = pipeline_apply(list(self.stage_blocks), mbs,
                                 self._stage_fn(), self._mesh, n, axis='pp')
        hidden = self.norm(out.reshape(B, S, -1))
        return hidden @ self.lm_head

    def _stage_fn(self):
        per = self.per_stage

        def stage_fn(stage_params, h):
            mb, s, _ = h.shape
            positions = jnp.broadcast_to(
                jnp.arange(s)[None], (mb, s)).astype(jnp.int32)
            for i in range(per):
                h, _ = stage_params[i](h, positions)
            return h

        return stage_fn

    def loss(self, input_ids, labels=None):
        from ..ops import softmax_cross_entropy

        if labels is None:
            labels = input_ids[:, 1:]
            input_ids = input_ids[:, :-1]
        if self.schedule in ('1f1b', 'interleaved'):
            return self._loss_1f1b(input_ids, labels)
        logits = self(input_ids)
        return softmax_cross_entropy(logits, labels).mean()

    def _loss_1f1b(self, input_ids, labels):
        """1F1B fused fwd/bwd: loss (norm+head+xent) runs on the LAST
        stage per microbatch so backward starts while later microbatches
        are still in flight; live activations stay O(n_stages) (ref:
        pipeline_parallel.py::forward_backward_pipeline 1F1B)."""
        from ..ops import softmax_cross_entropy

        B, S = input_ids.shape
        n = self._n_micro
        assert B % n == 0, f'batch {B} % microbatches {n} != 0'
        x = self.embed_tokens[input_ids]                   # (B, S, H)
        mbs = x.reshape(n, B // n, S, -1)
        tgts = labels.reshape(n, B // n, S)
        extra = {'norm': self.norm, 'head': self.lm_head}

        def loss_fn(extra, y, tgt):
            hidden = extra['norm'](y)
            logits = hidden @ extra['head']
            return softmax_cross_entropy(logits, tgt).mean()

        if self.schedule == 'interleaved':
            return pipeline_interleaved_1f1b_loss(
                list(self.stage_blocks), extra, mbs, tgts, self._stage_fn(),
                loss_fn, self._mesh, n, self.n_virtual, axis='pp')
        return pipeline_1f1b_loss(
            list(self.stage_blocks), extra, mbs, tgts, self._stage_fn(),
            loss_fn, self._mesh, n, axis='pp')
