"""Checkpoint converters for the model zoo.

ref (capability): the reference ecosystem ships weight converters
between frameworks (PaddleNLP's `convert_*` utilities for HF
checkpoints). Here: HuggingFace Llama -> `LlamaForCausalLM`, which
doubles as an end-to-end numerics validation of the flagship (RoPE
rotate-half convention, GQA head layout, SwiGLU wiring) against the
canonical implementation — see tests/test_hf_convert.py.
"""
from __future__ import annotations

import re

import jax.numpy as jnp
import numpy as np

from ..nn.layer.base import Parameter
from .llama import LlamaConfig, LlamaForCausalLM


def _np(v):
    """torch tensor / numpy / jax -> numpy (torch bf16 upcast via float)."""
    if hasattr(v, 'detach'):                      # torch tensor
        v = v.detach().cpu()
        if str(getattr(v, 'dtype', '')) == 'torch.bfloat16':
            v = v.float()
        v = v.numpy()
    return np.asarray(v)


def hf_llama_config(hf_config) -> LlamaConfig:
    """Map a transformers LlamaConfig (object or dict) onto ours."""
    get = (hf_config.get if isinstance(hf_config, dict)
           else lambda k, d=None: getattr(hf_config, k, d))
    scaling = get('rope_scaling')
    if scaling and (not isinstance(scaling, dict)
                    or scaling.get('rope_type', scaling.get('type',
                                                            'default'))
                    not in (None, 'default')):
        raise ValueError(
            f'rope_scaling={scaling!r} is not supported by this converter '
            f'(plain rope_theta RoPE only) — converting would produce '
            f'silently wrong logits at long positions')
    act = get('hidden_act', 'silu')
    if act not in ('silu', 'swish'):
        raise ValueError(
            f'hidden_act={act!r} unsupported: the model hardcodes SwiGLU')
    return LlamaConfig(
        vocab_size=get('vocab_size'),
        hidden_size=get('hidden_size'),
        intermediate_size=get('intermediate_size'),
        num_hidden_layers=get('num_hidden_layers'),
        num_attention_heads=get('num_attention_heads'),
        num_key_value_heads=(get('num_key_value_heads')
                             or get('num_attention_heads')),
        max_position_embeddings=get('max_position_embeddings', 4096),
        rms_norm_eps=get('rms_norm_eps', 1e-5),
        rope_theta=get('rope_theta', 10000.0),
        tie_word_embeddings=bool(get('tie_word_embeddings', False)),
    )


def from_hf_llama(state_dict, config, dtype=None):
    """Build a LlamaForCausalLM from a HuggingFace Llama state dict.

    state_dict: name -> array (torch tensors, numpy, or jax arrays;
    the usual ``model.layers.N...`` HF names). config: our LlamaConfig
    (use `hf_llama_config` to derive one). HF Linear weights are
    (out, in) applied as x·Wᵀ; ours are (in, out) applied as x·W, so
    every projection transposes.
    """
    def arr(v):
        a = jnp.asarray(_np(v))
        return a.astype(dtype) if dtype else a

    sd = {k: state_dict[k] for k in state_dict}
    model = LlamaForCausalLM(config)

    def assign(layer, name, value):
        # keep the layer's registered PartitionSpec (tp/vocab sharding)
        # — a bare Parameter would overwrite the meta and the converted
        # model would silently lose tensor parallelism
        meta = layer.meta_for(name)
        layer.__setattr__(name, Parameter(
            arr(value), spec=meta.spec if meta is not None else None))

    m = model.model
    assign(m, 'embed_tokens', sd.pop('model.embed_tokens.weight'))
    for i, layer in enumerate(m.layers):
        p = f'model.layers.{i}.'
        attn = layer.self_attn
        for w in ('q_proj', 'k_proj', 'v_proj', 'o_proj'):
            assign(attn, w, np.asarray(_np(sd.pop(
                p + f'self_attn.{w}.weight'))).T)
        mlp = layer.mlp
        for w in ('gate_proj', 'up_proj', 'down_proj'):
            assign(mlp, w, np.asarray(_np(sd.pop(p + f'mlp.{w}.weight'))).T)
        assign(layer.input_layernorm, 'weight',
               sd.pop(p + 'input_layernorm.weight'))
        assign(layer.post_attention_layernorm, 'weight',
               sd.pop(p + 'post_attention_layernorm.weight'))
    assign(m.norm, 'weight', sd.pop('model.norm.weight'))
    if config.tie_word_embeddings:
        sd.pop('lm_head.weight', None)
    else:
        assign(model, 'lm_head', np.asarray(_np(sd.pop('lm_head.weight'))).T)

    leftovers = [k for k in sd
                 if not re.search(r'rotary_emb|inv_freq|position_ids', k)]
    if leftovers:
        raise ValueError(f'unconverted HF weights: {leftovers[:8]}')
    return model


def from_hf_llama_pretrained(model_or_path, dtype=None):
    """Convenience: accept a transformers LlamaForCausalLM instance (or a
    local path loadable by transformers) and convert it."""
    if isinstance(model_or_path, str):
        from transformers import LlamaForCausalLM as HFLlama

        model_or_path = HFLlama.from_pretrained(model_or_path)
    cfg = hf_llama_config(model_or_path.config)
    return from_hf_llama(model_or_path.state_dict(), cfg, dtype=dtype)


# ---------------------------------------------------------------------------
# BERT (encoder-stack anchor, mirrors the Llama converter)
# ---------------------------------------------------------------------------

def hf_bert_config(hf_config):
    """Map a transformers BertConfig (object or dict) onto ours."""
    from .bert import BertConfig

    get = (hf_config.get if isinstance(hf_config, dict)
           else lambda k, d=None: getattr(hf_config, k, d))
    act = get('hidden_act', 'gelu')
    if act not in ('gelu',):
        raise ValueError(f'hidden_act={act!r} unsupported: the encoder '
                         f'hardcodes exact gelu')
    return BertConfig(
        vocab_size=get('vocab_size'),
        hidden_size=get('hidden_size'),
        num_hidden_layers=get('num_hidden_layers'),
        num_attention_heads=get('num_attention_heads'),
        intermediate_size=get('intermediate_size'),
        max_position_embeddings=get('max_position_embeddings', 512),
        type_vocab_size=get('type_vocab_size', 2),
        layer_norm_eps=get('layer_norm_eps', 1e-12),
        dropout=0.0,                       # inference conversion
    )


def from_hf_bert(state_dict, config, dtype=None):
    """Build a BertModel from a HuggingFace bert-base-style state dict.

    HF Linear weights are (out, in); ours are (in, out) — transposed on
    the way in. Returns the bare encoder (ref transformers BertModel);
    wrap in BertForSequenceClassification/MaskedLM yourself (pretraining
    and fine-tuning heads — cls.*, classifier.*, qa_outputs.* — are
    skipped; checkpoints without a pooler keep the fresh random one).
    """
    from .bert import BertModel

    sd = {k: state_dict[k] for k in state_dict}
    model = BertModel(config)

    def assign(layer, name, value, transpose=False):
        v = _np(value)
        if transpose:
            v = v.T
        a = jnp.asarray(v)
        if dtype:
            a = a.astype(dtype)
        meta = layer.meta_for(name)
        layer.__setattr__(name, Parameter(
            a, spec=meta.spec if meta is not None else None))

    def pop(key):
        return sd.pop(f'bert.{key}' if f'bert.{key}' in sd else key)

    emb = model.embeddings
    assign(emb, 'word_embeddings', pop('embeddings.word_embeddings.weight'))
    assign(emb, 'position_embeddings',
           pop('embeddings.position_embeddings.weight'))
    assign(emb, 'token_type_embeddings',
           pop('embeddings.token_type_embeddings.weight'))
    assign(emb.layer_norm, 'weight', pop('embeddings.LayerNorm.weight'))
    assign(emb.layer_norm, 'bias', pop('embeddings.LayerNorm.bias'))

    for i, layer in enumerate(model.encoder):
        p = f'encoder.layer.{i}.'
        for ours, theirs in (('q_proj', 'attention.self.query'),
                             ('k_proj', 'attention.self.key'),
                             ('v_proj', 'attention.self.value'),
                             ('out_proj', 'attention.output.dense')):
            lin = getattr(layer.attn, ours)
            assign(lin, 'weight', pop(p + theirs + '.weight'), transpose=True)
            assign(lin, 'bias', pop(p + theirs + '.bias'))
        assign(layer.ln1, 'weight', pop(p + 'attention.output.LayerNorm.weight'))
        assign(layer.ln1, 'bias', pop(p + 'attention.output.LayerNorm.bias'))
        assign(layer.fc1, 'weight', pop(p + 'intermediate.dense.weight'),
               transpose=True)
        assign(layer.fc1, 'bias', pop(p + 'intermediate.dense.bias'))
        assign(layer.fc2, 'weight', pop(p + 'output.dense.weight'),
               transpose=True)
        assign(layer.fc2, 'bias', pop(p + 'output.dense.bias'))
        assign(layer.ln2, 'weight', pop(p + 'output.LayerNorm.weight'))
        assign(layer.ln2, 'bias', pop(p + 'output.LayerNorm.bias'))

    if any('pooler.dense.weight' in k for k in sd):
        assign(model.pooler, 'weight', pop('pooler.dense.weight'),
               transpose=True)
        assign(model.pooler, 'bias', pop('pooler.dense.bias'))
    else:
        # MaskedLM-style checkpoints ship no pooler (add_pooling_layer
        # False); the fresh random pooler stays
        import warnings

        warnings.warn('state dict has no pooler weights; pooled output '
                      'uses a randomly initialised pooler', stacklevel=2)

    leftovers = [k for k in sd if not re.search(
        r'position_ids|cls\.|seq_relationship|classifier\.|qa_outputs\.',
        k)]
    if leftovers:
        raise ValueError(f'unconverted HF weights: {leftovers[:8]}')
    return model
