"""Checkpoint converters for the model zoo.

ref (capability): the reference ecosystem ships weight converters
between frameworks (PaddleNLP's `convert_*` utilities for HF
checkpoints). Here: HuggingFace Llama -> `LlamaForCausalLM`, which
doubles as an end-to-end numerics validation of the flagship (RoPE
rotate-half convention, GQA head layout, SwiGLU wiring) against the
canonical implementation — see tests/test_hf_convert.py.
"""
from __future__ import annotations

import re

import jax.numpy as jnp
import numpy as np

from ..nn.layer.base import Parameter
from .llama import LlamaConfig, LlamaForCausalLM


def _np(v):
    """torch tensor / numpy / jax -> numpy (torch bf16 upcast via float)."""
    if hasattr(v, 'detach'):                      # torch tensor
        v = v.detach().cpu()
        if str(getattr(v, 'dtype', '')) == 'torch.bfloat16':
            v = v.float()
        v = v.numpy()
    return np.asarray(v)


def _make_assign(dtype=None):
    """Spec-preserving Parameter assignment shared by all converters:
    keeps the layer's registered PartitionSpec (tensor parallelism would
    silently vanish otherwise) and applies the optional load dtype."""
    def assign(layer, name, value, transpose=False):
        v = _np(value)
        if transpose:
            v = v.T
        a = jnp.asarray(v)
        if dtype:
            a = a.astype(dtype)
        meta = layer.meta_for(name)
        layer.__setattr__(name, Parameter(
            a, spec=meta.spec if meta is not None else None))
    return assign


def _make_pop(sd, prefix):
    """Pop keys tolerating an optional wrapper prefix ('bert.',
    'transformer.', ...)."""
    def pop(key):
        return sd.pop(f'{prefix}{key}' if f'{prefix}{key}' in sd else key)
    return pop


def hf_llama_config(hf_config) -> LlamaConfig:
    """Map a transformers LlamaConfig (object or dict) onto ours."""
    get = (hf_config.get if isinstance(hf_config, dict)
           else lambda k, d=None: getattr(hf_config, k, d))
    scaling = get('rope_scaling')
    if scaling and (not isinstance(scaling, dict)
                    or scaling.get('rope_type', scaling.get('type',
                                                            'default'))
                    not in (None, 'default', 'llama3', 'yarn')):
        raise ValueError(
            f'rope_scaling={scaling!r} is not supported by this converter '
            f"(plain rope_theta RoPE, rope_type='llama3', or 'yarn' only) "
            f'— converting would produce silently wrong logits at long '
            f'positions')
    if scaling and scaling.get('rope_type',
                               scaling.get('type')) == 'llama3':
        missing = [k for k in ('factor', 'low_freq_factor',
                               'high_freq_factor',
                               'original_max_position_embeddings')
                   if k not in scaling]
        if missing:
            raise ValueError(
                f"rope_scaling rope_type='llama3' is missing required "
                f'keys {missing} — refusing rather than guessing '
                f'defaults transformers would reject')
    if scaling and scaling.get('rope_type',
                               scaling.get('type')) == 'yarn':
        if 'factor' not in scaling:
            raise ValueError(
                "rope_scaling rope_type='yarn' is missing 'factor' — "
                'refusing rather than guessing')
    act = get('hidden_act', 'silu')
    if act not in ('silu', 'swish'):
        raise ValueError(
            f'hidden_act={act!r} unsupported: the model hardcodes SwiGLU')
    return LlamaConfig(
        vocab_size=get('vocab_size'),
        hidden_size=get('hidden_size'),
        intermediate_size=get('intermediate_size'),
        num_hidden_layers=get('num_hidden_layers'),
        num_attention_heads=get('num_attention_heads'),
        num_key_value_heads=(get('num_key_value_heads')
                             or get('num_attention_heads')),
        max_position_embeddings=get('max_position_embeddings', 4096),
        rms_norm_eps=get('rms_norm_eps', 1e-5),
        rope_theta=get('rope_theta', 10000.0),
        rope_scaling=dict(scaling) if scaling else None,
        tie_word_embeddings=bool(get('tie_word_embeddings', False)),
        # Llama-architecture checkpoints with qkv biases (HF
        # attention_bias=True) convert via the same bias path Qwen2 uses;
        # without this mapping they'd fail late with an opaque
        # 'unconverted HF weights: [...bias...]'
        attention_bias=bool(get('attention_bias', False)),
        # Mistral-style SWA: sliding_window set and no gating flag (a
        # Qwen2 config gates it behind use_sliding_window — handled in
        # hf_qwen2_config); Llama configs have no sliding_window at all
        sliding_window=(get('sliding_window')
                        if get('use_sliding_window', True) else None),
    )


def from_hf_llama(state_dict, config, dtype=None):
    """Build a LlamaForCausalLM from a HuggingFace Llama state dict.

    state_dict: name -> array (torch tensors, numpy, or jax arrays;
    the usual ``model.layers.N...`` HF names). config: our LlamaConfig
    (use `hf_llama_config` to derive one). HF Linear weights are
    (out, in) applied as x·Wᵀ; ours are (in, out) applied as x·W, so
    every projection transposes.
    """
    sd = {k: state_dict[k] for k in state_dict}
    model = LlamaForCausalLM(config)
    assign = _make_assign(dtype)

    m = model.model
    assign(m, 'embed_tokens', sd.pop('model.embed_tokens.weight'))
    for i, layer in enumerate(m.layers):
        p = f'model.layers.{i}.'
        attn = layer.self_attn
        for w in ('q_proj', 'k_proj', 'v_proj', 'o_proj'):
            assign(attn, w, sd.pop(p + f'self_attn.{w}.weight'),
                   transpose=True)
        if config.attention_bias:          # Qwen2-style qkv biases
            for w in ('q', 'k', 'v'):
                assign(attn, f'{w}_bias',
                       sd.pop(p + f'self_attn.{w}_proj.bias'))
        mlp = layer.mlp
        for w in ('gate_proj', 'up_proj', 'down_proj'):
            assign(mlp, w, sd.pop(p + f'mlp.{w}.weight'), transpose=True)
        assign(layer.input_layernorm, 'weight',
               sd.pop(p + 'input_layernorm.weight'))
        assign(layer.post_attention_layernorm, 'weight',
               sd.pop(p + 'post_attention_layernorm.weight'))
    assign(m.norm, 'weight', sd.pop('model.norm.weight'))
    if config.tie_word_embeddings:
        sd.pop('lm_head.weight', None)
    else:
        assign(model, 'lm_head', sd.pop('lm_head.weight'), transpose=True)

    leftovers = [k for k in sd
                 if not re.search(r'rotary_emb|inv_freq|position_ids', k)]
    if leftovers:
        raise ValueError(f'unconverted HF weights: {leftovers[:8]}')
    return model


def from_hf_llama_pretrained(model_or_path, dtype=None):
    """Convenience: accept a transformers LlamaForCausalLM instance (or a
    local path loadable by transformers) and convert it."""
    if isinstance(model_or_path, str):
        from transformers import LlamaForCausalLM as HFLlama

        model_or_path = HFLlama.from_pretrained(model_or_path)
    cfg = hf_llama_config(model_or_path.config)
    return from_hf_llama(model_or_path.state_dict(), cfg, dtype=dtype)


# ---------------------------------------------------------------------------
# BERT (encoder-stack anchor, mirrors the Llama converter)
# ---------------------------------------------------------------------------

def hf_bert_config(hf_config):
    """Map a transformers BertConfig (object or dict) onto ours."""
    from .bert import BertConfig

    get = (hf_config.get if isinstance(hf_config, dict)
           else lambda k, d=None: getattr(hf_config, k, d))
    act = get('hidden_act', 'gelu')
    if act not in ('gelu',):
        raise ValueError(f'hidden_act={act!r} unsupported: the encoder '
                         f'hardcodes exact gelu')
    pet = get('position_embedding_type', 'absolute')
    if pet != 'absolute':
        raise ValueError(
            f'position_embedding_type={pet!r} unsupported: the encoder has '
            f'no relative-position attention term — converting would give '
            f'silently wrong hidden states')
    return BertConfig(
        vocab_size=get('vocab_size'),
        hidden_size=get('hidden_size'),
        num_hidden_layers=get('num_hidden_layers'),
        num_attention_heads=get('num_attention_heads'),
        intermediate_size=get('intermediate_size'),
        max_position_embeddings=get('max_position_embeddings', 512),
        type_vocab_size=get('type_vocab_size', 2),
        layer_norm_eps=get('layer_norm_eps', 1e-12),
        dropout=0.0,                       # inference conversion
    )


def from_hf_bert(state_dict, config, dtype=None):
    """Build a BertModel from a HuggingFace bert-base-style state dict.

    HF Linear weights are (out, in); ours are (in, out) — transposed on
    the way in. Returns the bare encoder (ref transformers BertModel);
    wrap in BertForSequenceClassification/MaskedLM yourself (pretraining
    and fine-tuning heads — cls.*, classifier.*, qa_outputs.* — are
    skipped; checkpoints without a pooler keep the fresh random one).
    """
    from .bert import BertModel

    sd = {k: state_dict[k] for k in state_dict}
    model = BertModel(config)
    assign = _make_assign(dtype)
    pop = _make_pop(sd, 'bert.')

    emb = model.embeddings
    assign(emb, 'word_embeddings', pop('embeddings.word_embeddings.weight'))
    assign(emb, 'position_embeddings',
           pop('embeddings.position_embeddings.weight'))
    assign(emb, 'token_type_embeddings',
           pop('embeddings.token_type_embeddings.weight'))
    assign(emb.layer_norm, 'weight', pop('embeddings.LayerNorm.weight'))
    assign(emb.layer_norm, 'bias', pop('embeddings.LayerNorm.bias'))

    for i, layer in enumerate(model.encoder):
        p = f'encoder.layer.{i}.'
        for ours, theirs in (('q_proj', 'attention.self.query'),
                             ('k_proj', 'attention.self.key'),
                             ('v_proj', 'attention.self.value'),
                             ('out_proj', 'attention.output.dense')):
            lin = getattr(layer.attn, ours)
            assign(lin, 'weight', pop(p + theirs + '.weight'), transpose=True)
            assign(lin, 'bias', pop(p + theirs + '.bias'))
        assign(layer.ln1, 'weight', pop(p + 'attention.output.LayerNorm.weight'))
        assign(layer.ln1, 'bias', pop(p + 'attention.output.LayerNorm.bias'))
        assign(layer.fc1, 'weight', pop(p + 'intermediate.dense.weight'),
               transpose=True)
        assign(layer.fc1, 'bias', pop(p + 'intermediate.dense.bias'))
        assign(layer.fc2, 'weight', pop(p + 'output.dense.weight'),
               transpose=True)
        assign(layer.fc2, 'bias', pop(p + 'output.dense.bias'))
        assign(layer.ln2, 'weight', pop(p + 'output.LayerNorm.weight'))
        assign(layer.ln2, 'bias', pop(p + 'output.LayerNorm.bias'))

    if any('pooler.dense.weight' in k for k in sd):
        assign(model.pooler, 'weight', pop('pooler.dense.weight'),
               transpose=True)
        assign(model.pooler, 'bias', pop('pooler.dense.bias'))
    else:
        # MaskedLM-style checkpoints ship no pooler (add_pooling_layer
        # False); the fresh random pooler stays
        import warnings

        warnings.warn('state dict has no pooler weights; pooled output '
                      'uses a randomly initialised pooler', stacklevel=2)

    leftovers = [k for k in sd if not re.search(
        r'position_ids|cls\.|seq_relationship|classifier\.|qa_outputs\.',
        k)]
    if leftovers:
        raise ValueError(f'unconverted HF weights: {leftovers[:8]}')
    return model


# ---------------------------------------------------------------------------
# GPT-2 (learned-pos-emb pre-LN decoder anchor)
# ---------------------------------------------------------------------------

def hf_gpt2_config(hf_config):
    """Map a transformers GPT2Config (object or dict) onto ours."""
    from .gpt import GPTConfig

    get = (hf_config.get if isinstance(hf_config, dict)
           else lambda k, d=None: getattr(hf_config, k, d))
    act = get('activation_function', 'gelu_new')
    if act not in ('gelu_new', 'gelu_pytorch_tanh'):
        raise ValueError(f'activation_function={act!r} unsupported: the '
                         f'model hardcodes gelu_new (tanh approximation)')
    if not get('tie_word_embeddings', True):
        raise ValueError('untied GPT-2 embeddings unsupported: the model '
                         'computes logits as hidden @ wte.T')
    for flag in ('scale_attn_by_inverse_layer_idx', 'reorder_and_upcast_attn'):
        if get(flag, False):
            raise ValueError(
                f'{flag}=True unsupported: attention always scales by '
                f'1/sqrt(head_dim) — converting would give silently wrong '
                f'logits')
    if get('scale_attn_weights', True) is False:
        raise ValueError('scale_attn_weights=False unsupported')
    h = get('n_embd')
    return GPTConfig(
        vocab_size=get('vocab_size'),
        hidden_size=h,
        num_hidden_layers=get('n_layer'),
        num_attention_heads=get('n_head'),
        intermediate_size=get('n_inner') or 4 * h,
        max_position_embeddings=get('n_positions', 1024),
        layer_norm_epsilon=get('layer_norm_epsilon', 1e-5),
        dropout=0.0,                        # inference conversion
        tie_word_embeddings=True,           # GPT-2 always ties
    )


def from_hf_gpt2(state_dict, config, dtype=None):
    """Build a GPTForCausalLM from a HuggingFace GPT-2 state dict.

    HF GPT-2 uses Conv1D modules whose weights are ALREADY (in, out) —
    no transposes, unlike the Llama/BERT converters.
    """
    from .gpt import GPTForCausalLM

    sd = {k: state_dict[k] for k in state_dict}
    model = GPTForCausalLM(config)
    assign = _make_assign(dtype)
    pop = _make_pop(sd, 'transformer.')

    t = model.transformer
    assign(t, 'wte', pop('wte.weight'))
    assign(t, 'wpe', pop('wpe.weight'))
    for i, block in enumerate(t.h):
        p = f'h.{i}.'
        assign(block.ln_1, 'weight', pop(p + 'ln_1.weight'))
        assign(block.ln_1, 'bias', pop(p + 'ln_1.bias'))
        assign(block.attn, 'qkv', pop(p + 'attn.c_attn.weight'))
        assign(block.attn, 'qkv_bias', pop(p + 'attn.c_attn.bias'))
        assign(block.attn, 'out_proj', pop(p + 'attn.c_proj.weight'))
        assign(block.attn, 'out_bias', pop(p + 'attn.c_proj.bias'))
        assign(block.ln_2, 'weight', pop(p + 'ln_2.weight'))
        assign(block.ln_2, 'bias', pop(p + 'ln_2.bias'))
        assign(block, 'fc_in', pop(p + 'mlp.c_fc.weight'))
        assign(block, 'fc_in_bias', pop(p + 'mlp.c_fc.bias'))
        assign(block, 'fc_out', pop(p + 'mlp.c_proj.weight'))
        assign(block, 'fc_out_bias', pop(p + 'mlp.c_proj.bias'))
    assign(t.ln_f, 'weight', pop('ln_f.weight'))
    assign(t.ln_f, 'bias', pop('ln_f.bias'))

    leftovers = [k for k in sd if not re.search(
        r'attn\.bias|attn\.masked_bias|lm_head\.weight', k)]
    if leftovers:
        raise ValueError(f'unconverted HF weights: {leftovers[:8]}')
    return model


# ---------------------------------------------------------------------------
# Mixtral (sparse-MoE decoder → MoEForCausalLM, mirrors the Llama converter)
# ---------------------------------------------------------------------------


def hf_mixtral_config(hf_config):
    """Map a transformers MixtralConfig (object or dict) onto MoEConfig.

    Mixtral = Llama attention + top-k routed SwiGLU experts, no shared
    experts. `dispatch_mode='ragged'` (dropless) is forced: the GShard
    capacity dispatch drops tokens, which would silently diverge from
    the HF reference.
    """
    from .moe_lm import MoEConfig

    get = (hf_config.get if isinstance(hf_config, dict)
           else lambda k, d=None: getattr(hf_config, k, d))
    act = get('hidden_act', 'silu')
    if act not in ('silu', 'swish'):
        raise ValueError(
            f'hidden_act={act!r} unsupported: the experts hardcode SwiGLU')
    if get('sliding_window') not in (None, 0):
        raise ValueError(
            f"sliding_window={get('sliding_window')!r} unsupported: "
            f'attention here is full-causal — converting would give '
            f'silently wrong logits past the window')
    if get('tie_word_embeddings', False):
        raise ValueError(
            'tie_word_embeddings=True unsupported: MoEForCausalLM has a '
            'separate lm_head (and tied checkpoints omit lm_head.weight)')
    return MoEConfig(
        vocab_size=get('vocab_size'),
        hidden_size=get('hidden_size'),
        intermediate_size=get('intermediate_size'),
        num_hidden_layers=get('num_hidden_layers'),
        num_attention_heads=get('num_attention_heads'),
        num_key_value_heads=(get('num_key_value_heads')
                             or get('num_attention_heads')),
        num_experts=get('num_local_experts'),
        num_shared_experts=0,
        top_k=get('num_experts_per_tok', 2),
        max_position_embeddings=get('max_position_embeddings', 4096),
        rms_norm_eps=get('rms_norm_eps', 1e-5),
        rope_theta=get('rope_theta', 1e6),
        aux_loss_weight=get('router_aux_loss_coef', 0.001),
        dispatch_mode='ragged',
    )


def from_hf_mixtral(state_dict, config, dtype=None):
    """Build a MoEForCausalLM from a HuggingFace Mixtral state dict.

    Routing parity: HF softmaxes ALL router logits, takes top-k, and
    renormalises over the chosen k — the same operation `_topk_gates`
    performs. HF per-expert Linears w1/w3/w2 are (out, in); ours are
    batched (E, in, out) tensors w_gate/w_up/w_down, so each expert
    transposes then stacks.
    """
    from .moe_lm import MoEForCausalLM

    sd = {k: state_dict[k] for k in state_dict}
    model = MoEForCausalLM(config)
    assign = _make_assign(dtype)

    assign(model, 'embed_tokens', sd.pop('model.embed_tokens.weight'))
    for i, layer in enumerate(model.layers):
        p = f'model.layers.{i}.'
        attn = layer.self_attn
        for w in ('q_proj', 'k_proj', 'v_proj', 'o_proj'):
            assign(attn, w, sd.pop(p + f'self_attn.{w}.weight'),
                   transpose=True)
        moe = layer.moe
        assign(moe, 'gate', sd.pop(p + 'block_sparse_moe.gate.weight'),
               transpose=True)
        stacks = {'w1': [], 'w3': [], 'w2': []}
        for e in range(config.num_experts):
            for w in stacks:
                stacks[w].append(
                    _np(sd.pop(p + f'block_sparse_moe.experts.{e}.{w}.weight'))
                    .T)
        assign(moe.experts, 'w_gate', np.stack(stacks['w1']))
        assign(moe.experts, 'w_up', np.stack(stacks['w3']))
        assign(moe.experts, 'w_down', np.stack(stacks['w2']))
        assign(layer.input_layernorm, 'weight',
               sd.pop(p + 'input_layernorm.weight'))
        assign(layer.post_attention_layernorm, 'weight',
               sd.pop(p + 'post_attention_layernorm.weight'))
    assign(model.norm, 'weight', sd.pop('model.norm.weight'))
    assign(model, 'lm_head', sd.pop('lm_head.weight'), transpose=True)

    leftovers = [k for k in sd
                 if not re.search(r'rotary_emb|inv_freq|position_ids', k)]
    if leftovers:
        raise ValueError(f'unconverted HF weights: {leftovers[:8]}')
    return model


def from_hf_mixtral_pretrained(model_or_path, dtype=None):
    """Accept a transformers MixtralForCausalLM (or local path) and
    convert it."""
    if isinstance(model_or_path, str):
        from transformers import MixtralForCausalLM as HFMixtral

        model_or_path = HFMixtral.from_pretrained(model_or_path)
    cfg = hf_mixtral_config(model_or_path.config)
    return from_hf_mixtral(model_or_path.state_dict(), cfg, dtype=dtype)


# ---------------------------------------------------------------------------
# Qwen2 (Llama architecture + qkv biases, mirrors the Llama converter)
# ---------------------------------------------------------------------------


def hf_qwen2_config(hf_config) -> LlamaConfig:
    """Map a transformers Qwen2Config onto LlamaConfig: identical
    architecture (RMSNorm/RoPE/SwiGLU/GQA) plus qkv biases
    (`attention_bias=True`). Reuses the Llama mapping — including its
    rope_scaling / hidden_act guards — then overrides the defaults that
    differ. use_sliding_window checkpoints convert with SWA applied to
    layers >= max_window_layers (Qwen2 semantics)."""
    import dataclasses

    get = (hf_config.get if isinstance(hf_config, dict)
           else lambda k, d=None: getattr(hf_config, k, d))
    cfg = hf_llama_config(hf_config)
    # Qwen2 SWA semantics with QWEN2's defaults (not Mistral's, which
    # hf_llama_config assumes): use_sliding_window defaults to False and
    # max_window_layers to 28, and the window applies only to layers
    # >= max_window_layers (transformers Qwen2Attention)
    # transformers defaults sliding_window to 4096 only when the key is
    # ABSENT; an explicit null in config.json means full attention —
    # mirror both (an `or 4096` would silently window a null config)
    has_sw = ('sliding_window' in hf_config if isinstance(hf_config, dict)
              else hasattr(hf_config, 'sliding_window'))
    if get('use_sliding_window', False):
        sliding = (get('sliding_window') or None) if has_sw else 4096
    else:
        sliding = None
    return dataclasses.replace(
        cfg,
        max_position_embeddings=get('max_position_embeddings', 32768),
        rms_norm_eps=get('rms_norm_eps', 1e-6),
        rope_theta=get('rope_theta', 1e6),
        attention_bias=True,
        sliding_window=sliding,
        max_window_layers=(get('max_window_layers', 28) or 0
                           if sliding is not None else 0),
    )


def from_hf_qwen2(state_dict, config, dtype=None):
    """Build a LlamaForCausalLM from a HuggingFace Qwen2 state dict —
    the Llama mapping pops the per-projection qkv bias vectors when
    `config.attention_bias` is set, so this is a thin alias."""
    return from_hf_llama(state_dict, config, dtype=dtype)


def from_hf_qwen2_pretrained(model_or_path, dtype=None):
    """Accept a transformers Qwen2ForCausalLM (or local path) and
    convert it."""
    if isinstance(model_or_path, str):
        from transformers import Qwen2ForCausalLM as HFQwen2

        model_or_path = HFQwen2.from_pretrained(model_or_path)
    cfg = hf_qwen2_config(model_or_path.config)
    return from_hf_qwen2(model_or_path.state_dict(), cfg, dtype=dtype)
