"""ResNet family (ref: python/paddle/vision/models/resnet.py).

Same architecture graph (BasicBlock / BottleneckBlock, stages
[64,128,256,512], stride-2 downsample shortcuts), built on our pytree
layers. Default data_format is NHWC — the TPU-native layout (XLA:TPU
keeps channels minor for the MXU's convolution tiling); Paddle's NCHW
is accepted and handled by the conv layers' `data_format` passthrough.
"""
from __future__ import annotations

import jax.numpy as jnp

from .. import nn


class BasicBlock(nn.Layer):
    expansion = 1

    def __init__(self, inplanes, planes, stride=1, downsample=None,
                 norm_layer=None, data_format='NHWC'):
        super().__init__()
        norm_layer = norm_layer or nn.BatchNorm2D
        self.conv1 = nn.Conv2D(inplanes, planes, 3, stride=stride, padding=1,
                               bias_attr=False, data_format=data_format)
        self.bn1 = norm_layer(planes, data_format=data_format)
        self.conv2 = nn.Conv2D(planes, planes, 3, padding=1, bias_attr=False,
                               data_format=data_format)
        self.bn2 = norm_layer(planes, data_format=data_format)
        self.relu = nn.ReLU()
        self.downsample = downsample

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class BottleneckBlock(nn.Layer):
    expansion = 4

    def __init__(self, inplanes, planes, stride=1, downsample=None,
                 norm_layer=None, data_format='NHWC', groups=1,
                 base_width=64):
        super().__init__()
        norm_layer = norm_layer or nn.BatchNorm2D
        # ResNeXt/wide variants widen the 3x3 stage: width scales with
        # base_width and splits into `groups` cardinality paths
        width = int(planes * (base_width / 64.0)) * groups
        self.conv1 = nn.Conv2D(inplanes, width, 1, bias_attr=False,
                               data_format=data_format)
        self.bn1 = norm_layer(width, data_format=data_format)
        self.conv2 = nn.Conv2D(width, width, 3, stride=stride, padding=1,
                               groups=groups, bias_attr=False,
                               data_format=data_format)
        self.bn2 = norm_layer(width, data_format=data_format)
        self.conv3 = nn.Conv2D(width, planes * self.expansion, 1,
                               bias_attr=False, data_format=data_format)
        self.bn3 = norm_layer(planes * self.expansion, data_format=data_format)
        self.relu = nn.ReLU()
        self.downsample = downsample

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class ResNet(nn.Layer):
    """ref: paddle.vision.models.ResNet(Block, depth, num_classes)."""

    def __init__(self, block, depth=50, width=64, num_classes=1000,
                 with_pool=True, data_format='NHWC', groups=1,
                 width_per_group=64):
        super().__init__()
        self._groups = groups
        self._base_width = width_per_group
        layer_cfg = {
            18: [2, 2, 2, 2], 34: [3, 4, 6, 3], 50: [3, 4, 6, 3],
            101: [3, 4, 23, 3], 152: [3, 8, 36, 3],
        }
        layers = layer_cfg[depth]
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.data_format = data_format
        self.inplanes = width
        self.conv1 = nn.Conv2D(3, self.inplanes, 7, stride=2, padding=3,
                               bias_attr=False, data_format=data_format)
        self.bn1 = nn.BatchNorm2D(self.inplanes, data_format=data_format)
        self.relu = nn.ReLU()
        self.maxpool = nn.MaxPool2D(3, stride=2, padding=1, data_format=data_format)
        self.layer1 = self._make_layer(block, 64, layers[0], 1, data_format)
        self.layer2 = self._make_layer(block, 128, layers[1], 2, data_format)
        self.layer3 = self._make_layer(block, 256, layers[2], 2, data_format)
        self.layer4 = self._make_layer(block, 512, layers[3], 2, data_format)
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D(1, data_format=data_format)
        if num_classes > 0:
            self.fc = nn.Linear(512 * block.expansion, num_classes)

    def _make_layer(self, block, planes, blocks, stride, data_format):
        downsample = None
        if stride != 1 or self.inplanes != planes * block.expansion:
            downsample = nn.Sequential(
                nn.Conv2D(self.inplanes, planes * block.expansion, 1,
                          stride=stride, bias_attr=False, data_format=data_format),
                nn.BatchNorm2D(planes * block.expansion, data_format=data_format),
            )
        extra = ({'groups': self._groups, 'base_width': self._base_width}
                 if block.expansion == 4 else {})
        seq = [block(self.inplanes, planes, stride, downsample,
                     data_format=data_format, **extra)]
        self.inplanes = planes * block.expansion
        for _ in range(1, blocks):
            seq.append(block(self.inplanes, planes, data_format=data_format,
                             **extra))
        return nn.Sequential(*seq)

    def forward(self, x):
        x = self.maxpool(self.relu(self.bn1(self.conv1(x))))
        x = self.layer4(self.layer3(self.layer2(self.layer1(x))))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = jnp.reshape(x, (x.shape[0], -1))
            x = self.fc(x)
        return x


def _resnet(block, depth, **kw):
    return ResNet(block, depth, **kw)


def resnet18(**kw):
    return _resnet(BasicBlock, 18, **kw)


def resnet34(**kw):
    return _resnet(BasicBlock, 34, **kw)


def resnet50(**kw):
    return _resnet(BottleneckBlock, 50, **kw)


def resnet101(**kw):
    return _resnet(BottleneckBlock, 101, **kw)


def resnet152(**kw):
    return _resnet(BottleneckBlock, 152, **kw)


def resnext50_32x4d(**kw):
    """ref: paddle.vision.models.resnext50_32x4d."""
    return ResNet(BottleneckBlock, 50, groups=32, width_per_group=4, **kw)


def resnext50_64x4d(**kw):
    return ResNet(BottleneckBlock, 50, groups=64, width_per_group=4, **kw)


def resnext101_32x4d(**kw):
    return ResNet(BottleneckBlock, 101, groups=32, width_per_group=4, **kw)


def resnext101_64x4d(**kw):
    return ResNet(BottleneckBlock, 101, groups=64, width_per_group=4, **kw)


def resnext152_32x4d(**kw):
    return ResNet(BottleneckBlock, 152, groups=32, width_per_group=4, **kw)


def resnext152_64x4d(**kw):
    return ResNet(BottleneckBlock, 152, groups=64, width_per_group=4, **kw)


def wide_resnet50_2(**kw):
    """ref: paddle.vision.models.wide_resnet50_2 (2x-wide 3x3 stage)."""
    return ResNet(BottleneckBlock, 50, width_per_group=128, **kw)


def wide_resnet101_2(**kw):
    return ResNet(BottleneckBlock, 101, width_per_group=128, **kw)
