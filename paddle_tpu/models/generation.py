"""Shared autoregressive generation over the cached-call contract (ref:
python/paddle/nn/decode.py + the reference generation loops).

Any causal LM that implements
  - ``init_cache(batch_size, max_len, dtype=None)`` and
  - ``self(input_ids, caches=..., cache_index=...) -> (logits, caches)``
gets greedy/temperature/top-k/top-p sampling and beam search by mixing
this in (LlamaForCausalLM, MoEForCausalLM). Everything is static-shape
`lax.scan` so one call compiles to a single XLA program.
"""
from __future__ import annotations

import functools
import typing

import jax
import jax.numpy as jnp
import numpy as np


def filter_logits(logits, top_k=0, top_p=1.0):
    """top-k / nucleus filtering on (already temperature-scaled) logits
    — the one implementation behind sampled generate() and sampled
    speculative decoding (filtering both target and draft keeps the
    rejection-sampling identity: it holds for ANY pt/pd pair).

    top_k may be a Python int (static: folded into the trace) or a
    traced scalar (e.g. a serving knob passed as a jit argument): the
    traced path clamps with lax.min/max and gathers the k-th threshold
    dynamically — no host sync, and top_k <= 0 still means keep-all.
    """
    V = logits.shape[-1]
    if isinstance(top_k, jax.core.Tracer):
        # clamp to [1, V] on device; the k<1 case is masked out by the
        # where(top_k > 0, ...) below, the clamp just keeps the gather
        # index in bounds
        k = jax.lax.max(jnp.int32(1),
                        jax.lax.min(jnp.asarray(top_k, jnp.int32),
                                    jnp.int32(V)))
        srt = jnp.sort(logits, axis=-1)
        idx = jnp.broadcast_to(jnp.asarray(V - k, jnp.int32),
                               logits.shape[:-1] + (1,))
        kth = jnp.take_along_axis(srt, idx, axis=-1)
        logits = jnp.where(top_k > 0,
                           jnp.where(logits < kth, -jnp.inf, logits),
                           logits)
    elif top_k > 0:
        # clamp to the vocab (HF semantics): top_k > V means "keep all",
        # not an IndexError at trace time
        top_k = min(int(top_k), V)
        kth = jnp.sort(logits, axis=-1)[..., -top_k][..., None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p < 1.0:
        sorted_logits = jnp.flip(jnp.sort(logits, axis=-1), -1)
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        cutoff_idx = jnp.sum(cum < top_p, axis=-1, keepdims=True)
        cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx, axis=-1)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return logits


def default_positions(batch, seq, cache_index=None, kv_write_pos=None):
    """The serving-contract position rule shared by every causal LM:
    per-row offsets when kv_write_pos is given (batched speculative),
    else the uniform cache_index base."""
    if kv_write_pos is not None:
        wp = jnp.reshape(jnp.asarray(kv_write_pos, jnp.int32), (-1,))
        positions = wp[:, None] + jnp.arange(seq)[None, :]
    else:
        base = 0 if cache_index is None else cache_index
        positions = base + jnp.arange(seq)[None, :].astype(jnp.int32)
    return jnp.broadcast_to(positions, (batch, seq))


class QuantKVCache(typing.NamedTuple):
    """Cache-KV int8 (ref capability:
    python/paddle/incubate/nn/functional/block_multihead_attention.py:44,60
    — dynamic/static cache-KV quantization in the reference serving
    stack). K/V live int8 in HBM with per-(kv-head, dim) f32 scales,
    calibrated at prefill ('dynamic' in the reference's terms) and held
    static over decode. Halves the cache stream — the binding term of
    decode at batch >= 8 and long contexts."""

    kq: jax.Array        # int8 (B, max_len, Hkv, D)
    vq: jax.Array        # int8 (B, max_len, Hkv, D)
    kscale: jax.Array    # f32 (Hkv, D)
    vscale: jax.Array    # f32 (Hkv, D)


class QuantPagedKVCache(typing.NamedTuple):
    """Int8 paged KV pool with PER-ROW scales (the ServingEngine's
    `kv_cache_dtype='int8'` layout — ref capability: the reference
    serving stack's cache-KV int8 block_multihead_attention). K/V pages
    live int8; each written row (one token's K or V at one kv head)
    carries its own f32 scale at `ks[page, head, slot]` /
    `vs[page, head, slot]`, computed from that row alone
    (`quantize_kv_row`). Per-row scales make quantization a pure
    function of the token's bf16 K/V row — independent of write
    batching — so re-prefill after preemption, prefix-cache sharing,
    CoW copies, and snapshot/restore all reproduce bit-identical int8
    pages, which is what keeps greedy serving streams bit-equal across
    every scheduler path. Storage overhead is 4/D per element (~6% at
    D=64). Halves the decode cache stream vs bf16 — the binding term
    at batch >= 8 and long contexts."""

    kp: jax.Array        # int8 (num_blocks, Hkv, block_size, D)
    vp: jax.Array        # int8 (num_blocks, Hkv, block_size, D)
    ks: jax.Array        # f32 (num_blocks, Hkv, block_size)
    vs: jax.Array        # f32 (num_blocks, Hkv, block_size)


class RowQuantKVCache(typing.NamedTuple):
    """CONTIGUOUS int8 KV cache with per-row scales — the temp-cache
    twin of QuantPagedKVCache, used by the serving engine's fused
    multi-token bodies (admission prefill, chunked prefill, the
    speculative verify): rows gathered from int8 pages stay int8 here
    (scales ride along), and rows the forward writes quantize with the
    SAME per-row rule the paged pools use. Attending through this
    cache therefore sees exactly the int8-roundtripped values a paged
    decode step would — the invariant that makes int8 serving streams
    bit-equal across monolithic prefill, chunked prefill, speculative
    windows, and plain decode (every path attends the same quantized
    world). Layouts: kq/vq (B, max_len, Hkv, D) int8, ks/vs
    (B, max_len, Hkv) f32."""

    kq: jax.Array
    vq: jax.Array
    ks: jax.Array
    vs: jax.Array


class PagedKVCache(typing.NamedTuple):
    """Paged (block-table) KV cache for continuous-batching serving
    (ref capability: the reference serving stack's
    block_multihead_attention pages; design: vLLM PagedAttention). K/V
    live as a POOL of fixed-size pages (num_blocks, Hkv, block_size, D)
    shared by every in-flight request; a per-request block table maps
    logical block j of the sequence to a physical page id. Page 0 is
    reserved as the SCRATCH page (inactive/finished rows write there
    harmlessly), so allocators hand out ids >= 1 — see
    inference/serving.py::BlockAllocator. Decode steps route through
    `cached_attention(..., block_tables=...)`, which dispatches the
    fused pallas paged kernel on TPU and a gather reference elsewhere."""

    kp: jax.Array        # (num_blocks, Hkv, block_size, D) pages
    vp: jax.Array        # (num_blocks, Hkv, block_size, D) pages


def quantize_kv_rows(x, scale):
    """Symmetric int8 quantization of new K/V rows (B, S, Hkv, D) with
    per-(head, dim) scales; saturates rows that exceed the prefill
    calibration range."""
    q = jnp.round(x.astype(jnp.float32) / scale[None, None])
    return jnp.clip(q, -127, 127).astype(jnp.int8)


def calibrate_kv_scale(x, margin=1.0):
    """Per-(kv-head, dim) amax scales from the prefill rows."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=(0, 1))
    return jnp.maximum(amax * margin, 1e-6) / 127.0


def quantize_kv_row(x):
    """PER-ROW symmetric int8 quantization: each (..., Hkv, D) row
    quantizes against its own per-(row, head) amax — a pure function
    of the row's values, so the SAME bf16 row always produces the SAME
    int8 bytes + scale no matter when or where it is written (prefill
    scatter, decode append, chunk continuation, speculative verify,
    re-prefill after preemption). Returns (q int8 (..., Hkv, D),
    scale f32 (..., Hkv))."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.maximum(amax, 1e-6) / 127.0
    q = jnp.clip(jnp.round(xf / scale[..., None]), -127, 127)
    return q.astype(jnp.int8), scale


def dequantize_kv_row(q, scale, dtype):
    """Inverse of `quantize_kv_row`: int8 rows x their per-row scales,
    cast to the compute dtype. The ONE dequant expression every
    attention path shares (paged gather reference, RowQuant contiguous
    fallback, pallas in-VMEM) so the attended values are bit-identical
    across them."""
    return (q.astype(jnp.float32) * scale[..., None]).astype(dtype)


def filter_logits_batched(logits, top_k, top_p):
    """Per-ROW top-k / nucleus filtering: `top_k` (B,) int32 and
    `top_p` (B,) f32 ride as DEVICE data, so a batch can mix greedy,
    top-k, and nucleus rows in one trace (the serving engine's
    per-request sampling — changing the mix never retraces). Semantics
    per row match `filter_logits` exactly: top_k <= 0 keeps all,
    top_k > V clamps to keep-all, top_p == 1.0 is a no-op (masked, not
    skipped — the cumsum's float roundoff must not drop valid tokens
    for keep-all rows)."""
    V = logits.shape[-1]
    k = jnp.clip(jnp.asarray(top_k, jnp.int32), 1, V)
    srt = jnp.sort(logits, axis=-1)
    kth = jnp.take_along_axis(srt, (V - k)[:, None], axis=-1)
    logits = jnp.where((jnp.asarray(top_k, jnp.int32) > 0)[:, None],
                       jnp.where(logits < kth, -jnp.inf, logits), logits)
    tp = jnp.asarray(top_p, jnp.float32)
    sorted_desc = jnp.flip(jnp.sort(logits, axis=-1), -1)
    probs = jax.nn.softmax(sorted_desc, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    cutoff_idx = jnp.sum(cum < tp[:, None], axis=-1, keepdims=True)
    cutoff = jnp.take_along_axis(sorted_desc, cutoff_idx, axis=-1)
    nucleus = jnp.where(logits < cutoff, -jnp.inf, logits)
    return jnp.where((tp < 1.0)[:, None], nucleus, logits)


class GenerationMixin:
    def quantize_weights(self, bits=8):
        """Weight-only PTQ for serving: every 2-D trainable projection
        becomes a pallas-served QuantizedWeight (int8 or packed int4) —
        decode streams 2x/4x fewer weight bytes from HBM. Per-model
        exemptions are STRUCTURAL: lookup tables / routers declare
        `no_quantize` on their layer class (embed_tokens, wte/wpe, MoE
        gates) and nn.Embedding subtrees are never touched. Returns a
        new model; the original is untouched.

        MoE expert weights (E, in, out) quantize too at bits=8
        (per-(expert, out-col) scales). Caveats: int4 leaves experts fp
        (packing unimplemented), and tied heads served off the embedding
        table stay full precision (see
        quantization.quantize_matmul_weights)."""
        from ..quantization import quantize_matmul_weights

        return quantize_matmul_weights(self, bits=bits, min_features=1)

    def cache_dtype(self):
        """Dtype for the preallocated KV cache — override per model
        (usually the embedding table's dtype)."""
        raise NotImplementedError

    def init_cache(self, batch_size, max_len, dtype=None, quantized=False):
        """Per-layer (k, v) zero pairs of (B, max_len, kv_heads, head_dim),
        derived from `self.config` (`head_dim` property or
        hidden_size // num_attention_heads).

        quantized=True returns QuantKVCache entries (int8 data +
        per-(head, dim) scales). The first cached call must be a
        multi-token prefill — that's where the scales calibrate."""
        cfg = self.config
        head_dim = getattr(cfg, 'head_dim', None)
        if head_dim is None:
            head_dim = cfg.hidden_size // cfg.num_attention_heads
        kv_heads = (getattr(cfg, 'num_key_value_heads', None)
                    or cfg.num_attention_heads)
        dtype = dtype or self.cache_dtype()
        shape = (batch_size, max_len, kv_heads, head_dim)

        def make():
            return jnp.zeros(shape, dtype)

        mesh = None
        if not isinstance(batch_size, jax.core.Tracer):
            from ..distributed.mesh import get_mesh

            mesh = get_mesh()
        if mesh is not None:
            # sharded serving (ref: fleet mpu mp_layers serving path —
            # mp_layers.py:47,334,541): KV cache lives TP-sharded on the
            # heads axis (and dp/fsdp on batch when divisible) so a
            # 7B-class model's cache splits across chips instead of
            # replicating; GSPMD keeps the decode step's attention local
            # to each head shard
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P

            from ..distributed.parallel import _valid_spec

            spec = _valid_spec(P(('dp', 'fsdp'), None, 'tp', None),
                               shape, mesh)
            sharding = NamedSharding(mesh, spec)

            def make():  # noqa: F811 - mesh-aware variant
                return jax.device_put(jnp.zeros(shape, dtype), sharding)

        if quantized:
            sshape = (kv_heads, head_dim)

            def make_scale():
                return jnp.zeros(sshape, jnp.float32)

            if mesh is not None:
                sspec = _valid_spec(P('tp', None), sshape, mesh)
                ssharding = NamedSharding(mesh, sspec)

                def make_scale():  # noqa: F811
                    return jax.device_put(jnp.zeros(sshape, jnp.float32),
                                          ssharding)

            def make_q():
                z = jnp.zeros(shape, jnp.int8)
                return jax.device_put(z, sharding) if mesh is not None else z

            return [QuantKVCache(make_q(), make_q(), make_scale(),
                                 make_scale())
                    for _ in range(cfg.num_hidden_layers)]
        return [(make(), make()) for _ in range(cfg.num_hidden_layers)]

    def init_paged_cache(self, num_blocks, block_size, dtype=None):
        """Per-layer PagedKVCache pools of (num_blocks, kv_heads,
        block_size, head_dim) zero pages. The pool is request-agnostic:
        the ServingEngine's BlockAllocator hands page ids to requests
        and the per-request block tables ride into each decode step as
        device data (inference/serving.py). Page 0 is the reserved
        scratch page, so a usable pool needs num_blocks >= 2."""
        cfg = self.config
        head_dim = getattr(cfg, 'head_dim', None)
        if head_dim is None:
            head_dim = cfg.hidden_size // cfg.num_attention_heads
        kv_heads = (getattr(cfg, 'num_key_value_heads', None)
                    or cfg.num_attention_heads)
        dtype = dtype or self.cache_dtype()
        dtype = jnp.dtype(dtype)
        quant = dtype == jnp.int8
        shape = (int(num_blocks), kv_heads, int(block_size), head_dim)
        sshape = shape[:3]                    # per-row scales (NB,Hkv,BS)

        def make(sh=shape, dt=dtype):
            return jnp.zeros(sh, dt)

        from ..distributed.mesh import get_mesh

        mesh = get_mesh()
        if mesh is not None:
            # TP-sharded serving (ROADMAP item 1; the ServingEngine
            # activates its mesh around this call): the page pools
            # carry a NamedSharding splitting the kv-head dim over
            # 'tp' — a 7B-class model's paged KV splits across chips
            # instead of replicating, mirroring init_cache's layout.
            # Page ids / block tables stay replicated host state.
            # kv_heads % tp != 0 clamps to replicated (the GQA
            # fallback, same as init_cache).
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P

            from ..distributed.parallel import _valid_spec

            spec = _valid_spec(P(None, 'tp', None, None), shape, mesh)
            sharding = NamedSharding(mesh, spec)
            sspec = _valid_spec(P(None, 'tp', None), sshape, mesh)
            ssharding = NamedSharding(mesh, sspec)

            def make(sh=shape, dt=dtype):  # noqa: F811 - mesh-aware
                s = ssharding if len(sh) == 3 else sharding
                return jax.device_put(jnp.zeros(sh, dt), s)

        if quant:
            # int8 pages + per-row f32 scales (QuantPagedKVCache): the
            # scale pools shard on the same kv-head axis, so one page's
            # data AND its scales live on the same shard
            return [QuantPagedKVCache(make(), make(),
                                      make(sshape, jnp.float32),
                                      make(sshape, jnp.float32))
                    for _ in range(cfg.num_hidden_layers)]
        return [PagedKVCache(make(), make())
                for _ in range(cfg.num_hidden_layers)]

    def generate(self, input_ids, max_new_tokens=32, temperature=0.0, top_k=0,
                 top_p=1.0, rng_key=None, eos_token_id=None, num_beams=1,
                 length_penalty=0.0, attention_mask=None,
                 kv_cache_int8=False):
        """attention_mask (B, S) 0/1 supports LEFT-padded batches of
        unequal-length prompts (HF decoder-only convention): positions
        are counted from each row's first real token and pad rows never
        receive attention. Requires the model's cached forward to accept
        `positions`/`kvalid` (the Llama family does).

        kv_cache_int8=True serves with a quantized KV cache (see
        QuantKVCache): scales calibrate on the prompt, decode streams
        half the cache bytes. Requires a multi-token prompt."""
        if attention_mask is not None and not isinstance(
                attention_mask, jax.core.Tracer):
            # HF tokenizers hand back an all-ones mask for equal-length
            # batches; collapsing it to None BEFORE the capability
            # checks keeps GPT/beam-search usable with standard HF
            # pipelines and preserves the fused decode kernel. The
            # collapse (and the left-contiguity fast path) inspect
            # CONCRETE masks only — a traced mask skips both, so
            # jit-wrapping generate() should pass mask=None for
            # equal-length batches (the errors below say so).
            if bool(np.asarray(attention_mask).all()):
                attention_mask = None
        if attention_mask is not None:
            import inspect

            traced_hint = (
                ' (note: the mask is a tracer here — the all-ones '
                'collapse only inspects concrete masks, so jit-wrapped '
                'generate() must pass attention_mask=None for '
                'equal-length batches)'
                if isinstance(attention_mask, jax.core.Tracer) else '')
            params = inspect.signature(self.forward).parameters
            if 'kvalid' not in params:
                raise NotImplementedError(
                    f'{type(self).__name__} does not support attention_mask '
                    f'generation (cached forward lacks positions/kvalid)'
                    + traced_hint)
            if num_beams > 1:
                raise NotImplementedError(
                    'attention_mask + beam search is not supported yet'
                    + traced_hint)
        # decode always runs in eval mode: dropout inside the scan would
        # corrupt greedy decoding and make beam scores non-deterministic
        # (the mode flag is static layer state, restored on exit)
        was_training = bool(getattr(self, 'training', False))
        if was_training:
            self.eval()
        try:
            if num_beams > 1:
                if temperature != 0.0 or top_k != 0 or top_p != 1.0:
                    raise ValueError(
                        'beam search is deterministic: temperature/top_k/'
                        'top_p are not supported with num_beams > 1')
                return self.beam_search(input_ids, max_new_tokens, num_beams,
                                        eos_token_id=eos_token_id,
                                        length_penalty=length_penalty,
                                        kv_cache_int8=kv_cache_int8)
            return self._generate_sample(input_ids, max_new_tokens,
                                         temperature, top_k, top_p, rng_key,
                                         eos_token_id, attention_mask,
                                         kv_cache_int8=kv_cache_int8)
        finally:
            if was_training:
                self.train()

    def beam_search(self, input_ids, max_new_tokens=32, num_beams=4,
                    eos_token_id=None, length_penalty=0.0,
                    kv_cache_int8=False):
        """Static-shape beam search with a shared KV-cache (ref:
        python/paddle/nn/decode.py::BeamSearchDecoder semantics on the
        causal-LM surface).

        Every step scores all num_beams*vocab continuations, keeps the
        top num_beams by cumulative log-prob (finished beams frozen),
        and gathers the KV-cache rows along the flattened batch*beam
        axis — one `lax.scan`, fully jittable.
        """
        B, S = input_ids.shape
        if kv_cache_int8 and S < 2:
            raise ValueError(
                'kv_cache_int8 needs a multi-token prompt: the per-head '
                'scales calibrate on the prefill rows')
        K = num_beams
        max_len = S + max_new_tokens
        NEG = -1e9

        # prefill ONCE at batch B, then replicate the KV rows K ways —
        # the K beams share an identical prompt, so prefilling (B*K, S)
        # would do K-fold redundant attention/MLP work
        caches = self.init_cache(B, max_len, quantized=kv_cache_int8)
        logits, caches = self(input_ids, caches=caches, cache_index=0)
        # replicate per-beam: only the 4-D (B, L, H, D) data leaves have a
        # batch axis — QuantKVCache scales are 2-D and beam-invariant
        caches = jax.tree.map(
            lambda c: jnp.repeat(c, K, axis=0) if c.ndim == 4 else c, caches)
        logp = jax.nn.log_softmax(
            logits[:, -1, :].astype(jnp.float32), axis=-1)
        logp = jnp.repeat(logp, K, axis=0)               # (B*K, V)
        V = logp.shape[-1]

        def select_and_reorder(scores_kv, caches, bufs):
            """scores_kv: (B, K, V) candidate scores → top-K beams."""
            flat = scores_kv.reshape(B, K * V)
            top_scores, top_idx = jax.lax.top_k(flat, K)  # (B, K)
            beam_idx = top_idx // V
            tok = (top_idx % V).astype(input_ids.dtype)
            gather = (jnp.arange(B)[:, None] * K + beam_idx).reshape(-1)
            caches = jax.tree.map(
                lambda c: c[gather] if c.ndim == 4 else c, caches)
            bufs = [b[jnp.arange(B)[:, None], beam_idx] for b in bufs]
            return top_scores, tok, caches, bufs, beam_idx

        # first expansion: all K rows hold the same prefix — keep only
        # beam 0's candidates or every beam would duplicate
        first = jnp.where(jnp.arange(K)[None, :, None] == 0,
                          logp.reshape(B, K, V), NEG)
        tokens_buf = jnp.zeros((B, K, max_new_tokens), input_ids.dtype)
        finished0 = jnp.zeros((B, K), bool)
        lengths0 = jnp.ones((B, K), jnp.float32)
        scores, tok, caches, (tokens_buf,), _ = select_and_reorder(
            first, caches, [tokens_buf])
        tokens_buf = tokens_buf.at[:, :, 0].set(tok)
        if eos_token_id is not None:
            finished0 = tok == eos_token_id

        def step(carry, i):
            scores, tok, finished, lengths, caches, tokens_buf = carry
            logits, caches = self(tok.reshape(B * K, 1), caches=caches,
                                  cache_index=S + i)
            logp = jax.nn.log_softmax(
                logits[:, -1, :].astype(jnp.float32), -1).reshape(B, K, V)
            if eos_token_id is not None:
                # finished beams emit only eos at zero cost (frozen score)
                frozen = jnp.full((V,), NEG).at[eos_token_id].set(0.0)
                logp = jnp.where(finished[:, :, None], frozen[None, None],
                                 logp)
            cand = scores[:, :, None] + logp
            scores, tok, caches, bufs, beam_idx = select_and_reorder(
                cand, caches, [tokens_buf, finished.astype(jnp.float32),
                               lengths])
            tokens_buf, finished_f, lengths = bufs
            finished = finished_f > 0.5
            lengths = jnp.where(finished, lengths, lengths + 1)
            if eos_token_id is not None:
                finished = finished | (tok == eos_token_id)
            tokens_buf = tokens_buf.at[:, :, i + 1].set(tok)
            return (scores, tok, finished, lengths, caches, tokens_buf), None

        if max_new_tokens > 1:
            (scores, _, finished, lengths, _, tokens_buf), _ = jax.lax.scan(
                step, (scores, tok, finished0, lengths0, caches, tokens_buf),
                jnp.arange(max_new_tokens - 1))
        else:
            lengths = lengths0

        if length_penalty:
            final = scores / (lengths ** length_penalty)
        else:
            final = scores
        best = jnp.argmax(final, axis=-1)                # (B,)
        seq = tokens_buf[jnp.arange(B), best]            # (B, max_new)
        return jnp.concatenate([input_ids, seq], axis=1)

    def _generate_sample(self, input_ids, max_new_tokens=32, temperature=0.0,
                         top_k=0, top_p=1.0, rng_key=None, eos_token_id=None,
                         attention_mask=None, kv_cache_int8=False):
        """Greedy / sampled decode with a preallocated KV-cache.

        Functional loop (`lax.while_loop`-shaped via scan): prefill once,
        then one-token steps; static shapes throughout so the whole decode
        compiles to a single XLA program. With `attention_mask`, prompts
        are LEFT-padded: per-row positions count real tokens only and
        pad cache rows stay invalid for every later step.
        """
        B, S = input_ids.shape
        if kv_cache_int8 and S < 2:
            raise ValueError(
                'kv_cache_int8 needs a multi-token prompt: the per-head '
                'scales calibrate on the prefill rows')
        max_len = S + max_new_tokens
        caches = self.init_cache(B, max_len, quantized=kv_cache_int8)
        if rng_key is None:
            rng_key = jax.random.PRNGKey(0)

        if attention_mask is not None:
            import inspect

            am = jnp.asarray(attention_mask, jnp.int32)
            # pad rows clip to position 0; they are masked out anyway
            prompt_pos = jnp.maximum(jnp.cumsum(am, axis=1) - 1, 0)
            real_len = am.sum(axis=1).astype(jnp.int32)       # (B,)
            kvalid = jnp.concatenate(
                [am, jnp.ones((B, max_new_tokens), jnp.int32)], axis=1)
            extra = dict(positions=prompt_pos, kvalid=kvalid)
            # left-padded masks are the contiguous window [S - real_len,
            # now]: models that accept kv_start keep the fused decode
            # kernel (per-row start) instead of the masked XLA fallback.
            # Gate on verified left-contiguity (host check on the
            # concrete mask): a right-padded or holed mask must keep the
            # exact masked path — kv_start would attend the wrong window.
            if ('kv_start' in inspect.signature(self.forward).parameters
                    and not isinstance(am, jax.core.Tracer)):
                amn = np.asarray(am)
                rl = amn.sum(axis=1)
                left_contig = bool(
                    (amn == (np.arange(S)[None, :]
                             >= (S - rl)[:, None])).all())
                if left_contig:
                    extra['kv_start'] = S - real_len
        else:
            extra = {}

        # prefill
        logits, caches = self(input_ids, caches=caches, cache_index=0,
                              **extra)
        last_logits = logits[:, -1, :]

        def sample(logits, key):
            if temperature == 0.0:
                return jnp.argmax(logits, axis=-1).astype(input_ids.dtype)
            logits = filter_logits(logits / temperature, top_k, top_p)
            return jax.random.categorical(key, logits, axis=-1).astype(input_ids.dtype)

        finished0 = jnp.zeros((B,), bool)

        def step(carry, _):
            last_logits, caches, idx, key, finished = carry
            key, sub = jax.random.split(key)
            tok = sample(last_logits, sub)
            if eos_token_id is not None:
                # finished rows emit eos forever (HF pads with
                # pad_token_id == eos in the default setup)
                tok = jnp.where(finished,
                                jnp.asarray(eos_token_id, tok.dtype), tok)
                finished = finished | (tok == eos_token_id)
            if attention_mask is not None:
                # per-row rope position = real tokens so far; buffer
                # index stays the uniform idx
                step_extra = dict(
                    positions=(real_len + (idx - S))[:, None], kvalid=kvalid)
                if 'kv_start' in extra:
                    step_extra['kv_start'] = extra['kv_start']
            else:
                step_extra = {}
            logits, caches = self(tok[:, None], caches=caches, cache_index=idx,
                                  **step_extra)
            return (logits[:, -1, :], caches, idx + 1, key, finished), tok

        (_, _, _, _, _), tokens = jax.lax.scan(
            step,
            (last_logits, caches, jnp.asarray(S, jnp.int32), rng_key,
             finished0),
            None, length=max_new_tokens,
        )
        return jnp.concatenate([input_ids, tokens.T], axis=1)


def generate_speculative(target, draft, input_ids, max_new_tokens=32,
                         num_draft_tokens=4, eos_token_id=None,
                         kv_cache_int8=False):
    """Greedy speculative decoding (ref capability: the reference
    ecosystem's speculative/draft-model inference).

    LOSSLESS for greedy: emits exactly the tokens `target.generate(...)`
    would, but the big model runs one forward per accepted window
    (~(m+1) tokens per dispatch, m = accepted draft prefix) instead of
    one per token. Both models keep KV caches; rejected draft rows are
    simply overwritten on the next window (cache writes always start at
    the committed length, and position masking hides rows beyond it).

    The accepted length is data-dependent, but at batch 1 it only
    steers on-device state, so the whole window loop runs as ONE
    compiled lax.while_loop with a single host sync per call — the win
    is fewer *target* forwards, which is what dominates when the draft
    is much smaller. Batched prompts (B > 1, equal length; these sync
    once per window) commit per row at their own rates via per-row
    cache write offsets (`kv_write_pos` — models that lack it are
    batch-1 only): each row commits by the same greedy rule its solo
    `generate()` follows. (As with batched generate(), bit-exactness vs
    a SOLO run holds unless some step's top-2 logits sit within float
    rounding of each other — XLA may tile batched matmuls differently;
    see examples/generate.py for the same caveat.)

    kv_cache_int8=True serves BOTH models with quantized KV caches
    (scales calibrate at their prefills); the greedy commit rule then
    matches `target.generate(..., kv_cache_int8=True)`.
    """
    B, S = input_ids.shape
    if kv_cache_int8 and S < 2:
        raise ValueError(
            'kv_cache_int8 needs a multi-token prompt: the per-head '
            'scales calibrate on the prefill rows')
    if B != 1:
        import inspect

        for m_ in (target, draft):
            if 'kv_write_pos' not in inspect.signature(
                    m_.forward).parameters:
                raise NotImplementedError(
                    f'{type(m_).__name__} does not support batched '
                    f'speculative decoding (cached forward lacks '
                    f'kv_write_pos); loop prompts individually')
    # same eval-mode rule as generate(): dropout would break the
    # losslessness contract (and differ between draft and verify)
    restore = []
    for m_ in (target, draft):
        if bool(getattr(m_, 'training', False)):
            m_.eval()
            restore.append(m_)
    try:
        if B == 1:
            return _speculative_loop(target, draft, input_ids,
                                     max_new_tokens, num_draft_tokens,
                                     eos_token_id, kv_cache_int8)
        return _speculative_loop_batched(target, draft, input_ids,
                                         max_new_tokens, num_draft_tokens,
                                         eos_token_id, kv_cache_int8)
    finally:
        for m_ in restore:
            m_.train()


def _commit_window(c, d_row, t_row, k):
    """The greedy speculative commit rule as a host-side REFERENCE:
    accept the longest draft prefix the target agrees with, commit [c]
    + that prefix, and pick the next committed token from the target's
    own choices. Returns (committed_tokens, next_c).

    The production loops now run this rule ON DEVICE inside the fused
    window step (inference.engine._spec_window_*: m = sum(cumprod(d ==
    t[:k])), next = t[m]); this function stays as the executable spec
    the engine's commit is tested against
    (tests/test_decode_engine.py)."""
    # one host transfer per ROW, not one per token: the old while loop
    # did int(d_row[i]) == int(t_row[i]) per position — two device
    # round-trips per draft token (tracelint TL002). Pull both rows
    # across once, then the commit rule is pure host arithmetic (and
    # the cumprod mirrors the engine's on-device form exactly).
    d = np.asarray(d_row)
    t = np.asarray(t_row)
    agree = (d[:k] == t[:k]).astype(np.int64)
    m_acc = int(agree.cumprod().sum())
    committed = [int(c)] + [int(x) for x in d[:m_acc]]
    next_c = int(t[m_acc]) if m_acc < k else int(t[k])
    return committed, next_c


def _speculative_loop(target, draft, input_ids, max_new_tokens,
                      num_draft_tokens, eos_token_id,
                      kv_cache_int8=False):
    """Batch-1 greedy speculative decoding through the COMPILED whole
    loop (inference.engine._spec_decode_b1): propose + verify + commit
    for EVERY window run inside one module-level-jitted lax.while_loop
    (steady state: zero retraces across calls — the jit closures used
    to live inside this function, guaranteeing a fresh trace every
    invocation), KV caches are donated (updated in place), and the
    host syncs once per generate call."""
    from ..inference.engine import _spec_loop_host_b1

    B, S = input_ids.shape
    k = int(num_draft_tokens)
    if k < 1:
        raise ValueError('num_draft_tokens must be >= 1')
    max_len = S + max_new_tokens + k + 1      # room for the last window
    tcaches = target.init_cache(B, max_len, quantized=kv_cache_int8)
    dcaches = draft.init_cache(B, max_len, quantized=kv_cache_int8)
    gen = _spec_loop_host_b1(target, draft, tcaches, dcaches, input_ids,
                             max_new_tokens, k, eos_token_id)
    return jnp.concatenate(
        [input_ids, jnp.asarray(gen, input_ids.dtype)], axis=1)


def _speculative_loop_batched(target, draft, input_ids, max_new_tokens,
                              num_draft_tokens, eos_token_id,
                              kv_cache_int8=False):
    """B > 1 speculative decoding: rows accept different draft prefixes,
    so each row carries its OWN committed length — cache writes go to
    per-row offsets (kv_write_pos) and attention masks by per-row
    position. The per-row commit rule is byte-identical to the batch-1
    loop, so losslessness holds row-wise. Runs through the compiled
    fused window (inference.engine._spec_window_batched) with donated
    caches — one dispatch and one host sync per window."""
    from ..inference.engine import _spec_loop_host_batched

    B, S = input_ids.shape
    k = int(num_draft_tokens)
    if k < 1:
        raise ValueError('num_draft_tokens must be >= 1')
    max_len = S + max_new_tokens + k + 1
    tcaches = target.init_cache(B, max_len, quantized=kv_cache_int8)
    dcaches = draft.init_cache(B, max_len, quantized=kv_cache_int8)
    gen = _spec_loop_host_batched(target, draft, tcaches, dcaches,
                                  input_ids, max_new_tokens, k,
                                  eos_token_id)
    return jnp.concatenate(
        [input_ids, jnp.asarray(gen, input_ids.dtype)], axis=1)


def _speculative_accept_dists(pt, pd):
    """The rejection-sampling identity, exposed for testing: given the
    target and draft distributions at one position, the procedure
    'sample x~pd; accept w.p. min(1, pt(x)/pd(x)); else resample from
    norm((pt-pd)+)' outputs exactly pt. Returns (accept_prob_per_token,
    residual_dist)."""
    accept = jnp.minimum(1.0, pt / jnp.maximum(pd, 1e-30))
    residual = jnp.maximum(pt - pd, 0.0)
    residual = residual / jnp.maximum(residual.sum(-1, keepdims=True),
                                      1e-30)
    return accept, residual


def generate_speculative_sampled(target, draft, input_ids,
                                 max_new_tokens=32, num_draft_tokens=4,
                                 temperature=1.0, top_k=0, top_p=1.0,
                                 rng_key=None, eos_token_id=None):
    """SAMPLED speculative decoding (ref capability: the speculative
    sampling loops of the reference serving ecosystem — Leviathan/Chen
    rejection sampling): the draft proposes tokens sampled at
    `temperature`; each is accepted with probability
    min(1, p_target/p_draft), and a rejection resamples from the
    normalised residual (p_target - p_draft)+. The OUTPUT DISTRIBUTION
    equals sampling from the target directly (with temperature/top_k/
    top_p applied to BOTH models, the law is the filtered target's) —
    speculative execution changes the cost, not the law (see
    tests/test_decode.py::TestSampledSpeculative for the identity
    check). temperature=0 delegates to the lossless greedy loop.

    Batch 1 (rows would commit at different lengths); host-driven like
    the greedy loop — one sync per window.
    """
    if temperature == 0.0:
        return generate_speculative(target, draft, input_ids,
                                    max_new_tokens, num_draft_tokens,
                                    eos_token_id)
    B, S = input_ids.shape
    if B != 1:
        raise NotImplementedError(
            'sampled speculative decoding is batch-1; loop prompts '
            'individually (greedy supports batches)')
    if rng_key is None:
        rng_key = jax.random.PRNGKey(0)
    restore = []
    for m_ in (target, draft):
        if bool(getattr(m_, 'training', False)):
            m_.eval()
            restore.append(m_)
    try:
        return _speculative_sampled_loop(target, draft, input_ids,
                                         max_new_tokens, num_draft_tokens,
                                         temperature, top_k, top_p,
                                         rng_key, eos_token_id)
    finally:
        for m_ in restore:
            m_.train()


def _sampled_dist(logits, temperature, top_k, top_p):
    """temperature + top-k/top-p filtering applied to BOTH models'
    dists; -inf entries softmax to exact 0, so filtered-out tokens can
    neither be proposed nor resampled."""
    return jax.nn.softmax(
        filter_logits(logits.astype(jnp.float32) / temperature, top_k,
                      top_p), -1)


# Module-level jits (the same persistent-cache discipline as
# inference.engine): sampling config rides as static args, caches are
# donated — repeated calls with one (model, shapes, config) never
# retrace and never copy the KV cache.

@functools.partial(jax.jit, donate_argnames=('caches',),
                   static_argnames=('temperature', 'top_k', 'top_p'))
def _sampled_prefill(m, caches, ids, *, temperature, top_k, top_p):
    logits, caches = m(ids, caches=caches, cache_index=0)
    return _sampled_dist(logits[:, -1, :], temperature, top_k,
                         top_p), caches


@functools.partial(jax.jit, donate_argnames=('caches',),
                   static_argnames=('k', 'temperature', 'top_k', 'top_p'))
def _sampled_propose(m, caches, c, idx, key, *, k, temperature, top_k,
                     top_p):
    """Draft samples k tokens; returns them WITH the draft's full
    distribution at every position (the acceptance rule needs p_draft
    of the chosen token and the residual needs the target dist,
    gathered on the host per window)."""
    def body(carry, i):
        tok, caches, key = carry
        logits, caches = m(tok, caches=caches, cache_index=idx + i)
        p = _sampled_dist(logits[:, -1], temperature, top_k, top_p)
        key, sub = jax.random.split(key)
        nxt = jax.random.categorical(
            sub, jnp.log(jnp.maximum(p, 1e-30))).astype(jnp.int32)
        return (nxt[:, None], caches, key), (nxt, p)
    (_, caches, key), (toks, ps) = jax.lax.scan(
        body, (c, caches, key), jnp.arange(k + 1))
    return toks[:k, 0], ps[:k, 0], caches, key   # (k,), (k, V)


@functools.partial(jax.jit, donate_argnames=('caches',),
                   static_argnames=('temperature', 'top_k', 'top_p'))
def _sampled_verify(m, caches, window, idx, *, temperature, top_k, top_p):
    logits, caches = m(window, caches=caches, cache_index=idx)
    return _sampled_dist(logits[0], temperature, top_k, top_p), caches


def _speculative_sampled_loop(target, draft, input_ids, max_new_tokens,
                              num_draft_tokens, temperature, top_k, top_p,
                              rng_key, eos_token_id):
    B, S = input_ids.shape
    k = int(num_draft_tokens)
    if k < 1:
        raise ValueError('num_draft_tokens must be >= 1')
    max_len = S + max_new_tokens + k + 1
    tcaches = target.init_cache(B, max_len)
    dcaches = draft.init_cache(B, max_len)
    cfg = dict(temperature=float(temperature), top_k=int(top_k),
               top_p=float(top_p))

    def propose(m, caches, c, idx, key):
        return _sampled_propose(m, caches, c, idx, key, k=k, **cfg)

    def verify(m, caches, window, idx):
        return _sampled_verify(m, caches, window, idx, **cfg)

    p_last, tcaches = _sampled_prefill(target, tcaches, input_ids, **cfg)
    _, dcaches = _sampled_prefill(draft, dcaches, input_ids, **cfg)
    rng_key, sub = jax.random.split(rng_key)
    c_host = int(jax.random.categorical(
        sub, jnp.log(jnp.maximum(p_last[0], 1e-30))))

    out = []
    L = S
    # independent streams: the accept/resample coins must not correlate
    # with the proposal keys (the exactness proof assumes independence)
    rng_key, seed_key = jax.random.split(rng_key)
    rng = np.random.default_rng(int(jax.random.randint(
        seed_key, (), 0, 2 ** 31 - 1)))
    while len(out) < max_new_tokens:
        c = jnp.asarray([[c_host]], jnp.int32)
        rng_key, pkey = jax.random.split(rng_key)
        drafts, pd, dcaches, _ = propose(draft, dcaches, c,
                                         jnp.asarray(L, jnp.int32), pkey)
        window = jnp.concatenate([c, drafts[None, :]], axis=1)
        pt, tcaches = verify(target, tcaches, window,
                             jnp.asarray(L, jnp.int32))
        # ONE batched host read per window (the speculative serving
        # contract): the drafts and both models' distributions cross
        # the fence together — was three separate np.asarray syncs per
        # window before tracelint.
        # tracelint: disable=TL002 - one sync per window by design
        d, pt_h, pd_h = jax.device_get((drafts, pt, pd))  # (k,),(k+1,V),(k,V)
        def draw(p):
            # float64 renormalize: f32 quotients can miss Generator.
            # choice's sum-to-1 tolerance at large vocabs
            p = np.asarray(p, np.float64)
            return int(rng.choice(len(p), p=p / p.sum()))

        committed = [c_host]
        nxt = None
        for i in range(k):
            x = int(d[i])
            # ONE source of the acceptance math (the identity-tested
            # helper) for both the test and the production loop
            accept, residual = _speculative_accept_dists(
                jnp.asarray(pt_h[i]), jnp.asarray(pd_h[i]))
            if rng.random() < float(accept[x]):
                committed.append(x)
                continue
            residual = np.asarray(residual, np.float64)
            if residual.sum() <= 0:                   # degenerate: pt<=pd
                residual = pt_h[i]
            nxt = draw(residual)
            break
        if nxt is None:                               # full window accepted
            nxt = draw(pt_h[k])
        out.extend(committed)
        if eos_token_id is not None and eos_token_id in committed:
            out = out[:out.index(eos_token_id) + 1]
            break
        c_host = nxt
        L += len(committed)
    if eos_token_id is not None and len(out) < max_new_tokens:
        out += [eos_token_id] * (max_new_tokens - len(out))
    gen = jnp.asarray([out[:max_new_tokens]], input_ids.dtype)
    return jnp.concatenate([input_ids, gen], axis=1)
