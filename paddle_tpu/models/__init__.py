"""Model zoo (ref: python/paddle/vision/models + PaddleNLP-style LMs).

Flagship: Llama-2 family (`models/llama.py`) — the hybrid-parallel
pretrain target. Vision: ResNet et al (`models/resnet.py`, NHWC,
TPU-friendly layouts).
"""
from . import llama  # noqa: F401
from .llama import LlamaConfig, LlamaForCausalLM, LlamaModel  # noqa: F401
from .resnet import (  # noqa: F401
    ResNet,
    resnet18,
    resnet34,
    resnet50,
    resnet101,
    resnet152,
    resnext50_32x4d,
    resnext50_64x4d,
    resnext101_32x4d,
    resnext101_64x4d,
    resnext152_32x4d,
    resnext152_64x4d,
    wide_resnet50_2,
    wide_resnet101_2,
)
from . import bert  # noqa: F401
from . import gpt  # noqa: F401
from . import llama_pp  # noqa: F401
from . import moe_lm  # noqa: F401
from . import vision  # noqa: F401
from .llama_pp import LlamaForCausalLMPipelined  # noqa: F401
from .bert import BertConfig, BertForMaskedLM, BertForSequenceClassification, BertModel  # noqa: F401
from .gpt import GPTConfig, GPTForCausalLM, GPTModel  # noqa: F401
from .moe_lm import MoEConfig, MoEForCausalLM  # noqa: F401
from .vision import (  # noqa: F401
    AlexNet,
    DenseNet,
    MobileNetV3Large,
    MobileNetV3Small,
    densenet161,
    densenet169,
    densenet201,
    densenet264,
    shufflenet_v2_swish,
    shufflenet_v2_x0_5,
    shufflenet_v2_x0_25,
    shufflenet_v2_x0_33,
    shufflenet_v2_x1_5,
    shufflenet_v2_x2_0,
    GoogLeNet,
    InceptionV3,
    LeNet,
    MobileNetV1,
    MobileNetV2,
    MobileNetV3,
    ShuffleNetV2,
    SqueezeNet,
    VGG,
    alexnet,
    densenet121,
    googlenet,
    inception_v3,
    mobilenet_v1,
    mobilenet_v2,
    mobilenet_v3_large,
    mobilenet_v3_small,
    shufflenet_v2_x1_0,
    squeezenet1_0,
    squeezenet1_1,
    vgg11,
    vgg13,
    vgg16,
    vgg19,
)
from . import convert  # noqa: F401
