"""Model zoo (ref: python/paddle/vision/models + PaddleNLP-style LMs).

Flagship: Llama-2 family (`models/llama.py`) — the hybrid-parallel
pretrain target. Vision: ResNet et al (`models/resnet.py`, NHWC,
TPU-friendly layouts).
"""
from . import llama  # noqa: F401
from .llama import LlamaConfig, LlamaForCausalLM, LlamaModel  # noqa: F401
from .resnet import (  # noqa: F401
    ResNet,
    resnet18,
    resnet34,
    resnet50,
    resnet101,
    resnet152,
)
