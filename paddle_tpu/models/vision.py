"""Vision model zoo (ref: python/paddle/vision/models/*).

Same architecture graphs as the reference zoo (lenet.py, alexnet.py,
vgg.py, mobilenetv1/v2/v3.py, squeezenet.py, shufflenetv2.py,
densenet.py, googlenet.py, inceptionv3.py), rebuilt on pytree layers
with NHWC-first layouts for the TPU MXU conv path.
"""
from __future__ import annotations

import jax.numpy as jnp

from .. import nn


def _flat(x):
    return jnp.reshape(x, (x.shape[0], -1))


class ConvBNAct(nn.Layer):
    def __init__(self, cin, cout, k, stride=1, padding=0, groups=1,
                 act='relu', data_format='NHWC'):
        super().__init__()
        self.conv = nn.Conv2D(cin, cout, k, stride=stride, padding=padding,
                              groups=groups, bias_attr=False,
                              data_format=data_format)
        self.bn = nn.BatchNorm2D(cout, data_format=data_format)
        acts = {'relu': nn.ReLU, 'relu6': nn.ReLU6, 'hardswish': nn.Hardswish,
                'swish': nn.Swish, None: nn.Identity}
        self.act = acts[act]()

    def forward(self, x):
        return self.act(self.bn(self.conv(x)))


# ---------------------------------------------------------------------------
# LeNet (ref: vision/models/lenet.py)
# ---------------------------------------------------------------------------

class LeNet(nn.Layer):
    def __init__(self, num_classes=10, data_format='NHWC'):
        super().__init__()
        self.features = nn.Sequential(
            nn.Conv2D(1, 6, 3, stride=1, padding=1, data_format=data_format),
            nn.ReLU(),
            nn.MaxPool2D(2, 2, data_format=data_format),
            nn.Conv2D(6, 16, 5, stride=1, padding=0, data_format=data_format),
            nn.ReLU(),
            nn.MaxPool2D(2, 2, data_format=data_format),
        )
        self.fc = nn.Sequential(
            nn.Linear(400, 120), nn.Linear(120, 84), nn.Linear(84, num_classes)
        ) if num_classes > 0 else None

    def forward(self, x):
        x = self.features(x)
        if self.fc is not None:
            x = self.fc(_flat(x))
        return x


# ---------------------------------------------------------------------------
# AlexNet (ref: vision/models/alexnet.py)
# ---------------------------------------------------------------------------

class AlexNet(nn.Layer):
    def __init__(self, num_classes=1000, data_format='NHWC'):
        super().__init__()
        df = data_format
        self.features = nn.Sequential(
            nn.Conv2D(3, 64, 11, stride=4, padding=2, data_format=df), nn.ReLU(),
            nn.MaxPool2D(3, 2, data_format=df),
            nn.Conv2D(64, 192, 5, padding=2, data_format=df), nn.ReLU(),
            nn.MaxPool2D(3, 2, data_format=df),
            nn.Conv2D(192, 384, 3, padding=1, data_format=df), nn.ReLU(),
            nn.Conv2D(384, 256, 3, padding=1, data_format=df), nn.ReLU(),
            nn.Conv2D(256, 256, 3, padding=1, data_format=df), nn.ReLU(),
            nn.MaxPool2D(3, 2, data_format=df),
        )
        self.pool = nn.AdaptiveAvgPool2D(6, data_format=df)
        self.classifier = nn.Sequential(
            nn.Dropout(0.5), nn.Linear(256 * 36, 4096), nn.ReLU(),
            nn.Dropout(0.5), nn.Linear(4096, 4096), nn.ReLU(),
            nn.Linear(4096, num_classes),
        )

    def forward(self, x):
        return self.classifier(_flat(self.pool(self.features(x))))


def alexnet(**kw):
    return AlexNet(**kw)


# ---------------------------------------------------------------------------
# VGG (ref: vision/models/vgg.py)
# ---------------------------------------------------------------------------

_VGG_CFGS = {
    11: [64, 'M', 128, 'M', 256, 256, 'M', 512, 512, 'M', 512, 512, 'M'],
    13: [64, 64, 'M', 128, 128, 'M', 256, 256, 'M', 512, 512, 'M', 512, 512, 'M'],
    16: [64, 64, 'M', 128, 128, 'M', 256, 256, 256, 'M', 512, 512, 512, 'M',
         512, 512, 512, 'M'],
    19: [64, 64, 'M', 128, 128, 'M', 256, 256, 256, 256, 'M', 512, 512, 512,
         512, 'M', 512, 512, 512, 512, 'M'],
}


class VGG(nn.Layer):
    def __init__(self, depth=16, num_classes=1000, batch_norm=False,
                 data_format='NHWC'):
        super().__init__()
        layers, cin = [], 3
        for v in _VGG_CFGS[depth]:
            if v == 'M':
                layers.append(nn.MaxPool2D(2, 2, data_format=data_format))
            else:
                layers.append(nn.Conv2D(cin, v, 3, padding=1,
                                        data_format=data_format))
                if batch_norm:
                    layers.append(nn.BatchNorm2D(v, data_format=data_format))
                layers.append(nn.ReLU())
                cin = v
        self.features = nn.Sequential(*layers)
        self.pool = nn.AdaptiveAvgPool2D(7, data_format=data_format)
        self.classifier = nn.Sequential(
            nn.Linear(512 * 49, 4096), nn.ReLU(), nn.Dropout(0.5),
            nn.Linear(4096, 4096), nn.ReLU(), nn.Dropout(0.5),
            nn.Linear(4096, num_classes),
        )

    def forward(self, x):
        return self.classifier(_flat(self.pool(self.features(x))))


def vgg11(**kw):
    return VGG(11, **kw)


def vgg13(**kw):
    return VGG(13, **kw)


def vgg16(**kw):
    return VGG(16, **kw)


def vgg19(**kw):
    return VGG(19, **kw)


# ---------------------------------------------------------------------------
# MobileNetV1 (ref: vision/models/mobilenetv1.py)
# ---------------------------------------------------------------------------

class MobileNetV1(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, data_format='NHWC'):
        super().__init__()
        s = lambda c: max(8, int(c * scale))
        cfg = [(32, 64, 1), (64, 128, 2), (128, 128, 1), (128, 256, 2),
               (256, 256, 1), (256, 512, 2)] + [(512, 512, 1)] * 5 + \
              [(512, 1024, 2), (1024, 1024, 1)]
        layers = [ConvBNAct(3, s(32), 3, 2, 1, data_format=data_format)]
        for cin, cout, stride in cfg:
            layers.append(ConvBNAct(s(cin), s(cin), 3, stride, 1,
                                    groups=s(cin), data_format=data_format))
            layers.append(ConvBNAct(s(cin), s(cout), 1, data_format=data_format))
        self.features = nn.Sequential(*layers)
        self.pool = nn.AdaptiveAvgPool2D(1, data_format=data_format)
        self.fc = nn.Linear(s(1024), num_classes)

    def forward(self, x):
        return self.fc(_flat(self.pool(self.features(x))))


def mobilenet_v1(scale=1.0, **kw):
    return MobileNetV1(scale=scale, **kw)


# ---------------------------------------------------------------------------
# MobileNetV2 (ref: vision/models/mobilenetv2.py)
# ---------------------------------------------------------------------------

class InvertedResidual(nn.Layer):
    def __init__(self, cin, cout, stride, expand, data_format='NHWC'):
        super().__init__()
        hidden = int(round(cin * expand))
        self.use_res = stride == 1 and cin == cout
        layers = []
        if expand != 1:
            layers.append(ConvBNAct(cin, hidden, 1, act='relu6',
                                    data_format=data_format))
        layers += [
            ConvBNAct(hidden, hidden, 3, stride, 1, groups=hidden, act='relu6',
                      data_format=data_format),
            ConvBNAct(hidden, cout, 1, act=None, data_format=data_format),
        ]
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res else out


class MobileNetV2(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, data_format='NHWC'):
        super().__init__()
        cfg = [(1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
               (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1)]
        cin = max(8, int(32 * scale))
        layers = [ConvBNAct(3, cin, 3, 2, 1, act='relu6', data_format=data_format)]
        for t, c, n, stride in cfg:
            cout = max(8, int(c * scale))
            for i in range(n):
                layers.append(InvertedResidual(cin, cout, stride if i == 0 else 1,
                                               t, data_format))
                cin = cout
        last = max(1280, int(1280 * scale))
        layers.append(ConvBNAct(cin, last, 1, act='relu6', data_format=data_format))
        self.features = nn.Sequential(*layers)
        self.pool = nn.AdaptiveAvgPool2D(1, data_format=data_format)
        self.classifier = nn.Sequential(nn.Dropout(0.2), nn.Linear(last, num_classes))

    def forward(self, x):
        return self.classifier(_flat(self.pool(self.features(x))))


def mobilenet_v2(scale=1.0, **kw):
    return MobileNetV2(scale=scale, **kw)


# ---------------------------------------------------------------------------
# MobileNetV3 (ref: vision/models/mobilenetv3.py)
# ---------------------------------------------------------------------------

class SqueezeExcite(nn.Layer):
    def __init__(self, c, r=4, data_format='NHWC'):
        super().__init__()
        self.pool = nn.AdaptiveAvgPool2D(1, data_format=data_format)
        self.fc1 = nn.Conv2D(c, c // r, 1, data_format=data_format)
        self.fc2 = nn.Conv2D(c // r, c, 1, data_format=data_format)
        self.relu = nn.ReLU()
        self.hsig = nn.Hardsigmoid()

    def forward(self, x):
        s = self.hsig(self.fc2(self.relu(self.fc1(self.pool(x)))))
        return x * s


class MBV3Block(nn.Layer):
    def __init__(self, cin, hidden, cout, k, stride, se, act, data_format='NHWC'):
        super().__init__()
        self.use_res = stride == 1 and cin == cout
        layers = []
        if hidden != cin:
            layers.append(ConvBNAct(cin, hidden, 1, act=act, data_format=data_format))
        layers.append(ConvBNAct(hidden, hidden, k, stride, k // 2, groups=hidden,
                                act=act, data_format=data_format))
        if se:
            layers.append(SqueezeExcite(hidden, data_format=data_format))
        layers.append(ConvBNAct(hidden, cout, 1, act=None, data_format=data_format))
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res else out


_MBV3_LARGE = [
    # k, hidden, cout, se, act, stride
    (3, 16, 16, False, 'relu', 1), (3, 64, 24, False, 'relu', 2),
    (3, 72, 24, False, 'relu', 1), (5, 72, 40, True, 'relu', 2),
    (5, 120, 40, True, 'relu', 1), (5, 120, 40, True, 'relu', 1),
    (3, 240, 80, False, 'hardswish', 2), (3, 200, 80, False, 'hardswish', 1),
    (3, 184, 80, False, 'hardswish', 1), (3, 184, 80, False, 'hardswish', 1),
    (3, 480, 112, True, 'hardswish', 1), (3, 672, 112, True, 'hardswish', 1),
    (5, 672, 160, True, 'hardswish', 2), (5, 960, 160, True, 'hardswish', 1),
    (5, 960, 160, True, 'hardswish', 1),
]

_MBV3_SMALL = [
    (3, 16, 16, True, 'relu', 2), (3, 72, 24, False, 'relu', 2),
    (3, 88, 24, False, 'relu', 1), (5, 96, 40, True, 'hardswish', 2),
    (5, 240, 40, True, 'hardswish', 1), (5, 240, 40, True, 'hardswish', 1),
    (5, 120, 48, True, 'hardswish', 1), (5, 144, 48, True, 'hardswish', 1),
    (5, 288, 96, True, 'hardswish', 2), (5, 576, 96, True, 'hardswish', 1),
    (5, 576, 96, True, 'hardswish', 1),
]


class MobileNetV3(nn.Layer):
    def __init__(self, config='large', scale=1.0, num_classes=1000,
                 data_format='NHWC'):
        super().__init__()
        cfg = _MBV3_LARGE if config == 'large' else _MBV3_SMALL
        last_exp = 960 if config == 'large' else 576
        s = lambda c: max(8, int(c * scale))
        cin = s(16)
        layers = [ConvBNAct(3, cin, 3, 2, 1, act='hardswish',
                            data_format=data_format)]
        for k, hidden, cout, se, act, stride in cfg:
            layers.append(MBV3Block(cin, s(hidden), s(cout), k, stride, se, act,
                                    data_format))
            cin = s(cout)
        layers.append(ConvBNAct(cin, s(last_exp), 1, act='hardswish',
                                data_format=data_format))
        self.features = nn.Sequential(*layers)
        self.pool = nn.AdaptiveAvgPool2D(1, data_format=data_format)
        self.classifier = nn.Sequential(
            nn.Linear(s(last_exp), 1280), nn.Hardswish(), nn.Dropout(0.2),
            nn.Linear(1280, num_classes),
        )

    def forward(self, x):
        return self.classifier(_flat(self.pool(self.features(x))))


class MobileNetV3Large(MobileNetV3):
    """ref: paddle.vision.models.MobileNetV3Large."""

    def __init__(self, scale=1.0, num_classes=1000, **kw):
        super().__init__('large', scale, num_classes, **kw)


class MobileNetV3Small(MobileNetV3):
    """ref: paddle.vision.models.MobileNetV3Small."""

    def __init__(self, scale=1.0, num_classes=1000, **kw):
        super().__init__('small', scale, num_classes, **kw)


def mobilenet_v3_large(scale=1.0, **kw):
    return MobileNetV3('large', scale, **kw)


def mobilenet_v3_small(scale=1.0, **kw):
    return MobileNetV3('small', scale, **kw)


# ---------------------------------------------------------------------------
# SqueezeNet (ref: vision/models/squeezenet.py)
# ---------------------------------------------------------------------------

class Fire(nn.Layer):
    def __init__(self, cin, squeeze, e1, e3, data_format='NHWC'):
        super().__init__()
        self.axis = -1 if data_format == 'NHWC' else 1
        self.squeeze = nn.Conv2D(cin, squeeze, 1, data_format=data_format)
        self.expand1 = nn.Conv2D(squeeze, e1, 1, data_format=data_format)
        self.expand3 = nn.Conv2D(squeeze, e3, 3, padding=1, data_format=data_format)
        self.relu = nn.ReLU()

    def forward(self, x):
        x = self.relu(self.squeeze(x))
        return jnp.concatenate(
            [self.relu(self.expand1(x)), self.relu(self.expand3(x))],
            axis=self.axis)


class SqueezeNet(nn.Layer):
    def __init__(self, version='1.1', num_classes=1000, data_format='NHWC'):
        super().__init__()
        df = data_format
        if version == '1.0':
            self.features = nn.Sequential(
                nn.Conv2D(3, 96, 7, stride=2, data_format=df), nn.ReLU(),
                nn.MaxPool2D(3, 2, data_format=df),
                Fire(96, 16, 64, 64, df), Fire(128, 16, 64, 64, df),
                Fire(128, 32, 128, 128, df),
                nn.MaxPool2D(3, 2, data_format=df),
                Fire(256, 32, 128, 128, df), Fire(256, 48, 192, 192, df),
                Fire(384, 48, 192, 192, df), Fire(384, 64, 256, 256, df),
                nn.MaxPool2D(3, 2, data_format=df),
                Fire(512, 64, 256, 256, df),
            )
        else:
            self.features = nn.Sequential(
                nn.Conv2D(3, 64, 3, stride=2, data_format=df), nn.ReLU(),
                nn.MaxPool2D(3, 2, data_format=df),
                Fire(64, 16, 64, 64, df), Fire(128, 16, 64, 64, df),
                nn.MaxPool2D(3, 2, data_format=df),
                Fire(128, 32, 128, 128, df), Fire(256, 32, 128, 128, df),
                nn.MaxPool2D(3, 2, data_format=df),
                Fire(256, 48, 192, 192, df), Fire(384, 48, 192, 192, df),
                Fire(384, 64, 256, 256, df), Fire(512, 64, 256, 256, df),
            )
        self.head = nn.Sequential(
            nn.Dropout(0.5), nn.Conv2D(512, num_classes, 1, data_format=df),
            nn.ReLU(), nn.AdaptiveAvgPool2D(1, data_format=df),
        )

    def forward(self, x):
        return _flat(self.head(self.features(x)))


def squeezenet1_0(**kw):
    return SqueezeNet('1.0', **kw)


def squeezenet1_1(**kw):
    return SqueezeNet('1.1', **kw)


# ---------------------------------------------------------------------------
# ShuffleNetV2 (ref: vision/models/shufflenetv2.py)
# ---------------------------------------------------------------------------

def channel_shuffle(x, groups, data_format='NHWC'):
    if data_format == 'NHWC':
        B, H, W, C = x.shape
        x = x.reshape(B, H, W, groups, C // groups)
        x = jnp.swapaxes(x, 3, 4)
        return x.reshape(B, H, W, C)
    B, C, H, W = x.shape
    x = x.reshape(B, groups, C // groups, H, W)
    x = jnp.swapaxes(x, 1, 2)
    return x.reshape(B, C, H, W)


class ShuffleUnit(nn.Layer):
    def __init__(self, cin, cout, stride, data_format='NHWC', act='relu'):
        super().__init__()
        self.stride = stride
        self.data_format = data_format
        branch = cout // 2
        self.axis = -1 if data_format == 'NHWC' else 1
        if stride > 1:
            self.branch1 = nn.Sequential(
                ConvBNAct(cin, cin, 3, stride, 1, groups=cin, act=None,
                          data_format=data_format),
                ConvBNAct(cin, branch, 1, act=act, data_format=data_format),
            )
            b2_in = cin
        else:
            self.branch1 = None
            b2_in = cin // 2
        self.branch2 = nn.Sequential(
            ConvBNAct(b2_in, branch, 1, act=act, data_format=data_format),
            ConvBNAct(branch, branch, 3, stride, 1, groups=branch, act=None,
                      data_format=data_format),
            ConvBNAct(branch, branch, 1, act=act, data_format=data_format),
        )

    def forward(self, x):
        if self.stride == 1:
            x1, x2 = jnp.split(x, 2, axis=self.axis)
            out = jnp.concatenate([x1, self.branch2(x2)], axis=self.axis)
        else:
            out = jnp.concatenate([self.branch1(x), self.branch2(x)],
                                  axis=self.axis)
        return channel_shuffle(out, 2, self.data_format)


class ShuffleNetV2(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, data_format='NHWC',
                 act='relu'):
        super().__init__()
        stage_out = {0.25: [24, 48, 96, 512], 0.33: [32, 64, 128, 512],
                     0.5: [48, 96, 192, 1024], 1.0: [116, 232, 464, 1024],
                     1.5: [176, 352, 704, 1024], 2.0: [244, 488, 976, 2048]}[scale]
        repeats = [4, 8, 4]
        self.conv1 = ConvBNAct(3, 24, 3, 2, 1, act=act,
                               data_format=data_format)
        self.maxpool = nn.MaxPool2D(3, 2, padding=1, data_format=data_format)
        cin = 24
        stages = []
        for i, r in enumerate(repeats):
            units = [ShuffleUnit(cin, stage_out[i], 2, data_format, act)]
            for _ in range(r - 1):
                units.append(ShuffleUnit(stage_out[i], stage_out[i], 1,
                                         data_format, act))
            stages.append(nn.Sequential(*units))
            cin = stage_out[i]
        self.stages = nn.Sequential(*stages)
        self.conv_last = ConvBNAct(cin, stage_out[3], 1, act=act,
                                   data_format=data_format)
        self.pool = nn.AdaptiveAvgPool2D(1, data_format=data_format)
        self.fc = nn.Linear(stage_out[3], num_classes)

    def forward(self, x):
        x = self.conv_last(self.stages(self.maxpool(self.conv1(x))))
        return self.fc(_flat(self.pool(x)))


def shufflenet_v2_x1_0(**kw):
    return ShuffleNetV2(1.0, **kw)


def shufflenet_v2_x0_25(**kw):
    return ShuffleNetV2(0.25, **kw)


def shufflenet_v2_x0_33(**kw):
    return ShuffleNetV2(0.33, **kw)


def shufflenet_v2_x0_5(**kw):
    return ShuffleNetV2(0.5, **kw)


def shufflenet_v2_x1_5(**kw):
    return ShuffleNetV2(1.5, **kw)


def shufflenet_v2_x2_0(**kw):
    return ShuffleNetV2(2.0, **kw)


def shufflenet_v2_swish(**kw):
    """ref: paddle.vision.models.shufflenet_v2_swish — x1.0 channels
    with swish activations in place of relu."""
    return ShuffleNetV2(1.0, act='swish', **kw)


# ---------------------------------------------------------------------------
# DenseNet (ref: vision/models/densenet.py)
# ---------------------------------------------------------------------------

class DenseLayer(nn.Layer):
    def __init__(self, cin, growth, bn_size, data_format='NHWC'):
        super().__init__()
        self.axis = -1 if data_format == 'NHWC' else 1
        self.bn1 = nn.BatchNorm2D(cin, data_format=data_format)
        self.conv1 = nn.Conv2D(cin, bn_size * growth, 1, bias_attr=False,
                               data_format=data_format)
        self.bn2 = nn.BatchNorm2D(bn_size * growth, data_format=data_format)
        self.conv2 = nn.Conv2D(bn_size * growth, growth, 3, padding=1,
                               bias_attr=False, data_format=data_format)
        self.relu = nn.ReLU()

    def forward(self, x):
        y = self.conv1(self.relu(self.bn1(x)))
        y = self.conv2(self.relu(self.bn2(y)))
        return jnp.concatenate([x, y], axis=self.axis)


class Transition(nn.Layer):
    def __init__(self, cin, cout, data_format='NHWC'):
        super().__init__()
        self.bn = nn.BatchNorm2D(cin, data_format=data_format)
        self.conv = nn.Conv2D(cin, cout, 1, bias_attr=False,
                              data_format=data_format)
        self.pool = nn.AvgPool2D(2, 2, data_format=data_format)
        self.relu = nn.ReLU()

    def forward(self, x):
        return self.pool(self.conv(self.relu(self.bn(x))))


class DenseNet(nn.Layer):
    def __init__(self, layers=121, growth=32, bn_size=4, num_classes=1000,
                 data_format='NHWC'):
        super().__init__()
        blocks = {121: [6, 12, 24, 16], 161: [6, 12, 36, 24],
                  169: [6, 12, 32, 32], 201: [6, 12, 48, 32],
                  264: [6, 12, 64, 48]}[layers]
        df = data_format
        cin = 96 if layers == 161 else 64  # 161 doubles the stem too
        feats = [ConvBNAct(3, cin, 7, 2, 3, data_format=df),
                 nn.MaxPool2D(3, 2, padding=1, data_format=df)]
        for i, n in enumerate(blocks):
            for _ in range(n):
                feats.append(DenseLayer(cin, growth, bn_size, df))
                cin += growth
            if i != len(blocks) - 1:
                feats.append(Transition(cin, cin // 2, df))
                cin //= 2
        feats += [nn.BatchNorm2D(cin, data_format=df), nn.ReLU()]
        self.features = nn.Sequential(*feats)
        self.pool = nn.AdaptiveAvgPool2D(1, data_format=df)
        self.fc = nn.Linear(cin, num_classes)

    def forward(self, x):
        return self.fc(_flat(self.pool(self.features(x))))


def densenet121(**kw):
    return DenseNet(121, **kw)


def densenet161(**kw):
    """ref: paddle.vision.models.densenet161 (growth 48, 96-wide stem)."""
    return DenseNet(161, growth=48, **kw)


def densenet169(**kw):
    return DenseNet(169, **kw)


def densenet201(**kw):
    return DenseNet(201, **kw)


def densenet264(**kw):
    return DenseNet(264, **kw)


# ---------------------------------------------------------------------------
# GoogLeNet (ref: vision/models/googlenet.py)
# ---------------------------------------------------------------------------

class Inception(nn.Layer):
    def __init__(self, cin, c1, c3r, c3, c5r, c5, pool_proj, data_format='NHWC'):
        super().__init__()
        df = data_format
        self.axis = -1 if df == 'NHWC' else 1
        self.b1 = ConvBNAct(cin, c1, 1, data_format=df)
        self.b2 = nn.Sequential(ConvBNAct(cin, c3r, 1, data_format=df),
                                ConvBNAct(c3r, c3, 3, 1, 1, data_format=df))
        self.b3 = nn.Sequential(ConvBNAct(cin, c5r, 1, data_format=df),
                                ConvBNAct(c5r, c5, 5, 1, 2, data_format=df))
        self.b4 = nn.Sequential(nn.MaxPool2D(3, 1, padding=1, data_format=df),
                                ConvBNAct(cin, pool_proj, 1, data_format=df))

    def forward(self, x):
        return jnp.concatenate(
            [self.b1(x), self.b2(x), self.b3(x), self.b4(x)], axis=self.axis)


class GoogLeNet(nn.Layer):
    def __init__(self, num_classes=1000, data_format='NHWC'):
        super().__init__()
        df = data_format
        self.stem = nn.Sequential(
            ConvBNAct(3, 64, 7, 2, 3, data_format=df),
            nn.MaxPool2D(3, 2, padding=1, data_format=df),
            ConvBNAct(64, 64, 1, data_format=df),
            ConvBNAct(64, 192, 3, 1, 1, data_format=df),
            nn.MaxPool2D(3, 2, padding=1, data_format=df),
        )
        self.blocks = nn.Sequential(
            Inception(192, 64, 96, 128, 16, 32, 32, df),
            Inception(256, 128, 128, 192, 32, 96, 64, df),
            nn.MaxPool2D(3, 2, padding=1, data_format=df),
            Inception(480, 192, 96, 208, 16, 48, 64, df),
            Inception(512, 160, 112, 224, 24, 64, 64, df),
            Inception(512, 128, 128, 256, 24, 64, 64, df),
            Inception(512, 112, 144, 288, 32, 64, 64, df),
            Inception(528, 256, 160, 320, 32, 128, 128, df),
            nn.MaxPool2D(3, 2, padding=1, data_format=df),
            Inception(832, 256, 160, 320, 32, 128, 128, df),
            Inception(832, 384, 192, 384, 48, 128, 128, df),
        )
        self.pool = nn.AdaptiveAvgPool2D(1, data_format=df)
        self.dropout = nn.Dropout(0.2)
        self.fc = nn.Linear(1024, num_classes)

    def forward(self, x):
        x = self.pool(self.blocks(self.stem(x)))
        return self.fc(self.dropout(_flat(x)))


def googlenet(**kw):
    return GoogLeNet(**kw)


# ---------------------------------------------------------------------------
# InceptionV3 (ref: vision/models/inceptionv3.py) — compact faithful variant
# ---------------------------------------------------------------------------

class InceptionA(nn.Layer):
    def __init__(self, cin, pool_feat, df='NHWC'):
        super().__init__()
        self.axis = -1 if df == 'NHWC' else 1
        self.b1 = ConvBNAct(cin, 64, 1, data_format=df)
        self.b5 = nn.Sequential(ConvBNAct(cin, 48, 1, data_format=df),
                                ConvBNAct(48, 64, 5, 1, 2, data_format=df))
        self.b3 = nn.Sequential(ConvBNAct(cin, 64, 1, data_format=df),
                                ConvBNAct(64, 96, 3, 1, 1, data_format=df),
                                ConvBNAct(96, 96, 3, 1, 1, data_format=df))
        self.bp = nn.Sequential(nn.AvgPool2D(3, 1, padding=1, data_format=df),
                                ConvBNAct(cin, pool_feat, 1, data_format=df))

    def forward(self, x):
        return jnp.concatenate([self.b1(x), self.b5(x), self.b3(x), self.bp(x)],
                               axis=self.axis)


class InceptionV3(nn.Layer):
    def __init__(self, num_classes=1000, data_format='NHWC'):
        super().__init__()
        df = data_format
        self.stem = nn.Sequential(
            ConvBNAct(3, 32, 3, 2, data_format=df),
            ConvBNAct(32, 32, 3, data_format=df),
            ConvBNAct(32, 64, 3, 1, 1, data_format=df),
            nn.MaxPool2D(3, 2, data_format=df),
            ConvBNAct(64, 80, 1, data_format=df),
            ConvBNAct(80, 192, 3, data_format=df),
            nn.MaxPool2D(3, 2, data_format=df),
        )
        self.blocks = nn.Sequential(
            InceptionA(192, 32, df), InceptionA(256, 64, df),
            InceptionA(288, 64, df),
        )
        self.pool = nn.AdaptiveAvgPool2D(1, data_format=df)
        self.dropout = nn.Dropout(0.5)
        self.fc = nn.Linear(288, num_classes)

    def forward(self, x):
        x = self.pool(self.blocks(self.stem(x)))
        return self.fc(self.dropout(_flat(x)))


def inception_v3(**kw):
    return InceptionV3(**kw)
