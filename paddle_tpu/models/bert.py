"""BERT/ERNIE-base encoder (ref: PaddleNLP BERT/ERNIE; architecture
parity with the reference's transformer encoder stacks): token/position/
segment embeddings + post-LN encoder, MLM head and sequence-classifier
heads for fine-tuning.
"""
from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from .. import nn
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer.base import Layer, Parameter


@dataclasses.dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12
    dropout: float = 0.1
    initializer_range: float = 0.02


def bert_tiny(**kw):
    defaults = dict(vocab_size=256, hidden_size=64, num_hidden_layers=2,
                    num_attention_heads=4, intermediate_size=128,
                    max_position_embeddings=128, dropout=0.0)
    defaults.update(kw)
    return BertConfig(**defaults)


class BertEmbeddings(Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        init = I.Normal(0.0, config.initializer_range)
        h = config.hidden_size
        self.word_embeddings = Parameter(init((config.vocab_size, h), 'float32'))
        self.position_embeddings = Parameter(
            init((config.max_position_embeddings, h), 'float32'))
        self.token_type_embeddings = Parameter(
            init((config.type_vocab_size, h), 'float32'))
        self.layer_norm = nn.LayerNorm(h, epsilon=config.layer_norm_eps)
        self.dropout = nn.Dropout(config.dropout)

    def forward(self, input_ids, token_type_ids=None):
        B, S = input_ids.shape
        pos = jnp.arange(S)[None, :]
        x = self.word_embeddings[input_ids] + self.position_embeddings[pos]
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        x = x + self.token_type_embeddings[token_type_ids]
        return self.dropout(self.layer_norm(x))


class BertLayer(Layer):
    """Post-LN encoder block (original BERT ordering)."""

    def __init__(self, config: BertConfig):
        super().__init__()
        h = config.hidden_size
        self.attn = nn.MultiHeadAttention(h, config.num_attention_heads,
                                          dropout=config.dropout)
        self.ln1 = nn.LayerNorm(h, epsilon=config.layer_norm_eps)
        self.fc1 = nn.Linear(h, config.intermediate_size)
        self.fc2 = nn.Linear(config.intermediate_size, h)
        self.ln2 = nn.LayerNorm(h, epsilon=config.layer_norm_eps)
        self.dropout = nn.Dropout(config.dropout)

    def forward(self, x, attn_mask=None):
        x = self.ln1(x + self.dropout(self.attn(x, attn_mask=attn_mask)))
        h = self.fc2(F.gelu(self.fc1(x)))
        return self.ln2(x + self.dropout(h))


class BertModel(Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.config = config
        self.embeddings = BertEmbeddings(config)
        self.encoder = nn.LayerList(
            [BertLayer(config) for _ in range(config.num_hidden_layers)])
        self.pooler = nn.Linear(config.hidden_size, config.hidden_size)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        x = self.embeddings(input_ids, token_type_ids)
        mask = None
        if attention_mask is not None:
            # (B, S) 1/0 → (B, 1, 1, S) additive-compatible bool
            mask = attention_mask[:, None, None, :].astype(bool)
        for layer in self.encoder:
            x = layer(x, attn_mask=mask)
        pooled = jnp.tanh(self.pooler(x[:, 0]))
        return x, pooled


class BertForMaskedLM(Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.bert = BertModel(config)
        self.transform = nn.Linear(config.hidden_size, config.hidden_size)
        self.layer_norm = nn.LayerNorm(config.hidden_size,
                                       epsilon=config.layer_norm_eps)
        self.decoder_bias = Parameter(jnp.zeros((config.vocab_size,)))

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        hidden, _ = self.bert(input_ids, token_type_ids, attention_mask)
        h = self.layer_norm(F.gelu(self.transform(hidden)))
        return h @ self.bert.embeddings.word_embeddings.T + self.decoder_bias

    def loss(self, input_ids, labels, ignore_index=-100):
        """labels: -100 everywhere except masked positions."""
        logits = self(input_ids).astype(jnp.float32)
        mask = labels != ignore_index
        safe = jnp.where(mask, labels, 0)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
        denom = jnp.maximum(mask.sum(), 1)
        return jnp.where(mask, nll, 0.0).sum() / denom


class BertForSequenceClassification(Layer):
    def __init__(self, config: BertConfig, num_classes=2):
        super().__init__()
        self.bert = BertModel(config)
        self.dropout = nn.Dropout(config.dropout)
        self.classifier = nn.Linear(config.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        _, pooled = self.bert(input_ids, token_type_ids, attention_mask)
        return self.classifier(self.dropout(pooled))

    def loss(self, input_ids, labels, **kw):
        logits = self(input_ids, **kw).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.take_along_axis(logp, labels[:, None], axis=-1).mean()
