"""Llama-2 family — the flagship decoder LM.

ref (architecture parity): PaddleNLP Llama / the reference's
`python/paddle/incubate` transformer stacks; components: RMSNorm
pre-norm, rotary position embedding, SwiGLU MLP, grouped-query
attention, tied-or-untied LM head.

TPU-native design notes:
  - the whole model is a pytree `nn.Layer`; one `jax.jit` / `pjit`
    train step covers fwd+bwd+update.
  - attention goes through `F.scaled_dot_product_attention`, which
    dispatches to the pallas flash-attention kernel on TPU.
  - parameters carry default `PartitionSpec`s for tensor parallelism
    (column-split QKV/gate/up, row-split o_proj/down) so
    `distributed.parallelize` can shard with zero per-model rules;
    the embedding is vocab-sharded ('tp' on vocab axis).
  - generation decodes with a functional KV-cache under
    `lax.while_loop` (static shapes: cache preallocated at max_len).
"""
from __future__ import annotations

import dataclasses
import math
import typing

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .. import nn
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer.base import Layer, Parameter
from .generation import GenerationMixin, PagedKVCache


@dataclasses.dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 32          # < num_attention_heads → GQA
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-5
    rope_theta: float = 10000.0
    # optional dict, e.g. {'rope_type': 'llama3', 'factor': 8.0, ...}
    # (Llama-3.x frequency rescale); None = plain RoPE
    rope_scaling: typing.Optional[dict] = None
    tie_word_embeddings: bool = False
    attention_bias: bool = False           # qkv biases (Qwen2-style)
    initializer_range: float = 0.02
    dtype: str = 'float32'                 # param dtype; compute follows
    remat: bool = False                    # jax.checkpoint each decoder layer
    remat_policy: str = 'dots'             # 'full' | 'dots' (save matmul outs)
    sequence_parallel: bool = False        # shard seq over the 'sp' axis
    sp_mode: str = 'ring'                  # 'ring' | 'ulysses' attention
    # sliding-window (local) attention: each token attends its last
    # `sliding_window` positions (Mistral/Qwen2-style SWA). None = full
    # causal. Layers with index < max_window_layers keep FULL attention
    # (Qwen2's use_sliding_window/max_window_layers semantics).
    sliding_window: typing.Optional[int] = None
    max_window_layers: int = 0

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads


def llama_7b() -> LlamaConfig:
    """Llama-2-7B pretrain config (headline benchmark shape)."""
    return LlamaConfig()


def llama_tiny(vocab_size=256, hidden_size=64, layers=2, heads=4, kv_heads=2,
               intermediate_size=128, max_pos=128) -> LlamaConfig:
    """Tiny config for tests / dryruns."""
    return LlamaConfig(
        vocab_size=vocab_size, hidden_size=hidden_size,
        intermediate_size=intermediate_size, num_hidden_layers=layers,
        num_attention_heads=heads, num_key_value_heads=kv_heads,
        max_position_embeddings=max_pos,
    )


# ---------------------------------------------------------------------------
# Rotary position embedding
# ---------------------------------------------------------------------------

def _llama3_scaled_inv_freq(inv_freq, scaling):
    """Llama-3.x rope scaling (ref: transformers
    modeling_rope_utils._compute_llama3_parameters): long wavelengths
    are slowed by `factor`, short ones kept, with a smooth ramp between
    the low/high frequency cutoffs."""
    factor = scaling['factor']
    low = scaling.get('low_freq_factor', 1.0)
    high = scaling.get('high_freq_factor', 4.0)
    orig = scaling.get('original_max_position_embeddings', 8192)
    wavelen = 2.0 * math.pi / inv_freq
    smooth = (orig / wavelen - low) / (high - low)
    interp = (1.0 - smooth) * inv_freq / factor + smooth * inv_freq
    return jnp.where(wavelen < orig / high, inv_freq,
                     jnp.where(wavelen > orig / low, inv_freq / factor,
                               interp))


def _yarn_scaled_inv_freq(inv_freq, scaling, head_dim, theta):
    """YaRN rope scaling (ref: transformers
    modeling_rope_utils._compute_yarn_parameters): interpolated (long-
    wavelength) and extrapolated (short-wavelength) frequencies blended
    by a per-dimension linear ramp between the beta_fast/beta_slow
    correction dims. Returns (inv_freq, attention_factor) — the factor
    scales cos/sin (softmax temperature correction)."""
    factor = scaling['factor']
    beta_fast = scaling.get('beta_fast', 32.0)
    beta_slow = scaling.get('beta_slow', 1.0)
    # `or`: an explicit None (transformers accepts it) must not reach
    # math.log; model callers inject config.max_position_embeddings
    orig = scaling.get('original_max_position_embeddings') or 4096

    def get_mscale(scale, mscale=1.0):
        # transformers' guard: no temperature correction for scale <= 1
        if scale <= 1:
            return 1.0
        return 0.1 * mscale * math.log(scale) + 1.0

    attention_factor = scaling.get('attention_factor')
    if attention_factor is None:
        mscale = scaling.get('mscale')
        mscale_all_dim = scaling.get('mscale_all_dim')
        if mscale and mscale_all_dim:
            # DeepSeek-style: the two mscales RATIO (transformers
            # _compute_yarn_parameters); mscale without mscale_all_dim is
            # ignored, matching transformers
            attention_factor = float(get_mscale(factor, mscale)
                                     / get_mscale(factor, mscale_all_dim))
        else:
            attention_factor = get_mscale(factor)

    def correction_dim(num_rotations):
        return (head_dim * math.log(orig / (num_rotations * 2 * math.pi))
                ) / (2 * math.log(theta))

    low = max(math.floor(correction_dim(beta_fast)), 0)
    high = min(math.ceil(correction_dim(beta_slow)), head_dim - 1)
    ramp = jnp.clip(
        (jnp.arange(head_dim // 2, dtype=jnp.float32) - low)
        / max(high - low, 0.001), 0.0, 1.0)
    extrapolation_factor = 1.0 - ramp
    inv_freq = (inv_freq / factor * (1 - extrapolation_factor)
                + inv_freq * extrapolation_factor)
    return inv_freq, float(attention_factor)


def rope_cos_sin(positions, head_dim, theta=10000.0, dtype=jnp.float32,
                 rope_scaling=None):
    """cos/sin tables for the given integer positions, shape (..., head_dim//2).

    rope_scaling: optional dict; rope_type 'llama3' applies the Llama-3.x
    frequency rescale, 'yarn' the YaRN interpolation (incl. the
    attention-temperature factor on cos/sin, matching transformers);
    other types are rejected at config time."""
    inv_freq = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, jnp.float32) / head_dim))
    att = 1.0
    if rope_scaling:
        rt = rope_scaling.get('rope_type', rope_scaling.get('type'))
        if rt == 'llama3':
            inv_freq = _llama3_scaled_inv_freq(inv_freq, rope_scaling)
        elif rt == 'yarn':
            inv_freq, att = _yarn_scaled_inv_freq(inv_freq, rope_scaling,
                                                  head_dim, theta)
        elif rt not in (None, 'default'):
            raise ValueError(f'unsupported rope_scaling type {rt!r}')
    angles = positions[..., None].astype(jnp.float32) * inv_freq  # (..., D/2)
    return ((jnp.cos(angles) * att).astype(dtype),
            (jnp.sin(angles) * att).astype(dtype))


def apply_rotary(x, cos, sin):
    """x: (B, S, H, D); cos/sin: (B, S, D/2) or (S, D/2). Rotate-half form."""
    if cos.ndim == 2:
        cos, sin = cos[None], sin[None]
    cos = cos[:, :, None, :]  # (B, S, 1, D/2)
    sin = sin[:, :, None, :]
    x1, x2 = jnp.split(x, 2, axis=-1)
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
    ).astype(x.dtype)


# ---------------------------------------------------------------------------
# Blocks
# ---------------------------------------------------------------------------

def cached_attention(q, k, v, cache, cache_index, kvalid=None,
                     kv_start=None, kv_write_pos=None, window=None,
                     block_tables=None):
    """Shared KV-cached attention step (LlamaAttention, GPTAttention):
    write the S new rows at cache_index, attend over the full cache
    masked by position; single-token steps dispatch to the fused pallas
    decode kernel. `kvalid` (B, max_len) 0/1 marks cache rows that may
    be attended at all — left-padded batched generation puts 0 on the
    pad rows. `kv_start` (B,) asserts the caller's kvalid is exactly the
    contiguous window [kv_start, now] (left-pad hole at the front) —
    with it, single-token steps KEEP the fused kernel (per-row start via
    scalar prefetch) instead of falling back to the masked XLA path.
    `kv_write_pos` (B,) replaces the uniform cache_index with PER-ROW
    write offsets (batched speculative decoding: rows commit at
    different lengths); rows stay contiguous per row — position i of the
    chunk lands at kv_write_pos[b] + i, and attention masks by per-row
    position. `window` (int) applies sliding-window attention over the
    cache: only the last `window` positions are attended — on the fused
    decode path this is just a larger per-row start, so the kernel still
    streams only the live band. Returns (out (B, S, H, D), new_cache).

    A QuantKVCache stores K/V int8 with per-(head, dim) scales: prefill
    (S > 1) calibrates the scales from its own rows, decode steps
    quantize against them; attention dequantizes (in-kernel on the
    pallas path, whole-cache on the XLA fallback).

    A PagedKVCache (with `block_tables` (B, MAXB) int32) is the
    continuous-batching serving layout: the new K/V row of batch row b
    lands in page block_tables[b, wp // BS] slot wp % BS (wp =
    kv_write_pos[b], required), and attention streams exactly the pages
    the row occupies — the fused pallas paged kernel on TPU
    (ops/pallas/paged_attention.py, block table scalar-prefetched into
    the BlockSpec index map), a gather reference elsewhere. Decode-only
    (S == 1); rows whose table entry is 0 write to the reserved scratch
    page (inference/serving.py parks inactive slots there)."""
    from .generation import (PagedKVCache, QuantKVCache,
                             QuantPagedKVCache, RowQuantKVCache,
                             calibrate_kv_scale, dequantize_kv_row,
                             quantize_kv_row, quantize_kv_rows)

    B, S, H, D = q.shape
    if isinstance(cache, (PagedKVCache, QuantPagedKVCache)):
        return _paged_cached_attention(q, k, v, cache, kv_write_pos,
                                       block_tables, window, kvalid,
                                       kv_start)
    if kv_write_pos is not None:
        wp = jnp.reshape(jnp.asarray(kv_write_pos, jnp.int32), (-1,))
        wp = jnp.broadcast_to(wp, (B,))
        rows = jnp.arange(B)[:, None]
        wcols = wp[:, None] + jnp.arange(S)[None, :]

        def write(buf, new):
            return buf.at[rows, wcols].set(new.astype(buf.dtype))
    else:
        def write(buf, new):
            return jax.lax.dynamic_update_slice(
                buf, new.astype(buf.dtype), (0, cache_index, 0, 0))
    rowquant = isinstance(cache, RowQuantKVCache)
    if rowquant:
        # per-row int8 (the serving engine's fused multi-token bodies):
        # rows quantize one at a time against their own amax — the
        # exact rule the QuantPagedKVCache pools apply — and the whole
        # cache dequantizes EAGERLY for attention, so every attended
        # value is the int8-roundtripped one a paged decode step would
        # see. That shared roundtrip is what keeps int8 serving streams
        # bit-equal across prefill / chunk / speculative / decode paths.
        kq, vq, ks, vs = cache
        knew, ks_new = quantize_kv_row(k)
        vnew, vs_new = quantize_kv_row(v)
        kq = write(kq, knew)
        vq = write(vq, vnew)
        if kv_write_pos is not None:
            ks = ks.at[rows, wcols].set(ks_new)
            vs = vs.at[rows, wcols].set(vs_new)
        else:
            ks = jax.lax.dynamic_update_slice(ks, ks_new,
                                              (0, cache_index, 0))
            vs = jax.lax.dynamic_update_slice(vs, vs_new,
                                              (0, cache_index, 0))
        new_cache = RowQuantKVCache(kq, vq, ks, vs)
        ck = dequantize_kv_row(kq, ks, q.dtype)
        cv = dequantize_kv_row(vq, vs, q.dtype)
    quant = isinstance(cache, QuantKVCache)
    if quant:
        kq, vq, kscale, vscale = cache
        # calibrate ONLY on the index-0 prefill: a later multi-token
        # chunk (chunked prefill, speculative verify) must keep the
        # existing scales — recalibrating would reinterpret every int8
        # row already in the cache under new scales. cache_index is a
        # concrete 0 at prefill in all generation loops; traced indices
        # are by construction later steps.
        is_prefill = (S > 1 and kv_write_pos is None
                      and not isinstance(cache_index, jax.core.Tracer)
                      and int(cache_index) == 0)
        if is_prefill:
            kscale = calibrate_kv_scale(k)
            vscale = calibrate_kv_scale(v)
        kq = write(kq, quantize_kv_rows(k, kscale))
        vq = write(vq, quantize_kv_rows(v, vscale))
        new_cache = QuantKVCache(kq, vq, kscale, vscale)
        ck, cv = kq, vq
    elif not rowquant:                 # rowquant set ck/cv above
        ck, cv = cache
        ck = write(ck, k)
        cv = write(cv, v)
        new_cache = (ck, cv)
    max_len = ck.shape[1]
    out = None
    if S == 1 and D % 8 == 0 and (kvalid is None or kv_start is not None):
        from ..ops import use_pallas

        if use_pallas():
            # fused single-token decode: one streaming pass over the
            # cache, routed through the serving dispatcher
            # (ops/pallas/decode_attention.py — the same entry point the
            # DecodeEngine decode loop reaches); under a tp mesh each
            # head-shard runs its own kernel via shard_map (the GQA
            # group alignment survives contiguous head sharding)
            try:
                from ..ops.pallas.decode_attention import (
                    dispatch_decode_attention)

                mesh = None
                from ..distributed.mesh import get_mesh

                m = get_mesh()
                if (m is not None and m.shape.get('tp', 1) > 1
                        and ck.shape[2] % m.shape['tp'] == 0
                        and H % m.shape['tp'] == 0):
                    mesh = m
                if mesh is not None:
                    from jax.sharding import PartitionSpec as P

                    from ..distributed._spmd import shard_map

                    from ..distributed.parallel import _valid_spec

                    # mirror init_cache's placement (batch over dp/fsdp
                    # when divisible, heads over tp) so a batch-sharded
                    # cache is NOT all-gathered every decode step
                    hspec = _valid_spec(
                        P(('dp', 'fsdp'), None, 'tp', None), ck.shape, mesh)
                    bat = hspec[0]
                    vl = jnp.broadcast_to(jnp.asarray(
                        wp + 1 if kv_write_pos is not None
                        else cache_index + 1, jnp.int32), (B,))
                    st = jnp.broadcast_to(jnp.asarray(
                        0 if kv_start is None else kv_start, jnp.int32),
                        (B,))
                    if quant:
                        sspec = _valid_spec(P('tp', None), kscale.shape,
                                            mesh)

                        def _da8(q_, k_, v_, vl_, st_, ks_, vs_):
                            return dispatch_decode_attention(
                                q_, k_, v_, vl_, start=st_, window=window,
                                k_scale=ks_, v_scale=vs_)

                        out = shard_map(
                            _da8, mesh=mesh,
                            in_specs=(hspec, hspec, hspec, P(bat), P(bat),
                                      sspec, sspec),
                            out_specs=hspec, check_vma=False,
                        )(q, ck, cv, vl, st, kscale, vscale)
                    else:
                        def _da(q_, k_, v_, vl_, st_):
                            return dispatch_decode_attention(
                                q_, k_, v_, vl_, start=st_, window=window)

                        out = shard_map(
                            _da, mesh=mesh,
                            in_specs=(hspec, hspec, hspec, P(bat), P(bat)),
                            out_specs=hspec, check_vma=False,
                        )(q, ck, cv, vl, st)
                else:
                    vl1 = (wp + 1 if kv_write_pos is not None
                           else cache_index + 1)
                    out = dispatch_decode_attention(
                        q, ck, cv, vl1, start=kv_start, window=window,
                        k_scale=kscale if quant else None,
                        v_scale=vscale if quant else None)
            except Exception as e:
                from ..ops import pallas_failed

                pallas_failed('decode_attention', e)
    if out is None:
        # valid keys: position <= current query position (& kvalid)
        kpos = jnp.arange(max_len)
        if kv_write_pos is not None:
            # per-row query positions (batched speculative verify)
            qpos = wp[:, None] + jnp.arange(S)[None, :]        # (B, S)
            mask = (kpos[None, None, None, :] <= qpos[:, None, :, None])
        else:
            qpos = cache_index + jnp.arange(S)
            mask = (kpos[None, :] <= qpos[:, None])[None, None]
        if kvalid is not None:
            mask = mask & (kvalid[:, None, None, :] > 0)
        if kv_start is not None:
            # honor the window start here too: a caller passing only
            # kv_start must see the same window whether or not the
            # fused kernel ran
            st = jnp.reshape(jnp.asarray(kv_start, jnp.int32), (-1,))
            mask = mask & (kpos[None, :] >= st[:, None])[:, None, None, :]
        if window is not None:
            # sliding window: qpos - kpos < window (qpos is (S,) uniform
            # or (B, S) per-row; both broadcast against kpos)
            if qpos.ndim == 2:
                band = (qpos[:, :, None] - kpos[None, None, :]
                        < window)[:, None]
            else:
                band = (qpos[:, None] - kpos[None, :] < window)[None, None]
            mask = mask & band
        if quant:
            # XLA fallback: whole-cache dequant (correctness path; the
            # bandwidth win lives in the pallas kernel)
            ck = (ck.astype(jnp.float32) * kscale[None, None]).astype(q.dtype)
            cv = (cv.astype(jnp.float32) * vscale[None, None]).astype(q.dtype)
        out = F.scaled_dot_product_attention(q, ck, cv, attn_mask=mask)
    return out, new_cache


def _paged_cached_attention(q, k, v, cache, kv_write_pos, block_tables,
                            window, kvalid, kv_start):
    """Single-token decode over a PagedKVCache: scatter the new row
    into its page, then attend over the row's pages masked by the
    per-row valid length (kv_write_pos + 1). See cached_attention."""
    B, S, H, D = q.shape
    if kvalid is not None or kv_start is not None:
        # these are masking CONTRACTS on the other branches — dropping
        # them silently would attend pad rows; paged serving right-pads
        # at prefill so neither is ever needed (positions [0, wp) are
        # always exactly the live tokens)
        raise NotImplementedError(
            'kvalid/kv_start are not supported with a PagedKVCache: '
            'paged prefill is right-padded, so the valid window is '
            'always [0, kv_write_pos) with no pad hole to mask')
    if S != 1:
        raise NotImplementedError(
            'PagedKVCache is decode-only (S == 1): prefill scatters '
            'whole prompts into pages via '
            'inference.serving._paged_prefill, and speculative windows '
            'are not paged yet')
    if kv_write_pos is None or block_tables is None:
        raise ValueError(
            'PagedKVCache needs kv_write_pos (per-row write positions) '
            'and block_tables (per-row page ids)')
    if window is not None:
        raise NotImplementedError(
            'sliding-window attention over a paged cache is not '
            'supported: serve SWA models through the contiguous '
            'DecodeEngine path')
    from .generation import (QuantPagedKVCache, dequantize_kv_row,
                             quantize_kv_row)

    quant = isinstance(cache, QuantPagedKVCache)
    if quant:
        kp, vp, kss, vss = cache
    else:
        kp, vp = cache
    NB, Hkv, BS, _ = kp.shape
    tbl = jnp.asarray(block_tables, jnp.int32)
    maxb = tbl.shape[1]
    wp = jnp.broadcast_to(
        jnp.reshape(jnp.asarray(kv_write_pos, jnp.int32), (-1,)), (B,))
    rows = jnp.arange(B)
    # frozen rows can sit one position past their last allocated page:
    # clamp the COLUMN (the scheduler parks such rows on table entry 0,
    # the scratch page, so the clamped write stays harmless)
    page = tbl[rows, jnp.minimum(wp // BS, maxb - 1)]
    slot = wp % BS
    if quant:
        # per-row int8: the new row quantizes against its own amax (the
        # same pure-function rule the serving prefill scatter applies),
        # so this row's int8 bytes are identical whether it was written
        # here or by a re-prefill after preemption
        kq, ksr = quantize_kv_row(k[:, 0])       # (B, Hkv, D), (B, Hkv)
        vq, vsr = quantize_kv_row(v[:, 0])
        kp = kp.at[page, :, slot, :].set(kq)
        vp = vp.at[page, :, slot, :].set(vq)
        kss = kss.at[page, :, slot].set(ksr)
        vss = vss.at[page, :, slot].set(vsr)
        new_cache = QuantPagedKVCache(kp, vp, kss, vss)
    else:
        kp = kp.at[page, :, slot, :].set(k[:, 0].astype(kp.dtype))
        vp = vp.at[page, :, slot, :].set(v[:, 0].astype(vp.dtype))
        new_cache = PagedKVCache(kp, vp)
    counts = wp + 1
    out = None
    if D % 8 == 0:
        from ..ops import use_pallas

        if use_pallas():
            try:
                from ..ops.pallas.paged_attention import (
                    paged_decode_attention)

                out = paged_decode_attention(
                    q, kp, vp, tbl, counts,
                    k_scale=kss if quant else None,
                    v_scale=vss if quant else None)
            except Exception as e:
                from ..ops import pallas_failed

                pallas_failed('paged_attention', e)
    if out is None:
        # gather reference (CPU tests / non-TPU): pages -> a contiguous
        # (B, MAXB*BS, Hkv, D) view, masked by per-row valid length;
        # int8 pools dequantize with the shared per-row expression
        gk, gv = kp[tbl], vp[tbl]                # (B, maxb, Hkv, BS, D)
        if quant:
            gk = dequantize_kv_row(gk, kss[tbl], q.dtype)
            gv = dequantize_kv_row(gv, vss[tbl], q.dtype)
        ck = jnp.swapaxes(gk, 2, 3).reshape(B, maxb * BS, Hkv, D)
        cv = jnp.swapaxes(gv, 2, 3).reshape(B, maxb * BS, Hkv, D)
        mask = (jnp.arange(maxb * BS)[None, :]
                < counts[:, None])[:, None, None, :]
        out = F.scaled_dot_product_attention(q, ck, cv, attn_mask=mask)
    return out, new_cache


class LlamaAttention(Layer):
    """GQA attention with RoPE. Column-parallel QKV, row-parallel output."""

    def __init__(self, config: LlamaConfig, layer_idx: int = 0):
        super().__init__()
        # Qwen2 semantics: SWA only on layers >= max_window_layers
        self.sliding_window = (
            config.sliding_window
            if (config.sliding_window is not None
                and layer_idx >= config.max_window_layers) else None)
        self.num_heads = config.num_attention_heads
        self.num_kv_heads = config.num_key_value_heads
        self.head_dim = config.head_dim
        self.rope_theta = config.rope_theta
        rs = config.rope_scaling
        if (rs and rs.get('rope_type', rs.get('type')) == 'yarn'
                and rs.get('original_max_position_embeddings') is None):
            # transformers falls back to config.max_position_embeddings
            # for the yarn correction ramp — a 4096 guess here would
            # silently skew every frequency
            rs = dict(rs, original_max_position_embeddings=config
                      .max_position_embeddings)
        self.rope_scaling = rs
        self.sequence_parallel = config.sequence_parallel
        if self.sequence_parallel and self.sliding_window is not None:
            import warnings

            warnings.warn(
                'sliding_window disables the ring/ulysses sequence-'
                'parallel attention path (the ring schedule has no '
                'window fast path yet); attention falls back to the '
                'flash kernel on sp-sharded activations, which GSPMD '
                'reshards — expect a perf cliff, not wrong results',
                stacklevel=3)
        if config.sp_mode not in ('ring', 'ulysses'):
            raise ValueError(
                f"sp_mode must be 'ring' or 'ulysses', got "
                f'{config.sp_mode!r}')
        self.sp_mode = config.sp_mode
        init = I.Normal(0.0, config.initializer_range)
        h, d = config.hidden_size, self.head_dim
        self.q_proj = Parameter(init((h, self.num_heads * d), config.dtype), spec=P(None, 'tp'))
        self.k_proj = Parameter(init((h, self.num_kv_heads * d), config.dtype), spec=P(None, 'tp'))
        self.v_proj = Parameter(init((h, self.num_kv_heads * d), config.dtype), spec=P(None, 'tp'))
        self.o_proj = Parameter(init((self.num_heads * d, h), config.dtype), spec=P('tp', None))
        if config.attention_bias:          # Qwen2-style qkv biases
            zeros = lambda n: jnp.zeros((n,), jnp.dtype(config.dtype))
            self.q_bias = Parameter(zeros(self.num_heads * d), spec=P('tp'))
            self.k_bias = Parameter(zeros(self.num_kv_heads * d), spec=P('tp'))
            self.v_bias = Parameter(zeros(self.num_kv_heads * d), spec=P('tp'))
        else:
            self.q_bias = self.k_bias = self.v_bias = None

    def forward(self, x, positions, attn_mask=None, cache=None,
                cache_index=None, kvalid=None, kv_start=None,
                kv_write_pos=None, block_tables=None):
        """x: (B, S, H). cache: optional (k, v) of (B, max_len, Hkv, D).

        Returns (out, new_cache). With a cache, writes the S new kv rows at
        cache_index and attends over the full cache (masked by position;
        `kvalid` additionally invalidates rows — left-pad support).
        """
        B, S, _ = x.shape
        q, k, v = x @ self.q_proj, x @ self.k_proj, x @ self.v_proj
        if self.q_bias is not None:
            q, k, v = q + self.q_bias, k + self.k_bias, v + self.v_bias
        q = q.reshape(B, S, self.num_heads, self.head_dim)
        k = k.reshape(B, S, self.num_kv_heads, self.head_dim)
        v = v.reshape(B, S, self.num_kv_heads, self.head_dim)

        cos, sin = rope_cos_sin(positions, self.head_dim, self.rope_theta,
                                rope_scaling=self.rope_scaling)
        q = apply_rotary(q, cos, sin)
        k = apply_rotary(k, cos, sin)

        if cache is None:
            win = self.sliding_window
            if kvalid is not None or (win is not None
                                      and attn_mask is not None):
                # honor pad-invalidation (and the SWA band when a user
                # mask blocks the kernel path) on the uncached path too:
                # fold into an explicit causal mask (silently ignoring
                # kvalid would let real tokens attend to pads)
                extra_mask = (jnp.arange(S)[None, :]
                              <= jnp.arange(S)[:, None])[None, None]
                if kvalid is not None:
                    extra_mask = extra_mask & (
                        kvalid[:, :S] > 0)[:, None, None, :]
                if win is not None:
                    extra_mask = extra_mask & (
                        jnp.arange(S)[:, None] - jnp.arange(S)[None, :]
                        < win)[None, None]
                    win = None          # folded; don't pass to sdpa too
                if attn_mask is None:
                    attn_mask = extra_mask
                elif attn_mask.dtype == jnp.bool_:
                    attn_mask = attn_mask & extra_mask
                else:                  # additive float mask (see attention.py)
                    attn_mask = attn_mask + jnp.where(
                        extra_mask, 0.0, -1e30).astype(attn_mask.dtype)
            out = None
            if (self.sequence_parallel and attn_mask is None
                    and win is None):
                from ..distributed.mesh import get_mesh

                mesh = get_mesh()
                if (mesh is not None and 'sp' in mesh.axis_names
                        and mesh.shape['sp'] > 1
                        and S % mesh.shape['sp'] == 0):
                    n_sp = mesh.shape['sp']
                    use_ulysses = self.sp_mode == 'ulysses'
                    if use_ulysses and (self.num_heads % n_sp
                                        or self.num_kv_heads % n_sp):
                        import warnings

                        warnings.warn(
                            f'sp_mode=ulysses needs heads divisible by the '
                            f'sp axis ({self.num_heads}/{self.num_kv_heads} '
                            f'heads vs sp={n_sp}); falling back to ring '
                            f'attention', stacklevel=2)
                        use_ulysses = False
                    if use_ulysses:
                        # all-to-all swaps the shard dim seq->heads; each
                        # rank runs full-seq flash for its head slice
                        from ..distributed.ulysses import (
                            ulysses_attention_sharded)

                        out = ulysses_attention_sharded(
                            q, k, v, mesh, axis='sp', causal=True)
                    else:
                        # KV blocks ring around the ICI via ppermute —
                        # no device ever holds the full KV
                        from ..distributed.ring_attention import (
                            ring_attention_sharded)

                        out = ring_attention_sharded(q, k, v, mesh,
                                                     axis='sp', causal=True)
            if out is None:
                out = F.scaled_dot_product_attention(
                    q, k, v, attn_mask=attn_mask, is_causal=attn_mask is None,
                    window_size=win)
            new_cache = None
        else:
            out, new_cache = cached_attention(q, k, v, cache, cache_index,
                                              kvalid=kvalid,
                                              kv_start=kv_start,
                                              kv_write_pos=kv_write_pos,
                                              window=self.sliding_window,
                                              block_tables=block_tables)

        out = out.reshape(B, S, self.num_heads * self.head_dim)
        return out @ self.o_proj, new_cache


class LlamaMLP(Layer):
    """SwiGLU: down(silu(gate(x)) * up(x)). Column gate/up, row down."""

    def __init__(self, config: LlamaConfig):
        super().__init__()
        init = I.Normal(0.0, config.initializer_range)
        h, m = config.hidden_size, config.intermediate_size
        self.gate_proj = Parameter(init((h, m), config.dtype), spec=P(None, 'tp'))
        self.up_proj = Parameter(init((h, m), config.dtype), spec=P(None, 'tp'))
        self.down_proj = Parameter(init((m, h), config.dtype), spec=P('tp', None))

    def forward(self, x):
        return (F.silu(x @ self.gate_proj) * (x @ self.up_proj)) @ self.down_proj


class LlamaDecoderLayer(Layer):
    def __init__(self, config: LlamaConfig, layer_idx: int = 0):
        super().__init__()
        self.input_layernorm = nn.RMSNorm(config.hidden_size, epsilon=config.rms_norm_eps)
        self.self_attn = LlamaAttention(config, layer_idx)
        self.post_attention_layernorm = nn.RMSNorm(config.hidden_size, epsilon=config.rms_norm_eps)
        self.mlp = LlamaMLP(config)

    def forward(self, x, positions, attn_mask=None, cache=None,
                cache_index=None, kvalid=None, kv_start=None,
                kv_write_pos=None, block_tables=None):
        attn_out, new_cache = self.self_attn(
            self.input_layernorm(x), positions, attn_mask, cache,
            cache_index, kvalid, kv_start, kv_write_pos,
            block_tables=block_tables
        )
        x = x + attn_out
        x = x + self.mlp(self.post_attention_layernorm(x))
        return x, new_cache


class LlamaModel(Layer):
    """Embedding + decoder stack + final norm."""

    # vocab table is gathered (and .T-served when tied) — exempt from
    # weight-only PTQ (quantization.quantize_matmul_weights)
    no_quantize = ('embed_tokens',)

    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        init = I.Normal(0.0, config.initializer_range)
        self.embed_tokens = Parameter(
            init((config.vocab_size, config.hidden_size), config.dtype), spec=P('tp', None)
        )
        self.layers = nn.LayerList(
            [LlamaDecoderLayer(config, i)
             for i in range(config.num_hidden_layers)]
        )
        self.norm = nn.RMSNorm(config.hidden_size, epsilon=config.rms_norm_eps)

    def forward(self, input_ids, positions=None, attn_mask=None, caches=None,
                cache_index=None, kvalid=None, kv_start=None,
                kv_write_pos=None, block_tables=None):
        B, S = input_ids.shape
        if positions is None:
            from .generation import default_positions

            positions = default_positions(B, S, cache_index, kv_write_pos)
        # mesh-aware lookup: one_hot matmul under a sharded mesh so the
        # (tp, fsdp) table sharding doesn't force an activation remat
        # (see distributed.embedding_lookup)
        from ..distributed import embedding_lookup
        x = embedding_lookup(self.embed_tokens, input_ids)
        new_caches = [] if caches is not None else None
        use_remat = self.config.remat and caches is None
        for i, layer in enumerate(self.layers):
            cache = caches[i] if caches is not None else None
            if use_remat:
                # 'dots': keep matmul outputs, recompute elementwise — far
                # cheaper recompute than full remat at slightly more HBM
                policy = (jax.checkpoint_policies.dots_with_no_batch_dims_saveable
                          if self.config.remat_policy == 'dots' else None)
                x = jax.checkpoint(
                    lambda lyr, h: lyr(h, positions, attn_mask,
                                       kvalid=kvalid)[0],
                    policy=policy,
                )(layer, x)
                nc = None
            else:
                x, nc = layer(x, positions, attn_mask, cache, cache_index,
                              kvalid, kv_start, kv_write_pos,
                              block_tables=block_tables)
            if new_caches is not None:
                new_caches.append(nc)
        return self.norm(x), new_caches


class LlamaForCausalLM(GenerationMixin, Layer):
    """LM head on top; loss = causal cross-entropy (shifted); generation
    (greedy/sampled/beam) via models/generation.py::GenerationMixin."""

    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.model = LlamaModel(config)
        if config.tie_word_embeddings:
            self.lm_head = None
        else:
            init = I.Normal(0.0, config.initializer_range)
            self.lm_head = Parameter(
                init((config.hidden_size, config.vocab_size), config.dtype),
                spec=P(None, 'tp'),
            )

    def logits(self, hidden):
        if self.lm_head is None:
            return hidden @ self.model.embed_tokens.T
        return hidden @ self.lm_head

    def forward(self, input_ids, positions=None, attn_mask=None, caches=None,
                cache_index=None, kvalid=None, kv_start=None,
                kv_write_pos=None, block_tables=None):
        hidden, new_caches = self.model(input_ids, positions, attn_mask, caches,
                                        cache_index, kvalid, kv_start,
                                        kv_write_pos, block_tables)
        logits = self.logits(hidden)
        if caches is None:
            return logits
        return logits, new_caches

    def loss(self, input_ids, labels=None):
        """Next-token cross-entropy (fused pallas softmax-xent on TPU)."""
        from ..ops import softmax_cross_entropy

        if labels is None:
            labels = input_ids[:, 1:]
            input_ids = input_ids[:, :-1]
        logits = self(input_ids)
        return softmax_cross_entropy(logits, labels).mean()


    # -- generation (loops from GenerationMixin) ---------------------------
    def cache_dtype(self):
        return self.model.embed_tokens.dtype



# ---------------------------------------------------------------------------
# TP sharding rules (consumed by distributed.parallelize)
# ---------------------------------------------------------------------------

LLAMA_TP_RULES: typing.List[typing.Tuple[str, typing.Any]] = [
    (r'.*embed_tokens$', P('tp', None)),
    (r'.*(q|k|v)_proj$', P(None, 'tp')),
    (r'.*o_proj$', P('tp', None)),
    (r'.*(gate|up)_proj$', P(None, 'tp')),
    (r'.*down_proj$', P('tp', None)),
    (r'.*lm_head$', P(None, 'tp')),
]
