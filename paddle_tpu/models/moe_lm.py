"""MoE language model — DeepSeek/ERNIE-MoE style (ref:
python/paddle/incubate/distributed/models/moe + DeepSeek-MoE shared+
routed experts): a Llama-style decoder where MLPs are replaced by
`distributed.moe.MoELayer` (top-k routed experts + shared experts),
expert-parallel over the 'ep' mesh axis.
"""
from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from .. import nn
from ..distributed.moe import MoELayer
from ..nn import initializer as I
from ..nn.layer.base import Layer, Parameter
from .generation import GenerationMixin
from .llama import LlamaAttention, LlamaConfig


@dataclasses.dataclass
class MoEConfig:
    vocab_size: int = 32000
    hidden_size: int = 2048
    intermediate_size: int = 1408      # per-expert
    num_hidden_layers: int = 24
    num_attention_heads: int = 16
    num_key_value_heads: int = 16
    num_experts: int = 64
    num_shared_experts: int = 2
    top_k: int = 6
    capacity_factor: float = 1.25
    aux_loss_weight: float = 0.01
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-6
    rope_theta: float = 10000.0
    initializer_range: float = 0.02
    # 'auto' | 'dense' (GShard capacity dispatch) | 'ragged' (dropless
    # sort + grouped GEMM — required for HF-Mixtral logit parity, since
    # capacity dispatch drops tokens)
    dispatch_mode: str = 'auto'

    def attn_config(self) -> LlamaConfig:
        return LlamaConfig(
            vocab_size=self.vocab_size, hidden_size=self.hidden_size,
            intermediate_size=self.intermediate_size,
            num_hidden_layers=self.num_hidden_layers,
            num_attention_heads=self.num_attention_heads,
            num_key_value_heads=self.num_key_value_heads,
            max_position_embeddings=self.max_position_embeddings,
            rms_norm_eps=self.rms_norm_eps, rope_theta=self.rope_theta,
            initializer_range=self.initializer_range,
        )


def moe_tiny(**kw):
    defaults = dict(vocab_size=256, hidden_size=64, intermediate_size=64,
                    num_hidden_layers=2, num_attention_heads=4,
                    num_key_value_heads=2, num_experts=4,
                    num_shared_experts=1, top_k=2, max_position_embeddings=128)
    defaults.update(kw)
    return MoEConfig(**defaults)


class MoEDecoderLayer(Layer):
    def __init__(self, config: MoEConfig):
        super().__init__()
        acfg = config.attn_config()
        self.input_layernorm = nn.RMSNorm(config.hidden_size,
                                          epsilon=config.rms_norm_eps)
        self.self_attn = LlamaAttention(acfg)
        self.post_attention_layernorm = nn.RMSNorm(config.hidden_size,
                                                   epsilon=config.rms_norm_eps)
        self.moe = MoELayer(
            hidden=config.hidden_size, intermediate=config.intermediate_size,
            num_experts=config.num_experts, top_k=config.top_k,
            capacity_factor=config.capacity_factor,
            num_shared_experts=config.num_shared_experts, return_aux=True,
            dispatch_mode=config.dispatch_mode,
        )

    def forward(self, x, positions, cache=None, cache_index=None,
                kvalid=None, kv_start=None, kv_write_pos=None):
        attn_out, new_cache = self.self_attn(
            self.input_layernorm(x), positions, None, cache, cache_index,
            kvalid, kv_start, kv_write_pos)
        x = x + attn_out
        # cached decode routes dropless: dense capacity computed from a
        # single-token call would drop colliding tokens
        moe_out, aux = self.moe(self.post_attention_layernorm(x),
                                dropless=cache is not None)
        return x + moe_out, aux, new_cache


class MoEForCausalLM(GenerationMixin, Layer):
    # vocab table is gathered, not matmul'd — exempt from weight-only PTQ
    no_quantize = ('embed_tokens',)

    def __init__(self, config: MoEConfig):
        super().__init__()
        self.config = config
        init = I.Normal(0.0, config.initializer_range)
        self.embed_tokens = Parameter(
            init((config.vocab_size, config.hidden_size), 'float32'))
        self.layers = nn.LayerList(
            [MoEDecoderLayer(config) for _ in range(config.num_hidden_layers)])
        self.norm = nn.RMSNorm(config.hidden_size, epsilon=config.rms_norm_eps)
        self.lm_head = Parameter(
            init((config.hidden_size, config.vocab_size), 'float32'))

    def forward(self, input_ids, positions=None, caches=None,
                cache_index=None, kvalid=None, kv_start=None,
                kv_write_pos=None):
        """Returns (logits, total_aux_loss), or (logits, new_caches) when
        called with a KV-cache (the GenerationMixin cached-call
        contract, same as LlamaForCausalLM — incl. left-padded
        attention_mask generation and batched speculative decoding via
        positions/kvalid/kv_start/kv_write_pos)."""
        B, S = input_ids.shape
        if positions is None:
            from .generation import default_positions

            positions = default_positions(B, S, cache_index, kv_write_pos)
        x = self.embed_tokens[input_ids]
        aux_total = jnp.zeros((), jnp.float32)
        new_caches = [] if caches is not None else None
        for i, layer in enumerate(self.layers):
            cache = caches[i] if caches is not None else None
            x, aux, nc = layer(x, positions, cache, cache_index, kvalid,
                               kv_start, kv_write_pos)
            aux_total = aux_total + aux
            if new_caches is not None:
                new_caches.append(nc)
        logits = self.norm(x) @ self.lm_head
        if caches is not None:
            return logits, new_caches
        return logits, aux_total

    def cache_dtype(self):
        return self.embed_tokens.dtype


    def loss(self, input_ids, labels=None):
        if labels is None:
            labels = input_ids[:, 1:]
            input_ids = input_ids[:, :-1]
        logits, aux = self(input_ids)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        nll = -jnp.take_along_axis(logp, labels[..., None], axis=-1).mean()
        return nll + self.config.aux_loss_weight * aux
