"""Image transforms (ref: python/paddle/vision/transforms/transforms.py).

Operate on numpy HWC uint8/float arrays (the dataloader-worker side —
host CPU, not traced), matching the reference's numpy/PIL backend.
"""
from __future__ import annotations

import numbers
import random

import numpy as np


class BaseTransform:
    def __call__(self, img):
        return self._apply_image(img)


class Compose:
    """ref: transforms.Compose."""

    def __init__(self, transforms):
        self.transforms = list(transforms)

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


def _size_pair(size):
    if isinstance(size, numbers.Number):
        return int(size), int(size)
    return int(size[0]), int(size[1])


def _resize_np(img, h, w):
    """Bilinear resize, pure numpy (no PIL dependency)."""
    ih, iw = img.shape[:2]
    if (ih, iw) == (h, w):
        return img
    ys = (np.arange(h) + 0.5) * ih / h - 0.5
    xs = (np.arange(w) + 0.5) * iw / w - 0.5
    y0 = np.clip(np.floor(ys).astype(int), 0, ih - 1)
    x0 = np.clip(np.floor(xs).astype(int), 0, iw - 1)
    y1 = np.clip(y0 + 1, 0, ih - 1)
    x1 = np.clip(x0 + 1, 0, iw - 1)
    wy = np.clip(ys - y0, 0, 1)[:, None, None]
    wx = np.clip(xs - x0, 0, 1)[None, :, None]
    img = img.astype(np.float32)
    if img.ndim == 2:
        img = img[:, :, None]
    top = img[y0][:, x0] * (1 - wx) + img[y0][:, x1] * wx
    bot = img[y1][:, x0] * (1 - wx) + img[y1][:, x1] * wx
    out = top * (1 - wy) + bot * wy
    return out


class Resize(BaseTransform):
    def __init__(self, size, interpolation='bilinear', keys=None):
        self.size = size

    def _apply_image(self, img):
        img = np.asarray(img)
        if isinstance(self.size, numbers.Number):
            ih, iw = img.shape[:2]
            scale = self.size / min(ih, iw)
            h, w = int(round(ih * scale)), int(round(iw * scale))
        else:
            h, w = _size_pair(self.size)
        return _resize_np(img, h, w)


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode='constant', keys=None):
        self.size = _size_pair(size)
        self.padding = padding
        self.pad_if_needed = pad_if_needed
        self.fill = fill

    def _apply_image(self, img):
        img = np.asarray(img)
        h, w = self.size
        if self.padding:
            p = self.padding if not isinstance(self.padding, int) else (
                self.padding,) * 4
            img = np.pad(img, ((p[1], p[3]), (p[0], p[2])) + ((0, 0),) * (img.ndim - 2),
                         constant_values=self.fill)
        ih, iw = img.shape[:2]
        if self.pad_if_needed and (ih < h or iw < w):
            ph, pw = max(h - ih, 0), max(w - iw, 0)
            img = np.pad(img, ((0, ph), (0, pw)) + ((0, 0),) * (img.ndim - 2),
                         constant_values=self.fill)
            ih, iw = img.shape[:2]
        top = random.randint(0, ih - h)
        left = random.randint(0, iw - w)
        return img[top:top + h, left:left + w]


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        self.size = _size_pair(size)

    def _apply_image(self, img):
        img = np.asarray(img)
        h, w = self.size
        ih, iw = img.shape[:2]
        top = max((ih - h) // 2, 0)
        left = max((iw - w) // 2, 0)
        return img[top:top + h, left:left + w]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return np.asarray(img)[:, ::-1].copy()
        return np.asarray(img)


RandomFlip = RandomHorizontalFlip


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return np.asarray(img)[::-1].copy()
        return np.asarray(img)


class Normalize(BaseTransform):
    """ref: transforms.Normalize — (x - mean) / std, channel-last."""

    def __init__(self, mean=0.0, std=1.0, data_format='HWC', to_rgb=False,
                 keys=None):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def _apply_image(self, img):
        img = np.asarray(img, np.float32)
        if self.data_format == 'CHW':
            return (img - self.mean.reshape(-1, 1, 1)) / self.std.reshape(-1, 1, 1)
        return (img - self.mean) / self.std


class Transpose(BaseTransform):
    """ref: transforms.Transpose — default HWC→CHW (for NCHW nets)."""

    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = order

    def _apply_image(self, img):
        img = np.asarray(img)
        if img.ndim == 2:
            img = img[:, :, None]
        return img.transpose(self.order)


class ToTensor(BaseTransform):
    """ref: transforms.ToTensor — uint8 HWC → float CHW in [0,1].

    TPU note: keep `data_format='HWC'` for NHWC models (the default zoo
    layout here); CHW matches the reference default.
    """

    def __init__(self, data_format='CHW', keys=None):
        self.data_format = data_format

    def _apply_image(self, img):
        img = np.asarray(img)
        if img.dtype == np.uint8:
            img = img.astype(np.float32) / 255.0
        if img.ndim == 2:
            img = img[:, :, None]
        if self.data_format == 'CHW':
            img = img.transpose(2, 0, 1)
        return img


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return np.asarray(img)
        alpha = 1 + np.random.uniform(-self.value, self.value)
        return np.clip(np.asarray(img, np.float32) * alpha, 0,
                       255 if np.asarray(img).dtype == np.uint8 else np.inf)


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return np.asarray(img)
        img = np.asarray(img, np.float32)
        alpha = 1 + np.random.uniform(-self.value, self.value)
        mean = img.mean()
        return np.clip(mean + alpha * (img - mean), 0, 255)


class ColorJitter(BaseTransform):
    """Brightness/contrast jitter (saturation/hue: grayscale-safe stub)."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0, keys=None):
        self.t = Compose([BrightnessTransform(brightness),
                          ContrastTransform(contrast)])

    def _apply_image(self, img):
        return self.t(img)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode='constant', keys=None):
        self.padding = (padding,) * 4 if isinstance(padding, int) else padding
        self.fill = fill

    def _apply_image(self, img):
        img = np.asarray(img)
        p = self.padding
        return np.pad(img, ((p[1], p[3]), (p[0], p[2])) + ((0, 0),) * (img.ndim - 2),
                      constant_values=self.fill)


class RandomRotation(BaseTransform):
    """90-degree-step random rotation (arbitrary-angle needs scipy; the
    dataloader path keeps to numpy)."""

    def __init__(self, degrees, keys=None):
        self.degrees = degrees

    def _apply_image(self, img):
        k = random.randint(0, 3)
        return np.rot90(np.asarray(img), k).copy()


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        self.n = num_output_channels

    def _apply_image(self, img):
        img = np.asarray(img, np.float32)
        if img.ndim == 3 and img.shape[-1] == 3:
            g = img @ np.asarray([0.299, 0.587, 0.114], np.float32)
        else:
            g = img.reshape(img.shape[:2])
        g = g[:, :, None]
        return np.repeat(g, self.n, axis=-1) if self.n > 1 else g


# functional aliases (ref: paddle.vision.transforms.functional)
def to_tensor(img, data_format='CHW'):
    return ToTensor(data_format)(img)


def resize(img, size, interpolation='bilinear'):
    return Resize(size, interpolation)(img)


def normalize(img, mean, std, data_format='HWC', to_rgb=False):
    return Normalize(mean, std, data_format)(img)


def hflip(img):
    return np.asarray(img)[:, ::-1].copy()


def vflip(img):
    return np.asarray(img)[::-1].copy()


def center_crop(img, output_size):
    return CenterCrop(output_size)(img)


def crop(img, top, left, height, width):
    return np.asarray(img)[top:top + height, left:left + width]


def pad(img, padding, fill=0, padding_mode='constant'):
    return Pad(padding, fill, padding_mode)(img)


# ---- functional color / geometry (ref: vision/transforms/functional.py) ----
# numpy implementations over HWC uint8/float arrays — the host side of
# the input pipeline, like the reference's cv2/PIL backends.


def adjust_brightness(img, brightness_factor):
    """ref: transforms.adjust_brightness — scale toward black."""
    arr = np.asarray(img).astype(np.float32)
    out = arr * brightness_factor
    return _like(img, out)


def adjust_contrast(img, contrast_factor):
    """ref: transforms.adjust_contrast — blend with the gray mean."""
    arr = np.asarray(img).astype(np.float32)
    mean = _gray(arr).mean()
    out = (arr - mean) * contrast_factor + mean
    return _like(img, out)


def adjust_hue(img, hue_factor):
    """ref: transforms.adjust_hue — rotate hue by hue_factor in [-0.5, 0.5]
    via RGB->HSV->RGB."""
    if not -0.5 <= hue_factor <= 0.5:
        raise ValueError('hue_factor must be in [-0.5, 0.5]')
    arr = np.asarray(img).astype(np.float32)
    scale = 255.0 if arr.max() > 1.5 else 1.0
    rgb = arr / scale
    import colorsys

    # vectorized rgb->hsv
    r, g, b = rgb[..., 0], rgb[..., 1], rgb[..., 2]
    maxc = rgb.max(-1)
    minc = rgb.min(-1)
    v = maxc
    spread = maxc - minc
    s = np.where(maxc > 0, spread / np.maximum(maxc, 1e-12), 0)
    rc = (maxc - r) / np.maximum(spread, 1e-12)
    gc = (maxc - g) / np.maximum(spread, 1e-12)
    bc = (maxc - b) / np.maximum(spread, 1e-12)
    h = np.where(maxc == r, bc - gc,
                 np.where(maxc == g, 2.0 + rc - bc, 4.0 + gc - rc))
    h = np.where(spread == 0, 0.0, (h / 6.0) % 1.0)
    h = (h + hue_factor) % 1.0
    # hsv->rgb
    i = np.floor(h * 6.0)
    f = h * 6.0 - i
    p = v * (1 - s)
    q = v * (1 - s * f)
    t = v * (1 - s * (1 - f))
    i = (i.astype(np.int32) % 6)[..., None]   # broadcast over channels
    out = np.select(
        [i == 0, i == 1, i == 2, i == 3, i == 4, i == 5],
        [np.stack([v, t, p], -1), np.stack([q, v, p], -1),
         np.stack([p, v, t], -1), np.stack([p, q, v], -1),
         np.stack([t, p, v], -1), np.stack([v, p, q], -1)])
    return _like(img, out * scale)


def to_grayscale(img, num_output_channels=1):
    """ref: transforms.to_grayscale."""
    arr = np.asarray(img).astype(np.float32)
    g = _gray(arr)[..., None]
    if num_output_channels == 3:
        g = np.repeat(g, 3, axis=-1)
    return _like(img, g)


def _gray(arr):
    if arr.ndim == 2 or arr.shape[-1] == 1:
        return arr.reshape(arr.shape[:2])
    return (0.299 * arr[..., 0] + 0.587 * arr[..., 1]
            + 0.114 * arr[..., 2])


def _like(img, out):
    arr = np.asarray(img)
    if arr.dtype == np.uint8:
        return np.clip(out, 0, 255).astype(np.uint8)
    return out.astype(arr.dtype)


def _affine_grid_np(h, w, matrix):
    """Inverse-map sampling grid for a 3x3 (or 2x3) affine matrix in
    pixel coordinates (center-origin, like the reference)."""
    m = np.eye(3, dtype=np.float64)
    m[:2] = np.asarray(matrix, np.float64).reshape(2, 3)
    inv = np.linalg.inv(m)
    ys, xs = np.meshgrid(np.arange(h), np.arange(w), indexing='ij')
    cx, cy = (w - 1) / 2.0, (h - 1) / 2.0
    coords = np.stack([xs - cx, ys - cy, np.ones_like(xs)], axis=-1)
    src = coords @ inv.T
    return src[..., 0] + cx, src[..., 1] + cy


def _sample_np(arr, sx, sy, fill=0):
    h, w = arr.shape[:2]
    x0 = np.clip(np.round(sx).astype(int), 0, w - 1)
    y0 = np.clip(np.round(sy).astype(int), 0, h - 1)
    out = arr[y0, x0]
    valid = (sx >= -0.5) & (sx <= w - 0.5) & (sy >= -0.5) & (sy <= h - 0.5)
    if arr.ndim == 3:
        valid = valid[..., None]
    return np.where(valid, out, fill)


def affine(img, angle, translate, scale, shear, interpolation='nearest',
           fill=0, center=None):
    """ref: transforms.affine — rotate/translate/scale/shear about the
    center (nearest sampling; the pipeline's augmentation fidelity, not
    a resampling kernel benchmark)."""
    arr = np.asarray(img)
    a = np.deg2rad(angle)
    sx_deg, sy_deg = (tuple(shear) if isinstance(shear, (list, tuple))
                      else (shear, 0.0))
    sxr, syr = np.deg2rad(sx_deg), np.deg2rad(sy_deg)
    # forward matrix: scale * R(angle) @ Shear, then translation —
    # Shear = [[1, tan(sx)], [tan(sy), 1]] (x-shear tilts vertical
    # lines; det stays ~1, matching the reference's RSS composition)
    rot = np.array([[np.cos(a), -np.sin(a)], [np.sin(a), np.cos(a)]])
    sh = np.array([[1.0, np.tan(sxr)], [np.tan(syr), 1.0]])
    lin = scale * (rot @ sh)
    m = np.array([
        [lin[0, 0], lin[0, 1], translate[0]],
        [lin[1, 0], lin[1, 1], translate[1]],
    ])
    sx, sy = _affine_grid_np(arr.shape[0], arr.shape[1], m)
    return _like(img, _sample_np(arr.astype(np.float32), sx, sy, fill))


def rotate(img, angle, interpolation='nearest', expand=False, center=None,
           fill=0):
    """ref: transforms.rotate."""
    return affine(img, angle, (0, 0), 1.0, 0.0, interpolation, fill, center)


def perspective(img, startpoints, endpoints, interpolation='nearest',
                fill=0):
    """ref: transforms.perspective — warp by the homography mapping
    endpoints back to startpoints."""
    arr = np.asarray(img)
    a = []
    bvec = []
    for (sx_, sy_), (ex_, ey_) in zip(startpoints, endpoints):
        a.append([ex_, ey_, 1, 0, 0, 0, -sx_ * ex_, -sx_ * ey_])
        a.append([0, 0, 0, ex_, ey_, 1, -sy_ * ex_, -sy_ * ey_])
        bvec += [sx_, sy_]
    coef, *_ = np.linalg.lstsq(np.asarray(a, np.float64),
                               np.asarray(bvec, np.float64), rcond=None)
    hmat = np.append(coef, 1.0).reshape(3, 3)
    h, w = arr.shape[:2]
    ys, xs = np.meshgrid(np.arange(h), np.arange(w), indexing='ij')
    coords = np.stack([xs, ys, np.ones_like(xs)], axis=-1) @ hmat.T
    sx = coords[..., 0] / np.maximum(np.abs(coords[..., 2]), 1e-9) \
        * np.sign(coords[..., 2])
    sy = coords[..., 1] / np.maximum(np.abs(coords[..., 2]), 1e-9) \
        * np.sign(coords[..., 2])
    return _like(img, _sample_np(arr.astype(np.float32), sx, sy, fill))


def erase(img, i, j, h, w, v, inplace=False):
    """ref: transforms.erase — fill the (i, j, h, w) window with v."""
    arr = np.array(img, copy=True)
    arr[i:i + h, j:j + w] = v
    return arr


class SaturationTransform(BaseTransform):
    """ref: transforms.SaturationTransform — blend with grayscale."""

    def __init__(self, value, keys=None):
        self.value = value

    def __call__(self, img):
        f = 1.0 + np.random.uniform(-self.value, self.value)
        arr = np.asarray(img).astype(np.float32)
        g = _gray(arr)[..., None]
        return _like(img, arr * f + g * (1 - f))


class HueTransform(BaseTransform):
    """ref: transforms.HueTransform."""

    def __init__(self, value, keys=None):
        if not 0 <= value <= 0.5:
            raise ValueError('hue value must be in [0, 0.5]')
        self.value = value

    def __call__(self, img):
        return adjust_hue(img, np.random.uniform(-self.value, self.value))


class RandomResizedCrop(BaseTransform):
    """ref: transforms.RandomResizedCrop — random area/aspect crop then
    resize to `size`."""

    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation='bilinear', keys=None):
        self.size = _size_pair(size)
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def __call__(self, img):
        arr = np.asarray(img)
        h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]),
                                          np.log(self.ratio[1])))
            cw = int(round(np.sqrt(target * ar)))
            ch = int(round(np.sqrt(target / ar)))
            if 0 < cw <= w and 0 < ch <= h:
                top = np.random.randint(0, h - ch + 1)
                left = np.random.randint(0, w - cw + 1)
                patch = arr[top:top + ch, left:left + cw]
                return _resize_np(patch, *self.size)
        return _resize_np(arr, *self.size)         # fallback: full image


class RandomAffine(BaseTransform):
    """ref: transforms.RandomAffine."""

    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation='nearest', fill=0, center=None, keys=None):
        self.degrees = (-degrees, degrees) if np.isscalar(degrees) \
            else tuple(degrees)
        self.translate = translate
        self.scale = scale
        self.shear = shear
        self.interpolation = interpolation
        self.fill = fill
        self.center = center

    def _draw_shear(self):
        """scalar s -> x-shear in [-s, s]; (min, max) -> x-shear range;
        (xmin, xmax, ymin, ymax) -> both axes (reference convention)."""
        sh = self.shear
        if sh is None:
            return 0.0
        if np.isscalar(sh):
            return np.random.uniform(-sh, sh) if sh else 0.0
        sh = tuple(sh)
        if len(sh) == 2:
            return np.random.uniform(sh[0], sh[1])
        return (np.random.uniform(sh[0], sh[1]),
                np.random.uniform(sh[2], sh[3]))

    def __call__(self, img):
        arr = np.asarray(img)
        h, w = arr.shape[:2]
        angle = np.random.uniform(*self.degrees)
        tx = ty = 0.0
        if self.translate is not None:
            tx = np.random.uniform(-self.translate[0], self.translate[0]) * w
            ty = np.random.uniform(-self.translate[1], self.translate[1]) * h
        sc = np.random.uniform(*self.scale) if self.scale else 1.0
        return affine(img, angle, (tx, ty), sc, self._draw_shear(),
                      interpolation=self.interpolation, fill=self.fill,
                      center=self.center)


class RandomPerspective(BaseTransform):
    """ref: transforms.RandomPerspective."""

    def __init__(self, prob=0.5, distortion_scale=0.5,
                 interpolation='nearest', fill=0, keys=None):
        self.prob = prob
        self.distortion_scale = distortion_scale
        self.fill = fill

    def __call__(self, img):
        if np.random.random() >= self.prob:
            return np.asarray(img)
        arr = np.asarray(img)
        h, w = arr.shape[:2]
        d = self.distortion_scale
        dx, dy = int(d * w / 2), int(d * h / 2)
        start = [(0, 0), (w - 1, 0), (w - 1, h - 1), (0, h - 1)]
        jitter = lambda lo, hi: np.random.randint(lo, hi + 1)
        end = [(jitter(0, dx), jitter(0, dy)),
               (w - 1 - jitter(0, dx), jitter(0, dy)),
               (w - 1 - jitter(0, dx), h - 1 - jitter(0, dy)),
               (jitter(0, dx), h - 1 - jitter(0, dy))]
        return perspective(img, start, end, fill=self.fill)


class RandomErasing(BaseTransform):
    """ref: transforms.RandomErasing."""

    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False, keys=None):
        self.prob = prob
        self.scale = scale
        self.ratio = ratio
        self.value = value

    def __call__(self, img):
        arr = np.asarray(img)
        if np.random.random() >= self.prob:
            return arr
        h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.random.uniform(*self.ratio)
            eh = int(round(np.sqrt(target / ar)))
            ew = int(round(np.sqrt(target * ar)))
            if eh < h and ew < w:
                top = np.random.randint(0, h - eh)
                left = np.random.randint(0, w - ew)
                v = (np.random.normal(size=(eh, ew) + arr.shape[2:])
                     if self.value == 'random' else self.value)
                return erase(arr, top, left, eh, ew, v)
        return arr
