"""Image transforms (ref: python/paddle/vision/transforms/transforms.py).

Operate on numpy HWC uint8/float arrays (the dataloader-worker side —
host CPU, not traced), matching the reference's numpy/PIL backend.
"""
from __future__ import annotations

import numbers
import random

import numpy as np


class BaseTransform:
    def __call__(self, img):
        return self._apply_image(img)


class Compose:
    """ref: transforms.Compose."""

    def __init__(self, transforms):
        self.transforms = list(transforms)

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


def _size_pair(size):
    if isinstance(size, numbers.Number):
        return int(size), int(size)
    return int(size[0]), int(size[1])


def _resize_np(img, h, w):
    """Bilinear resize, pure numpy (no PIL dependency)."""
    ih, iw = img.shape[:2]
    if (ih, iw) == (h, w):
        return img
    ys = (np.arange(h) + 0.5) * ih / h - 0.5
    xs = (np.arange(w) + 0.5) * iw / w - 0.5
    y0 = np.clip(np.floor(ys).astype(int), 0, ih - 1)
    x0 = np.clip(np.floor(xs).astype(int), 0, iw - 1)
    y1 = np.clip(y0 + 1, 0, ih - 1)
    x1 = np.clip(x0 + 1, 0, iw - 1)
    wy = np.clip(ys - y0, 0, 1)[:, None, None]
    wx = np.clip(xs - x0, 0, 1)[None, :, None]
    img = img.astype(np.float32)
    if img.ndim == 2:
        img = img[:, :, None]
    top = img[y0][:, x0] * (1 - wx) + img[y0][:, x1] * wx
    bot = img[y1][:, x0] * (1 - wx) + img[y1][:, x1] * wx
    out = top * (1 - wy) + bot * wy
    return out


class Resize(BaseTransform):
    def __init__(self, size, interpolation='bilinear', keys=None):
        self.size = size

    def _apply_image(self, img):
        img = np.asarray(img)
        if isinstance(self.size, numbers.Number):
            ih, iw = img.shape[:2]
            scale = self.size / min(ih, iw)
            h, w = int(round(ih * scale)), int(round(iw * scale))
        else:
            h, w = _size_pair(self.size)
        return _resize_np(img, h, w)


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode='constant', keys=None):
        self.size = _size_pair(size)
        self.padding = padding
        self.pad_if_needed = pad_if_needed
        self.fill = fill

    def _apply_image(self, img):
        img = np.asarray(img)
        h, w = self.size
        if self.padding:
            p = self.padding if not isinstance(self.padding, int) else (
                self.padding,) * 4
            img = np.pad(img, ((p[1], p[3]), (p[0], p[2])) + ((0, 0),) * (img.ndim - 2),
                         constant_values=self.fill)
        ih, iw = img.shape[:2]
        if self.pad_if_needed and (ih < h or iw < w):
            ph, pw = max(h - ih, 0), max(w - iw, 0)
            img = np.pad(img, ((0, ph), (0, pw)) + ((0, 0),) * (img.ndim - 2),
                         constant_values=self.fill)
            ih, iw = img.shape[:2]
        top = random.randint(0, ih - h)
        left = random.randint(0, iw - w)
        return img[top:top + h, left:left + w]


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        self.size = _size_pair(size)

    def _apply_image(self, img):
        img = np.asarray(img)
        h, w = self.size
        ih, iw = img.shape[:2]
        top = max((ih - h) // 2, 0)
        left = max((iw - w) // 2, 0)
        return img[top:top + h, left:left + w]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return np.asarray(img)[:, ::-1].copy()
        return np.asarray(img)


RandomFlip = RandomHorizontalFlip


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return np.asarray(img)[::-1].copy()
        return np.asarray(img)


class Normalize(BaseTransform):
    """ref: transforms.Normalize — (x - mean) / std, channel-last."""

    def __init__(self, mean=0.0, std=1.0, data_format='HWC', to_rgb=False,
                 keys=None):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def _apply_image(self, img):
        img = np.asarray(img, np.float32)
        if self.data_format == 'CHW':
            return (img - self.mean.reshape(-1, 1, 1)) / self.std.reshape(-1, 1, 1)
        return (img - self.mean) / self.std


class Transpose(BaseTransform):
    """ref: transforms.Transpose — default HWC→CHW (for NCHW nets)."""

    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = order

    def _apply_image(self, img):
        img = np.asarray(img)
        if img.ndim == 2:
            img = img[:, :, None]
        return img.transpose(self.order)


class ToTensor(BaseTransform):
    """ref: transforms.ToTensor — uint8 HWC → float CHW in [0,1].

    TPU note: keep `data_format='HWC'` for NHWC models (the default zoo
    layout here); CHW matches the reference default.
    """

    def __init__(self, data_format='CHW', keys=None):
        self.data_format = data_format

    def _apply_image(self, img):
        img = np.asarray(img)
        if img.dtype == np.uint8:
            img = img.astype(np.float32) / 255.0
        if img.ndim == 2:
            img = img[:, :, None]
        if self.data_format == 'CHW':
            img = img.transpose(2, 0, 1)
        return img


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return np.asarray(img)
        alpha = 1 + np.random.uniform(-self.value, self.value)
        return np.clip(np.asarray(img, np.float32) * alpha, 0,
                       255 if np.asarray(img).dtype == np.uint8 else np.inf)


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return np.asarray(img)
        img = np.asarray(img, np.float32)
        alpha = 1 + np.random.uniform(-self.value, self.value)
        mean = img.mean()
        return np.clip(mean + alpha * (img - mean), 0, 255)


class ColorJitter(BaseTransform):
    """Brightness/contrast jitter (saturation/hue: grayscale-safe stub)."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0, keys=None):
        self.t = Compose([BrightnessTransform(brightness),
                          ContrastTransform(contrast)])

    def _apply_image(self, img):
        return self.t(img)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode='constant', keys=None):
        self.padding = (padding,) * 4 if isinstance(padding, int) else padding
        self.fill = fill

    def _apply_image(self, img):
        img = np.asarray(img)
        p = self.padding
        return np.pad(img, ((p[1], p[3]), (p[0], p[2])) + ((0, 0),) * (img.ndim - 2),
                      constant_values=self.fill)


class RandomRotation(BaseTransform):
    """90-degree-step random rotation (arbitrary-angle needs scipy; the
    dataloader path keeps to numpy)."""

    def __init__(self, degrees, keys=None):
        self.degrees = degrees

    def _apply_image(self, img):
        k = random.randint(0, 3)
        return np.rot90(np.asarray(img), k).copy()


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        self.n = num_output_channels

    def _apply_image(self, img):
        img = np.asarray(img, np.float32)
        if img.ndim == 3 and img.shape[-1] == 3:
            g = img @ np.asarray([0.299, 0.587, 0.114], np.float32)
        else:
            g = img.reshape(img.shape[:2])
        g = g[:, :, None]
        return np.repeat(g, self.n, axis=-1) if self.n > 1 else g


# functional aliases (ref: paddle.vision.transforms.functional)
def to_tensor(img, data_format='CHW'):
    return ToTensor(data_format)(img)


def resize(img, size, interpolation='bilinear'):
    return Resize(size, interpolation)(img)


def normalize(img, mean, std, data_format='HWC', to_rgb=False):
    return Normalize(mean, std, data_format)(img)


def hflip(img):
    return np.asarray(img)[:, ::-1].copy()


def vflip(img):
    return np.asarray(img)[::-1].copy()


def center_crop(img, output_size):
    return CenterCrop(output_size)(img)


def crop(img, top, left, height, width):
    return np.asarray(img)[top:top + height, left:left + width]


def pad(img, padding, fill=0, padding_mode='constant'):
    return Pad(padding, fill, padding_mode)(img)
