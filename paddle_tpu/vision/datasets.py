"""Built-in datasets (ref: python/paddle/vision/datasets).

Download-free: MNIST/CIFAR read standard local archive files when
`image_path`/`data_file` is given; otherwise deterministic synthetic
data with the right shapes/classes (for tests and smoke training —
this environment has no network egress).
"""
from __future__ import annotations

import gzip
import os
import pickle
import tarfile

import numpy as np

from ..io.dataset import Dataset


class FakeData(Dataset):
    """Synthetic classification images (ref: paddle.vision.datasets.FakeData
    has no direct analogue; used as the offline fallback)."""

    def __init__(self, size=256, image_shape=(32, 32, 3), num_classes=10,
                 transform=None, seed=0):
        self.size = size
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform
        self._rng = np.random.default_rng(seed)
        self._images = self._rng.integers(
            0, 256, (size,) + self.image_shape).astype(np.uint8)
        self._labels = self._rng.integers(0, num_classes, (size,)).astype(np.int64)

    def __len__(self):
        return self.size

    def __getitem__(self, i):
        img = self._images[i]
        if self.transform:
            img = self.transform(img)
        return img, self._labels[i]


class MNIST(Dataset):
    """ref: paddle.vision.datasets.MNIST — reads idx-ubyte(.gz) files from
    `image_path`/`label_path`; synthetic fallback when absent."""

    def __init__(self, image_path=None, label_path=None, mode='train',
                 transform=None, download=False, backend=None):
        self.transform = transform
        if image_path and os.path.exists(image_path):
            self.images = self._read_images(image_path)
            self.labels = self._read_labels(label_path)
        else:
            fake = FakeData(size=512 if mode == 'train' else 128,
                            image_shape=(28, 28, 1), num_classes=10,
                            seed=0 if mode == 'train' else 1)
            self.images = fake._images
            self.labels = fake._labels

    @staticmethod
    def _open(path):
        return gzip.open(path, 'rb') if path.endswith('.gz') else open(path, 'rb')

    def _read_images(self, path):
        with self._open(path) as f:
            data = f.read()
        n = int.from_bytes(data[4:8], 'big')
        rows = int.from_bytes(data[8:12], 'big')
        cols = int.from_bytes(data[12:16], 'big')
        return np.frombuffer(data, np.uint8, offset=16).reshape(n, rows, cols, 1)

    def _read_labels(self, path):
        with self._open(path) as f:
            data = f.read()
        return np.frombuffer(data, np.uint8, offset=8).astype(np.int64)

    def __len__(self):
        return len(self.images)

    def __getitem__(self, i):
        img = self.images[i]
        if self.transform:
            img = self.transform(img)
        return img, self.labels[i]


class Cifar10(Dataset):
    """ref: paddle.vision.datasets.Cifar10 — reads the python-pickle tar;
    synthetic fallback when `data_file` is absent."""

    n_classes = 10

    def __init__(self, data_file=None, mode='train', transform=None,
                 download=False, backend=None):
        self.transform = transform
        if data_file and os.path.exists(data_file):
            self.images, self.labels = self._read_tar(data_file, mode)
        else:
            fake = FakeData(size=512 if mode == 'train' else 128,
                            image_shape=(32, 32, 3),
                            num_classes=self.n_classes,
                            seed=2 if mode == 'train' else 3)
            self.images = fake._images
            self.labels = fake._labels

    def _read_tar(self, path, mode):
        images, labels = [], []
        want = 'data_batch' if mode == 'train' else 'test_batch'
        label_key = b'labels' if self.n_classes == 10 else b'fine_labels'
        with tarfile.open(path) as tar:
            for member in tar.getmembers():
                if want in member.name:
                    d = pickle.load(tar.extractfile(member), encoding='bytes')
                    images.append(d[b'data'])
                    labels.extend(d[label_key])
        images = np.concatenate(images).reshape(-1, 3, 32, 32)
        return images.transpose(0, 2, 3, 1).copy(), np.asarray(labels, np.int64)

    def __len__(self):
        return len(self.images)

    def __getitem__(self, i):
        img = self.images[i]
        if self.transform:
            img = self.transform(img)
        return img, self.labels[i]


class Cifar100(Cifar10):
    n_classes = 100


class FashionMNIST(MNIST):
    """ref: paddle.vision.datasets.FashionMNIST — same idx-ubyte format
    as MNIST, clothing classes."""


class DatasetFolder(Dataset):
    """ref: paddle.vision.datasets.DatasetFolder — class-per-subdirectory
    layout; real directory walker (PIL decodes)."""

    IMG_EXTENSIONS = ('.jpg', '.jpeg', '.png', '.bmp', '.ppm', '.webp')

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader or self._pil_loader
        exts = tuple(e.lower() for e in (extensions or self.IMG_EXTENSIONS))
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        if not classes:
            raise FileNotFoundError(f'no class directories under {root!r}')
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for base, _, files in sorted(os.walk(cdir)):
                for f in sorted(files):
                    path = os.path.join(base, f)
                    ok = (is_valid_file(path) if is_valid_file
                          else f.lower().endswith(exts))
                    if ok:
                        self.samples.append((path, self.class_to_idx[c]))
        if not self.samples:
            raise FileNotFoundError(f'no images under {root!r}')

    @staticmethod
    def _pil_loader(path):
        from PIL import Image

        with Image.open(path) as img:
            return np.asarray(img.convert('RGB'))

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, i):
        path, target = self.samples[i]
        sample = self.loader(path)
        if self.transform:
            sample = self.transform(sample)
        return sample, target


class ImageFolder(Dataset):
    """ref: paddle.vision.datasets.ImageFolder — unlabeled flat/recursive
    image directory."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.transform = transform
        self.loader = loader or DatasetFolder._pil_loader
        exts = tuple(e.lower() for e in
                     (extensions or DatasetFolder.IMG_EXTENSIONS))
        self.samples = []
        for base, _, files in sorted(os.walk(root)):
            for f in sorted(files):
                path = os.path.join(base, f)
                ok = (is_valid_file(path) if is_valid_file
                      else f.lower().endswith(exts))
                if ok:
                    self.samples.append(path)
        if not self.samples:
            raise FileNotFoundError(f'no images under {root!r}')

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, i):
        sample = self.loader(self.samples[i])
        if self.transform:
            sample = self.transform(sample)
        return [sample]


class Flowers(Dataset):
    """ref: paddle.vision.datasets.Flowers (102 classes) — reads the
    local image directory when given, synthetic fallback otherwise."""

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode='train', transform=None, download=False, backend=None):
        self.transform = transform
        if data_file and os.path.isdir(data_file):
            inner = DatasetFolder(data_file, transform=None)
            self._images = None
            self._folder = inner
            self._labels = None
        else:
            fake = FakeData(size=128 if mode == 'train' else 32,
                            image_shape=(64, 64, 3), num_classes=102,
                            seed=2 if mode == 'train' else 3)
            self._folder = None
            self._images = fake._images
            self._labels = fake._labels

    def __len__(self):
        return len(self._folder) if self._folder else len(self._images)

    def __getitem__(self, i):
        if self._folder:
            img, label = self._folder[i]
        else:
            img, label = self._images[i], self._labels[i]
        if self.transform:
            img = self.transform(img)
        return img, label


class VOC2012(Dataset):
    """ref: paddle.vision.datasets.VOC2012 (segmentation pairs) — reads
    a local VOCdevkit layout when given, synthetic (image, mask) pairs
    otherwise."""

    def __init__(self, data_file=None, mode='train', transform=None,
                 download=False, backend=None):
        self.transform = transform
        self.pairs = []
        if data_file and os.path.isdir(data_file):
            img_dir = os.path.join(data_file, 'JPEGImages')
            seg_dir = os.path.join(data_file, 'SegmentationClass')
            names = sorted(os.path.splitext(f)[0]
                           for f in os.listdir(seg_dir)) \
                if os.path.isdir(seg_dir) else []
            for n in names:
                self.pairs.append((os.path.join(img_dir, n + '.jpg'),
                                   os.path.join(seg_dir, n + '.png')))
        if not self.pairs:
            rng = np.random.default_rng(4 if mode == 'train' else 5)
            self._images = rng.integers(0, 256, (32, 64, 64, 3)).astype(np.uint8)
            self._masks = rng.integers(0, 21, (32, 64, 64)).astype(np.uint8)

    def __len__(self):
        return len(self.pairs) if self.pairs else len(self._images)

    def __getitem__(self, i):
        if self.pairs:
            from PIL import Image

            ip, mp = self.pairs[i]
            img = np.asarray(Image.open(ip).convert('RGB'))
            mask = np.asarray(Image.open(mp))
        else:
            img, mask = self._images[i], self._masks[i]
        if self.transform:
            img = self.transform(img)
        return img, mask
