"""Detection ops (ref: python/paddle/vision/ops.py — roi_align:1705,
nms:1934, deform_conv2d:766, yolo_box:277, box_coder:584, prior_box:438,
psroi_pool:1441, roi_pool:1572).

TPU-native redesign: every op is expressed as static-shape gather /
bilinear-interpolation / elementwise math so it jits onto the VPU/MXU.
The reference's CUDA kernels loop over ROIs; here each ROI's sampling
grid is computed as one batched gather, which XLA fuses. `nms` keeps the
greedy O(N²) semantics as a `fori_loop` over a boolean keep-mask —
`nms_mask` is the in-graph (static-shape) primitive; `nms` returns the
reference's variable-length index list (eager/host use).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np


# ---------------------------------------------------------------------------
# bilinear sampling helper
# ---------------------------------------------------------------------------

def _bilinear_gather(feat, ys, xs):
    """feat: (C, H, W); ys/xs: (...) float coords. Returns (..., C).

    Border rule matches the reference kernels: samples beyond one pixel
    outside the map contribute 0; samples in the one-pixel fringe clamp
    to the edge row/column (full weight on the edge pixel).
    """
    C, H, W = feat.shape
    valid = (ys >= -1.0) & (ys <= H) & (xs >= -1.0) & (xs <= W)
    ys = jnp.clip(ys, 0.0, H - 1)
    xs = jnp.clip(xs, 0.0, W - 1)
    y0 = jnp.floor(ys)
    x0 = jnp.floor(xs)
    ly = ys - y0
    lx = xs - x0
    hy = 1.0 - ly
    hx = 1.0 - lx

    def tap(yi, xi, w):
        yc = jnp.clip(yi, 0, H - 1).astype(jnp.int32)
        xc = jnp.clip(xi, 0, W - 1).astype(jnp.int32)
        vals = feat[:, yc, xc]                        # (C, ...)
        vals = jnp.moveaxis(vals, 0, -1)              # (..., C)
        return vals * w[..., None]

    out = (tap(y0, x0, hy * hx) + tap(y0, x0 + 1, hy * lx)
           + tap(y0 + 1, x0, ly * hx) + tap(y0 + 1, x0 + 1, ly * lx))
    return jnp.where(valid[..., None], out, 0.0)


def _rois_batch_index(boxes_num, num_rois):
    """Concatenated-ROIs → per-roi image index (static shapes)."""
    ends = jnp.cumsum(jnp.asarray(boxes_num, jnp.int32))
    return jnp.searchsorted(ends, jnp.arange(num_rois), side='right')


# ---------------------------------------------------------------------------
# RoI pooling family
# ---------------------------------------------------------------------------

_ADAPTIVE_MAX_SAMPLES = 8   # static cap for sampling_ratio=-1 grids


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True):
    """ref: paddle.vision.ops.roi_align (vision/ops.py:1705).

    x: (N, C, H, W); boxes: (num_rois, 4) [x1, y1, x2, y2]; boxes_num:
    (N,) rois per image. Returns (num_rois, C, ph, pw).

    sampling_ratio=-1 reproduces the reference's ADAPTIVE sampling —
    per-ROI grid of ceil(bin_h)×ceil(bin_w) taps — with static shapes:
    every ROI samples on a fixed max-size grid and the mean masks down
    to its own ceil() count (exact match while the count stays ≤ the
    cap, ``_ADAPTIVE_MAX_SAMPLES``; larger ROIs saturate at the cap,
    a bounded approximation only for ROIs wider than cap·output_size
    feature cells).
    """
    ph, pw = ((output_size, output_size) if isinstance(output_size, int)
              else tuple(output_size))
    adaptive = sampling_ratio <= 0
    s = _ADAPTIVE_MAX_SAMPLES if adaptive else sampling_ratio
    num_rois = boxes.shape[0]
    bidx = _rois_batch_index(boxes_num, num_rois)

    offset = 0.5 if aligned else 0.0
    b = boxes.astype(jnp.float32) * spatial_scale - offset
    x1, y1, x2, y2 = b[:, 0], b[:, 1], b[:, 2], b[:, 3]
    if not aligned:
        x2 = jnp.maximum(x2, x1 + 1.0)
        y2 = jnp.maximum(y2, y1 + 1.0)
    bin_h = (y2 - y1) / ph
    bin_w = (x2 - x1) / pw

    if adaptive:
        # ref vision/ops.py:1705: roi_bin_grid = ceil(roi_size / bin)
        ry = jnp.clip(jnp.ceil(bin_h), 1, s).astype(jnp.int32)  # (R,)
        rx = jnp.clip(jnp.ceil(bin_w), 1, s).astype(jnp.int32)
    else:
        ry = jnp.full((num_rois,), s, jnp.int32)
        rx = jnp.full((num_rois,), s, jnp.int32)

    j = jnp.arange(s)
    # in-bin fractions (j + .5)/ratio, masked beyond each ROI's own count
    fy = (j[None, :] + 0.5) / ry[:, None]               # (R, s)
    fx = (j[None, :] + 0.5) / rx[:, None]
    wy = (j[None, :] < ry[:, None]).astype(jnp.float32) / ry[:, None]
    wx = (j[None, :] < rx[:, None]).astype(jnp.float32) / rx[:, None]

    ys = (y1[:, None, None]
          + (jnp.arange(ph)[None, :, None] + fy[:, None, :])
          * bin_h[:, None, None])                       # (R, ph, s)
    xs = (x1[:, None, None]
          + (jnp.arange(pw)[None, :, None] + fx[:, None, :])
          * bin_w[:, None, None])                       # (R, pw, s)

    def per_roi(feat, ys_r, xs_r, wy_r, wx_r):
        yy = ys_r[:, :, None, None]                     # (ph, s, 1, 1)
        xx = xs_r[None, None, :, :]                     # (1, 1, pw, s)
        yy, xx = jnp.broadcast_arrays(yy, xx)           # (ph, s, pw, s)
        vals = _bilinear_gather(feat, yy, xx)           # (ph, s, pw, s, C)
        w = wy_r[:, None, None] * wx_r[None, None, :]   # (s, 1, s) -> bcast
        out = jnp.sum(vals * w[None, :, :, :, None], axis=(1, 3))
        return out.transpose(2, 0, 1)                   # (C, ph, pw)

    feats = x[bidx]                                     # (R, C, H, W)
    out = jax.vmap(per_roi)(feats, ys, xs, wy, wx)
    return out.astype(x.dtype)                          # fp32 weights upcast


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0):
    """ref: paddle.vision.ops.roi_pool (vision/ops.py:1572) — max pool
    over quantized bins."""
    ph, pw = ((output_size, output_size) if isinstance(output_size, int)
              else tuple(output_size))
    num_rois = boxes.shape[0]
    N, C, H, W = x.shape
    bidx = _rois_batch_index(boxes_num, num_rois)

    b = jnp.round(boxes.astype(jnp.float32) * spatial_scale)
    x1, y1 = b[:, 0], b[:, 1]
    # reference kernel uses inclusive end coords (height = end - start + 1)
    x2 = jnp.maximum(b[:, 2] + 1, x1 + 1)
    y2 = jnp.maximum(b[:, 3] + 1, y1 + 1)
    bin_h = (y2 - y1) / ph
    bin_w = (x2 - x1) / pw

    hh = jnp.arange(H, dtype=jnp.float32)
    ww = jnp.arange(W, dtype=jnp.float32)

    def per_roi(feat, x1r, y1r, bh, bw):
        # bin membership masks, computed statically over the full map
        ystart = y1r + jnp.arange(ph) * bh              # (ph,)
        yend = jnp.ceil(y1r + (jnp.arange(ph) + 1) * bh)
        ystart = jnp.floor(ystart)
        xstart = jnp.floor(x1r + jnp.arange(pw) * bw)
        xend = jnp.ceil(x1r + (jnp.arange(pw) + 1) * bw)
        ymask = ((hh[None, :] >= ystart[:, None])
                 & (hh[None, :] < jnp.maximum(yend[:, None],
                                              ystart[:, None] + 1)))
        xmask = ((ww[None, :] >= xstart[:, None])
                 & (ww[None, :] < jnp.maximum(xend[:, None],
                                              xstart[:, None] + 1)))
        m = (ymask[:, None, :, None] & xmask[None, :, None, :])  # ph,pw,H,W
        masked = jnp.where(m[None], feat[:, None, None, :, :], -jnp.inf)
        out = jnp.max(masked, axis=(-2, -1))            # (C, ph, pw)
        return jnp.where(jnp.isfinite(out), out, 0.0)

    feats = x[bidx]
    return jax.vmap(per_roi)(feats, x1, y1, bin_h, bin_w)


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0):
    """ref: paddle.vision.ops.psroi_pool (vision/ops.py:1441) —
    position-sensitive average pooling: input channels C = out_c*ph*pw,
    bin (i, j) reads its own channel group."""
    ph, pw = ((output_size, output_size) if isinstance(output_size, int)
              else tuple(output_size))
    num_rois = boxes.shape[0]
    N, C, H, W = x.shape
    if C % (ph * pw):
        raise ValueError(f'channels {C} not divisible by {ph}x{pw}')
    out_c = C // (ph * pw)
    bidx = _rois_batch_index(boxes_num, num_rois)

    b = boxes.astype(jnp.float32) * spatial_scale
    x1, y1, x2, y2 = b[:, 0], b[:, 1], b[:, 2], b[:, 3]
    bin_h = (y2 - y1) / ph
    bin_w = (x2 - x1) / pw
    hh = jnp.arange(H, dtype=jnp.float32) + 0.5
    ww = jnp.arange(W, dtype=jnp.float32) + 0.5

    def per_roi(feat, x1r, y1r, bh, bw):
        # average of pixels whose centers fall inside each bin
        ystart = y1r + jnp.arange(ph) * bh
        xstart = x1r + jnp.arange(pw) * bw
        ymask = ((hh[None, :] >= ystart[:, None])
                 & (hh[None, :] < (ystart + bh)[:, None]))  # (ph, H)
        xmask = ((ww[None, :] >= xstart[:, None])
                 & (ww[None, :] < (xstart + bw)[:, None]))  # (pw, W)
        m = (ymask[:, None, :, None] & xmask[None, :, None, :]).astype(
            feat.dtype)                                  # (ph, pw, H, W)
        fg = feat.reshape(out_c, ph, pw, H, W)           # channel groups
        num = jnp.einsum('cijhw,ijhw->cij', fg, m)
        den = jnp.maximum(jnp.sum(m, axis=(-2, -1)), 1.0)
        return num / den                                 # (out_c, ph, pw)

    feats = x[bidx]
    return jax.vmap(per_roi)(feats, x1, y1, bin_h, bin_w)


# ---------------------------------------------------------------------------
# NMS
# ---------------------------------------------------------------------------

def _iou_matrix(boxes):
    x1, y1, x2, y2 = boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3]
    area = jnp.maximum(x2 - x1, 0) * jnp.maximum(y2 - y1, 0)
    ix1 = jnp.maximum(x1[:, None], x1[None, :])
    iy1 = jnp.maximum(y1[:, None], y1[None, :])
    ix2 = jnp.minimum(x2[:, None], x2[None, :])
    iy2 = jnp.minimum(y2[:, None], y2[None, :])
    inter = (jnp.maximum(ix2 - ix1, 0) * jnp.maximum(iy2 - iy1, 0))
    union = area[:, None] + area[None, :] - inter
    return inter / jnp.maximum(union, 1e-10)


def nms_mask(boxes, iou_threshold=0.3, scores=None):
    """Static-shape greedy NMS: returns a keep-mask in score order
    applied to the ORIGINAL box indices (in-graph primitive)."""
    n = boxes.shape[0]
    if scores is None:
        order = jnp.arange(n)
    else:
        order = jnp.argsort(-scores)
    sb = boxes[order]
    iou = _iou_matrix(sb)

    def body(i, keep):
        # suppressed if any higher-scored kept box overlaps > threshold
        over = (iou[i] > iou_threshold) & keep & (jnp.arange(n) < i)
        return keep.at[i].set(~jnp.any(over))

    keep_sorted = jax.lax.fori_loop(0, n, body, jnp.ones(n, bool))
    # scatter back to original order
    keep = jnp.zeros(n, bool).at[order].set(keep_sorted)
    return keep


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """ref: paddle.vision.ops.nms (vision/ops.py:1934). Returns kept box
    indices sorted by descending score (variable length — eager/host
    API; use `nms_mask` inside jit)."""
    if category_idxs is not None:
        # class-aware: offset boxes per category so classes never overlap
        extent = jnp.max(boxes[:, 2:]) - jnp.min(boxes[:, :2]) + 1.0
        offs = (jnp.asarray(category_idxs, boxes.dtype))[:, None] * extent
        shifted = boxes + offs
    else:
        shifted = boxes
    keep = nms_mask(shifted, iou_threshold, scores)
    idx = np.nonzero(np.asarray(keep))[0]
    if scores is not None:
        s = np.asarray(scores)[idx]
        idx = idx[np.argsort(-s)]
    if top_k is not None:
        idx = idx[:top_k]
    return jnp.asarray(idx, jnp.int32)


# ---------------------------------------------------------------------------
# box utilities
# ---------------------------------------------------------------------------

def box_coder(prior_box, prior_box_var, target_box,
              code_type='encode_center_size', box_normalized=True, axis=0):
    """ref: paddle.vision.ops.box_coder (vision/ops.py:584): encode boxes
    to center-size deltas against priors, or decode deltas back."""
    pb = prior_box.astype(jnp.float32)
    norm = 0.0 if box_normalized else 1.0
    pw = pb[:, 2] - pb[:, 0] + norm
    phh = pb[:, 3] - pb[:, 1] + norm
    px = pb[:, 0] + pw * 0.5
    py = pb[:, 1] + phh * 0.5
    if isinstance(prior_box_var, (float, int)) or prior_box_var is None:
        var = jnp.ones((4,), jnp.float32)
    else:
        var = jnp.asarray(prior_box_var, jnp.float32)
        if var.ndim == 1:
            var = jnp.broadcast_to(var, (4,))

    t = target_box.astype(jnp.float32)
    if code_type == 'encode_center_size':
        # target (M, 4) corners vs priors (N, 4) → (M, N, 4) deltas
        tw = t[:, 2] - t[:, 0] + norm
        th = t[:, 3] - t[:, 1] + norm
        tx = t[:, 0] + tw * 0.5
        ty = t[:, 1] + th * 0.5
        dx = (tx[:, None] - px[None, :]) / pw[None, :]
        dy = (ty[:, None] - py[None, :]) / phh[None, :]
        dw = jnp.log(tw[:, None] / pw[None, :])
        dh = jnp.log(th[:, None] / phh[None, :])
        out = jnp.stack([dx, dy, dw, dh], -1)
        if var.ndim == 2:
            out = out / var[None]
        else:
            out = out / var
        return out
    elif code_type == 'decode_center_size':
        # t: (..., M, 4) deltas → corner boxes; `axis` says which target
        # dim the M priors line up with (ref box_coder axis semantics)
        if t.ndim == 3 and axis == 0:
            expand = lambda v: v[:, None]
        else:
            expand = lambda v: v
        pw_, phh_ = expand(pw), expand(phh)
        px_, py_ = expand(px), expand(py)
        d = t * var
        cx = d[..., 0] * pw_ + px_
        cy = d[..., 1] * phh_ + py_
        w = jnp.exp(d[..., 2]) * pw_
        h = jnp.exp(d[..., 3]) * phh_
        return jnp.stack([cx - w * 0.5, cy - h * 0.5,
                          cx + w * 0.5 - norm, cy + h * 0.5 - norm], -1)
    raise ValueError(f'unknown code_type {code_type}')


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0., 0.), offset=0.5, min_max_aspect_ratios_order=False):
    """ref: paddle.vision.ops.prior_box (vision/ops.py:438) — SSD anchor
    generation from feature-map geometry."""
    _, _, fh, fw = input.shape
    _, _, ih, iw = image.shape
    step_h = steps[1] if steps[1] > 0 else ih / fh
    step_w = steps[0] if steps[0] > 0 else iw / fw

    ars = [1.0]
    for ar in aspect_ratios:
        if not any(abs(ar - a) < 1e-6 for a in ars):
            ars.append(float(ar))
            if flip:
                ars.append(1.0 / float(ar))

    # one batched host conversion up front: float(max_sizes[i]) inside
    # the loop reads a possibly-device sequence element per iteration
    # (the TL002 host-sync-per-iteration pattern)
    mins = np.asarray(min_sizes, np.float64).reshape(-1)
    maxs = (np.asarray(max_sizes, np.float64).reshape(-1)
            if max_sizes is not None and len(max_sizes) else None)

    whs = []
    for i in range(mins.shape[0]):
        ms = float(mins[i])
        ar_whs = [(ms * math.sqrt(ar), ms / math.sqrt(ar))
                  for ar in ars if abs(ar - 1.0) > 1e-6]
        mx_wh = None
        if maxs is not None:
            mx = float(maxs[i] if i < maxs.shape[0] else maxs[-1])
            mx_wh = (math.sqrt(ms * mx), math.sqrt(ms * mx))
        whs.append((ms, ms))
        # reference ordering (phi prior_box kernel): default emits
        # [min, aspect_ratios..., max]; the flag flips to [min, max, ars]
        if min_max_aspect_ratios_order:
            if mx_wh:
                whs.append(mx_wh)
            whs.extend(ar_whs)
        else:
            whs.extend(ar_whs)
            if mx_wh:
                whs.append(mx_wh)

    cx = (jnp.arange(fw) + offset) * step_w
    cy = (jnp.arange(fh) + offset) * step_h
    cxg, cyg = jnp.meshgrid(cx, cy)                     # (fh, fw)
    wh = jnp.asarray(whs, jnp.float32)                  # (P, 2)
    x1 = (cxg[..., None] - wh[None, None, :, 0] / 2) / iw
    y1 = (cyg[..., None] - wh[None, None, :, 1] / 2) / ih
    x2 = (cxg[..., None] + wh[None, None, :, 0] / 2) / iw
    y2 = (cyg[..., None] + wh[None, None, :, 1] / 2) / ih
    boxes = jnp.stack([x1, y1, x2, y2], -1)             # (fh, fw, P, 4)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variance, jnp.float32),
                           boxes.shape)
    return boxes, var


# ---------------------------------------------------------------------------
# deformable convolution
# ---------------------------------------------------------------------------

def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None):
    """ref: paddle.vision.ops.deform_conv2d (vision/ops.py:766) — v1
    (mask=None) and v2 (modulated).

    x: (N, Cin, H, W); offset: (N, 2*dg*kh*kw, Ho, Wo) ordered (dy, dx)
    per kernel tap; weight: (Cout, Cin//groups, kh, kw);
    mask: (N, dg*kh*kw, Ho, Wo).

    Implementation: bilinear-gather the kh*kw sampling taps into an
    im2col tensor, then one grouped matmul (MXU) — the gather replaces
    the reference's per-pixel CUDA kernel.
    """
    stride = (stride, stride) if isinstance(stride, int) else tuple(stride)
    padding = (padding, padding) if isinstance(padding, int) else tuple(padding)
    dilation = (dilation, dilation) if isinstance(dilation, int) else tuple(dilation)
    N, Cin, H, W = x.shape
    Cout, Cin_g, kh, kw = weight.shape
    dg = deformable_groups
    Ho = (H + 2 * padding[0] - dilation[0] * (kh - 1) - 1) // stride[0] + 1
    Wo = (W + 2 * padding[1] - dilation[1] * (kw - 1) - 1) // stride[1] + 1

    # base sampling positions (without learned offset), in input coords
    oy = jnp.arange(Ho) * stride[0] - padding[0]
    ox = jnp.arange(Wo) * stride[1] - padding[1]
    ky = jnp.arange(kh) * dilation[0]
    kx = jnp.arange(kw) * dilation[1]
    base_y = oy[:, None, None, None] + ky[None, None, :, None]  # Ho,1,kh,1
    base_x = ox[None, :, None, None] + kx[None, None, None, :]  # 1,Wo,1,kw

    off = offset.reshape(N, dg, kh * kw, 2, Ho, Wo)
    off_y = off[:, :, :, 0].reshape(N, dg, kh, kw, Ho, Wo)
    off_x = off[:, :, :, 1].reshape(N, dg, kh, kw, Ho, Wo)
    ys = base_y.transpose(2, 3, 0, 1)[None, None] + off_y  # N,dg,kh,kw,Ho,Wo
    xs = base_x.transpose(2, 3, 0, 1)[None, None] + off_x

    if mask is not None:
        m = mask.reshape(N, dg, kh, kw, Ho, Wo)
    else:
        m = jnp.ones((N, dg, kh, kw, Ho, Wo), x.dtype)

    cpg = Cin // dg                                     # channels per dg

    def per_image(feat, ys_i, xs_i, m_i):
        # feat (Cin, H, W) → sample per deformable group
        fg = feat.reshape(dg, cpg, H, W)

        def per_dg(fgrp, yy, xx, mm):
            vals = _bilinear_gather(fgrp, yy, xx)       # kh,kw,Ho,Wo,cpg
            return vals * mm[..., None]

        vals = jax.vmap(per_dg)(fg, ys_i, xs_i, m_i)    # dg,kh,kw,Ho,Wo,cpg
        # → (Cin, kh, kw, Ho, Wo)
        return vals.transpose(0, 5, 1, 2, 3, 4).reshape(Cin, kh, kw, Ho, Wo)

    cols = jax.vmap(per_image)(x, ys, xs, m)            # N,Cin,kh,kw,Ho,Wo

    # grouped matmul: weight (Cout, Cin/g, kh, kw)
    cols = cols.reshape(N, groups, Cin // groups, kh, kw, Ho, Wo)
    wg = weight.reshape(groups, Cout // groups, Cin_g, kh, kw)
    out = jnp.einsum('ngchwyx,gochw->ngoyx', cols, wg)
    out = out.reshape(N, Cout, Ho, Wo)
    if bias is not None:
        out = out + bias.reshape(1, -1, 1, 1)
    return out


# ---------------------------------------------------------------------------
# YOLO
# ---------------------------------------------------------------------------

def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, clip_bbox=True, scale_x_y=1.0,
             iou_aware=False, iou_aware_factor=0.5):
    """ref: paddle.vision.ops.yolo_box (vision/ops.py:277) — decode a
    YOLOv3 head (N, na*(5+nc), H, W) into boxes + per-class scores."""
    N, C, H, W = x.shape
    na = len(anchors) // 2
    an = jnp.asarray(anchors, jnp.float32).reshape(na, 2)  # (w, h)

    if iou_aware:
        ioup = jax.nn.sigmoid(x[:, :na].reshape(N, na, 1, H, W))
        x = x[:, na:]
    feats = x.reshape(N, na, 5 + class_num, H, W)
    tx, ty = feats[:, :, 0], feats[:, :, 1]
    tw, th = feats[:, :, 2], feats[:, :, 3]
    obj = jax.nn.sigmoid(feats[:, :, 4:5])
    if iou_aware:
        obj = obj ** (1 - iou_aware_factor) * ioup ** iou_aware_factor
    cls = jax.nn.sigmoid(feats[:, :, 5:])

    gx = jnp.arange(W, dtype=jnp.float32)
    gy = jnp.arange(H, dtype=jnp.float32)
    alpha = scale_x_y
    beta = -0.5 * (scale_x_y - 1.0)
    cx = (jax.nn.sigmoid(tx) * alpha + beta + gx[None, None, None, :]) / W
    cy = (jax.nn.sigmoid(ty) * alpha + beta + gy[None, None, :, None]) / H
    input_w = downsample_ratio * W
    input_h = downsample_ratio * H
    bw = jnp.exp(tw) * an[None, :, 0, None, None] / input_w
    bh = jnp.exp(th) * an[None, :, 1, None, None] / input_h

    img = jnp.asarray(img_size, jnp.float32)            # (N, 2) [h, w]
    imh, imw = img[:, 0], img[:, 1]
    x1 = (cx - bw / 2) * imw[:, None, None, None]
    y1 = (cy - bh / 2) * imh[:, None, None, None]
    x2 = (cx + bw / 2) * imw[:, None, None, None]
    y2 = (cy + bh / 2) * imh[:, None, None, None]
    if clip_bbox:
        x1 = jnp.clip(x1, 0.0, imw[:, None, None, None] - 1)
        y1 = jnp.clip(y1, 0.0, imh[:, None, None, None] - 1)
        x2 = jnp.clip(x2, 0.0, imw[:, None, None, None] - 1)
        y2 = jnp.clip(y2, 0.0, imh[:, None, None, None] - 1)

    boxes = jnp.stack([x1, y1, x2, y2], 2)              # (N, na, 4, H, W)
    scores = obj * cls                                  # (N, na, nc, H, W)
    conf_ok = obj > conf_thresh                         # (N, na, 1, H, W)
    boxes = jnp.where(conf_ok[:, :, 0:1].repeat(4, 2) > 0, boxes, 0.0)
    scores = jnp.where(conf_ok, scores, 0.0)
    boxes = boxes.transpose(0, 1, 3, 4, 2).reshape(N, na * H * W, 4)
    scores = scores.transpose(0, 1, 3, 4, 2).reshape(N, na * H * W,
                                                     class_num)
    return boxes, scores


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, scale_x_y=1.0):
    """ref: paddle.vision.ops.yolo_loss (vision/ops.py:69) — YOLOv3 loss.

    x: (N, S*(5+nc), H, W) head output; gt_box: (N, B, 4) center-format
    (cx, cy, w, h) normalized to [0, 1]; gt_label: (N, B) int;
    gt_score: (N, B) mixup weights. Returns per-image loss (N,).

    Targets are built with static-shape scatters (one `.at[].add` per
    component over the (N, B) ground-truth table) instead of the
    reference's per-box CUDA loops; ignore masking compares every
    decoded prediction against every gt in one batched IoU.
    """
    N, C, H, W = x.shape
    S = len(anchor_mask)
    nc = class_num
    assert C == S * (5 + nc), (C, S, nc)
    an_all = jnp.asarray(anchors, jnp.float32).reshape(-1, 2)   # (A, 2)
    an_sel = an_all[jnp.asarray(anchor_mask)]                   # (S, 2)
    input_h = downsample_ratio * H
    input_w = downsample_ratio * W

    feats = x.reshape(N, S, 5 + nc, H, W)
    tx, ty = feats[:, :, 0], feats[:, :, 1]                     # logits
    tw, th = feats[:, :, 2], feats[:, :, 3]
    obj_logit = feats[:, :, 4]
    cls_logit = feats[:, :, 5:]                                 # (N,S,nc,H,W)

    gtb = gt_box.astype(jnp.float32)
    gx, gy, gw, gh = gtb[..., 0], gtb[..., 1], gtb[..., 2], gtb[..., 3]
    valid = (gw > 0) & (gh > 0)                                 # (N, B)
    score = (jnp.ones_like(gx) if gt_score is None
             else gt_score.astype(jnp.float32))

    # best anchor per gt by shape IoU (centered boxes)
    gw_abs, gh_abs = gw * input_w, gh * input_h
    inter = (jnp.minimum(gw_abs[..., None], an_all[None, None, :, 0])
             * jnp.minimum(gh_abs[..., None], an_all[None, None, :, 1]))
    union = (gw_abs * gh_abs)[..., None] + \
        (an_all[:, 0] * an_all[:, 1])[None, None] - inter
    best_anchor = jnp.argmax(inter / jnp.maximum(union, 1e-10), -1)  # (N,B)
    mask_arr = jnp.asarray(anchor_mask)
    on_scale = jnp.any(best_anchor[..., None] == mask_arr[None, None], -1)
    a_local = jnp.argmax(
        (best_anchor[..., None] == mask_arr[None, None]).astype(jnp.int32),
        -1)                                                     # (N, B)
    use = valid & on_scale

    gi = jnp.clip((gx * W).astype(jnp.int32), 0, W - 1)
    gj = jnp.clip((gy * H).astype(jnp.int32), 0, H - 1)
    n_idx = jnp.broadcast_to(jnp.arange(N)[:, None], gi.shape)
    # route unused gts to cell (0,0,0) with zero weight
    a_s = jnp.where(use, a_local, 0)
    gj_s = jnp.where(use, gj, 0)
    gi_s = jnp.where(use, gi, 0)
    live = jnp.where(use, 1.0, 0.0)                             # (N, B)

    def scatter(vals):
        out = jnp.zeros((N, S, H, W), jnp.float32)
        return out.at[n_idx, a_s, gj_s, gi_s].add(vals * live)

    sel_w = an_sel[a_local][..., 0] / input_w                   # (N, B)
    sel_h = an_sel[a_local][..., 1] / input_h
    t_x = gx * W - gi.astype(jnp.float32)
    t_y = gy * H - gj.astype(jnp.float32)
    t_w = jnp.log(jnp.maximum(gw / jnp.maximum(sel_w, 1e-9), 1e-9))
    t_h = jnp.log(jnp.maximum(gh / jnp.maximum(sel_h, 1e-9), 1e-9))
    box_w = 2.0 - gw * gh                                       # small-box boost

    cnt = scatter(jnp.ones_like(gx))                            # (N,S,H,W)
    safe = jnp.maximum(cnt, 1.0)                                # avg collisions
    pos = jnp.minimum(cnt, 1.0)
    tgt_x = scatter(t_x) / safe
    tgt_y = scatter(t_y) / safe
    tgt_w = scatter(t_w) / safe
    tgt_h = scatter(t_h) / safe
    # per-cell loss weight: small-box boost × mixup score
    wmap = scatter(box_w * score) / safe

    # ignore mask: decoded pred boxes vs every gt
    gxs = jnp.arange(W, dtype=jnp.float32)
    gys = jnp.arange(H, dtype=jnp.float32)
    alpha, beta = scale_x_y, -0.5 * (scale_x_y - 1.0)
    px = (jax.nn.sigmoid(tx) * alpha + beta + gxs[None, None, None, :]) / W
    py = (jax.nn.sigmoid(ty) * alpha + beta + gys[None, None, :, None]) / H
    pw = jnp.exp(tw) * an_sel[None, :, 0, None, None] / input_w
    phh = jnp.exp(th) * an_sel[None, :, 1, None, None] / input_h

    def corners(cx, cy, w, h):
        return cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2

    px1, py1, px2, py2 = corners(px, py, pw, phh)               # (N,S,H,W)
    gx1, gy1, gx2, gy2 = corners(gx, gy, gw, gh)                # (N,B)

    def bcast_pred(t):
        return t.reshape(N, S * H * W, 1)

    def bcast_gt(t):
        return t.reshape(N, 1, -1)

    iw = jnp.maximum(jnp.minimum(bcast_pred(px2), bcast_gt(gx2))
                     - jnp.maximum(bcast_pred(px1), bcast_gt(gx1)), 0)
    ih = jnp.maximum(jnp.minimum(bcast_pred(py2), bcast_gt(gy2))
                     - jnp.maximum(bcast_pred(py1), bcast_gt(gy1)), 0)
    inter_p = iw * ih
    area_p = bcast_pred(pw * phh)
    area_g = bcast_gt(gw * gh)
    iou = inter_p / jnp.maximum(area_p + area_g - inter_p, 1e-10)
    iou = jnp.where(bcast_gt(valid.astype(jnp.float32)) > 0, iou, 0.0)
    best_iou = jnp.max(iou, -1).reshape(N, S, H, W)
    noobj_mask = (best_iou <= ignore_thresh).astype(jnp.float32) * (1 - pos)

    def bce(logit, target):
        return jax.nn.softplus(logit) - logit * target

    loss_xy = (bce(tx, tgt_x) + bce(ty, tgt_y)) * wmap * pos
    loss_wh = (jnp.abs(tw - tgt_w) + jnp.abs(th - tgt_h)) * wmap * pos
    sc_map = scatter(score) / safe                    # mixup score per cell
    # mixup: the objectness TARGET is the gt score (soft label), matching
    # the reference's tobj assignment — not a loss weight
    loss_obj = bce(obj_logit, pos * sc_map) * (pos + noobj_mask)

    smooth_pos = 1.0 - 1.0 / nc if use_label_smooth else 1.0
    smooth_neg = 1.0 / nc if use_label_smooth else 0.0
    lbl = jnp.where(use, gt_label, 0).astype(jnp.int32)
    cls_hit = jnp.zeros((N, S, nc, H, W), jnp.float32)
    cls_hit = cls_hit.at[n_idx, a_s, lbl, gj_s, gi_s].add(live)
    cls_soft = jnp.where(cls_hit > 0, smooth_pos, smooth_neg)
    loss_cls = bce(cls_logit, cls_soft) * (pos * sc_map)[:, :, None]

    per_image = (loss_xy.sum((1, 2, 3)) + loss_wh.sum((1, 2, 3))
                 + loss_obj.sum((1, 2, 3)) + loss_cls.sum((1, 2, 3, 4)))
    return per_image


@functools.lru_cache(maxsize=64)
def _matrix_nms_decay_fn(score_threshold, top, use_gaussian, gaussian_sigma,
                         normalized):
    """Jitted per-class decay core for matrix_nms (reference semantics:
    matrix_nms_kernel.cc:81-152 — boxes <= score_threshold are dropped
    BEFORE suppression, decay is min-capped at 1, gaussian decay is
    exp((max²-iou²)*sigma)). Cached so repeated inference reuses the
    compilation."""
    norm_off = 0.0 if normalized else 1.0

    def _iou_off(b):
        x1, y1, x2, y2 = b[:, 0], b[:, 1], b[:, 2], b[:, 3]
        area = (jnp.maximum(x2 - x1 + norm_off, 0)
                * jnp.maximum(y2 - y1 + norm_off, 0))
        iw = (jnp.minimum(x2[:, None], x2[None, :])
              - jnp.maximum(x1[:, None], x1[None, :]) + norm_off)
        ih = (jnp.minimum(y2[:, None], y2[None, :])
              - jnp.maximum(y1[:, None], y1[None, :]) + norm_off)
        inter = jnp.maximum(iw, 0) * jnp.maximum(ih, 0)
        return inter / jnp.maximum(area[:, None] + area[None, :] - inter,
                                   1e-10)

    def decay_scores(boxes, sc):
        order = jnp.argsort(-sc)[:top]
        sb = boxes[order]
        ss = sc[order]
        valid = ss > score_threshold
        iou = _iou_off(sb)
        upper = jnp.tril(iou, -1).T        # upper[j, i] = iou(j, i), j < i
        upper = upper * valid[:, None]     # dropped boxes never suppress
        # compensate of suppressor j: its own max overlap with any
        # higher-scored (valid) box
        comp = jnp.max(upper, axis=0)
        if use_gaussian:
            decay = jnp.exp((comp[:, None] ** 2 - upper ** 2)
                            * gaussian_sigma)
        else:
            decay = (1 - upper) / jnp.maximum(1 - comp[:, None], 1e-10)
        factor = jnp.minimum(jnp.min(decay, axis=0), 1.0)
        return ss * factor, order, valid

    # tracelint: disable=TL001 - the factory itself is lru_cache'd on
    # the static config, so each config jits (and traces) exactly once
    return jax.jit(jax.vmap(decay_scores, in_axes=(None, 0)))


def matrix_nms(bboxes, scores, score_threshold, post_threshold=0.0,
               nms_top_k=400, keep_top_k=200, use_gaussian=False,
               gaussian_sigma=2.0, background_label=0, normalized=True,
               return_index=False, return_rois_num=True):
    """ref: paddle.vision.ops.matrix_nms (vision/ops.py:2358) — SOLOv2's
    parallel soft-NMS: every box's score is decayed by its overlap with
    higher-scored boxes of the same class, no sequential suppression.

    bboxes: (N, M, 4); scores: (N, C, M). Returns (out (K, 6) rows of
    [label, score, x1, y1, x2, y2], [index], rois_num) like the
    reference (eager/host API — the decay core is jittable).
    """
    N, M, _ = bboxes.shape
    C = scores.shape[1]
    top = M if nms_top_k is None or nms_top_k < 0 else min(nms_top_k, M)
    # module-level jitted factory: compilation is cached across calls
    # (keyed on the static decay parameters + shapes)
    decay_all = _matrix_nms_decay_fn(
        float(score_threshold), int(top), bool(use_gaussian),
        float(gaussian_sigma), bool(normalized))

    # device pass first (no syncs), then ONE batched host transfer for
    # the boxes and every image's decay results — the per-iteration
    # np.asarray(bboxes[n]) was a host sync per image (TL002)
    decayed = [decay_all(bboxes[n], scores[n]) for n in range(N)]
    bboxes_h, decayed_h = jax.device_get((bboxes, decayed))

    outs, idxs, counts = [], [], []
    for n in range(N):
        rows = []
        boxes_np = bboxes_h[n]
        dec_a, order_a, valid_a = decayed_h[n]
        for c in range(C):
            if c == background_label:
                continue
            dec_np, order_np = dec_a[c], order_a[c]
            keep = (dec_np > post_threshold) & valid_a[c]
            for rank in np.nonzero(keep)[0]:
                i = int(order_np[rank])
                rows.append((float(c), float(dec_np[rank]),
                             *boxes_np[i].tolist(), i))
        rows.sort(key=lambda r: -r[1])
        if keep_top_k is not None and keep_top_k >= 0:
            rows = rows[:keep_top_k]
        counts.append(len(rows))
        for r in rows:
            outs.append(r[:6])
            idxs.append(n * M + r[6])
    out = jnp.asarray(np.asarray(outs, np.float32).reshape(-1, 6))
    index = jnp.asarray(np.asarray(idxs, np.int32).reshape(-1, 1))
    rois_num = jnp.asarray(counts, jnp.int32)
    result = [out]
    if return_index:
        result.append(index)
    if return_rois_num:
        result.append(rois_num)
    return tuple(result) if len(result) > 1 else out


# ---------------------------------------------------------------------------
# Layer wrappers (ref: vision/ops.py classes)
# ---------------------------------------------------------------------------

from ..nn.layer.base import Layer, Parameter  # noqa: E402
from ..nn import initializer as _I  # noqa: E402


class RoIAlign(Layer):
    """ref: paddle.vision.ops.RoIAlign (vision/ops.py:1826)."""

    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num, aligned=True):
        return roi_align(x, boxes, boxes_num, self.output_size,
                         self.spatial_scale, aligned=aligned)


class RoIPool(Layer):
    """ref: paddle.vision.ops.RoIPool (vision/ops.py:1657)."""

    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self.output_size,
                        self.spatial_scale)


class PSRoIPool(Layer):
    """ref: paddle.vision.ops.PSRoIPool (vision/ops.py:1523)."""

    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self.output_size,
                          self.spatial_scale)


class DeformConv2D(Layer):
    """ref: paddle.vision.ops.DeformConv2D (vision/ops.py:973)."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        kh, kw = ((kernel_size, kernel_size)
                  if isinstance(kernel_size, int) else tuple(kernel_size))
        self.stride, self.padding, self.dilation = stride, padding, dilation
        self.deformable_groups, self.groups = deformable_groups, groups
        fan_in = in_channels // groups * kh * kw
        bound = 1.0 / math.sqrt(fan_in)
        init = _I.Uniform(-bound, bound)
        self.weight = Parameter(
            init((out_channels, in_channels // groups, kh, kw), 'float32'))
        self.bias = (None if bias_attr is False
                     else Parameter(init((out_channels,), 'float32')))

    def forward(self, x, offset, mask=None):
        return deform_conv2d(x, offset, self.weight, self.bias, self.stride,
                             self.padding, self.dilation,
                             self.deformable_groups, self.groups, mask)


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False, rois_num=None):
    """Assign RoIs to FPN levels by scale: level = floor(refer_level +
    log2(sqrt(area) / refer_scale)) clipped to [min, max]
    (ref: vision/ops.py::distribute_fpn_proposals).

    Host-side grouping (eager): per-level RoI counts are data-dependent,
    which no static-shape program can express — the reference kernel is
    likewise a host-sequenced scatter. Returns
    (multi_rois, restore_ind, rois_num_per_level).
    """
    import numpy as np

    rois = np.asarray(fpn_rois, np.float32)
    off = 1.0 if pixel_offset else 0.0
    w = rois[:, 2] - rois[:, 0] + off
    h = rois[:, 3] - rois[:, 1] + off
    scale = np.sqrt(np.clip(w * h, 0, None))
    level = np.floor(refer_level + np.log2(scale / refer_scale + 1e-8))
    level = np.clip(level, min_level, max_level).astype(np.int64)

    multi_rois, per_level_idx = [], []
    for lv in range(min_level, max_level + 1):
        idx = np.nonzero(level == lv)[0]
        per_level_idx.append(idx)
        multi_rois.append(jnp.asarray(rois[idx]))
    order = np.concatenate(per_level_idx) if per_level_idx else np.zeros(0)
    restore = np.empty_like(order)
    restore[order.astype(np.int64)] = np.arange(len(order))
    rois_num_per_level = None
    if rois_num is not None:
        rois_num_per_level = [jnp.asarray(np.asarray([len(i)]))
                              for i in per_level_idx]
    return multi_rois, jnp.asarray(restore.astype(np.int32)), rois_num_per_level


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False, return_rois_num=False):
    """RPN proposal generation (ref: vision/ops.py::generate_proposals):
    decode anchor deltas, clip to the image, drop tiny boxes, NMS.

    Shapes: scores (N, A, H, W), bbox_deltas (N, 4*A, H, W),
    anchors (H, W, A, 4), variances like anchors. Eager host-side
    pipeline (proposal counts are data-dependent), one image at a time,
    matching the reference kernel's per-image loop.
    """
    import numpy as np

    scores = np.asarray(scores, np.float32)
    deltas = np.asarray(bbox_deltas, np.float32)
    img_size = np.asarray(img_size, np.float32)
    anc = np.asarray(anchors, np.float32).reshape(-1, 4)
    var = np.asarray(variances, np.float32).reshape(-1, 4)
    n, a, hgt, wid = scores.shape
    offset = 1.0 if pixel_offset else 0.0

    all_rois, all_scores, rois_num = [], [], []
    for i in range(n):
        sc = scores[i].transpose(1, 2, 0).reshape(-1)          # (H*W*A,)
        dl = deltas[i].reshape(a, 4, hgt, wid).transpose(2, 3, 0, 1)
        dl = dl.reshape(-1, 4)
        keep_n = min(pre_nms_top_n, len(sc))
        top = np.argsort(-sc)[:keep_n]
        sc, dl_t, anc_t, var_t = sc[top], dl[top], anc[top], var[top]
        # decode center-size deltas
        aw = anc_t[:, 2] - anc_t[:, 0] + offset
        ah = anc_t[:, 3] - anc_t[:, 1] + offset
        ax = anc_t[:, 0] + aw * 0.5
        ay = anc_t[:, 1] + ah * 0.5
        cx = var_t[:, 0] * dl_t[:, 0] * aw + ax
        cy = var_t[:, 1] * dl_t[:, 1] * ah + ay
        bw = np.exp(np.minimum(var_t[:, 2] * dl_t[:, 2], 10.0)) * aw
        bh = np.exp(np.minimum(var_t[:, 3] * dl_t[:, 3], 10.0)) * ah
        boxes = np.stack([cx - bw / 2, cy - bh / 2,
                          cx + bw / 2 - offset, cy + bh / 2 - offset], axis=1)
        # clip to image
        ih, iw = img_size[i, 0], img_size[i, 1]
        boxes[:, 0::2] = np.clip(boxes[:, 0::2], 0, iw - offset)
        boxes[:, 1::2] = np.clip(boxes[:, 1::2], 0, ih - offset)
        # remove small
        bw2 = boxes[:, 2] - boxes[:, 0] + offset
        bh2 = boxes[:, 3] - boxes[:, 1] + offset
        keep = np.nonzero((bw2 >= min_size) & (bh2 >= min_size))[0]
        boxes, sc = boxes[keep], sc[keep]
        # nms (reuse the static-shape masked kernel)
        if len(boxes):
            kept = np.asarray(nms(jnp.asarray(boxes), nms_thresh,
                                  scores=jnp.asarray(sc),
                                  top_k=post_nms_top_n))
            boxes, sc = boxes[kept], sc[kept]
        all_rois.append(jnp.asarray(boxes))
        all_scores.append(jnp.asarray(sc))
        rois_num.append(len(boxes))
    rois = jnp.concatenate(all_rois) if all_rois else jnp.zeros((0, 4))
    out_scores = (jnp.concatenate(all_scores) if all_scores
                  else jnp.zeros((0,)))
    if return_rois_num:
        return rois, out_scores, jnp.asarray(rois_num)
    return rois, out_scores


def read_file(filename):
    """ref: paddle.vision.ops.read_file — raw bytes as a uint8 tensor."""
    import numpy as np

    with open(filename, 'rb') as f:
        data = f.read()
    return jnp.asarray(np.frombuffer(data, np.uint8))


def decode_jpeg(x, mode='unchanged'):
    """ref: paddle.vision.ops.decode_jpeg (the reference uses nvjpeg;
    here PIL decodes on the host — the TPU has no JPEG engine)."""
    import io

    import numpy as np
    from PIL import Image

    img = Image.open(io.BytesIO(np.asarray(x).tobytes()))
    if mode == 'gray':
        img = img.convert('L')
    elif mode == 'rgb':
        img = img.convert('RGB')
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None]                      # (1, H, W)
    else:
        arr = arr.transpose(2, 0, 1)         # (C, H, W)
    return jnp.asarray(arr)
