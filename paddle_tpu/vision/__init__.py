"""paddle_tpu.vision (ref: python/paddle/vision)."""
from . import datasets  # noqa: F401
from . import ops  # noqa: F401
from . import transforms  # noqa: F401
from .. import models  # noqa: F401  (paddle.vision.models alias)
from .ops import (DeformConv2D, PSRoIPool, RoIAlign, RoIPool,  # noqa: F401
                  box_coder, deform_conv2d, matrix_nms, nms, nms_mask,
                  prior_box, psroi_pool, roi_align, roi_pool, yolo_box,
                  yolo_loss)
