"""paddle_tpu.vision (ref: python/paddle/vision)."""
from . import datasets  # noqa: F401
from . import ops  # noqa: F401
from . import transforms  # noqa: F401
from .. import models  # noqa: F401  (paddle.vision.models alias)
from .ops import (DeformConv2D, PSRoIPool, RoIAlign, RoIPool,  # noqa: F401
                  box_coder, deform_conv2d, matrix_nms, nms, nms_mask,
                  prior_box, psroi_pool, roi_align, roi_pool, yolo_box,
                  yolo_loss)


_image_backend = ['pil']


def set_image_backend(backend):
    """ref: paddle.vision.set_image_backend ('pil' or 'cv2')."""
    if backend not in ('pil', 'cv2'):
        raise ValueError(f"backend must be 'pil' or 'cv2', got {backend}")
    _image_backend[0] = backend


def get_image_backend():
    return _image_backend[0]


def image_load(path, backend=None):
    """ref: paddle.vision.image_load — PIL image (or HWC ndarray for
    cv2 backend; cv2 is not shipped, numpy stands in)."""
    backend = backend or _image_backend[0]
    from PIL import Image

    img = Image.open(path)
    if backend == 'cv2':
        import numpy as np

        # cv2.imread always yields 3-channel BGR, even for gray files
        return np.asarray(img.convert('RGB'))[:, :, ::-1]
    return img
