"""Regularizers (ref: python/paddle/regularizer.py).

Paddle attaches L1Decay/L2Decay to params or optimizers; here they are
consumed by `Optimizer` (weight_decay accepts a float — coupled L2 — or
one of these objects; AdamW applies decoupled decay).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


class WeightDecayRegularizer:
    coeff: float = 0.0

    def grad_term(self, p):  # pragma: no cover - abstract
        raise NotImplementedError

    def __call__(self, grads, params):
        """Add the regularisation gradient term (coupled style)."""
        return jax.tree.map(
            lambda g, p: g + self.grad_term(p) if g is not None else None,
            grads, params)


class L2Decay(WeightDecayRegularizer):
    """ref: paddle.regularizer.L2Decay — grad += coeff * p."""

    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)

    def grad_term(self, p):
        return self.coeff * p


class L1Decay(WeightDecayRegularizer):
    """ref: paddle.regularizer.L1Decay — grad += coeff * sign(p)."""

    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)

    def grad_term(self, p):
        return self.coeff * jnp.sign(p)
