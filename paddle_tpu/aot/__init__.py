"""paddle_tpu.aot — AOT engine artifacts: warmup, export, and
zero-compile cold start.

A fresh serving replica used to pay trace + XLA compile for every
prefill bucket, decode geometry, and speculative window at first
traffic (minutes of warmup — the autoscaling killer, ROADMAP item 4).
This subsystem composes three pieces that already existed separately —
the engines' CompileCache key registries, `jit.save`'s jax.export
serialization, and sysconfig's persistent XLA executable cache — into
one artifact flow:

    # build machine (CI, or the first replica):
    srv = ServingEngine(model, **cfg)
    art = aot.build(srv, '/models/llama-serve.aot')

    # every later replica, before the first request:
    srv = ServingEngine(model, **cfg)
    srv.warmup(artifact='/models/llama-serve.aot')
    # first token is now ONE dispatch: zero traces, zero compiles
    # (bench.py's gate_cold_start proves the >=10x cold-start win)

`GeometrySet` (aot.geometry) enumerates every jit geometry an engine
config will dispatch; `build`/`EngineArtifact`/`warm_attach`
(aot.artifact) persist and re-attach the compiled executables with a
fingerprint-checked manifest. See docs/aot_warmup.md.
"""
from __future__ import annotations

from .artifact import (  # noqa: F401
    MANIFEST_NAME, ArtifactMismatch, EngineArtifact, build, config_hash,
    fingerprint, warm_attach,
)
from .geometry import (  # noqa: F401
    Geometry, GeometrySet, for_decode_engine, for_engine,
    for_serving_engine, for_train_engine,
)

__all__ = [
    'ArtifactMismatch', 'EngineArtifact', 'build', 'warm_attach',
    'fingerprint', 'config_hash', 'MANIFEST_NAME',
    'Geometry', 'GeometrySet', 'for_engine', 'for_decode_engine',
    'for_serving_engine', 'for_train_engine',
]
