"""Geometry enumeration — the declarative half of the AOT subsystem.

A *geometry* is one (jitted function, traced shapes, static config)
combination an engine will dispatch: a prefill bucket at a batch width,
a decode window over the paged pool, a speculative window, a train
micro-batch scan. The engines already key their CompileCache registries
on exactly these combinations; this module enumerates them STATICALLY
from an engine's config, so a build machine can compile every one of
them before the first request exists (aot.build) and a fresh replica
can warm-attach the results (engine.warmup).

The contract tests/test_aot.py pins: for a declared workload, the
GeometrySet's `registry_keys(engine)` equal EXACTLY the keys the live
engine notes while serving that workload — no missing (a first request
would compile) and no extra (the artifact would carry dead executables
and the build would overclaim coverage).

Every Geometry is a dict of primitives (it round-trips through the
artifact manifest's JSON); see docs/aot_warmup.md.
"""
from __future__ import annotations

import re

from ..inference.engine import bucket_length

_SAFE = re.compile(r'[^A-Za-z0-9_.]')


class Geometry:
    """One compilable dispatch shape: `kind` + a params dict of
    primitives. Kinds and their params:

      decode           batch, prompt_len, max_new_tokens
      decode_spec      batch, prompt_len, max_new_tokens, num_draft_tokens
      serve_step       window, bucket
      serve_window     window
      serve_prefill    bucket
      serve_chunk_step window, chunk, bucket (chunked/continuation
                       prefill fused with the decode window: `chunk`
                       buckets the per-step token width, `bucket` the
                       contiguous temp-cache length — the largest end
                       position in the batch)
      serve_spec_step  spec, bucket, ctx (speculative propose/verify
                       window fused with an admission prefill: `spec`
                       is the draft window k, `bucket` the admission
                       prefill bucket, `ctx` the verify's gathered
                       temp-cache length — bucket(max live context +
                       k + 1))
      serve_spec_window spec, ctx (a pure speculative window, no
                       admissions this step)
      serve_export     ctx (the KV-migration gather behind
                       `export_kv`: `ctx` buckets the exported
                       kv length — bucket(context_len - 1))
      serve_import     ctx (the KV-migration scatter behind
                       `import_kv`, same `ctx` bucketing — a decode
                       pool warms these instead of admission kinds)
      train_step       input_shapes, input_dtypes, label_shapes,
                       label_dtypes (shape entries are tuples/lists of int)
    """

    __slots__ = ('kind', 'params')

    def __init__(self, kind, **params):
        self.kind = str(kind)
        self.params = params

    def to_dict(self):
        return {'kind': self.kind, **self.params}

    @classmethod
    def from_dict(cls, d):
        d = dict(d)
        kind = d.pop('kind')
        # JSON turns tuples into lists; normalise shape-like params back
        # so keys computed from a loaded manifest equal freshly
        # enumerated ones
        for k, v in d.items():
            if isinstance(v, list):
                d[k] = tuple(tuple(x) if isinstance(x, list) else x
                             for x in v)
        return cls(kind, **d)

    def label(self):
        """Filesystem-safe short name (stablehlo export file stems,
        warmup report lines)."""

        def flat(v):
            if isinstance(v, (list, tuple)):
                return 'x'.join(flat(x) for x in v)
            return _SAFE.sub('', str(v))

        bits = [self.kind]
        for k in sorted(self.params):
            bits.append(f'{k[0]}{flat(self.params[k])}')
        return '-'.join(bits)

    def _key(self):
        def freeze(v):
            if isinstance(v, (list, tuple)):
                return tuple(freeze(x) for x in v)
            return v

        return (self.kind,
                tuple(sorted((k, freeze(v))
                             for k, v in self.params.items())))

    def __eq__(self, other):
        return (isinstance(other, Geometry)
                and self._key() == other._key())

    def __hash__(self):
        return hash(self._key())

    def __repr__(self):
        return f'Geometry({self.label()})'


class GeometrySet:
    """An ordered, de-duplicated collection of Geometry entries plus
    the key-derivation against a live engine."""

    def __init__(self, entries=()):
        self.entries = []
        seen = set()
        for g in entries:
            if g not in seen:
                seen.add(g)
                self.entries.append(g)

    def __iter__(self):
        return iter(self.entries)

    def __len__(self):
        return len(self.entries)

    def to_manifest(self):
        return [g.to_dict() for g in self.entries]

    @classmethod
    def from_manifest(cls, dicts):
        return cls(Geometry.from_dict(d) for d in dicts)

    def registry_keys(self, engine):
        """The exact CompileCache keys the live `engine` notes when it
        dispatches these geometries, deduped in enumeration order.
        (Multiple geometries can share one registry key: a bucketed
        generate records one key per (B, bucket) while dispatching two
        jitted functions.)"""
        keys, seen = [], set()
        for g in self.entries:
            k = _registry_key(engine, g)
            if k not in seen:
                seen.add(k)
                keys.append(k)
        return keys


def _registry_key(engine, g):
    p = g.params
    if g.kind == 'decode':
        return engine.registry_key_generate(
            p['batch'], p['prompt_len'], p['max_new_tokens'])
    if g.kind == 'decode_spec':
        return engine.registry_key_speculative(
            p['batch'], p['prompt_len'], p['max_new_tokens'],
            p['num_draft_tokens'])
    if g.kind == 'serve_step':
        return engine.registry_key('serve_step', p['window'], p['bucket'])
    if g.kind == 'serve_window':
        return engine.registry_key('serve_window', p['window'])
    if g.kind == 'serve_prefill':
        return engine.registry_key('serve_prefill', p['bucket'])
    if g.kind == 'serve_chunk_step':
        return engine.registry_key('serve_chunk_step', p['window'],
                                   p['chunk'], p['bucket'])
    if g.kind == 'serve_spec_step':
        return engine.registry_key('serve_spec_step', p['spec'],
                                   p['bucket'], p['ctx'])
    if g.kind == 'serve_spec_window':
        return engine.registry_key('serve_spec_window', p['spec'],
                                   p['ctx'])
    if g.kind == 'serve_export':
        return engine.registry_key('serve_export', p['ctx'])
    if g.kind == 'serve_import':
        return engine.registry_key('serve_import', p['ctx'])
    if g.kind == 'train_step':
        return engine.registry_key(p['input_shapes'][0],
                                   p['input_dtypes'][0])
    raise ValueError(f'unknown geometry kind {g.kind!r}')


# ---------------------------------------------------------------------------
# Per-engine enumeration
# ---------------------------------------------------------------------------

def for_decode_engine(engine, prompt_lens, batch_sizes=(1,),
                      max_new_tokens=None, spec_draft_tokens=None,
                      spec_batch_sizes=(1,)):
    """Geometries a DecodeEngine serves for the declared workload.

    `prompt_lens` — iterable of prompt lengths the deployment admits
    (only their BUCKETS matter for `generate`: one geometry per
    (batch, bucket) pair). `max_new_tokens` — per-call budgets; None
    means the engine default. `spec_draft_tokens` — iterable of k
    values to additionally enumerate speculative windows for (the
    speculative path is NOT bucketed, so every distinct prompt length
    is its own geometry there)."""
    entries = []
    mnts = (max_new_tokens if isinstance(max_new_tokens, (list, tuple))
            else [max_new_tokens])
    for B in batch_sizes:
        for mnt in mnts:
            budget = engine.max_new_tokens if mnt is None else int(mnt)
            seen_buckets = set()
            for L in prompt_lens:
                b = bucket_length(int(L), engine.buckets)
                # one representative prompt length per (bucket,
                # exactness) pair: any padded length in a bucket shares
                # one compilation (left-pad + traced real_len), but an
                # EXACT-length prompt takes the unpadded prefill and
                # the padded=False decode loop — a distinct trace under
                # the same registry key, so both variants must be
                # warmable when the workload declares both
                variant = (b, int(L) == b)
                if variant in seen_buckets:
                    continue
                seen_buckets.add(variant)
                entries.append(Geometry(
                    'decode', batch=int(B), prompt_len=int(L),
                    max_new_tokens=budget))
    if spec_draft_tokens:
        # the speculative path honors the same per-call budgets as
        # generate (and is NOT bucketed: the exact prompt length is
        # part of its cache shape, so every declared length enumerates)
        for B in spec_batch_sizes:
            for k in spec_draft_tokens:
                for mnt in mnts:
                    budget = (engine.max_new_tokens if mnt is None
                              else int(mnt))
                    for L in prompt_lens:
                        entries.append(Geometry(
                            'decode_spec', batch=int(B),
                            prompt_len=int(L), max_new_tokens=budget,
                            num_draft_tokens=int(k)))
    return GeometrySet(entries)


def for_serving_engine(engine, prompt_lens=None,
                       include_standalone_prefill=True,
                       max_new_tokens=None, migration=False):
    """Geometries a ServingEngine dispatches: one fused admit+decode
    step per admission bucket, the pure decode window, (when
    `include_standalone_prefill`) the standalone prefill each bucket
    can additionally hit on a multi-bucket admission step, and — for
    engines with `prefill_chunk` and/or `prefix_cache` configured —
    the fused chunk-continuation step per (chunk bucket, context
    bucket) pair.

    `prompt_lens` bounds the admission context lengths (prompt +
    resumed prefix) the deployment will see; default is full coverage
    of 1..max_context_len — the safe choice for an artifact, since a
    preempted request re-prefills at prompt+prefix length.

    With chunking enabled, contexts longer than `prefill_chunk` ride
    the chunk path, so the MONOLITHIC serve_step/serve_prefill buckets
    clamp to lengths <= prefill_chunk; the chunk pairs cover every
    (per-step token width, end position) bucket combination a chunked
    or prefix-hit-continuation admission can dispatch (chunk widths
    cap at bucket(prefill_chunk); with prefix caching alone the width
    is the unshared suffix, at most max_context_len - block_size
    since a hit is at least one full page).

    Disaggregated roles (engine.phase_role) reshape the set:

      'decode'   — an import-fed decode pool dispatches NO admission
                   kinds at all: only the `serve_import` scatter, the
                   one-token continuation chunk that recomputes the
                   boundary position, and the pure decode window.
                   `prompt_lens` then declares the CONTEXT lengths at
                   import (prompt + tokens generated on the prefill
                   side). Assumes no preemption re-admissions — size
                   the pool for the declared workload.
      'prefill'  — the monolithic set plus the `serve_export` gather
                   per reachable handoff context bucket (the request
                   hands off holding 1..decode_window tokens).
      'monolithic' (default) — unchanged; pass `migration=True` to
                   additionally enumerate export+import at the
                   declared buckets (a monolithic engine exercising
                   round-trip migration, e.g. the bit-equality
                   tests)."""
    W = engine.decode_window
    if prompt_lens is None:
        prompt_lens = range(1, engine.max_context_len + 1)
    prompt_lens = [int(L) for L in prompt_lens]
    chunk = getattr(engine, 'prefill_chunk', None)
    prefix = bool(getattr(engine, 'prefix_cache', False))
    spec = getattr(engine, 'spec_window', None)
    role = getattr(engine, 'phase_role', 'monolithic')
    if role == 'decode':
        return _for_decode_pool(engine, prompt_lens, W, spec,
                                max_new_tokens)
    mono_lens = (prompt_lens if chunk is None
                 else [L for L in prompt_lens if L <= chunk])
    buckets = []
    for L in mono_lens:
        b = bucket_length(L, engine.buckets)
        if b not in buckets:
            buckets.append(b)
    if spec is None:
        entries = [Geometry('serve_step', window=W, bucket=b)
                   for b in buckets]
        entries.append(Geometry('serve_window', window=W))
    else:
        # a speculative engine dispatches serve_spec_step /
        # serve_spec_window on every non-chunk iteration — the plain
        # serve_step/serve_window executables are never reached, so
        # enumerating them would stamp dead executables into the
        # artifact. The verify's gathered temp-cache length is
        # bucket(max live context + k + 1): live contexts M run from
        # the smallest declared admission length up to the largest
        # context a still-decoding row can hold — min(max prompt +
        # max_new_tokens, max_context_len) - 1 (a live row always has
        # >= 1 token of budget left), honoring per-call
        # `max_new_tokens` overrides when declared.
        k = int(spec)
        mnts = (max_new_tokens if isinstance(max_new_tokens,
                                             (list, tuple))
                else [max_new_tokens])
        budget = max(engine.max_new_tokens if m is None else int(m)
                     for m in mnts)
        m_lo = min(prompt_lens)
        m_hi = min(max(prompt_lens) + budget,
                   engine.max_context_len) - 1
        ladder, v = [], m_lo + k + 1
        while v <= m_hi + k + 1:
            b = bucket_length(v, engine.buckets)
            ladder.append(b)
            v = b + 1
        entries = []
        # fused admission + spec window: the verify bucket can never
        # sit below the smallest context this admission bucket can
        # contribute (the admitted row is live, so max-live-ctx >= its
        # own length); every ladder entry at or above that floor is
        # reachable by batching the admission with a longer-context
        # in-flight row
        for Sb in buckets:
            lmin = min(L for L in mono_lens
                       if bucket_length(L, engine.buckets) == Sb)
            floor = bucket_length(lmin + k + 1, engine.buckets)
            entries.extend(
                Geometry('serve_spec_step', spec=k, bucket=Sb, ctx=c)
                for c in ladder if c >= floor)
        entries.extend(Geometry('serve_spec_window', spec=k, ctx=c)
                       for c in ladder)
    if include_standalone_prefill:
        entries.extend(Geometry('serve_prefill', bucket=b)
                       for b in buckets)
    if (chunk is not None or prefix) and prompt_lens:
        max_end = max(prompt_lens)
        # the bucket ladder every chunk END can land on (intermediate
        # chunk ends cover 1..max_end even when prompt_lens is sparse)
        ladder, L = [], 1
        while L <= max_end:
            b = bucket_length(L, engine.buckets)
            ladder.append(b)
            L = b + 1
        if chunk is not None:
            max_take = min(chunk, max_end)
        else:
            max_take = max(1, max_end - engine.block_size)
        cb_max = bucket_length(max_take, engine.buckets)
        # equal-bucket pairs are only reachable through a start-0
        # chunked admission's FIRST chunk, whose take is exactly
        # prefill_chunk (so cb == sb == bucket(prefill_chunk), and
        # only when some declared context exceeds the chunk at all):
        # later chunks and tails sit at end > chunk (sb > cb), and a
        # prefix-hit continuation passes the profitability guard only
        # when bucket(take) < bucket(end) — any other equal pair would
        # be a dead executable in the artifact
        entries.extend(
            Geometry('serve_chunk_step', window=W, chunk=cb, bucket=sb)
            for cb in ladder if cb <= cb_max
            for sb in ladder
            if cb < sb or (chunk is not None and max_end > chunk
                           and cb == sb == cb_max))
    if role == 'prefill' or migration:
        # the handoff export: a prefill-role request hands off holding
        # g in 1..W generated tokens, so the exported kv length is
        # L + g - 1 — one serve_export per reachable bucket. The
        # migration=True monolithic variant covers the same range (an
        # export mid-decode reaches higher contexts; declare them via
        # prompt_lens).
        cxs = []
        for L in prompt_lens:
            for g in range(1, W + 1):
                n = L + g - 1
                if n < 1 or n + 1 > engine.max_context_len:
                    continue
                c = bucket_length(n, engine.buckets)
                if c not in cxs:
                    cxs.append(c)
        entries.extend(Geometry('serve_export', ctx=c) for c in cxs)
        if migration:
            entries.extend(Geometry('serve_import', ctx=c) for c in cxs)
    return GeometrySet(entries)


def _for_decode_pool(engine, context_lens, W, spec, max_new_tokens):
    """The decode-role set: import scatter + one-token continuation
    chunk per import-context bucket, plus the pure window (speculative
    engines: the spec window over its reachable verify ladder). No
    admission kinds — an import-fed pool never dispatches them, and
    enumerating them would stamp dead executables into the artifact
    (the no-extra half of the exactness contract)."""
    cb1 = bucket_length(1, engine.buckets)
    entries = []
    sbs, cxs = [], []
    for L in context_lens:
        if L < 2:
            continue               # an import carries kv_len >= 1
        sb = bucket_length(L, engine.buckets)
        if sb not in sbs:
            sbs.append(sb)
        c = bucket_length(L - 1, engine.buckets)
        if c not in cxs:
            cxs.append(c)
    entries.extend(Geometry('serve_import', ctx=c) for c in cxs)
    entries.extend(
        Geometry('serve_chunk_step', window=W, chunk=cb1, bucket=sb)
        for sb in sbs)
    if spec is None:
        entries.append(Geometry('serve_window', window=W))
    else:
        # the verify ladder over live decode contexts, exactly the
        # monolithic spec derivation with import contexts as the floor
        k = int(spec)
        mnts = (max_new_tokens if isinstance(max_new_tokens,
                                             (list, tuple))
                else [max_new_tokens])
        budget = max(engine.max_new_tokens if m is None else int(m)
                     for m in mnts)
        lens = [L for L in context_lens if L >= 2]
        if lens:
            m_lo = min(lens)
            m_hi = min(max(lens) + budget, engine.max_context_len) - 1
            ladder, v = [], m_lo + k + 1
            while v <= m_hi + k + 1:
                b = bucket_length(v, engine.buckets)
                ladder.append(b)
                v = b + 1
            entries.extend(Geometry('serve_spec_window', spec=k, ctx=c)
                           for c in ladder)
    return GeometrySet(entries)


def for_train_engine(engine, batch_shape, batch_dtype='int32',
                     extra_input_shapes=(), extra_input_dtypes=(),
                     label_shapes=(), label_dtypes=()):
    """The fused-train-step geometry for one global batch shape (pass
    several shapes through repeated calls + `GeometrySet(a.entries +
    b.entries)` if the loader yields more than one)."""
    shapes = (tuple(int(s) for s in batch_shape),) + tuple(
        tuple(int(s) for s in sh) for sh in extra_input_shapes)
    dtypes = (str(batch_dtype),) + tuple(str(d) for d in extra_input_dtypes)
    return GeometrySet([Geometry(
        'train_step',
        input_shapes=shapes, input_dtypes=dtypes,
        label_shapes=tuple(tuple(int(s) for s in sh)
                           for sh in label_shapes),
        label_dtypes=tuple(str(d) for d in label_dtypes))])


# ---------------------------------------------------------------------------
# Donation contract per serve kind
# ---------------------------------------------------------------------------

# Which ARGUMENT NAMES each serve-dispatch kind donates to jit — the
# single source of truth shared by the dispatch decorators in
# inference/serving.py and the hlolint HL001 prover (which counts the
# `input_output_alias` entries XLA actually emitted against the flat
# leaves of these args). serve_export deliberately donates NOTHING:
# the source pool must survive the export (the request keeps serving
# until its owner retires it).
DONATED_ARGNAMES = {
    'serve_step': ('pages', 'last_logits'),
    'serve_window': ('pages', 'last_logits'),
    'serve_prefill': ('pages', 'last_logits'),
    'serve_chunk_step': ('pages', 'last_logits'),
    'serve_spec_step': ('pages', 'dpages', 'last_logits'),
    'serve_spec_window': ('pages', 'dpages', 'last_logits'),
    'serve_export': (),
    'serve_import': ('pages',),
}


def donated_argnames(kind):
    """Declared donated argument names for a serve-dispatch geometry
    kind. Raises on unknown kinds so a new dispatch cannot silently
    ship with an undeclared (and therefore unproven) donation
    contract."""
    try:
        return DONATED_ARGNAMES[kind]
    except KeyError:
        raise ValueError(
            f'no declared donation contract for geometry kind {kind!r}'
            f' — add it to aot.geometry.DONATED_ARGNAMES') from None


def for_engine(engine, **workload):
    """Dispatch on engine type (the `aot.build` entry point)."""
    from ..inference.engine import DecodeEngine
    from ..inference.serving import ServingEngine
    from ..training.engine import TrainEngine

    if isinstance(engine, ServingEngine):
        return for_serving_engine(engine, **workload)
    if isinstance(engine, DecodeEngine):
        return for_decode_engine(engine, **workload)
    if isinstance(engine, TrainEngine):
        return for_train_engine(engine, **workload)
    raise TypeError(
        f'no geometry enumeration for {type(engine).__name__}; expected '
        f'a DecodeEngine, ServingEngine, or TrainEngine')


__all__ = ['Geometry', 'GeometrySet', 'for_engine', 'for_decode_engine',
           'for_serving_engine', 'for_train_engine',
           'DONATED_ARGNAMES', 'donated_argnames']
