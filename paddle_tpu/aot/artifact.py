"""EngineArtifact — build, persist, and warm-attach AOT-compiled
engine executables.

The zero-compile cold start has three moving parts, and this module is
where they compose:

  1. `build(engine, out_dir, **workload)` enumerates the engine's
     GeometrySet (aot.geometry), wires jax's persistent compilation
     cache into `out_dir/xla_cache`, and DRIVES every geometry through
     the same module-level jitted steps the live engine dispatches —
     so the executables persisted to disk are keyed exactly as the
     serving process will look them up (an AOT-only `.lower().compile()`
     path could drift from the dispatch path's cache keys; executing
     the real dispatch cannot). A manifest records the engine config
     hash, the jax/jaxlib/backend fingerprint, and every geometry with
     its stable CompileCache key string.

  2. `EngineArtifact.load(path)` + `engine.warmup(artifact=...)`
     (`warm_attach` here) verify the fingerprint and config hash —
     refusing loudly on mismatch, because a stale artifact silently
     degrades to full cold-start compiles — then re-drive the
     geometries: jax traces, finds every executable in the persistent
     cache, and the process's in-memory jit cache is hot before the
     first request arrives. First token is then ONE dispatch: zero
     traces, zero registry misses (bench.py's `gate_cold_start` holds
     it to exactly that).

  3. Optionally, `build(..., export_stablehlo=True)` also serializes
     each geometry through `jax.export` (the full Exported flatbuffer,
     the same portable layer `jit.save` writes) into
     `out_dir/stablehlo/` — a compiler-version-independent fallback the
     XLA executable cache is not.

Artifact layout and invalidation rules: docs/aot_warmup.md.
"""
from __future__ import annotations

import hashlib
import json
import os
import time

import jax

from ..inference.engine import key_str
from ..observability import metrics as _obs
from ..observability import tracing as _obs_trace
from . import geometry as _geometry


def _all_traces():
    """Process-wide trace count across BOTH engine families (the
    inference and training counters are separate by design; a build/
    warmup report wants their sum)."""
    from ..inference.engine import total_traces as _it
    from ..training.engine import total_traces as _tt

    return _it() + _tt()


MANIFEST_NAME = 'manifest.json'
MANIFEST_VERSION = 1


class ArtifactMismatch(RuntimeError):
    """An EngineArtifact refused to attach: the manifest's fingerprint
    or config hash disagrees with the live process/engine. Attaching
    anyway would silently recompile everything — the exact failure mode
    this subsystem exists to make loud."""


def fingerprint():
    """The compilation environment an artifact is only valid within:
    persistent-cache entries are compiler-output, so a different
    jaxlib/backend would miss every key and recompile silently."""
    import jaxlib
    import sys

    dev = jax.devices()[0]
    return {
        'jax': jax.__version__,
        'jaxlib': jaxlib.__version__,
        'backend': jax.default_backend(),
        'device_kind': getattr(dev, 'device_kind', '?'),
        'python': f'{sys.version_info[0]}.{sys.version_info[1]}',
    }


def config_hash(config):
    """sha256 over the canonical JSON of an engine's `aot_config()`."""
    blob = json.dumps(config, sort_keys=True, separators=(',', ':'))
    return hashlib.sha256(blob.encode()).hexdigest()


def _portable_key(key):
    """Manifest form of a registry key: the model-id component (a
    per-process creation-order counter) is normalized to -1, because
    the attaching process's counter need not agree with the builder's.
    Manifest keys are for observability and cross-run diffing — live
    equality checks (the enumeration==live proof) always recompute
    keys in-process against the live engine."""
    return (key[0], -1) + tuple(key[2:])


class EngineArtifact:
    """A built artifact directory: manifest + persistent executable
    cache (+ optional StableHLO layer). Construct via `load` or
    `build`."""

    def __init__(self, path, manifest):
        self.path = os.path.abspath(path)
        self.manifest = manifest

    @property
    def cache_dir(self):
        return os.path.join(self.path, 'xla_cache')

    @property
    def stablehlo_dir(self):
        return os.path.join(self.path, 'stablehlo')

    # manifest entries carry build metadata (key, build_s, stablehlo,
    # cost) on top of the geometry params; strip it so a restored
    # Geometry equals a freshly enumerated one
    _GEOMETRY_META = ('key', 'build_s', 'stablehlo', 'cost')

    def geometry_set(self):
        return _geometry.GeometrySet.from_manifest(
            [{k: v for k, v in d.items() if k not in self._GEOMETRY_META}
             for d in self.manifest['geometries']])

    def geometry_costs(self):
        """(Geometry, cost dict) pairs for every manifest entry that
        carries a usable cost stamp — what `warm_attach` feeds into the
        engine's dispatch-cost table for the live MFU gauges."""
        out = []
        for d in self.manifest.get('geometries', ()):
            cost = d.get('cost')
            if isinstance(cost, dict) and cost.get('flops'):
                g = _geometry.Geometry.from_dict(
                    {k: v for k, v in d.items()
                     if k not in self._GEOMETRY_META})
                out.append((g, cost))
        return out

    @classmethod
    def load(cls, path):
        mpath = os.path.join(path, MANIFEST_NAME)
        if not os.path.isfile(mpath):
            raise FileNotFoundError(
                f'{path} is not an EngineArtifact (no {MANIFEST_NAME}); '
                f'build one with paddle_tpu.aot.build')
        with open(mpath) as f:
            manifest = json.load(f)
        if manifest.get('version') != MANIFEST_VERSION:
            raise ArtifactMismatch(
                f"artifact manifest version {manifest.get('version')} != "
                f'supported {MANIFEST_VERSION}; rebuild the artifact')
        return cls(path, manifest)

    def check(self, engine):
        """Refuse (ArtifactMismatch) unless this artifact was built in
        an equivalent compilation environment FOR an equivalently
        configured engine. Weight VALUES are not checked (same-
        architecture checkpoints share artifacts by design), but the
        model's param STRUCTURE is — aot_config's `model_struct` hash —
        since a differently-sized model would miss every cache entry."""
        want = self.manifest['fingerprint']
        have = fingerprint()
        for field in ('jax', 'jaxlib', 'backend', 'device_kind'):
            if want.get(field) != have.get(field):
                raise ArtifactMismatch(
                    f'artifact fingerprint mismatch on {field!r}: built '
                    f'with {want.get(field)!r}, this process has '
                    f'{have.get(field)!r} — persistent-cache entries '
                    f'would silently miss; rebuild the artifact for '
                    f'this environment')
        cfg = engine.aot_config()
        h = config_hash(cfg)
        if h != self.manifest['config_hash']:
            built = self.manifest.get('engine', {})
            diff = sorted(k for k in set(built) | set(cfg)
                          if built.get(k) != cfg.get(k))
            raise ArtifactMismatch(
                f'artifact was built for a different engine config '
                f'(hash {self.manifest["config_hash"][:12]} != '
                f'{h[:12]}); differing fields: {diff} — rebuild, or '
                f'construct the engine with the manifest\'s config')


def _register_export_containers():
    """jax.export serialization needs every pytree container in an
    exported calling convention registered by name; the KV-cache
    NamedTuples are ours to register (idempotent — a re-register of
    the same class raises and is swallowed)."""
    from jax import export as jax_export

    from ..models.generation import (PagedKVCache, QuantKVCache,
                                     QuantPagedKVCache, RowQuantKVCache)

    for cls in (PagedKVCache, QuantKVCache, QuantPagedKVCache,
                RowQuantKVCache):
        try:
            jax_export.register_namedtuple_serialization(
                cls, serialized_name=f'paddle_tpu.{cls.__name__}')
        except ValueError:
            pass


def _export_stablehlo(out_dir, engine, g, draft):
    """Serialize one geometry's traced computations as full jax.export
    Exported flatbuffers (restorable via jax.export.deserialize — the
    same portable layer jit.save writes). A geometry can span several
    jitted steps (a bucketed generate is prefill + decode loop); each
    exports to its own file. Returns the list of relative file names
    and/or error strings — export failures are recorded, never fatal
    (the executable cache, not StableHLO, is the zero-compile path)."""
    from jax import export as jax_export

    out = []
    try:
        _register_export_containers()
        specs = list(engine._export_specs(g, draft=draft))
    except NotImplementedError as e:
        return [f'skipped: {e}']
    except Exception as e:  # noqa: BLE001 - per-geometry, never fatal
        return [f'error: {type(e).__name__}: {e}']
    for suffix, fn, args in specs:
        fname = f'{g.label()}{suffix}.stablehlo'
        try:
            exported = jax_export.export(fn)(*args)
            data = exported.serialize()
        except Exception as e:  # noqa: BLE001
            out.append(f'error[{fname}]: {type(e).__name__}: {e}')
            continue
        os.makedirs(out_dir, exist_ok=True)
        with open(os.path.join(out_dir, fname), 'wb') as f:
            f.write(data)
        out.append(fname)
    return out


def _geometry_cost(engine, g, draft):
    """Per-geometry cost stamp for the manifest: flops / bytes via
    observability.costs over the engine's `_cost_specs` (the live
    dispatch functions; with the artifact's persistent cache wired the
    compile inside is a disk read of the executable the build just
    persisted). Failures degrade to an {'error': ...} stamp — costs
    are observability, never allowed to fail a build."""
    from ..observability import costs as _costs

    try:
        return _costs.geometry_cost(engine, g, draft=draft)
    except NotImplementedError as e:
        return {'error': f'skipped: {e}'}
    except Exception as e:  # noqa: BLE001 - per-geometry, never fatal
        return {'error': f'{type(e).__name__}: {e}'}


def build(engine, out_dir, geometries=None, draft=None,
          export_stablehlo=False, stamp_costs=True, **workload):
    """Build an EngineArtifact for `engine` into `out_dir`.

    `geometries` — an explicit GeometrySet; default is
    `aot.geometry.for_engine(engine, **workload)` (workload kwargs like
    `prompt_lens=range(1, 33)` are forwarded there). `draft` — the
    draft model, required when speculative geometries are enumerated.
    Compilation happens through the live dispatch path with the
    persistent cache wired to the artifact directory, so building is
    also a warmup of the CURRENT process.

    `stamp_costs` (default on) additionally records each geometry's
    XLA cost analysis (flops / bytes accessed — observability.costs)
    in the manifest; engines that later `warmup(artifact=...)` turn
    those static numbers into live `serve.mfu_est`/`train.mfu_est`
    gauges at their existing window syncs."""
    from .. import sysconfig

    if geometries is None:
        geometries = _geometry.for_engine(engine, **workload)
    if not len(geometries):
        raise ValueError('refusing to build an empty artifact: the '
                         'GeometrySet enumerated no geometries')
    out_dir = os.path.abspath(out_dir)
    os.makedirs(out_dir, exist_ok=True)
    prev_cache_dir = sysconfig.persistent_compilation_cache_dir()
    cache_dir = sysconfig.enable_persistent_compilation_cache(
        os.path.join(out_dir, 'xla_cache'))
    if cache_dir is None:
        raise RuntimeError(
            'this jax build has no persistent compilation cache '
            'support; an EngineArtifact cannot persist executables')

    keys = geometries.registry_keys(engine)
    t0 = time.perf_counter()
    traces0 = _all_traces()
    gdicts = []
    # a process that already served traffic holds these geometries in
    # jax's IN-PROCESS jit cache: driving them again would hit there,
    # compile nothing, and persist NOTHING into the artifact — the warm
    # replica would then silently recompile exactly the hottest
    # geometries during attach. Evicting THIS engine family's jitted
    # steps (per function, never process-wide — other engines in the
    # process keep their hot caches) forces every geometry through a
    # real dispatch-path compile against the artifact's cache, and
    # re-populates the in-process cache as it goes, so a builder that
    # keeps serving afterwards stays warm for the driven geometries.
    for fn in engine._aot_jitted_fns():
        fn.clear_cache()
    try:
        with _obs_trace.span('aot.build', cat='compile',
                             geometries=len(geometries)):
            for g in geometries:
                gt0 = time.perf_counter()
                engine._warm_geometry(g, draft=draft)
                d = g.to_dict()
                d['key'] = key_str(_portable_key(
                    _geometry._registry_key(engine, g)))
                if stamp_costs:
                    d['cost'] = _geometry_cost(engine, g, draft)
                d['build_s'] = round(time.perf_counter() - gt0, 4)
                if export_stablehlo:
                    d['stablehlo'] = _export_stablehlo(
                        os.path.join(out_dir, 'stablehlo'), engine, g,
                        draft)
                gdicts.append(d)
                _obs.inc('aot.built_geometries')
    finally:
        # the redirection is SCOPED to the build: a builder that keeps
        # serving must not leak undeclared executables into the
        # artifact (contents would drift from the manifest) nor starve
        # a previously wired cache dir
        if prev_cache_dir != cache_dir:
            sysconfig.restore_persistent_compilation_cache(prev_cache_dir)
    cfg = engine.aot_config()
    manifest = {
        'version': MANIFEST_VERSION,
        'created_at': time.strftime('%Y-%m-%dT%H:%M:%SZ', time.gmtime()),
        'fingerprint': fingerprint(),
        'engine': cfg,
        'config_hash': config_hash(cfg),
        'geometries': gdicts,
        'registry_keys': [key_str(_portable_key(k)) for k in keys],
        'build': {
            'seconds': round(time.perf_counter() - t0, 3),
            'traces': _all_traces() - traces0,
            'n_geometries': len(geometries),
        },
    }
    with open(os.path.join(out_dir, MANIFEST_NAME), 'w') as f:
        json.dump(manifest, f, indent=2)
    return EngineArtifact(out_dir, manifest)


def warm_attach(engine, artifact=None, geometries=None, draft=None):
    """The engines' `warmup()` implementation. With `artifact` (an
    EngineArtifact or its directory path): fingerprint/config check,
    wire the persistent cache, drive the manifest's geometries. With
    bare `geometries`: drive those (in-process pre-trace only — no
    disk cache). Returns the warmup report.

    The cache redirection is SCOPED like build()'s: after the drive,
    the previous wiring (usually none) is restored — a replica's later
    undeclared compiles must not write into a shared (often read-only)
    artifact mount, nor drift its contents from the manifest."""
    from .. import sysconfig

    if artifact is None and geometries is None:
        raise ValueError('warmup needs an artifact=... or geometries=...')
    cache_dir = None
    prev_cache_dir = sysconfig.persistent_compilation_cache_dir()
    if artifact is not None:
        if isinstance(artifact, (str, os.PathLike)):
            artifact = EngineArtifact.load(artifact)
        artifact.check(engine)
        cache_dir = sysconfig.enable_persistent_compilation_cache(
            artifact.cache_dir)
        if geometries is None:
            geometries = artifact.geometry_set()
    t0 = time.perf_counter()
    traces0 = _all_traces()
    try:
        with _obs_trace.span('aot.warmup', cat='compile',
                             geometries=len(geometries)):
            for g in geometries:
                engine._warm_geometry(g, draft=draft)
                _obs.inc('aot.warmed_geometries')
    finally:
        if cache_dir is not None and prev_cache_dir != cache_dir:
            sysconfig.restore_persistent_compilation_cache(prev_cache_dir)
    # the manifest's per-geometry cost stamps feed the engine's
    # dispatch-cost table: from here on, window commits derive live
    # mfu/roofline gauges from static flops x host wall — no lowering,
    # no syncs, no retraces on the serving path
    costs_loaded = 0
    if artifact is not None and hasattr(engine, '_note_geometry_cost'):
        for g, cost in artifact.geometry_costs():
            engine._note_geometry_cost(g, cost)
            costs_loaded += 1
    report = {
        'geometries': len(geometries),
        'seconds': round(time.perf_counter() - t0, 3),
        'traces': _all_traces() - traces0,
        'persistent_cache_dir': cache_dir,
        'costs_loaded': costs_loaded,
    }
    _obs.set_gauge('aot.warmup_s', report['seconds'])
    return report


__all__ = ['ArtifactMismatch', 'EngineArtifact', 'build', 'warm_attach',
           'fingerprint', 'config_hash', 'MANIFEST_NAME']
