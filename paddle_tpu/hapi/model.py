"""High-level Model API (ref: python/paddle/hapi/model.py).

`Model(network).prepare(opt, loss, metrics)` then `fit/evaluate/
predict/save/load` — Paddle's Keras-style trainer. TPU-native twist:
the whole train step (fwd+bwd+update) is one jitted donated-state
program, rebuilt only when shapes change.
"""
from __future__ import annotations

import os
import typing

import jax
import jax.numpy as jnp
import numpy as np

from .. import autograd
from ..callbacks import CallbackList, ProgBarLogger
from ..framework import io as io_mod
from ..io.dataloader import DataLoader


def _to_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


class Model:
    """ref: paddle.Model."""

    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self._opt_state = None
        self._train_step = None
        self._eval_step = None
        self.stop_training = False

    # -- setup ------------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None, **kw):
        self._optimizer = optimizer
        self._loss = loss
        self._metrics = _to_list(metrics)
        if optimizer is not None:
            self._opt_state = optimizer.init(self.network)
        self._build_steps()
        return self

    def _build_steps(self):
        import inspect

        opt = self._optimizer
        loss_fn = self._loss
        # thread lr as a traced argument ONLY for optimizers whose
        # apply_gradients accepts it (the base Optimizer family); wrapper
        # optimizers (GradientMerge/LookAhead/sharding) keep their own
        # signature and stored rate
        self._lr_threaded = False
        if opt is not None:
            try:
                params = inspect.signature(opt.apply_gradients).parameters
                self._lr_threaded = ('lr' in params
                                     and hasattr(opt, 'get_lr'))
            except (TypeError, ValueError):
                pass

        if self._lr_threaded:
            def train_step(network, opt_state, inputs, labels, lr):
                def compute(m):
                    preds = m(*inputs)
                    loss = loss_fn(preds, *labels)
                    return loss, (m, preds)

                (loss, (m, preds)), grads = autograd.value_and_grad(
                    compute, has_aux=True)(network)
                # lr arrives traced so host-side set_lr / scheduler steps
                # take effect without retracing
                m, opt_state = opt.apply_gradients(m, grads, opt_state,
                                                   lr=lr)
                return m, opt_state, loss, preds
        else:
            def train_step(network, opt_state, inputs, labels):
                def compute(m):
                    preds = m(*inputs)
                    loss = loss_fn(preds, *labels)
                    return loss, (m, preds)

                (loss, (m, preds)), grads = autograd.value_and_grad(
                    compute, has_aux=True)(network)
                m, opt_state = opt.apply_gradients(m, grads, opt_state)
                return m, opt_state, loss, preds

        def eval_step(network, inputs, labels):
            preds = network(*inputs)
            loss = loss_fn(preds, *labels) if loss_fn is not None else 0.0
            return loss, preds

        # cached on self for the Model's lifetime: built once per
        # prepare(), every train/eval/predict batch reuses them
        # tracelint: disable=TL001
        self._train_step = jax.jit(train_step) if opt else None
        # tracelint: disable=TL001
        self._eval_step = jax.jit(eval_step)
        # tracelint: disable=TL001
        self._pred_step = jax.jit(lambda network, inputs: network(*inputs))

    # -- single-batch API (ref: Model.train_batch / eval_batch) ----------
    def train_batch(self, inputs, labels=None):
        inputs = tuple(jnp.asarray(x) for x in _to_list(inputs))
        labels = tuple(jnp.asarray(x) for x in _to_list(labels))
        self.network.train()
        if self._lr_threaded:
            opt = self._optimizer
            state = self._opt_state
            step_no = (int(state['step']) + 1
                       if isinstance(state, dict) and 'step' in state else 1)
            lr_now = jnp.asarray(opt.get_lr(step_no), jnp.float32)
            net, self._opt_state, loss, preds = self._train_step(
                self.network, self._opt_state, inputs, labels, lr_now)
        else:
            net, self._opt_state, loss, preds = self._train_step(
                self.network, self._opt_state, inputs, labels)
        self.network = net
        metrics = self._update_metrics(preds, labels)
        return [float(loss)] + metrics

    def eval_batch(self, inputs, labels=None):
        inputs = tuple(jnp.asarray(x) for x in _to_list(inputs))
        labels = tuple(jnp.asarray(x) for x in _to_list(labels))
        self.network.eval()
        loss, preds = self._eval_step(self.network, inputs, labels)
        metrics = self._update_metrics(preds, labels)
        return [float(loss)] + metrics

    def predict_batch(self, inputs):
        inputs = tuple(jnp.asarray(x) for x in _to_list(inputs))
        self.network.eval()
        return np.asarray(self._pred_step(self.network, inputs))

    def _update_metrics(self, preds, labels):
        out = []
        for m in self._metrics:
            args = m.compute(preds, *labels)
            if not isinstance(args, tuple):
                args = (args,)
            m.update(*args)
            acc = m.accumulate()
            out.append(acc)
        return out

    # -- loops ------------------------------------------------------------
    def _loader(self, data, batch_size, shuffle):
        if data is None or isinstance(data, DataLoader):
            return data
        return DataLoader(data, batch_size=batch_size, shuffle=shuffle)

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=1,
            shuffle=True, callbacks=None, **kw):
        train_loader = self._loader(train_data, batch_size, shuffle)
        eval_loader = self._loader(eval_data, batch_size, False)
        cbks = CallbackList(
            _to_list(callbacks) or [ProgBarLogger(log_freq, verbose)],
            model=self,
            params={'epochs': epochs, 'steps': len(train_loader),
                    'verbose': verbose},
        )
        self.stop_training = False
        cbks.on_train_begin()
        logs = {}
        for epoch in range(epochs):
            if self.stop_training:
                break
            for m in self._metrics:
                m.reset()
            cbks.on_epoch_begin(epoch)
            for step, batch in enumerate(train_loader):
                cbks.on_train_batch_begin(step)
                inputs, labels = self._split_batch(batch)
                vals = self.train_batch(inputs, labels)
                logs = self._logs(vals)
                cbks.on_train_batch_end(step, logs)
            cbks.on_epoch_end(epoch, logs)
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                eval_logs = self.evaluate(eval_loader, callbacks=cbks,
                                          verbose=0)
                cbks.on_eval_end(eval_logs)
            if save_dir and (epoch + 1) % save_freq == 0:
                self.save(os.path.join(save_dir, str(epoch)))
        cbks.on_train_end(logs)
        return self

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=1,
                 callbacks=None, **kw):
        loader = self._loader(eval_data, batch_size, False)
        for m in self._metrics:
            m.reset()
        losses = []
        for batch in loader:
            inputs, labels = self._split_batch(batch)
            vals = self.eval_batch(inputs, labels)
            losses.append(vals[0])
        logs = {'loss': float(np.mean(losses)) if losses else 0.0}
        for m in self._metrics:
            names = m.name()
            accs = m.accumulate()
            if isinstance(names, list):
                logs.update(dict(zip(names, accs)))
            else:
                logs[names] = accs
        return logs

    def predict(self, test_data, batch_size=1, **kw):
        loader = self._loader(test_data, batch_size, False)
        outs = []
        for batch in loader:
            inputs, _ = self._split_batch(batch, has_labels=False)
            outs.append(self.predict_batch(inputs))
        return outs

    def _split_batch(self, batch, has_labels=True):
        """(inputs..., label) convention — the trailing element is the
        label whenever the batch has >= 2 elements (ref: hapi/model.py
        feeds inputs+labels in one list; predict ignores the label)."""
        if isinstance(batch, (list, tuple)):
            if len(batch) >= 2:
                return tuple(batch[:-1]), (tuple(batch[-1:]) if has_labels else ())
            return tuple(batch), ()
        return (batch,), ()

    def _logs(self, vals):
        logs = {'loss': vals[0]}
        i = 1
        for m in self._metrics:
            names = m.name()
            if isinstance(names, list):
                # one accumulated array per metric: component j belongs
                # to name j (e.g. Accuracy(topk=(1, 5)) -> 2 entries)
                # tracelint: disable=TL002 - metric logging readback at
                # batch boundary (a handful of scalars, off the hot path)
                v = np.asarray(vals[i]).reshape(-1)
                for j, n in enumerate(names):
                    logs[n] = float(v[j])
                i += 1
            else:
                v = vals[i]
                logs[names] = float(np.asarray(v).reshape(-1)[0])
                i += 1
        return logs

    # -- persistence ------------------------------------------------------
    def save(self, path, training=True):
        io_mod.save(self.network.state_dict(), path + '.pdparams')
        if training and self._opt_state is not None:
            # opt state slots are model-shaped pytrees (Layer nodes) —
            # store leaves; load rebuilds via the optimizer's treedef
            leaves = jax.tree.leaves(self._opt_state)
            io_mod.save({str(i): leaf for i, leaf in enumerate(leaves)},
                        path + '.pdopt')

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        state = io_mod.load(path + '.pdparams')
        self.network.set_state_dict(state, strict=not skip_mismatch)
        opt_path = path + '.pdopt'
        if not reset_optimizer and os.path.exists(opt_path) and self._optimizer:
            template = self._optimizer.init(self.network)
            treedef = jax.tree.structure(template)
            flat = io_mod.load(opt_path)
            leaves = [jnp.asarray(flat[str(i)]) for i in range(len(flat))]
            self._opt_state = jax.tree.unflatten(treedef, leaves)
        return self

    def parameters(self):
        return self.network.parameters()

    def summary(self, input_size=None, dtype=None):
        from .summary import summary

        return summary(self.network, input_size, dtype)
