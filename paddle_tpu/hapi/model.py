"""High-level Model API (ref: python/paddle/hapi/model.py).

`Model(network).prepare(opt, loss, metrics)` then `fit/evaluate/
predict/save/load` — Paddle's Keras-style trainer. TPU-native twist:
the train/eval loops delegate to training.engine.TrainEngine, so every
model in the zoo gets the compiled-hot-path contract for free — one
donated fused step per global batch (params + optimizer state updated
in place), the lr schedule traced from the device step counter, batches
prefetched to device ahead of consumption, and ONE host sync per log
window instead of a `float(loss)` stall on every step.
"""
from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np

from ..callbacks import CallbackList, ProgBarLogger
from ..framework import io as io_mod
from ..io.dataloader import DataLoader
from ..training.engine import TrainEngine


def _to_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


class Model:
    """ref: paddle.Model."""

    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self._opt_state = None
        self._engine = None
        self.stop_training = False

    # -- setup ------------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None,
                accum_steps=1, scaler=None, mesh=None, **kw):
        self._optimizer = optimizer
        self._loss = loss
        self._metrics = _to_list(metrics)
        # the engine owns the compiled train/eval path: donated fused
        # step, traced lr, windowed metric sync (docs/train_engine.md)
        self._engine = TrainEngine(
            self.network, optimizer, loss_fn=loss,
            loss_mode='fn' if loss is not None else 'none',
            accum_steps=accum_steps, scaler=scaler, mesh=mesh,
            metrics=self._metrics)
        self._opt_state = self._engine.opt_state
        self._build_steps()
        return self

    def _build_steps(self):
        # cached on self for the Model's lifetime: built once per
        # prepare(), every predict batch reuses it (train/eval go
        # through the module-level engine jits instead)
        # tracelint: disable=TL001
        self._pred_step = jax.jit(lambda network, inputs: network(*inputs))

    def _after_engine_step(self):
        """The engine donated-and-rebuilt the pytrees: re-point the
        Model-level references at the live ones."""
        self.network = self._engine.model
        self._opt_state = self._engine.opt_state

    # -- single-batch API (ref: Model.train_batch / eval_batch) ----------
    def train_batch(self, inputs, labels=None):
        inputs = tuple(jnp.asarray(x) for x in _to_list(inputs))
        labels = tuple(jnp.asarray(x) for x in _to_list(labels))
        self.network.train()
        self._engine.step(inputs, labels)
        logs = self._engine.sync()          # per-batch API: sync now
        self._after_engine_step()
        return [logs['loss']] + [m.accumulate() for m in self._metrics]

    def eval_batch(self, inputs, labels=None):
        inputs = tuple(jnp.asarray(x) for x in _to_list(inputs))
        labels = tuple(jnp.asarray(x) for x in _to_list(labels))
        self.network.eval()
        flushed = self._engine.eval_step(inputs, labels)
        losses = (flushed or []) + self._engine.eval_sync()
        return [losses[-1]] + [m.accumulate() for m in self._metrics]

    def predict_batch(self, inputs):
        inputs = tuple(jnp.asarray(x) for x in _to_list(inputs))
        self.network.eval()
        return np.asarray(self._pred_step(self.network, inputs))

    # -- loops ------------------------------------------------------------
    def _loader(self, data, batch_size, shuffle):
        if data is None or isinstance(data, DataLoader):
            return data
        return DataLoader(data, batch_size=batch_size, shuffle=shuffle)

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=1,
            shuffle=True, callbacks=None, **kw):
        train_loader = self._loader(train_data, batch_size, shuffle)
        eval_loader = self._loader(eval_data, batch_size, False)
        cbks = CallbackList(
            _to_list(callbacks) or [ProgBarLogger(log_freq, verbose)],
            model=self,
            params={'epochs': epochs, 'steps': len(train_loader),
                    'verbose': verbose},
        )
        engine = self._engine
        engine.log_window = max(1, int(log_freq))
        self.stop_training = False
        cbks.on_train_begin()
        logs = {}
        self.network.train()
        for epoch in range(epochs):
            if self.stop_training:
                break
            for m in self._metrics:
                m.reset()
            cbks.on_epoch_begin(epoch)
            # device prefetch: the next global batch's H2D DMA overlaps
            # this step's compute (sharded over dp/fsdp when a mesh is
            # wired); losses/metrics stay on device between log windows
            for step, batch in enumerate(engine.prefetch(train_loader)):
                cbks.on_train_batch_begin(step)
                inputs, labels = self._split_batch(batch)
                window_logs = engine.step(inputs, labels)
                # re-point network/opt_state EVERY batch: the engine
                # donated the previous pytrees, and callbacks (weight
                # logging, mid-epoch checkpoints) read self.model
                self._after_engine_step()
                if window_logs is not None:
                    logs = self._window_logs(window_logs)
                cbks.on_train_batch_end(step, logs)
            tail = engine.sync()            # flush the partial window
            if tail is not None:
                logs = self._window_logs(tail)
            cbks.on_epoch_end(epoch, logs)
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                eval_logs = self.evaluate(eval_loader, callbacks=cbks,
                                          verbose=0)
                cbks.on_eval_end(eval_logs)
                self.network.train()
            if save_dir and (epoch + 1) % save_freq == 0:
                self.save(os.path.join(save_dir, str(epoch)))
        self._after_engine_step()
        cbks.on_train_end(logs)
        return self

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=1,
                 callbacks=None, **kw):
        loader = self._loader(eval_data, batch_size, False)
        for m in self._metrics:
            m.reset()
        engine = self._engine
        self.network.eval()
        losses = []
        for batch in engine.prefetch(loader):
            inputs, labels = self._split_batch(batch)
            flushed = engine.eval_step(inputs, labels)
            if flushed:
                losses.extend(flushed)
        losses.extend(engine.eval_sync())   # one device_get per window
        logs = {'loss': float(np.mean(losses)) if losses else 0.0}
        for m in self._metrics:
            names = m.name()
            accs = m.accumulate()
            if isinstance(names, list):
                logs.update(dict(zip(names, accs)))
            else:
                logs[names] = accs
        return logs

    def predict(self, test_data, batch_size=1, **kw):
        loader = self._loader(test_data, batch_size, False)
        outs = []
        for batch in loader:
            inputs, _ = self._split_batch(batch, has_labels=False)
            outs.append(self.predict_batch(inputs))
        return outs

    def _split_batch(self, batch, has_labels=True):
        """(inputs..., label) convention — the trailing element is the
        label whenever the batch has >= 2 elements (ref: hapi/model.py
        feeds inputs+labels in one list; predict ignores the label)."""
        if isinstance(batch, (list, tuple)):
            if len(batch) >= 2:
                return tuple(batch[:-1]), (tuple(batch[-1:]) if has_labels else ())
            return tuple(batch), ()
        return (batch,), ()

    def _window_logs(self, window_logs):
        """Engine window logs -> hapi logs dict (drop engine-internal
        keys so callbacks see the historical schema)."""
        return {k: v for k, v in window_logs.items()
                if k not in ('loss_mean', 'window')}

    def _logs(self, vals):
        logs = {'loss': vals[0]}
        # vals[1:] are the metrics' HOST accumulates (train_batch synced
        # them already): one slot per metric, one log entry per name
        # (e.g. Accuracy(topk=(1, 5)) -> 2 entries from its one slot)
        rest = [np.asarray(v).reshape(-1) for v in vals[1:]]
        for m, v in zip(self._metrics, rest):
            names = m.name()
            if isinstance(names, list):
                for j, n in enumerate(names):
                    logs[n] = float(v[j])
            else:
                logs[names] = float(v[0])
        return logs

    # -- persistence ------------------------------------------------------
    def save(self, path, training=True):
        io_mod.save(self.network.state_dict(), path + '.pdparams')
        if training and self._opt_state is not None:
            # opt state slots are model-shaped pytrees (Layer nodes) —
            # store leaves; load rebuilds via the optimizer's treedef
            leaves = jax.tree.leaves(self._opt_state)
            io_mod.save({str(i): leaf for i, leaf in enumerate(leaves)},
                        path + '.pdopt')

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        state = io_mod.load(path + '.pdparams')
        self.network.set_state_dict(state, strict=not skip_mismatch)
        opt_path = path + '.pdopt'
        if not reset_optimizer and os.path.exists(opt_path) and self._optimizer:
            template = self._optimizer.init(self.network)
            treedef = jax.tree.structure(template)
            flat = io_mod.load(opt_path)
            leaves = [jnp.asarray(flat[str(i)]) for i in range(len(flat))]
            self._opt_state = jax.tree.unflatten(treedef, leaves)
            if self._engine is not None:
                self._engine.opt_state = self._opt_state
        if self._engine is not None:
            self._engine.model = self.network
        return self

    def parameters(self):
        return self.network.parameters()

    def summary(self, input_size=None, dtype=None):
        from .summary import summary

        return summary(self.network, input_size, dtype)
