"""Model summary (ref: python/paddle/hapi/model_summary.py)."""
from __future__ import annotations

import numpy as np


def summary(net, input_size=None, dtype=None, print_fn=print):
    """Param table per layer + totals. Returns {'total_params', 'trainable_params'}."""
    rows = []
    total = 0
    trainable = 0
    for path, layer in net.named_sublayers(include_self=True):
        n_params = 0
        layer_trainable = 0
        for name, v in layer._children():
            from ..nn.layer.base import Layer

            if isinstance(v, Layer) or v is None:
                continue
            meta = layer.meta_for(name)
            if meta.kind != 'param':
                continue
            n = int(np.prod(v.shape))
            n_params += n
            if meta.trainable:
                layer_trainable += n
        if n_params:
            rows.append((path or type(layer).__name__,
                         type(layer).__name__, n_params))
            total += n_params
            trainable += layer_trainable
    if print_fn:
        width = max([len(r[0]) for r in rows], default=10) + 2
        print_fn(f"{'Layer':<{width}}{'Type':<24}{'Params':>12}")
        print_fn('-' * (width + 36))
        for path, tname, n in rows:
            print_fn(f'{path:<{width}}{tname:<24}{n:>12,}')
        print_fn('-' * (width + 36))
        print_fn(f'Total params: {total:,}')
        print_fn(f'Trainable params: {trainable:,}')
        print_fn(f'Non-trainable params: {total - trainable:,}')
    return {'total_params': total, 'trainable_params': trainable}
