"""Legacy reader decorators (ref: python/paddle/reader/decorator.py) —
kept for old training scripts; io.DataLoader is the modern input path.
A "reader" is a zero-arg callable returning an iterator of samples.
"""
from __future__ import annotations

import itertools
import random as _random

__all__ = ['cache', 'map_readers', 'buffered', 'compose', 'chain',
           'shuffle', 'firstn', 'xmap_readers', 'multiprocess_reader']


def cache(reader):
    """ref: paddle.reader.cache — materialize once, replay from memory."""
    data = list(reader())

    def rd():
        return iter(data)

    return rd


def map_readers(func, *readers):
    """ref: paddle.reader.map_readers — zip readers through func."""

    def rd():
        its = [r() for r in readers]
        for items in zip(*its):
            yield func(*items)

    return rd


def shuffle(reader, buf_size):
    """ref: paddle.reader.shuffle — windowed shuffle."""

    def rd():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) >= buf_size:
                _random.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            _random.shuffle(buf)
            yield from buf

    return rd


def chain(*readers):
    """ref: paddle.reader.chain — concatenate readers."""

    def rd():
        return itertools.chain(*[r() for r in readers])

    return rd


class ComposeNotAligned(ValueError):
    """ref: paddle.reader.ComposeNotAligned."""


def compose(*readers, check_alignment=True):
    """ref: paddle.reader.compose — tuple-zip outputs of readers;
    with check_alignment (the default) uneven readers RAISE instead of
    silently truncating the longer ones."""
    import itertools as _it

    def _flatten(item):
        return item if isinstance(item, tuple) else (item,)

    _end = object()

    def rd():
        its = [r() for r in readers]
        for items in _it.zip_longest(*its, fillvalue=_end):
            # identity check, not `in`: ndarray samples overload == and
            # would raise 'truth value of an array is ambiguous'
            if any(i is _end for i in items):
                if check_alignment and any(i is not _end for i in items):
                    raise ComposeNotAligned(
                        'readers produced different numbers of samples')
                return
            yield sum((_flatten(i) for i in items), ())

    return rd


def buffered(reader, size):
    """ref: paddle.reader.buffered — background-thread prefetch queue."""
    import queue
    import threading

    def rd():
        q = queue.Queue(maxsize=size)
        end = object()

        def fill():
            # the sentinel must reach the consumer even when the reader
            # raises, or q.get() blocks forever; ship the exception so
            # the consumer fails loudly instead of freezing
            try:
                for item in reader():
                    q.put(item)
                q.put(end)
            except BaseException as e:  # noqa: BLE001
                q.put(e)

        t = threading.Thread(target=fill, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is end:
                break
            if isinstance(item, BaseException):
                raise item
            yield item

    return rd


def firstn(reader, n):
    """ref: paddle.reader.firstn."""

    def rd():
        return itertools.islice(reader(), n)

    return rd


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """ref: paddle.reader.xmap_readers — parallel map via threads with a
    BOUNDED in-flight window (Executor.map would pull the whole reader
    up front and OOM on streaming datasets)."""
    from collections import deque
    from concurrent.futures import ThreadPoolExecutor

    def rd():
        window = max(int(buffer_size), process_num, 1)
        with ThreadPoolExecutor(max_workers=process_num) as pool:
            pending = deque()
            it = reader()
            for item in it:
                pending.append(pool.submit(mapper, item))
                if len(pending) >= window:
                    yield pending.popleft().result()
            while pending:
                yield pending.popleft().result()

    return rd


def multiprocess_reader(readers, use_pipe=True, queue_size=1000):
    """ref: paddle.reader.multiprocess_reader — here a sequential chain
    (the heavy-worker input path is io.DataLoader's process pool)."""
    return chain(*readers)
