"""Global RNG management.

Paddle exposes a global seed (`paddle.seed`, ref:
python/paddle/framework/random.py); jax is functional with explicit PRNG
keys. Bridge: a process-global key that is split on every draw *in eager
code* (module init, data pipeline). Inside jit-traced code, layers carry
their own key leaves (see nn.Layer rng handling) so tracing stays pure.
"""
from __future__ import annotations

import threading

import jax

_state = threading.local()


def _get_key():
    if not hasattr(_state, 'key'):
        _state.key = jax.random.PRNGKey(0)
    return _state.key


def seed(s: int):
    """Set the global seed (ref: paddle.seed)."""
    _state.key = jax.random.PRNGKey(int(s))
    return s


def split_key(num: int = 1):
    """Draw `num` fresh keys from the global stream (eager only)."""
    keys = jax.random.split(_get_key(), num + 1)
    _state.key = keys[0]
    if num == 1:
        return keys[1]
    return list(keys[1:])


def get_rng_state():
    return _get_key()


def set_rng_state(key):
    _state.key = key
