"""Model-pytree utilities: filtered partition/merge for autograd, and
sharding-spec extraction for pjit.

Paddle's autograd engine walks a C++ tape and only accumulates grads for
``stop_gradient=False`` tensors (ref: paddle/fluid/eager/backward.cc).
Here the equivalent is structural: split the model pytree into trainable
and frozen halves, differentiate w.r.t. the trainable half, merge back.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def _is_layer(x):
    from ..nn.layer.base import Layer

    return isinstance(Layer, type) and isinstance(x, Layer)


def _map_model(obj, fn, path=''):
    """Recursively copy a model-ish pytree, applying ``fn(meta, path, leaf)``
    to each array leaf. ``meta`` is the owning Layer's _Meta (or None for
    arrays outside any Layer). Layers are shallow-copied via pytree
    unflatten so originals are untouched."""
    from ..nn.layer.base import Layer, _META_BUFFER

    if isinstance(obj, Layer):
        cls = type(obj)
        new = object.__new__(cls)
        new.__dict__.update(
            {
                k: v
                for k, v in obj.__dict__.items()
                if not (isinstance(v, (jax.Array,)) or isinstance(v, Layer) or _is_arr(v))
            }
        )
        new.__dict__['_param_meta'] = dict(obj._param_meta)
        for name, child in obj._children():
            p = f"{path}.{name}" if path else name
            if isinstance(child, Layer):
                new.__dict__[name] = _map_model(child, fn, p)
            else:
                meta = obj._param_meta.get(name, _META_BUFFER)
                new.__dict__[name] = fn(meta, p, child)
        return new
    if isinstance(obj, dict):
        return {k: _map_model(v, fn, f"{path}.{k}" if path else str(k)) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = [_map_model(v, fn, f"{path}.{i}") for i, v in enumerate(obj)]
        return type(obj)(t) if not hasattr(obj, '_fields') else type(obj)(*t)
    if _is_arr(obj):
        return fn(None, path, obj)
    return obj


def _is_arr(v):
    import numpy as np

    return isinstance(v, (jax.Array, np.ndarray))


def _trainable(meta):
    return meta is not None and meta.kind == 'param' and meta.trainable


def split_trainable(model):
    """Return (trainable, frozen): two model-shaped copies where the
    complementary leaves are None (an empty pytree node for jax)."""
    t = _map_model(model, lambda m, p, x: x if _trainable(m) else None)
    f = _map_model(model, lambda m, p, x: None if _trainable(m) else x)
    return t, f


def merge(a, b):
    """Merge two same-structure partitions (None leaves filled from the other)."""
    from ..nn.layer.base import Layer

    if isinstance(a, Layer) and isinstance(b, Layer):
        cls = type(a)
        new = object.__new__(cls)
        new.__dict__.update({k: v for k, v in a.__dict__.items()})
        new.__dict__['_param_meta'] = dict(a._param_meta)
        names = {n for n, _ in a._children()} | {n for n, _ in b._children()}
        for name in names:
            va = a.__dict__.get(name)
            vb = b.__dict__.get(name)
            if isinstance(va, Layer) or isinstance(vb, Layer):
                new.__dict__[name] = merge(va, vb)
            else:
                new.__dict__[name] = va if va is not None else vb
        return new
    if a is None:
        return b
    if b is None:
        return a
    if isinstance(a, dict):
        return {k: merge(a[k], b[k]) for k in a}
    if isinstance(a, (list, tuple)):
        t = [merge(x, y) for x, y in zip(a, b)]
        return type(a)(t) if not hasattr(a, '_fields') else type(a)(*t)
    return a


def leaves_with_meta(model, path=''):
    """Yield (path, meta, leaf) for every array leaf in the model."""
    out = []

    def fn(meta, p, x):
        out.append((p, meta, x))
        return x

    _map_model(model, fn, path)
    return out


def spec_tree(model, default=None):
    """Model-shaped pytree of PartitionSpecs from parameter metadata
    (used by distributed.parallelize to build pjit shardings)."""
    from jax.sharding import PartitionSpec as P

    rep = P() if default is None else default
    return _map_model(model, lambda m, p, x: (m.spec if (m and m.spec is not None) else rep))


def tree_cast(tree, dtype, floating_only=True):
    def cast(x):
        if x is None:
            return x
        if floating_only and not (
            jnp.issubdtype(x.dtype, jnp.floating) or x.dtype == jnp.bfloat16
        ):
            return x
        return x.astype(dtype)

    return jax.tree.map(cast, tree)


def global_norm(tree):
    leaves = [jnp.sum(jnp.square(x.astype(jnp.float32))) for x in jax.tree.leaves(tree)]
    return jnp.sqrt(sum(leaves)) if leaves else jnp.array(0.0)
