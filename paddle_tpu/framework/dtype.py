"""Dtype system.

Paddle-style dtype names mapped onto jax/numpy dtypes
(ref: python/paddle/framework/dtype.py). TPU-first: bfloat16 is the
preferred low-precision compute dtype; float32 is the default.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

bool_ = jnp.bool_
uint8 = jnp.uint8
int8 = jnp.int8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int64
float16 = jnp.float16
bfloat16 = jnp.bfloat16
float32 = jnp.float32
float64 = jnp.float64
complex64 = jnp.complex64
complex128 = jnp.complex128
try:  # fp8 for quantized matmul paths (TPU v5+)
    float8_e4m3 = jnp.float8_e4m3fn
    float8_e5m2 = jnp.float8_e5m2
except AttributeError:  # pragma: no cover
    float8_e4m3 = None
    float8_e5m2 = None

_STR2DTYPE = {
    'bool': bool_,
    'uint8': uint8,
    'int8': int8,
    'int16': int16,
    'int32': int32,
    'int64': int64,
    'float16': float16,
    'fp16': float16,
    'bfloat16': bfloat16,
    'bf16': bfloat16,
    'float32': float32,
    'fp32': float32,
    'float64': float64,
    'fp64': float64,
    'complex64': complex64,
    'complex128': complex128,
}

_default_dtype = [float32]


def convert_dtype(dtype):
    """Normalise a dtype-ish value (str | np.dtype | jnp dtype) to a numpy dtype."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        if dtype not in _STR2DTYPE:
            raise ValueError(f"unknown dtype {dtype!r}")
        return np.dtype(_STR2DTYPE[dtype])
    return np.dtype(dtype)


def set_default_dtype(dtype):
    """ref: paddle.set_default_dtype (python/paddle/framework/framework.py)."""
    _default_dtype[0] = convert_dtype(dtype)


def get_default_dtype():
    return np.dtype(_default_dtype[0])


def is_floating_point(dtype):
    return np.issubdtype(convert_dtype(dtype), np.floating) or convert_dtype(
        dtype
    ) == np.dtype(bfloat16)


def is_integer(dtype):
    return np.issubdtype(convert_dtype(dtype), np.integer)


def finfo(dtype):
    return jnp.finfo(convert_dtype(dtype))


def iinfo(dtype):
    return jnp.iinfo(convert_dtype(dtype))
