"""Serialization: paddle_tpu.save / load (ref: python/paddle/framework/io.py).

State dicts (flat name->array) and nested pytrees are stored as .npz
with a JSON treedef sidecar entry — no pickle, portable, atomic write.
"""
from __future__ import annotations

import json
import os
import tempfile

import jax
import jax.numpy as jnp
import numpy as np


def _flatten_state(obj, prefix=''):
    out = {}
    if isinstance(obj, dict):
        items = obj.items()
    elif isinstance(obj, (list, tuple)):
        items = ((str(i), v) for i, v in enumerate(obj))
    else:
        from ..nn.layer.base import Layer

        if isinstance(obj, Layer):
            return _flatten_state(obj.state_dict(), prefix)
        out[prefix or 'value'] = np.asarray(obj)
        return out
    for k, v in items:
        path = f"{prefix}/{k}" if prefix else str(k)
        if isinstance(v, (dict, list, tuple)):
            out.update(_flatten_state(v, path))
        elif v is None:
            out[path + '#none'] = np.zeros(0)
        elif np.isscalar(v) or isinstance(v, (jax.Array, np.ndarray)):
            out[path] = np.asarray(v)
        else:
            out[path + '#json'] = np.frombuffer(
                json.dumps(v).encode(), dtype=np.uint8
            ).copy()
    return out


def save(obj, path, protocol=None):
    """ref: paddle.save. Atomic: writes tmp then renames."""
    path = str(path)
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    flat = _flatten_state(obj)
    structure = {
        'format': 'paddle_tpu.v1',
        'kind': type(obj).__name__,
    }
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(os.path.abspath(path)))
    os.close(fd)
    try:
        np.savez(tmp, __meta__=np.frombuffer(json.dumps(structure).encode(), dtype=np.uint8), **flat)
        os.replace(tmp + '.npz' if os.path.exists(tmp + '.npz') else tmp, path)
    finally:
        for t in (tmp, tmp + '.npz'):
            if os.path.exists(t):
                os.remove(t)


def load(path, return_numpy=False):
    """ref: paddle.load. Returns nested dict of arrays."""
    data = np.load(str(path), allow_pickle=False)
    out = {}
    for key in data.files:
        if key == '__meta__':
            continue
        v = data[key]
        if key.endswith('#none'):
            key, v = key[:-5], None
        elif key.endswith('#json'):
            key, v = key[:-5], json.loads(v.tobytes().decode())
        elif not return_numpy and isinstance(v, np.ndarray):
            if v.dtype != object:
                v = jnp.asarray(v)
        _insert(out, key.split('/'), v)
    if list(out.keys()) == ['value']:
        return out['value']
    return out


def _insert(d, parts, v):
    for p in parts[:-1]:
        d = d.setdefault(p, {})
    d[parts[-1]] = v
