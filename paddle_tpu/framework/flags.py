"""Global flags (ref: paddle.set_flags / get_flags over FLAGS_* env).

Known flags map to jax config / XLA behaviour where a TPU equivalent
exists; unknown FLAGS_* are stored and readable (many reference flags
are CUDA-specific and intentionally inert here).
"""
from __future__ import annotations

import typing

_flags: typing.Dict[str, typing.Any] = {
    'FLAGS_cudnn_deterministic': False,
    'FLAGS_embedding_deterministic': 0,
    'FLAGS_check_nan_inf': False,
    'FLAGS_use_pallas_kernels': True,
    # make a failing pallas kernel raise instead of silently taking the
    # (much slower) lax fallback
    'FLAGS_pallas_strict': False,
    'FLAGS_default_dtype': 'float32',
}


def set_flags(flags: dict):
    """ref: paddle.set_flags."""
    import jax

    for k, v in flags.items():
        _flags[k] = v
        if k == 'FLAGS_cudnn_deterministic' and v:
            # TPU analogue: make XLA reductions deterministic
            jax.config.update('jax_default_matmul_precision', 'highest')
        if k == 'FLAGS_check_nan_inf':
            jax.config.update('jax_debug_nans', bool(v))


def get_flags(keys):
    """ref: paddle.get_flags."""
    if isinstance(keys, str):
        keys = [keys]
    return {k: _flags.get(k) for k in keys}
