from . import dtype, random, tree  # noqa: F401
from .dtype import (  # noqa: F401
    convert_dtype,
    get_default_dtype,
    set_default_dtype,
)
from .random import get_rng_state, seed, set_rng_state  # noqa: F401
