"""API-compatibility helpers (ref: python/paddle/base/framework.py,
base/param_attr.py, jit/api.py::LazyGuard and friends).

These exist so reference training scripts import-and-run unchanged.
Static/dynamic mode is a no-op distinction here: everything traces
through jax, so "dynamic mode" is always truthful and `enable_static`
only flips a flag that `in_dynamic_mode` reports.
"""
from __future__ import annotations

import contextlib

import numpy as np

_static_mode = [False]


def enable_static():
    """ref: paddle.enable_static. Graph capture in this framework is
    `jit.to_static` (jax tracing); this flag only tracks intent."""
    _static_mode[0] = True


def disable_static():
    _static_mode[0] = False


def in_dynamic_mode():
    return not _static_mode[0]


def disable_signal_handler():
    """ref: paddle.disable_signal_handler — CUDA-runtime concern; no-op."""


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """ref: paddle.set_printoptions — arrays print through numpy."""
    kw = {}
    if precision is not None:
        kw['precision'] = precision
    if threshold is not None:
        kw['threshold'] = threshold
    if edgeitems is not None:
        kw['edgeitems'] = edgeitems
    if linewidth is not None:
        kw['linewidth'] = linewidth
    if sci_mode is not None:
        kw['suppress'] = not sci_mode
    np.set_printoptions(**kw)


class ParamAttr:
    """ref: paddle.ParamAttr — bundles initializer/regularizer/lr for a
    parameter; Layer.create_parameter unwraps `.initializer`."""

    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, do_model_average=True,
                 need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip


class LazyGuard:
    """ref: paddle.LazyGuard — defers parameter init in the reference.
    Initialization here is already lazy-at-trace (pure functions of PRNG
    keys), so the guard is a transparent context."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def batch(reader, batch_size, drop_last=False):
    """ref: paddle.batch — wrap a sample reader into a batch reader
    (legacy io API kept for script compatibility)."""

    def batched():
        buf = []
        for sample in reader():
            buf.append(sample)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    return batched


def check_shape(shape):
    """ref: paddle.static.check_shape — validate a shape declaration."""
    if isinstance(shape, (list, tuple)):
        for s in shape:
            if s is not None and not isinstance(s, int):
                raise TypeError(f'shape entries must be int/None, got {s!r}')
    return shape


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    """ref: paddle.create_parameter (static-graph helper): a free
    Parameter outside any Layer."""
    from ..nn import initializer as I
    from ..nn.layer.base import Parameter

    init = default_initializer
    if init is None and attr is not None and getattr(attr, 'initializer', None):
        init = attr.initializer
    if init is None:
        init = I.Constant(0.0) if is_bias else I.XavierNormal()
    from . import dtype as dtype_mod

    dt = dtype_mod.convert_dtype(dtype) or dtype_mod.get_default_dtype()
    return Parameter(init(tuple(shape), dt))


def get_cuda_rng_state():
    """CUDA-API compat: returns the framework PRNG state (the TPU/JAX
    analogue — one threaded key, not a per-device CUDA state vector)."""
    from .random import get_rng_state

    return [get_rng_state()]


def set_cuda_rng_state(state):
    from .random import set_rng_state

    if isinstance(state, (list, tuple)) and state:
        state = state[0]
    set_rng_state(state)


@contextlib.contextmanager
def set_grad_enabled(mode):
    """ref: paddle.set_grad_enabled."""
    from ..autograd import _grad_enabled

    _grad_enabled.append(bool(mode))
    try:
        yield
    finally:
        _grad_enabled.pop()
