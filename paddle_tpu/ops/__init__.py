"""Hand-written TPU kernels (pallas) with lax fallbacks.

Dispatch policy: pallas kernels on TPU backends, pure-lax reference
implementations elsewhere (CPU tests) — same math, verified against each
other in tests/test_pallas.py.
"""
from __future__ import annotations

import jax


def _on_tpu():
    return jax.default_backend() not in ('cpu',)


def rms_norm(x, weight=None, epsilon=1e-6):
    """Fused RMSNorm; pallas kernel on TPU (ops/pallas/rms_norm.py)."""
    if _on_tpu() and x.shape[-1] % 128 == 0 and x.dtype != jax.numpy.float64:
        try:
            from .pallas.rms_norm import rms_norm as _k

            return _k(x, weight, epsilon)
        except Exception:
            pass
    from ..nn.functional.norm import rms_norm as _ref

    return _ref(x, weight, epsilon)
