"""Hand-written TPU kernels (pallas) with lax fallbacks.

Dispatch policy: pallas kernels on TPU backends, pure-lax reference
implementations elsewhere (CPU tests) — same math, verified against each
other in tests/test_pallas.py.
"""
from __future__ import annotations

import jax


def _on_tpu():
    # TPU only: pallas-in-interpret on other accelerators is orders of
    # magnitude slower than the lax fallback
    return jax.default_backend() == 'tpu'


def use_pallas():
    """True when pallas fast paths should dispatch (TPU + flag on)."""
    from ..framework.flags import get_flags

    return _on_tpu() and get_flags(['FLAGS_use_pallas_kernels'])[
        'FLAGS_use_pallas_kernels']


def rms_norm(x, weight=None, epsilon=1e-6):
    """Fused RMSNorm; pallas kernel on TPU (ops/pallas/rms_norm.py)."""
    if use_pallas() and x.shape[-1] % 128 == 0 and x.dtype != jax.numpy.float64:
        try:
            from .pallas.rms_norm import rms_norm as _k

            return _k(x, weight, epsilon)
        except Exception:
            pass
    from ..nn.functional.norm import rms_norm as _ref

    return _ref(x, weight, epsilon)


def softmax_cross_entropy(logits, labels):
    """Fused softmax-xent; pallas on TPU (ops/pallas/softmax_xent.py),
    lax reference elsewhere. Per-example nll, fp32."""
    import jax
    import jax.numpy as jnp

    # any vocab size: the kernel masks the padded tail block (the guard
    # only excludes degenerate tiny vocabs where tiling can't help)
    if use_pallas() and logits.shape[-1] >= 128:
        try:
            from .pallas.softmax_xent import softmax_cross_entropy_with_logits

            return softmax_cross_entropy_with_logits(logits, labels)
        except Exception:
            pass
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return -jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
