"""Hand-written TPU kernels (pallas) with lax fallbacks.

Dispatch policy: pallas kernels on TPU backends, pure-lax reference
implementations elsewhere (CPU tests) — same math, verified against each
other in tests/test_pallas.py.
"""
from __future__ import annotations

import warnings

import jax

_kernel_warned = set()


def _on_tpu():
    # TPU only: pallas-in-interpret on other accelerators is orders of
    # magnitude slower than the lax fallback
    return jax.default_backend() == 'tpu'


def use_pallas():
    """True when pallas fast paths should dispatch (TPU + flag on)."""
    from ..framework.flags import get_flags

    return _on_tpu() and get_flags(['FLAGS_use_pallas_kernels'])[
        'FLAGS_use_pallas_kernels']


def pallas_failed(kernel_name, exc):
    """A pallas kernel raised while use_pallas() was true.

    Strict mode (``FLAGS_pallas_strict``) re-raises — a broken kernel is
    a perf cliff that should fail loudly in CI. Otherwise warn ONCE per
    kernel and let the caller fall back to the lax reference.
    """
    from ..framework.flags import get_flags

    if get_flags(['FLAGS_pallas_strict'])['FLAGS_pallas_strict']:
        raise RuntimeError(
            f'pallas kernel {kernel_name!r} failed and FLAGS_pallas_strict '
            f'is set (lax fallback suppressed): {exc!r}'
        ) from exc
    if kernel_name not in _kernel_warned:
        _kernel_warned.add(kernel_name)
        warnings.warn(
            f'pallas kernel {kernel_name!r} failed ({exc!r}); falling back '
            f'to the lax reference implementation. This is a large perf '
            f'cliff on TPU — set FLAGS_pallas_strict=True to make it fatal.',
            stacklevel=3,
        )


def rms_norm(x, weight=None, epsilon=1e-6):
    """Fused RMSNorm; pallas kernel on TPU (ops/pallas/rms_norm.py)."""
    if use_pallas() and x.shape[-1] % 128 == 0 and x.dtype != jax.numpy.float64:
        try:
            from .pallas.rms_norm import rms_norm as _k

            return _k(x, weight, epsilon)
        except Exception as e:
            pallas_failed('rms_norm', e)
    from ..nn.functional.norm import rms_norm as _ref

    return _ref(x, weight, epsilon)


def softmax_cross_entropy(logits, labels):
    """Fused softmax-xent; pallas on TPU (ops/pallas/softmax_xent.py),
    lax reference elsewhere. Per-example nll, fp32."""
    import jax
    import jax.numpy as jnp

    # any vocab size: the kernel masks the padded tail block (the guard
    # only excludes degenerate tiny vocabs where tiling can't help)
    if use_pallas() and logits.shape[-1] >= 128:
        try:
            from .pallas.softmax_xent import softmax_cross_entropy_with_logits

            return softmax_cross_entropy_with_logits(logits, labels)
        except Exception as e:
            pallas_failed('softmax_cross_entropy', e)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return -jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
