"""Pallas TPU kernels (SURVEY §2.12).

Each kernel ships a lax reference implementation and is verified
against it in tests (interpret mode on CPU).
"""
