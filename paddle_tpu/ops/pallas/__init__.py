"""Pallas TPU kernels (SURVEY §2.12).

Each kernel ships a lax reference implementation and is verified
against it in tests (interpret mode on CPU) — and, because interpret
green does not imply Mosaic-legality, every kernel entry point in this
package MUST also be registered in `paddle_tpu.analysis.mosaic.registry`
with its bench-representative shape suites.  mosaiclint
(docs/mosaiclint.md) abstract-evals those suites in tier-1 and
enforces the TPU lowering rules (tile alignment, tail masking, VMEM
budget, ...); `tests/test_mosaiclint.py::TestMeta` fails if a module
here has no registry entry, so a new kernel cannot land unanalyzed.
"""


def interpret_mode():
    """Shared dispatch predicate: pallas kernels run natively only on
    TPU backends; everywhere else (CPU tests) use interpret mode.

    mosaiclint's `force_tpu_variant()` patches this to False while
    TRACING (never lowering) so block-size policies take their TPU
    branch during static analysis — keep any new dispatch decisions
    routed through here for the same reason.
    """
    import jax

    return jax.default_backend() not in ('tpu',)
