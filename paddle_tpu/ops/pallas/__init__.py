"""Pallas TPU kernels (SURVEY §2.12).

Each kernel ships a lax reference implementation and is verified
against it in tests (interpret mode on CPU).
"""


def interpret_mode():
    """Shared dispatch predicate: pallas kernels run natively only on
    TPU backends; everywhere else (CPU tests) use interpret mode."""
    import jax

    return jax.default_backend() not in ('tpu',)
