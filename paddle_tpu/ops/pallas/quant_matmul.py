"""Int8 weight-only quantized matmul (pallas, TPU).

ref (capability): python/paddle/quantization + the reference's
weight_only_linear fused kernels (paddle/phi/kernels/fusion/gpu/
weight_only_linear_kernel.cu). Weights stored int8 with per-column
fp32 scales; the kernel dequantises tiles in VMEM right before the
MXU dot, so HBM traffic is halved vs bf16 weights.

Off-TPU, quant_matmul/quant_matmul_int4 dispatch to a native-XLA
equivalent (_quant_matmul_xla) instead of the pallas interpreter: the
math is identical (f32 dot over raw codes, per-column scale on the
accumulator) but it runs at XLA-CPU matmul speed, so quantized serving
benches on dev boxes measure the model, not the interpreter.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _interpret():
    from . import interpret_mode

    return interpret_mode()


def quantize_weight(w, axis=0):
    """fp weight (K, N) → (int8 weight, fp32 per-output-column scale)."""
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=axis, keepdims=True)
    scale = jnp.maximum(amax / 127.0, 1e-8)
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale), -127, 127)
    return q.astype(jnp.int8), scale.reshape(-1)


FP8_MAX = {jnp.float8_e4m3fn: 448.0, jnp.float8_e5m2: 57344.0}


def quantize_weight_fp8(w, axis=0, dtype=jnp.float8_e4m3fn):
    """fp weight (K, N) → (fp8 weight, fp32 per-output-column scale).

    Closes SURVEY §2.6/§2.12 fp8 stretch: same kernel as int8 (the
    dequant is an `astype` in VMEM), fp8 keeps ~2 decimal digits of
    mantissa where int8 keeps uniform steps — better for outlier-heavy
    weights; HBM traffic is halved vs bf16 either way.
    """
    fmax = FP8_MAX[dtype]
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=axis, keepdims=True)
    scale = jnp.maximum(amax / fmax, 1e-12)
    q = (w.astype(jnp.float32) / scale).astype(dtype)
    return q, scale.reshape(-1)


def quantize_weight_int4(w, axis=0):
    """fp weight (K, N) → (packed int4 weight (⌈K/2⌉, N) int8, scale).

    Two 4-bit codes per byte along K (row 2r in the low nibble, 2r+1 in
    the high nibble) — HALF the HBM traffic of the int8 path; the kernel
    sign-extends both nibbles in VMEM right before the MXU. Odd K pads
    one zero row.
    """
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=axis, keepdims=True)
    scale = jnp.maximum(amax / 7.0, 1e-8)
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale), -8, 7)
    q = q.astype(jnp.int8)
    if q.shape[0] % 2:
        q = jnp.concatenate([q, jnp.zeros((1, q.shape[1]), jnp.int8)], 0)
    lo = q[0::2].astype(jnp.uint8) & 0xF
    hi = (q[1::2].astype(jnp.uint8) & 0xF) << 4
    return (lo | hi).astype(jnp.int8), scale.reshape(-1)


def _unpack_int4(w8):
    """(bk/2, bn) packed int8 → (bk, bn) fp32 sign-extended codes."""
    lo = jnp.right_shift(jnp.left_shift(w8, 4), 4)       # arithmetic: sext
    hi = jnp.right_shift(w8, 4)
    half, bn = w8.shape
    return jnp.stack([lo, hi], axis=1).reshape(2 * half, bn).astype(
        jnp.float32)


def _kernel(x_ref, w_ref, s_ref, o_ref, acc, *, nk, bk, K, int4=False):
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _():
        acc[:] = jnp.zeros_like(acc)

    x = x_ref[:].astype(jnp.float32)                     # (bm, bk)
    if int4:
        w = _unpack_int4(w_ref[:])                       # (bk, bn) from bk/2
    else:
        w = w_ref[:].astype(jnp.float32)                 # (bk, bn) dequant in VMEM
    if K % bk:
        # tail K block: the padded x columns / w rows read unspecified
        # memory — zero them out of the accumulation
        kcol = k * bk + jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
        x = jnp.where(kcol < K, x, 0.0)
        krow = k * bk + jax.lax.broadcasted_iota(jnp.int32, w.shape, 0)
        w = jnp.where(krow < K, w, 0.0)
    acc[:] = acc[:] + jax.lax.dot_general(
        x, w, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(k == nk - 1)
    def _():
        o_ref[:] = (acc[:] * s_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


def _quant_matmul_xla(x, wq, scale, out_dtype):
    """Native-XLA path for non-TPU backends: the same math as _kernel
    (f32 dot over the raw codes, per-output-column scale applied to the
    accumulator) without the pallas interpreter, whose per-instruction
    emulation made the int8 DRAFT model slower than the bf16 target on
    CPU and sank the speculative-decode bench."""
    acc = jax.lax.dot_general(
        x.astype(jnp.float32), wq.astype(jnp.float32),
        (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
    return (acc * scale[None, :].astype(jnp.float32)).astype(out_dtype)


def quant_matmul(x, wq, scale, block_m=256, block_n=256, block_k=512,
                 out_dtype=None, interpret=None):
    """x: (M, K) fp; wq: (K, N) int8; scale: (N,) fp32 → (M, N).

    interpret=None (auto): pallas kernel on TPU, native XLA elsewhere.
    interpret=True forces the interpret-mode pallas kernel (kernel
    correctness tests)."""
    M, K = x.shape
    K2, N = wq.shape
    assert K == K2
    out_dtype = out_dtype or x.dtype
    if interpret is None and _interpret():
        return _quant_matmul_xla(x, wq, scale, out_dtype)
    bm, bn, bk = min(block_m, M), min(block_n, N), min(block_k, K)
    nk = pl.cdiv(K, bk)
    return pl.pallas_call(
        functools.partial(_kernel, nk=nk, bk=bk, K=K),
        grid=(pl.cdiv(M, bm), pl.cdiv(N, bn), nk),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, k: (i, k)),
            pl.BlockSpec((bk, bn), lambda i, j, k: (k, j)),
            pl.BlockSpec((1, bn), lambda i, j, k: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, k: (i, j)),
        out_shape=jax.ShapeDtypeStruct((M, N), out_dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        interpret=bool(interpret) or _interpret(),
    )(x, wq, scale.reshape(1, N))


def quant_matmul_int4(x, wq_packed, scale, block_m=256, block_n=256,
                      block_k=512, out_dtype=None, interpret=None):
    """x: (M, K) fp; wq_packed: (⌈K/2⌉, N) int8 (two int4 codes per
    byte along K); scale: (N,) fp32 → (M, N). interpret as in
    quant_matmul."""
    M, K = x.shape
    half, N = wq_packed.shape
    if half * 2 not in (K, K + 1):
        raise ValueError(
            f'packed int4 weight rows {half} do not match K={K}')
    out_dtype = out_dtype or x.dtype
    if K % 2:
        x = jnp.concatenate([x, jnp.zeros((M, 1), x.dtype)], axis=1)
        K = K + 1
    if interpret is None and _interpret():
        return _quant_matmul_xla(x, _unpack_int4(wq_packed), scale,
                                 out_dtype)
    bm, bn = min(block_m, M), min(block_n, N)
    bk = min(block_k, K)
    bk = bk + (bk % 2)                                   # even K blocks
    nk = pl.cdiv(K, bk)
    return pl.pallas_call(
        functools.partial(_kernel, nk=nk, bk=bk, K=K, int4=True),
        grid=(pl.cdiv(M, bm), pl.cdiv(N, bn), nk),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, k: (i, k)),
            pl.BlockSpec((bk // 2, bn), lambda i, j, k: (k, j)),
            pl.BlockSpec((1, bn), lambda i, j, k: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, k: (i, j)),
        out_shape=jax.ShapeDtypeStruct((M, N), out_dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        interpret=bool(interpret) or _interpret(),
    )(x, wq_packed, scale.reshape(1, N))


def weight_only_linear(x, wq, scale, bias=None, weight_dtype='int8'):
    """ref: paddle.nn.quant.weight_only_linear. x: (..., K)."""
    K = x.shape[-1]
    lead = x.shape[:-1]
    mm = quant_matmul_int4 if weight_dtype == 'int4' else quant_matmul
    out = mm(x.reshape(-1, K), wq, scale)
    out = out.reshape(*lead, -1)
    if bias is not None:
        out = out + bias
    return out
