"""Decode attention (pallas, TPU): single-token query vs a long KV cache.

ref (capability): the reference inference stack's fused decode/masked
multi-head attention (paddle/phi fused attention kernels used by the
generation loop). The XLA fallback path materialises q·Kᵀ, the mask,
softmax, and the V contraction as separate HBM round trips; this kernel
streams K and V exactly once per step — the whole op is
memory-bandwidth-bound, so one fused pass is the ceiling.

Layout: q (B, 1, Hq, D) against the cache's NATIVE (B, S, Hkv, D)
layout — no per-step transpose of the (large) cache. GQA: all
``group = Hq // Hkv`` query heads of one kv head are processed together
so K/V blocks are read once per kv head. Inference-only (no VJP).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_S = 1024
NEG_INF = -1e30


def _interpret():
    from . import interpret_mode

    return interpret_mode()


def _decode_kernel(q_ref, k_ref, v_ref, kv_ref, o_ref, acc, m_scr, l_scr,
                   *, scale, ns, bs, S):
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _():
        acc[:] = jnp.zeros_like(acc)
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    q = q_ref[0, 0].astype(jnp.float32)                 # (G, D)
    k = k_ref[0, :, 0].astype(jnp.float32)              # (bs, D)
    v = v_ref[0, :, 0].astype(jnp.float32)
    valid = kv_ref[0] > 0                               # (bs,)
    if S % bs != 0:
        # padded tail block reads unspecified memory: bound-mask from the
        # static S (the padded kvalid rows are themselves unspecified)
        kpos = j * bs + jax.lax.broadcasted_iota(jnp.int32, (bs,), 0)
        inb = kpos < S
        valid = valid & inb
        v = jnp.where(inb[:, None], v, 0.0)

    s = jax.lax.dot_general(q * scale, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)  # (G, bs)
    s = jnp.where(valid[None, :], s, NEG_INF)

    m_prev = m_scr[:, 0]                                # (G,)
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
    p = jnp.where(valid[None, :], jnp.exp(s - m_new[:, None]), 0.0)
    alpha = jnp.exp(m_prev - m_new)
    l_new = l_scr[:, 0] * alpha + jnp.sum(p, axis=-1)
    acc[:] = acc[:] * alpha[:, None] + jax.lax.dot_general(
        p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
    m_scr[:] = jnp.broadcast_to(m_new[:, None], m_scr.shape)
    l_scr[:] = jnp.broadcast_to(l_new[:, None], l_scr.shape)

    @pl.when(j == ns - 1)
    def _():
        safe = jnp.maximum(l_scr[:, 0], 1e-30)
        o_ref[0, 0] = (acc[:] / safe[:, None]).astype(o_ref.dtype)


def decode_attention(q, k_cache, v_cache, valid_len, scale=None,
                     block_s=DEFAULT_BLOCK_S):
    """One fused decode-attention step.

    q: (B, 1, Hq, D); k_cache/v_cache: (B, S, Hkv, D) in cache-native
    layout; valid_len: scalar or (B,) — number of cache positions the
    query may attend to (cache_index + 1). Returns (B, 1, Hq, D).
    """
    B, Sq, Hq, D = q.shape
    if Sq != 1:
        raise ValueError(f'decode_attention is single-token (Sq=1), got {Sq}')
    _, S, Hkv, _ = k_cache.shape
    if Hq % Hkv:
        raise ValueError(
            f'query heads ({Hq}) must be a multiple of kv heads ({Hkv})')
    group = Hq // Hkv
    scale = scale if scale is not None else 1.0 / (D ** 0.5)
    bs = min(block_s, S)
    ns = pl.cdiv(S, bs)

    # per-position validity: padded tail blocks fold into the same mask
    valid = jnp.reshape(jnp.asarray(valid_len, jnp.int32), (-1, 1))
    kvalid = (jnp.arange(S)[None, :] < valid).astype(jnp.int32)
    kvalid = jnp.broadcast_to(kvalid, (B, S))

    # q as (B, 1, Hkv*group, D): kv head h owns q-head rows [h*group, ...)
    kernel = functools.partial(_decode_kernel, scale=scale, ns=ns, bs=bs,
                               S=S)
    out = pl.pallas_call(
        kernel,
        grid=(B, Hkv, ns),
        in_specs=[
            pl.BlockSpec((1, 1, group, D), lambda b, h, j: (b, 0, h, 0)),
            pl.BlockSpec((1, bs, 1, D), lambda b, h, j: (b, j, h, 0)),
            pl.BlockSpec((1, bs, 1, D), lambda b, h, j: (b, j, h, 0)),
            pl.BlockSpec((1, bs), lambda b, h, j: (b, j)),
        ],
        out_specs=pl.BlockSpec((1, 1, group, D), lambda b, h, j: (b, 0, h, 0)),
        out_shape=jax.ShapeDtypeStruct((B, 1, Hq, D), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((group, D), jnp.float32),
            pltpu.VMEM((group, 128), jnp.float32),
            pltpu.VMEM((group, 128), jnp.float32),
        ],
        interpret=_interpret(),
    )(q, k_cache, v_cache, kvalid)
    return out
