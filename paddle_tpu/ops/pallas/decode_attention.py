"""Decode attention (pallas, TPU): single-token query vs a long KV cache.

ref (capability): the reference inference stack's fused decode/masked
multi-head attention (paddle/phi fused attention kernels used by the
generation loop). The XLA fallback path materialises q·Kᵀ, the mask,
softmax, and the V contraction as separate HBM round trips; this kernel
streams K and V exactly once per step — the whole op is
memory-bandwidth-bound, so one fused pass is the ceiling.

Layout: q (B, 1, Hq, D) against the cache's NATIVE (B, S, Hkv, D)
layout — no per-step transpose of the (large) cache. Blocks keep the
full head dim (Mosaic requires the trailing two block dims to equal the
array dims or tile evenly; per-head size-1 blocks are illegal), so GQA
is handled by a head-match mask on a dense (Hq, bs·Hkv) score matrix:
query row i may only attend columns whose kv head h == i // group.
The mask multiplies score-matmul FLOPs by Hkv, but decode is
HBM-bandwidth-bound — the MXU time stays far under the K/V stream time.
Inference-only (no VJP).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_S = 1024
# K and V blocks are (bs, Hkv, D) in VMEM; cap each at ~2 MiB so the
# kernel fits comfortably alongside scores + scratch at any head count.
VMEM_BLOCK_BUDGET = 2 * 1024 * 1024
NEG_INF = -1e30


def _interpret():
    from . import interpret_mode

    return interpret_mode()


def _decode_kernel(vl_ref, st_ref, q_ref, k_ref, v_ref, o_ref, acc, m_scr,
                   l_scr, *, scale, ns, bs, hkv, group):
    _decode_kernel_body(vl_ref, st_ref, q_ref, k_ref, v_ref, None, None,
                        o_ref, acc, m_scr, l_scr, scale=scale, ns=ns, bs=bs,
                        hkv=hkv, group=group)


def _decode_kernel_q8(vl_ref, st_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref,
                      o_ref, acc, m_scr, l_scr, *, scale, ns, bs, hkv, group):
    _decode_kernel_body(vl_ref, st_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref,
                        o_ref, acc, m_scr, l_scr, scale=scale, ns=ns, bs=bs,
                        hkv=hkv, group=group)


def _decode_kernel_body(vl_ref, st_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref,
                        o_ref, acc, m_scr, l_scr, *, scale, ns, bs, hkv,
                        group):
    b = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        acc[:] = jnp.zeros_like(acc)
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    hq = group * hkv
    cols = bs * hkv
    D = q_ref.shape[-1]
    q = q_ref[0, 0].astype(jnp.float32)                 # (Hq, D)
    # rows r = s*hkv + h: cache position r // hkv, kv head r % hkv.
    # Cache-KV int8: dequantize in VMEM with per-(head, dim) scales —
    # the multiply rides the (bs, hkv, D) layout BEFORE the same
    # major-dim collapse the fp path already uses (Mosaic-legal on
    # chip), so the HBM stream is half-width but the math is identical.
    k = k_ref[0].astype(jnp.float32)
    v = v_ref[0].astype(jnp.float32)
    if ks_ref is not None:
        k = k * ks_ref[...][None]                       # (hkv, D) scales
        v = v * vs_ref[...][None]
    k = k.reshape(cols, D)
    v = v.reshape(cols, D)
    # Validity comes in as a scalar count (SMEM prefetch) and every mask
    # is built from 2-D iota in its final shape: Mosaic cannot reshape or
    # minor-dim-broadcast i1 (or lane-misaligned i32) vectors, so no mask
    # array ever changes rank. Column c's global cache position is
    # j*bs + c//hkv; positions >= count (incl. the padded tail block's
    # unspecified memory) are masked out of the scores, and V is zeroed
    # there so garbage (inf/nan bit patterns) cannot reach the matmul.
    count = vl_ref[b]
    # per-row window start (left-padded batches: rows [0, start) are pad
    # holes) — same scalar-prefetch + 2-D-iota mechanism as the validity
    # count, so Mosaic legality is unchanged
    start = st_ref[b]
    vpos = j * bs + jax.lax.broadcasted_iota(jnp.int32, (cols, D), 0) // hkv
    v = jnp.where((vpos < count) & (vpos >= start), v, 0.0)
    rowh = jax.lax.broadcasted_iota(jnp.int32, (hq, cols), 0) // group
    colh = jax.lax.broadcasted_iota(jnp.int32, (hq, cols), 1) % hkv
    colp = j * bs + jax.lax.broadcasted_iota(jnp.int32, (hq, cols), 1) // hkv
    keep = (rowh == colh) & (colp < count) & (colp >= start)

    s = jax.lax.dot_general(q * scale, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)  # (Hq, cols)
    s = jnp.where(keep, s, NEG_INF)

    m_prev = m_scr[:, 0]                                # (Hq,)
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
    p = jnp.where(keep, jnp.exp(s - m_new[:, None]), 0.0)
    alpha = jnp.exp(m_prev - m_new)
    l_new = l_scr[:, 0] * alpha + jnp.sum(p, axis=-1)
    acc[:] = acc[:] * alpha[:, None] + jax.lax.dot_general(
        p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
    m_scr[:] = jnp.broadcast_to(m_new[:, None], m_scr.shape)
    l_scr[:] = jnp.broadcast_to(l_new[:, None], l_scr.shape)

    @pl.when(j == ns - 1)
    def _():
        safe = jnp.maximum(l_scr[:, 0], 1e-30)
        o_ref[0, 0] = (acc[:] / safe[:, None]).astype(o_ref.dtype)


def _pick_block(block_s, S, hkv, D, itemsize, interpret):
    """Block length along the cache axis: VMEM-bounded; on real TPU kept
    a multiple of 128 so the flattened (bs·hkv, D) K/V views stay
    sublane-aligned for Mosaic's layout inference."""
    # one cache position, all heads. int8 caches budget as if 2-byte: the
    # kernel dequantizes each block to f32 in VMEM, so the in-VMEM
    # working set tracks the block LENGTH, not the stored width — using
    # the bf16-proven bs keeps the same footprint while the HBM stream
    # (the measured win) still halves.
    row_bytes = max(1, hkv * D * max(itemsize, 2))
    cap = max(1, VMEM_BLOCK_BUDGET // row_bytes)
    bs = min(block_s, S, max(cap, 128))
    if bs >= S:
        return S
    if interpret:
        return bs
    return min(max(128, bs // 128 * 128), S)


def dispatch_decode_attention(q, k_cache, v_cache, valid_len, start=None,
                              window=None, k_scale=None, v_scale=None,
                              scale=None, block_s=DEFAULT_BLOCK_S):
    """Single serving entry point for the fused decode step (used by the
    models' cached_attention — and through it model.generate and the
    DecodeEngine decode loop).

    Composes the sliding-window band into the per-row `start` offset
    (the kernel streams only the live band either way) and routes fp
    vs int8-cache calls: pass `k_scale`/`v_scale` for a quantized
    cache, leave them None for bf16/f32. Keeping the composition here
    means every caller applies the identical window rule."""
    vl = jnp.asarray(valid_len, jnp.int32)
    if window is not None:
        wstart = jnp.maximum(vl - window, 0)
        start = (wstart if start is None
                 else jnp.maximum(jnp.asarray(start, jnp.int32), wstart))
    return decode_attention(q, k_cache, v_cache, vl, scale=scale,
                            block_s=block_s, k_scale=k_scale,
                            v_scale=v_scale, start=start)


def decode_attention(q, k_cache, v_cache, valid_len, scale=None,
                     block_s=DEFAULT_BLOCK_S, k_scale=None, v_scale=None,
                     start=None):
    """One fused decode-attention step.

    q: (B, 1, Hq, D); k_cache/v_cache: (B, S, Hkv, D) in cache-native
    layout; valid_len: scalar or (B,) — number of cache positions the
    query may attend to (cache_index + 1). `start`: scalar or (B,) —
    first attendable cache position per row (left-padded batches put the
    pad hole at [0, start); default 0). Returns (B, 1, Hq, D).

    Cache-KV int8 (ref capability: the reference serving stack's
    cache-quantized block_multihead_attention —
    python/paddle/incubate/nn/functional/block_multihead_attention.py:44,60):
    pass int8 caches plus per-(kv-head, dim) f32 scales `k_scale`/
    `v_scale` of shape (Hkv, D); rows dequantize in VMEM after the
    half-width HBM stream — the binding term at batch >= 8.
    """
    B, Sq, Hq, D = q.shape
    if Sq != 1:
        raise ValueError(f'decode_attention is single-token (Sq=1), got {Sq}')
    _, S, Hkv, _ = k_cache.shape
    if Hq % Hkv:
        raise ValueError(
            f'query heads ({Hq}) must be a multiple of kv heads ({Hkv})')
    group = Hq // Hkv
    scale = scale if scale is not None else 1.0 / (D ** 0.5)
    interp = _interpret()
    bs = _pick_block(block_s, S, Hkv, D, k_cache.dtype.itemsize, interp)
    ns = pl.cdiv(S, bs)

    # per-batch valid count, scalar-prefetched to SMEM (no mask array);
    # clamped to S so an out-of-range count can never unmask the padded
    # tail block's unspecified memory
    vl = jnp.minimum(jnp.broadcast_to(
        jnp.reshape(jnp.asarray(valid_len, jnp.int32), (-1,)), (B,)), S)
    st = jnp.clip(jnp.broadcast_to(
        jnp.reshape(jnp.asarray(0 if start is None else start, jnp.int32),
                    (-1,)), (B,)), 0, S)

    quant = k_scale is not None
    in_specs = [
        pl.BlockSpec((1, 1, Hq, D), lambda b, j, vl, st: (b, 0, 0, 0)),
        pl.BlockSpec((1, bs, Hkv, D), lambda b, j, vl, st: (b, j, 0, 0)),
        pl.BlockSpec((1, bs, Hkv, D), lambda b, j, vl, st: (b, j, 0, 0)),
    ]
    args = [vl, st, q, k_cache, v_cache]
    if quant:
        kernel = functools.partial(_decode_kernel_q8, scale=scale, ns=ns,
                                   bs=bs, hkv=Hkv, group=group)
        # scales are tiny and constant across the grid: one full block
        in_specs += [pl.BlockSpec((Hkv, D), lambda b, j, vl, st: (0, 0))] * 2
        args += [k_scale.astype(jnp.float32), v_scale.astype(jnp.float32)]
    else:
        kernel = functools.partial(_decode_kernel, scale=scale, ns=ns, bs=bs,
                                   hkv=Hkv, group=group)
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B, ns),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, 1, Hq, D),
                                   lambda b, j, vl, st: (b, 0, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((Hq, D), jnp.float32),
                pltpu.VMEM((Hq, 128), jnp.float32),
                pltpu.VMEM((Hq, 128), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B, 1, Hq, D), q.dtype),
        interpret=interp,
    )(*args)
    return out
