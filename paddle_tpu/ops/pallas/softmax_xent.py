"""Fused softmax cross-entropy (pallas, TPU) with custom VJP.

ref (capability): the reference's c_softmax_with_cross_entropy /
softmax_with_cross_entropy fused kernels (paddle/phi/kernels/gpu/
c_softmax_with_cross_entropy_kernel.cu). One pass over the vocab per
row computes max / sum-exp / label logit together (no materialised
softmax); backward streams softmax-minus-onehot directly.

For a 'tp'-sharded vocab use `distributed.parallel_cross_entropy`
(GSPMD inserts the cross-shard max/sum); this kernel is the
single-shard fast path.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _interpret():
    from . import interpret_mode

    return interpret_mode()


def _fwd_kernel(x_ref, label_ref, loss_ref, lse_ref, m_scr, l_scr, p_scr, *,
                bv, nv, V):
    """grid (row_blocks, vocab_blocks); scratch persists across vocab steps."""
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        p_scr[:] = jnp.full_like(p_scr, NEG_INF)

    x = x_ref[:].astype(jnp.float32)                    # (br, bv)
    label = label_ref[:, 0]                             # (br,)
    br = x.shape[0]
    cols = j * bv + jax.lax.broadcasted_iota(jnp.int32, (br, x.shape[1]), 1)
    if V % bv:
        # tail vocab block: the padded columns read unspecified memory —
        # mask them out of the running max / sum-exp
        x = jnp.where(cols < V, x, NEG_INF)
    m_prev = m_scr[:, 0]
    m_new = jnp.maximum(m_prev, jnp.max(x, axis=-1))
    alpha = jnp.exp(m_prev - m_new)
    l_new = l_scr[:, 0] * alpha + jnp.sum(jnp.exp(x - m_new[:, None]), axis=-1)
    m_scr[:] = jnp.broadcast_to(m_new[:, None], m_scr.shape)
    l_scr[:] = jnp.broadcast_to(l_new[:, None], l_scr.shape)

    # pick this block's label logit if the label falls in [j*bv, (j+1)*bv)
    hit = cols == label[:, None]
    picked = jnp.max(jnp.where(hit, x, NEG_INF), axis=-1)
    p_scr[:] = jnp.maximum(p_scr[:], jnp.broadcast_to(picked[:, None],
                                                      p_scr.shape))

    @pl.when(j == nv - 1)
    def _():
        lse = m_scr[:, 0] + jnp.log(jnp.maximum(l_scr[:, 0], 1e-30))
        loss_ref[:, 0] = lse - p_scr[:, 0]
        lse_ref[:, 0] = lse


def _bwd_kernel(x_ref, label_ref, lse_ref, g_ref, dx_ref, *, bv, V):
    j = pl.program_id(1)
    x = x_ref[:].astype(jnp.float32)
    label = label_ref[:, 0]
    lse = lse_ref[:, 0]
    g = g_ref[:, 0]
    br = x.shape[0]
    cols = j * bv + jax.lax.broadcasted_iota(jnp.int32, (br, x.shape[1]), 1)
    p = jnp.exp(x - lse[:, None])                       # softmax block
    if V % bv:
        # tail block: exp(garbage) can be inf/nan — force dx=0 off-vocab
        p = jnp.where(cols < V, p, 0.0)
    onehot = (cols == label[:, None]).astype(jnp.float32)
    dx_ref[:] = ((p - onehot) * g[:, None]).astype(dx_ref.dtype)


def _block_sizes(R, V):
    bv = min(V, 2048)
    br = max(8, min(256, (1 << 21) // max(4 * bv, 1)))
    return min(br, R), bv


def _run_fwd(x2, labels):
    R, V = x2.shape
    br, bv = _block_sizes(R, V)
    loss, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, bv=bv, nv=pl.cdiv(V, bv), V=V),
        grid=(pl.cdiv(R, br), pl.cdiv(V, bv)),
        in_specs=[
            pl.BlockSpec((br, bv), lambda i, j: (i, j)),
            pl.BlockSpec((br, 1), lambda i, j: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((br, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((br, 1), lambda i, j: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((R, 1), jnp.float32),
            jax.ShapeDtypeStruct((R, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((br, 128), jnp.float32),
            pltpu.VMEM((br, 128), jnp.float32),
            pltpu.VMEM((br, 128), jnp.float32),
        ],
        interpret=_interpret(),
    )(x2, labels[:, None])
    return loss[:, 0], lse[:, 0]


@jax.custom_vjp
def _xent2d(x2, labels):
    loss, _ = _run_fwd(x2, labels)
    return loss


def _xent_fwd(x2, labels):
    loss, lse = _run_fwd(x2, labels)
    return loss, (x2, labels, lse)


def _xent_bwd(res, g):
    x2, labels, lse = res
    R, V = x2.shape
    br, bv = _block_sizes(R, V)
    dx = pl.pallas_call(
        functools.partial(_bwd_kernel, bv=bv, V=V),
        grid=(pl.cdiv(R, br), pl.cdiv(V, bv)),
        in_specs=[
            pl.BlockSpec((br, bv), lambda i, j: (i, j)),
            pl.BlockSpec((br, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((br, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((br, 1), lambda i, j: (i, 0)),
        ],
        out_specs=pl.BlockSpec((br, bv), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((R, V), x2.dtype),
        interpret=_interpret(),
    )(x2, labels[:, None], lse[:, None], g[:, None].astype(jnp.float32))
    return dx, None


_xent2d.defvjp(_xent_fwd, _xent_bwd)


def softmax_cross_entropy_with_logits(logits, labels):
    """logits: (..., V); labels: (...) int. Returns per-example nll (...)."""
    V = logits.shape[-1]
    shape = logits.shape[:-1]
    loss = _xent2d(logits.reshape(-1, V), labels.reshape(-1).astype(jnp.int32))
    return loss.reshape(shape)
