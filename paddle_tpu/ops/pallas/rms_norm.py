"""Fused RMSNorm (pallas, TPU) with custom VJP.

ref (capability): the reference's FusedRMSNorm
(paddle/phi/kernels/fusion/gpu/fused_rms_norm*). One pass over HBM for
the forward (XLA would otherwise materialise the normalised
intermediate when the weight multiply lands in a different fusion);
row-blocked over the flattened leading dims, feature dim resident in
VMEM. Backward computes dx in one fused kernel; dweight is a cross-row
reduction left to XLA (it fuses into the surrounding backward).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

def _block_rows(n_feat: int, n_rows: int) -> int:
    """Rows per block sized so the working set (~6 fp32 row-buffers:
    x, g, gw, out + copies) stays well under the 16MB VMEM budget."""
    target = (2 * 1024 * 1024) // max(4 * n_feat, 1)   # ~2MB per buffer
    rows = max(8, min(256, target))
    return min(rows, n_rows)


def _interpret():
    from . import interpret_mode

    return interpret_mode()


def _fwd_kernel(x_ref, w_ref, o_ref, r_ref, *, epsilon):
    x = x_ref[:].astype(jnp.float32)
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    r = jax.lax.rsqrt(var + epsilon)                      # (rows, 1)
    o_ref[:] = (x * r * w_ref[:].astype(jnp.float32)).astype(o_ref.dtype)
    r_ref[:] = r


def _dx_kernel(x_ref, w_ref, r_ref, g_ref, dx_ref, *, n_feat):
    x = x_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    w = w_ref[:].astype(jnp.float32)
    r = r_ref[:]                                          # (rows, 1)
    gw = g * w
    # dx = r*gw - x * r^3 * mean(gw * x)
    mean_gwx = jnp.mean(gw * x, axis=-1, keepdims=True)
    dx_ref[:] = (r * gw - x * (r * r * r) * mean_gwx).astype(dx_ref.dtype)


def _run_fwd(x2, w, epsilon, rows_blk):
    R, N = x2.shape
    grid = (pl.cdiv(R, rows_blk),)
    return pl.pallas_call(
        functools.partial(_fwd_kernel, epsilon=epsilon),
        grid=grid,
        in_specs=[
            pl.BlockSpec((rows_blk, N), lambda i: (i, 0)),
            pl.BlockSpec((N,), lambda i: (0,)),
        ],
        out_specs=[
            pl.BlockSpec((rows_blk, N), lambda i: (i, 0)),
            pl.BlockSpec((rows_blk, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((R, N), x2.dtype),
            jax.ShapeDtypeStruct((R, 1), jnp.float32),
        ],
        interpret=_interpret(),
    )(x2, w)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _rms_norm2d(x2, w, epsilon):
    out, _ = _run_fwd(x2, w, epsilon, _block_rows(x2.shape[1], x2.shape[0]))
    return out


def _rms_fwd(x2, w, epsilon):
    out, r = _run_fwd(x2, w, epsilon, _block_rows(x2.shape[1], x2.shape[0]))
    return out, (x2, w, r)


def _rms_bwd(epsilon, res, g):
    x2, w, r = res
    R, N = x2.shape
    rows_blk = _block_rows(N, R)
    dx = pl.pallas_call(
        functools.partial(_dx_kernel, n_feat=N),
        grid=(pl.cdiv(R, rows_blk),),
        in_specs=[
            pl.BlockSpec((rows_blk, N), lambda i: (i, 0)),
            pl.BlockSpec((N,), lambda i: (0,)),
            pl.BlockSpec((rows_blk, 1), lambda i: (i, 0)),
            pl.BlockSpec((rows_blk, N), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((rows_blk, N), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((R, N), x2.dtype),
        interpret=_interpret(),
    )(x2, w, r, g)
    # dw: cross-row reduction — XLA fuses this fine
    xf = x2.astype(jnp.float32)
    dw = jnp.sum(g.astype(jnp.float32) * xf * r, axis=0).astype(w.dtype)
    return dx, dw


_rms_norm2d.defvjp(_rms_fwd, _rms_bwd)


def rms_norm(x, weight=None, epsilon=1e-6):
    """x: (..., N). Fused on TPU; matches nn.functional.norm.rms_norm."""
    N = x.shape[-1]
    if weight is None:
        weight = jnp.ones((N,), x.dtype)
    shape = x.shape
    out = _rms_norm2d(x.reshape(-1, N), weight, float(epsilon))
    return out.reshape(shape)
