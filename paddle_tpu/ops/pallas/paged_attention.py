"""Paged (block-table) and head-major decode attention — pallas, TPU.

ref (capability): the reference serving stack's
`block_multihead_attention` paged-KV decode
(python/paddle/incubate/nn/functional/block_multihead_attention.py:30 —
CUDA kernels over [max_block_num, num_head, block_size, head_size]
pages) and `masked_multihead_attention` (contiguous
[2, B, num_head, max_seq, head_size] caches). TPU-native design: for
pages, the block table itself is SCALAR-PREFETCHED and drives the
BlockSpec index map, so each grid step DMAs exactly the page the
sequence occupies — no gather materialisation. The contiguous head-major
cache is the degenerate case of the same kernel (page j = S-slice j), so
both share ONE online-softmax body. Optional per-(head, dim) int8 scales
dequantize in VMEM. Inference-only (no VJP).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _interpret():
    from . import interpret_mode

    return interpret_mode()


def _body(cl_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref, acc, m_scr,
          l_scr, *, scale, nb, bs, hkv, group, rowscale=False):
    """Shared head-major online-softmax pass. Column order: the (hkv, bs,
    D) block flattens to c = h*bs + s, so head(c) = c // bs and
    position(c) = j*bs + c % bs."""
    b = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        acc[:] = jnp.zeros_like(acc)
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    hq = group * hkv
    cols = hkv * bs
    D = q_ref.shape[-1]
    q = q_ref[0, 0].astype(jnp.float32)                 # (Hq, D)
    k = k_ref[0].astype(jnp.float32)                    # (hkv, bs, D)
    v = v_ref[0].astype(jnp.float32)
    if ks_ref is not None:
        # int8 dequant rides the (hkv, bs, D) layout BEFORE the
        # major-dim collapse (the Mosaic-proven pattern). Two scale
        # layouts: (Hkv, D) global per-(head, dim) calibration
        # (QuantKVCache), or (1, Hkv, BS) PER-ROW scales riding the
        # page itself (QuantPagedKVCache — each token row carries its
        # own amax, so quantization is write-order independent)
        if rowscale:
            k = k * ks_ref[0][:, :, None]
            v = v * vs_ref[0][:, :, None]
        else:
            k = k * ks_ref[...][:, None, :]
            v = v * vs_ref[...][:, None, :]
    k = k.reshape(cols, D)
    v = v.reshape(cols, D)

    count = cl_ref[b]
    vpos = j * bs + jax.lax.broadcasted_iota(jnp.int32, (cols, D), 0) % bs
    v = jnp.where(vpos < count, v, 0.0)
    rowh = jax.lax.broadcasted_iota(jnp.int32, (hq, cols), 0) // group
    colh = jax.lax.broadcasted_iota(jnp.int32, (hq, cols), 1) // bs
    colp = j * bs + jax.lax.broadcasted_iota(jnp.int32, (hq, cols), 1) % bs
    keep = (rowh == colh) & (colp < count)

    s = jax.lax.dot_general(q * scale, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)  # (Hq, cols)
    s = jnp.where(keep, s, NEG_INF)

    m_prev = m_scr[:, 0]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
    p = jnp.where(keep, jnp.exp(s - m_new[:, None]), 0.0)
    alpha = jnp.exp(m_prev - m_new)
    l_new = l_scr[:, 0] * alpha + jnp.sum(p, axis=-1)
    acc[:] = acc[:] * alpha[:, None] + jax.lax.dot_general(
        p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
    m_scr[:] = jnp.broadcast_to(m_new[:, None], m_scr.shape)
    l_scr[:] = jnp.broadcast_to(l_new[:, None], l_scr.shape)

    @pl.when(j == nb - 1)
    def _():
        safe = jnp.maximum(l_scr[:, 0], 1e-30)
        o_ref[0, 0] = (acc[:] / safe[:, None]).astype(o_ref.dtype)


def _kernel(cl_ref, tbl_ref, q_ref, k_ref, v_ref, o_ref, acc, m_scr, l_scr,
            **kw):
    _body(cl_ref, q_ref, k_ref, v_ref, None, None, o_ref, acc, m_scr,
          l_scr, **kw)


def _kernel_q8(cl_ref, tbl_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref,
               acc, m_scr, l_scr, **kw):
    _body(cl_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref, acc, m_scr,
          l_scr, **kw)


def _kernel_hm(cl_ref, q_ref, k_ref, v_ref, o_ref, acc, m_scr, l_scr, **kw):
    _body(cl_ref, q_ref, k_ref, v_ref, None, None, o_ref, acc, m_scr,
          l_scr, **kw)


def _kernel_hm_q8(cl_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref, acc,
                  m_scr, l_scr, **kw):
    _body(cl_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref, acc, m_scr,
          l_scr, **kw)


def _run(kernel, grid, in_specs, out_spec, args, out_sd, interp):
    return pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=grid[0],
            grid=grid[1],
            in_specs=in_specs,
            out_specs=out_spec,
            scratch_shapes=[
                pltpu.VMEM(out_sd.shape[-2:], jnp.float32),
                pltpu.VMEM((out_sd.shape[-2], 128), jnp.float32),
                pltpu.VMEM((out_sd.shape[-2], 128), jnp.float32),
            ],
        ),
        out_shape=out_sd,
        interpret=interp,
    )(*args)


def paged_decode_attention(q, key_cache, value_cache, block_tables,
                           context_lens, scale=None, k_scale=None,
                           v_scale=None):
    """One fused paged decode step.

    q: (B, 1, Hq, D); key_cache/value_cache: (NB, Hkv, BS, D) pages;
    block_tables: (B, MAXB) int32 page ids (entries past the sequence's
    pages may be any value — they are clamped and masked); context_lens:
    (B,) valid positions per row. Optional k_scale/v_scale dequantize
    int8 pages in VMEM, in either of two layouts: (Hkv, D) f32 global
    per-(head, dim) calibration (QuantKVCache), or (NB, Hkv, BS) f32
    PER-ROW scales riding page-shaped pools (QuantPagedKVCache — the
    scale block is prefetched by the same block-table index map as its
    page). Returns (B, 1, Hq, D).
    """
    B, Sq, Hq, D = q.shape
    if Sq != 1:
        raise ValueError(f'paged decode is single-token (Sq=1), got {Sq}')
    NB, Hkv, BS, _ = key_cache.shape
    if Hq % Hkv:
        raise ValueError(
            f'query heads ({Hq}) must be a multiple of kv heads ({Hkv})')
    group = Hq // Hkv
    scale = scale if scale is not None else 1.0 / (D ** 0.5)
    nb = block_tables.shape[1]
    # out-of-range / sentinel (-1) page ids must not index OOB: clamp —
    # the count mask already zeroes their contribution
    tbl = jnp.clip(jnp.asarray(block_tables, jnp.int32), 0, NB - 1)
    cl = jnp.minimum(jnp.broadcast_to(
        jnp.reshape(jnp.asarray(context_lens, jnp.int32), (-1,)), (B,)),
        nb * BS)

    quant = k_scale is not None
    rowscale = quant and k_scale.ndim == 3
    in_specs = [
        pl.BlockSpec((1, 1, Hq, D), lambda b, j, cl, tbl: (b, 0, 0, 0)),
        # the prefetched block table IS the page index: grid step (b, j)
        # DMAs page block_tables[b, j]
        pl.BlockSpec((1, Hkv, BS, D),
                     lambda b, j, cl, tbl: (tbl[b, j], 0, 0, 0)),
        pl.BlockSpec((1, Hkv, BS, D),
                     lambda b, j, cl, tbl: (tbl[b, j], 0, 0, 0)),
    ]
    args = [cl, tbl, q, key_cache, value_cache]
    kw = dict(scale=scale, nb=nb, bs=BS, hkv=Hkv, group=group,
              rowscale=rowscale)
    if quant:
        kernel = functools.partial(_kernel_q8, **kw)
        if rowscale:
            # per-row scales live in page-shaped (NB, Hkv, BS) pools:
            # the scale block for grid step (b, j) is the same
            # prefetched page the K/V blocks DMA
            in_specs += [pl.BlockSpec(
                (1, Hkv, BS), lambda b, j, cl, tbl: (tbl[b, j], 0, 0))] * 2
        else:
            in_specs += [pl.BlockSpec((Hkv, D),
                                      lambda b, j, cl, tbl: (0, 0))] * 2
        args += [k_scale.astype(jnp.float32), v_scale.astype(jnp.float32)]
    else:
        kernel = functools.partial(_kernel, **kw)
    return _run(
        kernel, (2, (B, nb)), in_specs,
        pl.BlockSpec((1, 1, Hq, D), lambda b, j, cl, tbl: (b, 0, 0, 0)),
        args, jax.ShapeDtypeStruct((B, 1, Hq, D), q.dtype), _interpret())


def decode_attention_headmajor(q, k_cache, v_cache, context_lens,
                               scale=None, k_scale=None, v_scale=None,
                               block_s=1024):
    """Fused decode over a CONTIGUOUS head-major cache (B, Hkv, S, D) —
    the masked_multihead_attention layout. Same body as the paged
    kernel: page j is simply S-slice j, blocked to a VMEM budget, so any
    cache length streams once with no transpose."""
    B, Sq, Hq, D = q.shape
    if Sq != 1:
        raise ValueError(f'decode is single-token (Sq=1), got {Sq}')
    _, Hkv, S, _ = k_cache.shape
    if Hq % Hkv:
        raise ValueError(
            f'query heads ({Hq}) must be a multiple of kv heads ({Hkv})')
    group = Hq // Hkv
    scale = scale if scale is not None else 1.0 / (D ** 0.5)
    interp = _interpret()
    # VMEM-bounded block along S: the same policy as the contiguous
    # kernel, shared so tuning lands in both
    from .decode_attention import _pick_block

    bs = _pick_block(block_s, S, Hkv, D, k_cache.dtype.itemsize, interp)
    nb = pl.cdiv(S, bs)
    cl = jnp.minimum(jnp.broadcast_to(
        jnp.reshape(jnp.asarray(context_lens, jnp.int32), (-1,)), (B,)), S)

    quant = k_scale is not None
    in_specs = [
        pl.BlockSpec((1, 1, Hq, D), lambda b, j, cl: (b, 0, 0, 0)),
        pl.BlockSpec((1, Hkv, bs, D), lambda b, j, cl: (b, 0, j, 0)),
        pl.BlockSpec((1, Hkv, bs, D), lambda b, j, cl: (b, 0, j, 0)),
    ]
    args = [cl, q, k_cache, v_cache]
    kw = dict(scale=scale, nb=nb, bs=bs, hkv=Hkv, group=group)
    if quant:
        kernel = functools.partial(_kernel_hm_q8, **kw)
        in_specs += [pl.BlockSpec((Hkv, D), lambda b, j, cl: (0, 0))] * 2
        args += [k_scale.astype(jnp.float32), v_scale.astype(jnp.float32)]
    else:
        kernel = functools.partial(_kernel_hm, **kw)
    return _run(
        kernel, (1, (B, nb)), in_specs,
        pl.BlockSpec((1, 1, Hq, D), lambda b, j, cl: (b, 0, 0, 0)),
        args, jax.ShapeDtypeStruct((B, 1, Hq, D), q.dtype), interp)
