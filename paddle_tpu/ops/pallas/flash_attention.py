"""Flash attention (pallas, TPU) — fwd + custom-VJP bwd.

ref (capability): the reference's flash_attention op
(python/paddle/nn/functional/flash_attention.py → CUDA flash-attn
kernels). This is a from-scratch TPU kernel: online-softmax tiling over
(q-block × k-block) grid steps, fp32 accumulators in VMEM scratch,
MXU-shaped (128×128) tiles, causal masking, GQA via head-index mapping.

Layout: (B, S, H, D) in/out (Paddle's flash layout); kernels run on
(B, H, S, D) transposed views.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# 1024x1024 measured 8.5x faster than 128x128 on v5e (59.9 vs 7.0 TF/s
# effective): the grid collapses from ~49k tiny steps to ~770, amortising
# per-step overhead; VMEM use stays ~6.5MB
DEFAULT_BLOCK_Q = 1024
DEFAULT_BLOCK_K = 1024
NEG_INF = -1e30


def _interpret():
    return jax.default_backend() not in ('tpu',)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc, m_scr, l_scr, *,
                scale, causal, bq, bk, nk, offset, Sq, Sk):
    ik = pl.program_id(3)
    iq = pl.program_id(2)
    k_tail = Sk % bk != 0                               # static

    @pl.when(ik == 0)
    def _():
        acc[:] = jnp.zeros_like(acc)
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    # causal block skip: whole q block above the diagonal → contributes 0
    live = (iq * bq + (bq - 1) + offset >= ik * bk) if causal else True

    @pl.when(live)
    def _():
        q = q_ref[0, 0].astype(jnp.float32)             # (bq, D)
        k = k_ref[0, 0].astype(jnp.float32)             # (bk, D)
        v = v_ref[0, 0].astype(jnp.float32)
        if k_tail:
            # padded key rows read unspecified memory; zero v so the
            # (masked-to-zero-prob) tail can't inject inf/nan into acc
            vrow = ik * bk + jax.lax.broadcasted_iota(jnp.int32, v.shape, 0)
            v = jnp.where(vrow < Sk, v, 0.0)

        s = jax.lax.dot_general(q * scale, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)  # (bq, bk)
        if causal or k_tail:
            qpos = iq * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            kpos = ik * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            # bottom-right causal (matches _sdpa_reference tril k=Sk-Sq),
            # merged with the key-tail validity mask
            ok = (qpos + offset >= kpos) if causal else True
            if k_tail:
                ok = ok & (kpos < Sk) if causal else (kpos < Sk)
            s = jnp.where(ok, s, NEG_INF)

        m_prev = m_scr[:, 0]                             # (bq,)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_scr[:, 0] * alpha + jnp.sum(p, axis=-1)
        acc[:] = acc[:] * alpha[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new[:, None], m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new[:, None], l_scr.shape)

    @pl.when(ik == nk - 1)
    def _():
        l = l_scr[:, 0]
        safe = jnp.maximum(l, 1e-30)
        o_ref[0, 0] = (acc[:] / safe[:, None]).astype(o_ref.dtype)
        # lse stored (B,H,1,Sq): Sq on the lane dim — a (B,H,Sq,1) layout
        # pads the trailing 1 to 128 lanes in HBM (128x expansion, ~190MB
        # at 7B bench shapes)
        lse_ref[0, 0, 0] = m_scr[:, 0] + jnp.log(safe)


def _fwd(q, k, v, scale, causal, bq, bk):
    """q: (B, H, Sq, D); k/v: (B, Hkv, Sk, D) → (out, lse)."""
    B, H, Sq, D = q.shape
    _, Hkv, Sk, _ = k.shape
    group = H // Hkv
    bq = min(bq, Sq)
    bk = min(bk, Sk)
    nq, nk = pl.cdiv(Sq, bq), pl.cdiv(Sk, bk)

    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               bq=bq, bk=bk, nk=nk, offset=Sk - Sq,
                               Sq=Sq, Sk=Sk)
    out, lse = pl.pallas_call(
        kernel,
        grid=(B, H, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bk, D), lambda b, h, i, j: (b, h // group, j, 0)),
            pl.BlockSpec((1, 1, bk, D), lambda b, h, i, j: (b, h // group, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, 1, bq), lambda b, h, i, j: (b, h, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, Sq, D), q.dtype),
            jax.ShapeDtypeStruct((B, H, 1, Sq), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, D), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
        ],
        interpret=_interpret(),
    )(q, k, v)
    return out, lse


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   dq_acc, *, scale, causal, bq, bk, nk, offset, Sq, Sk):
    ik = pl.program_id(3)
    iq = pl.program_id(2)
    k_tail = Sk % bk != 0                                # static

    @pl.when(ik == 0)
    def _():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    # causal block skip (same as fwd): fully-masked blocks contribute 0
    live = (iq * bq + (bq - 1) + offset >= ik * bk) if causal else True

    @pl.when(live)
    def _():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0, 0]                           # (bq,)
        delta = delta_ref[0, 0, 0]                       # (bq,)
        if k_tail:
            krow = ik * bk + jax.lax.broadcasted_iota(jnp.int32, k.shape, 0)
            k = jnp.where(krow < Sk, k, 0.0)
            v = jnp.where(krow < Sk, v, 0.0)

        s = jax.lax.dot_general(q * scale, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        kvalid = True
        if causal or k_tail:
            qpos = iq * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            kpos = ik * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            # bottom-right causal (matches _sdpa_reference tril k=Sk-Sq)
            ok = (qpos + offset >= kpos) if causal else True
            if k_tail:
                kvalid = kpos < Sk
                ok = (ok & kvalid) if causal else kvalid
            s = jnp.where(ok, s, NEG_INF)
        p = jnp.exp(s - lse[:, None])                    # (bq, bk)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None])
        if k_tail:
            ds = jnp.where(kvalid, ds, 0.0)
        dq_acc[:] = dq_acc[:] + scale * jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ik == nk - 1)
    def _():
        dq_ref[0, 0] = dq_acc[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc, *, scale, causal, bq, bk, nq,
                    offset, Sq, Sk):
    iq = pl.program_id(3)
    ik = pl.program_id(2)
    q_tail = Sq % bq != 0                                # static

    @pl.when(iq == 0)
    def _():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    # causal block skip (same as fwd): fully-masked blocks contribute 0
    live = (iq * bq + (bq - 1) + offset >= ik * bk) if causal else True

    @pl.when(live)
    def _():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0, 0]                           # (bq,)
        delta = delta_ref[0, 0, 0]                       # (bq,)
        qvalid = True
        if q_tail:
            # padded query rows read unspecified q/do/lse/delta — they
            # would contaminate the dk/dv sums over the query axis. Zero
            # the loads and (below) the p/ds rows.
            qrow = iq * bq + jax.lax.broadcasted_iota(jnp.int32, q.shape, 0)
            q = jnp.where(qrow < Sq, q, 0.0)
            do = jnp.where(qrow < Sq, do, 0.0)
            qvalid = iq * bq + jax.lax.broadcasted_iota(
                jnp.int32, (bq, bk), 0) < Sq

        s = jax.lax.dot_general(q * scale, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)  # (bq, bk)
        if causal:
            qpos = iq * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            kpos = ik * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            # bottom-right causal (matches _sdpa_reference tril k=Sk-Sq)
            s = jnp.where(qpos + offset >= kpos, s, NEG_INF)
        p = jnp.exp(s - lse[:, None])
        if q_tail:
            p = jnp.where(qvalid, p, 0.0)
        dv_acc[:] = dv_acc[:] + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None])
        if q_tail:
            ds = jnp.where(qvalid, ds, 0.0)
        dk_acc[:] = dk_acc[:] + scale * jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(iq == nq - 1)
    def _():
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd(scale, causal, bq, bk, res, g):
    q, k, v, out, lse = res
    do, _ = g
    B, H, Sq, D = q.shape
    _, Hkv, Sk, _ = k.shape
    group = H // Hkv
    bq_ = min(bq, Sq)
    bk_ = min(bk, Sk)
    nq, nk = pl.cdiv(Sq, bq_), pl.cdiv(Sk, bk_)

    # (B, H, 1, Sq): Sq on the lane dim to avoid 128x HBM padding
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)[:, :, None, :]

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          bq=bq_, bk=bk_, nk=nk, offset=Sk - Sq,
                          Sq=Sq, Sk=Sk),
        grid=(B, H, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, bq_, D), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bk_, D), lambda b, h, i, j: (b, h // group, j, 0)),
            pl.BlockSpec((1, 1, bk_, D), lambda b, h, i, j: (b, h // group, j, 0)),
            pl.BlockSpec((1, 1, bq_, D), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, 1, bq_), lambda b, h, i, j: (b, h, 0, i)),
            pl.BlockSpec((1, 1, 1, bq_), lambda b, h, i, j: (b, h, 0, i)),
        ],
        out_specs=pl.BlockSpec((1, 1, bq_, D), lambda b, h, i, j: (b, h, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B, H, Sq, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq_, D), jnp.float32)],
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)

    # per-q-head dk/dv, then reduce GQA groups
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          bq=bq_, bk=bk_, nq=nq, offset=Sk - Sq,
                          Sq=Sq, Sk=Sk),
        grid=(B, H, nk, nq),
        in_specs=[
            pl.BlockSpec((1, 1, bq_, D), lambda b, h, j, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bk_, D), lambda b, h, j, i: (b, h // group, j, 0)),
            pl.BlockSpec((1, 1, bk_, D), lambda b, h, j, i: (b, h // group, j, 0)),
            pl.BlockSpec((1, 1, bq_, D), lambda b, h, j, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, 1, bq_), lambda b, h, j, i: (b, h, 0, i)),
            pl.BlockSpec((1, 1, 1, bq_), lambda b, h, j, i: (b, h, 0, i)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bk_, D), lambda b, h, j, i: (b, h, j, 0)),
            pl.BlockSpec((1, 1, bk_, D), lambda b, h, j, i: (b, h, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, Sk, D), jnp.float32),
            jax.ShapeDtypeStruct((B, H, Sk, D), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk_, D), jnp.float32),
            pltpu.VMEM((bk_, D), jnp.float32),
        ],
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)

    if group > 1:
        dk = dk.reshape(B, Hkv, group, Sk, D).sum(axis=2)
        dv = dv.reshape(B, Hkv, group, Sk, D).sum(axis=2)
    return dq, dk.astype(k.dtype), dv.astype(v.dtype)


# ---------------------------------------------------------------------------
# public op
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, scale, causal, bq, bk):
    out, _ = _fwd(q, k, v, scale, causal, bq, bk)
    return out


def _flash_fwd(q, k, v, scale, causal, bq, bk):
    out, lse = _fwd(q, k, v, scale, causal, bq, bk)
    return out, (q, k, v, out, lse)


def _flash_bwd(scale, causal, bq, bk, res, g):
    return _bwd(scale, causal, bq, bk, res, (g, None))


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, causal=False, scale=None,
                    block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K):
    """q: (B, Sq, H, D); k/v: (B, Sk, Hkv, D). Returns (B, Sq, H, D)."""
    D = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(D)
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    out = _flash(qt, kt, vt, float(scale), bool(causal), block_q, block_k)
    return jnp.swapaxes(out, 1, 2)
