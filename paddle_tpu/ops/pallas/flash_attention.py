"""Flash attention (pallas, TPU) — fwd + custom-VJP bwd.

ref (capability): the reference's flash_attention op
(python/paddle/nn/functional/flash_attention.py → CUDA flash-attn
kernels). This is a from-scratch TPU kernel: online-softmax tiling over
(q-block × k-block) grid steps, fp32 accumulators in VMEM scratch,
MXU-shaped (128×128) tiles, causal masking, GQA via head-index mapping.

Layout: (B, S, H, D) in/out (Paddle's flash layout); kernels run on
(B, H, S, D) transposed views.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# 1024x1024 measured 8.5x faster than 128x128 on v5e (59.9 vs 7.0 TF/s
# effective): the grid collapses from ~49k tiny steps to ~770, amortising
# per-step overhead; VMEM use stays ~6.5MB
DEFAULT_BLOCK_Q = 1024
DEFAULT_BLOCK_K = 1024
NEG_INF = -1e30


def _interpret():
    from . import interpret_mode

    return interpret_mode()


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, *refs,
                scale, causal, bq, bk, nk, offset, Sq, Sk, has_seg=False,
                window=None):
    if has_seg:
        qseg_ref, kseg_ref, o_ref, lse_ref, acc, m_scr, l_scr = refs
    else:
        o_ref, lse_ref, acc, m_scr, l_scr = refs
    ik = pl.program_id(3)
    iq = pl.program_id(2)
    k_tail = Sk % bk != 0                               # static

    @pl.when(ik == 0)
    def _():
        acc[:] = jnp.zeros_like(acc)
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    # causal block skip: whole q block above the diagonal → contributes 0
    live = (iq * bq + (bq - 1) + offset >= ik * bk) if causal else True
    if window is not None:
        # sliding-window block skip: a k block wholly BEFORE every query's
        # window start (qpos + offset - w < kpos) is dead — same static
        # machinery as the causal skip, mirrored to the other side
        live = live & (ik * bk + (bk - 1) > iq * bq + offset - window)

    @pl.when(live)
    def _():
        q = q_ref[0, 0].astype(jnp.float32)             # (bq, D)
        k = k_ref[0, 0].astype(jnp.float32)             # (bk, D)
        v = v_ref[0, 0].astype(jnp.float32)
        if k_tail:
            # padded key rows read unspecified memory; zero v so the
            # (masked-to-zero-prob) tail can't inject inf/nan into acc
            vrow = ik * bk + jax.lax.broadcasted_iota(jnp.int32, v.shape, 0)
            v = jnp.where(vrow < Sk, v, 0.0)

        s = jax.lax.dot_general(q * scale, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)  # (bq, bk)
        ok = None
        if causal or k_tail or has_seg or window is not None:
            qpos = iq * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            kpos = ik * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            # bottom-right causal (matches _sdpa_reference tril k=Sk-Sq),
            # merged with the key-tail validity and segment masks
            ok = (qpos + offset >= kpos) if causal else \
                jnp.ones((bq, bk), bool)
            if window is not None:
                # attend only the last `window` positions (incl. self)
                ok = ok & (qpos + offset - kpos < window)
            if k_tail:
                ok = ok & (kpos < Sk)
            if has_seg:
                ok = ok & (qseg_ref[0][:, None] == kseg_ref[0][None, :])
            s = jnp.where(ok, s, NEG_INF)

        m_prev = m_scr[:, 0]                             # (bq,)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        if ok is not None:
            # a fully-masked row has m_new == NEG_INF and exp(0) == 1
            # everywhere — force those probabilities to the true 0 so
            # empty-segment queries return 0 and leak no gradient
            p = jnp.where(ok, p, 0.0)
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_scr[:, 0] * alpha + jnp.sum(p, axis=-1)
        acc[:] = acc[:] * alpha[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new[:, None], m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new[:, None], l_scr.shape)

    @pl.when(ik == nk - 1)
    def _():
        l = l_scr[:, 0]
        safe = jnp.maximum(l, 1e-30)
        o_ref[0, 0] = (acc[:] / safe[:, None]).astype(o_ref.dtype)
        # lse stored (B,H,1,Sq): Sq on the lane dim — a (B,H,Sq,1) layout
        # pads the trailing 1 to 128 lanes in HBM (128x expansion, ~190MB
        # at 7B bench shapes)
        lse_ref[0, 0, 0] = m_scr[:, 0] + jnp.log(safe)


def _fwd(q, k, v, scale, causal, bq, bk, qseg=None, kseg=None,
         window=None):
    """q: (B, H, Sq, D); k/v: (B, Hkv, Sk, D) → (out, lse).

    qseg/kseg: optional (B, Sq)/(B, Sk) int32 segment ids — tokens only
    attend within equal ids (packed-sequence block-diagonal mask).
    """
    B, H, Sq, D = q.shape
    _, Hkv, Sk, _ = k.shape
    group = H // Hkv
    bq = min(bq, Sq)
    bk = min(bk, Sk)
    nq, nk = pl.cdiv(Sq, bq), pl.cdiv(Sk, bk)
    has_seg = qseg is not None

    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               bq=bq, bk=bk, nk=nk, offset=Sk - Sq,
                               Sq=Sq, Sk=Sk, has_seg=has_seg, window=window)
    in_specs = [
        pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0)),
        pl.BlockSpec((1, 1, bk, D), lambda b, h, i, j: (b, h // group, j, 0)),
        pl.BlockSpec((1, 1, bk, D), lambda b, h, i, j: (b, h // group, j, 0)),
    ]
    operands = [q, k, v]
    if has_seg:
        in_specs += [
            pl.BlockSpec((1, bq), lambda b, h, i, j: (b, i)),
            pl.BlockSpec((1, bk), lambda b, h, i, j: (b, j)),
        ]
        operands += [jnp.asarray(qseg, jnp.int32),
                     jnp.asarray(kseg, jnp.int32)]
    out, lse = pl.pallas_call(
        kernel,
        grid=(B, H, nq, nk),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, 1, bq), lambda b, h, i, j: (b, h, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, Sq, D), q.dtype),
            jax.ShapeDtypeStruct((B, H, 1, Sq), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, D), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
        ],
        interpret=_interpret(),
    )(*operands)
    return out, lse


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *refs,
                   scale, causal, bq, bk, nk, offset, Sq, Sk,
                   has_seg=False, window=None):
    if has_seg:
        qseg_ref, kseg_ref, dq_ref, dq_acc = refs
    else:
        dq_ref, dq_acc = refs
    ik = pl.program_id(3)
    iq = pl.program_id(2)
    k_tail = Sk % bk != 0                                # static

    @pl.when(ik == 0)
    def _():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    # causal block skip (same as fwd): fully-masked blocks contribute 0
    live = (iq * bq + (bq - 1) + offset >= ik * bk) if causal else True
    if window is not None:
        live = live & (ik * bk + (bk - 1) > iq * bq + offset - window)

    @pl.when(live)
    def _():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0, 0]                           # (bq,)
        delta = delta_ref[0, 0, 0]                       # (bq,)
        if k_tail:
            krow = ik * bk + jax.lax.broadcasted_iota(jnp.int32, k.shape, 0)
            k = jnp.where(krow < Sk, k, 0.0)
            v = jnp.where(krow < Sk, v, 0.0)

        s = jax.lax.dot_general(q * scale, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        kvalid = True
        if causal or k_tail or has_seg or window is not None:
            qpos = iq * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            kpos = ik * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            # bottom-right causal (matches _sdpa_reference tril k=Sk-Sq)
            ok = (qpos + offset >= kpos) if causal else \
                jnp.ones((bq, bk), bool)
            if window is not None:
                ok = ok & (qpos + offset - kpos < window)
            if k_tail:
                kvalid = kpos < Sk
                ok = ok & kvalid
            if has_seg:
                ok = ok & (qseg_ref[0][:, None] == kseg_ref[0][None, :])
            s = jnp.where(ok, s, NEG_INF)
        p = jnp.exp(s - lse[:, None])                    # (bq, bk)
        if causal or k_tail or has_seg or window is not None:
            # empty-segment rows: lse ≈ NEG_INF makes exp(s - lse) = 1
            p = jnp.where(ok, p, 0.0)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None])
        if k_tail:
            ds = jnp.where(kvalid, ds, 0.0)
        dq_acc[:] = dq_acc[:] + scale * jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ik == nk - 1)
    def _():
        dq_ref[0, 0] = dq_acc[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    *refs, scale, causal, bq, bk, nq,
                    offset, Sq, Sk, has_seg=False, window=None):
    if has_seg:
        qseg_ref, kseg_ref, dk_ref, dv_ref, dk_acc, dv_acc = refs
    else:
        dk_ref, dv_ref, dk_acc, dv_acc = refs
    iq = pl.program_id(3)
    ik = pl.program_id(2)
    q_tail = Sq % bq != 0                                # static

    @pl.when(iq == 0)
    def _():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    # causal block skip (same as fwd): fully-masked blocks contribute 0
    live = (iq * bq + (bq - 1) + offset >= ik * bk) if causal else True
    if window is not None:
        live = live & (ik * bk + (bk - 1) > iq * bq + offset - window)

    @pl.when(live)
    def _():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0, 0]                           # (bq,)
        delta = delta_ref[0, 0, 0]                       # (bq,)
        qvalid = True
        if q_tail:
            # padded query rows read unspecified q/do/lse/delta — they
            # would contaminate the dk/dv sums over the query axis. Zero
            # the loads and (below) the p/ds rows.
            qrow = iq * bq + jax.lax.broadcasted_iota(jnp.int32, q.shape, 0)
            q = jnp.where(qrow < Sq, q, 0.0)
            do = jnp.where(qrow < Sq, do, 0.0)
            qvalid = iq * bq + jax.lax.broadcasted_iota(
                jnp.int32, (bq, bk), 0) < Sq

        s = jax.lax.dot_general(q * scale, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)  # (bq, bk)
        if causal or has_seg or window is not None:
            qpos = iq * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            kpos = ik * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            # bottom-right causal (matches _sdpa_reference tril k=Sk-Sq)
            ok = (qpos + offset >= kpos) if causal else \
                jnp.ones((bq, bk), bool)
            if window is not None:
                ok = ok & (qpos + offset - kpos < window)
            if has_seg:
                ok = ok & (qseg_ref[0][:, None] == kseg_ref[0][None, :])
            s = jnp.where(ok, s, NEG_INF)
        p = jnp.exp(s - lse[:, None])
        if causal or has_seg or window is not None:
            # empty-segment rows: lse ≈ NEG_INF makes exp(s - lse) = 1
            p = jnp.where(ok, p, 0.0)
        if q_tail:
            p = jnp.where(qvalid, p, 0.0)
        dv_acc[:] = dv_acc[:] + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None])
        if q_tail:
            ds = jnp.where(qvalid, ds, 0.0)
        dk_acc[:] = dk_acc[:] + scale * jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(iq == nq - 1)
    def _():
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd(scale, causal, bq, bk, res, g, qseg=None, kseg=None,
         window=None):
    q, k, v, out, lse = res
    do, _ = g
    has_seg = qseg is not None
    seg_ops = ([jnp.asarray(qseg, jnp.int32), jnp.asarray(kseg, jnp.int32)]
               if has_seg else [])
    B, H, Sq, D = q.shape
    _, Hkv, Sk, _ = k.shape
    group = H // Hkv
    bq_ = min(bq, Sq)
    bk_ = min(bk, Sk)
    nq, nk = pl.cdiv(Sq, bq_), pl.cdiv(Sk, bk_)

    # (B, H, 1, Sq): Sq on the lane dim to avoid 128x HBM padding
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)[:, :, None, :]

    dq_in_specs = [
        pl.BlockSpec((1, 1, bq_, D), lambda b, h, i, j: (b, h, i, 0)),
        pl.BlockSpec((1, 1, bk_, D), lambda b, h, i, j: (b, h // group, j, 0)),
        pl.BlockSpec((1, 1, bk_, D), lambda b, h, i, j: (b, h // group, j, 0)),
        pl.BlockSpec((1, 1, bq_, D), lambda b, h, i, j: (b, h, i, 0)),
        pl.BlockSpec((1, 1, 1, bq_), lambda b, h, i, j: (b, h, 0, i)),
        pl.BlockSpec((1, 1, 1, bq_), lambda b, h, i, j: (b, h, 0, i)),
    ]
    if has_seg:
        dq_in_specs += [
            pl.BlockSpec((1, bq_), lambda b, h, i, j: (b, i)),
            pl.BlockSpec((1, bk_), lambda b, h, i, j: (b, j)),
        ]
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          bq=bq_, bk=bk_, nk=nk, offset=Sk - Sq,
                          Sq=Sq, Sk=Sk, has_seg=has_seg, window=window),
        grid=(B, H, nq, nk),
        in_specs=dq_in_specs,
        out_specs=pl.BlockSpec((1, 1, bq_, D), lambda b, h, i, j: (b, h, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B, H, Sq, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq_, D), jnp.float32)],
        interpret=_interpret(),
    )(q, k, v, do, lse, delta, *seg_ops)

    # per-q-head dk/dv, then reduce GQA groups
    dkv_in_specs = [
        pl.BlockSpec((1, 1, bq_, D), lambda b, h, j, i: (b, h, i, 0)),
        pl.BlockSpec((1, 1, bk_, D), lambda b, h, j, i: (b, h // group, j, 0)),
        pl.BlockSpec((1, 1, bk_, D), lambda b, h, j, i: (b, h // group, j, 0)),
        pl.BlockSpec((1, 1, bq_, D), lambda b, h, j, i: (b, h, i, 0)),
        pl.BlockSpec((1, 1, 1, bq_), lambda b, h, j, i: (b, h, 0, i)),
        pl.BlockSpec((1, 1, 1, bq_), lambda b, h, j, i: (b, h, 0, i)),
    ]
    if has_seg:
        dkv_in_specs += [
            pl.BlockSpec((1, bq_), lambda b, h, j, i: (b, i)),
            pl.BlockSpec((1, bk_), lambda b, h, j, i: (b, j)),
        ]
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          bq=bq_, bk=bk_, nq=nq, offset=Sk - Sq,
                          Sq=Sq, Sk=Sk, has_seg=has_seg, window=window),
        grid=(B, H, nk, nq),
        in_specs=dkv_in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, bk_, D), lambda b, h, j, i: (b, h, j, 0)),
            pl.BlockSpec((1, 1, bk_, D), lambda b, h, j, i: (b, h, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, Sk, D), jnp.float32),
            jax.ShapeDtypeStruct((B, H, Sk, D), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk_, D), jnp.float32),
            pltpu.VMEM((bk_, D), jnp.float32),
        ],
        interpret=_interpret(),
    )(q, k, v, do, lse, delta, *seg_ops)

    if group > 1:
        dk = dk.reshape(B, Hkv, group, Sk, D).sum(axis=2)
        dv = dv.reshape(B, Hkv, group, Sk, D).sum(axis=2)
    return dq, dk.astype(k.dtype), dv.astype(v.dtype)


# ---------------------------------------------------------------------------
# public op
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, scale, causal, bq, bk, window):
    out, _ = _fwd(q, k, v, scale, causal, bq, bk, window=window)
    return out


def _flash_fwd(q, k, v, scale, causal, bq, bk, window):
    out, lse = _fwd(q, k, v, scale, causal, bq, bk, window=window)
    return out, (q, k, v, out, lse)


def _flash_bwd(scale, causal, bq, bk, window, res, g):
    return _bwd(scale, causal, bq, bk, res, (g, None), window=window)


_flash.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _flash_seg(q, k, v, qseg, kseg, scale, causal, bq, bk, window):
    out, _ = _fwd(q, k, v, scale, causal, bq, bk, qseg, kseg, window=window)
    return out


def _flash_seg_fwd(q, k, v, qseg, kseg, scale, causal, bq, bk, window):
    out, lse = _fwd(q, k, v, scale, causal, bq, bk, qseg, kseg,
                    window=window)
    return out, (q, k, v, out, lse, qseg, kseg)


def _flash_seg_bwd(scale, causal, bq, bk, window, res, g):
    q, k, v, out, lse, qseg, kseg = res
    dq, dk, dv = _bwd(scale, causal, bq, bk, (q, k, v, out, lse),
                      (g, None), qseg, kseg, window=window)
    return dq, dk, dv, None, None


_flash_seg.defvjp(_flash_seg_fwd, _flash_seg_bwd)


def flash_attention(q, k, v, causal=False, scale=None,
                    block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
                    segment_ids=None, kv_segment_ids=None, window_size=None):
    """q: (B, Sq, H, D); k/v: (B, Sk, Hkv, D). Returns (B, Sq, H, D).

    segment_ids/(kv_segment_ids): optional (B, Sq)/(B, Sk) int32 packed-
    sequence ids — attention is block-diagonal within equal ids (tokens
    of different packed documents never attend to each other). With
    causal=True both masks compose. A query whose segment has no kv
    tokens returns 0 for that row.

    window_size: optional int — sliding-window (local) attention: each
    query attends only the last `window_size` keys including itself
    (ref: python/paddle/nn/functional/flash_attention.py:1106 —
    flash_attention's window_size). Requires causal=True; k blocks
    wholly outside the band are SKIPPED (same grid machinery as the
    causal skip), so long-sequence SWA costs O(S·w) not O(S²).
    """
    if window_size is not None:
        window_size = int(window_size)
        if not causal:
            raise ValueError(
                'window_size requires causal=True (decoder sliding-window '
                'attention); use an explicit mask for bidirectional bands')
        if window_size < 1:
            raise ValueError(f'window_size must be >= 1, got {window_size}')
    D = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(D)
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    if segment_ids is not None:
        kv_seg = kv_segment_ids if kv_segment_ids is not None else segment_ids
        out = _flash_seg(qt, kt, vt, jnp.asarray(segment_ids, jnp.int32),
                         jnp.asarray(kv_seg, jnp.int32),
                         float(scale), bool(causal), block_q, block_k,
                         window_size)
    else:
        out = _flash(qt, kt, vt, float(scale), bool(causal), block_q,
                     block_k, window_size)
    return jnp.swapaxes(out, 1, 2)
