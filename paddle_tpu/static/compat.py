"""Static-graph program/executor compatibility
(ref: python/paddle/static/__init__.py — Program, Executor, scopes,
inference-model io).

What is REAL here: the deployment path. `load_inference_model` restores
a StableHLO export as a callable Program and `Executor.run` feeds it —
the pattern every reference inference script uses — and the serialize/
deserialize helpers shuttle the same artifacts. Programs can also wrap
any Python callable (`Program.from_callable`), which is how `to_static`
output plugs in.

What is NOT here: build-block graph capture (`with program_guard(...):`
executing symbolic Variables). jax traces *functions*, not with-block
bodies; the migration guide maps that pattern to `jit.to_static`.
`append_backward`/`gradients` therefore raise with pointers instead of
silently mis-computing.
"""
from __future__ import annotations

import contextlib

import numpy as np


class Scope:
    """ref: paddle.static.global_scope — a name -> array variable store
    (also backs static.nn's lazily-created parameters)."""

    def __init__(self):
        self.vars = {}

    def var(self, name):
        return self.vars.get(name)

    def find_var(self, name):
        return self.vars.get(name)

    def set(self, name, value):
        self.vars[name] = value
        return value

    def get_or_create(self, name, factory):
        if name not in self.vars:
            self.vars[name] = factory()
        return self.vars[name]


_global_scope = Scope()
_scope_stack = [_global_scope]


def global_scope():
    return _scope_stack[-1]


@contextlib.contextmanager
def scope_guard(scope):
    _scope_stack.append(scope)
    try:
        yield scope
    finally:
        _scope_stack.pop()


class Program:
    """ref: paddle.static.Program — here a callable-backed program:
    `fn(feed_dict) -> list of fetches` plus declared feed/fetch names."""

    def __init__(self):
        self._fn = None
        self._feed_names = []
        self._fetch_names = []
        self._state = None
        self.random_seed = 0

    @classmethod
    def from_callable(cls, fn, feed_names=(), fetch_names=(), state=None):
        p = cls()
        p._fn = fn
        p._feed_names = list(feed_names)
        p._fetch_names = list(fetch_names)
        p._state = state
        return p

    def clone(self, for_test=False):
        return Program.from_callable(self._fn, self._feed_names,
                                     self._fetch_names, self._state)

    def state_dict(self, mode='all', scope=None):
        return dict(self._state or {})

    def set_state_dict(self, state_dict, scope=None):
        self._state = dict(state_dict)

    def global_block(self):
        return _Block(self)

    def list_vars(self):
        return list(self._feed_names) + list(self._fetch_names)

    def __repr__(self):
        return (f'Program(feeds={self._feed_names}, '
                f'fetches={self._fetch_names})')


class _Block:
    def __init__(self, program):
        self.program = program

    def var(self, name):
        return name if name in self.program.list_vars() else None


_default_main = [Program()]
_default_startup = [Program()]


def default_main_program():
    return _default_main[-1]


def default_startup_program():
    return _default_startup[-1]


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    """ref: paddle.static.program_guard. Declarations inside the block
    (static.data, py_func) register on `main_program`; symbolic op
    capture is NOT performed (see module docstring)."""
    _default_main.append(main_program)
    _default_startup.append(startup_program or Program())
    try:
        yield
    finally:
        _default_main.pop()
        _default_startup.pop()


@contextlib.contextmanager
def name_scope(prefix=None):
    """ref: paddle.static.name_scope — prefixes generated names."""
    from ..utils import unique_name

    with unique_name.guard((prefix or '') + '/' if prefix else None):
        yield


def data(name, shape, dtype='float32', lod_level=0):
    """ref: paddle.static.data — a named feed declaration. Returns an
    InputSpec (the shape/dtype handle `to_static` consumes) and records
    the name on the current main program."""
    from ..jit import InputSpec

    spec = InputSpec(tuple(shape), dtype, name=name)
    prog = default_main_program()
    if name not in prog._feed_names:
        prog._feed_names.append(name)
    return spec


def py_func(func, x=None, out=None, backward_func=None,
            skip_vars_in_backward_input=None):
    """ref: paddle.static.py_func — install a Python callable as the
    current program's body."""
    prog = default_main_program()
    prog._fn = func
    return out


def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_lod=True,
          print_phase='both'):
    """ref: paddle.static.Print — debug-print a value (works under jit
    via jax.debug.print) and pass it through."""
    import jax

    jax.debug.print((message or 'Print') + ': {x}', x=input)
    return input


class Executor:
    """ref: paddle.static.Executor — feeds a callable-backed Program."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None, scope=None,
            return_numpy=True):
        program = program or default_main_program()
        feed = feed or {}
        if program._fn is None:
            # the reference's `exe.run(startup_program)` initializes
            # parameters; ours are initialized at construction — no-op
            return []
        out = program._fn(feed) if _wants_dict(program._fn) else \
            program._fn(*[feed[n] for n in program._feed_names])
        outs = list(out) if isinstance(out, (list, tuple)) else [out]
        if fetch_list:
            outs = outs[:len(fetch_list)]
        if return_numpy:
            outs = [np.asarray(o) for o in outs]
        return outs

    def close(self):
        pass


def _wants_dict(fn):
    import inspect

    try:
        params = list(inspect.signature(fn).parameters.values())
    except (TypeError, ValueError):
        return False
    return len(params) == 1 and params[0].name in ('feed', 'feed_dict')


class BuildStrategy:
    """ref: paddle.static.BuildStrategy — pass toggles; XLA owns fusion
    here, so these record intent."""

    def __init__(self):
        self.fuse_elewise_add_act_ops = False
        self.fuse_bn_act_ops = False
        self.enable_auto_fusion = False
        self.memory_optimize = True
        self.reduce_strategy = 0
        self.build_cinn_pass = False


class ExecutionStrategy:
    def __init__(self):
        self.num_threads = 1
        self.num_iteration_per_drop_scope = 10


class CompiledProgram:
    """ref: paddle.static.CompiledProgram — jit the program's callable."""

    def __init__(self, program, build_strategy=None):
        import jax

        self._program = program
        self.build_strategy = build_strategy or BuildStrategy()
        if program._fn is not None:
            self._program = program.clone()
            # tracelint: disable=TL001 - cached on the cloned program
            self._program._fn = jax.jit(program._fn)

    def __getattr__(self, name):
        return getattr(self._program, name)


def ipu_shard_guard(index=-1, stage=-1):
    raise NotImplementedError(
        'IPU support is out of scope on the TPU build (SURVEY §6); '
        'device placement is mesh sharding — see distributed.ProcessMesh')


class IpuStrategy:
    def __init__(self, *a, **k):
        ipu_shard_guard()


class IpuCompiledProgram:
    def __init__(self, *a, **k):
        ipu_shard_guard()


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None):
    raise NotImplementedError(
        'append_backward needs symbolic graph capture, which jax tracing '
        'replaces: express the step as a function and use '
        'autograd.value_and_grad (or hapi Model / dist.to_static), '
        'then Executor.run the jitted result')


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    raise NotImplementedError(
        'static.gradients needs symbolic graph capture; use jax-style '
        'autograd.grad over a function of `inputs` '
        '(docs/migration.md §2-3 shows the pattern)')


# ---- inference-model io -----------------------------------------------------


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         program=None, **kwargs):
    """ref: paddle.static.save_inference_model — StableHLO + weights via
    jit.save. `feed_vars` may be InputSpecs (from static.data) or a
    Layer is passed via kwargs['layer']."""
    from ..jit import save as jit_save

    layer = kwargs.get('layer')
    prog = program or default_main_program()
    target = layer if layer is not None else prog._fn
    if target is None:
        raise ValueError('nothing to export: pass layer=<Layer> or a '
                         'program built from a callable')
    jit_save(target, path_prefix, input_spec=list(feed_vars))
    # sidecar: the feed/fetch NAMES, so load_inference_model can hand
    # back the same name-keyed interface the export declared
    import json

    feed_names = [getattr(s, 'name', None) or f'x{i}'
                  for i, s in enumerate(feed_vars)]
    fetch_names = ([getattr(v, 'name', str(v)) for v in fetch_vars]
                   if fetch_vars else ['out'])
    with open(path_prefix + '.pdmodel.json', 'w') as f:
        json.dump({'feed_names': feed_names, 'fetch_names': fetch_names}, f)
    return path_prefix


def load_inference_model(path_prefix, executor=None, **kwargs):
    """ref: paddle.static.load_inference_model — returns
    [program, feed_names, fetch_names]; run it with Executor.run."""
    import json
    import os

    from ..jit import load as jit_load

    loaded = jit_load(path_prefix)

    def fn(*args):
        return loaded(*args)

    feed_names, fetch_names = ['x'], ['out']
    meta_path = path_prefix + '.pdmodel.json'
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
        feed_names = meta.get('feed_names', feed_names)
        fetch_names = meta.get('fetch_names', fetch_names)
    state = loaded.state_dict() if hasattr(loaded, 'state_dict') else None
    prog = Program.from_callable(fn, feed_names=feed_names,
                                 fetch_names=fetch_names, state=state)
    prog._loaded = loaded
    return [prog, prog._feed_names, prog._fetch_names]


def serialize_program(feed_vars=None, fetch_vars=None, program=None):
    """ref: paddle.static.serialize_program — the portable program bytes
    (serialized StableHLO export of the program's callable)."""
    import jax

    prog = program or default_main_program()
    if getattr(prog, '_loaded', None) is not None:
        raise ValueError('already a deserialized program')
    if prog._fn is None or not feed_vars:
        raise ValueError('need a callable program and feed specs')
    structs = [s.to_shape_struct() for s in feed_vars]
    # tracelint: disable=TL001 - one-shot export, not a hot path
    exported = jax.export.export(jax.jit(prog._fn))(*structs)
    return exported.serialize()


def deserialize_program(data):
    import jax

    exported = jax.export.deserialize(bytearray(data))
    return Program.from_callable(lambda *a: exported.call(*a))


def serialize_persistables(feed_vars=None, fetch_vars=None, program=None):
    """Weights as npz bytes."""
    import io

    prog = program or default_main_program()
    buf = io.BytesIO()
    state = prog.state_dict()
    np.savez(buf, **{k: np.asarray(v) for k, v in state.items()})
    return buf.getvalue()


def deserialize_persistables(program, data, executor=None):
    import io

    loaded = np.load(io.BytesIO(data))
    program.set_state_dict({k: loaded[k] for k in loaded.files})
    return program


def save(program, model_path, protocol=4, **configs):
    """ref: paddle.static.save — the program's parameter state to
    `model_path + '.pdparams'`."""
    from ..framework.io import save as save_state

    save_state(program.state_dict(), model_path + '.pdparams')


def load(program, model_path, executor=None, var_list=None):
    """ref: paddle.static.load — restore parameter state into the
    program."""
    from ..framework.io import load as load_state

    program.set_state_dict(load_state(model_path + '.pdparams'))
    return program


def save_to_file(path, content):
    with open(path, 'wb') as f:
        f.write(content)


def load_from_file(path):
    with open(path, 'rb') as f:
        return f.read()


def normalize_program(program, feed_vars, fetch_vars, **kwargs):
    """ref: paddle.static.normalize_program — prune to the feed->fetch
    subgraph; XLA's export already dead-code-eliminates, so this is the
    identity on callable-backed programs."""
    return program


class WeightNormParamAttr:
    """ref: paddle.static.WeightNormParamAttr."""

    def __init__(self, dim=None, name=None, initializer=None,
                 learning_rate=1.0, regularizer=None, trainable=True,
                 do_model_average=False, need_clip=True):
        self.dim = dim
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip


def load_program_state(model_path, var_list=None):
    """ref: paddle.static.load_program_state — dict of arrays."""
    from ..framework.io import load as load_state

    return load_state(model_path + '.pdparams')


def set_program_state(program, state_dict):
    program.set_state_dict(state_dict)


def cpu_places(device_count=None):
    """ref: paddle.static.cpu_places."""
    from ..device import CPUPlace

    n = device_count or 1
    return [CPUPlace(i) for i in range(n)]


def cuda_places(device_ids=None):
    """Accelerator places (CUDA name kept for script compat)."""
    import jax

    from ..device import TPUPlace

    ids = device_ids if device_ids is not None \
        else range(len(jax.devices()))
    return [TPUPlace(i) for i in ids]


xpu_places = cuda_places


@contextlib.contextmanager
def device_guard(device=None):
    """ref: paddle.static.device_guard — XLA owns placement; sharding
    annotations are the placement mechanism. Records intent only."""
    yield


def set_ipu_shard(layer, index=-1, stage=-1):
    ipu_shard_guard()


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    """ref: paddle.static.create_global_var — a named scope variable."""
    import jax.numpy as jnp

    from ..utils import unique_name

    name = name or unique_name.generate('global_var')
    return global_scope().set(name, jnp.full(tuple(shape), value, dtype))


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    """ref: paddle.static.create_parameter — scope-registered parameter
    (value array; see framework.compat.create_parameter for the
    Parameter-object form)."""
    from ..framework.compat import create_parameter as mk

    from ..utils import unique_name

    name = name or unique_name.generate('parameter')
    p = mk(shape, dtype, name, attr, is_bias, default_initializer)
    return global_scope().set(name, p.value)


def accuracy(input, label, k=1, correct=None, total=None):
    """ref: paddle.static.accuracy — same math as metric.accuracy."""
    from ..metric import accuracy as acc

    return acc(input, label, k, correct, total)


def auc(input, label, curve='ROC', num_thresholds=4095, topk=1,
        slide_steps=1, ins_tag_weight=None):
    """ref: paddle.static.auc — batch AUC via the metric implementation."""
    import jax.numpy as jnp

    from ..metric import Auc

    m = Auc(num_thresholds=num_thresholds)
    import numpy as _np

    preds = _np.asarray(input)
    if preds.ndim == 1:
        preds = _np.stack([1 - preds, preds], axis=1)
    m.update(preds, _np.asarray(label))
    val = m.accumulate()
    return (jnp.asarray(val), jnp.asarray(val), [])


def ctr_metric_bundle(input, label, ins_tag_weight=None):
    """ref: paddle.static.ctr_metric_bundle (ps-mode CTR metrics) —
    out of scope with parameter-server mode (SURVEY §6); the dynamic
    metric namespace covers AUC."""
    raise NotImplementedError(
        'ctr_metric_bundle belongs to the reference\'s parameter-server '
        'mode (excluded on TPU — SURVEY §6); use metric.Auc')


class Variable:
    """ref: paddle.static.Variable — the symbolic graph handle. Jax
    tracing has no user-visible symbolic variables; InputSpec (shapes)
    and jax tracers (values) play this role. Kept as an isinstance
    target for reference scripts."""

    def __init__(self, *a, **k):
        raise NotImplementedError(
            'static.Variable is a symbolic-graph handle; under tracing '
            'use static.data (InputSpec) and plain arrays — '
            'docs/migration.md §3')
