"""static.nn — functional layers with scope-backed parameters
(ref: python/paddle/static/nn/__init__.py, common.py, sequence_lod.py).

These are REAL ops: each creates (or reuses, keyed by name in the
current `static.global_scope()`) its parameters and computes eagerly /
under tracing through the same jnp paths as the dynamic layers. That
reproduces the reference's program-scope parameter model closely enough
that repeated calls share weights, while staying a pure function of
(input, scope) for XLA.

Sequence (LoD) ops take explicit per-sequence lengths instead of the
reference's implicit LoD metadata — TPU static shapes need the lengths
anyway, and every reference call site has them.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .compat import global_scope
from ..utils import unique_name

# re-exported control flow (already TPU-native here)
from . import cond, case, switch_case, while_loop  # noqa: F401
from .compat import py_func  # noqa: F401


def _param(name, shape, init=None, is_bias=False, dtype='float32'):
    from ..nn import initializer as I

    def factory():
        initializer = init
        if initializer is None:
            initializer = I.Constant(0.0) if is_bias else I.XavierNormal()
        return initializer(tuple(shape), dtype)

    return global_scope().get_or_create(name, factory)


def _name(prefix, given=None):
    return given or unique_name.generate(prefix)


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    """ref: static.nn.fc — flatten trailing dims, affine, activation."""
    from ..nn import functional as F

    base = _name('fc', name)
    lead = x.shape[:num_flatten_dims]
    flat = int(np.prod(x.shape[num_flatten_dims:]))
    x2 = jnp.reshape(x, lead + (flat,))
    w = _param(base + '.w_0', (flat, size),
               getattr(weight_attr, 'initializer', None))
    out = x2 @ w
    if bias_attr is not False:
        out = out + _param(base + '.b_0', (size,),
                           getattr(bias_attr, 'initializer', None),
                           is_bias=True)
    if activation:
        out = getattr(F, activation)(out)
    return out


def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, param_attr=None, dtype='float32', name=None):
    """ref: static.nn.embedding."""
    from ..nn import functional as F
    from ..nn import initializer as I

    base = _name('embedding', name)
    table = _param(base + '.w_0', tuple(size),
                   getattr(param_attr, 'initializer', None)
                   or I.Normal(0.0, 1.0), dtype=dtype)
    return F.embedding(jnp.asarray(input), table, padding_idx=padding_idx)


def sparse_embedding(input, size, padding_idx=None, is_test=False,
                     entry=None, table_class='MemorySparseTable',
                     param_attr=None, dtype='float32', slot=None):
    """ref: static.nn.sparse_embedding (ps-mode distributed table) —
    the dense mesh-sharded table stands in (VocabParallelEmbedding for
    the sharded case)."""
    return embedding(input, size, padding_idx=padding_idx,
                     param_attr=param_attr, dtype=dtype)


def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout='NCHW',
               in_place=False, name=None, moving_mean_name=None,
               moving_variance_name=None, do_model_average_for_mean_and_var=True,
               use_global_stats=False):
    """ref: static.nn.batch_norm — scope-backed scale/shift + running
    stats (updated in place in the scope during training)."""
    from ..nn import functional as F
    from ..nn import initializer as I

    c_axis = 1 if data_layout == 'NCHW' else -1
    c = input.shape[c_axis]
    base = _name('batch_norm', name)
    scale = _param(base + '.w_0', (c,), I.Constant(1.0))
    shift = _param(base + '.b_0', (c,), None, is_bias=True)
    mean = global_scope().get_or_create(
        moving_mean_name or base + '.mean', lambda: jnp.zeros((c,)))
    var = global_scope().get_or_create(
        moving_variance_name or base + '.var', lambda: jnp.ones((c,)))
    training = not is_test and not use_global_stats
    out, new_mean, new_var = F.batch_norm(
        input, mean, var, scale, shift, training=training,
        momentum=momentum, epsilon=epsilon, data_format=data_layout)
    if training:
        global_scope().set(moving_mean_name or base + '.mean', new_mean)
        global_scope().set(moving_variance_name or base + '.var', new_var)
    if act:
        out = getattr(F, act)(out)
    return out


def data_norm(input, act=None, epsilon=1e-5, param_attr=None,
              data_layout='NCHW', in_place=False, name=None,
              moving_mean_name=None, moving_variance_name=None,
              do_model_average_for_mean_and_var=True, slot_dim=-1,
              sync_stats=False, summary_decay_rate=0.9999999,
              enable_scale_and_shift=False):
    """ref: static.nn.data_norm — normalization by accumulated batch
    statistics (no learned scale unless enabled)."""
    from ..nn import functional as F

    c = input.shape[-1]
    base = _name('data_norm', name)
    ssum = global_scope().get_or_create(base + '.sum', lambda: jnp.zeros((c,)))
    ssqsum = global_scope().get_or_create(base + '.sqsum',
                                          lambda: jnp.zeros((c,)))
    cnt = global_scope().get_or_create(base + '.count',
                                       lambda: jnp.zeros(()))
    x = jnp.asarray(input)
    n = x.reshape(-1, c).shape[0]
    ssum = ssum + x.reshape(-1, c).sum(0)
    ssqsum = ssqsum + (x.reshape(-1, c) ** 2).sum(0)
    cnt = cnt + n
    global_scope().set(base + '.sum', ssum)
    global_scope().set(base + '.sqsum', ssqsum)
    global_scope().set(base + '.count', cnt)
    mean = ssum / jnp.maximum(cnt, 1)
    var = ssqsum / jnp.maximum(cnt, 1) - mean ** 2
    out = (x - mean) / jnp.sqrt(jnp.maximum(var, epsilon))
    if enable_scale_and_shift:
        scale = _param(base + '.w_0', (c,))
        bias = _param(base + '.b_0', (c,), is_bias=True)
        out = out * (1.0 + scale) + bias
    if act:
        out = getattr(F, act)(out)
    return out


def _conv(input, num_filters, filter_size, nd, transpose=False, stride=1,
          padding=0, dilation=1, groups=1, param_attr=None, bias_attr=None,
          act=None, data_format=None, name=None):
    from ..nn import functional as F
    from ..nn import initializer as I

    base = _name('conv', name)
    c_in = input.shape[1 if (data_format or 'NC').startswith('NC') else -1]
    ks = (filter_size,) * nd if isinstance(filter_size, int) \
        else tuple(filter_size)
    if transpose:
        wshape = (c_in, num_filters // groups) + ks
    else:
        wshape = (num_filters, c_in // groups) + ks
    w = _param(base + '.w_0', wshape,
               getattr(param_attr, 'initializer', None) or I.XavierNormal())
    fn = getattr(F, f'conv{nd}d_transpose' if transpose else f'conv{nd}d')
    out = fn(input, w, None, stride=stride, padding=padding,
             dilation=dilation, groups=groups,
             data_format=data_format or ('NCHW' if nd == 2 else 'NCDHW'))
    if bias_attr is not False:
        b = _param(base + '.b_0', (num_filters,), is_bias=True)
        shape = [1] * out.ndim
        shape[1 if (data_format or 'NC').startswith('NC') else -1] = -1
        out = out + b.reshape(shape)
    if act:
        out = getattr(F, act)(out)
    return out


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, use_cudnn=True,
           act=None, name=None, data_format='NCHW'):
    """ref: static.nn.conv2d."""
    return _conv(input, num_filters, filter_size, 2, False, stride, padding,
                 dilation, groups, param_attr, bias_attr, act, data_format,
                 name)


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                     stride=1, padding=0, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None, data_format='NCHW'):
    return _conv(input, num_filters, filter_size, 2, True, stride, padding,
                 dilation, groups, param_attr, bias_attr, act, data_format,
                 name)


def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, use_cudnn=True,
           act=None, name=None, data_format='NCDHW'):
    return _conv(input, num_filters, filter_size, 3, False, stride, padding,
                 dilation, groups, param_attr, bias_attr, act, data_format,
                 name)


def conv3d_transpose(input, num_filters, output_size=None, filter_size=None,
                     stride=1, padding=0, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None, data_format='NCDHW'):
    return _conv(input, num_filters, filter_size, 3, True, stride, padding,
                 dilation, groups, param_attr, bias_attr, act, data_format,
                 name)


def deform_conv2d(input, offset, mask, num_filters, filter_size, stride=1,
                  padding=0, dilation=1, groups=1, deformable_groups=1,
                  im2col_step=1, param_attr=None, bias_attr=None, name=None):
    """ref: static.nn.deform_conv2d — scope-parameterized wrapper over
    the vision op."""
    from ..nn import initializer as I
    from ..vision.ops import deform_conv2d as dcv

    base = _name('deform_conv', name)
    c_in = input.shape[1]
    ks = (filter_size,) * 2 if isinstance(filter_size, int) \
        else tuple(filter_size)
    w = _param(base + '.w_0', (num_filters, c_in // groups) + ks,
               getattr(param_attr, 'initializer', None) or I.XavierNormal())
    b = None if bias_attr is False else _param(base + '.b_0',
                                               (num_filters,), is_bias=True)
    return dcv(input, offset, w, bias=b, stride=stride, padding=padding,
               dilation=dilation, deformable_groups=deformable_groups,
               groups=groups, mask=mask)


def group_norm(input, groups, epsilon=1e-5, param_attr=None, bias_attr=None,
               act=None, data_layout='NCHW', name=None):
    from ..nn import functional as F
    from ..nn import initializer as I

    c = input.shape[1 if data_layout == 'NCHW' else -1]
    base = _name('group_norm', name)
    w = _param(base + '.w_0', (c,), I.Constant(1.0))
    b = _param(base + '.b_0', (c,), is_bias=True)
    out = F.group_norm(input, groups, epsilon=epsilon, weight=w, bias=b,
                       data_format=data_layout)
    if act:
        out = getattr(F, act)(out)
    return out


def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None,
                  name=None):
    from ..nn import functional as F
    from ..nn import initializer as I

    c = input.shape[1]
    base = _name('instance_norm', name)
    w = _param(base + '.scale', (c,), I.Constant(1.0))
    b = _param(base + '.bias', (c,), is_bias=True)
    return F.instance_norm(input, weight=w, bias=b, epsilon=epsilon)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    from ..nn import functional as F
    from ..nn import initializer as I

    shape = tuple(input.shape[begin_norm_axis:])
    base = _name('layer_norm', name)
    w = _param(base + '.w_0', shape, I.Constant(1.0)) if scale else None
    b = _param(base + '.b_0', shape, is_bias=True) if shift else None
    out = F.layer_norm(input, shape, weight=w, bias=b, epsilon=epsilon)
    if act:
        out = getattr(F, act)(out)
    return out


def prelu(x, mode='all', param_attr=None, data_format='NCHW', name=None):
    """ref: static.nn.prelu — modes all/channel/element."""
    from ..nn import functional as F
    from ..nn import initializer as I

    base = _name('prelu', name)
    if mode == 'all':
        shape = (1,)
    elif mode == 'channel':
        shape = (x.shape[1 if data_format == 'NCHW' else -1],)
    else:
        shape = tuple(x.shape[1:])
    alpha = _param(base + '.w_0', shape,
                   getattr(param_attr, 'initializer', None)
                   or I.Constant(0.25))
    return F.prelu(x, alpha, data_format=data_format)


def bilinear_tensor_product(x, y, size, act=None, name=None,
                            param_attr=None, bias_attr=None):
    """ref: static.nn.bilinear_tensor_product."""
    from ..nn import functional as F

    base = _name('bilinear', name)
    w = _param(base + '.w_0', (size, x.shape[-1], y.shape[-1]),
               getattr(param_attr, 'initializer', None))
    out = jnp.einsum('bi,oij,bj->bo', x, w, y)
    if bias_attr is not False:
        out = out + _param(base + '.b_0', (size,), is_bias=True)
    if act:
        out = getattr(F, act)(out)
    return out


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    """ref: static.nn.spectral_norm — normalize by the leading singular
    value (power iteration each call)."""
    w = jnp.moveaxis(jnp.asarray(weight), dim, 0)
    mat = w.reshape(w.shape[0], -1)
    base = _name('spectral_norm', name)
    u = global_scope().get_or_create(
        base + '.u', lambda: jnp.ones((mat.shape[0],)) / np.sqrt(mat.shape[0]))
    # v derives from the stored u even with power_iters=0 (the reference
    # allows 0: reuse the converged direction without refining)
    v = mat.T @ u
    v = v / (jnp.linalg.norm(v) + eps)
    for _ in range(power_iters):
        u = mat @ v
        u = u / (jnp.linalg.norm(u) + eps)
        v = mat.T @ u
        v = v / (jnp.linalg.norm(v) + eps)
    global_scope().set(base + '.u', u)
    sigma = u @ mat @ v
    return (jnp.moveaxis(w, 0, dim) / (sigma + eps)).reshape(weight.shape)


def nce(input, label, num_total_classes, sample_weight=None,
        param_attr=None, bias_attr=None, num_neg_samples=10, name=None,
        sampler='uniform', custom_dist=None, seed=0, is_sparse=False):
    """Noise-contrastive estimation loss (ref: static.nn.nce): one
    positive + k uniform negatives per example, logistic loss."""
    from ..framework import random as random_mod

    base = _name('nce', name)
    d = input.shape[-1]
    w = _param(base + '.w_0', (num_total_classes, d))
    b = _param(base + '.b_0', (num_total_classes,), is_bias=True)
    label = jnp.asarray(label).reshape(-1)
    x = jnp.asarray(input)
    key = random_mod.split_key()
    neg = jax.random.randint(key, (x.shape[0], num_neg_samples), 0,
                             num_total_classes)
    pos_logit = jnp.einsum('bd,bd->b', x, w[label]) + b[label]
    neg_logit = jnp.einsum('bd,bkd->bk', x, w[neg]) + b[neg]
    loss = (jax.nn.softplus(-pos_logit)
            + jax.nn.softplus(neg_logit).sum(-1))
    return loss[:, None]


def row_conv(input, future_context_size, param_attr=None, act=None):
    """ref: static.nn.row_conv — lookahead row convolution over time:
    out[t] = sum_{j=0..k} x[t+j] * w[j]."""
    from ..nn import functional as F

    base = _name('row_conv', None)
    d = input.shape[-1]
    k = future_context_size + 1
    w = _param(base + '.w_0', (k, d),
               getattr(param_attr, 'initializer', None))
    x = jnp.asarray(input)            # (B, T, D)
    pad = jnp.pad(x, ((0, 0), (0, future_context_size), (0, 0)))
    out = sum(pad[:, j:j + x.shape[1]] * w[j] for j in range(k))
    if act:
        out = getattr(F, act)(out)
    return out


def static_pylayer(forward_fn, inputs, backward_fn=None, name=None):
    """ref: static.nn.static_pylayer — custom forward/backward pair
    (jax.custom_vjp under the hood)."""
    if backward_fn is None:
        return forward_fn(*inputs)

    @jax.custom_vjp
    def op(*args):
        return forward_fn(*args)

    def fwd(*args):
        return forward_fn(*args), args

    def bwd(res, g):
        out = backward_fn(g)
        return tuple(out) if isinstance(out, (list, tuple)) else (out,)

    op.defvjp(fwd, bwd)
    return op(*inputs)


# ---- sequence (LoD) ops -----------------------------------------------------
# Padded layout (B, T, ...) + explicit `lengths` replaces LoD metadata.


def _time_mask(lengths, t):
    return (jnp.arange(t)[None] < jnp.asarray(lengths)[:, None])


def sequence_conv(input, lengths=None, num_filters=None, filter_size=3,
                  filter_stride=1, padding=True, padding_start=None,
                  bias_attr=None, param_attr=None, act=None, name=None):
    """ref: static.nn.sequence_conv — 1-D context conv over time."""
    from ..nn import functional as F

    base = _name('sequence_conv', name)
    b, t, d = input.shape
    w = _param(base + '.w_0', (filter_size * d, num_filters),
               getattr(param_attr, 'initializer', None))
    start = padding_start if padding_start is not None \
        else -((filter_size - 1) // 2)
    cols = []
    x = jnp.asarray(input)
    for j in range(filter_size):
        off = start + j
        shifted = jnp.roll(x, -off, axis=1)
        idx = jnp.arange(t) + off
        valid = (idx >= 0) & (idx < t)
        cols.append(jnp.where(valid[None, :, None], shifted, 0.0))
    ctx = jnp.concatenate(cols, axis=-1)          # (B, T, k*D)
    out = ctx @ w
    if bias_attr is not False:
        out = out + _param(base + '.b_0', (num_filters,), is_bias=True)
    if lengths is not None:
        out = out * _time_mask(lengths, t)[..., None]
    if act:
        out = getattr(F, act)(out)
    return out


def sequence_softmax(input, lengths=None, use_cudnn=False, name=None):
    """ref: static.nn.sequence_softmax — softmax within each sequence."""
    x = jnp.asarray(input)
    if lengths is None:
        return jax.nn.softmax(x, axis=1)
    mask = _time_mask(lengths, x.shape[1])
    logits = jnp.where(mask if x.ndim == 2 else mask[..., None],
                       x, -1e30)
    return jax.nn.softmax(logits, axis=1)


def sequence_pool(input, pool_type, lengths=None, is_test=False, pad_value=0.0):
    """ref: static.nn.sequence_pool — sum/average/sqrt/max/last/first."""
    x = jnp.asarray(input)
    b, t = x.shape[:2]
    if lengths is None:
        lengths = jnp.full((b,), t)
    lengths = jnp.asarray(lengths)
    mask = _time_mask(lengths, t)
    m = mask[..., None] if x.ndim == 3 else mask
    pool_type = pool_type.lower()
    if pool_type == 'sum':
        return jnp.sum(x * m, axis=1)
    if pool_type == 'average':
        return jnp.sum(x * m, axis=1) / jnp.maximum(
            lengths[:, None].astype(x.dtype), 1)
    if pool_type == 'sqrt':
        return jnp.sum(x * m, axis=1) / jnp.sqrt(jnp.maximum(
            lengths[:, None].astype(x.dtype), 1))
    if pool_type == 'max':
        return jnp.max(jnp.where(m, x, -jnp.inf), axis=1)
    if pool_type == 'first':
        return x[:, 0]
    if pool_type == 'last':
        idx = jnp.maximum(lengths - 1, 0)
        return x[jnp.arange(b), idx]
    raise ValueError(f'bad pool_type {pool_type}')


def sequence_first_step(input, lengths=None):
    return sequence_pool(input, 'first', lengths)


def sequence_last_step(input, lengths=None):
    return sequence_pool(input, 'last', lengths)


def sequence_slice(input, offset, length, name=None):
    """ref: static.nn.sequence_slice — per-sequence [offset, offset+len)
    window, re-padded to max(length)."""
    x = jnp.asarray(input)
    offset = jnp.asarray(offset).reshape(-1)
    length = jnp.asarray(length).reshape(-1)
    t = x.shape[1]
    out_t = int(np.max(np.asarray(length)))
    idx = offset[:, None] + jnp.arange(out_t)[None]
    take = jnp.take_along_axis(
        x, jnp.clip(idx, 0, t - 1)[..., None] if x.ndim == 3 else jnp.clip(idx, 0, t - 1),
        axis=1)
    mask = jnp.arange(out_t)[None] < length[:, None]
    return take * (mask[..., None] if x.ndim == 3 else mask)


def sequence_expand(x, y_lengths, ref_level=-1, name=None):
    """ref: static.nn.sequence_expand — repeat row i of x `y_lengths[i]`
    times (static output uses max length with zero padding)."""
    x = jnp.asarray(x)
    reps = np.asarray(y_lengths).reshape(-1)
    pieces = [np.repeat(np.asarray(x[i:i + 1]), int(reps[i]), axis=0)
              for i in range(x.shape[0])]
    return jnp.asarray(np.concatenate(pieces, axis=0))


def sequence_expand_as(x, y, name=None):
    """ref: static.nn.sequence_expand_as — expand x rows to y's row
    count (uniform factor)."""
    x = jnp.asarray(x)
    factor = jnp.asarray(y).shape[0] // x.shape[0]
    return jnp.repeat(x, factor, axis=0)


def sequence_pad(x, pad_value, lengths, maxlen=None, name=None):
    """ref: static.nn.sequence_pad — (packed rows, lengths) -> padded
    (B, T, ...) + lengths."""
    x = np.asarray(x)
    lengths = np.asarray(lengths).reshape(-1)
    t = int(maxlen or lengths.max())
    feat = x.shape[1:]
    out = np.full((len(lengths), t) + feat, np.asarray(pad_value),
                  dtype=x.dtype)
    off = 0
    for i, n in enumerate(lengths):
        out[i, :n] = x[off:off + n]
        off += n
    return jnp.asarray(out), jnp.asarray(lengths)


def sequence_unpad(x, length, name=None):
    """ref: static.nn.sequence_unpad — padded -> packed rows."""
    x = np.asarray(x)
    length = np.asarray(length).reshape(-1)
    return jnp.asarray(np.concatenate(
        [x[i, :n] for i, n in enumerate(length)], axis=0))


def sequence_reshape(input, new_dim, lengths=None):
    """ref: static.nn.sequence_reshape — refold the feature dim of
    packed rows."""
    x = jnp.asarray(input)
    return x.reshape(-1, new_dim)


def sequence_scatter(input, index, updates, name=None):
    """ref: static.nn.sequence_scatter — add updates at (seq, idx)."""
    x = jnp.asarray(input)
    index = np.asarray(index).reshape(len(x), -1)
    updates = jnp.asarray(updates).reshape(index.shape)
    rows = np.repeat(np.arange(index.shape[0]), index.shape[1])
    return x.at[rows, index.reshape(-1)].add(updates.reshape(-1))


def sequence_enumerate(input, win_size, pad_value=0, name=None):
    """ref: static.nn.sequence_enumerate — sliding windows of ids."""
    x = jnp.asarray(input)
    b, t = x.shape[:2]
    pad = jnp.pad(x, ((0, 0), (0, win_size - 1)),
                  constant_values=pad_value)
    return jnp.stack([pad[:, j:j + t] for j in range(win_size)], axis=-1)
