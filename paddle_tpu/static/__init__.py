"""Static-graph compatibility namespace (ref: python/paddle/static).

Paddle's static graph is replaced wholesale by jax tracing; this module
keeps the API names that still make sense: `InputSpec` for shape/dtype
declarations and the control-flow primitives (`cond`, `while_loop`,
`case`, `switch_case`) that lower to XLA's structured control flow
(ref: python/paddle/static/nn/control_flow.py).
"""
from __future__ import annotations

import jax
from jax import lax

from ..jit import InputSpec  # noqa: F401


def cond(pred, true_fn, false_fn, *operands):
    """ref: paddle.static.nn.cond → lax.cond (both branches traced)."""
    return lax.cond(pred, true_fn, false_fn, *operands)


def while_loop(cond_fn, body_fn, loop_vars):
    """ref: paddle.static.nn.while_loop. loop_vars is a pytree carried
    through `body_fn`; XLA compiles one rolled loop."""
    if isinstance(loop_vars, (list, tuple)):
        out = lax.while_loop(lambda v: cond_fn(*v), lambda v: tuple(body_fn(*v)),
                             tuple(loop_vars))
        return list(out) if isinstance(loop_vars, list) else out
    return lax.while_loop(cond_fn, body_fn, loop_vars)


def scan(fn, init, xs, length=None, reverse=False, unroll=1):
    """lax.scan re-export (the graph-mode RNN/decode primitive)."""
    return lax.scan(fn, init, xs, length=length, reverse=reverse,
                    unroll=unroll)


def case(pred_fn_pairs, default=None):
    """ref: paddle.static.nn.case — first true predicate wins."""
    if not pred_fn_pairs:
        raise ValueError('pred_fn_pairs must be non-empty')

    def build(pairs):
        (pred, fn), *rest = pairs
        if not rest:
            if default is None:
                return fn()
            return lax.cond(pred, fn, default)
        return lax.cond(pred, fn, lambda: build(rest))

    return build(list(pred_fn_pairs))


def switch_case(branch_index, branch_fns, default=None):
    """ref: paddle.static.nn.switch_case → lax.switch."""
    if isinstance(branch_fns, dict):
        keys = sorted(branch_fns)
        fns = [branch_fns[k] for k in keys]
        # map arbitrary keys to dense switch via searchsorted-style select
        import jax.numpy as jnp

        idx = jnp.sum(jnp.asarray([branch_index == k for k in keys])
                      * jnp.arange(1, len(keys) + 1)) - 1
        if default is not None:
            fns = fns + [default]
            idx = jnp.where(idx < 0, len(fns) - 1, idx)
        return lax.switch(jnp.clip(idx, 0, len(fns) - 1), fns)
    fns = list(branch_fns)
    if default is not None:
        fns = fns + [default]
    return lax.switch(branch_index, fns)


# program / executor / inference-io compatibility (see compat.py)
from .compat import (  # noqa: F401,E402
    BuildStrategy,
    CompiledProgram,
    Executor,
    ExecutionStrategy,
    IpuCompiledProgram,
    IpuStrategy,
    Print,
    Program,
    WeightNormParamAttr,
    append_backward,
    data,
    default_main_program,
    default_startup_program,
    deserialize_persistables,
    deserialize_program,
    global_scope,
    gradients,
    ipu_shard_guard,
    load_from_file,
    load_inference_model,
    name_scope,
    normalize_program,
    program_guard,
    py_func,
    save_inference_model,
    save_to_file,
    scope_guard,
    serialize_persistables,
    serialize_program,
)
from .compat import load, save  # noqa: F401,E402
from .compat import (  # noqa: F401,E402
    Variable,
    accuracy,
    auc,
    cpu_places,
    create_global_var,
    create_parameter,
    ctr_metric_bundle,
    cuda_places,
    device_guard,
    load_program_state,
    set_ipu_shard,
    set_program_state,
    xpu_places,
)
from ..optimizer.wrappers import ExponentialMovingAverage  # noqa: F401,E402
from . import nn  # noqa: F401,E402
