"""DLPack interop (ref: python/paddle/utils/dlpack.py).

Zero-copy(ish) tensor exchange with torch/numpy/any DLPack producer —
jax arrays natively speak the protocol; these wrappers give the
reference's to_dlpack/from_dlpack names and make the round trip
``from_dlpack(to_dlpack(x))`` work like the reference's.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ['to_dlpack', 'from_dlpack']


def to_dlpack(x):
    """ref: paddle.utils.dlpack.to_dlpack — export for DLPack consumers.

    Returns an object implementing ``__dlpack__``/``__dlpack_device__``
    (jax arrays speak the protocol natively), which both
    ``torch.from_dlpack`` and this module's ``from_dlpack`` accept.
    TPU-backed arrays are copied to host first: DLPack export only
    covers CPU/GPU buffers, so the exchange costs one device->host
    transfer there.
    """
    try:
        platform = list(x.devices())[0].platform
    except Exception:
        platform = 'cpu'
    if platform not in ('cpu', 'cuda', 'gpu', 'rocm'):
        x = jax.device_put(x, jax.devices('cpu')[0])
    return x


class _CapsuleWrapper:
    """Adapt a raw DLPack PyCapsule (legacy producers) to the
    object-protocol jax's importer requires. A bare capsule carries no
    device info; host memory is assumed (kDLCPU), matching what
    legacy-style producers hand over."""

    def __init__(self, capsule):
        self._capsule = capsule

    def __dlpack__(self, **kwargs):
        return self._capsule

    def __dlpack_device__(self):
        return (1, 0)  # (kDLCPU, device 0)


def from_dlpack(dlpack):
    """ref: paddle.utils.dlpack.from_dlpack — import from any DLPack
    protocol object (torch tensor, numpy array, jax array, ...) or a
    raw legacy capsule."""
    if hasattr(dlpack, '__dlpack__'):
        return jnp.from_dlpack(dlpack)
    return jnp.from_dlpack(_CapsuleWrapper(dlpack))
