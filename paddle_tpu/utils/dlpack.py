"""DLPack interop (ref: python/paddle/utils/dlpack.py).

Zero-copy(ish) tensor exchange with torch/numpy/any DLPack producer —
jax arrays natively speak the protocol; these wrappers give the
reference's to_dlpack/from_dlpack names.
"""
from __future__ import annotations

import jax.numpy as jnp

__all__ = ['to_dlpack', 'from_dlpack']


def to_dlpack(x):
    """ref: paddle.utils.dlpack.to_dlpack — export a DLPack capsule.

    Also fine: pass the jax array straight to any consumer that accepts
    objects implementing ``__dlpack__`` (torch.from_dlpack(x) works).
    """
    return x.__dlpack__()


def from_dlpack(dlpack):
    """ref: paddle.utils.dlpack.from_dlpack — import from a capsule or
    any object implementing the DLPack protocol (torch tensor, numpy
    array, cupy, ...)."""
    return jnp.from_dlpack(dlpack)
