"""Custom-op extension surface (ref: python/paddle/utils/cpp_extension).

The reference builds CUDA/C++ custom operators against the Phi kernel
ABI. On TPU that ABI does not exist: XLA owns code generation, so
custom compute belongs in a pallas kernel (device) or a `jax.ffi` /
ctypes-wrapped native library (host). These entry points keep ported
build scripts importable and fail with the migration path instead of a
missing-symbol error at runtime.
"""
from __future__ import annotations

__all__ = ['CppExtension', 'CUDAExtension', 'load', 'setup']

_GUIDE = (
    'custom C++/CUDA operators target the reference\'s Phi kernel ABI, '
    'which has no TPU equivalent. Port the compute to: (1) a pallas TPU '
    'kernel (paddle_tpu/ops/pallas has five worked examples), (2) plain '
    'jax.numpy (XLA fuses it), or (3) for host-side native code, a '
    'ctypes/cffi-wrapped shared library like paddle_tpu/_native. '
    'See docs/migration.md.'
)


def CppExtension(*args, **kwargs):
    raise NotImplementedError(f'CppExtension: {_GUIDE}')


def CUDAExtension(*args, **kwargs):
    raise NotImplementedError(f'CUDAExtension: {_GUIDE}')


def load(name=None, sources=None, **kwargs):
    raise NotImplementedError(f'cpp_extension.load: {_GUIDE}')


def setup(**kwargs):
    raise NotImplementedError(f'cpp_extension.setup: {_GUIDE}')
