"""Utilities (ref: python/paddle/utils)."""
from .unique_name import generate, guard, switch  # noqa: F401
from .flops import flops  # noqa: F401

try:  # optional alias namespace
    from . import download  # noqa: F401
except ImportError:  # pragma: no cover
    pass


def run_check():
    """ref: paddle.utils.run_check — sanity-check the install + device."""
    import jax
    import jax.numpy as jnp

    x = jnp.ones((128, 128))
    y = (x @ x).block_until_ready()
    dev = jax.devices()[0]
    print(f'paddle_tpu is installed successfully! '
          f'backend={jax.default_backend()} device={dev.device_kind} '
          f'check={float(y[0, 0])}')
    return True


def try_import(name):
    import importlib

    try:
        return importlib.import_module(name)
    except ImportError:
        return None


def deprecated(update_to='', since='', reason='', level=0):
    """ref: paddle.utils.deprecated — decorator emitting a
    DeprecationWarning on first call."""
    import functools
    import warnings

    def wrap(fn):
        @functools.wraps(fn)
        def inner(*args, **kwargs):
            msg = f'API {fn.__name__} is deprecated'
            if since:
                msg += f' since {since}'
            if update_to:
                msg += f'; use {update_to} instead'
            if reason:
                msg += f' ({reason})'
            warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return fn(*args, **kwargs)

        return inner

    return wrap


def require_version(min_version, max_version=None):
    """ref: paddle.utils.require_version — version gate against this
    package's version."""
    from .. import __version__ as ver

    def parse(v):
        return tuple(int(p) for p in str(v).split('.')[:3] if p.isdigit())

    cur = parse(ver)
    if parse(min_version) > cur:
        raise RuntimeError(f'requires version >= {min_version}, have {ver}')
    if max_version is not None and parse(max_version) < cur:
        raise RuntimeError(f'requires version <= {max_version}, have {ver}')
    return True
from . import cpp_extension  # noqa: F401
from . import dlpack  # noqa: F401
