"""Utilities (ref: python/paddle/utils)."""
from .unique_name import generate, guard, switch  # noqa: F401
from .flops import flops  # noqa: F401

try:  # optional alias namespace
    from . import download  # noqa: F401
except ImportError:  # pragma: no cover
    pass


def run_check():
    """ref: paddle.utils.run_check — sanity-check the install + device."""
    import jax
    import jax.numpy as jnp

    x = jnp.ones((128, 128))
    y = (x @ x).block_until_ready()
    dev = jax.devices()[0]
    print(f'paddle_tpu is installed successfully! '
          f'backend={jax.default_backend()} device={dev.device_kind} '
          f'check={float(y[0, 0])}')
    return True


def try_import(name):
    import importlib

    try:
        return importlib.import_module(name)
    except ImportError:
        return None
