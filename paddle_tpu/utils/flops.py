"""FLOPs estimation (ref: python/paddle/hapi/dynamic_flops.py).

TPU-native: instead of per-layer hooks, trace the model with jax and
read XLA's own cost analysis — exact for whatever fuses to the device.
The quirk handling (list-vs-dict returns, backends that raise) lives in
observability.costs.analyze, shared with profiler.op_summary,
jit.compilation_report, and the AOT manifest's cost stamps.
"""
from __future__ import annotations


def flops(net, input_size=None, inputs=None, custom_ops=None, print_detail=False):
    """Returns total FLOPs of one forward pass (XLA cost analysis)."""
    import jax
    import jax.numpy as jnp

    from ..observability.costs import analyze

    if inputs is None:
        if input_size is None:
            raise ValueError('provide input_size or inputs')
        inputs = (jnp.zeros(tuple(input_size), jnp.float32),)
    elif not isinstance(inputs, (list, tuple)):
        inputs = (inputs,)

    # tracelint: disable=TL001 - one-shot FLOPs analysis, never executed
    lowered = jax.jit(lambda m, *xs: m(*xs)).lower(net, *inputs)
    total = int(analyze(lowered)['flops'] or 0)
    if print_detail:
        print(f'Total FLOPs: {total:,}')
    return total
