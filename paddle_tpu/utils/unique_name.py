"""Unique name generator (ref: python/paddle/utils/unique_name.py)."""
from __future__ import annotations

import contextlib


class _Generator:
    def __init__(self):
        self.ids = {}
        self.prefix = ''

    def __call__(self, key):
        self.ids.setdefault(key, 0)
        name = f'{self.prefix}{key}_{self.ids[key]}'
        self.ids[key] += 1
        return name


_generator = _Generator()


def generate(key):
    return _generator(key)


def switch(new_generator=None):
    global _generator
    old = _generator
    _generator = new_generator or _Generator()
    return old


@contextlib.contextmanager
def guard(new_generator=None):
    if isinstance(new_generator, str):
        g = _Generator()
        g.prefix = new_generator
        new_generator = g
    old = switch(new_generator)
    try:
        yield
    finally:
        switch(old)
