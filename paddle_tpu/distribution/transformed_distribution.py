"""TransformedDistribution (ref: python/paddle/distribution/
transformed_distribution.py): push a base distribution through a chain
of bijective transforms."""
from __future__ import annotations

import jax.numpy as jnp

from .distribution import Distribution
from .transform import ChainTransform, Transform


class TransformedDistribution(Distribution):
    """y = T(x), x ~ base. log p(y) = log p_base(T⁻¹(y)) + log|det J_T⁻¹|."""

    def __init__(self, base, transforms):
        if isinstance(transforms, Transform):
            transforms = [transforms]
        self.base = base
        self.transform = (transforms[0] if len(transforms) == 1
                          else ChainTransform(transforms))
        base_event = base.batch_shape + base.event_shape
        out = self.transform.forward_shape(base_event)
        # event rank grows to at least the transform's event rank
        ev = max(len(base.event_shape), self.transform.event_rank)
        super().__init__(out[:len(out) - ev], out[len(out) - ev:])

    def rsample(self, shape=(), key=None):
        return self.transform.forward(self.base.rsample(shape, key))

    def sample(self, shape=(), key=None):
        return self.transform.forward(self.base.sample(shape, key))

    def log_prob(self, value):
        x = self.transform.inverse(value)
        ildj = -self.transform.forward_log_det_jacobian(x)
        lp = self.base.log_prob(x)
        # reduce base log_prob over dims the transform absorbed into the
        # event (elementwise base + event_rank>0 transform)
        extra = self.transform.event_rank - len(self.base.event_shape)
        if extra > 0:
            lp = jnp.sum(lp, axis=tuple(range(-extra, 0)))
        return lp + ildj
