"""Discrete distributions (ref: python/paddle/distribution/{bernoulli,
binomial,categorical,geometric,multinomial,poisson}.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.scipy import special as jss

from .distribution import Distribution, ExponentialFamily


def _f(x):
    return jnp.asarray(x, jnp.result_type(float))


def _probs_to_logits(probs):
    return jnp.log(probs) - jnp.log1p(-probs)


class Bernoulli(ExponentialFamily):
    """ref: paddle.distribution.Bernoulli(probs)."""

    def __init__(self, probs=None, logits=None):
        if (probs is None) == (logits is None):
            raise ValueError('pass exactly one of probs/logits')
        if probs is not None:
            self.probs = _f(probs)
            self.logits = _probs_to_logits(self.probs)
        else:
            self.logits = _f(logits)
            self.probs = jax.nn.sigmoid(self.logits)
        super().__init__(self.probs.shape)

    @property
    def mean(self):
        return self.probs

    @property
    def variance(self):
        return self.probs * (1 - self.probs)

    def sample(self, shape=(), key=None):
        return jax.random.bernoulli(self._key(key), self.probs,
                                    self._extend(shape)).astype(
                                        self.probs.dtype)

    def rsample(self, shape=(), key=None, temperature=1.0):
        """Gumbel-sigmoid relaxation (ref: Bernoulli.rsample temperature)."""
        u = jax.random.uniform(self._key(key), self._extend(shape),
                               minval=1e-6, maxval=1 - 1e-6)
        logistic = jnp.log(u) - jnp.log1p(-u)
        return jax.nn.sigmoid((self.logits + logistic) / temperature)

    def log_prob(self, value):
        v = _f(value)
        return -jax.nn.softplus(jnp.where(v > 0.5, -self.logits, self.logits))

    def entropy(self):
        p = self.probs
        return -(jss.xlogy(p, p) + jss.xlog1py(1 - p, -p))

    def cdf(self, value):
        v = _f(value)
        return jnp.where(v < 0, 0.0, jnp.where(v < 1, 1 - self.probs, 1.0))


class Geometric(Distribution):
    """ref: paddle.distribution.Geometric(probs) — pmf (1-p)^k p, k>=0."""

    def __init__(self, probs):
        self.probs = _f(probs)
        super().__init__(self.probs.shape)

    @property
    def mean(self):
        return (1 - self.probs) / self.probs

    @property
    def variance(self):
        return (1 - self.probs) / self.probs ** 2

    def sample(self, shape=(), key=None):
        u = jax.random.uniform(self._key(key), self._extend(shape),
                               minval=jnp.finfo(jnp.float32).tiny)
        return jnp.floor(jnp.log(u) / jnp.log1p(-self.probs))

    def log_prob(self, value):
        k = _f(value)
        return jss.xlog1py(k, -self.probs) + jnp.log(self.probs)

    def entropy(self):
        p = self.probs
        return -(jss.xlog1py(1 - p, -p) + jss.xlogy(p, p)) / p

    def cdf(self, value):
        return -jnp.expm1(jss.xlog1py(jnp.floor(_f(value)) + 1, -self.probs))


class Categorical(Distribution):
    """ref: paddle.distribution.Categorical(logits) over the last axis."""

    def __init__(self, logits=None, probs=None):
        if (probs is None) == (logits is None):
            raise ValueError('pass exactly one of probs/logits')
        if logits is not None:
            self.logits = jax.nn.log_softmax(_f(logits), -1)
        else:
            self.logits = jnp.log(_f(probs)
                                  / jnp.sum(_f(probs), -1, keepdims=True))
        self.probs = jnp.exp(self.logits)
        super().__init__(self.logits.shape[:-1])

    @property
    def num_categories(self):
        return self.logits.shape[-1]

    @property
    def mean(self):
        return jnp.sum(self.probs * jnp.arange(self.num_categories), -1)

    @property
    def variance(self):
        idx = jnp.arange(self.num_categories)
        m = self.mean[..., None]
        return jnp.sum(self.probs * (idx - m) ** 2, -1)

    def sample(self, shape=(), key=None):
        return jax.random.categorical(self._key(key), self.logits,
                                      shape=self._extend(shape))

    def log_prob(self, value):
        v = jnp.asarray(value, jnp.int32)
        return jnp.take_along_axis(
            jnp.broadcast_to(self.logits, v.shape + self.logits.shape[-1:]),
            v[..., None], -1)[..., 0]

    def probs_of(self, value):
        """ref: Categorical.probs(value) (renamed: `probs` is the param)."""
        return jnp.exp(self.log_prob(value))

    def entropy(self):
        return -jnp.sum(self.probs * self.logits, -1)


class Multinomial(Distribution):
    """ref: paddle.distribution.Multinomial(total_count, probs)."""

    def __init__(self, total_count, probs):
        self.total_count = int(total_count)
        p = _f(probs)
        self.probs = p / jnp.sum(p, -1, keepdims=True)
        self.logits = jnp.log(self.probs)
        super().__init__(self.probs.shape[:-1], self.probs.shape[-1:])

    @property
    def mean(self):
        return self.total_count * self.probs

    @property
    def variance(self):
        return self.total_count * self.probs * (1 - self.probs)

    def sample(self, shape=(), key=None):
        # n iid categorical draws, counted per bucket — static shapes
        draws = jax.random.categorical(
            self._key(key), self.logits,
            shape=(self.total_count,) + self._extend(shape))
        onehot = jax.nn.one_hot(draws, self.probs.shape[-1],
                                dtype=self.probs.dtype)
        return jnp.sum(onehot, axis=0)

    def log_prob(self, value):
        v = _f(value)
        coeff = jss.gammaln(jnp.asarray(self.total_count + 1.0)) - jnp.sum(
            jss.gammaln(v + 1), -1)
        return coeff + jnp.sum(jss.xlogy(v, self.probs), -1)


class Binomial(Distribution):
    """ref: paddle.distribution.Binomial(total_count, probs)."""

    def __init__(self, total_count, probs):
        self.total_count = int(total_count)
        self.probs = _f(probs)
        super().__init__(self.probs.shape)

    @property
    def mean(self):
        return self.total_count * self.probs

    @property
    def variance(self):
        return self.total_count * self.probs * (1 - self.probs)

    def sample(self, shape=(), key=None):
        draws = jax.random.bernoulli(
            self._key(key), self.probs,
            (self.total_count,) + self._extend(shape))
        return jnp.sum(draws.astype(self.probs.dtype), axis=0)

    def log_prob(self, value):
        v = _f(value)
        n = float(self.total_count)
        coeff = (jss.gammaln(jnp.asarray(n + 1.0)) - jss.gammaln(v + 1)
                 - jss.gammaln(n - v + 1))
        return coeff + jss.xlogy(v, self.probs) + jss.xlog1py(n - v,
                                                              -self.probs)

    def entropy(self):
        # exact summation over the (static) support — same approach the
        # reference uses for distributions without a closed form
        k = jnp.arange(self.total_count + 1.0)
        shape = (self.total_count + 1,) + (1,) * self.probs.ndim
        lp = self.log_prob(k.reshape(shape))
        return -jnp.sum(jnp.exp(lp) * lp, axis=0)


class Poisson(ExponentialFamily):
    """ref: paddle.distribution.Poisson(rate)."""

    # truncation depth for the entropy summation (no closed form exists;
    # covers rates up to ~200 at fp32 accuracy)
    _ENTROPY_TERMS = 512

    def __init__(self, rate):
        self.rate = _f(rate)
        super().__init__(self.rate.shape)

    @property
    def mean(self):
        return self.rate

    @property
    def variance(self):
        return self.rate

    def sample(self, shape=(), key=None):
        return jax.random.poisson(self._key(key), self.rate,
                                  self._extend(shape)).astype(self.rate.dtype)

    def log_prob(self, value):
        v = _f(value)
        return jss.xlogy(v, self.rate) - self.rate - jss.gammaln(v + 1)

    def entropy(self):
        k = jnp.arange(float(self._ENTROPY_TERMS))
        shape = (self._ENTROPY_TERMS,) + (1,) * self.rate.ndim
        lp = self.log_prob(k.reshape(shape))
        return -jnp.sum(jnp.exp(lp) * lp, axis=0)
