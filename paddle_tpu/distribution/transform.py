"""Bijective transforms (ref: python/paddle/distribution/transform.py).

Each transform maps x → y with a tracked log|det J|; compose with
TransformedDistribution for reparameterized flows. All ops are jnp
elementwise/softmax primitives, so transforms jit and differentiate.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp


class Transform:
    """Base bijector. `event_rank` is the event ndim the log-det sums over
    (0 = elementwise)."""

    event_rank = 0

    def forward(self, x):
        raise NotImplementedError

    def inverse(self, y):
        raise NotImplementedError

    def forward_log_det_jacobian(self, x):
        raise NotImplementedError

    def inverse_log_det_jacobian(self, y):
        return -self.forward_log_det_jacobian(self.inverse(y))

    def forward_shape(self, shape):
        return tuple(shape)

    def inverse_shape(self, shape):
        return tuple(shape)

    def __call__(self, x):
        return self.forward(x)


class AffineTransform(Transform):
    """y = loc + scale * x."""

    def __init__(self, loc, scale):
        self.loc = jnp.asarray(loc, jnp.result_type(float))
        self.scale = jnp.asarray(scale, jnp.result_type(float))

    def forward(self, x):
        return self.loc + self.scale * x

    def inverse(self, y):
        return (y - self.loc) / self.scale

    def forward_log_det_jacobian(self, x):
        return jnp.broadcast_to(jnp.log(jnp.abs(self.scale)), jnp.shape(x))


class ExpTransform(Transform):
    """y = exp(x)."""

    def forward(self, x):
        return jnp.exp(x)

    def inverse(self, y):
        return jnp.log(y)

    def forward_log_det_jacobian(self, x):
        return x


class PowerTransform(Transform):
    """y = x ** power (x > 0)."""

    def __init__(self, power):
        self.power = jnp.asarray(power, jnp.result_type(float))

    def forward(self, x):
        return jnp.power(x, self.power)

    def inverse(self, y):
        return jnp.power(y, 1.0 / self.power)

    def forward_log_det_jacobian(self, x):
        return jnp.log(jnp.abs(self.power * jnp.power(x, self.power - 1)))


class SigmoidTransform(Transform):
    """y = sigmoid(x)."""

    def forward(self, x):
        return jax.nn.sigmoid(x)

    def inverse(self, y):
        return jnp.log(y) - jnp.log1p(-y)

    def forward_log_det_jacobian(self, x):
        return -jax.nn.softplus(-x) - jax.nn.softplus(x)


class TanhTransform(Transform):
    """y = tanh(x)."""

    def forward(self, x):
        return jnp.tanh(x)

    def inverse(self, y):
        return jnp.arctanh(y)

    def forward_log_det_jacobian(self, x):
        # log(1 - tanh^2 x) = 2(log2 - x - softplus(-2x)), numerically safe
        return 2.0 * (math.log(2.0) - x - jax.nn.softplus(-2.0 * x))


class AbsTransform(Transform):
    """y = |x| (not bijective; inverse returns the positive branch)."""

    def forward(self, x):
        return jnp.abs(x)

    def inverse(self, y):
        return y

    def forward_log_det_jacobian(self, x):
        return jnp.zeros_like(x)


class ChainTransform(Transform):
    """Composition t_n ∘ … ∘ t_1 applied left-to-right."""

    def __init__(self, transforms):
        self.transforms = list(transforms)
        self.event_rank = max([t.event_rank for t in self.transforms],
                              default=0)

    def forward(self, x):
        for t in self.transforms:
            x = t.forward(x)
        return x

    def inverse(self, y):
        for t in reversed(self.transforms):
            y = t.inverse(y)
        return y

    def forward_log_det_jacobian(self, x):
        ldj = 0.0
        for t in self.transforms:
            part = t.forward_log_det_jacobian(x)
            # lift elementwise parts to this chain's event rank
            extra = self.event_rank - t.event_rank
            if extra > 0:
                part = jnp.sum(part, axis=tuple(range(-extra, 0)))
            ldj = ldj + part
            x = t.forward(x)
        return ldj

    def forward_shape(self, shape):
        for t in self.transforms:
            shape = t.forward_shape(shape)
        return shape

    def inverse_shape(self, shape):
        for t in reversed(self.transforms):
            shape = t.inverse_shape(shape)
        return shape


class IndependentTransform(Transform):
    """Sum the base transform's log-det over trailing batch dims."""

    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self.reinterpreted_batch_rank = int(reinterpreted_batch_rank)
        self.event_rank = base.event_rank + self.reinterpreted_batch_rank

    def forward(self, x):
        return self.base.forward(x)

    def inverse(self, y):
        return self.base.inverse(y)

    def forward_log_det_jacobian(self, x):
        ldj = self.base.forward_log_det_jacobian(x)
        if self.reinterpreted_batch_rank == 0:
            return ldj
        return jnp.sum(ldj, axis=tuple(range(-self.reinterpreted_batch_rank,
                                             0)))


class ReshapeTransform(Transform):
    """Reshape the event block; volume-preserving (log-det 0)."""

    def __init__(self, in_event_shape, out_event_shape):
        self.in_event_shape = tuple(in_event_shape)
        self.out_event_shape = tuple(out_event_shape)
        import numpy as np

        if int(np.prod(self.in_event_shape)) != int(
                np.prod(self.out_event_shape)):
            raise ValueError('in/out event sizes differ')
        self.event_rank = len(self.in_event_shape)

    def forward(self, x):
        batch = x.shape[:x.ndim - len(self.in_event_shape)]
        return x.reshape(batch + self.out_event_shape)

    def inverse(self, y):
        batch = y.shape[:y.ndim - len(self.out_event_shape)]
        return y.reshape(batch + self.in_event_shape)

    def forward_log_det_jacobian(self, x):
        batch = x.shape[:x.ndim - len(self.in_event_shape)]
        return jnp.zeros(batch, x.dtype)

    def forward_shape(self, shape):
        n = len(self.in_event_shape)
        return tuple(shape[:-n]) + self.out_event_shape if n else tuple(shape)

    def inverse_shape(self, shape):
        n = len(self.out_event_shape)
        return tuple(shape[:-n]) + self.in_event_shape if n else tuple(shape)


class SoftmaxTransform(Transform):
    """y = softmax(x) over the last axis (not bijective: inverse returns
    log y, normalised up to a constant — matches the reference)."""

    event_rank = 1

    def forward(self, x):
        return jax.nn.softmax(x, -1)

    def inverse(self, y):
        return jnp.log(y)

    def forward_log_det_jacobian(self, x):
        raise NotImplementedError('softmax is not bijective')


class StackTransform(Transform):
    """Apply transforms[i] to slice i along `axis`."""

    def __init__(self, transforms, axis=0):
        self.transforms = list(transforms)
        self.axis = int(axis)

    def _map(self, method, x):
        parts = [getattr(t, method)(xi) for t, xi in zip(
            self.transforms,
            jnp.split(x, len(self.transforms), axis=self.axis))]
        return jnp.concatenate(parts, axis=self.axis)

    def forward(self, x):
        return self._map('forward', x)

    def inverse(self, y):
        return self._map('inverse', y)

    def forward_log_det_jacobian(self, x):
        return self._map('forward_log_det_jacobian', x)


class StickBreakingTransform(Transform):
    """R^{K-1} → open (K)-simplex via stick breaking (ref:
    transform.py::StickBreakingTransform)."""

    event_rank = 1

    def forward(self, x):
        k = x.shape[-1]
        offset = jnp.log(jnp.arange(k, 0, -1, dtype=x.dtype))
        z = jax.nn.sigmoid(x - offset)
        z1m_cumprod = jnp.cumprod(1 - z, -1)
        pad_z = jnp.pad(z, [(0, 0)] * (x.ndim - 1) + [(0, 1)],
                        constant_values=1.0)
        pad_cum = jnp.pad(z1m_cumprod, [(0, 0)] * (x.ndim - 1) + [(1, 0)],
                          constant_values=1.0)
        return pad_z * pad_cum

    def inverse(self, y):
        k = y.shape[-1] - 1
        offset = jnp.log(jnp.arange(k, 0, -1, dtype=y.dtype))
        cum = 1 - jnp.cumsum(y[..., :-1], -1)
        shifted = jnp.concatenate(
            [jnp.ones_like(y[..., :1]), cum[..., :-1]], -1)
        z = y[..., :-1] / shifted
        return jnp.log(z) - jnp.log1p(-z) + offset

    def forward_log_det_jacobian(self, x):
        k = x.shape[-1]
        offset = jnp.log(jnp.arange(k, 0, -1, dtype=x.dtype))
        t = x - offset
        # dy_i/dx_i = sigmoid'(t_i) * (stick remaining before segment i);
        # the Jacobian is triangular, so the log-det is the diagonal sum
        y = self.forward(x)                       # (..., k+1)
        remaining = 1 - jnp.cumsum(y[..., :-1], -1)
        before = jnp.concatenate(
            [jnp.ones_like(y[..., :1]), remaining[..., :-1]], -1)
        return jnp.sum(-jax.nn.softplus(-t) - jax.nn.softplus(t)
                       + jnp.log(before + 1e-38), -1)

    def forward_shape(self, shape):
        return tuple(shape[:-1]) + (shape[-1] + 1,)

    def inverse_shape(self, shape):
        return tuple(shape[:-1]) + (shape[-1] - 1,)
