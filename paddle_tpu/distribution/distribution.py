"""Distribution base (ref: python/paddle/distribution/distribution.py).

TPU-native redesign: pure-functional math on jnp arrays; sampling draws
explicit `jax.random` keys (from the framework's global stream when the
caller passes none), so every method traces cleanly under `jax.jit` and
reparameterized samples (`rsample`) differentiate through `jax.grad`.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def _shape(shape):
    if shape is None:
        return ()
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s) for s in shape)


class Distribution:
    """Base class (ref: paddle.distribution.Distribution)."""

    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = _shape(batch_shape)
        self._event_shape = _shape(event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    @property
    def mean(self):
        raise NotImplementedError

    @property
    def variance(self):
        raise NotImplementedError

    @property
    def stddev(self):
        return jnp.sqrt(self.variance)

    def _key(self, key):
        if key is not None:
            return key
        from ..framework import random as random_mod

        return random_mod.split_key()

    def sample(self, shape=(), key=None):
        """Draw (non-differentiable) samples of `shape + batch + event`."""
        return jax.lax.stop_gradient(self.rsample(shape, key))

    def rsample(self, shape=(), key=None):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return jnp.exp(self.log_prob(value))

    def entropy(self):
        raise NotImplementedError

    def cdf(self, value):
        raise NotImplementedError

    def icdf(self, value):
        raise NotImplementedError

    def kl_divergence(self, other):
        from .kl import kl_divergence

        return kl_divergence(self, other)

    def _extend(self, shape):
        """sample shape + batch shape."""
        return _shape(shape) + self._batch_shape

    def __repr__(self):
        return (f'{type(self).__name__}(batch_shape={self._batch_shape}, '
                f'event_shape={self._event_shape})')


class ExponentialFamily(Distribution):
    """Marker base for exponential-family members (ref:
    distribution/exponential_family.py). Concrete members implement
    closed-form entropy/KL directly; the natural-parameter Bregman
    machinery the reference uses is replaced by per-pair registrations
    in kl.py (same results, no double-backward trick needed)."""


class Independent(Distribution):
    """Reinterpret batch dims as event dims (ref: distribution/independent.py)."""

    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self.reinterpreted_batch_rank = int(reinterpreted_batch_rank)
        if self.reinterpreted_batch_rank > len(base.batch_shape):
            raise ValueError(
                f'reinterpreted_batch_rank {reinterpreted_batch_rank} exceeds '
                f'batch rank {len(base.batch_shape)}')
        cut = len(base.batch_shape) - self.reinterpreted_batch_rank
        super().__init__(base.batch_shape[:cut],
                         base.batch_shape[cut:] + base.event_shape)

    @property
    def mean(self):
        return self.base.mean

    @property
    def variance(self):
        return self.base.variance

    def rsample(self, shape=(), key=None):
        return self.base.rsample(shape, key)

    def sample(self, shape=(), key=None):
        return self.base.sample(shape, key)

    def log_prob(self, value):
        lp = self.base.log_prob(value)
        if self.reinterpreted_batch_rank == 0:
            return lp
        return jnp.sum(lp, axis=tuple(range(-self.reinterpreted_batch_rank, 0)))

    def entropy(self):
        ent = self.base.entropy()
        if self.reinterpreted_batch_rank == 0:
            return ent
        return jnp.sum(ent, axis=tuple(range(-self.reinterpreted_batch_rank, 0)))
