"""paddle_tpu.distribution (ref: python/paddle/distribution/__init__.py).

The reference's probability toolbox rebuilt on `jax.random` +
`jax.scipy.special`: every density/entropy/KL is a traced closed form
(jit/grad/vmap-able) and every sampler threads explicit PRNG keys from
the framework's global stream. LKJCholesky samples via the vectorized
onion construction (beta marginals are a jax.random primitive).
"""
from . import transform  # noqa: F401
from .continuous import (Beta, Cauchy, Chi2, ContinuousBernoulli, Dirichlet,
                         Exponential, Gamma, Gumbel, Laplace, LKJCholesky,
                         LogNormal, MultivariateNormal, Normal, StudentT,
                         Uniform)
from .discrete import (Bernoulli, Binomial, Categorical, Geometric,
                       Multinomial, Poisson)
from .distribution import Distribution, ExponentialFamily, Independent
from .kl import kl_divergence, register_kl
from .transform import (AbsTransform, AffineTransform, ChainTransform,
                        ExpTransform, IndependentTransform, PowerTransform,
                        ReshapeTransform, SigmoidTransform, SoftmaxTransform,
                        StackTransform, StickBreakingTransform, TanhTransform,
                        Transform)
from .transformed_distribution import TransformedDistribution

__all__ = [
    'Bernoulli', 'Beta', 'Binomial', 'Categorical', 'Cauchy', 'Chi2',
    'ContinuousBernoulli', 'LKJCholesky',
    'Dirichlet', 'Distribution', 'Exponential', 'ExponentialFamily', 'Gamma',
    'Geometric', 'Gumbel', 'Independent', 'Laplace', 'LogNormal',
    'Multinomial', 'MultivariateNormal', 'Normal', 'Poisson', 'StudentT',
    'TransformedDistribution', 'Uniform', 'kl_divergence', 'register_kl',
    'AbsTransform', 'AffineTransform', 'ChainTransform', 'ExpTransform',
    'IndependentTransform', 'PowerTransform', 'ReshapeTransform',
    'SigmoidTransform', 'SoftmaxTransform', 'StackTransform',
    'StickBreakingTransform', 'TanhTransform', 'Transform', 'transform',
]
