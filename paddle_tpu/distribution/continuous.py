"""Continuous distributions (ref: python/paddle/distribution/{normal,
uniform,beta,cauchy,chi2,dirichlet,exponential,gamma,gumbel,laplace,
lognormal,multivariate_normal,student_t}.py).

Each is a thin closed-form layer over `jax.random` samplers and
`jax.scipy.special`, so sampling/log_prob/entropy all jit and batch.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np
from jax.scipy import special as jss

from .distribution import Distribution, ExponentialFamily

_EULER = float(np.euler_gamma)
_LOG2PI = math.log(2.0 * math.pi)


def _f(x):
    return jnp.asarray(x, jnp.result_type(float))


class Normal(ExponentialFamily):
    """ref: paddle.distribution.Normal(loc, scale)."""

    def __init__(self, loc, scale):
        self.loc, self.scale = jnp.broadcast_arrays(_f(loc), _f(scale))
        super().__init__(self.loc.shape)

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        return self.scale ** 2

    def rsample(self, shape=(), key=None):
        eps = jax.random.normal(self._key(key), self._extend(shape))
        return self.loc + self.scale * eps

    def log_prob(self, value):
        z = (value - self.loc) / self.scale
        return -0.5 * z ** 2 - jnp.log(self.scale) - 0.5 * _LOG2PI

    def entropy(self):
        return 0.5 + 0.5 * _LOG2PI + jnp.log(self.scale)

    def cdf(self, value):
        return 0.5 * (1 + jss.erf((value - self.loc)
                                  / (self.scale * math.sqrt(2.0))))

    def icdf(self, value):
        return self.loc + self.scale * math.sqrt(2.0) * jss.erfinv(
            2 * value - 1)


class LogNormal(ExponentialFamily):
    """ref: paddle.distribution.LogNormal — exp of a Normal."""

    def __init__(self, loc, scale):
        self.loc, self.scale = jnp.broadcast_arrays(_f(loc), _f(scale))
        self.base = Normal(self.loc, self.scale)
        super().__init__(self.loc.shape)

    @property
    def mean(self):
        return jnp.exp(self.loc + self.scale ** 2 / 2)

    @property
    def variance(self):
        s2 = self.scale ** 2
        return (jnp.exp(s2) - 1) * jnp.exp(2 * self.loc + s2)

    def rsample(self, shape=(), key=None):
        return jnp.exp(self.base.rsample(shape, key))

    def log_prob(self, value):
        return self.base.log_prob(jnp.log(value)) - jnp.log(value)

    def entropy(self):
        return self.base.entropy() + self.loc

    def cdf(self, value):
        return self.base.cdf(jnp.log(value))


class Uniform(Distribution):
    """ref: paddle.distribution.Uniform(low, high) on [low, high)."""

    def __init__(self, low, high):
        self.low, self.high = jnp.broadcast_arrays(_f(low), _f(high))
        super().__init__(self.low.shape)

    @property
    def mean(self):
        return (self.low + self.high) / 2

    @property
    def variance(self):
        return (self.high - self.low) ** 2 / 12

    def rsample(self, shape=(), key=None):
        u = jax.random.uniform(self._key(key), self._extend(shape))
        return self.low + (self.high - self.low) * u

    def log_prob(self, value):
        inside = (value >= self.low) & (value < self.high)
        return jnp.where(inside, -jnp.log(self.high - self.low), -jnp.inf)

    def entropy(self):
        return jnp.log(self.high - self.low)

    def cdf(self, value):
        return jnp.clip((value - self.low) / (self.high - self.low), 0.0, 1.0)


class Exponential(ExponentialFamily):
    """ref: paddle.distribution.Exponential(rate)."""

    def __init__(self, rate):
        self.rate = _f(rate)
        super().__init__(self.rate.shape)

    @property
    def mean(self):
        return 1.0 / self.rate

    @property
    def variance(self):
        return self.rate ** -2

    def rsample(self, shape=(), key=None):
        e = jax.random.exponential(self._key(key), self._extend(shape))
        return e / self.rate

    def log_prob(self, value):
        lp = jnp.log(self.rate) - self.rate * value
        return jnp.where(value >= 0, lp, -jnp.inf)

    def entropy(self):
        return 1.0 - jnp.log(self.rate)

    def cdf(self, value):
        return jnp.where(value >= 0, 1 - jnp.exp(-self.rate * value), 0.0)


class Laplace(Distribution):
    """ref: paddle.distribution.Laplace(loc, scale)."""

    def __init__(self, loc, scale):
        self.loc, self.scale = jnp.broadcast_arrays(_f(loc), _f(scale))
        super().__init__(self.loc.shape)

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        return 2 * self.scale ** 2

    def rsample(self, shape=(), key=None):
        e = jax.random.laplace(self._key(key), self._extend(shape))
        return self.loc + self.scale * e

    def log_prob(self, value):
        return (-jnp.abs(value - self.loc) / self.scale
                - jnp.log(2 * self.scale))

    def entropy(self):
        return 1.0 + jnp.log(2 * self.scale)

    def cdf(self, value):
        z = (value - self.loc) / self.scale
        return 0.5 - 0.5 * jnp.sign(z) * jnp.expm1(-jnp.abs(z))


class Cauchy(Distribution):
    """ref: paddle.distribution.Cauchy(loc, scale)."""

    def __init__(self, loc, scale):
        self.loc, self.scale = jnp.broadcast_arrays(_f(loc), _f(scale))
        super().__init__(self.loc.shape)

    @property
    def mean(self):
        return jnp.full_like(self.loc, jnp.nan)

    @property
    def variance(self):
        return jnp.full_like(self.loc, jnp.nan)

    def rsample(self, shape=(), key=None):
        c = jax.random.cauchy(self._key(key), self._extend(shape))
        return self.loc + self.scale * c

    def log_prob(self, value):
        z = (value - self.loc) / self.scale
        return -jnp.log(math.pi * self.scale * (1 + z ** 2))

    def entropy(self):
        return jnp.log(4 * math.pi * self.scale)

    def cdf(self, value):
        return jnp.arctan((value - self.loc) / self.scale) / math.pi + 0.5


class Gamma(ExponentialFamily):
    """ref: paddle.distribution.Gamma(concentration, rate)."""

    def __init__(self, concentration, rate):
        self.concentration, self.rate = jnp.broadcast_arrays(
            _f(concentration), _f(rate))
        super().__init__(self.concentration.shape)

    @property
    def mean(self):
        return self.concentration / self.rate

    @property
    def variance(self):
        return self.concentration / self.rate ** 2

    def rsample(self, shape=(), key=None):
        g = jax.random.gamma(self._key(key), self.concentration,
                             self._extend(shape))
        return g / self.rate

    def log_prob(self, value):
        a, b = self.concentration, self.rate
        lp = (a * jnp.log(b) + (a - 1) * jnp.log(value) - b * value
              - jss.gammaln(a))
        return jnp.where(value > 0, lp, -jnp.inf)

    def entropy(self):
        a, b = self.concentration, self.rate
        return a - jnp.log(b) + jss.gammaln(a) + (1 - a) * jss.digamma(a)


class Chi2(Gamma):
    """ref: paddle.distribution.Chi2(df) == Gamma(df/2, 1/2)."""

    def __init__(self, df):
        self.df = _f(df)
        super().__init__(self.df / 2.0, jnp.full_like(self.df, 0.5))


class Beta(ExponentialFamily):
    """ref: paddle.distribution.Beta(alpha, beta)."""

    def __init__(self, alpha, beta):
        self.alpha, self.beta = jnp.broadcast_arrays(_f(alpha), _f(beta))
        super().__init__(self.alpha.shape)

    @property
    def mean(self):
        return self.alpha / (self.alpha + self.beta)

    @property
    def variance(self):
        s = self.alpha + self.beta
        return self.alpha * self.beta / (s ** 2 * (s + 1))

    def rsample(self, shape=(), key=None):
        return jax.random.beta(self._key(key), self.alpha, self.beta,
                               self._extend(shape))

    def log_prob(self, value):
        a, b = self.alpha, self.beta
        return (jss.xlogy(a - 1, value) + jss.xlog1py(b - 1, -value)
                - jss.betaln(a, b))

    def entropy(self):
        a, b = self.alpha, self.beta
        return (jss.betaln(a, b) - (a - 1) * jss.digamma(a)
                - (b - 1) * jss.digamma(b)
                + (a + b - 2) * jss.digamma(a + b))


class Dirichlet(ExponentialFamily):
    """ref: paddle.distribution.Dirichlet(concentration)."""

    def __init__(self, concentration):
        self.concentration = _f(concentration)
        super().__init__(self.concentration.shape[:-1],
                         self.concentration.shape[-1:])

    @property
    def mean(self):
        return self.concentration / jnp.sum(self.concentration, -1,
                                            keepdims=True)

    @property
    def variance(self):
        a0 = jnp.sum(self.concentration, -1, keepdims=True)
        m = self.concentration / a0
        return m * (1 - m) / (a0 + 1)

    def rsample(self, shape=(), key=None):
        return jax.random.dirichlet(self._key(key), self.concentration,
                                    self._extend(shape))

    def log_prob(self, value):
        a = self.concentration
        norm = jss.gammaln(jnp.sum(a, -1)) - jnp.sum(jss.gammaln(a), -1)
        return jnp.sum(jss.xlogy(a - 1, value), -1) + norm

    def entropy(self):
        a = self.concentration
        K = a.shape[-1]
        a0 = jnp.sum(a, -1)
        log_b = jnp.sum(jss.gammaln(a), -1) - jss.gammaln(a0)
        return (log_b + (a0 - K) * jss.digamma(a0)
                - jnp.sum((a - 1) * jss.digamma(a), -1))


class Gumbel(Distribution):
    """ref: paddle.distribution.Gumbel(loc, scale)."""

    def __init__(self, loc, scale):
        self.loc, self.scale = jnp.broadcast_arrays(_f(loc), _f(scale))
        super().__init__(self.loc.shape)

    @property
    def mean(self):
        return self.loc + self.scale * _EULER

    @property
    def variance(self):
        return (math.pi ** 2 / 6) * self.scale ** 2

    def rsample(self, shape=(), key=None):
        g = jax.random.gumbel(self._key(key), self._extend(shape))
        return self.loc + self.scale * g

    def log_prob(self, value):
        z = (value - self.loc) / self.scale
        return -(z + jnp.exp(-z)) - jnp.log(self.scale)

    def entropy(self):
        return jnp.log(self.scale) + 1 + _EULER

    def cdf(self, value):
        return jnp.exp(-jnp.exp(-(value - self.loc) / self.scale))


class StudentT(Distribution):
    """ref: paddle.distribution.StudentT(df, loc, scale)."""

    def __init__(self, df, loc=0.0, scale=1.0):
        self.df, self.loc, self.scale = jnp.broadcast_arrays(
            _f(df), _f(loc), _f(scale))
        super().__init__(self.df.shape)

    @property
    def mean(self):
        return jnp.where(self.df > 1, self.loc, jnp.nan)

    @property
    def variance(self):
        v = self.scale ** 2 * self.df / (self.df - 2)
        return jnp.where(self.df > 2, v,
                         jnp.where(self.df > 1, jnp.inf, jnp.nan))

    def rsample(self, shape=(), key=None):
        t = jax.random.t(self._key(key), self.df, self._extend(shape))
        return self.loc + self.scale * t

    def log_prob(self, value):
        d = self.df
        z = (value - self.loc) / self.scale
        return (jss.gammaln((d + 1) / 2) - jss.gammaln(d / 2)
                - 0.5 * jnp.log(d * math.pi) - jnp.log(self.scale)
                - (d + 1) / 2 * jnp.log1p(z ** 2 / d))

    def entropy(self):
        d = self.df
        return ((d + 1) / 2 * (jss.digamma((d + 1) / 2) - jss.digamma(d / 2))
                + 0.5 * jnp.log(d) + jss.betaln(d / 2, 0.5)
                + jnp.log(self.scale))


class MultivariateNormal(Distribution):
    """ref: paddle.distribution.MultivariateNormal(loc, covariance_matrix |
    scale_tril)."""

    def __init__(self, loc, covariance_matrix=None, scale_tril=None):
        self.loc = _f(loc)
        if (covariance_matrix is None) == (scale_tril is None):
            raise ValueError(
                'exactly one of covariance_matrix/scale_tril required')
        if scale_tril is not None:
            self.scale_tril = _f(scale_tril)
        else:
            self.scale_tril = jnp.linalg.cholesky(_f(covariance_matrix))
        super().__init__(self.loc.shape[:-1], self.loc.shape[-1:])

    @property
    def mean(self):
        return self.loc

    @property
    def covariance_matrix(self):
        L = self.scale_tril
        return L @ jnp.swapaxes(L, -1, -2)

    @property
    def variance(self):
        return jnp.sum(self.scale_tril ** 2, -1)

    def rsample(self, shape=(), key=None):
        eps = jax.random.normal(self._key(key),
                                self._extend(shape) + self.event_shape)
        return self.loc + jnp.einsum('...ij,...j->...i', self.scale_tril, eps)

    def log_prob(self, value):
        d = value - self.loc
        # solve L z = d (triangular); broadcast L over the value batch
        L = jnp.broadcast_to(self.scale_tril,
                             d.shape[:-1] + self.scale_tril.shape[-2:])
        z = jax.scipy.linalg.solve_triangular(
            L, d[..., None], lower=True)[..., 0]
        half_logdet = jnp.sum(
            jnp.log(jnp.diagonal(self.scale_tril, axis1=-2, axis2=-1)), -1)
        k = self.loc.shape[-1]
        return -0.5 * jnp.sum(z ** 2, -1) - half_logdet - 0.5 * k * _LOG2PI

    def entropy(self):
        k = self.loc.shape[-1]
        half_logdet = jnp.sum(
            jnp.log(jnp.diagonal(self.scale_tril, axis1=-2, axis2=-1)), -1)
        return 0.5 * k * (1 + _LOG2PI) + half_logdet


class ContinuousBernoulli(ExponentialFamily):
    """ref: paddle.distribution.ContinuousBernoulli(probs, lims) — the
    [0, 1]-supported exponential-family relaxation of Bernoulli
    (Loaiza-Ganem & Cunningham 2019). Near probs=0.5 the normalizer's
    closed form is 0/0, so a Taylor expansion takes over inside `lims`
    (same scheme as the reference kernel)."""

    def __init__(self, probs, lims=(0.499, 0.501)):
        self.probs = _f(probs)
        self._lims = lims
        super().__init__(jnp.shape(self.probs))

    def _outside(self):
        lo, hi = self._lims
        return (self.probs < lo) | (self.probs > hi)

    def _safe_probs(self):
        # value used on the closed-form branch only
        return jnp.where(self._outside(), self.probs, 0.499)

    def _log_norm(self):
        """log C(p) with C = 2 atanh(1-2p) / (1-2p) (p != 1/2) else 2."""
        p = self._safe_probs()
        closed = jnp.log(jnp.abs(2.0 * jnp.arctanh(1 - 2 * p))) \
            - jnp.log(jnp.abs(1 - 2 * p))
        # Taylor around 1/2: log 2 + 4/3 (p-1/2)^2 + 104/45 (p-1/2)^4
        d = self.probs - 0.5
        taylor = math.log(2.0) + (4.0 / 3.0) * d ** 2 \
            + (104.0 / 45.0) * d ** 4
        return jnp.where(self._outside(), closed, taylor)

    @property
    def mean(self):
        p = self._safe_probs()
        closed = p / (2 * p - 1) + 1 / (2 * jnp.arctanh(1 - 2 * p))
        d = self.probs - 0.5
        taylor = 0.5 + d / 3.0 + (16.0 / 45.0) * d ** 3
        return jnp.where(self._outside(), closed, taylor)

    @property
    def variance(self):
        p = self._safe_probs()
        closed = p * (p - 1) / (1 - 2 * p) ** 2 \
            + 1 / (2 * jnp.arctanh(1 - 2 * p)) ** 2
        d = self.probs - 0.5
        taylor = 1.0 / 12.0 - (2.0 / 15.0) * d ** 2
        return jnp.where(self._outside(), closed, taylor)

    def log_prob(self, value):
        value = _f(value)
        return (self._log_norm() + value * jnp.log(self.probs)
                + (1 - value) * jnp.log1p(-self.probs))

    def prob(self, value):
        return jnp.exp(self.log_prob(value))

    def cdf(self, value):
        p = self._safe_probs()
        x = _f(value)
        num = (p ** x) * ((1 - p) ** (1 - x)) + p - 1
        closed = num / (2 * p - 1)
        out = jnp.where(self._outside(), closed, x)
        return jnp.clip(out, 0.0, 1.0)

    def icdf(self, value):
        p = self._safe_probs()
        u = _f(value)
        closed = (jnp.log1p(u * (2 * p - 1) / (1 - p))
                  / (jnp.log(p) - jnp.log1p(-p)))
        return jnp.where(self._outside(), closed, u)

    def rsample(self, shape=(), key=None):
        u = jax.random.uniform(self._key(key), self._extend(shape))
        return self.icdf(u)

    def sample(self, shape=(), key=None):
        return self.rsample(shape, key)

    def entropy(self):
        m = self.mean
        return -(self._log_norm() + m * jnp.log(self.probs)
                 + (1 - m) * jnp.log1p(-self.probs))


class LKJCholesky(Distribution):
    """ref: paddle.distribution.LKJCholesky(dim, concentration) — prior
    over Cholesky factors of correlation matrices. Sampling uses the
    vectorized onion construction (beta marginals + hypersphere rows);
    density follows the Stan LKJ-Cholesky form
    prod L_ii^(2(eta-1) + dim - i) with the mvlgamma normalizer."""

    def __init__(self, dim, concentration=1.0, sample_method='onion'):
        if dim < 2:
            raise ValueError(f'dim must be >= 2, got {dim}')
        if sample_method not in ('onion', 'cvine'):
            raise ValueError(f'bad sample_method: {sample_method}')
        self.dim = int(dim)
        self.concentration = _f(concentration)
        super().__init__(jnp.shape(self.concentration))
        offset = jnp.concatenate(
            [jnp.zeros((1,)), jnp.arange(self.dim - 1, dtype=jnp.float32)])
        self._beta_a = offset + 0.5
        self._beta_b = (self.concentration[..., None]
                        + 0.5 * (self.dim - 2) - 0.5 * offset)

    def sample(self, shape=(), key=None):
        key = self._key(key)
        k1, k2 = jax.random.split(key)
        bshape = tuple(shape) + self.batch_shape + (self.dim,)
        y = jax.random.beta(k1, jnp.broadcast_to(self._beta_a, bshape),
                            jnp.broadcast_to(self._beta_b, bshape))
        u = jnp.tril(jax.random.normal(
            k2, bshape + (self.dim,)), -1)
        norm = jnp.linalg.norm(u, axis=-1, keepdims=True)
        u_sphere = u / jnp.where(norm == 0, 1.0, norm)
        w = jnp.sqrt(y)[..., None] * u_sphere
        diag = jnp.sqrt(jnp.clip(1 - jnp.sum(w ** 2, axis=-1), 1e-38))
        return w + jnp.eye(self.dim) * diag[..., None, :]

    def log_prob(self, value):
        value = _f(value)
        diag = jnp.diagonal(value, axis1=-2, axis2=-1)[..., 1:]
        order = (2 * (self.concentration[..., None] - 1)
                 + self.dim - jnp.arange(2, self.dim + 1))
        unnorm = jnp.sum(order * jnp.log(diag), axis=-1)
        dm1 = self.dim - 1
        alpha = self.concentration + 0.5 * dm1
        denominator = jss.gammaln(alpha) * dm1
        numerator = jss.multigammaln(alpha - 0.5, dm1)
        pi_const = 0.5 * dm1 * math.log(math.pi)
        return unnorm - (pi_const + numerator - denominator)
