"""KL divergences (ref: python/paddle/distribution/kl.py).

`register_kl(P, Q)` decorates a closed-form KL(p || q); dispatch walks
both MROs and picks the most specific registered pair (so Chi2 — a
Gamma subclass — resolves to the Gamma/Gamma rule).
"""
from __future__ import annotations

import jax.numpy as jnp
from jax.scipy import special as jss

from .continuous import (Beta, Cauchy, Dirichlet, Exponential, Gamma, Gumbel,
                         Laplace, LogNormal, MultivariateNormal, Normal,
                         Uniform)
from .discrete import Bernoulli, Categorical, Geometric, Poisson
from .distribution import Independent

_REGISTRY = {}


def register_kl(p_cls, q_cls):
    """ref: paddle.distribution.register_kl."""
    def decorator(fn):
        _REGISTRY[(p_cls, q_cls)] = fn
        return fn

    return decorator


def kl_divergence(p, q):
    """ref: paddle.distribution.kl_divergence(p, q) = KL(p || q)."""
    best, best_score = None, None
    for (pc, qc), fn in _REGISTRY.items():
        if isinstance(p, pc) and isinstance(q, qc):
            score = (type(p).__mro__.index(pc), type(q).__mro__.index(qc))
            if best_score is None or score < best_score:
                best, best_score = fn, score
    if best is None:
        raise NotImplementedError(
            f'no KL registered for ({type(p).__name__}, {type(q).__name__})')
    return best(p, q)


@register_kl(Normal, Normal)
def _kl_normal_normal(p, q):
    var_ratio = (p.scale / q.scale) ** 2
    t1 = ((p.loc - q.loc) / q.scale) ** 2
    return 0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio))


@register_kl(LogNormal, LogNormal)
def _kl_lognormal_lognormal(p, q):
    return _kl_normal_normal(p.base, q.base)


@register_kl(Uniform, Uniform)
def _kl_uniform_uniform(p, q):
    # infinite where p's support leaves q's
    result = jnp.log((q.high - q.low) / (p.high - p.low))
    outside = (p.low < q.low) | (p.high > q.high)
    return jnp.where(outside, jnp.inf, result)


@register_kl(Exponential, Exponential)
def _kl_exponential_exponential(p, q):
    ratio = q.rate / p.rate
    return ratio - 1 - jnp.log(ratio)


@register_kl(Gamma, Gamma)
def _kl_gamma_gamma(p, q):
    ap, bp, aq, bq = p.concentration, p.rate, q.concentration, q.rate
    return ((ap - aq) * jss.digamma(ap) - jss.gammaln(ap) + jss.gammaln(aq)
            + aq * (jnp.log(bp) - jnp.log(bq)) + ap * (bq / bp - 1))


@register_kl(Beta, Beta)
def _kl_beta_beta(p, q):
    sp = p.alpha + p.beta
    return (jss.betaln(q.alpha, q.beta) - jss.betaln(p.alpha, p.beta)
            + (p.alpha - q.alpha) * jss.digamma(p.alpha)
            + (p.beta - q.beta) * jss.digamma(p.beta)
            + (q.alpha - p.alpha + q.beta - p.beta) * jss.digamma(sp))


@register_kl(Dirichlet, Dirichlet)
def _kl_dirichlet_dirichlet(p, q):
    ap, aq = p.concentration, q.concentration
    a0 = jnp.sum(ap, -1)
    return (jss.gammaln(a0) - jnp.sum(jss.gammaln(ap), -1)
            - jss.gammaln(jnp.sum(aq, -1)) + jnp.sum(jss.gammaln(aq), -1)
            + jnp.sum((ap - aq) * (jss.digamma(ap)
                                   - jss.digamma(a0)[..., None]), -1))


@register_kl(Categorical, Categorical)
def _kl_categorical_categorical(p, q):
    return jnp.sum(p.probs * (p.logits - q.logits), -1)


@register_kl(Bernoulli, Bernoulli)
def _kl_bernoulli_bernoulli(p, q):
    t1 = jss.xlogy(p.probs, p.probs / q.probs)
    t2 = jss.xlogy(1 - p.probs, (1 - p.probs) / (1 - q.probs))
    return t1 + t2


@register_kl(Geometric, Geometric)
def _kl_geometric_geometric(p, q):
    # E_p[k] = (1-p)/p; KL = log(p/q) + E[k] log((1-p)/(1-q))
    return (jnp.log(p.probs) - jnp.log(q.probs)
            + (1 - p.probs) / p.probs
            * (jnp.log1p(-p.probs) - jnp.log1p(-q.probs)))


@register_kl(Poisson, Poisson)
def _kl_poisson_poisson(p, q):
    return (p.rate * (jnp.log(p.rate) - jnp.log(q.rate))
            + q.rate - p.rate)


@register_kl(Laplace, Laplace)
def _kl_laplace_laplace(p, q):
    scale_ratio = p.scale / q.scale
    d = jnp.abs(p.loc - q.loc)
    return (-jnp.log(scale_ratio)
            + scale_ratio * jnp.exp(-d / p.scale)
            + d / q.scale - 1)


@register_kl(Gumbel, Gumbel)
def _kl_gumbel_gumbel(p, q):
    import numpy as np

    euler = float(np.euler_gamma)
    ratio = p.scale / q.scale
    # E_p[exp(-(x - q.loc)/q.scale)] via the Gumbel MGF
    t = jnp.exp((q.loc - p.loc) / q.scale) * jnp.exp(
        jss.gammaln(1 + ratio))
    return (jnp.log(q.scale) - jnp.log(p.scale)
            + euler * (ratio - 1)
            + t - 1 + (p.loc - q.loc) / q.scale)


@register_kl(MultivariateNormal, MultivariateNormal)
def _kl_mvn_mvn(p, q):
    import jax

    k = p.loc.shape[-1]
    Lp, Lq = p.scale_tril, q.scale_tril
    half_logdet_p = jnp.sum(
        jnp.log(jnp.diagonal(Lp, axis1=-2, axis2=-1)), -1)
    half_logdet_q = jnp.sum(
        jnp.log(jnp.diagonal(Lq, axis1=-2, axis2=-1)), -1)
    # tr(Σq⁻¹ Σp) = ||Lq⁻¹ Lp||_F²
    M = jax.scipy.linalg.solve_triangular(Lq, Lp, lower=True)
    trace = jnp.sum(M ** 2, axis=(-2, -1))
    d = q.loc - p.loc
    z = jax.scipy.linalg.solve_triangular(Lq, d[..., None], lower=True)[..., 0]
    maha = jnp.sum(z ** 2, -1)
    return half_logdet_q - half_logdet_p + 0.5 * (trace + maha - k)


@register_kl(Cauchy, Cauchy)
def _kl_cauchy_cauchy(p, q):
    # closed form (Chyzak & Nielsen 2019)
    num = (p.scale + q.scale) ** 2 + (p.loc - q.loc) ** 2
    den = 4 * p.scale * q.scale
    return jnp.log(num / den)


@register_kl(Independent, Independent)
def _kl_independent_independent(p, q):
    if p.reinterpreted_batch_rank != q.reinterpreted_batch_rank:
        raise NotImplementedError('mismatched reinterpreted ranks')
    kl = kl_divergence(p.base, q.base)
    if p.reinterpreted_batch_rank == 0:
        return kl
    return jnp.sum(kl, axis=tuple(range(-p.reinterpreted_batch_rank, 0)))
