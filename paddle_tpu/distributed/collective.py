"""Collective communication (ref: python/paddle/distributed/communication/*).

Paddle: eager tensors + ProcessGroupNCCL streams. TPU-native: these are
*traced* collectives — inside `shard_map` they lower to XLA ICI
collectives (psum / all-gather / ppermute / all-to-all); outside any
mapped context they're the single-participant identity, which matches
Paddle's behaviour with world_size == 1.

`group` is a mesh axis name (str) or tuple of names — the moral
equivalent of Paddle's `Group` object.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

__all__ = [
    'ReduceOp', 'all_reduce', 'all_gather', 'reduce_scatter', 'broadcast',
    'all_to_all', 'send_recv', 'ppermute', 'barrier', 'scatter', 'reduce',
    'axis_size', 'axis_index',
]


class ReduceOp:
    SUM = 'sum'
    MAX = 'max'
    MIN = 'min'
    PROD = 'prod'
    AVG = 'avg'


def _in_mapped_context(axis):
    try:
        lax.axis_index(axis)
        return True
    except NameError:
        return False
    except Exception:
        return False


def axis_size(axis) -> int:
    from ._spmd import axis_size as _axis_size

    return _axis_size(axis)


def axis_index(axis):
    return lax.axis_index(axis)


def all_reduce(x, op: str = ReduceOp.SUM, group='dp'):
    if not _in_mapped_context(group):
        return x
    if op == ReduceOp.SUM:
        return lax.psum(x, group)
    if op == ReduceOp.MAX:
        return lax.pmax(x, group)
    if op == ReduceOp.MIN:
        return lax.pmin(x, group)
    if op == ReduceOp.AVG:
        return lax.pmean(x, group)
    if op == ReduceOp.PROD:
        # gather + prod handles zeros and negatives exactly (an
        # exp-of-psum-of-logs trick would NaN on them)
        return jnp.prod(lax.all_gather(x, group, axis=0, tiled=False), axis=0)
    raise ValueError(f'unknown op {op}')


def all_gather(x, group='dp', axis=0, tiled=True):
    """Concatenate shards along `axis` (ref: communication/all_gather.py)."""
    if not _in_mapped_context(group):
        return x
    return lax.all_gather(x, group, axis=axis, tiled=tiled)


def reduce_scatter(x, op: str = ReduceOp.SUM, group='dp', axis=0):
    if not _in_mapped_context(group):
        return x
    assert op == ReduceOp.SUM, 'reduce_scatter supports SUM'
    return lax.psum_scatter(x, group, scatter_dimension=axis, tiled=True)


def broadcast(x, src: int = 0, group='dp'):
    """Every participant gets src's shard."""
    if not _in_mapped_context(group):
        return x
    n = axis_size(group)
    full = lax.all_gather(x, group, axis=0, tiled=False)
    return full[src]


def all_to_all(x, group='ep', split_axis=0, concat_axis=0):
    """ref: communication/all_to_all.py — the MoE dispatch primitive."""
    if not _in_mapped_context(group):
        return x
    return lax.all_to_all(x, group, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=True)


def ppermute(x, perm, group='pp'):
    if not _in_mapped_context(group):
        return x
    return lax.ppermute(x, group, perm)


def send_recv(x, group='pp', shift: int = 1):
    """Neighbour exchange on a ring (ref: communication/send.py/recv.py —
    p2p NCCL send/recv; on TPU a ppermute rides the ICI torus)."""
    if not _in_mapped_context(group):
        return x
    n = axis_size(group)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, group, perm)


def reduce(x, dst: int = 0, op: str = ReduceOp.SUM, group='dp'):
    if not _in_mapped_context(group):
        return x
    y = all_reduce(x, op, group)
    idx = lax.axis_index(group)
    return jnp.where(idx == dst, y, jnp.zeros_like(y))


def scatter(x, src: int = 0, group='dp'):
    """x holds the full array on all participants; return this rank's slice."""
    if not _in_mapped_context(group):
        return x
    n = axis_size(group)
    idx = lax.axis_index(group)
    chunk = x.shape[0] // n
    return lax.dynamic_slice_in_dim(x, idx * chunk, chunk, axis=0)


def barrier(group=None):
    """No-op under SPMD: every jitted program is already a global sync point."""
    return None
