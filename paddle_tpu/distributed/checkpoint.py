"""Distributed checkpoint save/resume (ref: python/paddle/distributed/
checkpoint/save_state_dict.py, load_state_dict.py).

Paddle writes per-rank shard files + metadata and reshards on load.
TPU-native: orbax-checkpoint does exactly this over jax arrays —
async, multi-host coordinated, resharding on restore via the target
shardings. This module adapts model/optimizer pytrees (Layer nodes)
to orbax's pure-tree world through jax.tree flatten/unflatten.
"""
from __future__ import annotations

import os
import typing

import jax
import numpy as np


def _leaves(tree):
    return jax.tree.leaves(tree)


def _as_saveable(tree):
    """Layer pytrees → {index: leaf} dict (orbax wants plain containers)."""
    leaves = _leaves(tree)
    return {f'leaf_{i}': leaf for i, leaf in enumerate(leaves)}


def _restore_into(template, restored: dict):
    leaves = [restored[f'leaf_{i}'] for i in range(len(restored))]
    return jax.tree.unflatten(jax.tree.structure(template), leaves)


class CheckpointManager:
    """Async, retention-managed checkpoints (orbax CheckpointManager).

    ref capability: fleet sharded save/load + auto-resume
    (distributed/checkpoint + incubate/distributed/fleet/utils).
    """

    def __init__(self, directory, max_to_keep=3, save_interval_steps=1,
                 async_save=True):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.directory = os.path.abspath(directory)
        options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            save_interval_steps=save_interval_steps,
            enable_async_checkpointing=async_save,
        )
        self.manager = ocp.CheckpointManager(self.directory, options=options)

    def save(self, step: int, state, force=False):
        """state: any pytree (model, {'model':..., 'opt':...}, ...)."""
        return self.manager.save(
            step, args=self._ocp.args.StandardSave(_as_saveable(state)),
            force=force)

    def restore(self, step: int | None, template):
        """Restore into the structure (and shardings) of `template`."""
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f'no checkpoint in {self.directory}')
        saveable = _as_saveable(template)
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(
                np.shape(x), x.dtype,
                sharding=getattr(x, 'sharding', None))
            if hasattr(x, 'dtype') else x,
            saveable)
        restored = self.manager.restore(
            step, args=self._ocp.args.StandardRestore(abstract))
        return _restore_into(template, restored)

    def latest_step(self):
        return self.manager.latest_step()

    def all_steps(self):
        return list(self.manager.all_steps())

    def wait_until_finished(self):
        self.manager.wait_until_finished()

    def close(self):
        self.manager.close()


def save_state_dict(state_dict, path, process_group=None, coordinator_rank=0,
                    async_save=False):
    """ref: paddle.distributed.save_state_dict — one-shot distributed save."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(path, _as_saveable(state_dict), force=True)
    ckptr.wait_until_finished()
    ckptr.close()


def load_state_dict(template, path, process_group=None, offload=False):
    """ref: paddle.distributed.load_state_dict — reshards onto the
    shardings present in `template`."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    saveable = _as_saveable(template)
    abstract = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(
            np.shape(x), x.dtype, sharding=getattr(x, 'sharding', None))
        if hasattr(x, 'dtype') else x,
        saveable)
    ckptr = ocp.StandardCheckpointer()
    restored = ckptr.restore(path, target=abstract)
    ckptr.close()
    return _restore_into(template, restored)
