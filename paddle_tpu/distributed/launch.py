"""Multi-host bring-up (ref: python/paddle/distributed/launch — the
`python -m paddle.distributed.launch` elastic launcher).

On TPU pods there is no mother process spawning ranks: each host runs
the same script and `jax.distributed.initialize()` wires the cluster
from the TPU metadata (or explicit coordinator args elsewhere). This
module is that entry point plus a tiny CLI for parity:

    python -m paddle_tpu.distributed.launch train.py --args...
"""
from __future__ import annotations

import os
import runpy
import sys


def init_on_cluster(coordinator_address=None, num_processes=None,
                    process_id=None, local_device_ids=None):
    """ref capability: launch's rank bring-up. On TPU hosts all args are
    auto-detected; set them explicitly for CPU/GPU clusters."""
    import jax

    kwargs = {}
    if coordinator_address is not None:
        kwargs.update(coordinator_address=coordinator_address,
                      num_processes=num_processes, process_id=process_id)
    if local_device_ids is not None:
        kwargs.update(local_device_ids=local_device_ids)
    jax.distributed.initialize(**kwargs)
    return {
        'rank': jax.process_index(),
        'world_size': jax.process_count(),
        'local_devices': len(jax.local_devices()),
        'global_devices': jax.device_count(),
    }


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv:
        print('usage: python -m paddle_tpu.distributed.launch SCRIPT [args...]')
        return 1
    # initialize the cluster unless the script opts out
    if os.environ.get('PADDLE_TPU_NO_AUTO_INIT') != '1':
        try:
            init_on_cluster()
        except Exception as e:    # single-host dev boxes
            print(f'launch: single-process mode ({e})', file=sys.stderr)
    script, *rest = argv
    sys.argv = [script] + rest
    runpy.run_path(script, run_name='__main__')
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
