"""Multi-host / multi-process bring-up (ref:
python/paddle/distributed/launch/main.py — the
`python -m paddle.distributed.launch` elastic launcher: process
spawning, per-rank logs, env wiring, fail-fast monitoring).

On TPU pods there is no mother process spawning ranks: each host runs
the same script and `jax.distributed.initialize()` wires the cluster
from the TPU metadata. This module is that entry point, PLUS the
reference launcher's local-process mode for CPU/GPU clusters and
multi-process testing:

    # TPU pod host (auto-detected cluster):
    python -m paddle_tpu.distributed.launch train.py --args...

    # spawn N local processes wired through a localhost coordinator
    # (reference: --nproc_per_node), per-rank logs under --log_dir:
    python -m paddle_tpu.distributed.launch --nproc_per_node 4 \\
        --log_dir ./logs train.py --args...

Child processes receive the coordinator address / world size / rank in
`PADDLE_TPU_COORDINATOR` / `PADDLE_TPU_NUM_PROCESSES` /
`PADDLE_TPU_PROCESS_ID` (plus the reference-compatible
`PADDLE_TRAINER_ID` / `PADDLE_TRAINERS_NUM`), which
`init_on_cluster()` picks up automatically. If any rank dies, the
launcher terminates the rest (the reference's fail-fast elastic
default) and returns that rank's exit code.
"""
from __future__ import annotations

import os
import runpy
import signal
import socket
import subprocess
import sys
import time


def _env_int(name):
    v = os.environ.get(name)
    return int(v) if v not in (None, '') else None


def init_on_cluster(coordinator_address=None, num_processes=None,
                    process_id=None, local_device_ids=None):
    """ref capability: launch's rank bring-up. On TPU hosts all args are
    auto-detected; explicit args (or the PADDLE_TPU_* env vars a parent
    launcher sets) wire CPU/GPU clusters."""
    import jax

    # env fills in ONLY missing args — explicit args always win
    if coordinator_address is None:
        coordinator_address = os.environ.get('PADDLE_TPU_COORDINATOR')
    if coordinator_address is not None:
        if num_processes is None:
            num_processes = _env_int('PADDLE_TPU_NUM_PROCESSES')
        if process_id is None:
            process_id = _env_int('PADDLE_TPU_PROCESS_ID')
    kwargs = {}
    if coordinator_address is not None:
        kwargs.update(coordinator_address=coordinator_address,
                      num_processes=num_processes, process_id=process_id)
    if local_device_ids is not None:
        kwargs.update(local_device_ids=local_device_ids)
    try:
        jax.distributed.initialize(**kwargs)
    except RuntimeError as e:
        # idempotent bring-up: the launcher auto-init may have run
        # already (children are spawned through the launcher itself)
        if 'already initialized' not in str(e).lower():
            raise
    return {
        'rank': jax.process_index(),
        'world_size': jax.process_count(),
        'local_devices': len(jax.local_devices()),
        'global_devices': jax.device_count(),
    }


def _free_port():
    with socket.socket() as s:
        s.bind(('127.0.0.1', 0))
        return s.getsockname()[1]


def launch_local(script, script_args=(), nprocs=1, log_dir=None, env=None,
                 poll_s=0.2, timeout_s=None, with_info=False):
    """Spawn `nprocs` local ranks of `script` wired through a localhost
    coordinator (ref: launch/main.py local mode + its per-rank
    workerlog.N files and fail-fast watch loop).

    Returns the list of per-rank exit codes. If any rank exits non-zero,
    the remaining ranks are terminated (SIGTERM, then SIGKILL after a
    grace period) — surviving stragglers of a dead collective would hang
    forever on the next barrier.

    with_info=True returns (codes, launcher_terminated) where
    launcher_terminated is the set of rank indices THIS launcher tore
    down (fail-fast or timeout) — their exit codes (-SIGTERM, or
    -SIGKILL for a straggler that ignored SIGTERM) are collateral, not
    the root failure, and must not masquerade as it.
    """
    port = _free_port()
    procs = []
    logs = []
    if log_dir:
        os.makedirs(log_dir, exist_ok=True)
    # children must be able to import this package regardless of cwd
    pkg_parent = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    try:
        for rank in range(nprocs):
            child_env = dict(os.environ)
            child_env.update(env or {})
            child_env['PYTHONPATH'] = os.pathsep.join(
                [pkg_parent] + ([child_env['PYTHONPATH']]
                                if child_env.get('PYTHONPATH') else []))
            child_env.update({
                'PADDLE_TPU_COORDINATOR': f'127.0.0.1:{port}',
                'PADDLE_TPU_NUM_PROCESSES': str(nprocs),
                'PADDLE_TPU_PROCESS_ID': str(rank),
                # reference-compatible names (fleet scripts read these)
                'PADDLE_TRAINER_ID': str(rank),
                'PADDLE_TRAINERS_NUM': str(nprocs),
            })
            if log_dir:
                f = open(os.path.join(log_dir, f'workerlog.{rank}'), 'wb')
                logs.append(f)
                out = err = f
            else:
                out = err = None
            # spawn THROUGH the launcher's single-process path so each
            # rank auto-runs init_on_cluster (picking up the env above)
            # before the script — same contract as the TPU-pod path
            procs.append(subprocess.Popen(
                [sys.executable, '-m', 'paddle_tpu.distributed.launch',
                 script, *script_args], env=child_env,
                stdout=out, stderr=err))
    except BaseException:
        # a failed spawn (ENOMEM, bad interpreter) must not strand the
        # ranks already running on a barrier that can never complete
        for pr in procs:
            pr.terminate()
        for f in logs:
            f.close()
        raise

    codes = [None] * nprocs
    launcher_terminated = set()
    t0 = time.time()
    try:
        while any(c is None for c in codes):
            for i, p in enumerate(procs):
                if codes[i] is None:
                    codes[i] = p.poll()
            failed = [i for i, c in enumerate(codes) if c not in (None, 0)]
            timed_out = timeout_s is not None and time.time() - t0 > timeout_s
            if failed or timed_out:
                for i, p in enumerate(procs):
                    if codes[i] is None:
                        launcher_terminated.add(i)
                        p.terminate()
                grace = time.time() + 10
                for i, p in enumerate(procs):
                    if codes[i] is None:
                        try:
                            codes[i] = p.wait(max(0.1, grace - time.time()))
                        except subprocess.TimeoutExpired:
                            p.send_signal(signal.SIGKILL)
                            codes[i] = p.wait()
                if timed_out and not failed:
                    raise TimeoutError(
                        f'launch_local: ranks still alive after '
                        f'{timeout_s}s; terminated (codes={codes})')
                break
            time.sleep(poll_s)
    finally:
        for f in logs:
            f.close()
    if with_info:
        return codes, launcher_terminated
    return codes


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    nprocs = 1
    log_dir = None
    # reference-style flags before the script path
    def usage():
        print('usage: python -m paddle_tpu.distributed.launch '
              '[--nproc_per_node N] [--log_dir DIR] SCRIPT [args...]',
              file=sys.stderr)

    while argv and argv[0].startswith('--'):
        flag = argv.pop(0)
        name, eq, inline = flag.lstrip('-').partition('=')

        def value():
            if eq:
                return inline
            if not argv:
                raise IndexError
            return argv.pop(0)

        try:
            if name in ('nproc_per_node', 'nprocs'):
                nprocs = int(value())
            elif name == 'log_dir':
                log_dir = value()
            elif name == 'help':
                print(__doc__)
                return 0
            else:
                print(f'launch: unknown flag {flag}', file=sys.stderr)
                return 2
        except (IndexError, ValueError):
            print(f'launch: flag {flag} needs a value', file=sys.stderr)
            usage()
            return 2
    if not argv:
        print('usage: python -m paddle_tpu.distributed.launch '
              '[--nproc_per_node N] [--log_dir DIR] SCRIPT [args...]')
        return 1
    script, *rest = argv
    if nprocs > 1:
        codes, terminated = launch_local(script, rest, nprocs=nprocs,
                                         log_dir=log_dir, with_info=True)
        if any(c != 0 for c in codes):
            print(f'launch: ranks failed with codes {codes}',
                  file=sys.stderr)
            return _pick_exit_code(codes, terminated)
        return 0
    # single process: initialize the cluster unless the script opts out
    if os.environ.get('PADDLE_TPU_NO_AUTO_INIT') != '1':
        try:
            init_on_cluster()
        except Exception as e:
            if os.environ.get('PADDLE_TPU_COORDINATOR'):
                # a child rank of an explicit cluster: running the
                # script standalone as rank 0 would silently compute on
                # 1/N of the data (and deadlock its peers) — fail loudly
                # so the launcher's fail-fast tears the job down
                print(f'launch: cluster init failed for rank '
                      f'{os.environ.get("PADDLE_TPU_PROCESS_ID", "?")} '
                      f'({e})', file=sys.stderr)
                return 1
            # single-host dev boxes: no coordinator requested, plain run
            print(f'launch: single-process mode ({e})', file=sys.stderr)
    sys.argv = [script] + rest
    runpy.run_path(script, run_name='__main__')
    return 0


def _pick_exit_code(codes, launcher_terminated):
    """The exit code the launcher should surface: prefer a rank that
    exited ON ITS OWN with a non-zero code (the root failure) over
    ranks the launcher itself tore down — a straggler that ignored
    SIGTERM gets SIGKILLed (-9), and that collateral -9 must not
    masquerade as an OOM kill. Falls back to any non-zero code (e.g.
    every rank was terminated by a timeout)."""
    self_exited = [c for i, c in enumerate(codes)
                   if c not in (None, 0) and i not in launcher_terminated]
    if self_exited:
        return self_exited[0]
    bad = [c for c in codes if c not in (None, 0)]
    return bad[0] if bad else 1


if __name__ == '__main__':
    raise SystemExit(main())
