"""paddle_tpu.distributed — hybrid-parallel training on a device mesh.

ref: python/paddle/distributed (Fleet, communication, auto_parallel).
Design: ONE `jax.sharding.Mesh` with axes (dp, fsdp, pp, tp, sp)
replaces Fleet's NCCL process-group topology; GSPMD + shard_map replace
hand-written collective calls. See SURVEY.md §2.7.
"""
from . import collective  # noqa: F401
from .collective import (  # noqa: F401
    ReduceOp,
    all_gather,
    all_reduce,
    all_to_all,
    barrier,
    broadcast,
    ppermute,
    reduce,
    reduce_scatter,
    scatter,
    send_recv,
)
from .mesh import (  # noqa: F401
    MESH_AXES,
    DistributedStrategy,
    build_mesh,
    get_mesh,
    get_rank,
    get_world_size,
    init_parallel_env,
    serving_mesh,
    set_mesh,
)
from .mp_layers import (  # noqa: F401
    ColumnParallelLinear,
    ParallelCrossEntropy,
    RowParallelLinear,
    VocabParallelEmbedding,
    parallel_cross_entropy,
    sharding_constraint,
)
from .parallel import (  # noqa: F401
    DataParallel,
    activation_batch_constraint,
    apply_rules,
    embedding_lookup,
    model_shardings,
    parallelize,
    shard_batch,
    shard_model,
    shard_tensor,
)
from .recompute import (  # noqa: F401
    recompute,
    recompute_sequential,
    recompute_wrapper,
)
from . import io  # noqa: F401
from .auto_parallel import (  # noqa: F401
    DistAttr,
    DistModel,
    Partial,
    Placement,
    ProcessMesh,
    ReduceType,
    Replicate,
    Shard,
    ShardingStage1,
    ShardingStage2,
    ShardingStage3,
    Strategy,
    dtensor_from_fn,
    placements_to_spec,
    reshard,
    shard_dataloader,
    shard_layer,
    shard_optimizer,
    shard_scaler,
    spec_to_placements,
    to_static,
    unshard_dtensor,
)
from .compat import (  # noqa: F401
    CountFilterEntry,
    Group,
    InMemoryDataset,
    ParallelEnv,
    ParallelMode,
    ProbabilityEntry,
    QueueDataset,
    ShowClickEntry,
    all_gather_object,
    alltoall,
    alltoall_single,
    broadcast_object_list,
    destroy_process_group,
    gather,
    get_backend,
    get_group,
    gloo_barrier,
    gloo_init_parallel_env,
    gloo_release,
    irecv,
    is_available,
    is_initialized,
    isend,
    new_group,
    recv,
    scatter_object_list,
    send,
    spawn,
    split,
    wait,
)
from . import fleet  # noqa: F401
from . import moe  # noqa: F401
from . import pipeline  # noqa: F401
from . import ring_attention  # noqa: F401
from . import ulysses  # noqa: F401
from .ulysses import ulysses_attention, ulysses_attention_sharded  # noqa: F401
from . import sharding  # noqa: F401
from .sharding import group_sharded_parallel, save_group_sharded_model  # noqa: F401
from . import checkpoint  # noqa: F401
from .checkpoint import load_state_dict, save_state_dict  # noqa: F401
from . import launch  # noqa: F401


def get_world_size_safe():
    import jax

    return jax.device_count()
