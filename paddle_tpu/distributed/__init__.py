"""paddle_tpu.distributed — hybrid-parallel training on a device mesh.

ref: python/paddle/distributed (Fleet, communication, auto_parallel).
Design: ONE `jax.sharding.Mesh` with axes (dp, fsdp, pp, tp, sp)
replaces Fleet's NCCL process-group topology; GSPMD + shard_map replace
hand-written collective calls. See SURVEY.md §2.7.
"""
from . import collective  # noqa: F401
from .collective import (  # noqa: F401
    ReduceOp,
    all_gather,
    all_reduce,
    all_to_all,
    barrier,
    broadcast,
    ppermute,
    reduce,
    reduce_scatter,
    scatter,
    send_recv,
)
from .mesh import (  # noqa: F401
    MESH_AXES,
    DistributedStrategy,
    build_mesh,
    get_mesh,
    get_rank,
    get_world_size,
    init_parallel_env,
    set_mesh,
)
from .mp_layers import (  # noqa: F401
    ColumnParallelLinear,
    ParallelCrossEntropy,
    RowParallelLinear,
    VocabParallelEmbedding,
    parallel_cross_entropy,
    sharding_constraint,
)
from .parallel import (  # noqa: F401
    DataParallel,
    apply_rules,
    model_shardings,
    parallelize,
    shard_batch,
    shard_model,
    shard_tensor,
)
from . import fleet  # noqa: F401
from . import moe  # noqa: F401
from . import pipeline  # noqa: F401
from . import ring_attention  # noqa: F401
from . import sharding  # noqa: F401
from .sharding import group_sharded_parallel, save_group_sharded_model  # noqa: F401
from . import checkpoint  # noqa: F401
from .checkpoint import load_state_dict, save_state_dict  # noqa: F401
from . import launch  # noqa: F401


def get_world_size_safe():
    import jax

    return jax.device_count()
