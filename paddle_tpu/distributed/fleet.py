"""Fleet facade (ref: python/paddle/distributed/fleet/fleet.py).

Paddle's `fleet.init(is_collective=True, strategy=...)` builds NCCL
groups; `fleet.distributed_model/distributed_optimizer` wrap model and
optimizer with hybrid-parallel machinery. TPU-native: init builds the
global Mesh from the strategy's hybrid_configs; distributed_model is
`parallelize` (annotate + place); distributed_optimizer applies the
strategy's optimizer-side knobs (ZeRO slot sharding for
sharding_stage 1/2, k-step GradientMerge for gradient_merge_steps).
"""
from __future__ import annotations

import typing

from .mesh import DistributedStrategy, get_mesh, init_parallel_env
from .parallel import parallelize

_strategy: typing.Optional[DistributedStrategy] = None


def init(role_maker=None, is_collective=True, strategy=None, log_level='INFO'):
    """ref: fleet.init. Accepts a DistributedStrategy or a dict-style
    hybrid_configs ({'dp_degree':..,'mp_degree':..,'pp_degree':..,
    'sharding_degree':..})."""
    global _strategy
    if isinstance(strategy, dict):
        strategy = _from_hybrid_configs(strategy)
    elif strategy is not None and hasattr(strategy, 'hybrid_configs') \
            and isinstance(strategy.hybrid_configs, dict):
        strategy = _from_hybrid_configs(strategy.hybrid_configs, strategy)
    _strategy = strategy or DistributedStrategy()
    init_parallel_env(_strategy)
    return _strategy


def _from_hybrid_configs(cfg: dict, base=None) -> DistributedStrategy:
    s = base if isinstance(base, DistributedStrategy) else DistributedStrategy()
    mapping = {
        'dp_degree': 'dp_degree', 'mp_degree': 'tp_degree',
        'pp_degree': 'pp_degree', 'sharding_degree': 'fsdp_degree',
        'sep_degree': 'sp_degree', 'ep_degree': 'ep_degree',
    }
    for k, attr in mapping.items():
        if k in cfg:
            setattr(s, attr, cfg[k])
    return s


def distributed_model(model, rules=None, fsdp=None):
    """ref: fleet.distributed_model — here: annotate + shard over the mesh."""
    strategy = _strategy or DistributedStrategy()
    fsdp_axis = 'fsdp' if (
        fsdp if fsdp is not None else strategy.sharding_stage >= 3
        or strategy.fsdp_degree not in (1,)) else None
    return parallelize(model, get_mesh(), rules=rules, fsdp_axis=fsdp_axis)


def distributed_optimizer(optimizer, strategy=None):
    """ref: fleet.distributed_optimizer — applies the strategy's
    optimizer-side knobs: gradient_merge_steps wraps the optimizer in
    GradientMerge (k-step accumulation), sharding_stage 1/2 wraps it in
    GroupShardedOptimizer (ZeRO slot/grad sharding over the data axes)."""
    strategy = strategy or _strategy or DistributedStrategy()
    if getattr(strategy, 'sharding_stage', 0) in (1, 2):
        from .sharding import GroupShardedOptimizer

        mesh = get_mesh()
        if mesh is not None:
            optimizer = GroupShardedOptimizer(
                optimizer, mesh,
                shard_grads=(strategy.sharding_stage == 2))
    k = getattr(strategy, 'gradient_merge_steps', 1)
    if k and k > 1:
        from ..optimizer.wrappers import GradientMerge

        optimizer = GradientMerge(optimizer, k_steps=k)
    return optimizer


def get_hybrid_communicate_group():
    """Minimal HCG parity: exposes the mesh + axis sizes."""
    mesh = get_mesh()

    class _HCG:
        def __init__(self, mesh):
            self.mesh = mesh

        def get_data_parallel_world_size(self):
            return (self.mesh.shape['dp'] * self.mesh.shape['fsdp']
                    if self.mesh else 1)

        def get_model_parallel_world_size(self):
            return self.mesh.shape['tp'] if self.mesh else 1

        def get_pipe_parallel_world_size(self):
            return self.mesh.shape['pp'] if self.mesh else 1

    return _HCG(mesh)


def worker_num():
    import jax

    return jax.process_count()


def worker_index():
    import jax

    return jax.process_index()


def barrier_worker():
    return None
