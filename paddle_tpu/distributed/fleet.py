"""Fleet facade (ref: python/paddle/distributed/fleet/fleet.py).

Paddle's `fleet.init(is_collective=True, strategy=...)` builds NCCL
groups; `fleet.distributed_model/distributed_optimizer` wrap model and
optimizer with hybrid-parallel machinery. TPU-native: init builds the
global Mesh from the strategy's hybrid_configs; distributed_model is
`parallelize` (annotate + place); distributed_optimizer applies the
strategy's optimizer-side knobs (ZeRO slot sharding for
sharding_stage 1/2, k-step GradientMerge for gradient_merge_steps).
"""
from __future__ import annotations

import typing

from .mesh import DistributedStrategy, get_mesh, init_parallel_env
from .parallel import parallelize

_strategy: typing.Optional[DistributedStrategy] = None


def init(role_maker=None, is_collective=True, strategy=None, log_level='INFO'):
    """ref: fleet.init. Accepts a DistributedStrategy or a dict-style
    hybrid_configs ({'dp_degree':..,'mp_degree':..,'pp_degree':..,
    'sharding_degree':..})."""
    global _strategy
    if isinstance(strategy, dict):
        strategy = _from_hybrid_configs(strategy)
    elif strategy is not None and hasattr(strategy, 'hybrid_configs') \
            and isinstance(strategy.hybrid_configs, dict):
        strategy = _from_hybrid_configs(strategy.hybrid_configs, strategy)
    _strategy = strategy or DistributedStrategy()
    init_parallel_env(_strategy)
    return _strategy


def _from_hybrid_configs(cfg: dict, base=None) -> DistributedStrategy:
    s = base if isinstance(base, DistributedStrategy) else DistributedStrategy()
    mapping = {
        'dp_degree': 'dp_degree', 'mp_degree': 'tp_degree',
        'pp_degree': 'pp_degree', 'sharding_degree': 'fsdp_degree',
        'sep_degree': 'sp_degree', 'ep_degree': 'ep_degree',
    }
    for k, attr in mapping.items():
        if k in cfg:
            setattr(s, attr, cfg[k])
    return s


def distributed_model(model, rules=None, fsdp=None):
    """ref: fleet.distributed_model — here: annotate + shard over the mesh."""
    strategy = _strategy or DistributedStrategy()
    fsdp_axis = 'fsdp' if (
        fsdp if fsdp is not None else strategy.sharding_stage >= 3
        or strategy.fsdp_degree not in (1,)) else None
    return parallelize(model, get_mesh(), rules=rules, fsdp_axis=fsdp_axis)


def distributed_optimizer(optimizer, strategy=None):
    """ref: fleet.distributed_optimizer — applies the strategy's
    optimizer-side knobs: gradient_merge_steps wraps the optimizer in
    GradientMerge (k-step accumulation), sharding_stage 1/2 wraps it in
    GroupShardedOptimizer (ZeRO slot/grad sharding over the data axes)."""
    strategy = strategy or _strategy or DistributedStrategy()
    if getattr(strategy, 'sharding_stage', 0) in (1, 2):
        from .sharding import GroupShardedOptimizer

        mesh = get_mesh()
        if mesh is not None:
            optimizer = GroupShardedOptimizer(
                optimizer, mesh,
                shard_grads=(strategy.sharding_stage == 2))
    k = getattr(strategy, 'gradient_merge_steps', 1)
    if k and k > 1:
        from ..optimizer.wrappers import GradientMerge

        optimizer = GradientMerge(optimizer, k_steps=k)
    return optimizer


def get_hybrid_communicate_group():
    """Minimal HCG parity: exposes the mesh + axis sizes."""
    mesh = get_mesh()

    class _HCG:
        def __init__(self, mesh):
            self.mesh = mesh

        def get_data_parallel_world_size(self):
            return (self.mesh.shape['dp'] * self.mesh.shape['fsdp']
                    if self.mesh else 1)

        def get_model_parallel_world_size(self):
            return self.mesh.shape['tp'] if self.mesh else 1

        def get_pipe_parallel_world_size(self):
            return self.mesh.shape['pp'] if self.mesh else 1

    return _HCG(mesh)


def worker_num():
    import jax

    return jax.process_count()


def worker_index():
    import jax

    return jax.process_index()


def barrier_worker():
    return None


class Fleet:
    """ref: paddle.distributed.fleet.Fleet — the stateful facade object.
    Module-level fleet.init/distributed_model/... already implement the
    behavior; this class binds them for scripts that instantiate or
    type-check `fleet.Fleet`."""

    def init(self, role_maker=None, is_collective=True, strategy=None,
             log_level='INFO'):
        return init(role_maker, is_collective, strategy, log_level)

    def distributed_model(self, model, **kw):
        return distributed_model(model, **kw)

    def distributed_optimizer(self, optimizer, strategy=None):
        return distributed_optimizer(optimizer, strategy)

    def worker_num(self):
        return worker_num()

    def worker_index(self):
        return worker_index()

    def barrier_worker(self):
        return barrier_worker()

    def is_first_worker(self):
        return worker_index() == 0

    @property
    def util(self):
        return UtilBase()


class UtilBase:
    """ref: fleet.UtilBase — small cross-worker utilities. Under SPMD
    every worker holds the same host values, so the reductions are
    element-wise over the provided list."""

    def all_reduce(self, input, mode='sum', comm_world='worker'):
        import numpy as np

        arr = np.asarray(input)
        return arr  # one program: the value is already the reduction

    def all_gather(self, input, comm_world='worker'):
        from .mesh import get_world_size

        return [input] * get_world_size()

    def barrier(self, comm_world='worker'):
        from .collective import barrier

        barrier()

    def get_file_shard(self, files):
        from .mesh import get_rank, get_world_size

        n = get_world_size()
        r = get_rank()
        return [f for i, f in enumerate(files) if i % n == r]

    def print_on_rank(self, message, rank_id=0):
        from .mesh import get_rank

        if get_rank() == rank_id:
            print(message)


class HybridCommunicateGroup:
    """ref: fleet.HybridCommunicateGroup — the topology view the
    meta-parallel wrappers query. Backed by the live Mesh axes."""

    def __init__(self, topology=None):
        self._topo = topology

    def _axis(self, name):
        m = get_mesh()
        return m.shape.get(name, 1) if m is not None else 1

    def get_data_parallel_world_size(self):
        return self._axis('dp') * self._axis('fsdp')

    def get_model_parallel_world_size(self):
        return self._axis('tp')

    def get_pipe_parallel_world_size(self):
        return self._axis('pp')

    def get_sharding_parallel_world_size(self):
        return self._axis('fsdp')

    def get_data_parallel_rank(self):
        return 0  # SPMD: one program, rank view is per-shard inside jit

    def get_model_parallel_rank(self):
        return 0

    def get_stage_id(self):
        return 0

    def topology(self):
        return self._topo


class CommunicateTopology:
    """ref: fleet.CommunicateTopology — named axes + degrees."""

    def __init__(self, hybrid_group_names=('data', 'pipe', 'sharding',
                                           'model'), dims=(1, 1, 1, 1)):
        self._names = list(hybrid_group_names)
        self._dims = list(dims)

    def get_hybrid_group_names(self):
        return self._names

    def get_dim(self, axis_name):
        return self._dims[self._names.index(axis_name)]

    def world_size(self):
        out = 1
        for d in self._dims:
            out *= d
        return out


class Role:
    """ref: fleet.base.role_maker.Role."""

    WORKER = 1
    SERVER = 2
    HETER_WORKER = 3
    ALL = 4
    COORDINATOR = 5


class PaddleCloudRoleMaker:
    """ref: fleet.PaddleCloudRoleMaker — env-var cluster discovery. The
    SPMD runtime discovers topology from jax.distributed instead; this
    records the collective flag for fleet.init."""

    def __init__(self, is_collective=False, **kwargs):
        self._is_collective = is_collective

    def _role(self):
        return Role.WORKER


class UserDefinedRoleMaker(PaddleCloudRoleMaker):
    """ref: fleet.UserDefinedRoleMaker."""

    def __init__(self, is_collective=False, init_gloo=False, **kwargs):
        super().__init__(is_collective)


def _ps_generator(name):
    class _Gen:
        """Parameter-server data generators are ps-mode machinery
        (excluded — SURVEY §6); io.DataLoader is the input path here."""

        def __init__(self, *a, **k):
            raise NotImplementedError(
                f'{name} belongs to ps mode (excluded on TPU — SURVEY '
                f'§6); use io.DataLoader')

    _Gen.__name__ = name
    return _Gen


MultiSlotDataGenerator = _ps_generator('MultiSlotDataGenerator')
MultiSlotStringDataGenerator = _ps_generator('MultiSlotStringDataGenerator')
