"""GroupSharded / ZeRO (ref: python/paddle/distributed/sharding/
group_sharded.py, fleet/meta_parallel/sharding/*).

Paddle implements three explicit stages (optimizer-state / gradient /
parameter sharding) with hand-written broadcast/reduce-scatter phases.
TPU-native, the three stages are *sharding declarations* that GSPMD
lowers to the same reduce-scatter/all-gather schedule:

  stage 1 ('os')   — optimizer slots (moments, master weights) carry a
      NamedSharding over the data axes: each device stores 1/N of every
      slot. `GroupShardedOptimizer` places the state at init and
      re-constrains it after every update so it STAYS sharded under jit.
  stage 2 ('os_g') — additionally constrains the incoming grads to the
      same specs, forcing the grad averaging into reduce-scatter form
      (each device materialises only its 1/N grad shard for the update).
  stage 3 ('p_g_os') — parameters themselves sharded:
      `shard_model(model, mesh, fsdp_axis='fsdp')`; XLA all-gathers
      just-in-time at each use — the ZeRO-3 schedule, compiled.

`group_sharded_parallel` keeps the reference's call shape.
"""
from __future__ import annotations

import jax
import numpy as np
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from .mesh import get_mesh
from .parallel import shard_model


def _zero_axes(mesh):
    """Data axes available for slot sharding (size > 1)."""
    return tuple(a for a in ('dp', 'fsdp')
                 if a in mesh.axis_names and mesh.shape[a] > 1)


def zero_spec(shape, mesh, axes=None):
    """PartitionSpec sharding the largest divisible dim over the data
    axes (ZeRO's flat 1/N split, expressed per-tensor)."""
    axes = axes if axes is not None else _zero_axes(mesh)
    if not axes or not shape:
        return P()
    n = int(np.prod([mesh.shape[a] for a in axes]))
    # largest dim divisible by the full axis product
    order = sorted(range(len(shape)), key=lambda i: -shape[i])
    for i in order:
        if shape[i] % n == 0:
            spec = [None] * len(shape)
            spec[i] = axes if len(axes) > 1 else axes[0]
            return P(*spec)
    return P()


def data_sharding(mesh, axes=('dp', 'fsdp')):
    """NamedSharding that splits the BATCH (leading) dim over the data
    axes — the placement every per-example tensor (ids, labels, masks)
    wants under dp/fsdp. `prefetch_to_device` applies it during H2D so
    each device receives only its shard of the global batch and the DMA
    overlaps the previous step's compute (training/engine.py's input
    contract); scalars and 0-d leaves ride along replicated."""
    axes = tuple(a for a in axes
                 if a in mesh.axis_names and mesh.shape[a] > 1)
    if not axes:
        return NamedSharding(mesh, P())
    return NamedSharding(mesh, P(axes if len(axes) > 1 else axes[0]))


class GroupShardedOptimizer:
    """ZeRO stage-1/2 wrapper (ref: sharding/group_sharded.py
    GroupShardedOptimizerStage2): delegates the math to the wrapped
    optimizer, owns the *placement* of its state."""

    def __init__(self, inner, mesh, shard_grads=False, axes=None):
        self._inner = inner
        self._mesh = mesh
        self._axes = axes if axes is not None else _zero_axes(mesh)
        self._shard_grads = shard_grads

    def _spec_tree(self, tree):
        return jax.tree.map(
            lambda x: zero_spec(getattr(x, 'shape', ()), self._mesh,
                                self._axes), tree)

    def _constrain(self, tree):
        return jax.tree.map(
            lambda x, s: jax.lax.with_sharding_constraint(
                x, NamedSharding(self._mesh, s))
            if hasattr(x, 'shape') else x,
            tree, self._spec_tree(tree))

    def init(self, model):
        state = self._inner.init(model)
        shardings = jax.tree.map(
            lambda x, s: NamedSharding(self._mesh, s),
            state, self._spec_tree(state))
        state = jax.device_put(state, shardings)
        self._inner.state = state
        return state

    def apply_gradients(self, model, grads, state=None):
        if self._shard_grads:
            # stage 2: grads land in reduce-scattered (sharded) form
            grads = self._constrain(grads)
        model, state = self._inner.apply_gradients(model, grads, state)
        state = self._constrain(state)
        self._inner.state = state
        return model, state

    def __getattr__(self, name):
        if name.startswith('_'):
            raise AttributeError(name)
        return getattr(self._inner, name)


def group_sharded_parallel(model, optimizer, level='p_g_os', scaler=None,
                           group=None, offload=False, sync_buffers=False,
                           buffer_max_size=None, segment_size=None,
                           sync_comm=False, dp_group=None,
                           exclude_layer=None):
    """ref: paddle.distributed.sharding.group_sharded_parallel.

    level: 'os' (stage 1) | 'os_g' (stage 2) | 'p_g_os' (stage 3).
    Returns (model, optimizer, scaler) like the reference.
    """
    mesh = get_mesh()
    if level not in ('os', 'os_g', 'p_g_os'):
        raise ValueError(f"level must be 'os'|'os_g'|'p_g_os', got {level}")
    if mesh is not None and level == 'p_g_os':
        model = shard_model(model, mesh, fsdp_axis='fsdp')
    elif mesh is not None:
        model = shard_model(model, mesh)
        optimizer = GroupShardedOptimizer(optimizer, mesh,
                                          shard_grads=(level == 'os_g'))
    return model, optimizer, scaler


def save_group_sharded_model(model, output, optimizer=None):
    """ref: paddle.distributed.sharding.save_group_sharded_model."""
    from ..framework import io as io_mod

    io_mod.save(model.state_dict(), output + '.pdparams')
    if optimizer is not None and getattr(optimizer, 'state', None) is not None:
        import jax

        leaves = jax.tree.leaves(optimizer.state)
        io_mod.save({str(i): l for i, l in enumerate(leaves)}, output + '.pdopt')
