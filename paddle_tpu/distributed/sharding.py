"""GroupSharded / ZeRO (ref: python/paddle/distributed/sharding/
group_sharded.py, fleet/meta_parallel/sharding/*).

Paddle implements three explicit stages (optimizer-state / gradient /
parameter sharding) with hand-written broadcast/reduce-scatter phases.
TPU-native, the three stages are *sharding declarations*, not code:

  stage 1/2 — optimizer slots inherit param PartitionSpecs when
      `opt.init` runs on sharded params; grads are reduce-scattered by
      GSPMD when the batch axis is sharded. Nothing to wrap.
  stage 3 — parameters themselves sharded over the data axis:
      `shard_model(model, mesh, fsdp_axis='fsdp')` adds the 'fsdp' axis
      to each param's largest free dim; XLA all-gathers just-in-time at
      each use and frees afterwards — the ZeRO-3 schedule, compiled.

`group_sharded_parallel` keeps the reference's call shape.
"""
from __future__ import annotations

from .mesh import get_mesh
from .parallel import shard_model


def group_sharded_parallel(model, optimizer, level='p_g_os', scaler=None,
                           group=None, offload=False, sync_buffers=False,
                           buffer_max_size=None, segment_size=None,
                           sync_comm=False, dp_group=None,
                           exclude_layer=None):
    """ref: paddle.distributed.sharding.group_sharded_parallel.

    level: 'os' (stage 1) | 'os_g' (stage 2) | 'p_g_os' (stage 3).
    Returns (model, optimizer, scaler) like the reference.
    """
    mesh = get_mesh()
    if level not in ('os', 'os_g', 'p_g_os'):
        raise ValueError(f"level must be 'os'|'os_g'|'p_g_os', got {level}")
    if mesh is not None and level == 'p_g_os':
        model = shard_model(model, mesh, fsdp_axis='fsdp')
    elif mesh is not None:
        # stages 1/2: params replicated over fsdp; optimizer slots will be
        # sharded by GSPMD's memory-saving pass; ensure placement is set
        model = shard_model(model, mesh)
    return model, optimizer, scaler


def save_group_sharded_model(model, output, optimizer=None):
    """ref: paddle.distributed.sharding.save_group_sharded_model."""
    from ..framework import io as io_mod

    io_mod.save(model.state_dict(), output + '.pdparams')
    if optimizer is not None and getattr(optimizer, 'state', None) is not None:
        import jax

        leaves = jax.tree.leaves(optimizer.state)
        io_mod.save({str(i): l for i, l in enumerate(leaves)}, output + '.pdopt')
