"""Pipeline parallelism (ref: python/paddle/distributed/fleet/
meta_parallel/pipeline_parallel.py, pp_utils).

Paddle: each pp rank owns a stage module; a Python scheduler
(forward_backward_pipeline) drives 1F1B micro-batch phases with NCCL
p2p send/recv between ranks.

TPU-native: the stage loop is *data*: all stages' parameters are stacked
on a leading 'pp'-sharded axis, and one `shard_map` program runs the
GPipe schedule as a `lax.fori_loop` with `ppermute` rotations riding the
ICI ring. XLA overlaps the collective permute with the stage compute —
the same overlap Paddle gets from separate CUDA streams.

The model side: `PipelineStage` wraps a list of per-stage step
functions with identical signatures; `pipeline_apply` runs the
schedule. For models built as a stack of identical blocks (the LLM
case) use `stacked_pipeline` — stage weights are a stacked pytree and
the per-stage fn is one block-stack forward.
"""
from __future__ import annotations

import functools
import typing

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from ._spmd import pvary as _pvary
from ._spmd import shard_map


def stack_stage_params(stage_models: typing.Sequence, axis=0):
    """Stack N same-structure stage pytrees into one pytree with a leading
    stage axis (shard it over 'pp')."""
    return jax.tree.map(lambda *xs: jnp.stack(xs, axis=axis), *stage_models)


def pipeline_spmd(stage_fn, n_stages: int, n_microbatches: int, axis='pp'):
    """Build the SPMD GPipe body to run under `shard_map`.

    stage_fn(stage_params, x) -> y, applied by every pp rank to its
    resident stage. Inside shard_map each rank holds: its stage's params
    (leading axis stripped to size 1) and the full microbatch queue.

    Schedule (GPipe, forward): T = n_micro + n_stages - 1 ticks; at tick
    t, rank s computes microbatch (t - s) if 0 <= t-s < n_micro. After
    each tick activations rotate +1 along the ring; outputs collect on
    the last rank then broadcast.
    """
    if n_microbatches < 1:
        raise ValueError(f'n_microbatches must be >= 1, got {n_microbatches}')

    def body(stage_params, microbatches):
        # microbatches: (n_micro, mb, ...) identical on every rank;
        # promote to pp-varying so the vma types line up with the
        # per-rank compute (check_vma=True)
        microbatches = _pvary(microbatches, axis)
        rank = lax.axis_index(axis)
        n_ticks = n_microbatches + n_stages - 1
        mb_shape = microbatches.shape[1:]
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def tick(carry, t):
            buf, outputs = carry
            # which microbatch this rank works on at tick t
            mb_idx = t - rank
            active = (mb_idx >= 0) & (mb_idx < n_microbatches)
            # stage 0 pulls fresh input from the queue; others use the
            # rotated buffer
            fresh = lax.dynamic_index_in_dim(
                microbatches, jnp.clip(mb_idx, 0, n_microbatches - 1), 0,
                keepdims=False)
            x = jnp.where(rank == 0, fresh, buf)
            y = stage_fn(stage_params, x)
            y = jnp.where(active, y, buf)
            # last stage: record finished microbatch
            done_idx = t - (n_stages - 1)
            is_done = (rank == n_stages - 1) & (done_idx >= 0) & (done_idx < n_microbatches)
            outputs = lax.cond(
                is_done,
                lambda o: lax.dynamic_update_index_in_dim(
                    o, y, jnp.clip(done_idx, 0, n_microbatches - 1), 0),
                lambda o: o,
                outputs,
            )
            buf = lax.ppermute(y, axis, perm)
            return (buf, outputs), None

        buf0 = _pvary(jnp.zeros(mb_shape, microbatches.dtype), axis)
        outs0 = _pvary(
            jnp.zeros((n_microbatches,) + mb_shape, microbatches.dtype), axis)
        # scan (not fori_loop): reverse-differentiable, so the 1F1B/GPipe
        # backward falls out of jax.grad through the schedule
        (_, outputs), _ = lax.scan(tick, (buf0, outs0), jnp.arange(n_ticks))
        # outputs live on the last rank; psum broadcasts (others hold zeros)
        return lax.psum(outputs, axis)

    return body


def pipeline_apply(stacked_params, microbatches, stage_fn, mesh: Mesh,
                   n_microbatches: int, axis='pp'):
    """Run the GPipe forward over a 'pp'-sharded stack of stage params.

    stacked_params: pytree with leading stage axis == mesh.shape[axis].
    microbatches: (n_micro, mb, ...) array (replicated).
    """
    n_stages = mesh.shape[axis]
    body = pipeline_spmd(stage_fn, n_stages, n_microbatches, axis)

    param_specs = jax.tree.map(lambda _: P(axis), stacked_params)
    other_axes = [a for a in mesh.axis_names if a != axis]

    def local_body(params, mbs):
        # strip the local stage axis (size 1 per rank)
        local = jax.tree.map(lambda p: p[0], params)
        return body(local, mbs)

    fn = shard_map(
        local_body, mesh=mesh,
        in_specs=(param_specs, P()), out_specs=P(),
        # only 'pp' is hand-scheduled; other mesh axes (dp/tp/fsdp) stay
        # under GSPMD so hybrid dp×pp×tp composes in one train step
        axis_names={axis},
        check_vma=True,
    )
    return fn(stacked_params, microbatches)


# ---------------------------------------------------------------------------
# 1F1B schedule (ref: fleet/meta_parallel/pipeline_parallel.py::
# forward_backward_pipeline — steady-state one-forward-one-backward)
# ---------------------------------------------------------------------------

def build_1f1b_schedule(n_stages: int, n_micro: int):
    """Static 1F1B timetable via greedy simulation with in-flight caps.

    Stage s keeps at most (n_stages - s) microbatches in flight (the
    classic 1F1B warmup depth), prefers backward when one is ready
    (drains activation memory ASAP), and respects the 1-tick ppermute
    communication latency between neighbouring stages.

    Returns dict of numpy int32 tables, each (T, n_stages), entry = the
    microbatch index the stage handles at that tick (-1 = none):
      fwd / bwd          — compute
      recv_act / recv_grad — message arriving at tick start (stored into
                             the act/grad queues before compute)
    plus queue depths (act_q, grad_q, stash) validated collision-free.
    """
    p, M = n_stages, n_micro
    INF = 1 << 30
    fwd_done = [[INF] * M for _ in range(p)]
    bwd_done = [[INF] * M for _ in range(p)]
    next_f, next_b = [0] * p, [0] * p
    fwd_rows, bwd_rows = [], []
    t = 0
    while any(nb < M for nb in next_b):
        frow, brow = [-1] * p, [-1] * p
        for s in range(p):
            mb, mf = next_b[s], next_f[s]
            bwd_ready = mb < M and fwd_done[s][mb] < t and (
                s == p - 1 or bwd_done[s + 1][mb] < t)
            fwd_ready = mf < M and (mf - mb) < (p - s) and (
                s == 0 or fwd_done[s - 1][mf] < t)
            if bwd_ready:
                brow[s] = mb
                bwd_done[s][mb] = t
                next_b[s] += 1
            elif fwd_ready:
                frow[s] = mf
                fwd_done[s][mf] = t
                next_f[s] += 1
        fwd_rows.append(frow)
        bwd_rows.append(brow)
        t += 1
        if t > 4 * (M + p) + 16:       # safety: schedule must converge
            raise RuntimeError('1f1b schedule did not converge')
    T = t
    fwd_tab = np.asarray(fwd_rows, np.int32)
    bwd_tab = np.asarray(bwd_rows, np.int32)

    # message-arrival tables: sent at end of tick t-1, usable at tick t
    recv_act = np.full((T, p), -1, np.int32)
    recv_grad = np.full((T, p), -1, np.int32)
    recv_act[1:, 1:] = fwd_tab[:-1, :-1]
    recv_grad[1:, :-1] = bwd_tab[:-1, 1:]

    def _min_depth(store_tick, consume_tick, pairs):
        # smallest Q such that no slot (m % Q) is overwritten while the
        # previous occupant is still unread (store precedes consume
        # within a tick, so a same-tick store/consume of different mbs
        # collides)
        for Q in range(1, M + 1):
            ok = True
            for (s, m) in pairs:
                m2 = m + Q
                if m2 < M:
                    st2 = store_tick(s, m2)
                    if st2 is not None and st2 <= consume_tick(s, m):
                        ok = False
                        break
            if ok:
                return Q
        return M

    pairs = [(s, m) for s in range(p) for m in range(M)]
    act_depth = _min_depth(
        lambda s, m: fwd_done[s - 1][m] + 1 if s >= 1 else None,
        lambda s, m: fwd_done[s][m], pairs)
    grad_depth = _min_depth(
        lambda s, m: bwd_done[s + 1][m] + 1 if s < p - 1 else None,
        lambda s, m: bwd_done[s][m], pairs)
    stash_depth = _min_depth(
        lambda s, m: fwd_done[s][m],
        lambda s, m: bwd_done[s][m], pairs)
    return {
        'fwd': fwd_tab, 'bwd': bwd_tab,
        'recv_act': recv_act, 'recv_grad': recv_grad,
        'act_q': act_depth, 'grad_q': grad_depth, 'stash': stash_depth,
        'ticks': T,
    }


def pipeline_1f1b(stacked_params, extra_params, microbatches, targets,
                  stage_fn, loss_fn, mesh: Mesh, n_microbatches: int,
                  axis='pp'):
    """1F1B fused forward+backward (ref: pipeline_parallel.py 1F1B).

    Hand-scheduled fwd/bwd interleave: each stage stashes only the
    *inputs* of its in-flight microbatches (≤ n_stages - s of them, vs
    GPipe's O(n_microbatches) scan residuals) and recomputes the stage
    forward inside `jax.vjp` when the microbatch's backward tick fires —
    the remat-style 1F1B every production pipeline uses.

    stage_fn(stage_params, x) -> y        (y.shape == x.shape)
    loss_fn(extra_params, y, target) -> scalar  (runs on the LAST stage)

    Returns (loss, d_stacked, d_extra, d_microbatches): mean loss over
    microbatches and the matching parameter/input cotangents.
    """
    p = mesh.shape[axis]
    M = n_microbatches
    if microbatches.shape[0] != M or targets.shape[0] != M:
        raise ValueError(
            f'microbatches/targets leading dim ({microbatches.shape[0]}/'
            f'{targets.shape[0]}) must equal n_microbatches ({M})')
    sched = build_1f1b_schedule(p, M)
    fwd_tab = jnp.asarray(sched['fwd'])
    bwd_tab = jnp.asarray(sched['bwd'])
    ra_tab = jnp.asarray(sched['recv_act'])
    rg_tab = jnp.asarray(sched['recv_grad'])
    Qa, Qg, S = sched['act_q'], sched['grad_q'], sched['stash']
    T = sched['ticks']
    perm_f = [(i, (i + 1) % p) for i in range(p)]
    perm_b = [(i, (i - 1) % p) for i in range(p)]

    mb_shape = microbatches.shape[1:]
    mb_dtype = microbatches.dtype
    # soft/float targets (regression, soft labels) get a real cotangent;
    # integer targets are non-differentiable
    diff_targets = jnp.issubdtype(targets.dtype, jnp.inexact)

    def body(params, extra, mbs, tgts):
        rank = lax.axis_index(axis)
        local = jax.tree.map(lambda x: x[0], params)   # strip stage axis
        # replicated inputs → pp-varying so vma types line up with the
        # per-rank compute (check_vma=True)
        pv = lambda t: jax.tree.map(lambda x: _pvary(x, axis), t)
        mbs, tgts, extra = pv(mbs), pv(tgts), pv(extra)

        zeros_mb = _pvary(jnp.zeros(mb_shape, mb_dtype), axis)
        zeros_p = jax.tree.map(jnp.zeros_like, local)
        zeros_e = jax.tree.map(jnp.zeros_like, extra)
        zeros_t = _pvary(jnp.zeros(targets.shape[1:], targets.dtype),
                            axis)

        def tick(carry, t):
            (act_q, grad_q, stash, act_msg, grad_msg,
             pgrad, egrad, dmbs, dtgts, loss_acc) = carry
            fm = fwd_tab[t, rank]
            bm = bwd_tab[t, rank]
            ram = ra_tab[t, rank]
            rgm = rg_tab[t, rank]

            # 1. receive (store precedes compute: warmup consumes the
            #    act that arrived this very tick)
            act_q = lax.cond(
                ram >= 0,
                lambda aq: lax.dynamic_update_index_in_dim(
                    aq, act_msg, jnp.clip(ram, 0) % Qa, 0),
                lambda aq: aq, act_q)
            grad_q = lax.cond(
                rgm >= 0,
                lambda gq: lax.dynamic_update_index_in_dim(
                    gq, grad_msg, jnp.clip(rgm, 0) % Qg, 0),
                lambda gq: gq, grad_q)

            # 2. forward (cond: ranks with no fwd this tick skip compute)
            def do_fwd(stash):
                fresh = lax.dynamic_index_in_dim(
                    mbs, jnp.clip(fm, 0, M - 1), 0, keepdims=False)
                queued = lax.dynamic_index_in_dim(
                    act_q, jnp.clip(fm, 0) % Qa, 0, keepdims=False)
                x = jnp.where(rank == 0, fresh, queued)
                y = stage_fn(local, x)
                stash = lax.dynamic_update_index_in_dim(
                    stash, x, jnp.clip(fm, 0) % S, 0)
                return stash, y

            stash, act_out = lax.cond(
                fm >= 0, do_fwd, lambda st: (st, zeros_mb), stash)

            # 3. backward (recompute-vjp on the stashed input)
            def do_bwd(args):
                pgrad, egrad, dmbs, dtgts, loss_acc = args
                x = lax.dynamic_index_in_dim(
                    stash, jnp.clip(bm, 0) % S, 0, keepdims=False)
                g_in = lax.dynamic_index_in_dim(
                    grad_q, jnp.clip(bm, 0) % Qg, 0, keepdims=False)
                tgt = lax.dynamic_index_in_dim(
                    tgts, jnp.clip(bm, 0, M - 1), 0, keepdims=False)

                def last_stage(_):
                    if diff_targets:
                        def f(par, ex, xx, tt):
                            return loss_fn(ex, stage_fn(par, xx), tt)

                        lval, vjp = jax.vjp(f, local, extra, x, tgt)
                        dpar, dex, dx, dt = vjp(_pvary(jnp.ones((), lval.dtype), axis))
                    else:
                        def f(par, ex, xx):
                            return loss_fn(ex, stage_fn(par, xx), tgt)

                        lval, vjp = jax.vjp(f, local, extra, x)
                        dpar, dex, dx = vjp(_pvary(jnp.ones((), lval.dtype), axis))
                        dt = zeros_t
                    return dpar, dex, dx, dt, lval.astype(jnp.float32)

                def mid_stage(_):
                    _, vjp = jax.vjp(lambda par, xx: stage_fn(par, xx),
                                     local, x)
                    dpar, dx = vjp(g_in)
                    return (dpar, zeros_e, dx, zeros_t,
                            _pvary(jnp.zeros((), jnp.float32), axis))

                dpar, dex, dx, dt, lval = lax.cond(
                    rank == p - 1, last_stage, mid_stage, None)
                pgrad = jax.tree.map(jnp.add, pgrad, dpar)
                egrad = jax.tree.map(jnp.add, egrad, dex)
                # stage 0's input-cotangent feeds the outer embedding vjp
                dmbs = lax.cond(
                    rank == 0,
                    lambda d: lax.dynamic_update_index_in_dim(
                        d, dx.astype(d.dtype), jnp.clip(bm, 0, M - 1), 0),
                    lambda d: d, dmbs)
                if diff_targets:
                    dtgts = lax.cond(
                        rank == p - 1,
                        lambda d: lax.dynamic_update_index_in_dim(
                            d, dt.astype(d.dtype), jnp.clip(bm, 0, M - 1), 0),
                        lambda d: d, dtgts)
                return (pgrad, egrad, dmbs, dtgts, loss_acc + lval), dx

            (pgrad, egrad, dmbs, dtgts, loss_acc), grad_out = lax.cond(
                bm >= 0, do_bwd,
                lambda args: (args, zeros_mb),
                (pgrad, egrad, dmbs, dtgts, loss_acc))

            # 4. rotate: activations ride +1, gradients ride -1
            act_msg = lax.ppermute(act_out, axis, perm_f)
            grad_msg = lax.ppermute(grad_out, axis, perm_b)
            return (act_q, grad_q, stash, act_msg, grad_msg,
                    pgrad, egrad, dmbs, dtgts, loss_acc), None

        init = (
            _pvary(jnp.zeros((Qa,) + mb_shape, mb_dtype), axis),
            _pvary(jnp.zeros((Qg,) + mb_shape, mb_dtype), axis),
            _pvary(jnp.zeros((S,) + mb_shape, mb_dtype), axis),
            zeros_mb, zeros_mb,
            zeros_p, zeros_e,
            _pvary(jnp.zeros((M,) + mb_shape, mb_dtype), axis),
            _pvary(jnp.zeros(targets.shape, targets.dtype), axis),
            _pvary(jnp.zeros((), jnp.float32), axis),
        )
        carry, _ = lax.scan(tick, init, jnp.arange(T))
        (_, _, _, _, _, pgrad, egrad, dmbs, dtgts, loss_acc) = carry
        # loss/extra-grads/input-grads live on single ranks; psum shares
        loss = lax.psum(loss_acc, axis) / M
        egrad = jax.tree.map(lambda g: lax.psum(g, axis) / M, egrad)
        dmbs = lax.psum(dmbs, axis) / M
        if diff_targets:
            dtgts = lax.psum(dtgts, axis) / M
        else:
            # integer targets: cotangent is all-zeros; psum just settles
            # the replication type for the P() out_spec
            dtgts = lax.psum(dtgts, axis)
        pgrad = jax.tree.map(lambda g: g[None] / M, pgrad)  # re-add stage axis
        return loss, pgrad, egrad, dmbs, dtgts

    param_specs = jax.tree.map(lambda _: P(axis), stacked_params)
    fn = shard_map(
        body, mesh=mesh,
        in_specs=(param_specs, P(), P(), P()),
        out_specs=(P(), param_specs, P(), P(), P()),
        # 'pp' is hand-scheduled; dp/tp/fsdp stay GSPMD-managed (hybrid)
        axis_names={axis},
        check_vma=True,
    )
    loss, pgrad, egrad, dmbs, dtgts = fn(stacked_params, extra_params,
                                         microbatches, targets)
    return loss, pgrad, egrad, dmbs, (dtgts if diff_targets else None)


def pipeline_1f1b_loss(stacked_params, extra_params, microbatches, targets,
                       stage_fn, loss_fn, mesh: Mesh, n_microbatches: int,
                       axis='pp'):
    """Differentiable scalar 1F1B loss: composes with outer `jax.grad`.

    custom_vjp wrapper — the forward pass runs the fused 1F1B schedule
    (which produces the parameter/input grads as a by-product) and
    caches them; the backward just scales by the incoming cotangent. An
    outer `value_and_grad` therefore drives the whole pipelined train
    step while activation residency stays O(n_stages).
    """
    def run(stacked, extra, mbs, tgts):
        return pipeline_1f1b(stacked, extra, mbs, tgts, stage_fn, loss_fn,
                             mesh, n_microbatches, axis)

    @jax.custom_vjp
    def f(stacked, extra, mbs, tgts):
        loss, _, _, _, _ = run(stacked, extra, mbs, tgts)
        return loss

    def f_fwd(stacked, extra, mbs, tgts):
        loss, dp, de, dm, dt = run(stacked, extra, mbs, tgts)
        return loss, (dp, de, dm, dt)

    def f_bwd(res, g):
        dp, de, dm, dt = res
        scale = lambda t: jax.tree.map(lambda x: x * g, t)
        return (scale(dp), scale(de), scale(dm),
                scale(dt) if dt is not None else None)

    f.defvjp(f_fwd, f_bwd)
    return f(stacked_params, extra_params, microbatches, targets)


def build_interleaved_1f1b_schedule(n_stages: int, n_micro: int,
                                    n_virtual: int):
    """Interleaved (virtual-stage) 1F1B timetable.

    ref: distributed/fleet/meta_parallel/pipeline_parallel.py:1143
    (``PipelineParallelWithInterleave``): the model is cut into
    p·v virtual stages; chunk c lives on rank c % p, so each rank holds
    v non-contiguous chunks and a microbatch makes v sweeps around the
    ring. The classic ordering (microbatches grouped in blocks of p per
    chunk, warmup depth (p-r-1)·2 + (v-1)·p per rank) brings the bubble
    from 2(p-1) full-stage ticks down to 2(p-1) CHUNK ticks — a 1/v
    bubble fraction, the whole point of interleaving.

    Simulated deterministically with blocking deps (1-tick ppermute
    latency between consecutive virtual stages; one compute per rank
    per tick). Requires n_micro % n_stages == 0 (the reference has the
    same constraint).

    Returns int32 tables shaped (T, n_stages): fwd_m/fwd_c, bwd_m/bwd_c
    (microbatch and LOCAL chunk handled at each tick, -1 = none),
    recv_act_m/_c, recv_grad_m/_c (message arriving at tick start), and
    scalar queue depths act_q / grad_q / stash (per chunk) validated
    collision-free, plus 'ticks'.
    """
    p, M, v = n_stages, n_micro, n_virtual
    if M % p:
        raise ValueError(
            f'interleaved 1F1B needs n_micro % n_stages == 0, got {M} % {p}')
    V = p * v
    nops = v * M
    INF = 1 << 30

    def fop(r, k):   # k-th chunk-forward on rank r -> (vstage, micro)
        return ((k // p) % v) * p + r, (k // (p * v)) * p + (k % p)

    def bop(r, k):
        return (v - 1 - (k // p) % v) * p + r, (k // (p * v)) * p + (k % p)

    fwd_done = [[INF] * M for _ in range(V)]
    bwd_done = [[INF] * M for _ in range(V)]
    kf, kb = [0] * p, [0] * p
    warm = [min((p - r - 1) * 2 + (v - 1) * p, nops) for r in range(p)]
    nxt_fwd = [True] * p
    fwd_m_rows, fwd_c_rows, bwd_m_rows, bwd_c_rows = [], [], [], []
    t = 0
    while any(kb[r] < nops for r in range(p)):
        fm_row, fc_row = [-1] * p, [-1] * p
        bm_row, bc_row = [-1] * p, [-1] * p
        for r in range(p):
            if kb[r] >= nops:
                continue

            def try_f():
                vs, m = fop(r, kf[r])
                if vs == 0 or fwd_done[vs - 1][m] < t:
                    fwd_done[vs][m] = t
                    fm_row[r], fc_row[r] = m, vs // p
                    kf[r] += 1
                    return True
                return False

            def try_b():
                vs, m = bop(r, kb[r])
                if fwd_done[vs][m] < t and (
                        vs == V - 1 or bwd_done[vs + 1][m] < t):
                    bwd_done[vs][m] = t
                    bm_row[r], bc_row[r] = m, vs // p
                    kb[r] += 1
                    return True
                return False

            if kf[r] < warm[r]:
                try_f()
            elif kf[r] >= nops:
                try_b()
            elif nxt_fwd[r]:
                if try_f():
                    nxt_fwd[r] = False
            else:
                if try_b():
                    nxt_fwd[r] = True
        fwd_m_rows.append(fm_row)
        fwd_c_rows.append(fc_row)
        bwd_m_rows.append(bm_row)
        bwd_c_rows.append(bc_row)
        t += 1
        if t > 16 * (nops + V) + 64:
            raise RuntimeError('interleaved 1f1b schedule did not converge')
    T = t
    fwd_m = np.asarray(fwd_m_rows, np.int32)
    fwd_c = np.asarray(fwd_c_rows, np.int32)
    bwd_m = np.asarray(bwd_m_rows, np.int32)
    bwd_c = np.asarray(bwd_c_rows, np.int32)

    # message-arrival tables: rank r's act at tick t came from rank r-1's
    # fwd at t-1 of vstage vs; it targets vs+1 (local chunk (vs+1)//p on
    # r). The last vstage's output and vstage 0's grad are dropped.
    recv_act_m = np.full((T, p), -1, np.int32)
    recv_act_c = np.full((T, p), -1, np.int32)
    recv_grad_m = np.full((T, p), -1, np.int32)
    recv_grad_c = np.full((T, p), -1, np.int32)
    for t0 in range(T - 1):
        for r in range(p):
            m, c = fwd_m[t0, r], fwd_c[t0, r]
            if m >= 0:
                vs = c * p + r
                if vs + 1 < V:
                    recv_act_m[t0 + 1, (r + 1) % p] = m
                    recv_act_c[t0 + 1, (r + 1) % p] = (vs + 1) // p
            m, c = bwd_m[t0, r], bwd_c[t0, r]
            if m >= 0:
                vs = c * p + r
                if vs - 1 >= 0:
                    recv_grad_m[t0 + 1, (r - 1) % p] = m
                    recv_grad_c[t0 + 1, (r - 1) % p] = (vs - 1) // p

    def _min_depth(store_tick, consume_tick):
        # per-chunk queues indexed m % Q: smallest Q with no slot
        # overwritten while the previous occupant is still unread
        for Q in range(1, M + 1):
            ok = True
            for vs in range(V):
                for m in range(M - Q):
                    st2 = store_tick(vs, m + Q)
                    if st2 is not None and st2 <= consume_tick(vs, m):
                        ok = False
                        break
                if not ok:
                    break
            if ok:
                return Q
        return M

    act_depth = _min_depth(
        lambda vs, m: fwd_done[vs - 1][m] + 1 if vs >= 1 else None,
        lambda vs, m: fwd_done[vs][m])
    grad_depth = _min_depth(
        lambda vs, m: bwd_done[vs + 1][m] + 1 if vs < V - 1 else None,
        lambda vs, m: bwd_done[vs][m])
    stash_depth = _min_depth(
        lambda vs, m: fwd_done[vs][m],
        lambda vs, m: bwd_done[vs][m])
    return {
        'fwd_m': fwd_m, 'fwd_c': fwd_c, 'bwd_m': bwd_m, 'bwd_c': bwd_c,
        'recv_act_m': recv_act_m, 'recv_act_c': recv_act_c,
        'recv_grad_m': recv_grad_m, 'recv_grad_c': recv_grad_c,
        'act_q': act_depth, 'grad_q': grad_depth, 'stash': stash_depth,
        'ticks': T,
    }


def pipeline_interleaved_1f1b(stacked_params, extra_params, microbatches,
                              targets, stage_fn, loss_fn, mesh: Mesh,
                              n_microbatches: int, n_virtual: int,
                              axis='pp'):
    """Interleaved 1F1B fused forward+backward over virtual stages.

    ref: pipeline_parallel.py:1143 (PipelineParallelWithInterleave).
    ``stacked_params`` carries a leading axis of p·v chunk pytrees in
    VIRTUAL-STAGE order (chunk vs applies vs-th in the model); chunk vs
    executes on rank vs % p. stage_fn(chunk_params, x) -> y applies ONE
    chunk; loss_fn(extra_params, y, target) -> scalar runs on the last
    virtual stage. Returns (loss, d_stacked, d_extra, d_microbatches)
    with d_stacked in the same virtual-stage order.
    """
    p = mesh.shape[axis]
    v = n_virtual
    M = n_microbatches
    V = p * v
    if microbatches.shape[0] != M or targets.shape[0] != M:
        raise ValueError(
            f'microbatches/targets leading dim ({microbatches.shape[0]}/'
            f'{targets.shape[0]}) must equal n_microbatches ({M})')
    sched = build_interleaved_1f1b_schedule(p, M, v)
    tabs = {k: jnp.asarray(sched[k]) for k in
            ('fwd_m', 'fwd_c', 'bwd_m', 'bwd_c', 'recv_act_m', 'recv_act_c',
             'recv_grad_m', 'recv_grad_c')}
    Qa, Qg, S = sched['act_q'], sched['grad_q'], sched['stash']
    T = sched['ticks']
    perm_f = [(i, (i + 1) % p) for i in range(p)]
    perm_b = [(i, (i - 1) % p) for i in range(p)]

    mb_shape = microbatches.shape[1:]
    mb_dtype = microbatches.dtype
    diff_targets = jnp.issubdtype(targets.dtype, jnp.inexact)

    # virtual-stage-major (V, ...) -> rank-major (p, v, ...) so the pp
    # shard gives each rank its v chunks
    def to_rank_major(t):
        return jax.tree.map(
            lambda a: jnp.swapaxes(
                a.reshape((v, p) + a.shape[1:]), 0, 1), t)

    def to_vstage_major(t):
        return jax.tree.map(
            lambda a: jnp.swapaxes(a, 0, 1).reshape((V,) + a.shape[2:]), t)

    rank_params = to_rank_major(stacked_params)

    def body(params, extra, mbs, tgts):
        rank = lax.axis_index(axis)
        local = jax.tree.map(lambda x: x[0], params)   # (v, ...) chunks
        pv = lambda t: jax.tree.map(lambda x: _pvary(x, axis), t)
        mbs, tgts, extra = pv(mbs), pv(tgts), pv(extra)

        zeros_mb = _pvary(jnp.zeros(mb_shape, mb_dtype), axis)
        zeros_p = jax.tree.map(jnp.zeros_like, local)   # per-chunk grads
        zeros_e = jax.tree.map(jnp.zeros_like, extra)
        zeros_t = _pvary(jnp.zeros(targets.shape[1:], targets.dtype),
                            axis)

        def chunk_of(tree, c):
            return jax.tree.map(
                lambda a: lax.dynamic_index_in_dim(a, c, 0, keepdims=False),
                tree)

        def add_at_chunk(tree, c, delta):
            def upd(a, d):
                cur = lax.dynamic_index_in_dim(a, c, 0, keepdims=False)
                return lax.dynamic_update_index_in_dim(a, cur + d, c, 0)
            return jax.tree.map(upd, tree, delta)

        def tick(carry, t):
            (act_q, grad_q, stash, act_msg, grad_msg,
             pgrad, egrad, dmbs, dtgts, loss_acc) = carry
            fm, fc = tabs['fwd_m'][t, rank], tabs['fwd_c'][t, rank]
            bm, bc = tabs['bwd_m'][t, rank], tabs['bwd_c'][t, rank]
            ram, rac = tabs['recv_act_m'][t, rank], tabs['recv_act_c'][t, rank]
            rgm, rgc = tabs['recv_grad_m'][t, rank], tabs['recv_grad_c'][t, rank]

            # 1. receive into per-chunk queues (store precedes compute)
            def store(q, msg, c, m, Q):
                row = lax.dynamic_index_in_dim(q, jnp.clip(c, 0), 0,
                                               keepdims=False)
                row = lax.dynamic_update_index_in_dim(
                    row, msg, jnp.clip(m, 0) % Q, 0)
                return lax.dynamic_update_index_in_dim(
                    q, row, jnp.clip(c, 0), 0)

            act_q = lax.cond(
                ram >= 0, lambda q: store(q, act_msg, rac, ram, Qa),
                lambda q: q, act_q)
            grad_q = lax.cond(
                rgm >= 0, lambda q: store(q, grad_msg, rgc, rgm, Qg),
                lambda q: q, grad_q)

            def fetch(q, c, m, Q):
                row = lax.dynamic_index_in_dim(q, jnp.clip(c, 0), 0,
                                               keepdims=False)
                return lax.dynamic_index_in_dim(
                    row, jnp.clip(m, 0) % Q, 0, keepdims=False)

            # 2. forward of (chunk fc, micro fm)
            def do_fwd(stash):
                fresh = lax.dynamic_index_in_dim(
                    mbs, jnp.clip(fm, 0, M - 1), 0, keepdims=False)
                queued = fetch(act_q, fc, fm, Qa)
                x = jnp.where((rank == 0) & (fc == 0), fresh, queued)
                y = stage_fn(chunk_of(local, jnp.clip(fc, 0)), x)
                stash = store(stash, x, fc, fm, S)
                return stash, y

            stash, act_out = lax.cond(
                fm >= 0, do_fwd, lambda st: (st, zeros_mb), stash)

            # 3. backward of (chunk bc, micro bm): recompute-vjp
            def do_bwd(args):
                pgrad, egrad, dmbs, dtgts, loss_acc = args
                cpar = chunk_of(local, jnp.clip(bc, 0))
                x = fetch(stash, bc, bm, S)
                g_in = fetch(grad_q, bc, bm, Qg)
                tgt = lax.dynamic_index_in_dim(
                    tgts, jnp.clip(bm, 0, M - 1), 0, keepdims=False)

                def last_stage(_):
                    if diff_targets:
                        def f(par, ex, xx, tt):
                            return loss_fn(ex, stage_fn(par, xx), tt)

                        lval, vjp = jax.vjp(f, cpar, extra, x, tgt)
                        dpar, dex, dx, dt = vjp(
                            _pvary(jnp.ones((), lval.dtype), axis))
                    else:
                        def f(par, ex, xx):
                            return loss_fn(ex, stage_fn(par, xx), tgt)

                        lval, vjp = jax.vjp(f, cpar, extra, x)
                        dpar, dex, dx = vjp(
                            _pvary(jnp.ones((), lval.dtype), axis))
                        dt = zeros_t
                    return dpar, dex, dx, dt, lval.astype(jnp.float32)

                def mid_stage(_):
                    _, vjp = jax.vjp(lambda par, xx: stage_fn(par, xx),
                                     cpar, x)
                    dpar, dx = vjp(g_in)
                    return (dpar, zeros_e, dx, zeros_t,
                            _pvary(jnp.zeros((), jnp.float32), axis))

                dpar, dex, dx, dt, lval = lax.cond(
                    (rank == p - 1) & (bc == v - 1), last_stage, mid_stage,
                    None)
                pgrad = add_at_chunk(pgrad, jnp.clip(bc, 0), dpar)
                egrad = jax.tree.map(jnp.add, egrad, dex)
                dmbs = lax.cond(
                    (rank == 0) & (bc == 0),
                    lambda d: lax.dynamic_update_index_in_dim(
                        d, dx.astype(d.dtype), jnp.clip(bm, 0, M - 1), 0),
                    lambda d: d, dmbs)
                if diff_targets:
                    dtgts = lax.cond(
                        (rank == p - 1) & (bc == v - 1),
                        lambda d: lax.dynamic_update_index_in_dim(
                            d, dt.astype(d.dtype), jnp.clip(bm, 0, M - 1), 0),
                        lambda d: d, dtgts)
                return (pgrad, egrad, dmbs, dtgts, loss_acc + lval), dx

            (pgrad, egrad, dmbs, dtgts, loss_acc), grad_out = lax.cond(
                bm >= 0, do_bwd,
                lambda args: (args, zeros_mb),
                (pgrad, egrad, dmbs, dtgts, loss_acc))

            # 4. rotate: activations ride +1, gradients ride -1
            act_msg = lax.ppermute(act_out, axis, perm_f)
            grad_msg = lax.ppermute(grad_out, axis, perm_b)
            return (act_q, grad_q, stash, act_msg, grad_msg,
                    pgrad, egrad, dmbs, dtgts, loss_acc), None

        init = (
            _pvary(jnp.zeros((v, Qa) + mb_shape, mb_dtype), axis),
            _pvary(jnp.zeros((v, Qg) + mb_shape, mb_dtype), axis),
            _pvary(jnp.zeros((v, S) + mb_shape, mb_dtype), axis),
            zeros_mb, zeros_mb,
            zeros_p, zeros_e,
            _pvary(jnp.zeros((M,) + mb_shape, mb_dtype), axis),
            _pvary(jnp.zeros(targets.shape, targets.dtype), axis),
            _pvary(jnp.zeros((), jnp.float32), axis),
        )
        carry, _ = lax.scan(tick, init, jnp.arange(T))
        (_, _, _, _, _, pgrad, egrad, dmbs, dtgts, loss_acc) = carry
        loss = lax.psum(loss_acc, axis) / M
        egrad = jax.tree.map(lambda g: lax.psum(g, axis) / M, egrad)
        dmbs = lax.psum(dmbs, axis) / M
        if diff_targets:
            dtgts = lax.psum(dtgts, axis) / M
        else:
            dtgts = lax.psum(dtgts, axis)
        pgrad = jax.tree.map(lambda g: g[None] / M, pgrad)  # (1, v, ...)
        return loss, pgrad, egrad, dmbs, dtgts

    param_specs = jax.tree.map(lambda _: P(axis), rank_params)
    fn = shard_map(
        body, mesh=mesh,
        in_specs=(param_specs, P(), P(), P()),
        out_specs=(P(), param_specs, P(), P(), P()),
        axis_names={axis},
        check_vma=True,
    )
    loss, pgrad, egrad, dmbs, dtgts = fn(rank_params, extra_params,
                                         microbatches, targets)
    pgrad = to_vstage_major(pgrad)
    return loss, pgrad, egrad, dmbs, (dtgts if diff_targets else None)


def pipeline_interleaved_1f1b_loss(stacked_params, extra_params,
                                   microbatches, targets, stage_fn, loss_fn,
                                   mesh: Mesh, n_microbatches: int,
                                   n_virtual: int, axis='pp'):
    """Differentiable scalar interleaved-1F1B loss (outer-grad composable),
    same custom_vjp pattern as pipeline_1f1b_loss."""
    def run(stacked, extra, mbs, tgts):
        return pipeline_interleaved_1f1b(
            stacked, extra, mbs, tgts, stage_fn, loss_fn, mesh,
            n_microbatches, n_virtual, axis)

    @jax.custom_vjp
    def f(stacked, extra, mbs, tgts):
        loss, _, _, _, _ = run(stacked, extra, mbs, tgts)
        return loss

    def f_fwd(stacked, extra, mbs, tgts):
        loss, dp, de, dm, dt = run(stacked, extra, mbs, tgts)
        return loss, (dp, de, dm, dt)

    def f_bwd(res, g):
        dp, de, dm, dt = res
        scale = lambda t: jax.tree.map(lambda x: x * g, t)
        return (scale(dp), scale(de), scale(dm),
                scale(dt) if dt is not None else None)

    f.defvjp(f_fwd, f_bwd)
    return f(stacked_params, extra_params, microbatches, targets)


class PipelineLayer:
    """ref: paddle.distributed.fleet.meta_parallel.PipelineLayer —
    user-facing wrapper: partition a LayerList of blocks into pp stages.

    For jit-ability all stages must be structurally identical (the usual
    transformer case). `forward` (inference) runs the GPipe schedule;
    `loss` (training) defaults to the fused 1F1B schedule — it computes
    each stage only on its scheduled ticks and keeps live activations
    O(n_stages), where GPipe's scan evaluates every stage every tick and
    stashes O(n_microbatches) residuals. Pass schedule='gpipe' to get
    the simpler reverse-differentiated scan, or 'interleaved' (+
    n_virtual) for virtual-stage 1F1B.
    """

    def __init__(self, blocks, mesh: Mesh, n_microbatches: int = 4,
                 block_fn=None, axis='pp', schedule='1f1b', n_virtual=1):
        if schedule not in ('gpipe', '1f1b', 'interleaved'):
            raise ValueError(
                f"schedule must be 'gpipe'|'1f1b'|'interleaved', "
                f'got {schedule}')
        if n_virtual > 1 and schedule != 'interleaved':
            raise ValueError("n_virtual > 1 requires schedule='interleaved'")
        if schedule == 'interleaved' and n_virtual < 1:
            raise ValueError('n_virtual must be >= 1')
        self.schedule = schedule
        self.n_virtual = n_virtual
        n_stages = mesh.shape[axis]
        n_parts = n_stages * (n_virtual if schedule == 'interleaved' else 1)
        if len(blocks) % n_parts:
            raise ValueError(
                f'{len(blocks)} blocks not divisible into {n_parts} '
                f'{"virtual " if n_parts != n_stages else ""}stages')
        per = len(blocks) // n_parts
        self.mesh, self.axis, self.n_microbatches = mesh, axis, n_microbatches
        self.block_fn = block_fn or (lambda blk, x: blk(x))
        # group blocks into (virtual) stages, stack on the leading axis —
        # virtual-stage order; chunk vs runs on rank vs % n_stages
        stages = []
        for s in range(n_parts):
            stage_blocks = blocks[s * per:(s + 1) * per]
            stages.append(stage_blocks)
        self.stacked = stack_stage_params(stages)
        self.per_stage = per

    def _stage_fn(self, stage_blocks, x):
        # stage_blocks is the local stage's list of `per_stage` block
        # pytrees (leaves already unstacked by pipeline_apply)
        for i in range(self.per_stage):
            x = self.block_fn(stage_blocks[i], x)
        return x

    def __call__(self, microbatches):
        def stage_fn(params, x):
            return self._stage_fn(params, x)

        if self.schedule == 'interleaved':
            # forward/inference: scan the virtual-stage chunk stack in
            # order (pipelining only pays during fused train steps)
            def chunk_step(x, chunk_params):
                return stage_fn(chunk_params, x), None

            def run_one(mb):
                y, _ = lax.scan(chunk_step, mb, self.stacked)
                return y

            return jax.vmap(run_one)(microbatches)
        return pipeline_apply(self.stacked, microbatches, stage_fn, self.mesh,
                              self.n_microbatches, self.axis)

    def loss(self, microbatches, targets, loss_fn, extra_params=None):
        """Differentiable pipelined loss under the configured schedule.

        loss_fn(extra_params, y, target) -> scalar, applied per
        microbatch on the last stage. Under '1f1b' the fused
        forward/backward schedule runs (live activations O(n_stages));
        under 'gpipe' the loss is computed on the forward outputs and
        the backward falls out of jax.grad through the scan.
        """
        extra = extra_params if extra_params is not None else {}

        def stage_fn(params, x):
            return self._stage_fn(params, x)

        if self.schedule == 'interleaved':
            return pipeline_interleaved_1f1b_loss(
                self.stacked, extra, microbatches, targets, stage_fn,
                loss_fn, self.mesh, self.n_microbatches, self.n_virtual,
                self.axis)
        if self.schedule == '1f1b':
            return pipeline_1f1b_loss(
                self.stacked, extra, microbatches, targets, stage_fn,
                loss_fn, self.mesh, self.n_microbatches, self.axis)
        outs = pipeline_apply(self.stacked, microbatches, stage_fn,
                              self.mesh, self.n_microbatches, self.axis)
        losses = [loss_fn(extra, outs[m], targets[m])
                  for m in range(self.n_microbatches)]
        return jnp.mean(jnp.stack(losses))
