"""Pipeline parallelism (ref: python/paddle/distributed/fleet/
meta_parallel/pipeline_parallel.py, pp_utils).

Paddle: each pp rank owns a stage module; a Python scheduler
(forward_backward_pipeline) drives 1F1B micro-batch phases with NCCL
p2p send/recv between ranks.

TPU-native: the stage loop is *data*: all stages' parameters are stacked
on a leading 'pp'-sharded axis, and one `shard_map` program runs the
GPipe schedule as a `lax.fori_loop` with `ppermute` rotations riding the
ICI ring. XLA overlaps the collective permute with the stage compute —
the same overlap Paddle gets from separate CUDA streams.

The model side: `PipelineStage` wraps a list of per-stage step
functions with identical signatures; `pipeline_apply` runs the
schedule. For models built as a stack of identical blocks (the LLM
case) use `stacked_pipeline` — stage weights are a stacked pytree and
the per-stage fn is one block-stack forward.
"""
from __future__ import annotations

import functools
import typing

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P


def stack_stage_params(stage_models: typing.Sequence, axis=0):
    """Stack N same-structure stage pytrees into one pytree with a leading
    stage axis (shard it over 'pp')."""
    return jax.tree.map(lambda *xs: jnp.stack(xs, axis=axis), *stage_models)


def pipeline_spmd(stage_fn, n_stages: int, n_microbatches: int, axis='pp'):
    """Build the SPMD GPipe body to run under `shard_map`.

    stage_fn(stage_params, x) -> y, applied by every pp rank to its
    resident stage. Inside shard_map each rank holds: its stage's params
    (leading axis stripped to size 1) and the full microbatch queue.

    Schedule (GPipe, forward): T = n_micro + n_stages - 1 ticks; at tick
    t, rank s computes microbatch (t - s) if 0 <= t-s < n_micro. After
    each tick activations rotate +1 along the ring; outputs collect on
    the last rank then broadcast.
    """
    if n_microbatches < 1:
        raise ValueError(f'n_microbatches must be >= 1, got {n_microbatches}')

    def body(stage_params, microbatches):
        # microbatches: (n_micro, mb, ...) identical on every rank
        rank = lax.axis_index(axis)
        n_ticks = n_microbatches + n_stages - 1
        mb_shape = microbatches.shape[1:]
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def tick(carry, t):
            buf, outputs = carry
            # which microbatch this rank works on at tick t
            mb_idx = t - rank
            active = (mb_idx >= 0) & (mb_idx < n_microbatches)
            # stage 0 pulls fresh input from the queue; others use the
            # rotated buffer
            fresh = lax.dynamic_index_in_dim(
                microbatches, jnp.clip(mb_idx, 0, n_microbatches - 1), 0,
                keepdims=False)
            x = jnp.where(rank == 0, fresh, buf)
            y = stage_fn(stage_params, x)
            y = jnp.where(active, y, buf)
            # last stage: record finished microbatch
            done_idx = t - (n_stages - 1)
            is_done = (rank == n_stages - 1) & (done_idx >= 0) & (done_idx < n_microbatches)
            outputs = lax.cond(
                is_done,
                lambda o: lax.dynamic_update_index_in_dim(
                    o, y, jnp.clip(done_idx, 0, n_microbatches - 1), 0),
                lambda o: o,
                outputs,
            )
            buf = lax.ppermute(y, axis, perm)
            return (buf, outputs), None

        buf0 = jnp.zeros(mb_shape, microbatches.dtype)
        outs0 = jnp.zeros((n_microbatches,) + mb_shape, microbatches.dtype)
        # scan (not fori_loop): reverse-differentiable, so the 1F1B/GPipe
        # backward falls out of jax.grad through the schedule
        (_, outputs), _ = lax.scan(tick, (buf0, outs0), jnp.arange(n_ticks))
        # outputs live on the last rank; psum broadcasts (others hold zeros)
        return lax.psum(outputs, axis)

    return body


def pipeline_apply(stacked_params, microbatches, stage_fn, mesh: Mesh,
                   n_microbatches: int, axis='pp'):
    """Run the GPipe forward over a 'pp'-sharded stack of stage params.

    stacked_params: pytree with leading stage axis == mesh.shape[axis].
    microbatches: (n_micro, mb, ...) array (replicated).
    """
    n_stages = mesh.shape[axis]
    body = pipeline_spmd(stage_fn, n_stages, n_microbatches, axis)

    param_specs = jax.tree.map(lambda _: P(axis), stacked_params)
    other_axes = [a for a in mesh.axis_names if a != axis]

    def local_body(params, mbs):
        # strip the local stage axis (size 1 per rank)
        local = jax.tree.map(lambda p: p[0], params)
        return body(local, mbs)

    fn = jax.shard_map(
        local_body, mesh=mesh,
        in_specs=(param_specs, P()), out_specs=P(),
        check_vma=False,
    )
    return fn(stacked_params, microbatches)


class PipelineLayer:
    """ref: paddle.distributed.fleet.meta_parallel.PipelineLayer —
    user-facing wrapper: partition a LayerList of blocks into pp stages.

    For jit-ability all stages must be structurally identical (the usual
    transformer case). `forward` runs GPipe over the mesh 'pp' axis.
    """

    def __init__(self, blocks, mesh: Mesh, n_microbatches: int = 4,
                 block_fn=None, axis='pp'):
        n_stages = mesh.shape[axis]
        if len(blocks) % n_stages:
            raise ValueError(
                f'{len(blocks)} blocks not divisible into {n_stages} stages')
        per = len(blocks) // n_stages
        self.mesh, self.axis, self.n_microbatches = mesh, axis, n_microbatches
        self.block_fn = block_fn or (lambda blk, x: blk(x))
        # group blocks into stages, stack stages on leading axis
        stages = []
        for s in range(n_stages):
            stage_blocks = blocks[s * per:(s + 1) * per]
            stages.append(stage_blocks)
        self.stacked = stack_stage_params(stages)
        self.per_stage = per

    def _stage_fn(self, stage_blocks, x):
        # stage_blocks is the local stage's list of `per_stage` block
        # pytrees (leaves already unstacked by pipeline_apply)
        for i in range(self.per_stage):
            x = self.block_fn(stage_blocks[i], x)
        return x

    def __call__(self, microbatches):
        def stage_fn(params, x):
            return self._stage_fn(params, x)

        return pipeline_apply(self.stacked, microbatches, stage_fn, self.mesh,
                              self.n_microbatches, self.axis)
