"""distributed.io (ref: python/paddle/distributed/io.py) — persistables
save/load for distributed programs; here thin forwards to the
framework's checkpoint machinery (orbax handles the sharded case)."""
from __future__ import annotations

from ..framework.io import load, save  # noqa: F401


def save_persistables(executor=None, dirname=None, main_program=None,
                      filename=None):
    """ref: distributed.io.save_persistables — static-graph form; the
    dynamic equivalent is framework.save(state_dict, path)."""
    if main_program is not None and hasattr(main_program, 'state_dict'):
        save(main_program.state_dict(), f'{dirname}/{filename or "model"}')
        return
    raise ValueError('pass an object with state_dict(); the TPU-native '
                     'path is framework.save / distributed.checkpoint')


def load_persistables(executor=None, dirname=None, main_program=None,
                      filename=None):
    return load(f'{dirname}/{filename or "model"}')
