"""Version bridge for the shard_map surface the distributed layer uses.

The distributed modules (ring_attention, ulysses, pipeline, llama's
sharded decode dispatch) are written against the current shard_map API:
`jax.shard_map(..., axis_names=..., check_vma=...)` plus
`lax.axis_size` and `lax.pvary`/`lax.pcast`.  Older jaxlibs (the pinned
0.4.x line) ship shard_map as `jax.experimental.shard_map.shard_map`
with the predecessor knobs — `check_rep` instead of `check_vma`,
`auto` (the complement set) instead of `axis_names` — and no
axis_size/pvary at all.  Every caller goes through this module so the
difference lives in exactly one place:

  - `shard_map(f, mesh, in_specs, out_specs, axis_names=None,
    check_vma=None)`: new-API passthrough when `jax.shard_map` exists;
    otherwise the experimental entry point with
    `auto = mesh.axis_names - axis_names` and `check_rep=False` (the
    old replication checker predates the varying-manual-axes system
    these bodies are written for — pvary-less code trips it even when
    the collectives are right, so the bridge disables it and shardlint's
    SL006 statically checks the collective/axis pairing instead).
  - `axis_size(axis)`: `lax.axis_size` when present, else the classic
    `psum(1, axis)` — which jax constant-folds to a static int under
    shard_map, so loop bounds stay Python ints.
  - `pvary(x, axis)`: pcast/pvary when present; identity on the old
    rep system (with check_rep=False nothing consumes the annotation).
"""
from __future__ import annotations

import jax
from jax import lax


def axis_size(axis) -> int:
    if hasattr(lax, 'axis_size'):
        return lax.axis_size(axis)
    return lax.psum(1, axis)


def pvary(x, axis):
    """Promote a replicated value to varying over `axis` (identity on
    jax versions without the vma type system)."""
    if hasattr(lax, 'pcast'):
        return lax.pcast(x, axis, to='varying')
    if hasattr(lax, 'pvary'):
        return lax.pvary(x, axis)
    return x


def shard_map(f, mesh=None, in_specs=None, out_specs=None,
              axis_names=None, check_vma=None):
    """`jax.shard_map` with the current keyword surface on any jax.

    `axis_names` is the set of MANUAL axes (None = all mesh axes);
    `check_vma` maps to the old `check_rep` only in the False direction
    (see module docstring).
    """
    if hasattr(jax, 'shard_map'):
        kwargs = {}
        if axis_names is not None:
            kwargs['axis_names'] = set(axis_names)
        if check_vma is not None:
            kwargs['check_vma'] = check_vma
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, **kwargs)
    from jax.experimental.shard_map import shard_map as _shard_map

    auto = frozenset()
    if axis_names is not None:
        # size-1 axes are semantically identical manual or auto (no
        # collective can span them, specs split nothing) — keeping them
        # manual avoids the old partial-auto path entirely on the
        # common "only the scheduled axis is > 1" meshes, which this
        # jaxlib's SPMD partitioner cannot lower (PartitionId refusal)
        auto = frozenset(a for a in mesh.axis_names
                         if a not in frozenset(axis_names)
                         and mesh.shape[a] > 1)
    fn = _shard_map(f, mesh=mesh, in_specs=in_specs,
                    out_specs=out_specs, check_rep=False, auto=auto)
    if auto:
        # the old implementation refuses partial-auto OUTSIDE a jit
        # (`if auto: raise NotImplementedError` in its eager impl);
        # under jit it stages fine — so eager callers get a jitted view.
        # tracelint: disable=TL001 - the wrapper is built once per
        # shard_map construction and cached by the CALLER exactly like
        # the shard_map closure it wraps; inside an outer jit it stages
        # as a no-op nested pjit
        fn = jax.jit(fn)
    return fn
