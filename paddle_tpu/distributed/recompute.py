"""Activation recomputation (ref: python/paddle/distributed/fleet/utils/
recompute.py, and fleet/meta_parallel's segment recompute).

The reference re-runs each wrapped segment's forward inside backward to
trade FLOPs for activation memory. On TPU that is exactly
`jax.checkpoint` (remat): XLA re-emits the segment's ops in the backward
computation, and the `dots` policy keeps MXU outputs resident (cheap to
store, expensive to recompute) while re-deriving the elementwise tail
(free to recompute, expensive in HBM).
"""
from __future__ import annotations

import functools

import jax

_POLICIES = {
    'full': None,  # save nothing: recompute everything
    'dots': 'dots_with_no_batch_dims_saveable',
    'dots_saveable': 'dots_saveable',
    'nothing_saveable': 'nothing_saveable',
    'everything_saveable': 'everything_saveable',
}


def _resolve_policy(policy):
    if policy is None or policy == 'full':
        return None
    name = _POLICIES.get(policy, policy)
    if callable(name):
        return name
    try:
        return getattr(jax.checkpoint_policies, name)
    except AttributeError:
        raise ValueError(
            f'unknown recompute policy {policy!r}; pick from '
            f'{sorted(_POLICIES)} or pass a jax.checkpoint_policies '
            f'callable') from None


def recompute(function, *args, policy='full', prevent_cse=True, **kwargs):
    """Run `function(*args, **kwargs)` with its activations rematerialized
    in backward (ref: fleet/utils/recompute.py::recompute).

    `policy='full'` recomputes everything (the reference's behaviour);
    `'dots'` keeps matmul outputs and recomputes only elementwise ops —
    usually the right TPU trade (HBM is the bottleneck, MXU re-runs are
    not free)."""
    fn = jax.checkpoint(function, policy=_resolve_policy(policy),
                        prevent_cse=prevent_cse)
    return fn(*args, **kwargs)


def recompute_wrapper(function=None, *, policy='full', prevent_cse=True):
    """Decorator form: `@recompute_wrapper(policy='dots')`."""
    def wrap(fn):
        return functools.wraps(fn)(
            jax.checkpoint(fn, policy=_resolve_policy(policy),
                           prevent_cse=prevent_cse))
    return wrap(function) if function is not None else wrap


def recompute_sequential(ctx, functions, *args, policy='full'):
    """Segmented remat over a Sequential / list of callables
    (ref: distributed/fleet/recompute/recompute.py::recompute_sequential).
    `ctx['segments']` (default 1) chunks the chain; each chunk is one
    remat segment, so peak live activations drop from the whole chain to
    one chunk. `preserve_rng_state` is implicit: PRNG keys are explicit
    pytree state here, so recomputation always replays the same keys."""
    fns = list(functions)
    segments = int(ctx.get('segments', 1)) if isinstance(ctx, dict) else 1
    segments = max(1, min(segments, len(fns) or 1))
    bounds = [len(fns) * i // segments for i in range(segments + 1)]

    def chunk_fn(chunk):
        def run(*xs):
            out = xs if len(xs) > 1 else xs[0]
            for fn in chunk:
                out = fn(*out) if isinstance(out, tuple) else fn(out)
            return out
        return run

    out = args if len(args) > 1 else args[0]
    for i in range(segments):
        chunk = fns[bounds[i]:bounds[i + 1]]
        if not chunk:
            continue
        ck = jax.checkpoint(chunk_fn(chunk), policy=_resolve_policy(policy))
        out = ck(*out) if isinstance(out, tuple) else ck(out)
    return out
