"""Process-group / point-to-point compatibility surface
(ref: python/paddle/distributed/{parallel,communication/*}.py).

Under SPMD there is ONE program on all devices: "groups" are mesh axis
names, point-to-point is `ppermute` (the ICI-native primitive), and
object collectives are trivial because every shard of the program
already holds the host object. Parameter-server datasets
(InMemoryDataset/QueueDataset, *Entry) are out of scope per SURVEY §6
(ps mode is CUDA/CPU-cluster machinery XLA replaces wholesale).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from . import collective
from .mesh import get_mesh, get_rank, get_world_size


class Group:
    """ref: paddle.distributed.collective.Group — here a named view of a
    mesh axis (or an explicit rank list for bookkeeping)."""

    _next_id = [0]

    def __init__(self, ranks=None, axis=None):
        self.ranks = list(ranks) if ranks is not None else []
        self.axis = axis
        self.id = Group._next_id[0]
        Group._next_id[0] += 1

    @property
    def nranks(self):
        if self.axis is not None:
            m = get_mesh()
            if m is not None and self.axis in m.axis_names:
                return m.shape[self.axis]
        return len(self.ranks) or get_world_size()

    def __repr__(self):
        return f'Group(id={self.id}, axis={self.axis}, ranks={self.ranks})'


_groups: dict[int, Group] = {}


def new_group(ranks=None, backend=None, timeout=None, axis=None):
    """ref: paddle.distributed.new_group. Prefer `axis='tp'` (a mesh
    axis); a bare rank list is retained for bookkeeping only — SPMD
    collectives are routed by axis name, not rank sets."""
    g = Group(ranks=ranks, axis=axis)
    _groups[g.id] = g
    return g


def get_group(id=0):
    """ref: paddle.distributed.get_group."""
    return _groups.get(id)


def is_initialized():
    """ref: paddle.distributed.is_initialized."""
    return get_mesh() is not None


def destroy_process_group(group=None):
    """ref: paddle.distributed.destroy_process_group."""
    if group is None:
        _groups.clear()
        from .mesh import set_mesh

        set_mesh(None)
    else:
        _groups.pop(getattr(group, 'id', group), None)


def is_available():
    """ref: paddle.distributed.is_available — XLA collectives are always
    compiled in."""
    return True


def get_backend(group=None):
    """ref: paddle.distributed.get_backend — 'XLA' (the reference
    reports NCCL/GLOO)."""
    return 'XLA'


class ParallelMode:
    """ref: paddle.distributed.ParallelMode."""

    DATA_PARALLEL = 0
    TENSOR_PARALLEL = 1
    PIPELINE_PARALLEL = 2
    SHARDING_PARALLEL = 3


class ParallelEnv:
    """ref: paddle.distributed.ParallelEnv — rank/world topology view."""

    @property
    def rank(self):
        return get_rank()

    @property
    def world_size(self):
        return get_world_size()

    @property
    def device_id(self):
        return jax.devices()[0].id

    @property
    def device_type(self):
        return jax.default_backend()

    @property
    def current_endpoint(self):
        import os

        return os.environ.get('PADDLE_CURRENT_ENDPOINT', '127.0.0.1:0')

    @property
    def trainer_endpoints(self):
        import os

        eps = os.environ.get('PADDLE_TRAINER_ENDPOINTS', '')
        return eps.split(',') if eps else [self.current_endpoint]

    @property
    def nranks(self):
        return get_world_size()

    @property
    def local_rank(self):
        return get_rank()


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    """ref: paddle.distributed.spawn — the reference forks one CUDA
    process per GPU. SPMD inverts this: ONE process drives every local
    TPU chip, and multi-host launch is `jax.distributed.initialize` (see
    distributed.launch). So spawn degenerates to calling `func` once."""
    return func(*args)


def gather(tensor, gather_list=None, dst=0, group=None, sync_op=True):
    """ref: paddle.distributed.gather — SPMD form: every shard computes
    the gather (XLA all-gather); `dst` is advisory."""
    out = collective.all_gather(tensor, group=_axis(group))
    if gather_list is not None:
        n = collective.axis_size(_axis(group))
        gather_list.extend(jnp.split(out, n, axis=0))
    return out


def _axis(group, default='dp'):
    if group is None:
        return default
    if isinstance(group, str):
        return group
    return getattr(group, 'axis', None) or default


def alltoall(in_tensor_list, out_tensor_list=None, group=None, sync_op=True):
    """ref: paddle.distributed.alltoall (list form) — stack, all_to_all
    over the axis, split back."""
    x = jnp.stack(list(in_tensor_list), axis=0)
    out = collective.all_to_all(x, group=_axis(group), split_axis=0,
                                concat_axis=0)
    outs = list(out)
    if out_tensor_list is not None:
        out_tensor_list.extend(outs)
    return outs


def alltoall_single(in_tensor, out_tensor=None, in_split_sizes=None,
                    out_split_sizes=None, group=None, sync_op=True):
    """ref: paddle.distributed.alltoall_single (equal splits)."""
    if in_split_sizes is not None or out_split_sizes is not None:
        raise NotImplementedError(
            'uneven alltoall splits are not expressible as one static SPMD '
            'op; pad to equal splits (the MoE layers here do exactly that)')
    return collective.all_to_all(in_tensor, group=_axis(group),
                                 split_axis=0, concat_axis=0)


def send(tensor, dst=0, group=None, sync_op=True):
    """ref: paddle.distributed.send. SPMD has no one-sided send; the
    matching send/recv PAIR is a ppermute by a uniform shift, so this
    returns the value that the (src -> dst) ring shift delivers. Use
    `collective.send_recv` / `ppermute` for pipeline exchanges."""
    shift = dst - get_rank()
    return collective.send_recv(tensor, group=_axis(group, 'pp'),
                                shift=shift if shift else 1)


def recv(tensor, src=0, group=None, sync_op=True):
    """ref: paddle.distributed.recv — see `send`."""
    shift = get_rank() - src
    return collective.send_recv(tensor, group=_axis(group, 'pp'),
                                shift=shift if shift else 1)


def isend(tensor, dst=0, group=None):
    """Async flavor: XLA overlaps collectives automatically; returns a
    completed-task handle for API parity."""
    return _DoneTask(send(tensor, dst, group))


def irecv(tensor, src=0, group=None):
    return _DoneTask(recv(tensor, src, group))


class _DoneTask:
    def __init__(self, value):
        self.value = value

    def wait(self):
        return self.value

    def is_completed(self):
        return True


def wait(tensor, group=None, use_calc_stream=True):
    """ref: paddle.distributed.wait — block until the async value is
    materialized."""
    return jax.block_until_ready(tensor)


def all_gather_object(object_list, obj, group=None):
    """ref: paddle.distributed.all_gather_object. One SPMD program =
    every "rank" already holds `obj`; the gathered list is world_size
    copies (exactly what the reference produces)."""
    n = get_world_size()
    object_list.extend([obj] * n)
    return object_list


def broadcast_object_list(object_list, src=0, group=None):
    """ref: paddle.distributed.broadcast_object_list — identity under
    one-program SPMD."""
    return object_list


def scatter_object_list(out_object_list, in_object_list=None, src=0,
                        group=None):
    """ref: paddle.distributed.scatter_object_list — rank r takes the
    r-th object."""
    if in_object_list:
        out_object_list.append(in_object_list[get_rank()
                                              % len(in_object_list)])
    return out_object_list


def gloo_init_parallel_env(rank_id, rank_num, server_endpoint):
    """ref: gloo CPU-barrier bootstrap — no-op (single-controller jax)."""


def gloo_barrier():
    collective.barrier()


def gloo_release():
    pass


def split(x, size, operation, axis=0, num_partitions=1, gather_out=True,
          weight_attr=None, bias_attr=None, name=None):
    """ref: paddle.distributed.split — megatron-style sharded
    linear/embedding. The TPU-native forms are the mp_layers
    (ColumnParallelLinear/RowParallelLinear/VocabParallelEmbedding);
    this functional form builds the matching layer on the fly."""
    from .mp_layers import (ColumnParallelLinear, RowParallelLinear,
                            VocabParallelEmbedding)

    if operation == 'linear':
        cls = ColumnParallelLinear if axis == 1 else RowParallelLinear
        layer = cls(size[0], size[1], weight_attr=weight_attr,
                    has_bias=bias_attr is not False)
        return layer(x)
    if operation == 'embedding':
        layer = VocabParallelEmbedding(size[0], size[1])
        return layer(x)
    raise ValueError(f'unsupported split operation: {operation}')


def _ps_mode_stub(name):
    class _Stub:
        """Parameter-server-mode API retained for import compatibility.

        The reference's ps mode (CPU clusters + sparse embedding tables)
        is out of scope for the TPU rebuild (SURVEY §6): TPU training
        feeds through `paddle_tpu.io.DataLoader` + `distributed.
        shard_dataloader`, and giant embeddings shard over the mesh via
        VocabParallelEmbedding instead of a parameter server.
        """

        def __init__(self, *a, **k):
            raise NotImplementedError(
                f'{name} belongs to the reference\'s parameter-server mode '
                f'(excluded on TPU — SURVEY §6). Use io.DataLoader / '
                f'distributed.shard_dataloader for input pipelines and '
                f'VocabParallelEmbedding for sharded embeddings.')

    _Stub.__name__ = name
    return _Stub


QueueDataset = _ps_mode_stub('QueueDataset')
InMemoryDataset = _ps_mode_stub('InMemoryDataset')
CountFilterEntry = _ps_mode_stub('CountFilterEntry')
ShowClickEntry = _ps_mode_stub('ShowClickEntry')
ProbabilityEntry = _ps_mode_stub('ProbabilityEntry')
