"""Tensor-parallel layers (ref: python/paddle/distributed/fleet/layers/
mpu/mp_layers.py, mp_ops.py).

Paddle's mp layers split weights manually per rank and call NCCL
(`c_identity` / `c_allreduce_sum` / `c_concat`) in forward/backward.
TPU-native: the layer holds the FULL logical weight annotated with a
`PartitionSpec`; GSPMD partitions it over the 'tp' mesh axis and inserts
the matching ICI collectives (the allreduce after a row-parallel matmul,
the allgather for `gather_output=True`) automatically — forward code is
the plain matmul.

`sharding_constraint` is applied to activations so the compiler keeps
the intended layout at layer boundaries instead of re-deciding.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer.base import Layer, Parameter
from .mesh import get_mesh


def sharding_constraint(x, *spec_entries, mesh=None):
    """`lax.with_sharding_constraint` that degrades to identity when no
    mesh (single-device tests)."""
    mesh = mesh or get_mesh()
    if mesh is None:
        return x
    from .parallel import _valid_spec

    spec = _valid_spec(P(*spec_entries), x.shape, mesh)
    try:
        return jax.lax.with_sharding_constraint(
            x, jax.sharding.NamedSharding(mesh, spec))
    except (ValueError, RuntimeError):
        return x    # outside jit with incompatible placement


class ColumnParallelLinear(Layer):
    """Output-dim-sharded Linear (ref: mp_layers.py::ColumnParallelLinear)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.gather_output = gather_output
        init = weight_attr if isinstance(weight_attr, I.Initializer) else I.XavierNormal()
        self.weight = Parameter(init((in_features, out_features), 'float32'), spec=P(None, 'tp'))
        self.bias = Parameter(jnp.zeros((out_features,)), spec=P('tp')) if has_bias else None

    def forward(self, x):
        y = F.linear(x, self.weight, self.bias)
        if self.gather_output:
            y = sharding_constraint(y, *([None] * (y.ndim - 1)), None)
        else:
            y = sharding_constraint(y, *([None] * (y.ndim - 1)), 'tp')
        return y


class RowParallelLinear(Layer):
    """Input-dim-sharded Linear; GSPMD adds the psum over 'tp'
    (ref: mp_layers.py::RowParallelLinear — manual mp_allreduce there)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.input_is_parallel = input_is_parallel
        init = weight_attr if isinstance(weight_attr, I.Initializer) else I.XavierNormal()
        self.weight = Parameter(init((in_features, out_features), 'float32'), spec=P('tp', None))
        self.bias = Parameter(jnp.zeros((out_features,))) if has_bias else None

    def forward(self, x):
        if self.input_is_parallel:
            x = sharding_constraint(x, *([None] * (x.ndim - 1)), 'tp')
        y = F.linear(x, self.weight, self.bias)
        return sharding_constraint(y, *([None] * (y.ndim - 1)), None)


class VocabParallelEmbedding(Layer):
    """Vocab-sharded embedding (ref: mp_layers.py::VocabParallelEmbedding).

    Paddle masks out-of-shard ids and allreduces partial lookups.
    GSPMD handles the gather over a sharded table directly."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        init = weight_attr if isinstance(weight_attr, I.Initializer) else I.Normal(0., 0.02)
        self.weight = Parameter(init((num_embeddings, embedding_dim), 'float32'), spec=P('tp', None))

    def forward(self, x):
        return self.weight[x]


def parallel_cross_entropy(logits, labels, axis='tp'):
    """Vocab-parallel softmax cross entropy (ref: mp_ops.py::
    _c_softmax_with_cross_entropy). Under GSPMD the log_softmax over a
    'tp'-sharded vocab axis lowers to (local max/sum + psum) — the same
    two-pass trick Paddle hand-codes — so we just write the math in fp32
    and keep the logits sharded via constraint."""
    logits = sharding_constraint(
        logits, *([None] * (logits.ndim - 1)), axis).astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    return nll


class ParallelCrossEntropy(Layer):
    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, logits, labels):
        # clamp ignored labels to a valid index before the gather, then
        # zero their contribution (negative ignore_index like the default
        # -100 would otherwise wrap in take_along_axis)
        mask = labels != self.ignore_index
        safe_labels = jnp.where(mask, labels, 0)
        nll = parallel_cross_entropy(logits, safe_labels)
        return jnp.where(mask, nll, 0.0)
