"""Ulysses-style all-to-all sequence parallelism.

ref (capability): the reference's sequence-parallel utilities
(distributed/fleet/layers/mpu/mp_layers.py + PaddleNLP's sep-parallel /
DeepSpeed-Ulysses recipe): long sequences are sharded over a mesh axis;
for attention, an all-to-all swaps the shard dimension from sequence to
heads, every rank runs FULL-sequence attention for its head slice, and
a second all-to-all swaps back.

TPU-native: `lax.all_to_all` over the 'sp' axis lowers to the ICI
all-to-all collective; the local full-sequence attention goes through
`F.scaled_dot_product_attention`, i.e. the pallas flash kernel on TPU.
Complements ring attention (ring_attention.py): Ulysses moves 2×
activations twice but keeps ONE dense attention per rank (best when
heads >= mesh axis and the sequence fits after gathering); the ring
keeps sequence sharded throughout (best at extreme lengths).
"""
from __future__ import annotations

import functools

import jax
from jax import lax
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

from ._spmd import axis_size, shard_map


def ulysses_attention(q, k, v, axis='sp', causal=False, scale=None):
    """Run inside shard_map: local q/k/v are (B, S/n, H, D), sequence
    sharded over `axis`; H (and kv heads) must be divisible by n.
    Returns (B, S/n, H, D) sequence-sharded output.
    """
    n = axis_size(axis)
    if q.shape[2] % n or k.shape[2] % n:
        raise ValueError(
            f'ulysses needs heads divisible by the axis size: '
            f'q heads {q.shape[2]}, kv heads {k.shape[2]}, axis {n}')

    def seq_to_heads(x):
        # (B, S/n, H, D) -> (B, S, H/n, D)
        return lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                              tiled=True)

    def heads_to_seq(x):
        return lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                              tiled=True)

    from ..nn import functional as F

    qg, kg, vg = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    out = F.scaled_dot_product_attention(qg, kg, vg, is_causal=causal,
                                         scale=scale)
    return heads_to_seq(out)


def ulysses_attention_sharded(q, k, v, mesh: Mesh, axis='sp', causal=False,
                              scale=None):
    """Convenience wrapper: q/k/v are global arrays; shards seq over
    `axis`, runs the all-to-all attention, returns the global output."""
    spec = P(None, axis, None, None)
    fn = shard_map(
        functools.partial(ulysses_attention, axis=axis, causal=causal,
                          scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
