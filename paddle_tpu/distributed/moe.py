"""Mixture-of-Experts with expert parallelism.

ref: python/paddle/incubate/distributed/models/moe (MoELayer, gate/
top-k dispatch, NCCL all-to-all) — Paddle routes token tensors between
expert ranks with `global_scatter`/`global_gather`.

TPU-native: gating + capacity-bucketed dispatch is dense einsum algebra
(one-hot combine/dispatch masks — the classic GShard formulation, which
IS what XLA wants: static shapes, MXU-friendly), and the rank-to-rank
exchange is `lax.all_to_all` over the 'ep' mesh axis when run under
shard_map — or plain GSPMD sharding of the expert axis under pjit
(experts sharded over 'ep'; XLA inserts the all-to-all pair itself).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer.base import Layer, Parameter


def _topk_gates(logits, k: int):
    """Shared gating math for the dense and ragged dispatch paths:
    softmax probs, top-k choice, per-token gate normalisation, and the
    Switch/GShard load-balance aux loss E·sum(frac_tokens·frac_probs)."""
    E = logits.shape[-1]
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    gate_vals, expert_idx = jax.lax.top_k(probs, k)           # (T, k)
    gate_vals = gate_vals / jnp.maximum(gate_vals.sum(-1, keepdims=True),
                                        1e-9)
    me = probs.mean(axis=0)                                   # (E,)
    ce = jax.nn.one_hot(expert_idx[:, 0], E).mean(axis=0)
    aux_loss = E * jnp.sum(me * ce)
    return probs, gate_vals, expert_idx, aux_loss


def limit_by_capacity(topk_idx, num_expert, capacity):
    """ref: incubate/.../moe/utils.py::limit_by_capacity — keep at most
    ``capacity`` (token-order) routings per expert; dropped entries
    become -1."""
    flat = topk_idx.reshape(-1).astype(jnp.int32)
    oh = jax.nn.one_hot(flat, num_expert, dtype=jnp.int32)
    pos = jnp.cumsum(oh, axis=0) - oh
    slot = (pos * oh).sum(-1)
    keep = slot < capacity
    return jnp.where(keep, flat, -1).reshape(topk_idx.shape)


def top_k_gating(logits, k: int, capacity: int, jitter_key=None):
    """GShard-style top-k gating with capacity.

    logits: (tokens, E). Returns (dispatch (T, E, C) bool-ish float,
    combine (T, E, C) float, aux_loss scalar).
    """
    T, E = logits.shape
    probs, gate_vals, expert_idx, aux_loss = _topk_gates(logits, k)

    # position of each (token, choice) within its expert's capacity buffer
    dispatch = jnp.zeros((T, E, capacity), jnp.float32)
    combine = jnp.zeros((T, E, capacity), jnp.float32)
    fill = jnp.zeros((E,), jnp.int32)
    for choice in range(k):
        e = expert_idx[:, choice]                             # (T,)
        onehot_e = jax.nn.one_hot(e, E, dtype=jnp.int32)      # (T, E)
        # slot index = tokens already routed to e before me (this choice pass)
        pos_in_e = jnp.cumsum(onehot_e, axis=0) - onehot_e    # (T, E)
        slot = (pos_in_e * onehot_e).sum(-1) + fill[e]        # (T,)
        keep = slot < capacity
        slot_oh = jax.nn.one_hot(slot, capacity) * keep[:, None]
        upd = onehot_e[:, :, None] * slot_oh[:, None, :]      # (T, E, C)
        dispatch = dispatch + upd
        combine = combine + upd * (gate_vals[:, choice] * keep)[:, None, None]
        fill = fill + onehot_e.sum(0)
    return dispatch, combine, aux_loss


def ragged_expert_apply(tokens, expert_idx, gate_vals, w_gate, w_up, w_down,
                        num_experts, act=F.silu):
    """Dropless expert compute: sort tokens by expert, run grouped GEMMs.

    ref: the reference's large-E MoE path (incubate/.../moe global_scatter
    to per-expert buffers). TPU-native: a stable sort by expert id turns
    the (token, choice) pairs into contiguous per-expert groups, and
    `jax.lax.ragged_dot` runs every expert's GEMM in one MXU call —
    O(T·k·H) memory instead of the GShard einsum's O(T·E·C), the right
    shape for E >= ~16 (DeepSeek-style).

    tokens (T, H); expert_idx/gate_vals (T, k). Returns (T, H).
    """
    T, H = tokens.shape
    k = expert_idx.shape[1]
    flat_e = expert_idx.reshape(-1).astype(jnp.int32)         # (T·k,)
    flat_g = gate_vals.reshape(-1)
    order = jnp.argsort(flat_e, stable=True)
    tok_ids = order // k                                      # source token
    x = jnp.take(tokens, tok_ids, axis=0)                     # (T·k, H)
    group_sizes = jnp.bincount(flat_e, length=num_experts).astype(jnp.int32)
    w_gate = _dense_expert(w_gate, x.dtype)
    w_up = _dense_expert(w_up, x.dtype)
    w_down = _dense_expert(w_down, x.dtype)
    h = act(jax.lax.ragged_dot(x, w_gate, group_sizes))
    h = h * jax.lax.ragged_dot(x, w_up, group_sizes)
    y = jax.lax.ragged_dot(h, w_down, group_sizes)            # (T·k, H)
    y = y * jnp.take(flat_g, order)[:, None].astype(y.dtype)
    return jnp.zeros((T, H), y.dtype).at[tok_ids].add(y)


# ---------------------------------------------------------------------------
# Gate variants (ref: incubate/distributed/models/moe/gate/{base,naive,
# switch,gshard}_gate.py — fastmoe lineage)
# ---------------------------------------------------------------------------

class BaseGate(Layer):
    """ref: gate/base_gate.py — scoring module contract: forward(inp) ->
    (topk_val, topk_idx); the load-balance loss is stashed on the gate."""

    # routing scores must stay full precision: int8 noise flips top-k
    # expert selection (quantization.quantize_matmul_weights honours this)
    no_quantize = True

    def __init__(self, num_expert, world_size=1):
        super().__init__()
        self.num_expert = num_expert
        self.world_size = world_size
        self.tot_expert = num_expert * world_size
        self.loss = jnp.zeros(())

    def set_loss(self, loss):
        object.__setattr__(self, 'loss', loss)

    def get_loss(self, clear=True):
        return self.loss


class NaiveGate(BaseGate):
    """ref: gate/naive_gate.py — plain linear scores, top-k, no balance
    loss."""

    def __init__(self, d_model, num_expert, world_size=1, topk=2):
        super().__init__(num_expert, world_size)
        from ..nn import Linear
        self.gate = Linear(d_model, self.tot_expert)
        self.top_k = topk

    def forward(self, inp, return_all_scores=False):
        gate = self.gate(inp)
        val, idx = jax.lax.top_k(gate, self.top_k)
        if return_all_scores:
            return val, idx, gate
        return val, idx


class SwitchGate(NaiveGate):
    """ref: gate/switch_gate.py — top-1 routing with train-time jitter
    noise and the Switch load-balance loss."""

    def __init__(self, d_model, num_expert, world_size=1, topk=1,
                 switch_eps=0.1, capacity=(1.2, 2.4)):
        if topk != 1:
            raise ValueError('topk should be 1 in switch')
        super().__init__(d_model, num_expert, world_size, topk=1)
        self.switch_eps = switch_eps
        self.capacity = capacity

    def forward(self, inp, jitter_key=None):
        import math

        score = self.gate(inp)
        if self.training:
            if jitter_key is None:
                from ..framework import random as random_mod
                jitter_key = random_mod.split_key()
            noise = jax.random.uniform(jitter_key, score.shape,
                                       dtype=score.dtype)
            score = score + noise * 2 * self.switch_eps + 1.0 - self.switch_eps
        probs = jax.nn.softmax(score.astype(jnp.float32), axis=-1)
        top1_val, top1_idx = jax.lax.top_k(probs, 1)
        # Switch balance loss: E * sum(frac_tokens_e * frac_prob_e)
        E = self.tot_expert
        ce = jax.nn.one_hot(top1_idx[:, 0], E).mean(axis=0)
        me = probs.mean(axis=0)
        self.set_loss(E * jnp.sum(ce * me))
        # capacity pruning (ref switch_gate.py -> limit_by_capacity):
        # per-expert budget from the train/eval capacity factor; dropped
        # routings come back as -1
        cap_rate = self.capacity[0 if self.training else 1]
        cap = max(1, math.ceil(cap_rate * inp.shape[0] / self.tot_expert))
        top1_idx = limit_by_capacity(top1_idx, self.tot_expert, cap)
        return top1_val.astype(inp.dtype), top1_idx


class GShardGate(NaiveGate):
    """ref: gate/gshard_gate.py — top-2 routing + GShard balance loss."""

    def __init__(self, d_model, num_expert, world_size=1, topk=2,
                 capacity=(1.2, 2.4), random_routing=True):
        if topk != 2:
            raise ValueError('topk should be 2 in gshard')
        super().__init__(d_model, num_expert, world_size)
        self.top_k = 2
        self.capacity = capacity
        self.random_routing = random_routing

    def forward(self, inp, rng_key=None):
        import math

        val, idx, score = super().forward(inp, return_all_scores=True)
        E = self.tot_expert
        ce = jax.nn.one_hot(idx.reshape(-1), E).sum(axis=0) / score.shape[0]
        me = jax.nn.softmax(score.astype(jnp.float32), axis=-1).mean(axis=0)
        self.set_loss(jnp.mean(ce * me) * (self.num_expert ** 2))
        # capacity pruning (ref gshard_gate.py -> limit_by_capacity)
        cap_rate = self.capacity[0 if self.training else 1]
        cap = max(1, math.ceil(cap_rate * inp.shape[0] / self.tot_expert))
        idx = limit_by_capacity(idx, self.tot_expert, cap)
        if self.random_routing:
            # ref gshard_gate.py: keep the 2nd choice with probability
            # proportional to its (doubled) gate value
            if rng_key is None:
                from ..framework import random as random_mod
                rng_key = random_mod.split_key()
            gate2 = jax.nn.softmax(score.astype(jnp.float32), axis=-1)
            gate2 = jnp.take_along_axis(gate2, idx[:, 1:2].clip(0), axis=-1)
            keep2 = (jax.random.uniform(rng_key, (score.shape[0], 1))
                     < 2.0 * gate2)
            idx = jnp.concatenate(
                [idx[:, :1], jnp.where(keep2, idx[:, 1:2], -1)], axis=-1)
        return val, idx


def _expert_einsum(eq, x, w):
    """Expert einsum that serves int8-quantized weights: a
    QuantizedExpertWeight feeds its codes into the dot (int8 HBM
    stream) and scales the output; dense arrays take the plain path."""
    from ..nn.quant import QuantizedExpertWeight

    if isinstance(w, QuantizedExpertWeight):
        return w.einsum(eq, x)
    return jnp.einsum(eq, x, w)


def _dense_expert(w, dtype):
    """ragged_dot needs dense operands: dequantize quantized experts
    (documented cost — see quantization.quantize_matmul_weights)."""
    from ..nn.quant import QuantizedExpertWeight

    if isinstance(w, QuantizedExpertWeight):
        return w.dequantize(dtype)
    return w


class ExpertMLP(Layer):
    """E experts' weights batched on a leading axis sharded over 'ep' —
    one einsum runs every expert (GSPMD splits it across ranks)."""

    def __init__(self, num_experts, hidden, intermediate, activation=F.silu):
        super().__init__()
        init = I.Normal(0.0, 0.02)
        self.w_up = Parameter(init((num_experts, hidden, intermediate), 'float32'),
                              spec=P('ep', None, 'tp'))
        self.w_gate = Parameter(init((num_experts, hidden, intermediate), 'float32'),
                                spec=P('ep', None, 'tp'))
        self.w_down = Parameter(init((num_experts, intermediate, hidden), 'float32'),
                                spec=P('ep', 'tp', None))
        self.act = activation

    def forward(self, x):
        """x: (E, C, H) expert-major buckets."""
        h = self.act(_expert_einsum('ech,ehm->ecm', x, self.w_gate))
        h = h * _expert_einsum('ech,ehm->ecm', x, self.w_up)
        return _expert_einsum('ecm,emh->ech', h, self.w_down)


class MoELayer(Layer):
    """ref: incubate.distributed.models.moe.MoELayer.

    Dense GShard dispatch: out = combine · expert(dispatchᵀ · x).
    Shared experts (DeepSeek-style) run on every token additively.
    """

    # the router weight: keep full precision under weight-only PTQ
    no_quantize = ('gate',)

    def __init__(self, hidden, intermediate, num_experts=8, top_k=2,
                 capacity_factor=1.25, num_shared_experts=0, gate_init=None,
                 return_aux=False, dispatch_mode='auto'):
        super().__init__()
        if dispatch_mode not in ('auto', 'dense', 'ragged'):
            raise ValueError(
                f"dispatch_mode must be 'auto'|'dense'|'ragged', "
                f'got {dispatch_mode}')
        self.num_experts = num_experts
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        # 'dense' = GShard (T, E, C) einsum dispatch: best for small E,
        # and the form GSPMD turns into the ep all-to-all. 'ragged' =
        # DROPLESS sort + lax.ragged_dot grouped GEMM: O(T·k) memory,
        # the right shape for E >= ~16 — note it ignores capacity_factor
        # (no token dropping, DeepSeek-style). 'auto' preserves the
        # historical dense numerics but nudges large-E users once.
        if dispatch_mode == 'auto':
            if num_experts >= 16:
                import warnings

                warnings.warn(
                    f'MoELayer(num_experts={num_experts}) defaults to the '
                    f'dense GShard dispatch, whose (tokens, E, C) tensors '
                    f"are O(T²); pass dispatch_mode='ragged' for the "
                    f'dropless grouped-GEMM path at this expert count.',
                    stacklevel=3)
            dispatch_mode = 'dense'
        self.dispatch_mode = dispatch_mode
        init = gate_init or I.Normal(0.0, 0.02)
        self.gate = Parameter(init((hidden, num_experts), 'float32'))
        self.experts = ExpertMLP(num_experts, hidden, intermediate)
        self.num_shared = num_shared_experts
        self.shared = (
            None if num_shared_experts == 0
            else ExpertMLP(num_shared_experts, hidden,
                           intermediate)
        )
        self.return_aux = return_aux
        self.aux_loss = jnp.zeros(())   # registered buffer: last aux loss

    def forward(self, x, dropless=False):
        """x: (B, S, H) → (B, S, H), or (out, aux_loss) if return_aux.

        ``dropless=True`` routes through the ragged (no-capacity) path
        regardless of dispatch_mode — KV-cached decode passes it, since
        capacity computed from a single-token call (T = B) drops every
        routing collision and silently degrades generation.

        `self.aux_loss` is also updated in place; being a registered
        buffer it follows the framework's state-in/state-out rule — under
        jit it carries out only if the (traced) model is returned from
        the jitted fn, like BatchNorm stats. Use `return_aux=True` (or
        read `m.aux_loss` on the traced model inside the step) when
        adding it to the training loss."""
        B, S, H = x.shape
        tokens = x.reshape(B * S, H)
        T = B * S
        logits = tokens @ self.gate
        if dropless or self.dispatch_mode == 'ragged':
            _, gate_vals, expert_idx, aux = _topk_gates(logits, self.top_k)
            out = ragged_expert_apply(
                tokens.astype(x.dtype), expert_idx, gate_vals,
                self.experts.w_gate, self.experts.w_up, self.experts.w_down,
                self.num_experts, act=self.experts.act)
            out = out.reshape(B, S, H).astype(x.dtype)
        else:
            capacity = int(
                self.capacity_factor * self.top_k * T / self.num_experts)
            capacity = max(capacity, 1)
            dispatch, combine, aux = top_k_gating(logits, self.top_k,
                                                  capacity)
            # (T,E,C)·(T,H) → (E,C,H): under GSPMD with 'ep'-sharded
            # experts this einsum IS the all-to-all dispatch
            expert_in = jnp.einsum('tec,th->ech', dispatch,
                                   tokens.astype(jnp.float32))
            expert_out = self.experts(expert_in.astype(x.dtype))
            out = jnp.einsum('tec,ech->th', combine,
                             expert_out.astype(jnp.float32))
            out = out.reshape(B, S, H).astype(x.dtype)
        if self.shared is not None:
            shared_in = jnp.broadcast_to(
                tokens[None], (self.num_shared, T, H)).astype(x.dtype)
            shared_out = self.shared(shared_in).sum(axis=0)
            out = out + shared_out.reshape(B, S, H)
        # state-in/state-out: only stash aux on a layer whose own leaves
        # are part of the active trace. When a CONCRETE model runs under
        # an inner trace (e.g. generate()'s lax.scan closes over self),
        # writing the traced aux would leak a tracer into the instance
        # and poison every later flatten/jit with UnexpectedTracerError.
        # NOTE: in that skipped case `self.aux_loss` retains its value
        # from the last eager call (stale) — read the aux via
        # `return_aux=True` inside jitted code, never off the instance.
        stash_ok = not (isinstance(aux, jax.core.Tracer)
                        and not isinstance(self.aux_loss, jax.core.Tracer))
        if stash_ok:
            object.__setattr__(self, 'aux_loss', aux)
        if self.return_aux:
            return out, aux
        return out
