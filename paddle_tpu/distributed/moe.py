"""Mixture-of-Experts with expert parallelism.

ref: python/paddle/incubate/distributed/models/moe (MoELayer, gate/
top-k dispatch, NCCL all-to-all) — Paddle routes token tensors between
expert ranks with `global_scatter`/`global_gather`.

TPU-native: gating + capacity-bucketed dispatch is dense einsum algebra
(one-hot combine/dispatch masks — the classic GShard formulation, which
IS what XLA wants: static shapes, MXU-friendly), and the rank-to-rank
exchange is `lax.all_to_all` over the 'ep' mesh axis when run under
shard_map — or plain GSPMD sharding of the expert axis under pjit
(experts sharded over 'ep'; XLA inserts the all-to-all pair itself).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer.base import Layer, Parameter


def top_k_gating(logits, k: int, capacity: int, jitter_key=None):
    """GShard-style top-k gating with capacity.

    logits: (tokens, E). Returns (dispatch (T, E, C) bool-ish float,
    combine (T, E, C) float, aux_loss scalar).
    """
    T, E = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    gate_vals, expert_idx = jax.lax.top_k(probs, k)          # (T, k)
    # normalise chosen gates
    gate_vals = gate_vals / jnp.maximum(gate_vals.sum(-1, keepdims=True), 1e-9)

    # load-balancing aux loss (Switch/GShard): E * mean(frac_tokens * frac_probs)
    me = probs.mean(axis=0)                                   # (E,)
    top1 = jax.nn.one_hot(expert_idx[:, 0], E)
    ce = top1.mean(axis=0)
    aux_loss = E * jnp.sum(me * ce)

    # position of each (token, choice) within its expert's capacity buffer
    dispatch = jnp.zeros((T, E, capacity), jnp.float32)
    combine = jnp.zeros((T, E, capacity), jnp.float32)
    fill = jnp.zeros((E,), jnp.int32)
    for choice in range(k):
        e = expert_idx[:, choice]                             # (T,)
        onehot_e = jax.nn.one_hot(e, E, dtype=jnp.int32)      # (T, E)
        # slot index = tokens already routed to e before me (this choice pass)
        pos_in_e = jnp.cumsum(onehot_e, axis=0) - onehot_e    # (T, E)
        slot = (pos_in_e * onehot_e).sum(-1) + fill[e]        # (T,)
        keep = slot < capacity
        slot_oh = jax.nn.one_hot(slot, capacity) * keep[:, None]
        upd = onehot_e[:, :, None] * slot_oh[:, None, :]      # (T, E, C)
        dispatch = dispatch + upd
        combine = combine + upd * (gate_vals[:, choice] * keep)[:, None, None]
        fill = fill + onehot_e.sum(0)
    return dispatch, combine, aux_loss


class ExpertMLP(Layer):
    """E experts' weights batched on a leading axis sharded over 'ep' —
    one einsum runs every expert (GSPMD splits it across ranks)."""

    def __init__(self, num_experts, hidden, intermediate, activation=F.silu):
        super().__init__()
        init = I.Normal(0.0, 0.02)
        self.w_up = Parameter(init((num_experts, hidden, intermediate), 'float32'),
                              spec=P('ep', None, 'tp'))
        self.w_gate = Parameter(init((num_experts, hidden, intermediate), 'float32'),
                                spec=P('ep', None, 'tp'))
        self.w_down = Parameter(init((num_experts, intermediate, hidden), 'float32'),
                                spec=P('ep', 'tp', None))
        self.act = activation

    def forward(self, x):
        """x: (E, C, H) expert-major buckets."""
        h = self.act(jnp.einsum('ech,ehm->ecm', x, self.w_gate))
        h = h * jnp.einsum('ech,ehm->ecm', x, self.w_up)
        return jnp.einsum('ecm,emh->ech', h, self.w_down)


class MoELayer(Layer):
    """ref: incubate.distributed.models.moe.MoELayer.

    Dense GShard dispatch: out = combine · expert(dispatchᵀ · x).
    Shared experts (DeepSeek-style) run on every token additively.
    """

    def __init__(self, hidden, intermediate, num_experts=8, top_k=2,
                 capacity_factor=1.25, num_shared_experts=0, gate_init=None,
                 return_aux=False):
        super().__init__()
        self.num_experts = num_experts
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        init = gate_init or I.Normal(0.0, 0.02)
        self.gate = Parameter(init((hidden, num_experts), 'float32'))
        self.experts = ExpertMLP(num_experts, hidden, intermediate)
        self.num_shared = num_shared_experts
        self.shared = (
            None if num_shared_experts == 0
            else ExpertMLP(num_shared_experts, hidden,
                           intermediate)
        )
        self.return_aux = return_aux
        self.aux_loss = jnp.zeros(())   # registered buffer: last aux loss

    def forward(self, x):
        """x: (B, S, H) → (B, S, H), or (out, aux_loss) if return_aux.

        `self.aux_loss` is also updated in place; being a registered
        buffer it follows the framework's state-in/state-out rule — under
        jit it carries out only if the (traced) model is returned from
        the jitted fn, like BatchNorm stats. Use `return_aux=True` (or
        read `m.aux_loss` on the traced model inside the step) when
        adding it to the training loss."""
        B, S, H = x.shape
        tokens = x.reshape(B * S, H)
        T = B * S
        capacity = int(self.capacity_factor * self.top_k * T / self.num_experts)
        capacity = max(capacity, 1)
        logits = tokens @ self.gate
        dispatch, combine, aux = top_k_gating(logits, self.top_k, capacity)
        # (T,E,C)·(T,H) → (E,C,H): under GSPMD with 'ep'-sharded experts
        # this einsum IS the all-to-all dispatch
        expert_in = jnp.einsum('tec,th->ech', dispatch, tokens.astype(jnp.float32))
        expert_out = self.experts(expert_in.astype(x.dtype))
        out = jnp.einsum('tec,ech->th', combine, expert_out.astype(jnp.float32))
        out = out.reshape(B, S, H).astype(x.dtype)
        if self.shared is not None:
            shared_in = jnp.broadcast_to(
                tokens[None], (self.num_shared, T, H)).astype(x.dtype)
            shared_out = self.shared(shared_in).sum(axis=0)
            out = out + shared_out.reshape(B, S, H)
        object.__setattr__(self, 'aux_loss', aux)
        if self.return_aux:
            return out, aux
        return out
