"""Model parallelization (ref: python/paddle/distributed/auto_parallel +
fleet.distributed_model).

Paddle: `fleet.distributed_model(model)` wraps the model in
DataParallel / TensorParallel / PipelineParallel classes that rewire
forward with NCCL calls. TPU-native: `parallelize(model, mesh, rules)`
*annotates* — every parameter gets a `PartitionSpec`, arrays are
device_put with `NamedSharding`, and GSPMD inserts the collectives when
the jitted train step runs. The model code never changes.
"""
from __future__ import annotations

import re
import typing

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from ..framework import tree as tree_util
from .mesh import get_mesh

Rules = typing.Sequence[typing.Tuple[str, typing.Any]]


def match_spec(path: str, rules: Rules):
    for pattern, spec in rules:
        if re.match(pattern, path):
            return spec
    return None


def apply_rules(model, rules: Rules):
    """Set Parameter PartitionSpec metadata by regex over param paths
    (ref: auto_parallel shard_tensor annotations). Mutates metadata only."""
    for layer_path, layer in model.named_sublayers(include_self=True):
        for name, v in list(layer._children()):
            from ..nn.layer.base import Layer

            if isinstance(v, Layer):
                continue
            path = f'{layer_path}.{name}' if layer_path else name
            spec = match_spec(path, rules)
            if spec is not None and layer.meta_for(name).kind == 'param':
                layer.set_param_meta(name, spec=spec)
    return model


def _valid_spec(spec, shape, mesh: Mesh):
    """Clamp a PartitionSpec to divisible dims on this mesh; drop axes the
    mesh doesn't have or that don't divide the dim."""
    if spec is None:
        return P()
    out = []
    for i, entry in enumerate(spec):
        if i >= len(shape):
            # truncate entries beyond the leaf's rank: a pytree attr can
            # carry leaves of different ranks (QuantizedExpertWeight's
            # 3-D codes + 2-D scale share one meta spec), and an
            # over-long spec is a hard NamedSharding error
            break
        if entry is None:
            out.append(None)
            continue
        axes = entry if isinstance(entry, tuple) else (entry,)
        keep = []
        size = 1
        for a in axes:
            if a in mesh.axis_names:
                keep.append(a)
                size *= mesh.shape[a]
        if keep and shape[i] % size == 0:
            out.append(tuple(keep) if len(keep) > 1 else keep[0])
        else:
            out.append(None)
    return P(*out)


def shard_model(model, mesh: Mesh | None = None, fsdp_axis=None):
    """device_put every array leaf per its PartitionSpec (replicated if
    none). `fsdp_axis`: additionally shard the largest unsharded dim of
    each param over this axis (ZeRO-3 / GroupSharded stage 3 —
    ref: fleet/meta_parallel/sharding/group_sharded_stage3.py)."""
    mesh = mesh or get_mesh()
    if mesh is None:
        return model

    def place(meta, path, x):
        if x is None or not hasattr(x, 'shape'):
            return x
        spec = meta.spec if (meta is not None and meta.spec is not None) else P()

        def put_leaf(leaf):
            s = _valid_spec(spec, leaf.shape, mesh)
            if fsdp_axis and meta is not None and meta.kind == 'param':
                s = _add_fsdp(s, leaf.shape, mesh, fsdp_axis)
            return jax.device_put(leaf, NamedSharding(mesh, s))

        if isinstance(x, jax.Array):
            return put_leaf(x)
        # pytree-wrapped weights (QuantizedWeight family): one attr spec,
        # leaves of DIFFERENT ranks (3-D codes + 2-D scale) — clamp the
        # spec per leaf or device_put broadcasts an over-long spec
        return jax.tree.map(put_leaf, x)

    return tree_util._map_model(model, place)


def _add_fsdp(spec, shape, mesh, axis):
    if axis not in mesh.axis_names or mesh.shape[axis] == 1:
        return spec
    # leave small 1-D params (norm scales, biases) replicated: sharding a
    # few hundred floats saves nothing, and a hidden-sharded norm weight
    # makes GSPMD reshard every batch-sharded activation it touches (the
    # spmd_partitioner "involuntary full rematerialization" warning)
    if len(shape) <= 1:
        return spec
    entries = list(spec) + [None] * (len(shape) - len(spec))
    used = set()
    for e in entries:
        for a in (e if isinstance(e, tuple) else (e,)):
            if a:
                used.add(a)
    if axis in used:
        return spec
    # shard the largest divisible unsharded dim
    best, best_size = None, 0
    for i, e in enumerate(entries):
        if e is None and shape[i] % mesh.shape[axis] == 0 and shape[i] > best_size:
            best, best_size = i, shape[i]
    if best is None:
        return spec
    entries[best] = axis
    return P(*entries)


def activation_batch_constraint(x, axes=('dp', 'fsdp')):
    """Constrain an activation to batch-dim sharding over the data axes.

    No-op without a mesh / data axes / divisible batch.
    """
    mesh = get_mesh()
    if mesh is None or not hasattr(x, 'ndim'):
        return x
    present = tuple(a for a in axes
                    if a in mesh.axis_names and mesh.shape[a] > 1)
    if not present:
        return x
    size = 1
    for a in present:
        size *= mesh.shape[a]
    if x.ndim == 0 or x.shape[0] % size != 0:
        return x
    spec = P(present if len(present) > 1 else present[0],
             *([None] * (x.ndim - 1)))
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def embedding_lookup(table, ids):
    """Mesh-friendly embedding lookup.

    A plain ``table[ids]`` gather propagates the (tp, fsdp) table
    sharding into the activation, which GSPMD can only undo by a full
    rematerialization (spmd_partitioner warning). Under a sharded mesh,
    lower to one_hot @ table instead — GSPMD partitions the contraction
    cleanly (vocab-tp -> psum; the MXU eats the extra FLOPs), the
    standard TPU recipe. Single-device / no-mesh keeps the O(B·S·H)
    gather.
    """
    mesh = get_mesh()
    # only when the axes that actually shard tables (tp/fsdp, per
    # LLAMA_TP_RULES/_add_fsdp) are active: under dp/pp-only meshes the
    # table is replicated and the gather is cheap and remat-free
    sharded = mesh is not None and any(
        a in mesh.axis_names and mesh.shape[a] > 1 for a in ('tp', 'fsdp'))
    if not sharded:
        return table[ids]
    oh = jax.nn.one_hot(ids, table.shape[0], dtype=table.dtype)
    out = jnp.einsum('...v,vh->...h', oh, table)
    return activation_batch_constraint(out)


def model_shardings(model, mesh: Mesh | None = None):
    """Model-shaped tree of NamedShardings (for pjit in/out_shardings)."""
    mesh = mesh or get_mesh()
    specs = tree_util.spec_tree(model)
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s) if isinstance(s, P) else s, specs,
        is_leaf=lambda s: isinstance(s, P),
    )


def parallelize(model, mesh: Mesh | None = None, rules: Rules | None = None,
                fsdp_axis=None):
    """Annotate + place: the one-call equivalent of
    `fleet.distributed_model` (ref: python/paddle/distributed/parallel.py).
    """
    mesh = mesh or get_mesh()
    if rules:
        apply_rules(model, rules)
    return shard_model(model, mesh, fsdp_axis=fsdp_axis)


def shard_tensor(x, mesh: Mesh | None = None, *spec_entries, spec=None,
                 placements=None):
    """ref: paddle.distributed.shard_tensor — place one array. Accepts
    either PartitionSpec entries (TPU-native) or the reference's
    `placements` list / ProcessMesh (auto-parallel semantic API)."""
    from .auto_parallel import Placement, ProcessMesh, placements_to_spec

    if isinstance(mesh, ProcessMesh):
        if placements is None and spec_entries and isinstance(
                spec_entries[0], (list, tuple)) and all(
                isinstance(p, Placement) for p in spec_entries[0]):
            placements = spec_entries[0]
        jm = mesh.get_mesh()
        spec = placements_to_spec(placements or [], jm,
                                  jax.numpy.asarray(x).ndim)
        return jax.device_put(x, NamedSharding(jm, spec))
    mesh = mesh or get_mesh()
    if placements is not None:
        from .auto_parallel import placements_to_spec as p2s

        spec = p2s(placements, mesh, jax.numpy.asarray(x).ndim)
    elif spec is None:
        spec = P(*spec_entries)
    return jax.device_put(x, NamedSharding(mesh, _valid_spec(spec, x.shape, mesh)))


def shard_batch(batch, mesh: Mesh | None = None, axes=('dp', 'fsdp')):
    """Shard the leading (batch) dim of every leaf over the data axes."""
    mesh = mesh or get_mesh()
    present = tuple(a for a in axes if a in mesh.axis_names and mesh.shape[a] > 1)

    def place(x):
        spec = P(present) if present and x.ndim and x.shape[0] % _prod(mesh, present) == 0 else P()
        return jax.device_put(x, NamedSharding(mesh, spec))

    return jax.tree.map(place, batch)


def _prod(mesh, axes):
    n = 1
    for a in axes:
        n *= mesh.shape[a]
    return n


class DataParallel:
    """ref: paddle.DataParallel — wraps a model for dp training.

    TPU-native: nothing to wrap. Holds the model with batch-sharding
    helpers; gradients are averaged by GSPMD when the loss mean spans the
    sharded batch axis. Provided for API parity."""

    def __init__(self, layers, mesh=None, **kw):
        self._layers = layers
        self.mesh = mesh or get_mesh()
        if self.mesh is not None:
            self._layers = shard_model(layers, self.mesh)

    def __getattr__(self, name):
        return getattr(self._layers, name)

    def __call__(self, *args, **kw):
        return self._layers(*args, **kw)

    def forward(self, *args, **kw):
        return self._layers(*args, **kw)

    def scale_loss(self, loss):
        return loss          # GSPMD mean already spans replicas

    def apply_collective_grads(self):
        return None          # grads are globally correct under GSPMD
