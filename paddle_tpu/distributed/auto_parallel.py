"""Semantic auto-parallel API (ref: python/paddle/distributed/auto_parallel
/api.py — ProcessMesh / Shard / Replicate / Partial, shard_tensor,
reshard, shard_layer, shard_optimizer, DistModel).

The mapping is exact, not emulated: paddle's ProcessMesh IS
`jax.sharding.Mesh`, a placements list IS a `PartitionSpec` (placement i
says how MESH dim i uses tensor dims), and `reshard` IS `device_put`
with a new NamedSharding — GSPMD then inserts the collectives the
reference's reshard pass hand-plans.
"""
from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


class ReduceType:
    """ref: paddle.distributed.ReduceType."""

    kRedSum = 0
    kRedMax = 1
    kRedMin = 2
    kRedProd = 3
    kRedAvg = 4
    kRedAny = 5
    kRedAll = 6


class Placement:
    """Base of Shard/Replicate/Partial (ref: dist.Placement)."""

    def is_shard(self, dim=None):
        return False

    def is_replicated(self):
        return False

    def is_partial(self):
        return False


class Shard(Placement):
    def __init__(self, dim):
        self.dim = int(dim)

    def is_shard(self, dim=None):
        return dim is None or dim == self.dim

    def __repr__(self):
        return f'Shard(dim={self.dim})'

    def __eq__(self, other):
        return isinstance(other, Shard) and other.dim == self.dim

    def __hash__(self):
        return hash(('shard', self.dim))


class Replicate(Placement):
    def is_replicated(self):
        return True

    def __repr__(self):
        return 'Replicate()'

    def __eq__(self, other):
        return isinstance(other, Replicate)

    def __hash__(self):
        return hash('replicate')


class Partial(Placement):
    """A pending-reduction placement. jax has no first-class partial
    arrays outside shard_map; at placement time it degrades to
    Replicate (the reduction is already done on materialized values)."""

    def __init__(self, reduce_type=ReduceType.kRedSum):
        self.reduce_type = reduce_type

    def is_partial(self):
        return True

    def __repr__(self):
        return f'Partial({self.reduce_type})'


class ProcessMesh:
    """ref: paddle.distributed.ProcessMesh(mesh, dim_names) — an
    n-dimensional array of process ids with named dims. Backed by one
    `jax.sharding.Mesh` over the matching devices."""

    def __init__(self, mesh, dim_names=None, shape=None, process_ids=None):
        arr = np.asarray(mesh)
        if dim_names is None:
            dim_names = [f'd{i}' for i in range(arr.ndim)]
        if len(dim_names) != arr.ndim:
            raise ValueError(f'{arr.ndim}-d mesh needs {arr.ndim} dim_names, '
                             f'got {list(dim_names)}')
        self._ids = arr
        self._dim_names = tuple(dim_names)
        devices = np.asarray(jax.devices(), object)[arr.reshape(-1) %
                                                    len(jax.devices())]
        self._jax_mesh = Mesh(devices.reshape(arr.shape), self._dim_names)

    @property
    def shape(self):
        return list(self._ids.shape)

    @property
    def ndim(self):
        return self._ids.ndim

    @property
    def dim_names(self):
        return list(self._dim_names)

    @property
    def process_ids(self):
        return self._ids.reshape(-1).tolist()

    @property
    def mesh(self):
        return self._ids

    def get_mesh(self):
        """The backing jax Mesh (TPU-native handle)."""
        return self._jax_mesh

    def get_dim_size(self, name):
        return self._ids.shape[self._dim_names.index(name)]

    def __eq__(self, other):
        return (isinstance(other, ProcessMesh)
                and self._dim_names == other._dim_names
                and np.array_equal(self._ids, other._ids))

    def __repr__(self):
        return f'ProcessMesh(shape={self.shape}, dim_names={self.dim_names})'


def _as_jax_mesh(mesh):
    if isinstance(mesh, ProcessMesh):
        return mesh.get_mesh()
    if isinstance(mesh, Mesh):
        return mesh
    raise TypeError(f'expected ProcessMesh or jax Mesh, got {type(mesh)}')


def placements_to_spec(placements, mesh, ndim):
    """placements[i] describes MESH dim i; invert to a PartitionSpec
    (tensor-dim major)."""
    jm = _as_jax_mesh(mesh)
    names = jm.axis_names
    per_tensor_dim = [[] for _ in range(ndim)]
    for i, pl in enumerate(placements):
        if isinstance(pl, Shard):
            per_tensor_dim[pl.dim].append(names[i])
    entries = [tuple(axs) if len(axs) > 1 else (axs[0] if axs else None)
               for axs in per_tensor_dim]
    while entries and entries[-1] is None:
        entries.pop()
    return P(*entries)


def spec_to_placements(spec, mesh, ndim):
    """Inverse of placements_to_spec."""
    jm = _as_jax_mesh(mesh)
    placements = [Replicate() for _ in jm.axis_names]
    for tdim, entry in enumerate(tuple(spec)):
        if entry is None:
            continue
        for name in (entry if isinstance(entry, tuple) else (entry,)):
            placements[jm.axis_names.index(name)] = Shard(tdim)
    return placements


def shard_tensor(x, mesh, placements, dtype=None, stop_gradient=True):
    """ref: dist.shard_tensor(data, mesh, placements)."""
    x = jax.numpy.asarray(x, dtype)
    jm = _as_jax_mesh(mesh)
    spec = placements_to_spec(placements, jm, x.ndim)
    return jax.device_put(x, NamedSharding(jm, spec))


def dtensor_from_fn(fn, mesh, placements, *args, **kwargs):
    """ref: dist.dtensor_from_fn — build then place."""
    return shard_tensor(fn(*args, **kwargs), mesh, placements)


def reshard(x, mesh, placements):
    """ref: dist.reshard — move to a new placement; XLA emits the
    collective (all-gather / all-to-all / slice) that realizes it."""
    jm = _as_jax_mesh(mesh)
    spec = placements_to_spec(placements, jm, jax.numpy.asarray(x).ndim)
    return jax.device_put(x, NamedSharding(jm, spec))


def unshard_dtensor(x):
    """ref: dist.unshard_dtensor — gather to a fully-replicated value."""
    if hasattr(x, 'sharding') and isinstance(getattr(x, 'sharding', None),
                                             NamedSharding):
        jm = x.sharding.mesh
        return jax.device_put(x, NamedSharding(jm, P()))
    return x


def shard_layer(layer, process_mesh, shard_fn=None, input_fn=None,
                output_fn=None):
    """ref: dist.shard_layer — place every parameter of `layer`.
    `shard_fn(name, layer, mesh)` may assign per-param placements; the
    default replicates (GSPMD still shards activations from the inputs).
    Returns the same pytree-Layer with placed parameter arrays."""
    jm = _as_jax_mesh(process_mesh)

    def place_params(lyr, prefix=''):
        if shard_fn is not None:
            # the user's shard_fn assigns placements itself (the
            # reference's contract) — do NOT re-place afterwards, that
            # would clobber its shardings with replication
            shard_fn(prefix.rstrip('.'), lyr, process_mesh)
        for name, value in list(getattr(lyr, '__dict__', {}).items()):
            from ..nn.layer.base import Layer

            if isinstance(value, Layer):
                place_params(value, f'{prefix}{name}.')
            elif (shard_fn is None
                  and name in getattr(lyr, '_param_meta', {})):
                lyr.__dict__[name] = jax.device_put(
                    value, NamedSharding(jm, P()))
        return lyr

    if shard_fn is None and input_fn is None and output_fn is None:
        return place_params(layer)
    out = place_params(layer)
    if input_fn is not None or output_fn is not None:
        inner_forward = out.forward

        def wrapped(*args, **kwargs):
            if input_fn is not None:
                args = input_fn(args, process_mesh)
            res = inner_forward(*args, **kwargs)
            if output_fn is not None:
                res = output_fn(res, process_mesh)
            return res

        out.forward = wrapped
    return out


class ShardingStage1:
    """ref: dist.ShardingStage1(axis, mesh) — shard optimizer STATE over
    the data axis (ZeRO-1)."""

    stage = 1

    def __init__(self, axis='dp', mesh=None):
        self.axis, self.mesh = axis, mesh


class ShardingStage2(ShardingStage1):
    stage = 2  # + gradient sharding (reduce-scatter; GSPMD emits it)


class ShardingStage3(ShardingStage1):
    stage = 3  # + parameter sharding


def shard_optimizer(optimizer, shard_fn=None):
    """ref: dist.shard_optimizer — wrap so optimizer slots are placed
    sharded. `shard_fn` is a ShardingStage1/2/3 marker (or a callable
    placing a single slot array)."""
    inner_init = optimizer.init

    def sharded_init(model):
        state = inner_init(model)
        if shard_fn is None:
            return state
        if callable(shard_fn) and not isinstance(shard_fn, ShardingStage1):
            state['slots'] = jax.tree.map(shard_fn, state['slots'])
            return state
        axis = shard_fn.axis
        mesh = shard_fn.mesh
        jm = _as_jax_mesh(mesh) if mesh is not None else None
        if jm is None:
            from .mesh import get_mesh

            jm = get_mesh()
        size = jm.shape[axis] if axis in jm.axis_names else 1

        def place(x):
            spec = P(axis) if (x.ndim and x.shape[0] % max(size, 1) == 0
                               and size > 1) else P()
            return jax.device_put(x, NamedSharding(jm, spec))

        state['slots'] = jax.tree.map(place, state['slots'])
        if 'master' in state:
            state['master'] = jax.tree.map(
                lambda m: place(m) if m is not None else None,
                state['master'])
        return state

    optimizer.init = sharded_init
    return optimizer


def shard_scaler(scaler):
    """ref: dist.shard_scaler — the GradScaler state is a scalar; it is
    already replicated under GSPMD, so this is the identity."""
    return scaler


class Strategy:
    """ref: dist.Strategy for dist.to_static — thin config carrier."""

    def __init__(self, config=None):
        self.sharding = type('c', (), {'enable': False, 'stage': 1})()
        self.fused_passes = type('c', (), {'enable': False})()
        self.pipeline = type('c', (), {'enable': False})()
        self.gradient_merge = type('c', (), {'enable': False, 'avg': True,
                                             'k_steps': 1})()
        if config:
            for k, v in config.items():
                setattr(self, k, v)


class DistModel:
    """ref: dist.to_static return type — a compiled distributed
    train/eval step around (model, loss, optimizer)."""

    def __init__(self, layer, loader=None, loss=None, optimizer=None,
                 strategy=None):
        self.network = layer
        self._loss = loss
        self._opt = optimizer
        self._mode = 'train'
        self._state = optimizer.init(layer) if optimizer is not None else None

        def train_step(model, state, *batch):
            from ..autograd import value_and_grad

            def closure(m):
                out = m(*batch[:-1])
                return self._loss(out, batch[-1])

            lossv, grads = value_and_grad(closure)(model)
            model, state = self._opt.apply_gradients(model, grads, state)
            return model, state, lossv

        def eval_step(model, *batch):
            out = model(*batch[:-1])
            return self._loss(out, batch[-1])

        # cached on self: built once per engine, reused every step
        # tracelint: disable=TL001
        self._train_step = jax.jit(train_step)
        # tracelint: disable=TL001
        self._eval_step = jax.jit(eval_step)

    def train(self):
        self._mode = 'train'
        self.network.train()

    def eval(self):
        self._mode = 'eval'
        self.network.eval()

    def __call__(self, *batch):
        if self._mode == 'train':
            self.network, self._state, loss = self._train_step(
                self.network, self._state, *batch)
            return loss
        return self._eval_step(self.network, *batch)

    def state_dict(self, mode='all'):
        return self.network.state_dict()


def to_static(layer, loader=None, loss=None, optimizer=None, strategy=None,
              input_spec=None):
    """ref: dist.to_static — build the jitted distributed model."""
    return DistModel(layer, loader, loss, optimizer, strategy)


class DistAttr:
    """ref: dist.DistAttr(mesh, sharding_specs) — legacy attr carrier."""

    def __init__(self, mesh, sharding_specs):
        self.process_mesh = mesh
        self.sharding_specs = sharding_specs


def shard_dataloader(dataloader, meshes, shard_dims=None, input_keys=None,
                     is_dataset_splitted=False):
    """ref: dist.shard_dataloader — wrap a DataLoader so every yielded
    batch is placed on the mesh (batch dim sharded over `shard_dims`)."""
    jm = _as_jax_mesh(meshes[0] if isinstance(meshes, (list, tuple))
                      else meshes)
    dims = shard_dims if shard_dims is not None else jm.axis_names[0]
    if isinstance(dims, str):
        dims = (dims,)

    def place(x):
        x = jax.numpy.asarray(x)
        size = 1
        for d in dims:
            size *= jm.shape[d]
        spec = P(tuple(dims)) if (x.ndim and x.shape[0] % size == 0
                                  and size > 1) else P()
        return jax.device_put(x, NamedSharding(jm, spec))

    class _ShardedLoader:
        def __iter__(self):
            for batch in dataloader:
                yield jax.tree.map(place, batch)

        def __len__(self):
            return len(dataloader)

    return _ShardedLoader()
