"""Mesh & strategy (ref: python/paddle/distributed/fleet/fleet.py::init,
base/topology.py::HybridCommunicateGroup).

Paddle builds NCCL process groups per parallel dimension (dp/mp/pp/
sharding) from `DistributedStrategy.hybrid_configs`. TPU-native: the
same topology is ONE `jax.sharding.Mesh` with named axes; GSPMD lowers
array shardings to ICI collectives — no process groups to manage.

Axis names (canonical order, outermost first):
    'dp'   data parallel (pure replica of params)
    'fsdp' fully-sharded data parallel / ZeRO-3 (params sharded too)
    'pp'   pipeline stages
    'tp'   tensor (model) parallel
    'sp'   sequence/context parallel (ring attention)
    'ep'   expert parallel (MoE) — usually aliases dp×fsdp in size
"""
from __future__ import annotations

import dataclasses
import typing

import jax
import numpy as np
from jax.sharding import Mesh

MESH_AXES = ('dp', 'fsdp', 'pp', 'tp', 'sp', 'ep')


@dataclasses.dataclass
class DistributedStrategy:
    """ref: paddle.distributed.fleet.DistributedStrategy (hybrid_configs).

    Degrees of -1 mean "absorb all remaining devices" (at most one).
    """

    dp_degree: int = -1
    fsdp_degree: int = 1
    pp_degree: int = 1
    tp_degree: int = 1
    sp_degree: int = 1
    ep_degree: int = 1
    # non-topology knobs (consumed elsewhere)
    amp: bool = False
    amp_dtype: str = 'bfloat16'
    gradient_merge_steps: int = 1
    sharding_stage: int = 0        # 0=off, 1/2/3 ≈ ZeRO stages

    def degrees(self) -> typing.Dict[str, int]:
        return {
            'dp': self.dp_degree, 'fsdp': self.fsdp_degree,
            'pp': self.pp_degree, 'tp': self.tp_degree, 'sp': self.sp_degree,
            'ep': self.ep_degree,
        }


_global_mesh: typing.Optional[Mesh] = None


def build_mesh(strategy: DistributedStrategy | None = None,
               devices=None, **degree_overrides) -> Mesh:
    """Factor `devices` into a named mesh per the strategy's degrees."""
    strategy = strategy or DistributedStrategy()
    for k, v in degree_overrides.items():
        setattr(strategy, f'{k}_degree', v)
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    degrees = strategy.degrees()
    fixed = {k: v for k, v in degrees.items() if v != -1}
    free = [k for k, v in degrees.items() if v == -1]
    prod = int(np.prod(list(fixed.values()))) if fixed else 1
    if n % prod != 0:
        raise ValueError(f'{n} devices not divisible by fixed degrees {fixed}')
    rest = n // prod
    if len(free) > 1:
        raise ValueError(f'at most one axis may be -1, got {free}')
    if free:
        fixed[free[0]] = rest
    elif prod != n:
        raise ValueError(f'degrees {fixed} (={prod}) != device count {n}')
    shape = tuple(fixed[a] for a in MESH_AXES)
    arr = np.asarray(devices).reshape(shape)
    return Mesh(arr, MESH_AXES)


def init_parallel_env(strategy: DistributedStrategy | None = None,
                      devices=None, **degree_overrides) -> Mesh:
    """ref: paddle.distributed.init_parallel_env / fleet.init.

    Builds the global mesh. For true multi-host, call
    `jax.distributed.initialize()` before this (see distributed/launch.py).
    """
    global _global_mesh
    _global_mesh = build_mesh(strategy, devices, **degree_overrides)
    return _global_mesh


def get_mesh() -> typing.Optional[Mesh]:
    return _global_mesh


def set_mesh(mesh: Mesh):
    global _global_mesh
    _global_mesh = mesh


def force_virtual_devices(n: int) -> None:
    """Append `--xla_force_host_platform_device_count=max(n, 8)` to
    XLA_FLAGS unless a count is already forced. Only effective BEFORE
    the backend initialises (and ignored by jax afterwards) — callers
    that need the devices to actually exist must still count them.
    The 8 floor matches the shardlint / test-rig virtual mesh."""
    import os

    flags = os.environ.get('XLA_FLAGS', '')
    if 'xla_force_host_platform_device_count' not in flags:
        os.environ['XLA_FLAGS'] = (
            flags + f' --xla_force_host_platform_device_count='
                    f'{max(int(n), 8)}').strip()


def serving_mesh(tp: int, devices=None) -> Mesh:
    """1-D tensor-parallel mesh for a TP-sharded `ServingEngine`
    (`ServingEngine(model, tp=4)` builds one of these internally; pass
    an explicit `devices` slice to pin which chips serve).

    Virtual-device fallback: when `devices` is not given and jax has
    not initialised a backend yet, the host-platform device-count flag
    is forced (to at least `tp`, and at least the 8 the shardlint /
    test rig uses) so CPU dev boxes can stand up a tp>1 engine without
    exporting XLA_FLAGS by hand. A backend that already woke up with
    fewer devices cannot be grown — that raises with the recipe
    instead of silently serving single-device."""
    tp = int(tp)
    if tp < 1:
        raise ValueError(f'tp must be >= 1, got {tp}')
    if devices is None:
        # the len() check below is the real gate either way
        force_virtual_devices(tp)
        devices = jax.devices()
    devices = list(devices)
    if len(devices) < tp:
        raise ValueError(
            f'serving_mesh(tp={tp}) needs {tp} devices, found '
            f'{len(devices)}: the backend initialised before the '
            f'virtual-device flag could be set — run with XLA_FLAGS='
            f'--xla_force_host_platform_device_count={max(tp, 8)} '
            f'(and JAX_PLATFORMS=cpu) for a virtual mesh')
    return build_mesh(devices=devices[:tp], tp=tp)


def get_world_size() -> int:
    return jax.device_count()


def get_rank() -> int:
    return jax.process_index()
