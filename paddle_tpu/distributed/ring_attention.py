"""Ring attention — sequence/context parallelism for long sequences.

ref: the reference's sequence-parallel utilities
(python/paddle/distributed/fleet/layers/mpu/mp_layers.py sequence-
parallel paths) scatter activations over ranks and allgather before
attention — O(S) memory per device for KV. Ring attention (Liu et al.;
see PAPERS.md) goes further: KV blocks *rotate* around the 'sp' ring
via `ppermute` while each device accumulates its queries' attention
online (log-sum-exp merge of per-block results), so no device ever
materialises the full sequence. On TPU the ppermute rides the ICI torus
(the hardware collective-permute DMA) and XLA overlaps it with the
per-block compute — the remote-DMA overlap the SURVEY §2.12 stretch
asks for, without hand-written DMA descriptors.

Fast path: each ring step runs the pallas flash-attention kernel
(fwd) under a custom_vjp whose backward *recomputes* the block with the
lax reference — so training memory per step is O(S_local·D) residuals
instead of the O(S_local²) score matrix, and grads equal the reference.

Use under `shard_map` with Q/K/V sharded (batch, seq→'sp', heads, dim).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

from ._spmd import axis_size, shard_map

NEG_INF = -1e30


def _block_ref(q, k, v, scale, diag_causal):
    """One (q-block × kv-block) attention → (normalized out, lse).

    q: (B, Sq, H, D); k/v: (B, Sk, Hkv, D). fp32 math.
    """
    H, Hk = q.shape[2], k.shape[2]
    if Hk != H:
        k = jnp.repeat(k, H // Hk, axis=2)
        v = jnp.repeat(v, H // Hk, axis=2)
    s = jnp.einsum('bqhd,bkhd->bhqk', q.astype(jnp.float32) * scale,
                   k.astype(jnp.float32))
    if diag_causal:
        Sq, Sk = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((Sq, Sk), bool), k=Sk - Sq)
        s = jnp.where(mask[None, None], s, NEG_INF)
    m = jnp.max(s, axis=-1)                      # (B, H, Sq)
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    o = jnp.einsum('bhqk,bkhd->bqhd', p, v.astype(jnp.float32))
    out = o / jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    lse = m + jnp.log(jnp.maximum(l, 1e-30))
    return out, lse


def _block_flash_fwd_pallas(q, k, v, scale, diag_causal):
    """pallas flash kernel for one ring step → (out, lse)."""
    from ..ops.pallas.flash_attention import _fwd

    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    out, lse = _fwd(qt, kt, vt, scale, diag_causal, 1024, 1024)
    # out (B,H,Sq,D) → (B,Sq,H,D); lse (B,H,1,Sq) → (B,H,Sq)
    return jnp.swapaxes(out, 1, 2).astype(jnp.float32), lse[:, :, 0, :]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _block_flash(q, k, v, scale, diag_causal):
    return _block_flash_fwd_pallas(q, k, v, scale, diag_causal)


def _block_flash_f(q, k, v, scale, diag_causal):
    out = _block_flash_fwd_pallas(q, k, v, scale, diag_causal)
    return out, (q, k, v)


def _block_flash_b(scale, diag_causal, res, cots):
    # recompute-based backward: vjp through the lax reference — grads
    # match the reference exactly, fwd stays pallas-fast
    q, k, v = res
    _, vjp = jax.vjp(lambda q, k, v: _block_ref(q, k, v, scale, diag_causal),
                     q, k, v)
    return vjp(cots)


_block_flash.defvjp(_block_flash_f, _block_flash_b)


def _merge(o1, lse1, o2, lse2):
    """Merge two normalized block results by log-sum-exp weights."""
    m = jnp.maximum(lse1, lse2)
    a1 = jnp.exp(lse1 - m)
    a2 = jnp.exp(lse2 - m)
    tot = jnp.maximum(a1 + a2, 1e-30)
    w1 = (a1 / tot).transpose(0, 2, 1)[..., None]
    w2 = (a2 / tot).transpose(0, 2, 1)[..., None]
    return o1 * w1 + o2 * w2, m + jnp.log(tot)


def ring_attention(q, k, v, axis='sp', causal=False, scale=None):
    """Full attention over a sequence sharded on `axis`; call under
    shard_map with q,k,v local blocks (B, S_local, H, D)."""
    from ..ops import use_pallas

    n = axis_size(axis)
    rank = lax.axis_index(axis)
    B, Sq, H, D = q.shape
    scale = scale or 1.0 / math.sqrt(D)
    perm = [(i, (i + 1) % n) for i in range(n)]   # kv moves to next rank

    # pallas fast path only where the kernel's tiling fits
    flash_ok = bool(use_pallas()) and D % 8 == 0 and Sq >= 128

    def block(qb, kb, vb, diag):
        if flash_ok:
            return _block_flash(qb, kb, vb, scale, diag)
        return _block_ref(qb, kb, vb, scale, diag)

    def step(carry, i):
        o, lse, kb, vb = carry
        # kv block currently held originated at rank (rank - i) mod n
        src = (rank - i) % n
        if causal:
            def full(_):
                return block(q, kb, vb, False)

            def diag(_):
                return block(q, kb, vb, True)

            def skip(_):
                return (jnp.zeros((B, Sq, H, D), jnp.float32),
                        jnp.full((B, H, Sq), NEG_INF, jnp.float32))

            case = jnp.where(src < rank, 0, jnp.where(src == rank, 1, 2))
            ob, lb = lax.switch(case, [full, diag, skip], None)
        else:
            ob, lb = block(q, kb, vb, False)
        o, lse = _merge(o, lse, ob, lb)
        kb = lax.ppermute(kb, axis, perm)
        vb = lax.ppermute(vb, axis, perm)
        return (o, lse, kb, vb), None

    o0 = jnp.zeros((B, Sq, H, D), jnp.float32)
    lse0 = jnp.full((B, H, Sq), NEG_INF, jnp.float32)
    # scan (not fori_loop): reverse-differentiable, so ring attention
    # trains — the bwd pass rings the gradients back around
    (o, lse, _, _), _ = lax.scan(step, (o0, lse0, k, v), jnp.arange(n))
    return o.astype(q.dtype)


def ring_attention_sharded(q, k, v, mesh: Mesh, axis='sp', causal=False,
                           scale=None):
    """Convenience wrapper: q/k/v are global arrays; shards seq over
    `axis`, runs the ring, returns the global output."""
    spec = P(None, axis, None, None)
    fn = shard_map(
        functools.partial(ring_attention, axis=axis, causal=causal, scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
