"""Ring attention — sequence/context parallelism for long sequences.

ref: the reference's sequence-parallel utilities
(python/paddle/distributed/fleet/layers/mpu/mp_layers.py sequence-
parallel paths) scatter activations over ranks and allgather before
attention — O(S) memory per device for KV. Ring attention (Liu et al.;
see PAPERS.md) goes further: KV blocks *rotate* around the 'sp' ring
via `ppermute` while each device accumulates its queries' attention
online (flash-style log-sum-exp merge), so no device ever materialises
the full sequence. On TPU the ppermute rides the ICI torus and XLA
overlaps it with the per-block matmuls — compute-communication overlap
without CUDA streams.

Use under `shard_map` with Q/K/V sharded (batch, seq→'sp', heads, dim).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

NEG_INF = -1e30


def _block_attn(q, k, v, scale, mask=None):
    """One (q-block, kv-block) partial attention.

    Returns (out_unnormalised, row_max, row_sumexp) in fp32 —
    the flash-attention accumulator triple.
    q: (B, Sq, H, D), k/v: (B, Sk, Hkv, D).
    """
    H, Hk = q.shape[2], k.shape[2]
    if Hk != H:
        k = jnp.repeat(k, H // Hk, axis=2)
        v = jnp.repeat(v, H // Hk, axis=2)
    s = jnp.einsum('bqhd,bkhd->bhqk', q.astype(jnp.float32) * scale,
                   k.astype(jnp.float32))
    if mask is not None:
        s = jnp.where(mask, s, NEG_INF)
    m = jnp.max(s, axis=-1)                      # (B, H, Sq)
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)                      # (B, H, Sq)
    o = jnp.einsum('bhqk,bkhd->bqhd', p, v.astype(jnp.float32))
    return o, m, l


def _merge(o1, m1, l1, o2, m2, l2):
    """Merge two flash accumulators (log-sum-exp algebra)."""
    m = jnp.maximum(m1, m2)
    a1 = jnp.exp(m1 - m)
    a2 = jnp.exp(m2 - m)
    o = o1 * a1.transpose(0, 2, 1)[..., None] + o2 * a2.transpose(0, 2, 1)[..., None]
    l = l1 * a1 + l2 * a2
    return o, m, l


def ring_attention(q, k, v, axis='sp', causal=False, scale=None):
    """Full attention over a sequence sharded on `axis`; call under
    shard_map with q,k,v local blocks (B, S_local, H, D)."""
    n = lax.axis_size(axis)
    rank = lax.axis_index(axis)
    B, Sq, H, D = q.shape
    scale = scale or 1.0 / math.sqrt(D)
    perm = [(i, (i + 1) % n) for i in range(n)]   # kv moves to next rank

    q32 = q.astype(jnp.float32)

    def step(carry, i):
        o, m, l, kb, vb = carry
        # kv block currently held originated at rank (rank - i) mod n
        src = (rank - i) % n
        if causal:
            qpos = rank * Sq + jnp.arange(Sq)
            kpos = src * kb.shape[1] + jnp.arange(kb.shape[1])
            mask = (kpos[None, :] <= qpos[:, None])[None, None]
        else:
            mask = None
        ob, mb, lb = _block_attn(q32, kb, vb, scale, mask)
        o, m, l = _merge(o, m, l, ob, mb, lb)
        kb = lax.ppermute(kb, axis, perm)
        vb = lax.ppermute(vb, axis, perm)
        return (o, m, l, kb, vb), None

    o0 = jnp.zeros((B, Sq, H, D), jnp.float32)
    m0 = jnp.full((B, H, Sq), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, Sq), jnp.float32)
    # scan (not fori_loop): reverse-differentiable, so ring attention
    # trains — the bwd pass rings the gradients back around
    (o, m, l, _, _), _ = lax.scan(step, (o0, m0, l0, k, v), jnp.arange(n))
    out = o / jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ring_attention_sharded(q, k, v, mesh: Mesh, axis='sp', causal=False,
                           scale=None):
    """Convenience wrapper: q/k/v are global arrays; shards seq over
    `axis`, runs the ring, returns the global output."""
    spec = P(None, axis, None, None)
    fn = jax.shard_map(
        functools.partial(ring_attention, axis=axis, causal=causal, scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
