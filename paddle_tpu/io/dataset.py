"""Dataset abstractions (ref: python/paddle/io/dataloader/dataset.py)."""
from __future__ import annotations

import bisect

import numpy as np


class Dataset:
    def __getitem__(self, idx):  # pragma: no cover - abstract
        raise NotImplementedError

    def __len__(self):  # pragma: no cover - abstract
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):  # pragma: no cover - abstract
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError('IterableDataset is not indexable')

    def __len__(self):
        raise RuntimeError('IterableDataset has no len()')


class TensorDataset(Dataset):
    def __init__(self, tensors):
        assert all(t.shape[0] == tensors[0].shape[0] for t in tensors)
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(np.asarray(t)[idx] for t in self.tensors)

    def __len__(self):
        return int(self.tensors[0].shape[0])


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cum = np.cumsum([len(d) for d in self.datasets]).tolist()

    def __len__(self):
        return self.cum[-1]

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        di = bisect.bisect_right(self.cum, idx)
        prev = 0 if di == 0 else self.cum[di - 1]
        return self.datasets[di][idx - prev]


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __len__(self):
        return min(len(d) for d in self.datasets)

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            item = d[idx]
            out.extend(item if isinstance(item, (list, tuple)) else [item])
        return tuple(out)


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    from ..framework import random as random_mod
    import jax

    n = len(dataset)
    if all(isinstance(l, float) for l in lengths):
        lengths = [int(np.floor(n * l)) for l in lengths]
        lengths[0] += n - sum(lengths)
    assert sum(lengths) == n, 'lengths must sum to dataset size'
    perm = np.asarray(jax.random.permutation(random_mod.split_key(), n))
    out, offset = [], 0
    for l in lengths:
        out.append(Subset(dataset, perm[offset : offset + l].tolist()))
        offset += l
    return out
