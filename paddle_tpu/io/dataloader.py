"""DataLoader (ref: python/paddle/io/dataloader/dataloader_iter.py).

Multiprocess map-style loading with order-preserving prefetch, plus a
device-prefetch wrapper that keeps `prefetch_depth` batches in flight to
HBM so the accelerator never waits on the host (the TPU analogue of
Paddle's pinned-memory + cudaMemcpyAsync pipeline).
"""
from __future__ import annotations

import itertools
import multiprocessing as mp
import queue as queue_mod
import threading

import numpy as np

from ..framework import random as random_mod
from .dataset import Dataset, IterableDataset


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):  # pragma: no cover - abstract
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None, generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self.num_samples = num_samples or len(data_source)

    def __iter__(self):
        import jax

        n = len(self.data_source)
        key = random_mod.split_key()
        if self.replacement:
            idx = np.asarray(jax.random.randint(key, (self.num_samples,), 0, n))
        else:
            idx = np.asarray(jax.random.permutation(key, n))[: self.num_samples]
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        rng = np.random.default_rng(int(np.asarray(random_mod.split_key())[0]))
        idx = rng.choice(len(p), size=self.num_samples, replace=self.replacement, p=p)
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False, batch_size=1, drop_last=False):
        self.sampler = sampler or (
            RandomSampler(dataset) if shuffle else SequenceSampler(dataset)
        )
        self.batch_size = batch_size
        self.drop_last = drop_last

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Shards the index space across data-parallel workers
    (ref: python/paddle/io/dataloader/batch_sampler.py)."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        import jax

        self.dataset = dataset
        self.batch_size = batch_size
        self.nranks = num_replicas if num_replicas is not None else jax.process_count()
        self.local_rank = rank if rank is not None else jax.process_index()
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.epoch = 0
        n = len(dataset)
        self.num_samples = (n + self.nranks - 1) // self.nranks if not drop_last else n // self.nranks
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        if self.shuffle:
            rng = np.random.default_rng(self.epoch)
            indices = rng.permutation(n).tolist()
        else:
            indices = list(range(n))
        indices += indices[: self.total_size - len(indices)]
        indices = indices[self.local_rank : self.total_size : self.nranks]
        batch = []
        for idx in indices:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch


def default_collate_fn(batch):
    item = batch[0]
    if isinstance(item, (np.ndarray, np.generic)) or np.isscalar(item):
        return np.stack([np.asarray(b) for b in batch])
    if hasattr(item, 'shape'):
        return np.stack([np.asarray(b) for b in batch])
    if isinstance(item, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in item}
    if isinstance(item, (list, tuple)):
        return type(item)(default_collate_fn(list(col)) for col in zip(*batch))
    return np.asarray(batch)


def _worker_loop(dataset, index_queue, data_queue, collate_fn,
                 worker_id=0, num_workers=1):
    _worker_info[0] = WorkerInfo(worker_id, num_workers, dataset)
    while True:
        task = index_queue.get()
        if task is None:
            break
        seq, idxs = task
        try:
            batch = collate_fn([dataset[i] for i in idxs])
            data_queue.put((seq, batch, None))
        except Exception as e:  # pragma: no cover
            data_queue.put((seq, None, repr(e)))


class ShmRingTimeout(RuntimeError):
    """Typed shm-ring stall. Raised by `_push_with_backoff` inside a
    worker when its push budget runs out (carrying the waited/budget
    seconds and ring stats), and RE-RAISED by the parent consumer loop
    with worker identity when a worker dies or the ring goes silent —
    so the failure surfaces as "worker 2 died pushing into ring X",
    not a bare RuntimeError deep in a forked process. Every raise
    records one `io.shm_timeouts` tick in the raising process's
    registry."""

    def __init__(self, msg, *, waited_s=None, budget_s=None,
                 worker_id=None, ring=None):
        super().__init__(msg)
        self.waited_s = waited_s
        self.budget_s = budget_s
        self.worker_id = worker_id
        self.ring = dict(ring or {})


def _push_with_backoff(push, timeout, sleep=None, worker_id=None,
                       ring=None):
    """Retry `push()` (returns False while the ring is full) with
    bounded exponential backoff until it lands or the push budget runs
    out — a dead consumer then RAISES `ShmRingTimeout` in the worker
    (surfacing as a ring timeout in the parent) instead of spinning the
    core forever at 1 kHz. The budget is deliberately LOOSER than the
    consumer-side `timeout`: a full ring is usually backpressure, not
    death — the consumer legitimately stalls for minutes while the
    first train step jit-compiles — so the worker waits several
    consumer-timeouts (floor 5 min) before concluding nobody is coming
    back. `worker_id`/`ring` (stats dict) ride on the exception for
    the parent's re-raise."""
    import time as time_mod

    from ..observability import metrics as _obs
    from ..testing import faults as _faults

    if _faults.ACTIVE is not None:
        _faults.fire('shm_push', worker_id=worker_id, timeout=timeout)
    sleep = sleep if sleep is not None else time_mod.sleep
    budget = max(timeout * 5, 300)
    delay = 0.0005
    waited = 0.0
    while not push():
        if waited >= budget:
            _obs.inc('io.shm_timeouts')
            raise ShmRingTimeout(
                f'shm ring full for {budget}s: consumer stalled or gone',
                waited_s=waited, budget_s=budget, worker_id=worker_id,
                ring=ring)
        # backoff tick: counts in THIS process's registry (a forked shm
        # worker's counts stay in the worker — the parent-side signal
        # for ring pressure is io.prefetch_wait_ms instead)
        _obs.inc('io.shm_backoff')
        sleep(delay)
        waited += delay
        delay = min(delay * 2, 0.05)


def _worker_loop_shm(dataset, index_queue, ring_name, collate_fn,
                     worker_id=0, num_workers=1, timeout=60):
    """Worker for the native shared-memory fast path: batches go through
    the C++ SPSC ring (one memcpy into shm) instead of a pickled pipe
    (ref: the reference's C++ DataLoader + shared-memory transport)."""
    import struct

    from .. import _native

    _worker_info[0] = WorkerInfo(worker_id, num_workers, dataset)
    ring = _native.ShmRing(name=ring_name, create=False)
    try:
        while True:
            task = index_queue.get()
            if task is None:
                break
            seq, idxs = task
            try:
                batch = collate_fn([dataset[i] for i in idxs])
                flat, spec = _flatten_batch(batch)
                payload = (struct.pack('<QB', seq, 0)
                           + struct.pack('<I', len(spec)) + spec
                           + _native.encode_batch(flat))
            except Exception as e:  # pragma: no cover
                msg = repr(e).encode()
                payload = struct.pack('<QB', seq, 1) + msg
            _push_with_backoff(
                lambda: ring.push(payload), timeout, worker_id=worker_id,
                ring={'name': ring_name, 'payload_bytes': len(payload)})
    finally:
        ring.close(unlink=False)


def _flatten_batch(batch):
    """Flatten nested (list/tuple/dict of) arrays → (arrays, json spec)."""
    import json

    flat = []

    def walk(x):
        if isinstance(x, dict):
            return {'__d__': {k: walk(v) for k, v in sorted(x.items())}}
        if isinstance(x, (list, tuple)):
            return {'__l__' if isinstance(x, list) else '__t__':
                    [walk(v) for v in x]}
        flat.append(np.asarray(x))
        return len(flat) - 1

    spec = walk(batch)
    return flat, json.dumps(spec).encode()


def _unflatten_batch(spec_bytes, flat):
    import json

    spec = json.loads(spec_bytes.decode())

    def walk(s):
        if isinstance(s, int):
            return flat[s]
        if '__d__' in s:
            return {k: walk(v) for k, v in s['__d__'].items()}
        if '__l__' in s:
            return [walk(v) for v in s['__l__']]
        return tuple(walk(v) for v in s['__t__'])

    return walk(spec)


class DataLoader:
    """ref: paddle.io.DataLoader."""

    def __init__(self, dataset, feed_list=None, places=None, return_list=True,
                 batch_sampler=None, batch_size=1, shuffle=False, drop_last=False,
                 collate_fn=None, num_workers=0, use_buffer_reader=True,
                 prefetch_factor=2, use_shared_memory=True, timeout=60,
                 worker_init_fn=None, persistent_workers=False):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = max(2, prefetch_factor)
        self.timeout = timeout
        self.use_shared_memory = use_shared_memory
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_size = batch_size
            self.drop_last = drop_last
            self.batch_sampler = None
        else:
            self.batch_sampler = batch_sampler or BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size, drop_last=drop_last
            )

    def __len__(self):
        if self._iterable_mode:
            raise TypeError('IterableDataset DataLoader has no len()')
        return len(self.batch_sampler)

    def __iter__(self):
        if self._iterable_mode:
            return self._iter_iterable()
        if self.num_workers == 0:
            return self._iter_inline()
        if self.use_shared_memory:
            from .. import _native

            if _native.AVAILABLE:
                return self._iter_workers_shm()
        return self._iter_workers()

    def _iter_iterable(self):
        batch = []
        for item in self.dataset:
            batch.append(item)
            if len(batch) == self.batch_size:
                yield self.collate_fn(batch)
                batch = []
        if batch and not self.drop_last:
            yield self.collate_fn(batch)

    def _iter_inline(self):
        for idxs in self.batch_sampler:
            yield self.collate_fn([self.dataset[i] for i in idxs])

    def _iter_workers(self):
        ctx = mp.get_context('fork')
        index_queue = ctx.Queue()
        data_queue = ctx.Queue()
        workers = [
            ctx.Process(
                target=_worker_loop,
                args=(self.dataset, index_queue, data_queue, self.collate_fn,
                      i, self.num_workers),
                daemon=True,
            )
            for i in range(self.num_workers)
        ]
        for w in workers:
            w.start()
        try:
            batches = list(self.batch_sampler)
            inflight = 0
            next_submit = 0
            max_inflight = self.num_workers * self.prefetch_factor
            reorder = {}
            next_yield = 0
            while next_submit < len(batches) and inflight < max_inflight:
                index_queue.put((next_submit, batches[next_submit]))
                next_submit += 1
                inflight += 1
            while next_yield < len(batches):
                if next_yield in reorder:
                    b = reorder.pop(next_yield)
                else:
                    seq, batch, err = data_queue.get(timeout=self.timeout)
                    inflight -= 1
                    if next_submit < len(batches):
                        index_queue.put((next_submit, batches[next_submit]))
                        next_submit += 1
                        inflight += 1
                    if err is not None:
                        raise RuntimeError(f'DataLoader worker failed: {err}')
                    if seq != next_yield:
                        reorder[seq] = batch
                        continue
                    b = batch
                yield b
                next_yield += 1
        finally:
            for _ in workers:
                index_queue.put(None)
            for w in workers:
                w.join(timeout=1)
                if w.is_alive():
                    w.terminate()

    def _iter_workers_shm(self):
        """Native fast path: per-worker C++ shm ring carries the batches."""
        import struct
        import time as time_mod

        from .. import _native
        from ..observability import metrics as _obs

        ctx = mp.get_context('fork')
        index_queue = ctx.Queue()
        rings = [_native.ShmRing(capacity=64 * 1024 * 1024, create=True)
                 for _ in range(self.num_workers)]
        workers = [
            ctx.Process(
                target=_worker_loop_shm,
                args=(self.dataset, index_queue, rings[i].name,
                      self.collate_fn, i, self.num_workers, self.timeout),
                daemon=True,
            )
            for i in range(self.num_workers)
        ]
        for w in workers:
            w.start()
        try:
            batches = list(self.batch_sampler)
            inflight = 0
            next_submit = 0
            max_inflight = self.num_workers * self.prefetch_factor
            reorder = {}
            next_yield = 0
            deadline_base = time_mod.time()
            death_scan_at = 0.0
            dead = {}                    # worker idx -> (pid, exitcode)
            while next_submit < len(batches) and inflight < max_inflight:
                index_queue.put((next_submit, batches[next_submit]))
                next_submit += 1
                inflight += 1
            while next_yield < len(batches):
                if next_yield in reorder:
                    b = reorder.pop(next_yield)
                    yield b
                    next_yield += 1
                    continue
                got_any = False
                for ring in rings:
                    payload = ring.pop()
                    if payload is None:
                        continue
                    got_any = True
                    deadline_base = time_mod.time()
                    seq, status = struct.unpack_from('<QB', payload, 0)
                    inflight -= 1
                    if next_submit < len(batches):
                        index_queue.put((next_submit, batches[next_submit]))
                        next_submit += 1
                        inflight += 1
                    if status == 1:
                        raise RuntimeError(
                            f'DataLoader worker failed: {payload[9:].decode()}')
                    (spec_len,) = struct.unpack_from('<I', payload, 9)
                    spec = payload[13:13 + spec_len]
                    flat = _native.decode_batch(payload[13 + spec_len:])
                    reorder[seq] = _unflatten_batch(spec, flat)
                if not got_any:
                    # a worker that exited non-zero mid-run died of an
                    # exception (a push timeout, an injected fault).
                    # When EVERY worker is gone no payload is ever
                    # coming — raise now with identity instead of
                    # burning the full consumer timeout on a silent
                    # ring. A PARTIAL death may be survivable (an idle
                    # victim held no popped batch, and the shared index
                    # queue lets the survivors finish the epoch), so it
                    # is only remembered here and named if the consumer
                    # really does stall out. The exitcode poll is a
                    # syscall per worker, so it runs at ~4 Hz rather
                    # than on every 0.5 ms idle tick
                    now = time_mod.time()
                    timed_out = now - deadline_base > self.timeout
                    if timed_out or now >= death_scan_at:
                        death_scan_at = now + 0.25
                        for i, w in enumerate(workers):
                            if (i not in dead and not w.is_alive()
                                    and w.exitcode not in (0, None)):
                                dead[i] = (w.pid, w.exitcode)
                    if timed_out or (dead and len(dead) == len(workers)):
                        _obs.inc('io.shm_timeouts')
                        if dead:
                            i = min(dead)
                            pid, code = dead[i]
                            raise ShmRingTimeout(
                                f'DataLoader shm worker {i} '
                                f'(pid {pid}) died with exitcode '
                                f'{code} — likely a ring push '
                                f'timeout or a fault in the worker '
                                f'(its stderr has the traceback)',
                                worker_id=i,
                                ring={'name': rings[i].name,
                                      'inflight': inflight})
                        raise ShmRingTimeout(
                            f'DataLoader shm timeout: no batch for '
                            f'{self.timeout}s with {inflight} in flight '
                            f'across {len(workers)} live worker(s)',
                            waited_s=now - deadline_base,
                            ring={'inflight': inflight})
                    time_mod.sleep(0.0005)
        finally:
            for _ in workers:
                index_queue.put(None)
            for w in workers:
                w.join(timeout=1)
                if w.is_alive():
                    w.terminate()
            for ring in rings:
                ring.close(unlink=True)


def prefetch_to_device(iterator, size=2, sharding=None):
    """Double-buffered device prefetch: keeps `size` batches resident in HBM
    ahead of consumption. The host thread stays `size` steps ahead;
    device_put is async so H2D DMA overlaps compute.

    `sharding` (e.g. distributed.sharding.data_sharding(mesh)) places
    every array leaf as a mesh-sharded GLOBAL array during the H2D copy
    — each device receives only its dp/fsdp shard of the batch, and the
    transfer still overlaps the in-flight step. Leaves with fewer dims
    than the spec needs (scalars riding along in a batch dict) fall back
    to the default replicated put instead of erroring.

    Telemetry: `io.prefetch_wait_ms` is the host time spent blocked on
    the UPSTREAM iterator (a loader that can't keep up shows here
    before it shows as device idle), `io.prefetch_depth` the batches
    currently staged in HBM, `io.prefetch_batches` the total served."""
    import time as time_mod

    import jax

    from ..observability import metrics as _obs

    def pull(it):
        t0 = time_mod.perf_counter()
        batch = next(it)                 # StopIteration propagates
        _obs.observe('io.prefetch_wait_ms',
                     (time_mod.perf_counter() - t0) * 1e3)
        return batch

    def put(batch):
        if sharding is not None:
            ndim_needed = len(getattr(sharding, 'spec', ()) or ())

            def place(x):
                if getattr(x, 'ndim', 0) >= ndim_needed:
                    return jax.device_put(x, sharding)
                return jax.device_put(x)

            return jax.tree.map(place, batch)
        return jax.tree.map(jax.device_put, batch)

    buf = []
    it = iter(iterator)
    try:
        for _ in range(size):
            buf.append(put(pull(it)))
    except StopIteration:
        pass
    while buf:
        out = buf.pop(0)
        try:
            buf.append(put(pull(it)))
        except StopIteration:
            pass
        _obs.set_gauge('io.prefetch_depth', len(buf))
        _obs.inc('io.prefetch_batches')
        yield out


class SubsetRandomSampler(Sampler):
    """ref: paddle.io.SubsetRandomSampler."""

    def __init__(self, indices, generator=None):
        self.indices = list(indices)

    def __iter__(self):
        import numpy as _np

        order = _np.random.permutation(len(self.indices))
        return iter(self.indices[i] for i in order)

    def __len__(self):
        return len(self.indices)


class WorkerInfo:
    """ref: paddle.io.get_worker_info return type."""

    def __init__(self, id, num_workers, dataset=None, seed=0):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset
        self.seed = seed


_worker_info = [None]


def get_worker_info():
    """ref: paddle.io.get_worker_info — None in the main process, worker
    metadata inside a DataLoader worker."""
    return _worker_info[0]
