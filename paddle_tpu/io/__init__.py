"""Data pipeline (ref: python/paddle/io/__init__.py).

Paddle's DataLoader: C++ worker pool → pinned buffers → async H2D copy.
TPU-native: Python/multiprocess workers producing numpy batches → a
double-buffered `jax.device_put` prefetcher that overlaps host→HBM DMA
with the running step (XLA's async dispatch gives the overlap for free).
"""
from .dataset import (  # noqa: F401
    ChainDataset,
    ComposeDataset,
    ConcatDataset,
    Dataset,
    IterableDataset,
    Subset,
    TensorDataset,
    random_split,
)
from .dataloader import (  # noqa: F401
    BatchSampler,
    DataLoader,
    DistributedBatchSampler,
    RandomSampler,
    Sampler,
    SequenceSampler,
    ShmRingTimeout,
    SubsetRandomSampler,
    WeightedRandomSampler,
    default_collate_fn,
    get_worker_info,
)
