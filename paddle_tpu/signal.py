"""Signal ops (ref: python/paddle/signal.py): frame, overlap_add, stft, istft."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def frame(x, frame_length, hop_length, axis=-1):
    """Slice overlapping frames (ref: paddle.signal.frame)."""
    if axis not in (-1, x.ndim - 1):
        x = jnp.moveaxis(x, axis, -1)
    n = x.shape[-1]
    num_frames = 1 + (n - frame_length) // hop_length
    idx = (jnp.arange(frame_length)[None, :]
           + hop_length * jnp.arange(num_frames)[:, None])
    out = x[..., idx]                     # (..., num_frames, frame_length)
    out = jnp.swapaxes(out, -1, -2)       # (..., frame_length, num_frames)
    if axis not in (-1, x.ndim - 1):
        out = jnp.moveaxis(out, -1, axis)
    return out


def overlap_add(x, hop_length, axis=-1):
    """Inverse of frame (ref: paddle.signal.overlap_add).
    x: (..., frame_length, num_frames)."""
    if axis not in (-1, x.ndim - 1):
        x = jnp.moveaxis(x, axis, -1)
    frame_length, num_frames = x.shape[-2], x.shape[-1]
    n = frame_length + hop_length * (num_frames - 1)
    out = jnp.zeros(x.shape[:-2] + (n,), x.dtype)
    for i in range(num_frames):          # static unroll — num_frames is static
        out = out.at[..., i * hop_length:i * hop_length + frame_length].add(
            x[..., i])
    return out


def stft(x, n_fft, hop_length=None, win_length=None, window=None, center=True,
         pad_mode='reflect', normalized=False, onesided=True, name=None):
    """ref: paddle.signal.stft. x: (..., T) real → (..., F, num_frames) complex."""
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    if window is None:
        window = jnp.ones((win_length,))
    if win_length < n_fft:
        pad = (n_fft - win_length) // 2
        window = jnp.pad(window, (pad, n_fft - win_length - pad))
    if center:
        pad = n_fft // 2
        x = jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(pad, pad)], mode=pad_mode)
    frames = frame(x, n_fft, hop_length)              # (..., n_fft, num_frames)
    frames = frames * window[:, None]
    spec = (jnp.fft.rfft(frames, axis=-2) if onesided
            else jnp.fft.fft(frames, axis=-2))
    if normalized:
        spec = spec / jnp.sqrt(n_fft)
    return spec


def istft(x, n_fft, hop_length=None, win_length=None, window=None, center=True,
          normalized=False, onesided=True, length=None, return_complex=False,
          name=None):
    """ref: paddle.signal.istft."""
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    if window is None:
        window = jnp.ones((win_length,))
    if win_length < n_fft:
        pad = (n_fft - win_length) // 2
        window = jnp.pad(window, (pad, n_fft - win_length - pad))
    if normalized:
        x = x * jnp.sqrt(n_fft)
    frames = (jnp.fft.irfft(x, n=n_fft, axis=-2) if onesided
              else jnp.fft.ifft(x, axis=-2).real)
    frames = frames * window[:, None]
    out = overlap_add(frames, hop_length)
    # window envelope normalisation
    wsq = jnp.tile((window ** 2)[:, None], (1, x.shape[-1]))
    env = overlap_add(wsq, hop_length)
    out = out / jnp.maximum(env, 1e-10)
    if center:
        out = out[..., n_fft // 2:-(n_fft // 2) or None]
    if length is not None:
        out = out[..., :length]
    return out
