"""paddle_tpu.jit — graph capture & compile (ref: python/paddle/jit).

Paddle: @to_static traces Python → ProgramDesc → PIR passes → CINN → CUDA.
Here: @to_static traces via jax → StableHLO → XLA:TPU. One decorator, the
whole compiler stack is XLA's.

`jit.save`/`jit.load` export params (npz) + the StableHLO module text
(via jax.export) — the TPU-native analogue of the inference Program
Paddle serialises.
"""
from __future__ import annotations

import functools
import os

import jax
import numpy as np


class InputSpec:
    """ref: paddle.static.InputSpec."""

    def __init__(self, shape, dtype='float32', name=None):
        from ..framework import dtype as dtype_mod

        self.shape = tuple(shape)
        self.dtype = dtype_mod.convert_dtype(dtype)
        self.name = name

    def to_shape_struct(self):
        shape = tuple(1 if s in (None, -1) else s for s in self.shape)
        return jax.ShapeDtypeStruct(shape, self.dtype)


class StaticFunction:
    """Compiled wrapper around a fn or Layer (ref: jit/dy2static 'StaticFunction')."""

    def __init__(self, fn, input_spec=None, donate_argnums=(), static_argnums=None, backend=None):
        self._fn = fn
        self._input_spec = input_spec
        self._is_layer = not callable(fn) or hasattr(fn, 'forward')
        from ..nn.layer.base import Layer

        self._layer = fn if isinstance(fn, Layer) else None
        if self._layer is not None:
            layer = self._layer

            def call(model, *args, **kwargs):
                return model(*args, **kwargs)

            # cached on self: a StaticFunction wraps one callable for
            # its lifetime, so the jit (and its trace cache) is built
            # exactly once here
            # tracelint: disable=TL001
            self._jitted = jax.jit(call, donate_argnums=donate_argnums)
        else:
            # tracelint: disable=TL001 - cached on self (see above)
            self._jitted = jax.jit(fn, donate_argnums=donate_argnums,
                                   static_argnums=static_argnums)
        functools.update_wrapper(self, fn if callable(fn) else fn.forward)

    def __call__(self, *args, **kwargs):
        if self._layer is not None:
            return self._jitted(self._layer, *args, **kwargs)
        return self._jitted(*args, **kwargs)

    @property
    def forward(self):
        return self

    def concrete_program(self, *args):
        return self._jitted.lower(*args)


def to_static(function=None, input_spec=None, build_strategy=None, backend=None,
              full_graph=True, donate_argnums=(), static_argnums=None, **kwargs):
    """Decorator/wrapper: compile a function or Layer with XLA
    (ref: paddle.jit.to_static)."""

    def wrap(fn):
        return StaticFunction(fn, input_spec, donate_argnums, static_argnums, backend)

    if function is not None:
        return wrap(function)
    return wrap


def not_to_static(fn):
    fn._not_to_static = True
    return fn


def ignore_module(modules):
    return None


def save(obj, path, input_spec=None, **config):
    """Export a Layer or StaticFunction: weights (.npz) + StableHLO (.mlir)
    (ref: paddle.jit.save → __model__ + params)."""
    from ..framework.io import save as save_state
    from ..nn.layer.base import Layer

    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    layer = obj._layer if isinstance(obj, StaticFunction) else obj
    if isinstance(layer, Layer):
        save_state(layer.state_dict(), path + '.pdiparams')
    if input_spec:
        structs = [
            s.to_shape_struct() if isinstance(s, InputSpec) else jax.ShapeDtypeStruct(s.shape, s.dtype)
            for s in input_spec
        ]
        if isinstance(layer, Layer):
            eval_layer = layer.eval() if hasattr(layer, 'eval') else layer

            def fwd(*xs):
                return eval_layer(*xs)

            # tracelint: disable=TL001 - one-shot export, not a hot path
            exported = jax.export.export(jax.jit(fwd))(*structs)
        else:
            fn = obj._fn if isinstance(obj, StaticFunction) else obj
            # tracelint: disable=TL001 - one-shot export, not a hot path
            exported = jax.export.export(jax.jit(fn))(*structs)
        with open(path + '.mlir', 'wb') as f:
            # the FULL Exported flatbuffer (what jax.export.deserialize
            # reads back) — not just mlir_module_serialized, which loses
            # the calling convention and cannot be restored
            f.write(exported.serialize())
        with open(path + '.pdmodel.txt', 'w') as f:
            f.write(str(exported.mlir_module()))


def load(path, **config):
    """Load a jit.save'd artifact. Returns a callable running the exported
    StableHLO if present, else the raw state dict."""
    from ..framework.io import load as load_state

    mlir_path = path + '.mlir'
    params_path = path + '.pdiparams'
    state = load_state(params_path) if os.path.exists(params_path) else None
    if os.path.exists(mlir_path):
        with open(mlir_path, 'rb') as f:
            exported = jax.export.deserialize(bytearray(f.read()))

        class LoadedFunction(TranslatedLayer):
            def __init__(self):
                self.state_dict_ = state

            def __call__(self, *args):
                return exported.call(*args)

            def state_dict(self):
                return self.state_dict_

        return LoadedFunction()
    return state


def enable_to_static(flag=True):
    return None


def enable_compilation_cache(cache_dir='~/.cache/paddle_tpu/xla_cache',
                             min_compile_time_secs=1.0):
    """AOT compile cache (ref capability: CINN compile cache + Paddle's
    program cache). Wires jax's persistent compilation cache so repeat
    runs skip XLA compilation entirely.

    Delegates to sysconfig.enable_persistent_compilation_cache — the
    ONE place that owns the wiring (explicit directory, telemetry
    instant/gauge, and the reset of jax's once-per-process cache-used
    verdict, without which enabling after any compile silently never
    persists; paddle_tpu.aot artifacts depend on all three) — then
    re-raises the persistence threshold to `min_compile_time_secs`
    (this entry point's contract: only compilations worth caching)."""
    import jax

    from ..sysconfig import enable_persistent_compilation_cache

    path = enable_persistent_compilation_cache(
        os.path.expanduser(cache_dir))
    if path is not None and min_compile_time_secs:
        jax.config.update('jax_persistent_cache_min_compile_time_secs',
                          min_compile_time_secs)
    return path


def compilation_report(fn, *example_args, **kw):
    """Compile-time reporting (ref: @to_static build reporting): returns
    {compile_time_s, flops, bytes, hlo_text_head}."""
    import time

    from ..observability.costs import analyze

    # tracelint: disable=TL001 - one-shot compile-time report
    jitted = jax.jit(fn, **kw)
    t0 = time.perf_counter()
    lowered = jitted.lower(*example_args)
    compiled = lowered.compile()
    dt = time.perf_counter() - t0
    # quirk handling (list-vs-dict, raising backends) lives in
    # observability.costs.analyze, shared with profiler.op_summary and
    # the AOT manifest cost stamps
    cost = analyze(compiled)
    return {
        'compile_time_s': dt,
        'flops': cost['flops'] or 0,
        'bytes_accessed': cost['bytes_accessed'] or 0,
        'hlo_head': compiled.as_text()[:2000] if hasattr(compiled, 'as_text') else '',
    }


# `jit.load` returns this callable wrapper; the reference's equivalent
# class is TranslatedLayer (ref: python/paddle/jit/translated_layer.py)
TranslatedLayer = type('TranslatedLayer', (), {})  # isinstance marker base

_sot_verbosity = [0]


def set_verbosity(level=0, also_to_stdout=False):
    """ref: paddle.jit.set_verbosity — tracing has no bytecode
    translator here; the knob stores intent for debugging hooks."""
    _sot_verbosity[0] = level


def set_code_level(level=100, also_to_stdout=False):
    """ref: paddle.jit.set_code_level (SOT bytecode dump — N/A under
    jax tracing; kept for script compatibility)."""
    _sot_verbosity[0] = level
