"""paddle_tpu.optimizer (ref: python/paddle/optimizer/__init__.py)."""
from . import lr  # noqa: F401
from .lbfgs import LBFGS  # noqa: F401
from .optimizer import Optimizer  # noqa: F401
from .wrappers import (ExponentialMovingAverage, GradientMerge,  # noqa: F401
                       LookAhead)
from .optimizers import (  # noqa: F401
    ASGD,
    Adadelta,
    Adagrad,
    Adam,
    Adamax,
    AdamW,
    Lamb,
    Momentum,
    NAdam,
    RAdam,
    RMSProp,
    Rprop,
    SGD,
)
