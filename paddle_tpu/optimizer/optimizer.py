"""Optimizer base (ref: python/paddle/optimizer/optimizer.py).

Functional core, Paddle surface. The whole update is one fused jitted
tree-map — the TPU-native equivalent of Paddle's multi_tensor/fused_adam
paths (XLA fuses the per-parameter lambdas into a handful of kernels).

Usage (inside a jitted train step):
    opt = AdamW(learning_rate=3e-4, weight_decay=0.01)
    state = opt.init(model)
    ...
    model, state = opt.apply_gradients(model, grads, state)

`multi_precision=True` keeps fp32 master weights for bf16 params
(ref: optimizer.py::_multi_precision logic).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.tree import merge, split_trainable
from .lr import LRScheduler


def _tmap(fn, *trees):
    return jax.tree.map(fn, *trees)


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        self._lr = learning_rate
        # weight_decay: float (coupled L2, or decoupled in AdamW) or a
        # paddle_tpu.regularizer.L1Decay/L2Decay object
        self._wd_regularizer = None
        if weight_decay is not None and not isinstance(weight_decay, (int, float)):
            self._wd_regularizer = weight_decay
            weight_decay = 0.0
        self._weight_decay = 0.0 if weight_decay is None else weight_decay
        self._decoupled_decay = False
        self.grad_clip = grad_clip
        self.multi_precision = multi_precision
        self._model_ref = parameters
        self.state = None
        # dygraph binding: `parameters=net.parameters()` (a ParamList
        # carrying its owner) or the Layer itself flips the module into
        # eager-tape mode so loss.backward()/opt.step() work — the
        # reference's imperative loop (ref: optimizer.py dygraph mode)
        self._bound_layer = None
        if parameters is not None:
            owner = getattr(parameters, 'owner', None)
            if owner is None:
                from ..nn.layer.base import Layer

                if isinstance(parameters, Layer):
                    owner = parameters
            if owner is not None:
                self._bound_layer = owner
                owner.__dict__['_dygraph'] = True

    # -- lr ---------------------------------------------------------------
    def get_lr(self, step=0):
        if isinstance(self._lr, LRScheduler):
            return self._lr(step)
        return jnp.asarray(self._lr, jnp.float32)

    def set_lr(self, lr):
        self._lr = lr

    @property
    def _learning_rate(self):
        return self._lr

    # -- functional API ---------------------------------------------------
    def init(self, model):
        """Build optimizer state for the trainable partition of `model`."""
        t, _ = split_trainable(model)
        state = {
            'step': jnp.zeros((), jnp.int32),
            'slots': self.init_slots(t),
        }
        if self.multi_precision:
            state['master'] = _tmap(
                lambda p: p.astype(jnp.float32) if p.dtype == jnp.bfloat16 else None, t
            )
        self.state = state
        return state

    def init_slots(self, trainable):  # per-optimizer moment slots
        return {}

    def update_param(self, p, g, slots, lr, step):  # pragma: no cover - abstract
        raise NotImplementedError

    def apply_gradients(self, model, grads, state=None, lr=None):
        """Returns (new_model, new_state). `grads` is the tree returned by
        autograd.value_and_grad (trainable-shaped). `lr` overrides the
        stored rate for this step — pass it as a TRACED argument when the
        update runs under jit and the rate must change between calls
        without retracing (hapi does this so set_lr / ReduceLROnPlateau
        take effect inside the compiled step)."""
        state = state if state is not None else self.state
        t, f = split_trainable(model)
        if self.grad_clip is not None:
            grads = self.grad_clip(grads)
        step = state['step'] + 1
        lr = self.get_lr(step) if lr is None else jnp.asarray(lr, jnp.float32)
        master = state.get('master')

        # coupled L2 (SGD/Momentum-style regularizer): g += wd * p
        if self._weight_decay and not self._decoupled_decay:
            wd = self._weight_decay
            grads = _tmap(lambda g, p: g + wd * p.astype(g.dtype), grads, t)
        if self._wd_regularizer is not None:
            reg = self._wd_regularizer
            grads = _tmap(
                lambda g, p: g + reg.grad_term(p).astype(g.dtype), grads, t)

        def upd(p, g, *slot_leaves):
            return None  # placeholder; real work below via packed trees

        slots = state['slots']
        new_t, new_slots, new_master = self._apply_tree(t, grads, slots, master, lr, step)
        new_state = {'step': step, 'slots': new_slots}
        if master is not None:
            new_state['master'] = new_master
        new_model = merge(new_t, f)
        self.state = new_state
        return new_model, new_state

    def _apply_tree(self, t, grads, slots, master, lr, step):
        # slots: dict name -> tree shaped like t
        slot_names = list(slots.keys())
        slot_trees = [slots[k] for k in slot_names]

        def leaf_update(p, g, m, *slot_leaves):
            if g is None:
                return (p,) + tuple(slot_leaves) + (m,)
            compute_p = m if m is not None else p.astype(jnp.float32)
            gf = g.astype(jnp.float32)
            if self._weight_decay and self._decoupled_decay:
                compute_p = compute_p - lr * self._weight_decay * compute_p
            new_p, new_slots_ = self.update_param(
                compute_p, gf, dict(zip(slot_names, slot_leaves)), lr, step
            )
            out_slots = tuple(new_slots_[k] for k in slot_names)
            if m is not None:
                return (new_p.astype(p.dtype),) + out_slots + (new_p,)
            return (new_p.astype(p.dtype),) + out_slots + (None,)

        if master is None:
            master = _tmap(lambda p: None, t)

        # tree.map over multiple trees with identical structure; None leaves in
        # grads align with None in t's frozen slots (both empty nodes).
        packed = jax.tree.map(
            lambda p, g, m, *sl: leaf_update(p, g, m, *sl),
            t, grads, master, *slot_trees,
            is_leaf=lambda x: x is None,
        )

        k = len(slot_names)

        def pick(i):
            return jax.tree.map(
                lambda tup: tup[i], packed,
                is_leaf=lambda x: isinstance(x, tuple) and len(x) == k + 2,
            )

        new_t = pick(0)
        new_slots = {name: pick(1 + i) for i, name in enumerate(slot_names)}
        new_master = pick(k + 1)
        return new_t, new_slots, new_master

    # -- paddle-style imperative conveniences ------------------------------
    def step(self):
        """Dygraph update: consume the grads `loss.backward()` deposited
        on the bound Layer and write updated params back in place."""
        layer = self._bound_layer
        if layer is None:
            raise RuntimeError(
                'opt.step() needs a bound module: construct the optimizer '
                'with parameters=net.parameters() (dygraph), or use '
                'model, state = opt.apply_gradients(model, grads, state) '
                'inside your (jitted) train step.'
            )
        grads = layer.__dict__.get('_param_grads')
        if grads is None:
            raise RuntimeError(
                'opt.step() found no gradients: call loss.backward() first '
                '(and construct the loss from the bound model\'s outputs)')
        if self.state is None:
            self.init(layer)
        lr = None
        if isinstance(self._lr, LRScheduler):
            lr = self._lr.get_lr()      # host epoch state (sched.step())
        new_model, _ = self.apply_gradients(layer, grads, self.state, lr=lr)
        from ..autograd.eager import _write_back

        _write_back(layer, new_model)

    def clear_grad(self):
        if self._bound_layer is not None:
            self._bound_layer.__dict__['_param_grads'] = None
        return None

    def state_dict(self):
        return self.state

    def set_state_dict(self, state):
        self.state = state
