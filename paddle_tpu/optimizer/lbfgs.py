"""L-BFGS optimizer (ref: python/paddle/optimizer/lbfgs.py).

The reference mutates parameters in place inside a closure-driven loop;
here the step is functional — `step(closure, model)` returns the updated
model — but the algorithm is the same: limited-memory two-loop recursion
over the last `history_size` (s, y) pairs, optional strong-Wolfe cubic
line search. Control flow runs on the host (L-BFGS is an eager,
full-batch method: each iteration is data-dependent, so there is nothing
for XLA to pipeline), while every loss/grad evaluation is a jitted jax
call over the flattened trainable vector.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.tree import merge, split_trainable


def _cubic_interpolate(x1, f1, g1, x2, f2, g2, bounds=None):
    """Argmin of the cubic fitting (x1,f1,g1),(x2,f2,g2), clipped to
    bounds — the safeguarded interpolation classic line searches use."""
    if bounds is not None:
        lo, hi = bounds
    else:
        lo, hi = (x1, x2) if x1 <= x2 else (x2, x1)
    d1 = g1 + g2 - 3 * (f1 - f2) / (x1 - x2)
    d2_sq = d1 * d1 - g1 * g2
    if d2_sq >= 0:
        d2 = np.sqrt(d2_sq)
        if x1 <= x2:
            t = x2 - (x2 - x1) * ((g2 + d2 - d1) / (g2 - g1 + 2 * d2))
        else:
            t = x1 - (x1 - x2) * ((g1 + d2 - d1) / (g1 - g2 + 2 * d2))
        return float(min(max(t, lo), hi))
    return float((lo + hi) / 2.0)


def _strong_wolfe(fdir, t, d_norm, f0, g0, c1=1e-4, c2=0.9,
                  tolerance_change=1e-9, max_ls=25):
    """Strong-Wolfe line search along a fixed direction.

    fdir(t) -> (f, directional_derivative). Returns (f, t, n_evals).
    Bracketing + zoom with cubic interpolation (the same scheme the
    reference's `_strong_wolfe` implements).
    """
    f_prev, g_prev, t_prev = f0, g0, 0.0
    f_new, g_new = fdir(t)
    ls_iter = 1
    bracket = None
    while ls_iter < max_ls:
        if f_new > f0 + c1 * t * g0 or (ls_iter > 1 and f_new >= f_prev):
            bracket = (t_prev, f_prev, g_prev, t, f_new, g_new)
            break
        if abs(g_new) <= -c2 * g0:
            return f_new, t, ls_iter
        if g_new >= 0:
            bracket = (t, f_new, g_new, t_prev, f_prev, g_prev)
            break
        t_next = _cubic_interpolate(t_prev, f_prev, g_prev, t, f_new, g_new,
                                    bounds=(1.01 * t, 10 * t))
        t_prev, f_prev, g_prev = t, f_new, g_new
        t = t_next
        f_new, g_new = fdir(t)
        ls_iter += 1
    if bracket is None:  # ran out of expansion budget
        return f_new, t, ls_iter

    lo_t, lo_f, lo_g, hi_t, hi_f, hi_g = bracket
    insuf_progress = False
    while ls_iter < max_ls:
        if abs(hi_t - lo_t) * d_norm < tolerance_change:
            break
        t = _cubic_interpolate(lo_t, lo_f, lo_g, hi_t, hi_f, hi_g)
        # keep the trial point meaningfully interior
        eps = 0.1 * abs(hi_t - lo_t)
        span_lo, span_hi = min(lo_t, hi_t), max(lo_t, hi_t)
        if min(t - span_lo, span_hi - t) < eps:
            if insuf_progress or t >= span_hi or t <= span_lo:
                t = span_hi - eps if abs(t - span_hi) < abs(t - span_lo) \
                    else span_lo + eps
                insuf_progress = False
            else:
                insuf_progress = True
        else:
            insuf_progress = False
        f_new, g_new = fdir(t)
        ls_iter += 1
        if f_new > f0 + c1 * t * g0 or f_new >= lo_f:
            hi_t, hi_f, hi_g = t, f_new, g_new
        else:
            if abs(g_new) <= -c2 * g0:
                return f_new, t, ls_iter
            if g_new * (hi_t - lo_t) >= 0:
                hi_t, hi_f, hi_g = lo_t, lo_f, lo_g
            lo_t, lo_f, lo_g = t, f_new, g_new
    return lo_f, lo_t, ls_iter


class LBFGS:
    """ref: python/paddle/optimizer/lbfgs.py::LBFGS.

    Usage:
        opt = LBFGS(learning_rate=1.0, line_search_fn='strong_wolfe')
        for _ in range(outer_steps):
            loss, model = opt.step(closure, model)   # closure(model)->loss
    """

    def __init__(self, learning_rate=1.0, max_iter=20, max_eval=None,
                 tolerance_grad=1e-7, tolerance_change=1e-9,
                 history_size=100, line_search_fn=None, parameters=None,
                 name=None):
        if line_search_fn not in (None, 'strong_wolfe'):
            raise ValueError(f'unsupported line_search_fn: {line_search_fn}')
        self.lr = float(learning_rate)
        self.max_iter = max_iter
        self.max_eval = max_eval if max_eval is not None else max_iter * 5 // 4
        self.tolerance_grad = tolerance_grad
        self.tolerance_change = tolerance_change
        self.history_size = history_size
        self.line_search_fn = line_search_fn
        # persistent across step() calls, like the reference's state dict
        self._old_dirs: list[np.ndarray] = []
        self._old_stps: list[np.ndarray] = []
        self._ro: list[float] = []
        self._H_diag = 1.0
        self._prev_flat_grad = None
        self._d = None          # last search direction (persists across steps)
        self._t = None          # last accepted step length
        self._n_iter = 0

    def _flatten(self, model):
        t, f = split_trainable(model)
        leaves, treedef = jax.tree.flatten(t)
        shapes = [l.shape for l in leaves]
        sizes = [int(np.prod(s)) if len(s) else 1 for s in shapes]

        def unflatten(vec):
            out, off = [], 0
            for s, n, proto in zip(shapes, sizes, leaves):
                out.append(vec[off:off + n].reshape(s).astype(proto.dtype))
                off += n
            return merge(jax.tree.unflatten(treedef, out), f)

        vec = jnp.concatenate([l.astype(jnp.float32).ravel()
                               for l in leaves]) if leaves else jnp.zeros((0,))
        return vec, unflatten

    def step(self, closure, model):
        """One outer L-BFGS step (up to `max_iter` inner iterations).
        `closure(model) -> scalar loss` must be re-evaluable (it is called
        again during the line search). Returns (initial_loss, new_model).
        """
        from ..autograd import value_and_grad

        x0, unflatten = self._flatten(model)

        # one compile per (closure, param structure) — NOT per step() call;
        # recompiling each outer step would dominate the runtime. The
        # closure is held by strong reference (`is`, not id()) so a freed
        # closure can never alias a new one; note the cached function also
        # captures the first call's non-trainable leaves, which is sound
        # because LBFGS closures are pure objectives.
        cache_key = (x0.shape, str(x0.dtype))
        if (getattr(self, '_fg_closure', None) is closure
                and getattr(self, '_fg_key', None) == cache_key):
            f_and_g = self._fg
        else:
            # tracelint: disable=TL001 - cached on self under _fg_key
            # (the `is` check above): one trace per (closure, shape)
            @jax.jit
            def f_and_g(vec):
                m = unflatten(vec)
                loss, grads = value_and_grad(closure)(m)
                gleaves = jax.tree.leaves(grads)
                flat = (jnp.concatenate([g.astype(jnp.float32).ravel()
                                         for g in gleaves])
                        if gleaves else jnp.zeros_like(vec))
                return loss.astype(jnp.float32), flat

            self._fg_key, self._fg = cache_key, f_and_g
            self._fg_closure = closure

        x = np.asarray(x0, np.float64)
        loss, flat_grad = f_and_g(jnp.asarray(x, jnp.float32))
        orig_loss = float(loss)
        loss = orig_loss
        flat_grad = np.asarray(flat_grad, np.float64)
        current_evals = 1
        if np.abs(flat_grad).max() <= self.tolerance_grad:
            return jnp.asarray(orig_loss), unflatten(jnp.asarray(x, jnp.float32))

        d, t = self._d, self._t
        for _ in range(self.max_iter):
            self._n_iter += 1
            if self._n_iter == 1 or self._prev_flat_grad is None:
                d = -flat_grad
                self._old_dirs, self._old_stps, self._ro = [], [], []
                self._H_diag = 1.0
            else:
                y = flat_grad - self._prev_flat_grad
                s = d * t
                ys = float(y @ s)
                if ys > 1e-10:
                    if len(self._old_dirs) == self.history_size:
                        self._old_dirs.pop(0)
                        self._old_stps.pop(0)
                        self._ro.pop(0)
                    self._old_dirs.append(y)
                    self._old_stps.append(s)
                    self._ro.append(1.0 / ys)
                    self._H_diag = ys / float(y @ y)
                # two-loop recursion
                num = len(self._old_dirs)
                q = -flat_grad
                al = [0.0] * num
                for i in range(num - 1, -1, -1):
                    al[i] = float(self._old_stps[i] @ q) * self._ro[i]
                    q = q - al[i] * self._old_dirs[i]
                d = q * self._H_diag
                for i in range(num):
                    be_i = float(self._old_dirs[i] @ d) * self._ro[i]
                    d = d + self._old_stps[i] * (al[i] - be_i)
            self._prev_flat_grad = flat_grad.copy()
            prev_loss = loss

            gtd = float(flat_grad @ d)
            if gtd > -self.tolerance_change:
                break
            if self._n_iter == 1:
                t = min(1.0, 1.0 / np.abs(flat_grad).sum()) * self.lr
            else:
                t = self.lr

            if self.line_search_fn == 'strong_wolfe':
                def fdir(tt):
                    fv, gv = f_and_g(jnp.asarray(x + tt * d, jnp.float32))
                    # strong-Wolfe brackets on host floats: the line
                    # search is host-driven by definition, one sync per
                    # objective evaluation is the algorithm.
                    # tracelint: disable=TL002 - host-driven line search
                    return float(fv), float(np.asarray(gv, np.float64) @ d)

                d_norm = np.abs(d).max()
                loss, t, ls_evals = _strong_wolfe(
                    fdir, t, d_norm, loss, gtd,
                    tolerance_change=self.tolerance_change)
                current_evals += ls_evals
                x = x + t * d
                _, flat_grad = f_and_g(jnp.asarray(x, jnp.float32))
                flat_grad = np.asarray(flat_grad, np.float64)
            else:
                x = x + t * d
                lv, gv = f_and_g(jnp.asarray(x, jnp.float32))
                # tracelint: disable=TL002 - host-driven optimizer step
                loss, flat_grad = float(lv), np.asarray(gv, np.float64)
                current_evals += 1

            if current_evals >= self.max_eval:
                break
            if np.abs(flat_grad).max() <= self.tolerance_grad:
                break
            if np.abs(t * d).max() <= self.tolerance_change and \
                    abs(loss - prev_loss) < self.tolerance_change:
                break

        self._d, self._t = d, t
        return jnp.asarray(orig_loss), unflatten(jnp.asarray(x, jnp.float32))
