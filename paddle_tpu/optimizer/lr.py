"""LR schedulers (ref: python/paddle/optimizer/lr.py).

Each scheduler is a pure function of the (traced) step count —
`sched(step) -> lr` — so the whole schedule lives inside the compiled
train step (no host→device sync per step, unlike the reference's
Python-side `lr_scheduler.step()`). A Paddle-style `.step()/.get_lr()`
shim is provided for imperative code.
"""
from __future__ import annotations

import math

import jax.numpy as jnp


class LRScheduler:
    # Traceable schedules are evaluated INSIDE the compiled train step
    # from the device step counter (training/engine.py) — zero host work
    # per step. Subclasses whose rate genuinely cannot be a pure
    # function of the step (metric-driven, host-stateful) set this False
    # and the engine threads their host rate in as a traced argument
    # instead (still no retrace, but the host computes it).
    traceable = True

    def __init__(self, learning_rate=0.1, last_epoch=-1, verbose=False):
        self.base_lr = learning_rate
        self.last_epoch = last_epoch
        self._host_step = 0

    def __call__(self, step):
        return self.get_lr_at(step)

    def get_lr_at(self, step):  # pragma: no cover - abstract
        raise NotImplementedError

    # imperative shims
    def step(self, epoch=None):
        self._host_step = self._host_step + 1 if epoch is None else epoch

    def get_lr(self):
        return float(self.get_lr_at(jnp.asarray(self._host_step, jnp.float32)))

    def state_dict(self):
        return {'host_step': self._host_step}

    def set_state_dict(self, state):
        self._host_step = int(state.get('host_step', 0))


class NoamDecay(LRScheduler):
    def __init__(self, d_model, warmup_steps, learning_rate=1.0, last_epoch=-1, verbose=False):
        super().__init__(learning_rate, last_epoch, verbose)
        self.d_model, self.warmup_steps = d_model, warmup_steps

    def get_lr_at(self, step):
        s = jnp.maximum(step.astype(jnp.float32) if hasattr(step, 'astype') else jnp.float32(step), 1.0)
        return self.base_lr * self.d_model ** -0.5 * jnp.minimum(s ** -0.5, s * self.warmup_steps ** -1.5)


class ExponentialDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        super().__init__(learning_rate, last_epoch, verbose)
        self.gamma = gamma

    def get_lr_at(self, step):
        return self.base_lr * jnp.power(self.gamma, _f(step))


class NaturalExpDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        super().__init__(learning_rate, last_epoch, verbose)
        self.gamma = gamma

    def get_lr_at(self, step):
        return self.base_lr * jnp.exp(-self.gamma * _f(step))


class InverseTimeDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        super().__init__(learning_rate, last_epoch, verbose)
        self.gamma = gamma

    def get_lr_at(self, step):
        return self.base_lr / (1 + self.gamma * _f(step))


class PolynomialDecay(LRScheduler):
    def __init__(self, learning_rate, decay_steps, end_lr=0.0001, power=1.0,
                 cycle=False, last_epoch=-1, verbose=False):
        super().__init__(learning_rate, last_epoch, verbose)
        self.decay_steps, self.end_lr, self.power, self.cycle = decay_steps, end_lr, power, cycle

    def get_lr_at(self, step):
        s = _f(step)
        if self.cycle:
            div = jnp.ceil(jnp.maximum(s / self.decay_steps, 1.0))
            decay_steps = self.decay_steps * div
        else:
            decay_steps = self.decay_steps
            s = jnp.minimum(s, decay_steps)
        return (self.base_lr - self.end_lr) * jnp.power(1 - s / decay_steps, self.power) + self.end_lr


class LinearWarmup(LRScheduler):
    def __init__(self, learning_rate, warmup_steps, start_lr, end_lr, last_epoch=-1, verbose=False):
        super().__init__(end_lr, last_epoch, verbose)
        self.inner = learning_rate if isinstance(learning_rate, LRScheduler) else None
        self.peak = learning_rate if not isinstance(learning_rate, LRScheduler) else None
        self.warmup_steps, self.start_lr, self.end_lr = warmup_steps, start_lr, end_lr

    def get_lr_at(self, step):
        s = _f(step)
        warm = self.start_lr + (self.end_lr - self.start_lr) * jnp.minimum(s, self.warmup_steps) / self.warmup_steps
        if self.inner is not None:
            after = self.inner(jnp.maximum(s - self.warmup_steps, 0))
        else:
            after = self.peak
        return jnp.where(s < self.warmup_steps, warm, after)


class StepDecay(LRScheduler):
    def __init__(self, learning_rate, step_size, gamma=0.1, last_epoch=-1, verbose=False):
        super().__init__(learning_rate, last_epoch, verbose)
        self.step_size, self.gamma = step_size, gamma

    def get_lr_at(self, step):
        return self.base_lr * jnp.power(self.gamma, jnp.floor(_f(step) / self.step_size))


class MultiStepDecay(LRScheduler):
    def __init__(self, learning_rate, milestones, gamma=0.1, last_epoch=-1, verbose=False):
        super().__init__(learning_rate, last_epoch, verbose)
        self.milestones = list(milestones)
        self.gamma = gamma

    def get_lr_at(self, step):
        s = _f(step)
        count = sum((s >= m).astype(jnp.float32) for m in self.milestones)
        return self.base_lr * jnp.power(self.gamma, count)


class LambdaDecay(LRScheduler):
    # wraps an arbitrary user callable: int(step)/branching in the
    # lambda would break under tracing, so the engine threads the
    # host-computed rate instead (see LRScheduler.traceable)
    traceable = False

    def __init__(self, learning_rate, lr_lambda, last_epoch=-1, verbose=False):
        super().__init__(learning_rate, last_epoch, verbose)
        self.lr_lambda = lr_lambda

    def get_lr_at(self, step):
        return self.base_lr * self.lr_lambda(step)


class MultiplicativeDecay(LRScheduler):
    # wraps an arbitrary user callable (see LambdaDecay)
    traceable = False

    def __init__(self, learning_rate, lr_lambda, last_epoch=-1, verbose=False):
        super().__init__(learning_rate, last_epoch, verbose)
        self.lr_lambda = lr_lambda

    def get_lr_at(self, step):
        # product form λ(1)·…·λ(t); for traceability assume λ const-per-step
        return self.base_lr * jnp.power(self.lr_lambda(1), _f(step))


class CosineAnnealingDecay(LRScheduler):
    def __init__(self, learning_rate, T_max, eta_min=0, last_epoch=-1, verbose=False):
        super().__init__(learning_rate, last_epoch, verbose)
        self.T_max, self.eta_min = T_max, eta_min

    def get_lr_at(self, step):
        s = jnp.minimum(_f(step), self.T_max)
        return self.eta_min + (self.base_lr - self.eta_min) * 0.5 * (1 + jnp.cos(jnp.pi * s / self.T_max))


class CosineAnnealingWarmRestarts(LRScheduler):
    def __init__(self, learning_rate, T_0, T_mult=1, eta_min=0, last_epoch=-1, verbose=False):
        super().__init__(learning_rate, last_epoch, verbose)
        self.T_0, self.T_mult, self.eta_min = T_0, T_mult, eta_min

    def get_lr_at(self, step):
        s = _f(step)
        if self.T_mult == 1:
            t_cur = jnp.mod(s, self.T_0)
            T_i = self.T_0
        else:
            n = jnp.floor(jnp.log(s / self.T_0 * (self.T_mult - 1) + 1) / math.log(self.T_mult))
            sum_prev = self.T_0 * (jnp.power(float(self.T_mult), n) - 1) / (self.T_mult - 1)
            t_cur = s - sum_prev
            T_i = self.T_0 * jnp.power(float(self.T_mult), n)
        return self.eta_min + (self.base_lr - self.eta_min) * 0.5 * (1 + jnp.cos(jnp.pi * t_cur / T_i))


class OneCycleLR(LRScheduler):
    def __init__(self, max_learning_rate, total_steps, divide_factor=25.0,
                 end_learning_rate=0.0001, phase_pct=0.3, anneal_strategy='cos',
                 three_phase=False, last_epoch=-1, verbose=False):
        super().__init__(max_learning_rate, last_epoch, verbose)
        self.max_lr = max_learning_rate
        self.initial_lr = max_learning_rate / divide_factor
        self.end_lr = end_learning_rate
        self.total_steps = total_steps
        self.up_steps = int(phase_pct * total_steps)

    def get_lr_at(self, step):
        s = jnp.minimum(_f(step), self.total_steps)
        up = self.initial_lr + (self.max_lr - self.initial_lr) * s / max(self.up_steps, 1)
        down_frac = (s - self.up_steps) / max(self.total_steps - self.up_steps, 1)
        down = self.end_lr + (self.max_lr - self.end_lr) * 0.5 * (1 + jnp.cos(jnp.pi * jnp.clip(down_frac, 0, 1)))
        return jnp.where(s < self.up_steps, up, down)


class CyclicLR(LRScheduler):
    def __init__(self, base_learning_rate, max_learning_rate, step_size_up=2000,
                 step_size_down=None, mode='triangular', exp_gamma=1.0,
                 scale_fn=None, scale_mode='cycle', last_epoch=-1, verbose=False):
        super().__init__(base_learning_rate, last_epoch, verbose)
        self.max_lr = max_learning_rate
        self.up = step_size_up
        self.down = step_size_down or step_size_up
        self.mode = mode
        self.exp_gamma = exp_gamma

    def get_lr_at(self, step):
        s = _f(step)
        total = self.up + self.down
        cycle = jnp.floor(1 + s / total)
        pos = jnp.mod(s, total)
        x = jnp.where(pos < self.up, pos / self.up, 1 - (pos - self.up) / self.down)
        amp = self.max_lr - self.base_lr
        if self.mode == 'triangular2':
            amp = amp / jnp.power(2.0, cycle - 1)
        elif self.mode == 'exp_range':
            amp = amp * jnp.power(self.exp_gamma, s)
        return self.base_lr + amp * x


class ReduceOnPlateau(LRScheduler):
    """Metric-driven scheduler — inherently host-side (ref: lr.py::ReduceOnPlateau).
    Use imperatively: call .step(metric) each eval, read .last_lr."""

    # the rate depends on observed metrics, not the step count: the
    # train engine must thread it in from the host (see LRScheduler)
    traceable = False

    def __init__(self, learning_rate, mode='min', factor=0.1, patience=10,
                 threshold=1e-4, threshold_mode='rel', cooldown=0, min_lr=0,
                 epsilon=1e-8, verbose=False):
        super().__init__(learning_rate)
        self.mode, self.factor, self.patience = mode, factor, patience
        self.threshold, self.threshold_mode = threshold, threshold_mode
        self.cooldown, self.min_lr = cooldown, min_lr
        self.last_lr = learning_rate
        self._best = None
        self._bad = 0
        self._cool = 0

    def get_lr_at(self, step):
        return jnp.asarray(self.last_lr, jnp.float32)

    def step(self, metrics=None, epoch=None):
        if metrics is None:
            return
        m = float(metrics)
        better = (
            self._best is None
            or (self.mode == 'min' and m < self._best - self._eps())
            or (self.mode == 'max' and m > self._best + self._eps())
        )
        if better:
            self._best = m
            self._bad = 0
        elif self._cool > 0:
            self._cool -= 1
        else:
            self._bad += 1
            if self._bad > self.patience:
                self.last_lr = max(self.last_lr * self.factor, self.min_lr)
                self._bad = 0
                self._cool = self.cooldown

    def _eps(self):
        if self.threshold_mode == 'rel':
            return abs(self._best or 0) * self.threshold
        return self.threshold


class PiecewiseDecay(LRScheduler):
    def __init__(self, boundaries, values, last_epoch=-1, verbose=False):
        super().__init__(values[0], last_epoch, verbose)
        self.boundaries = list(boundaries)
        self.values = list(values)

    def get_lr_at(self, step):
        s = _f(step)
        lr = jnp.asarray(self.values[-1], jnp.float32)
        for b, v in zip(reversed(self.boundaries), reversed(self.values[:-1])):
            lr = jnp.where(s < b, v, lr)
        return lr


class LinearLR(LRScheduler):
    def __init__(self, learning_rate, total_steps, start_factor=1. / 3, end_factor=1.0,
                 last_epoch=-1, verbose=False):
        super().__init__(learning_rate, last_epoch, verbose)
        self.total_steps, self.start_factor, self.end_factor = total_steps, start_factor, end_factor

    def get_lr_at(self, step):
        frac = jnp.clip(_f(step) / self.total_steps, 0, 1)
        return self.base_lr * (self.start_factor + (self.end_factor - self.start_factor) * frac)


def _f(step):
    return step.astype(jnp.float32) if hasattr(step, 'astype') else jnp.float32(step)
