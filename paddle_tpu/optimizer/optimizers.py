"""Concrete optimizers (ref: python/paddle/optimizer/{sgd,momentum,adam,adamw,
adagrad,adadelta,adamax,rmsprop,lamb,nadam,radam}.py).

Each defines moment slots + a per-leaf update in fp32; the base class
fuses the whole pytree update into one XLA program.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .optimizer import Optimizer, _tmap


def _zeros_like_tree(t):
    return _tmap(lambda p: jnp.zeros_like(p, dtype=jnp.float32), t)


class SGD(Optimizer):
    def init_slots(self, t):
        return {}

    def update_param(self, p, g, slots, lr, step):
        return p - lr * g, slots


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, multi_precision, name)
        self.momentum = momentum
        self.use_nesterov = use_nesterov

    def init_slots(self, t):
        return {'velocity': _zeros_like_tree(t)}

    def update_param(self, p, g, slots, lr, step):
        v = self.momentum * slots['velocity'] + g
        if self.use_nesterov:
            p = p - lr * (g + self.momentum * v)
        else:
            p = p - lr * v
        return p, {'velocity': v}


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None, lazy_mode=False,
                 multi_precision=False, use_multi_tensor=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, multi_precision, name)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def init_slots(self, t):
        return {'m': _zeros_like_tree(t), 'v': _zeros_like_tree(t)}

    def update_param(self, p, g, slots, lr, step):
        b1, b2 = self.beta1, self.beta2
        m = b1 * slots['m'] + (1 - b1) * g
        v = b2 * slots['v'] + (1 - b2) * g * g
        sf = step.astype(jnp.float32)
        mhat = m / (1 - jnp.power(b1, sf))
        vhat = v / (1 - jnp.power(b2, sf))
        p = p - lr * mhat / (jnp.sqrt(vhat) + self.epsilon)
        return p, {'m': m, 'v': v}


class AdamW(Adam):
    """Decoupled weight decay (ref: python/paddle/optimizer/adamw.py)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=0.01, lr_ratio=None,
                 apply_decay_param_fun=None, grad_clip=None, lazy_mode=False,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         weight_decay, grad_clip, lazy_mode, multi_precision, name=name)
        self._decoupled_decay = True
        self.apply_decay_param_fun = apply_decay_param_fun


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, initial_accumulator_value=0.0,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, multi_precision, name)
        self.epsilon = epsilon
        self.initial_accumulator_value = initial_accumulator_value

    def init_slots(self, t):
        iv = self.initial_accumulator_value
        return {'moment': _tmap(lambda p: jnp.full(p.shape, iv, jnp.float32), t)}

    def update_param(self, p, g, slots, lr, step):
        acc = slots['moment'] + g * g
        p = p - lr * g / (jnp.sqrt(acc) + self.epsilon)
        return p, {'moment': acc}


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, multi_precision, name)
        self.epsilon, self.rho = epsilon, rho

    def init_slots(self, t):
        return {'avg_sq_grad': _zeros_like_tree(t), 'avg_sq_update': _zeros_like_tree(t)}

    def update_param(self, p, g, slots, lr, step):
        asg = self.rho * slots['avg_sq_grad'] + (1 - self.rho) * g * g
        upd = jnp.sqrt(slots['avg_sq_update'] + self.epsilon) / jnp.sqrt(asg + self.epsilon) * g
        asu = self.rho * slots['avg_sq_update'] + (1 - self.rho) * upd * upd
        return p - lr * upd, {'avg_sq_grad': asg, 'avg_sq_update': asu}


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, multi_precision, name)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def init_slots(self, t):
        return {'m': _zeros_like_tree(t), 'inf': _zeros_like_tree(t)}

    def update_param(self, p, g, slots, lr, step):
        m = self.beta1 * slots['m'] + (1 - self.beta1) * g
        inf = jnp.maximum(self.beta2 * slots['inf'], jnp.abs(g))
        sf = step.astype(jnp.float32)
        p = p - lr / (1 - jnp.power(self.beta1, sf)) * m / (inf + self.epsilon)
        return p, {'m': m, 'inf': inf}


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, multi_precision, name)
        self.rho, self.epsilon, self.momentum, self.centered = rho, epsilon, momentum, centered

    def init_slots(self, t):
        slots = {'mean_sq': _zeros_like_tree(t), 'velocity': _zeros_like_tree(t)}
        if self.centered:
            slots['mean_g'] = _zeros_like_tree(t)
        return slots

    def update_param(self, p, g, slots, lr, step):
        ms = self.rho * slots['mean_sq'] + (1 - self.rho) * g * g
        out = {'mean_sq': ms}
        if self.centered:
            mg = self.rho * slots['mean_g'] + (1 - self.rho) * g
            denom = jnp.sqrt(ms - mg * mg + self.epsilon)
            out['mean_g'] = mg
        else:
            denom = jnp.sqrt(ms + self.epsilon)
        v = self.momentum * slots['velocity'] + lr * g / denom
        out['velocity'] = v
        return p - v, out


class Lamb(Optimizer):
    """ref: python/paddle/optimizer/lamb.py — layerwise-adaptive AdamW for
    large-batch training."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, multi_precision, name)
        self.lamb_weight_decay = lamb_weight_decay
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def init_slots(self, t):
        return {'m': _zeros_like_tree(t), 'v': _zeros_like_tree(t)}

    def update_param(self, p, g, slots, lr, step):
        b1, b2 = self.beta1, self.beta2
        m = b1 * slots['m'] + (1 - b1) * g
        v = b2 * slots['v'] + (1 - b2) * g * g
        sf = step.astype(jnp.float32)
        mhat = m / (1 - jnp.power(b1, sf))
        vhat = v / (1 - jnp.power(b2, sf))
        r = mhat / (jnp.sqrt(vhat) + self.epsilon) + self.lamb_weight_decay * p
        p_norm = jnp.sqrt(jnp.sum(p * p))
        r_norm = jnp.sqrt(jnp.sum(r * r))
        trust = jnp.where((p_norm > 0) & (r_norm > 0), p_norm / r_norm, 1.0)
        return p - lr * trust * r, {'m': m, 'v': v}


class NAdam(Adam):
    def update_param(self, p, g, slots, lr, step):
        b1, b2 = self.beta1, self.beta2
        m = b1 * slots['m'] + (1 - b1) * g
        v = b2 * slots['v'] + (1 - b2) * g * g
        sf = step.astype(jnp.float32)
        mhat = m / (1 - jnp.power(b1, sf + 1))
        vhat = v / (1 - jnp.power(b2, sf))
        m_bar = b1 * mhat + (1 - b1) * g / (1 - jnp.power(b1, sf))
        p = p - lr * m_bar / (jnp.sqrt(vhat) + self.epsilon)
        return p, {'m': m, 'v': v}


class RAdam(Adam):
    def update_param(self, p, g, slots, lr, step):
        b1, b2 = self.beta1, self.beta2
        m = b1 * slots['m'] + (1 - b1) * g
        v = b2 * slots['v'] + (1 - b2) * g * g
        sf = step.astype(jnp.float32)
        mhat = m / (1 - jnp.power(b1, sf))
        rho_inf = 2.0 / (1 - b2) - 1
        b2t = jnp.power(b2, sf)
        rho_t = rho_inf - 2 * sf * b2t / (1 - b2t)
        r = jnp.sqrt(
            jnp.clip((rho_t - 4) * (rho_t - 2) * rho_inf /
                     jnp.clip((rho_inf - 4) * (rho_inf - 2) * rho_t, 1e-12, None), 0, None)
        )
        vhat = jnp.sqrt(v / (1 - b2t)) + self.epsilon
        p = jnp.where(rho_t > 5, p - lr * r * mhat / vhat, p - lr * mhat)
        return p, {'m': m, 'v': v}


class ASGD(SGD):
    pass


class Rprop(Optimizer):
    def __init__(self, learning_rate=0.001, learning_rate_range=(1e-5, 50),
                 parameters=None, etas=(0.5, 1.2), grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, multi_precision, name)
        self.lr_range = learning_rate_range
        self.etas = etas

    def init_slots(self, t):
        init_lr = float(self._lr) if not callable(self._lr) else 0.001
        return {
            'prev_g': _zeros_like_tree(t),
            'lrs': _tmap(lambda p: jnp.full(p.shape, init_lr, jnp.float32), t),
        }

    def update_param(self, p, g, slots, lr, step):
        sign = jnp.sign(g * slots['prev_g'])
        lrs = jnp.clip(
            jnp.where(sign > 0, slots['lrs'] * self.etas[1],
                      jnp.where(sign < 0, slots['lrs'] * self.etas[0], slots['lrs'])),
            self.lr_range[0], self.lr_range[1],
        )
        g_eff = jnp.where(sign < 0, 0.0, g)
        return p - lrs * jnp.sign(g_eff), {'prev_g': g_eff, 'lrs': lrs}
