"""Optimizer wrappers: gradient accumulation, EMA, LookAhead.

refs: fleet gradient_merge pass (python/paddle/distributed/fleet/
meta_optimizers/gradient_merge_optimizer.py), ExponentialMovingAverage
(python/paddle/static/nn/common.py:4032), paddle.incubate.LookAhead.

All three are functional state transformers around the base Optimizer
protocol (init/apply_gradients), so they compose with jit, GSPMD
sharding, and each other.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.tree import split_trainable


def _zeros_like_trainable(model):
    t, _ = split_trainable(model)
    return jax.tree.map(jnp.zeros_like, t)


class GradientMerge:
    """Accumulate grads for k_steps micro-batches, then apply one real
    update with the (averaged) sum — the reference's gradient_merge.

    Consumes DistributedStrategy.gradient_merge_steps via
    fleet.distributed_optimizer; usable standalone:

        opt = GradientMerge(AdamW(1e-4), k_steps=4)
    """

    def __init__(self, inner, k_steps: int, avg: bool = True):
        if k_steps < 1:
            raise ValueError(f'k_steps must be >= 1, got {k_steps}')
        self._inner = inner
        self.k_steps = int(k_steps)
        self.avg = avg

    def init(self, model):
        state = {
            'inner': self._inner.init(model),
            'acc': _zeros_like_trainable(model),
            'count': jnp.zeros((), jnp.int32),
        }
        self.state = state
        return state

    def apply_gradients(self, model, grads, state=None):
        state = state if state is not None else self.state
        acc = jax.tree.map(jnp.add, state['acc'], grads)
        count = state['count'] + 1

        def do_update(_):
            scale = 1.0 / self.k_steps if self.avg else 1.0
            g = jax.tree.map(lambda a: a * scale, acc)
            new_model, inner_state = self._inner.apply_gradients(
                model, g, state['inner'])
            zeros = jax.tree.map(jnp.zeros_like, acc)
            return new_model, inner_state, zeros, jnp.zeros((), jnp.int32)

        def skip(_):
            return model, state['inner'], acc, count

        model, inner_state, acc, count = jax.lax.cond(
            count >= self.k_steps, do_update, skip, None)
        new_state = {'inner': inner_state, 'acc': acc, 'count': count}
        self.state = new_state
        return model, new_state

    def __getattr__(self, name):
        if name.startswith('_'):
            raise AttributeError(name)
        return getattr(self._inner, name)


class ExponentialMovingAverage:
    """ref: paddle.static.ExponentialMovingAverage — shadow weights
    w_ema = decay * w_ema + (1 - decay) * w, with bias correction like
    the reference's thres_steps-free default."""

    def __init__(self, decay=0.999):
        self.decay = float(decay)

    def init(self, model):
        # shadow starts at zero and apply() divides by (1 - decay^t),
        # matching the reference's bias-corrected recurrence
        t, _ = split_trainable(model)
        return {'shadow': jax.tree.map(
                    lambda p: jnp.zeros(p.shape, jnp.float32), t),
                'step': jnp.zeros((), jnp.int32)}

    def update(self, state, model):
        t, _ = split_trainable(model)
        d = self.decay
        shadow = jax.tree.map(
            lambda s, p: d * s + (1 - d) * p.astype(jnp.float32),
            state['shadow'], t)
        return {'shadow': shadow, 'step': state['step'] + 1}

    def apply(self, model, state, bias_correction=True):
        """Returns a copy of `model` with EMA weights swapped in."""
        from ..framework.tree import merge

        t, f = split_trainable(model)
        corr = 1.0 - self.decay ** jnp.maximum(state['step'], 1) \
            if bias_correction else 1.0
        ema_t = jax.tree.map(
            lambda s, p: (s / corr).astype(p.dtype), state['shadow'], t)
        return merge(ema_t, f)

    def restore(self, model, original_trainable):
        from ..framework.tree import merge

        _, f = split_trainable(model)
        return merge(original_trainable, f)


class LookAhead:
    """ref: paddle.incubate.LookAhead(inner, alpha=0.5, k=5) — keep slow
    weights; every k fast steps, slow += alpha*(fast - slow), fast = slow."""

    def __init__(self, inner, alpha=0.5, k=5):
        self._inner = inner
        self.alpha = float(alpha)
        self.k = int(k)

    def init(self, model):
        t, _ = split_trainable(model)
        state = {
            'inner': self._inner.init(model),
            'slow': jax.tree.map(lambda p: p.astype(jnp.float32), t),
            'count': jnp.zeros((), jnp.int32),
        }
        self.state = state
        return state

    def apply_gradients(self, model, grads, state=None):
        from ..framework.tree import merge

        state = state if state is not None else self.state
        model, inner_state = self._inner.apply_gradients(
            model, grads, state['inner'])
        count = state['count'] + 1

        def sync(_):
            t, f = split_trainable(model)
            slow = jax.tree.map(
                lambda s, p: s + self.alpha * (p.astype(jnp.float32) - s),
                state['slow'], t)
            fast = jax.tree.map(lambda s, p: s.astype(p.dtype), slow, t)
            return merge(fast, f), slow, jnp.zeros((), jnp.int32)

        def keep(_):
            return model, state['slow'], count

        model, slow, count = jax.lax.cond(count >= self.k, sync, keep, None)
        new_state = {'inner': inner_state, 'slow': slow, 'count': count}
        self.state = new_state
        return model, new_state

    def __getattr__(self, name):
        if name.startswith('_'):
            raise AttributeError(name)
        return getattr(self._inner, name)
