"""Autograd (ref: python/paddle/autograd).

Paddle's dygraph autograd records a C++ tape and walks it on
``Tensor.backward()``. TPU-native: differentiation is a program
transform. ``value_and_grad``/``grad`` differentiate a loss function
w.r.t. the *trainable* leaves of a model pytree (stop_gradient /
trainable=False params and buffers are frozen out structurally), which
is both the jax idiom and what XLA wants — one fused fwd+bwd program.
"""
from __future__ import annotations

import contextlib
import functools

import jax

from ..framework import tree as tree_util
from ..framework.tree import global_norm, merge, split_trainable

from .eager import Variable, backward, to_variable  # noqa: E402,F401

__all__ = [
    'grad',
    'value_and_grad',
    'no_grad',
    'enable_grad',
    'is_grad_enabled',
    'stop_gradient',
    'PyLayer',
    'jvp',
    'vjp',
    'jacobian',
    'hessian',
    'Variable',
    'to_variable',
    'backward',
]


def value_and_grad(fn, has_aux=False, model_arg=0):
    """Differentiate ``fn(model, *args)`` w.r.t. the trainable leaves of
    ``model``. Returns ``(value, grads)`` where ``grads`` is model-shaped
    with ``None`` in frozen slots (so ``jax.tree.map`` over
    ``(params, grads)`` aligns).

    If the step mutates layer state (BatchNorm stats, RNG keys), return
    the model from ``fn`` via ``has_aux`` to carry the updates out.
    """

    def wrapped(*args, **kwargs):
        model = args[model_arg]
        trainable, frozen = split_trainable(model)

        def inner(t):
            m = merge(t, frozen)
            new_args = args[:model_arg] + (m,) + args[model_arg + 1 :]
            return fn(*new_args, **kwargs)

        return jax.value_and_grad(inner, has_aux=has_aux)(trainable)

    return wrapped


def grad(fn, has_aux=False, model_arg=0):
    vg = value_and_grad(fn, has_aux=has_aux, model_arg=model_arg)

    def wrapped(*args, **kwargs):
        _, g = vg(*args, **kwargs)
        return g

    return wrapped


_grad_enabled = [True]


@contextlib.contextmanager
def no_grad():
    """API-parity context (ref: paddle.no_grad). In a functional framework
    gradients only flow through explicit grad transforms; this context
    flags intent and is consulted by Layer code paths that would
    otherwise thread state for backward."""
    _grad_enabled.append(False)
    try:
        yield
    finally:
        _grad_enabled.pop()


@contextlib.contextmanager
def enable_grad():
    _grad_enabled.append(True)
    try:
        yield
    finally:
        _grad_enabled.pop()


def is_grad_enabled():
    return _grad_enabled[-1]


def stop_gradient(x):
    return jax.lax.stop_gradient(x)


class PyLayer:
    """Custom-VJP op (ref: paddle.autograd.PyLayer).

    Subclass with static ``forward(ctx, *args)`` and
    ``backward(ctx, *grads)``; ``ctx.save_for_backward(*xs)`` stashes
    residuals. Compiles to a jax.custom_vjp under the hood.
    """

    class _Ctx:
        def __init__(self):
            self.saved = ()
            self._unpack = None

        def save_for_backward(self, *xs):
            # capture the hook PAIR at save time: backward may run after
            # the with-block exits (the reference's documented pattern),
            # so unpack must not be looked up from the live stack
            hooks = saved_tensors_hooks._active
            if hooks:
                pack, self._unpack = hooks[-1]
                xs = tuple(pack(x) for x in xs)
            self.saved = xs

        def saved_tensor(self):
            if self._unpack is not None:
                return tuple(self._unpack(x) for x in self.saved)
            return self.saved

    def __init_subclass__(cls, **kw):
        super().__init_subclass__(**kw)

        @jax.custom_vjp
        def op(*args):
            return cls.forward(PyLayer._Ctx(), *args)

        def fwd(*args):
            ctx = PyLayer._Ctx()
            out = cls.forward(ctx, *args)
            # the hook pair captured at save time is static (a Python
            # function, not a tracer): carry it on the class so bwd —
            # traced in the same grad transform — sees it even when
            # backward runs after the hooks context has exited
            cls._saved_unpack = ctx._unpack
            return out, ctx.saved

        def bwd(saved, g):
            ctx = PyLayer._Ctx()
            ctx.saved = saved
            ctx._unpack = getattr(cls, '_saved_unpack', None)
            grads = cls.backward(ctx, g)
            if not isinstance(grads, tuple):
                grads = (grads,)
            return grads

        op.defvjp(fwd, bwd)
        cls._op = staticmethod(op)

    @classmethod
    def apply(cls, *args):
        return cls._op(*args)


def jvp(fn, primals, tangents):
    return jax.jvp(fn, primals, tangents)


def vjp(fn, *primals):
    return jax.vjp(fn, *primals)


def jacobian(fn, x):
    return jax.jacrev(fn)(x)


def hessian(fn, x):
    return jax.hessian(fn)(x)


# ref: paddle.autograd.PyLayerContext — the ctx object handed to
# PyLayer.forward/backward
PyLayerContext = PyLayer._Ctx


class saved_tensors_hooks:
    """ref: paddle.autograd.saved_tensors_hooks(pack, unpack) — transform
    residuals as they are stashed for backward. PyLayer consults the
    active hook pair in save_for_backward / saved_tensor; jax.grad's own
    residuals are managed by XLA (remat covers the memory use case)."""

    _active = []

    def __init__(self, pack_hook, unpack_hook):
        self.pack_hook = pack_hook
        self.unpack_hook = unpack_hook

    def __enter__(self):
        saved_tensors_hooks._active.append((self.pack_hook,
                                            self.unpack_hook))
        return self

    def __exit__(self, *exc):
        saved_tensors_hooks._active.pop()
        return False


__all__ += ['PyLayerContext', 'saved_tensors_hooks']
