"""Autograd (ref: python/paddle/autograd).

Paddle's dygraph autograd records a C++ tape and walks it on
``Tensor.backward()``. TPU-native: differentiation is a program
transform. ``value_and_grad``/``grad`` differentiate a loss function
w.r.t. the *trainable* leaves of a model pytree (stop_gradient /
trainable=False params and buffers are frozen out structurally), which
is both the jax idiom and what XLA wants — one fused fwd+bwd program.
"""
from __future__ import annotations

import contextlib
import functools

import jax

from ..framework import tree as tree_util
from ..framework.tree import global_norm, merge, split_trainable

from .eager import Variable, backward, to_variable  # noqa: E402,F401

__all__ = [
    'grad',
    'value_and_grad',
    'no_grad',
    'enable_grad',
    'is_grad_enabled',
    'stop_gradient',
    'PyLayer',
    'jvp',
    'vjp',
    'jacobian',
    'hessian',
    'Variable',
    'to_variable',
    'backward',
]


def value_and_grad(fn, has_aux=False, model_arg=0):
    """Differentiate ``fn(model, *args)`` w.r.t. the trainable leaves of
    ``model``. Returns ``(value, grads)`` where ``grads`` is model-shaped
    with ``None`` in frozen slots (so ``jax.tree.map`` over
    ``(params, grads)`` aligns).

    If the step mutates layer state (BatchNorm stats, RNG keys), return
    the model from ``fn`` via ``has_aux`` to carry the updates out.
    """

    def wrapped(*args, **kwargs):
        model = args[model_arg]
        trainable, frozen = split_trainable(model)

        def inner(t):
            m = merge(t, frozen)
            new_args = args[:model_arg] + (m,) + args[model_arg + 1 :]
            return fn(*new_args, **kwargs)

        return jax.value_and_grad(inner, has_aux=has_aux)(trainable)

    return wrapped


def grad(fn, has_aux=False, model_arg=0):
    vg = value_and_grad(fn, has_aux=has_aux, model_arg=model_arg)

    def wrapped(*args, **kwargs):
        _, g = vg(*args, **kwargs)
        return g

    return wrapped


_grad_enabled = [True]


@contextlib.contextmanager
def no_grad():
    """API-parity context (ref: paddle.no_grad). In a functional framework
    gradients only flow through explicit grad transforms; this context
    flags intent and is consulted by Layer code paths that would
    otherwise thread state for backward."""
    _grad_enabled.append(False)
    try:
        yield
    finally:
        _grad_enabled.pop()


@contextlib.contextmanager
def enable_grad():
    _grad_enabled.append(True)
    try:
        yield
    finally:
        _grad_enabled.pop()


def is_grad_enabled():
    return _grad_enabled[-1]


def stop_gradient(x):
    return jax.lax.stop_gradient(x)


class PyLayer:
    """Custom-VJP op (ref: paddle.autograd.PyLayer).

    Subclass with static ``forward(ctx, *args)`` and
    ``backward(ctx, *grads)``; ``ctx.save_for_backward(*xs)`` stashes
    residuals. Compiles to a jax.custom_vjp under the hood.
    """

    class _Ctx:
        def __init__(self):
            self.saved = ()

        def save_for_backward(self, *xs):
            self.saved = xs

        def saved_tensor(self):
            return self.saved

    def __init_subclass__(cls, **kw):
        super().__init_subclass__(**kw)

        @jax.custom_vjp
        def op(*args):
            return cls.forward(PyLayer._Ctx(), *args)

        def fwd(*args):
            ctx = PyLayer._Ctx()
            out = cls.forward(ctx, *args)
            return out, ctx.saved

        def bwd(saved, g):
            ctx = PyLayer._Ctx()
            ctx.saved = saved
            grads = cls.backward(ctx, g)
            if not isinstance(grads, tuple):
                grads = (grads,)
            return grads

        op.defvjp(fwd, bwd)
        cls._op = staticmethod(op)

    @classmethod
    def apply(cls, *args):
        return cls._op(*args)


def jvp(fn, primals, tangents):
    return jax.jvp(fn, primals, tangents)


def vjp(fn, *primals):
    return jax.vjp(fn, *primals)


def jacobian(fn, x):
    return jax.jacrev(fn)(x)


def hessian(fn, x):
    return jax.hessian(fn)(x)
