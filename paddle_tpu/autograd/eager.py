"""Eager tape autograd — `Tensor.backward()` parity (ref:
python/paddle/base/dygraph/tensor_patch_methods.py::backward,
python/paddle/autograd/backward_mode.py).

Paddle's dygraph tensors record into a C++ autograd graph;
`loss.backward()` walks it and deposits `.grad` on leaves. The
TPU-native framework is functional (`value_and_grad` is the primary
API), but this shim provides the same eager feel for scripts and
interactive use: `Variable` wraps a jax array, every overloaded op runs
`jax.vjp` eagerly and records the pullback on a tape, and
`loss.backward()` walks the tape in reverse topological order.

    x = to_variable(jnp.ones((3,)), stop_gradient=False)
    y = (x * x).sum()
    y.backward()
    x.grad  # -> 2*x

Each op dispatches to XLA eagerly (no jit) — intended for convenience,
not the training hot path.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def _add_cot(a, b):
    if getattr(a, 'dtype', None) == jax.dtypes.float0:
        return a        # zero cotangent of a non-differentiable output
    return jnp.add(a, b)


def _acc(a, b):
    """Cotangent accumulation that also works for pytree cotangents
    (module-call nodes carry a whole trainable-tree cotangent)."""
    if a is None:
        return b
    if b is None:
        return a
    return jax.tree.map(_add_cot, a, b)


class Variable:
    """A tape-recording wrapper over a jax array (ref: dygraph Tensor)."""

    __array_priority__ = 100  # beat numpy in mixed binary ops

    def __init__(self, value, stop_gradient=True, _parents=(), _vjp=None):
        self.value = jnp.asarray(value)
        self.stop_gradient = stop_gradient
        self.grad = None
        self._parents = _parents      # Variables this value depends on
        self._vjp = _vjp              # pullback: out_cot -> parent cots

    # -- graph construction -------------------------------------------------
    @staticmethod
    def _apply(fn, *args, **kwargs):
        """Run fn on unwrapped values; record a vjp over Variable args."""
        vals = [a.value if isinstance(a, Variable) else a for a in args]
        live = [i for i, a in enumerate(args)
                if isinstance(a, Variable) and not a.stop_gradient]
        if not live:
            return Variable(fn(*vals, **kwargs), stop_gradient=True)

        def prim(*lv):
            full = list(vals)
            for i, v in zip(live, lv):
                full[i] = v
            return fn(*full, **kwargs)

        out, vjp = jax.vjp(prim, *[vals[i] for i in live])
        return Variable(out, stop_gradient=False,
                        _parents=tuple(args[i] for i in live), _vjp=vjp)

    # -- backward -----------------------------------------------------------
    def backward(self, grad_tensor=None, retain_graph=False):
        """ref: Tensor.backward — reverse-walk the tape, accumulate .grad."""
        if self.stop_gradient:
            raise RuntimeError('backward() on a stop_gradient tensor')
        seed = (jnp.ones_like(self.value) if grad_tensor is None
                else jnp.asarray(grad_tensor))
        if grad_tensor is None and self.value.ndim != 0:
            if self.value.size != 1:
                raise RuntimeError(
                    'backward() without grad_tensor needs a scalar loss')
            seed = jnp.ones_like(self.value)

        # reverse topological order
        order, seen = [], set()

        def visit(v):
            if id(v) in seen or v.stop_gradient:
                return
            seen.add(id(v))
            for p in v._parents:
                visit(p)
            order.append(v)

        visit(self)
        cots = {id(self): seed}
        for v in reversed(order):
            cot = cots.pop(id(v), None)
            if cot is None:
                continue
            sink = getattr(v, '_sink', None)
            if sink is not None:          # module-call param node
                sink(cot)
            elif not getattr(v, '_no_grad_store', False):
                v.grad = _acc(v.grad, cot)
            if v._vjp is None:
                continue
            parent_cots = v._vjp(cot)
            for p, pc in zip(v._parents, parent_cots):
                if p.stop_gradient:
                    continue
                cots[id(p)] = _acc(cots.get(id(p)), pc)
            if not retain_graph:
                v._vjp, v._parents = None, ()

    def clear_grad(self):
        self.grad = None

    # -- array protocol -----------------------------------------------------
    @property
    def shape(self):
        return self.value.shape

    @property
    def dtype(self):
        return self.value.dtype

    @property
    def ndim(self):
        return self.value.ndim

    def numpy(self):
        import numpy as np

        return np.asarray(self.value)

    def __array__(self, dtype=None):
        import numpy as np

        a = np.asarray(self.value)
        return a.astype(dtype) if dtype is not None else a

    def item(self):
        return self.value.item()

    def __repr__(self):
        return (f'Variable(shape={self.value.shape}, '
                f'stop_gradient={self.stop_gradient},\n{self.value})')

    def __float__(self):
        return float(self.value)

    # -- operators ------------------------------------------------------
    def __add__(self, o):
        return self._apply(jnp.add, self, o)

    __radd__ = __add__

    def __sub__(self, o):
        return self._apply(jnp.subtract, self, o)

    def __rsub__(self, o):
        return self._apply(jnp.subtract, o, self)

    def __mul__(self, o):
        return self._apply(jnp.multiply, self, o)

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._apply(jnp.divide, self, o)

    def __rtruediv__(self, o):
        return self._apply(jnp.divide, o, self)

    def __matmul__(self, o):
        return self._apply(jnp.matmul, self, o)

    def __rmatmul__(self, o):
        return self._apply(jnp.matmul, o, self)

    def __pow__(self, o):
        return self._apply(jnp.power, self, o)

    def __neg__(self):
        return self._apply(jnp.negative, self)

    def __getitem__(self, idx):
        return self._apply(lambda v: v[idx], self)

    # -- common methods (mirroring Tensor methods) ------------------------
    def sum(self, axis=None, keepdim=False):
        return self._apply(
            lambda v: jnp.sum(v, axis=axis, keepdims=keepdim), self)

    def mean(self, axis=None, keepdim=False):
        return self._apply(
            lambda v: jnp.mean(v, axis=axis, keepdims=keepdim), self)

    def max(self, axis=None, keepdim=False):
        return self._apply(
            lambda v: jnp.max(v, axis=axis, keepdims=keepdim), self)

    def min(self, axis=None, keepdim=False):
        return self._apply(
            lambda v: jnp.min(v, axis=axis, keepdims=keepdim), self)

    def reshape(self, shape):
        return self._apply(lambda v: jnp.reshape(v, shape), self)

    def transpose(self, perm=None):
        return self._apply(lambda v: jnp.transpose(v, perm), self)

    def exp(self):
        return self._apply(jnp.exp, self)

    def log(self):
        return self._apply(jnp.log, self)

    def tanh(self):
        return self._apply(jnp.tanh, self)

    def sigmoid(self):
        return self._apply(jax.nn.sigmoid, self)

    def relu(self):
        return self._apply(jax.nn.relu, self)

    def sqrt(self):
        return self._apply(jnp.sqrt, self)

    def abs(self):
        return self._apply(jnp.abs, self)

    def detach(self):
        return Variable(self.value, stop_gradient=True)

    def cast(self, dtype):
        return self._apply(lambda v: v.astype(dtype), self)

    astype = cast


def to_variable(value, stop_gradient=False):
    """ref: paddle.to_tensor(..., stop_gradient=False) in dygraph —
    wrap an array for eager tape autograd."""
    if isinstance(value, Variable):
        return value
    return Variable(value, stop_gradient=stop_gradient)


def apply(fn, *args, **kwargs):
    """Record an arbitrary jax function application on the tape."""
    return Variable._apply(fn, *args, **kwargs)


def backward(tensors, grad_tensors=None):
    """ref: paddle.autograd.backward(tensors, grad_tensors)."""
    tensors = tensors if isinstance(tensors, (list, tuple)) else [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    for t, g in zip(tensors, grad_tensors):
        t.backward(g, retain_graph=True)


# -- module-boundary taping (the dygraph train loop) ----------------------
#
# The canonical Paddle loop —
#     loss = loss_fn(net(x), y); loss.backward(); opt.step(); opt.clear_grad()
# — records per-op into a C++ tape. Recording the whole module call as ONE
# tape node is the TPU-native equivalent: the forward runs as a single
# (jit-cached) XLA program under `jax.vjp`, the node's pullback yields the
# cotangent for the module's entire trainable tree, and `backward()`
# deposits it on the owning Layer (`layer._param_grads`), where
# `Optimizer.step()` finds it. Activated by binding an optimizer with
# `parameters=net.parameters()` (the dygraph signal in Paddle) or by
# passing `Variable` inputs.

class _ParamNode:
    """Tape node standing for a module's whole trainable tree; the
    cotangent arriving here is a model-shaped pytree, sunk onto the
    owning layer rather than kept as `.grad`."""

    __slots__ = ('stop_gradient', 'grad', '_parents', '_vjp', 'layer')

    def __init__(self, layer):
        self.stop_gradient = False
        self.grad = None
        self._parents = ()
        self._vjp = None
        self.layer = layer

    def _sink(self, cot):
        d = self.layer.__dict__
        d['_param_grads'] = _acc(d.get('_param_grads'), cot)


# One stable jitted forward per (module structure, call signature): jax
# re-traces through the cached pjit cheaply per step instead of
# recompiling. Keyed on hashable static call structure; falls back to
# uncached eager when a static argument is unhashable.
_MODULE_FWD_CACHE: dict = {}


def _pure_module_fwd(in_tree, dyn_idx, static_vals):
    from ..framework.tree import merge, split_trainable

    def fwd(t, f, dyn_vals):
        flat = list(static_vals)
        for i, v in zip(dyn_idx, dyn_vals):
            flat[i] = v
        args, kwargs = jax.tree_util.tree_unflatten(in_tree, flat)
        m = merge(t, f)
        out = m.forward(*args, **kwargs)
        _, new_f = split_trainable(m)
        return out, new_f      # new_f is vjp aux: buffers aren't differentiated

    return fwd


def call_module(layer, args, kwargs):
    """Run `layer.forward(*args, **kwargs)` as one tape node.

    Differentiates w.r.t. the layer's trainable tree and any live
    `Variable` inputs; buffer mutations (BatchNorm stats, RNG threading)
    are carried out of the traced copy and written back in place.
    """
    from ..framework.tree import split_trainable

    flat, in_tree = jax.tree_util.tree_flatten(
        (args, kwargs), is_leaf=lambda x: isinstance(x, Variable))
    vals = [x.value if isinstance(x, Variable) else x for x in flat]
    live = tuple(i for i, x in enumerate(flat)
                 if isinstance(x, Variable) and not x.stop_gradient)
    dyn_idx = tuple(i for i, v in enumerate(vals)
                    if isinstance(v, (jax.Array,)) or hasattr(v, '__array__'))
    static_vals = tuple(None if i in dyn_idx else v
                        for i, v in enumerate(vals))
    try:
        key = (in_tree, dyn_idx, static_vals)
        fwd = _MODULE_FWD_CACHE.get(key)
        if fwd is None:
            # tracelint: disable=TL001 - cached in _MODULE_FWD_CACHE
            # keyed on (tree, dyn_idx, statics): one trace per shape
            fwd = jax.jit(_pure_module_fwd(in_tree, dyn_idx, static_vals))
            _MODULE_FWD_CACHE[key] = fwd
    except TypeError:   # unhashable static arg: run uncached
        fwd = _pure_module_fwd(in_tree, dyn_idx, static_vals)

    t, f = split_trainable(layer)
    dyn_vals = [jnp.asarray(vals[i]) for i in dyn_idx]
    live_dyn = tuple(dyn_idx.index(i) for i in live)

    def diff_fwd(t_, lv):
        dv = list(dyn_vals)
        for j, v in zip(live_dyn, lv):
            dv[j] = v
        return fwd(t_, f, dv)

    out, vjp_fn, new_f = jax.vjp(
        diff_fwd, t, [dyn_vals[j] for j in live_dyn], has_aux=True)
    _write_back(layer, new_f)

    out_leaves, out_tree = jax.tree_util.tree_flatten(out)
    pnode = _ParamNode(layer)

    def _zero_cot(l):
        if jnp.issubdtype(l.dtype, jnp.inexact):
            return jnp.zeros_like(l)
        import numpy as np

        return np.zeros(l.shape, jax.dtypes.float0)

    def module_pull(cot_list):
        t_cot, lv_cot = vjp_fn(
            jax.tree_util.tree_unflatten(out_tree, list(cot_list)))
        return (t_cot, *lv_cot)

    if len(out_leaves) == 1:
        parents = (pnode,) + tuple(flat[i] for i in live)
        l = out_leaves[0]
        wrapped = [
            Variable(l, stop_gradient=False, _parents=parents,
                     _vjp=lambda cot: module_pull([cot.astype(l.dtype)]))
            if jnp.issubdtype(l.dtype, jnp.inexact)
            else Variable(l, stop_gradient=True)
        ]
    else:
        # multi-output call: leaves feed a shared gather node whose
        # cotangent is the padded list; cots from the leaves ADD before
        # the module pullback runs, so the (expensive) vjp runs ONCE no
        # matter how many outputs participate in the loss
        gather = Variable.__new__(Variable)
        gather.value, gather.grad = None, None
        gather.stop_gradient = False
        gather._parents = (pnode,) + tuple(flat[i] for i in live)
        gather._vjp = module_pull
        gather._no_grad_store = True

        def make_leaf_pull(i, l):
            def pull(cot):
                return ([cot.astype(l.dtype) if j == i else _zero_cot(o)
                         for j, o in enumerate(out_leaves)],)

            return pull

        wrapped = [
            Variable(l, stop_gradient=False, _parents=(gather,),
                     _vjp=make_leaf_pull(i, l))
            if jnp.issubdtype(l.dtype, jnp.inexact)
            else Variable(l, stop_gradient=True)
            for i, l in enumerate(out_leaves)
        ]
    return jax.tree_util.tree_unflatten(out_tree, wrapped)


def _write_back(dst, src):
    """Copy `src`'s array leaves into the same-structure Layer `dst` in
    place (buffer mutations out of a traced copy; optimizer updates)."""
    from ..nn.layer.base import Layer

    for name, sv in list(src._children()) if isinstance(src, Layer) else []:
        dv = dst.__dict__.get(name)
        if isinstance(sv, Layer):
            _write_back(dv, sv)
        elif sv is not None:
            object.__setattr__(dst, name, sv)


def module_call_would_tape(layer, args, kwargs):
    """Decide whether Layer.__call__ should record (see call_module).

    Never tapes inside jax transforms: tracer inputs or tracer params
    mean a functional transform owns this call.
    """
    from . import is_grad_enabled

    flat = jax.tree_util.tree_leaves(
        (args, kwargs), is_leaf=lambda x: isinstance(x, Variable))
    has_var = any(isinstance(x, Variable) for x in flat)
    if not has_var and not layer.__dict__.get('_dygraph', False):
        return False, False
    if not is_grad_enabled():
        return False, has_var
    if any(isinstance(x, jax.core.Tracer) for x in flat):
        return False, has_var
    _, p0 = next(iter(layer.named_parameters()), (None, None))
    if isinstance(p0, jax.core.Tracer):
        return False, has_var
    return True, has_var


def unwrap(tree):
    """Strip Variables (no_grad forwarding of taped values)."""
    return jax.tree_util.tree_map(
        lambda x: x.value if isinstance(x, Variable) else x, tree,
        is_leaf=lambda x: isinstance(x, Variable))
