"""Eager tape autograd — `Tensor.backward()` parity (ref:
python/paddle/base/dygraph/tensor_patch_methods.py::backward,
python/paddle/autograd/backward_mode.py).

Paddle's dygraph tensors record into a C++ autograd graph;
`loss.backward()` walks it and deposits `.grad` on leaves. The
TPU-native framework is functional (`value_and_grad` is the primary
API), but this shim provides the same eager feel for scripts and
interactive use: `Variable` wraps a jax array, every overloaded op runs
`jax.vjp` eagerly and records the pullback on a tape, and
`loss.backward()` walks the tape in reverse topological order.

    x = to_variable(jnp.ones((3,)), stop_gradient=False)
    y = (x * x).sum()
    y.backward()
    x.grad  # -> 2*x

Each op dispatches to XLA eagerly (no jit) — intended for convenience,
not the training hot path.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


class Variable:
    """A tape-recording wrapper over a jax array (ref: dygraph Tensor)."""

    __array_priority__ = 100  # beat numpy in mixed binary ops

    def __init__(self, value, stop_gradient=True, _parents=(), _vjp=None):
        self.value = jnp.asarray(value)
        self.stop_gradient = stop_gradient
        self.grad = None
        self._parents = _parents      # Variables this value depends on
        self._vjp = _vjp              # pullback: out_cot -> parent cots

    # -- graph construction -------------------------------------------------
    @staticmethod
    def _apply(fn, *args, **kwargs):
        """Run fn on unwrapped values; record a vjp over Variable args."""
        vals = [a.value if isinstance(a, Variable) else a for a in args]
        live = [i for i, a in enumerate(args)
                if isinstance(a, Variable) and not a.stop_gradient]
        if not live:
            return Variable(fn(*vals, **kwargs), stop_gradient=True)

        def prim(*lv):
            full = list(vals)
            for i, v in zip(live, lv):
                full[i] = v
            return fn(*full, **kwargs)

        out, vjp = jax.vjp(prim, *[vals[i] for i in live])
        return Variable(out, stop_gradient=False,
                        _parents=tuple(args[i] for i in live), _vjp=vjp)

    # -- backward -----------------------------------------------------------
    def backward(self, grad_tensor=None, retain_graph=False):
        """ref: Tensor.backward — reverse-walk the tape, accumulate .grad."""
        if self.stop_gradient:
            raise RuntimeError('backward() on a stop_gradient tensor')
        seed = (jnp.ones_like(self.value) if grad_tensor is None
                else jnp.asarray(grad_tensor))
        if grad_tensor is None and self.value.ndim != 0:
            if self.value.size != 1:
                raise RuntimeError(
                    'backward() without grad_tensor needs a scalar loss')
            seed = jnp.ones_like(self.value)

        # reverse topological order
        order, seen = [], set()

        def visit(v):
            if id(v) in seen or v.stop_gradient:
                return
            seen.add(id(v))
            for p in v._parents:
                visit(p)
            order.append(v)

        visit(self)
        cots = {id(self): seed}
        for v in reversed(order):
            cot = cots.pop(id(v), None)
            if cot is None:
                continue
            v.grad = cot if v.grad is None else v.grad + cot
            if v._vjp is None:
                continue
            parent_cots = v._vjp(cot)
            for p, pc in zip(v._parents, parent_cots):
                if p.stop_gradient:
                    continue
                cots[id(p)] = cots[id(p)] + pc if id(p) in cots else pc
            if not retain_graph:
                v._vjp, v._parents = None, ()

    def clear_grad(self):
        self.grad = None

    # -- array protocol -----------------------------------------------------
    @property
    def shape(self):
        return self.value.shape

    @property
    def dtype(self):
        return self.value.dtype

    @property
    def ndim(self):
        return self.value.ndim

    def numpy(self):
        import numpy as np

        return np.asarray(self.value)

    def item(self):
        return self.value.item()

    def __repr__(self):
        return (f'Variable(shape={self.value.shape}, '
                f'stop_gradient={self.stop_gradient},\n{self.value})')

    def __float__(self):
        return float(self.value)

    # -- operators ------------------------------------------------------
    def __add__(self, o):
        return self._apply(jnp.add, self, o)

    __radd__ = __add__

    def __sub__(self, o):
        return self._apply(jnp.subtract, self, o)

    def __rsub__(self, o):
        return self._apply(jnp.subtract, o, self)

    def __mul__(self, o):
        return self._apply(jnp.multiply, self, o)

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._apply(jnp.divide, self, o)

    def __rtruediv__(self, o):
        return self._apply(jnp.divide, o, self)

    def __matmul__(self, o):
        return self._apply(jnp.matmul, self, o)

    def __rmatmul__(self, o):
        return self._apply(jnp.matmul, o, self)

    def __pow__(self, o):
        return self._apply(jnp.power, self, o)

    def __neg__(self):
        return self._apply(jnp.negative, self)

    def __getitem__(self, idx):
        return self._apply(lambda v: v[idx], self)

    # -- common methods (mirroring Tensor methods) ------------------------
    def sum(self, axis=None, keepdim=False):
        return self._apply(
            lambda v: jnp.sum(v, axis=axis, keepdims=keepdim), self)

    def mean(self, axis=None, keepdim=False):
        return self._apply(
            lambda v: jnp.mean(v, axis=axis, keepdims=keepdim), self)

    def max(self, axis=None, keepdim=False):
        return self._apply(
            lambda v: jnp.max(v, axis=axis, keepdims=keepdim), self)

    def min(self, axis=None, keepdim=False):
        return self._apply(
            lambda v: jnp.min(v, axis=axis, keepdims=keepdim), self)

    def reshape(self, shape):
        return self._apply(lambda v: jnp.reshape(v, shape), self)

    def transpose(self, perm=None):
        return self._apply(lambda v: jnp.transpose(v, perm), self)

    def exp(self):
        return self._apply(jnp.exp, self)

    def log(self):
        return self._apply(jnp.log, self)

    def tanh(self):
        return self._apply(jnp.tanh, self)

    def sigmoid(self):
        return self._apply(jax.nn.sigmoid, self)

    def relu(self):
        return self._apply(jax.nn.relu, self)

    def sqrt(self):
        return self._apply(jnp.sqrt, self)

    def abs(self):
        return self._apply(jnp.abs, self)

    def detach(self):
        return Variable(self.value, stop_gradient=True)

    def cast(self, dtype):
        return self._apply(lambda v: v.astype(dtype), self)

    astype = cast


def to_variable(value, stop_gradient=False):
    """ref: paddle.to_tensor(..., stop_gradient=False) in dygraph —
    wrap an array for eager tape autograd."""
    if isinstance(value, Variable):
        return value
    return Variable(value, stop_gradient=stop_gradient)


def apply(fn, *args, **kwargs):
    """Record an arbitrary jax function application on the tape."""
    return Variable._apply(fn, *args, **kwargs)


def backward(tensors, grad_tensors=None):
    """ref: paddle.autograd.backward(tensors, grad_tensors)."""
    tensors = tensors if isinstance(tensors, (list, tuple)) else [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    for t, g in zip(tensors, grad_tensors):
        t.backward(g, retain_graph=True)
