"""Audio feature layers (ref: python/paddle/audio/features/layers.py:
Spectrogram:45, MelSpectrogram:130, LogMelSpectrogram:237, MFCC:344).

Each layer is a thin pytree module over `signal.stft` + the functional
helpers — the whole feature pipeline is one fused XLA program.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..nn.layer.base import Layer
from ..signal import stft
from .functional import (compute_fbank_matrix, create_dct, get_window,
                         power_to_db)


class Spectrogram(Layer):
    """ref: audio.features.Spectrogram — |STFT|^power."""

    def __init__(self, n_fft=512, hop_length=None, win_length=None,
                 window='hann', power=2.0, center=True, pad_mode='reflect',
                 dtype='float32'):
        super().__init__()
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        self.window = get_window(window, self.win_length, fftbins=True,
                                 dtype=dtype)

    def forward(self, x):
        spec = stft(x, self.n_fft, self.hop_length, self.win_length,
                    window=self.window, center=self.center,
                    pad_mode=self.pad_mode)
        return jnp.abs(spec) ** self.power


class MelSpectrogram(Layer):
    """ref: audio.features.MelSpectrogram — fbank @ spectrogram."""

    def __init__(self, sr=22050, n_fft=512, hop_length=None, win_length=None,
                 window='hann', power=2.0, center=True, pad_mode='reflect',
                 n_mels=64, f_min=50.0, f_max=None, htk=False, norm='slaney',
                 dtype='float32'):
        super().__init__()
        self.spectrogram = Spectrogram(n_fft, hop_length, win_length, window,
                                       power, center, pad_mode, dtype)
        self.fbank = compute_fbank_matrix(
            sr=sr, n_fft=n_fft, n_mels=n_mels, f_min=f_min, f_max=f_max,
            htk=htk, norm=norm, dtype=dtype)

    def forward(self, x):
        spec = self.spectrogram(x)                  # (..., F, T)
        return jnp.einsum('mf,...ft->...mt', self.fbank, spec)


class LogMelSpectrogram(Layer):
    """ref: audio.features.LogMelSpectrogram — power_to_db(mel)."""

    def __init__(self, sr=22050, n_fft=512, hop_length=None, win_length=None,
                 window='hann', power=2.0, center=True, pad_mode='reflect',
                 n_mels=64, f_min=50.0, f_max=None, htk=False, norm='slaney',
                 ref_value=1.0, amin=1e-10, top_db=None, dtype='float32'):
        super().__init__()
        self.mel_spectrogram = MelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center,
            pad_mode, n_mels, f_min, f_max, htk, norm, dtype)
        self.ref_value = ref_value
        self.amin = amin
        self.top_db = top_db

    def forward(self, x):
        return power_to_db(self.mel_spectrogram(x), self.ref_value,
                           self.amin, self.top_db)


class MFCC(Layer):
    """ref: audio.features.MFCC — DCT-II over the log-mel spectrogram."""

    def __init__(self, sr=22050, n_mfcc=40, n_fft=512, hop_length=None,
                 win_length=None, window='hann', power=2.0, center=True,
                 pad_mode='reflect', n_mels=64, f_min=50.0, f_max=None,
                 htk=False, norm='slaney', ref_value=1.0, amin=1e-10,
                 top_db=None, dtype='float32'):
        super().__init__()
        self.log_mel = LogMelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center,
            pad_mode, n_mels, f_min, f_max, htk, norm, ref_value, amin,
            top_db, dtype)
        self.dct = create_dct(n_mfcc, n_mels, dtype=dtype)

    def forward(self, x):
        mel = self.log_mel(x)                       # (..., n_mels, T)
        return jnp.einsum('mk,...mt->...kt', self.dct, mel)
