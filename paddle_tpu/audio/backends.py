"""Audio file IO (ref: python/paddle/audio/backends — wave_backend).

The reference's default backend decodes PCM WAV with the stdlib `wave`
module (soundfile being optional); this implements exactly that, so
`load/save/info` work with no extra dependency. No downloads here —
datasets read local files (SURVEY §6 scope).
"""
from __future__ import annotations

import wave as _wave

import numpy as np


class AudioInfo:
    """ref: paddle.audio.backends.backend.AudioInfo."""

    def __init__(self, sample_rate, num_samples, num_channels,
                 bits_per_sample, encoding='PCM_S'):
        self.sample_rate = sample_rate
        self.num_samples = num_samples
        self.num_channels = num_channels
        self.bits_per_sample = bits_per_sample
        self.encoding = encoding

    def __repr__(self):
        return (f'AudioInfo(sample_rate={self.sample_rate}, '
                f'num_samples={self.num_samples}, '
                f'num_channels={self.num_channels}, '
                f'bits_per_sample={self.bits_per_sample})')


def info(filepath):
    """ref: paddle.audio.info — WAV header metadata."""
    with _wave.open(str(filepath), 'rb') as f:
        return AudioInfo(f.getframerate(), f.getnframes(), f.getnchannels(),
                         f.getsampwidth() * 8)


def load(filepath, frame_offset=0, num_frames=-1, normalize=True,
         channels_first=True):
    """ref: paddle.audio.load — (waveform, sample_rate). normalize=True
    scales int PCM to [-1, 1] float32; channels_first gives (C, T)."""
    with _wave.open(str(filepath), 'rb') as f:
        sr = f.getframerate()
        n_ch = f.getnchannels()
        width = f.getsampwidth()
        f.setpos(frame_offset)
        n = f.getnframes() - frame_offset if num_frames < 0 else num_frames
        raw = f.readframes(n)
    if width == 3:
        # 24-bit PCM: widen each little-endian 3-byte sample to int32
        b = np.frombuffer(raw, np.uint8).reshape(-1, 3)
        data = (b[:, 0].astype(np.int32)
                | (b[:, 1].astype(np.int32) << 8)
                | (b[:, 2].astype(np.int32) << 16))
        data = ((data << 8) >> 8).reshape(-1, n_ch)  # sign-extend
        scale = float(2 ** 23)
    else:
        if width not in (1, 2, 4):
            raise ValueError(f'unsupported PCM sample width {width} bytes '
                             f'(1, 2, 3, and 4 are handled)')
        dtype = {1: np.uint8, 2: np.int16, 4: np.int32}[width]
        data = np.frombuffer(raw, dtype=dtype).reshape(-1, n_ch)
        if width == 1:  # 8-bit WAV is unsigned
            data = data.astype(np.int16) - 128
            scale = 128.0
        else:
            scale = float(2 ** (8 * width - 1))
    if normalize:
        wavf = (data.astype(np.float32) / scale)
    else:
        wavf = data
    if channels_first:
        wavf = wavf.T
    import jax.numpy as jnp

    return jnp.asarray(wavf), sr


def save(filepath, src, sample_rate, channels_first=True,
         encoding='PCM_S', bits_per_sample=16):
    """ref: paddle.audio.save — float waveform in [-1, 1] -> PCM WAV."""
    arr = np.asarray(src)
    if channels_first:
        arr = arr.T                           # -> (T, C)
    if arr.ndim == 1:
        arr = arr[:, None]
    width = bits_per_sample // 8
    if width not in (2, 4):
        raise ValueError('bits_per_sample must be 16 or 32')
    scale = 2 ** (bits_per_sample - 1) - 1
    pcm = np.clip(arr, -1.0, 1.0) * scale
    pcm = pcm.astype(np.int16 if width == 2 else np.int32)
    with _wave.open(str(filepath), 'wb') as f:
        f.setnchannels(arr.shape[1])
        f.setsampwidth(width)
        f.setframerate(int(sample_rate))
        f.writeframes(pcm.tobytes())


def list_available_backends():
    return ['wave_backend']


def get_current_backend():
    return 'wave_backend'


def set_backend(backend_name):
    if backend_name != 'wave_backend':
        raise NotImplementedError(
            'only the stdlib wave backend ships here (soundfile is an '
            'optional extra in the reference too)')
