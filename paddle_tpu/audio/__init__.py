"""paddle_tpu.audio (ref: python/paddle/audio) — feature extraction
(Spectrogram/Mel/LogMel/MFCC) + functional helpers over jnp/signal.stft,
stdlib-wave file IO (load/save/info), and download-free datasets.
"""
from . import backends  # noqa: F401
from . import datasets  # noqa: F401
from . import features  # noqa: F401
from . import functional  # noqa: F401
from .backends import info, load, save  # noqa: F401
from .features import MFCC, LogMelSpectrogram, MelSpectrogram, Spectrogram  # noqa: F401
