"""paddle_tpu.audio (ref: python/paddle/audio) — feature extraction
(Spectrogram/Mel/LogMel/MFCC) + functional helpers over jnp/signal.stft.
Backends/datasets (file IO, download) are out of scope per SURVEY §6.
"""
from . import features  # noqa: F401
from . import functional  # noqa: F401
from .features import MFCC, LogMelSpectrogram, MelSpectrogram, Spectrogram  # noqa: F401
