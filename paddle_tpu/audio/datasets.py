"""Audio datasets (ref: python/paddle/audio/datasets — TESS, ESC50).

Download-free like vision/text datasets: read local archives when a
path is given, deterministic synthetic audio otherwise.
"""
from __future__ import annotations

import os

import numpy as np

from ..io.dataset import Dataset


class _SyntheticAudio(Dataset):
    n_classes = 2
    sample_rate = 16000

    def __init__(self, mode='train', feat_type='raw', archive_dir=None,
                 size=64, duration=0.5, seed=0, **feat_kwargs):
        self.feat_type = feat_type
        self.feat_kwargs = feat_kwargs
        if archive_dir is not None:
            self._load_local(archive_dir, mode)
            return
        rng = np.random.default_rng(seed if mode == 'train' else seed + 1)
        t = int(self.sample_rate * duration)
        self.labels = rng.integers(0, self.n_classes, size).astype(np.int64)
        freqs = 220.0 * (1 + self.labels)
        ts = np.arange(t) / self.sample_rate
        self.waves = (np.sin(2 * np.pi * freqs[:, None] * ts[None])
                      + 0.05 * rng.normal(size=(size, t))).astype(np.float32)

    def _label_of(self, filename):
        """Class id from the dataset's filename convention (ESC50:
        '<fold>-<id>-<take>-<target>.wav'; TESS: emotion word)."""
        stem = os.path.splitext(os.path.basename(filename))[0]
        last = stem.split('-')[-1].split('_')[-1]
        if last.isdigit():
            return int(last) % self.n_classes
        return abs(hash(last)) % self.n_classes

    def _load_local(self, archive_dir, mode):
        from .backends import load as load_wav

        files = sorted(
            os.path.join(root, f)
            for root, _, names in os.walk(archive_dir)
            for f in names if f.lower().endswith('.wav'))
        if not files:
            raise FileNotFoundError(
                f'no .wav files under {archive_dir!r}')
        waves, labels, max_t = [], [], 0
        for f in files:
            wav, _ = load_wav(f, channels_first=True)
            mono = np.asarray(wav).mean(0)
            waves.append(mono.astype(np.float32))
            labels.append(self._label_of(f))
            max_t = max(max_t, mono.shape[0])
        self.waves = np.stack([np.pad(w, (0, max_t - len(w)))
                               for w in waves])
        self.labels = np.asarray(labels, np.int64)

    def _features(self, wav):
        if self.feat_type == 'raw':
            return wav
        from . import features as F

        cls = {'spectrogram': F.Spectrogram,
               'melspectrogram': F.MelSpectrogram,
               'logmelspectrogram': F.LogMelSpectrogram,
               'mfcc': F.MFCC}[self.feat_type]
        kwargs = dict(self.feat_kwargs)
        if self.feat_type != 'spectrogram':
            kwargs.setdefault('sr', self.sample_rate)  # Spectrogram has no sr
        return np.asarray(cls(**kwargs)(wav[None])[0])

    def __len__(self):
        return len(self.labels)

    def __getitem__(self, i):
        return self._features(self.waves[i]), self.labels[i]


class TESS(_SyntheticAudio):
    """ref: paddle.audio.datasets.TESS (speech emotion, 7 classes)."""

    n_classes = 7
    sample_rate = 24414


class ESC50(_SyntheticAudio):
    """ref: paddle.audio.datasets.ESC50 (environmental sounds, 50
    classes)."""

    n_classes = 50
    sample_rate = 44100
