"""Audio functional ops (ref: python/paddle/audio/functional/
functional.py:29-353, window.py).

Pure jnp closed forms — every helper is a traced function of static
sizes, so feature extraction pipelines jit end-to-end on TPU.
"""
from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np


def hz_to_mel(freq, htk=False):
    """ref: audio/functional.py::hz_to_mel (Slaney by default)."""
    freq = jnp.asarray(freq, jnp.float32)
    if htk:
        return 2595.0 * jnp.log10(1.0 + freq / 700.0)
    f_min, f_sp = 0.0, 200.0 / 3
    mels = (freq - f_min) / f_sp
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = math.log(6.4) / 27.0
    return jnp.where(freq >= min_log_hz,
                     min_log_mel + jnp.log(jnp.maximum(freq, 1e-10)
                                           / min_log_hz) / logstep,
                     mels)


def mel_to_hz(mel, htk=False):
    """ref: audio/functional.py::mel_to_hz."""
    mel = jnp.asarray(mel, jnp.float32)
    if htk:
        return 700.0 * (10.0 ** (mel / 2595.0) - 1.0)
    f_min, f_sp = 0.0, 200.0 / 3
    freqs = f_min + f_sp * mel
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = math.log(6.4) / 27.0
    return jnp.where(mel >= min_log_mel,
                     min_log_hz * jnp.exp(logstep * (mel - min_log_mel)),
                     freqs)


def mel_frequencies(n_mels=64, f_min=0.0, f_max=11025.0, htk=False,
                    dtype='float32'):
    """ref: audio/functional.py::mel_frequencies."""
    lo, hi = hz_to_mel(f_min, htk), hz_to_mel(f_max, htk)
    mels = jnp.linspace(lo, hi, n_mels)
    return mel_to_hz(mels, htk).astype(dtype)


def fft_frequencies(sr, n_fft, dtype='float32'):
    """ref: audio/functional.py::fft_frequencies."""
    return jnp.linspace(0, sr / 2, 1 + n_fft // 2).astype(dtype)


def compute_fbank_matrix(sr, n_fft, n_mels=64, f_min=0.0, f_max=None,
                         htk=False, norm='slaney', dtype='float32'):
    """ref: audio/functional.py::compute_fbank_matrix — triangular mel
    filterbank, (n_mels, 1 + n_fft//2)."""
    f_max = f_max if f_max is not None else sr / 2.0
    fft_f = fft_frequencies(sr, n_fft)
    mel_f = mel_frequencies(n_mels + 2, f_min, f_max, htk)
    fdiff = jnp.diff(mel_f)
    ramps = mel_f[:, None] - fft_f[None, :]          # (n_mels+2, F)
    lower = -ramps[:-2] / fdiff[:-1, None]
    upper = ramps[2:] / fdiff[1:, None]
    weights = jnp.maximum(0.0, jnp.minimum(lower, upper))
    if norm == 'slaney':
        enorm = 2.0 / (mel_f[2:n_mels + 2] - mel_f[:n_mels])
        weights = weights * enorm[:, None]
    return weights.astype(dtype)


def power_to_db(magnitude, ref_value=1.0, amin=1e-10, top_db=80.0):
    """ref: audio/functional.py::power_to_db."""
    x = jnp.asarray(magnitude)
    log_spec = 10.0 * jnp.log10(jnp.maximum(x, amin))
    log_spec = log_spec - 10.0 * math.log10(max(ref_value, amin))
    if top_db is not None:
        log_spec = jnp.maximum(log_spec, jnp.max(log_spec) - top_db)
    return log_spec


def create_dct(n_mfcc, n_mels, norm='ortho', dtype='float32'):
    """ref: audio/functional.py::create_dct — DCT-II basis
    (n_mels, n_mfcc)."""
    n = jnp.arange(n_mels, dtype=jnp.float32)
    k = jnp.arange(n_mfcc, dtype=jnp.float32)
    basis = jnp.cos(math.pi / n_mels * (n[:, None] + 0.5) * k[None, :])
    if norm == 'ortho':
        scale = jnp.full((n_mfcc,), math.sqrt(2.0 / n_mels))
        scale = scale.at[0].set(math.sqrt(1.0 / n_mels))
        basis = basis * scale[None, :]
    else:
        basis = basis * 2.0
    return basis.astype(dtype)


# -- windows (ref: audio/functional/window.py::get_window) ------------------

def _hann(M, sym=True):
    return _general_cosine(M, [0.5, 0.5], sym)


def _hamming(M, sym=True):
    return _general_cosine(M, [0.54, 0.46], sym)


def _blackman(M, sym=True):
    return _general_cosine(M, [0.42, 0.5, 0.08], sym)


def _general_cosine(M, a, sym=True):
    if M <= 1:
        return jnp.ones((max(M, 0),))
    N = M if sym else M + 1
    fac = jnp.linspace(-math.pi, math.pi, N)
    w = jnp.zeros((N,))
    for i, c in enumerate(a):
        w = w + c * jnp.cos(i * fac)
    return w[:M]


def _bartlett(M, sym=True):
    if M <= 1:
        return jnp.ones((max(M, 0),))
    N = M if sym else M + 1
    n = jnp.arange(N, dtype=jnp.float32)
    w = 1.0 - jnp.abs(2.0 * n / (N - 1) - 1.0)
    return w[:M]


def _gaussian(M, std, sym=True):
    if M <= 1:
        return jnp.ones((max(M, 0),))
    N = M if sym else M + 1
    n = jnp.arange(N, dtype=jnp.float32) - (N - 1) / 2.0
    return jnp.exp(-0.5 * (n / std) ** 2)[:M]


def _cosine(M, sym=True):
    if M <= 1:
        return jnp.ones((max(M, 0),))
    N = M if sym else M + 1
    return jnp.sin(math.pi / N * (jnp.arange(N) + 0.5))[:M]


def _triang(M, sym=True):
    if M <= 1:
        return jnp.ones((max(M, 0),))
    N = M if sym else M + 1
    n = jnp.arange(1, (N + 1) // 2 + 1, dtype=jnp.float32)
    if N % 2 == 0:
        w = (2 * n - 1.0) / N
        w = jnp.concatenate([w, w[::-1]])
    else:
        w = 2 * n / (N + 1.0)
        w = jnp.concatenate([w, w[-2::-1]])
    return w[:M]


def _exponential(M, tau=1.0, sym=True):
    if M <= 1:
        return jnp.ones((max(M, 0),))
    N = M if sym else M + 1
    n = jnp.arange(N, dtype=jnp.float32)
    return jnp.exp(-jnp.abs(n - (N - 1) / 2.0) / tau)[:M]


_WINDOWS = {
    'hann': _hann, 'hamming': _hamming, 'blackman': _blackman,
    'bartlett': _bartlett, 'cosine': _cosine, 'triang': _triang,
}
_WINDOWS_PARAM = {'gaussian': _gaussian, 'exponential': _exponential}


def get_window(window, win_length, fftbins=True, dtype='float32'):
    """ref: audio/functional/window.py::get_window. `window` is a name
    or (name, param) tuple; fftbins=True gives the periodic variant."""
    sym = not fftbins
    if isinstance(window, str):
        name, args = window, ()
    elif isinstance(window, tuple):
        name, args = window[0], tuple(window[1:])
    else:
        raise ValueError(f'unsupported window spec {window!r}')
    if name in _WINDOWS:
        w = _WINDOWS[name](win_length, sym=sym)
    elif name in _WINDOWS_PARAM:
        w = _WINDOWS_PARAM[name](win_length, *args, sym=sym)
    else:
        raise ValueError(f'unknown window {name!r}')
    return w.astype(dtype)
