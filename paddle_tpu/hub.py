"""paddle_tpu.hub (ref: python/paddle/hub.py — list/help/load).

Local-directory sources only: this environment has no network egress,
and the reference's github/gitee fetch is transport, not semantics. A
hubconf.py in the source directory declares entrypoints exactly as the
reference expects.
"""
from __future__ import annotations

import importlib.util
import os

__all__ = ['list', 'help', 'load']

_builtin_list = list


def _load_hubconf(repo_dir):
    path = os.path.join(repo_dir, 'hubconf.py')
    if not os.path.exists(path):
        raise FileNotFoundError(
            f'no hubconf.py in {repo_dir!r} (hub sources must be local '
            f'directories — no network egress on this build)')
    spec = importlib.util.spec_from_file_location('hubconf', path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def list(repo_dir, source='local', force_reload=False):
    """ref: paddle.hub.list — entrypoint names exposed by hubconf.py."""
    if source != 'local':
        raise ValueError("only source='local' is supported (no egress)")
    mod = _load_hubconf(repo_dir)
    return _builtin_list(
        n for n in dir(mod)
        if callable(getattr(mod, n)) and not n.startswith('_'))


def help(repo_dir, model, source='local', force_reload=False):
    """ref: paddle.hub.help — the entrypoint's docstring."""
    if source != 'local':
        raise ValueError("only source='local' is supported (no egress)")
    return getattr(_load_hubconf(repo_dir), model).__doc__


def load(repo_dir, model, source='local', force_reload=False, **kwargs):
    """ref: paddle.hub.load — call the entrypoint."""
    if source != 'local':
        raise ValueError("only source='local' is supported (no egress)")
    return getattr(_load_hubconf(repo_dir), model)(**kwargs)
