"""Native (C++) runtime helpers.

Builds `ring_buffer.cpp` into a shared library on first import (g++,
cached beside the package) and exposes a ctypes binding plus the
`ShmRing` Python wrapper used by the DataLoader's shared-memory fast
path. Falls back gracefully (AVAILABLE=False) if no compiler.
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, 'ring_buffer.cpp')

AVAILABLE = False
_lib = None


def _lib_path():
    """Cache dir keyed on the source hash: a changed .cpp always rebuilds,
    and no binary artifact lives in the source tree / version control."""
    with open(_SRC, 'rb') as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    cache = os.environ.get(
        'PADDLE_TPU_CACHE',
        os.path.join(os.path.expanduser('~'), '.cache', 'paddle_tpu'))
    os.makedirs(cache, exist_ok=True)
    return os.path.join(cache, f'_libring-{digest}.so')


def _build(lib_path):
    # atomic: build to a temp name, rename into place
    tmp = lib_path + f'.tmp{os.getpid()}'
    cmd = ['g++', '-O3', '-shared', '-fPIC', '-std=c++17', _SRC, '-o', tmp]
    subprocess.run(cmd, check=True, capture_output=True)
    os.replace(tmp, lib_path)


def _load():
    global _lib, AVAILABLE
    try:
        lib_path = _lib_path()
        if not os.path.exists(lib_path):
            _build(lib_path)
        _lib = ctypes.CDLL(lib_path)
        _lib.rb_init.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        _lib.rb_push.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64]
        _lib.rb_push.restype = ctypes.c_int
        _lib.rb_peek.argtypes = [ctypes.c_void_p]
        _lib.rb_peek.restype = ctypes.c_uint64
        _lib.rb_pop.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64]
        _lib.rb_pop.restype = ctypes.c_int64
        _lib.rb_used.argtypes = [ctypes.c_void_p]
        _lib.rb_used.restype = ctypes.c_uint64
        AVAILABLE = True
    except Exception:
        AVAILABLE = False


_load()


class ShmRing:
    """SPSC ring over a multiprocessing.shared_memory block.

    Producer (worker) and consumer (main) each construct this around the
    same shm name; the C++ side does the lock-free cursor work.
    """

    HEADER = 24

    def __init__(self, name=None, capacity=64 * 1024 * 1024, create=True):
        from multiprocessing import shared_memory

        if not AVAILABLE:
            raise RuntimeError('native ring buffer unavailable (no g++?)')
        if create:
            self.shm = shared_memory.SharedMemory(
                name=name, create=True, size=capacity + self.HEADER)
            self._addr_init()
            _lib.rb_init(self._addr, capacity + self.HEADER)
        else:
            self.shm = shared_memory.SharedMemory(name=name, create=False)
            self._addr_init()
        self.name = self.shm.name
        self._owner = create

    def _addr_init(self):
        self._buf = self.shm.buf
        self._addr = ctypes.addressof(
            (ctypes.c_ubyte * len(self._buf)).from_buffer(self._buf))

    def push(self, payload: bytes) -> bool:
        return bool(_lib.rb_push(self._addr, payload, len(payload)))

    def pop(self):
        """Returns bytes or None if empty."""
        n = _lib.rb_peek(self._addr)
        if n == 0:
            return None
        out = ctypes.create_string_buffer(int(n))
        got = _lib.rb_pop(self._addr, out, n)
        if got <= 0:
            return None
        return out.raw[:got]

    def used(self) -> int:
        return int(_lib.rb_used(self._addr))

    def close(self, unlink=None):
        # release the exported buffer before closing the mapping
        import gc

        self._addr = None
        self._buf = None
        gc.collect()
        try:
            self.shm.close()
            if unlink if unlink is not None else self._owner:
                self.shm.unlink()
        except Exception:
            pass


# -- numpy record codec -----------------------------------------------------
import struct

import numpy as np


def encode_batch(arrays) -> bytes:
    """Serialise a flat list of numpy arrays: [count][per-array header+raw]."""
    parts = [struct.pack('<I', len(arrays))]
    for a in arrays:
        a = np.ascontiguousarray(a)
        dt = np.dtype(a.dtype).str.encode()
        parts.append(struct.pack('<I', len(dt)))
        parts.append(dt)
        parts.append(struct.pack('<I', a.ndim))
        parts.append(struct.pack(f'<{a.ndim}q', *a.shape))
        raw = a.tobytes()
        parts.append(struct.pack('<Q', len(raw)))
        parts.append(raw)
    return b''.join(parts)


def decode_batch(payload: bytes):
    off = 0
    (count,) = struct.unpack_from('<I', payload, off)
    off += 4
    out = []
    for _ in range(count):
        (dtlen,) = struct.unpack_from('<I', payload, off)
        off += 4
        dt = np.dtype(payload[off:off + dtlen].decode())
        off += dtlen
        (ndim,) = struct.unpack_from('<I', payload, off)
        off += 4
        shape = struct.unpack_from(f'<{ndim}q', payload, off)
        off += 8 * ndim
        (rawlen,) = struct.unpack_from('<Q', payload, off)
        off += 8
        arr = np.frombuffer(payload, dt, count=int(np.prod(shape)) if ndim else 1,
                            offset=off).reshape(shape)
        off += rawlen
        out.append(arr.copy())
    return out
