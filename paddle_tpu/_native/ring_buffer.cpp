// Shared-memory SPSC ring buffer — the worker→main fast path of the
// DataLoader (ref capability: the reference's C++ DataLoader workers +
// shared-memory tensor transport in paddle/fluid/operators/reader and
// python/paddle/io/dataloader/worker.py's shared-memory path).
//
// Layout of the shared region (Python allocates it, C++ operates on it):
//   [0]  u64 head   — consumer cursor (bytes consumed, monotonically grows)
//   [8]  u64 tail   — producer cursor (bytes written, monotonically grows)
//   [16] u64 capacity of the data area
//   [24] data[capacity]
//
// Records are length-prefixed (u64 le) byte blobs, written contiguously
// with wrap-around. One producer (worker process), one consumer (main).
// Lock-free: release/acquire on the cursors.

#include <atomic>
#include <cstdint>
#include <cstring>

namespace {

constexpr uint64_t HDR = 24;

struct Ctrl {
    std::atomic<uint64_t> head;
    std::atomic<uint64_t> tail;
    uint64_t capacity;
};

static_assert(sizeof(std::atomic<uint64_t>) == 8, "atomic u64 must be 8 bytes");

inline Ctrl* ctrl(uint8_t* base) { return reinterpret_cast<Ctrl*>(base); }
inline uint8_t* data(uint8_t* base) { return base + HDR; }

// copy len bytes into the ring at logical offset `pos` (wraps)
void ring_write(uint8_t* d, uint64_t cap, uint64_t pos, const uint8_t* src,
                uint64_t len) {
    uint64_t off = pos % cap;
    uint64_t first = (off + len <= cap) ? len : cap - off;
    std::memcpy(d + off, src, first);
    if (first < len) std::memcpy(d, src + first, len - first);
}

void ring_read(const uint8_t* d, uint64_t cap, uint64_t pos, uint8_t* dst,
               uint64_t len) {
    uint64_t off = pos % cap;
    uint64_t first = (off + len <= cap) ? len : cap - off;
    std::memcpy(dst, d + off, first);
    if (first < len) std::memcpy(dst + first, d, len - first);
}

}  // namespace

extern "C" {

void rb_init(uint8_t* base, uint64_t total_size) {
    Ctrl* c = ctrl(base);
    c->head.store(0, std::memory_order_relaxed);
    c->tail.store(0, std::memory_order_relaxed);
    c->capacity = total_size - HDR;
}

// Returns 1 on success, 0 if the record does not fit in current free space.
int rb_push(uint8_t* base, const uint8_t* src, uint64_t len) {
    Ctrl* c = ctrl(base);
    uint64_t cap = c->capacity;
    uint64_t need = len + 8;
    if (need > cap) return 0;  // can never fit
    uint64_t head = c->head.load(std::memory_order_acquire);
    uint64_t tail = c->tail.load(std::memory_order_relaxed);
    if (tail - head + need > cap) return 0;  // full — caller retries
    uint64_t le_len = len;
    ring_write(data(base), cap, tail, reinterpret_cast<uint8_t*>(&le_len), 8);
    ring_write(data(base), cap, tail + 8, src, len);
    c->tail.store(tail + need, std::memory_order_release);
    return 1;
}

// Returns the record size if one is pending (without consuming), 0 if empty.
uint64_t rb_peek(uint8_t* base) {
    Ctrl* c = ctrl(base);
    uint64_t head = c->head.load(std::memory_order_relaxed);
    uint64_t tail = c->tail.load(std::memory_order_acquire);
    if (tail == head) return 0;
    uint64_t len;
    ring_read(data(base), c->capacity, head, reinterpret_cast<uint8_t*>(&len), 8);
    return len;
}

// Pops one record into dst (must hold >= rb_peek() bytes).
// Returns bytes written, 0 if empty, -1 if dst_cap too small.
int64_t rb_pop(uint8_t* base, uint8_t* dst, uint64_t dst_cap) {
    Ctrl* c = ctrl(base);
    uint64_t head = c->head.load(std::memory_order_relaxed);
    uint64_t tail = c->tail.load(std::memory_order_acquire);
    if (tail == head) return 0;
    uint64_t len;
    ring_read(data(base), c->capacity, head, reinterpret_cast<uint8_t*>(&len), 8);
    if (len > dst_cap) return -1;
    ring_read(data(base), c->capacity, head + 8, dst, len);
    c->head.store(head + 8 + len, std::memory_order_release);
    return static_cast<int64_t>(len);
}

uint64_t rb_used(uint8_t* base) {
    Ctrl* c = ctrl(base);
    return c->tail.load(std::memory_order_acquire) -
           c->head.load(std::memory_order_acquire);
}

}  // extern "C"
