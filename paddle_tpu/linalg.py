"""paddle_tpu.linalg namespace (ref: python/paddle/linalg.py)."""
from .tensor.linalg import *  # noqa: F401,F403
from .tensor.math import matmul  # noqa: F401
