"""tracelint CLI.

    python -m paddle_tpu.analysis [paths...]        # lint vs baseline
    tracelint paddle_tpu/                           # console script
    tracelint --write-baseline                      # accept current debt
    tracelint --list-rules

Exit codes: 0 clean (modulo baseline), 1 new violations, 2 usage/IO
error.  Config comes from `[tool.tracelint]` in pyproject.toml at
`--root` (default: cwd); CLI flags win over config.
"""
from __future__ import annotations

import argparse
import os
import sys

from .config import load_config
from .engine import (filter_new, format_json, format_text, lint_paths,
                     load_baseline, write_baseline)
from .rules import all_rules


def _build_parser():
    p = argparse.ArgumentParser(
        prog='tracelint',
        description='AST-based TPU tracer-safety analyzer: enforces the '
                    'jit/donation/host-sync serving contract.')
    p.add_argument('paths', nargs='*',
                   help='files/directories to lint (default: from '
                        '[tool.tracelint] paths, else paddle_tpu)')
    p.add_argument('--root', default=None,
                   help='project root holding pyproject.toml and the '
                        'baseline (default: cwd)')
    p.add_argument('--format', choices=('text', 'json'), default='text')
    p.add_argument('--baseline', default=None,
                   help='baseline JSON path (default: from config)')
    p.add_argument('--no-baseline', action='store_true',
                   help='report every violation, ignoring the baseline')
    p.add_argument('--write-baseline', action='store_true',
                   help='write the current violations as the new baseline '
                        'and exit 0')
    p.add_argument('--select', default=None,
                   help='comma-separated rule ids to run (default: all)')
    p.add_argument('--list-rules', action='store_true')
    return p


def main(argv=None):
    args = _build_parser().parse_args(argv)
    if args.list_rules:
        for rule in all_rules():
            print(f'{rule.id} [{rule.severity}] {rule.name}: '
                  f'{rule.description}')
        return 0

    root = os.path.abspath(args.root or os.getcwd())
    cfg = load_config(root)
    select = ([s.strip() for s in args.select.split(',') if s.strip()]
              if args.select else cfg.select)
    try:
        rules = all_rules(select or None)
    except KeyError as e:
        print(f'tracelint: {e.args[0]}', file=sys.stderr)
        return 2

    paths = args.paths or cfg.paths
    paths = [p if os.path.isabs(p) else os.path.join(root, p)
             for p in paths]
    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        print(f'tracelint: no such path(s): {missing}', file=sys.stderr)
        return 2

    violations = lint_paths(paths, rules=rules, root=root,
                            exclude=cfg.exclude)

    baseline_path = args.baseline or cfg.baseline
    if not os.path.isabs(baseline_path):
        baseline_path = os.path.join(root, baseline_path)

    if args.write_baseline:
        counts = write_baseline(violations, baseline_path)
        print(f'tracelint: wrote baseline with {len(violations)} '
              f'violation(s) across {len(counts)} (file, rule) key(s) '
              f'to {baseline_path}')
        return 0

    baselined = 0
    if not args.no_baseline:
        baseline = load_baseline(baseline_path)
        new = filter_new(violations, baseline)
        baselined = len(violations) - len(new)
        violations = new

    if args.format == 'json':
        print(format_json(violations, baselined=baselined))
    else:
        print(format_text(violations, baselined=baselined))
    return 1 if violations else 0


if __name__ == '__main__':
    sys.exit(main())
