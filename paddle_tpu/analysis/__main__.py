"""tracelint / mosaiclint CLI.

    python -m paddle_tpu.analysis [paths...]        # tracelint (AST)
    python -m paddle_tpu.analysis --mosaic [paths]  # mosaiclint (jaxpr)
    tracelint paddle_tpu/                           # console script
    mosaiclint                                      # console script
    tracelint --write-baseline                      # accept current debt
    mosaiclint --list-rules

Exit codes: 0 clean (modulo baseline/suppressions), 1 new
ERROR-severity violations (warnings print but never gate — they exist
to be confirmed on chip, not to block it), 2 usage/IO error.  Config
comes from `[tool.tracelint]` /
`[tool.mosaiclint]` in pyproject.toml at `--root` (default: cwd); CLI
flags win over config.  mosaiclint traces the kernel registry with
jax, so pin `JAX_PLATFORMS=cpu` where touching an accelerator backend
is unwanted (bench.py's gates do).
"""
from __future__ import annotations

import argparse
import os
import sys

from .config import load_config, load_mosaic_config
from .engine import (filter_new, format_json, format_text, lint_paths,
                     load_baseline, write_baseline)
from .rules import all_rules


def _build_parser():
    p = argparse.ArgumentParser(
        prog='tracelint',
        description='Static TPU analyzers: tracelint enforces the '
                    'jit/donation/host-sync serving contract over the '
                    'AST; --mosaic (mosaiclint) enforces Mosaic/TPU '
                    'lowering legality over traced pallas kernels.')
    p.add_argument('paths', nargs='*',
                   help='files/directories to lint (default: from '
                        'config; with --mosaic, filters registry '
                        'entries by kernel source file)')
    p.add_argument('--mosaic', action='store_true',
                   help='run mosaiclint (ML rules over the pallas '
                        'kernel registry) instead of tracelint')
    p.add_argument('--root', default=None,
                   help='project root holding pyproject.toml and the '
                        'baseline (default: cwd)')
    p.add_argument('--format', choices=('text', 'json'), default='text')
    p.add_argument('--baseline', default=None,
                   help='baseline JSON path (default: from config)')
    p.add_argument('--no-baseline', action='store_true',
                   help='report every violation, ignoring the baseline')
    p.add_argument('--write-baseline', action='store_true',
                   help='write the current violations as the new baseline '
                        'and exit 0')
    p.add_argument('--select', default=None,
                   help='comma-separated rule ids to run (default: all)')
    p.add_argument('--list-rules', action='store_true')
    return p


def _finish(args, violations, baseline_path, baselined_filter=True,
            suppressed=0, extra=None):
    """Shared baseline-filter + output + exit-code tail of both modes."""
    if args.write_baseline:
        counts = write_baseline(violations, baseline_path)
        print(f'{"mosaiclint" if args.mosaic else "tracelint"}: wrote '
              f'baseline with {len(violations)} violation(s) across '
              f'{len(counts)} (file, rule) key(s) to {baseline_path}')
        return 0

    baselined = 0
    if baselined_filter and not args.no_baseline:
        baseline = load_baseline(baseline_path)
        new = filter_new(violations, baseline)
        baselined = len(violations) - len(new)
        violations = new

    if args.format == 'json':
        print(format_json(violations, baselined=baselined,
                          suppressed=suppressed, extra=extra))
    else:
        print(format_text(violations, baselined=baselined,
                          suppressed=suppressed))
    # warnings (ML003 lane-reshape, ML006 near-budget) are advisory by
    # design: they surface in the output and the baseline but must not
    # fail CI — only error-severity violations gate
    return 1 if any(v.severity == 'error' for v in violations) else 0


def _main_tracelint(args, root):
    cfg = load_config(root)
    select = ([s.strip() for s in args.select.split(',') if s.strip()]
              if args.select else cfg.select)
    try:
        rules = all_rules(select or None)
    except KeyError as e:
        print(f'tracelint: {e.args[0]}', file=sys.stderr)
        return 2

    paths = args.paths or cfg.paths
    paths = [p if os.path.isabs(p) else os.path.join(root, p)
             for p in paths]
    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        print(f'tracelint: no such path(s): {missing}', file=sys.stderr)
        return 2

    violations = lint_paths(paths, rules=rules, root=root,
                            exclude=cfg.exclude)
    baseline_path = args.baseline or cfg.baseline
    if not os.path.isabs(baseline_path):
        baseline_path = os.path.join(root, baseline_path)
    return _finish(args, violations, baseline_path)


def _main_mosaic(args, root):
    # imported here: mosaiclint needs jax, plain tracelint must not
    from .mosaic import lint_and_report
    from .mosaic.registry import entries_for
    from .mosaic.rules import all_rules as all_ml_rules

    cfg = load_mosaic_config(root)
    select = ([s.strip() for s in args.select.split(',') if s.strip()]
              if args.select else cfg.select)
    try:
        rules = all_ml_rules(select or None)
    except KeyError as e:
        print(f'mosaiclint: {e.args[0]}', file=sys.stderr)
        return 2

    paths = args.paths or cfg.paths
    try:
        entries = entries_for(paths or None, root=root)
    except Exception as e:  # noqa: BLE001 - registry import failure
        print(f'mosaiclint: registry failed to load: '
              f'{type(e).__name__}: {e}', file=sys.stderr)
        return 2
    if paths and not entries:
        print(f'mosaiclint: no registered kernels under {paths}',
              file=sys.stderr)
        return 2

    try:
        # one trace per suite covers both the rules and the vmem map
        violations, suppressed, vmem = lint_and_report(
            entries, rules=rules, root=root)
    except ValueError as e:
        # a registry misconfiguration (reasonless suppression) is a
        # usage error, not a kernel violation — rc 2, never rc 1
        print(f'mosaiclint: {e}', file=sys.stderr)
        return 2
    baseline_path = args.baseline or cfg.baseline
    if not os.path.isabs(baseline_path):
        baseline_path = os.path.join(root, baseline_path)
    extra = {'vmem': vmem} if args.format == 'json' else None
    return _finish(args, violations, baseline_path,
                   suppressed=len(suppressed), extra=extra)


def main(argv=None):
    args = _build_parser().parse_args(argv)
    if args.list_rules:
        if args.mosaic:
            from .mosaic.rules import all_rules as all_ml_rules

            rules = all_ml_rules()
        else:
            rules = all_rules()
        for rule in rules:
            print(f'{rule.id} [{rule.severity}] {rule.name}: '
                  f'{rule.description}')
        return 0

    root = os.path.abspath(args.root or os.getcwd())
    if args.mosaic:
        return _main_mosaic(args, root)
    return _main_tracelint(args, root)


def mosaic_main(argv=None):
    """Entry point for the `mosaiclint` console script."""
    argv = list(sys.argv[1:] if argv is None else argv)
    return main(['--mosaic'] + argv)


if __name__ == '__main__':
    sys.exit(main())
