"""tracelint / mosaiclint / shardlint / hlolint / statelint CLI.

    python -m paddle_tpu.analysis [paths...]        # tracelint (AST)
    python -m paddle_tpu.analysis --mosaic [paths]  # mosaiclint (jaxpr)
    python -m paddle_tpu.analysis --shard [paths]   # shardlint (GSPMD)
    python -m paddle_tpu.analysis --hlo [paths]     # hlolint (XLA HLO)
    python -m paddle_tpu.analysis --state [paths]   # statelint (engine state)
    python -m paddle_tpu.analysis --all             # all five families
    tracelint paddle_tpu/                           # console script
    mosaiclint                                      # console script
    shardlint                                       # console script
    hlolint                                         # console script
    statelint                                       # console script
    tracelint --write-baseline                      # accept current debt
    hlolint --write-fingerprints                    # re-baseline HL006
    shardlint --list-rules

`--mosaic` / `--shard` / `--hlo` / `--state` are mutually exclusive —
one invocation runs exactly one analyzer family; `--all` runs the
five families in sequence with ONE shared JSON report schema and a
single combined exit code (the entrypoint CI and bench.py call —
tools/lint_gate.sh wraps it with the env pins).

Exit codes: 0 clean (modulo baseline/suppressions), 1 new
ERROR-severity violations (warnings print but never gate — they exist
to be confirmed on chip, not to block it), 2 usage/IO error.  `--all`
combines: 1 if any family gated, else 2 if any family errored, else
0.  Config comes from `[tool.tracelint]` / `[tool.mosaiclint]` /
`[tool.shardlint]` / `[tool.hlolint]` / `[tool.statelint]` in
pyproject.toml at `--root` (default: cwd); CLI flags win over config.
mosaiclint traces the kernel registry with jax, shardlint/hlolint
compile their registries, and statelint builds tiny CPU engines for
its live wire schemas, so pin `JAX_PLATFORMS=cpu` where touching an
accelerator backend is unwanted (bench.py's gates do); shardlint and
hlolint additionally force the 8-virtual-device flag themselves when
the backend has not initialised yet.
"""
from __future__ import annotations

import argparse
import os
import sys

from .config import (load_config, load_hlo_config, load_mosaic_config,
                     load_shard_config, load_state_config)
from .engine import (filter_new, format_json, format_text, lint_paths,
                     load_baseline, write_baseline)
from .rules import all_rules


def _build_parser():
    p = argparse.ArgumentParser(
        prog='tracelint',
        description='Static TPU analyzers: tracelint enforces the '
                    'jit/donation/host-sync serving contract over the '
                    'AST; --mosaic (mosaiclint) enforces Mosaic/TPU '
                    'lowering legality over traced pallas kernels; '
                    '--shard (shardlint) enforces sharding and '
                    'communication budgets over the distributed-layer '
                    'registry on a virtual 8-device mesh.')
    p.add_argument('paths', nargs='*',
                   help='files/directories to lint (default: from '
                        'config; with --mosaic/--shard, filters '
                        'registry entries by anchor source file)')
    p.add_argument('--mosaic', action='store_true',
                   help='run mosaiclint (ML rules over the pallas '
                        'kernel registry) instead of tracelint')
    p.add_argument('--shard', action='store_true',
                   help='run shardlint (SL rules over the distributed '
                        'sharding registry) instead of tracelint')
    p.add_argument('--hlo', action='store_true',
                   help='run hlolint (HL rules over compiled XLA '
                        'artifacts of the serving/AOT registry) '
                        'instead of tracelint')
    p.add_argument('--state', action='store_true',
                   help='run statelint (ST rules over the stateful '
                        'engine classes: snapshot/restore, KV '
                        'migration, and AOT-refusal coverage of every '
                        'mutable attribute) instead of tracelint')
    p.add_argument('--all', action='store_true',
                   help='run all five analyzer families with one '
                        'combined report and exit code')
    p.add_argument('--write-fingerprints', action='store_true',
                   help='(hlolint) compile every suite and write the '
                        'HL006 retrace-fingerprint baseline, then '
                        'exit 0')
    p.add_argument('--root', default=None,
                   help='project root holding pyproject.toml and the '
                        'baseline (default: cwd)')
    p.add_argument('--format', choices=('text', 'json'), default='text')
    p.add_argument('--baseline', default=None,
                   help='baseline JSON path (default: from config)')
    p.add_argument('--no-baseline', action='store_true',
                   help='report every violation, ignoring the baseline')
    p.add_argument('--write-baseline', action='store_true',
                   help='write the current violations as the new baseline '
                        'and exit 0')
    p.add_argument('--select', default=None,
                   help='comma-separated rule ids to run (default: all)')
    p.add_argument('--list-rules', action='store_true')
    return p


def _family(args):
    return ('mosaiclint' if args.mosaic
            else 'shardlint' if args.shard
            else 'hlolint' if args.hlo
            else 'statelint' if args.state else 'tracelint')


def _finish(args, violations, baseline_path, baselined_filter=True,
            suppressed=0, extra=None):
    """Shared baseline-filter + output + exit-code tail of all modes."""
    if args.write_baseline:
        counts = write_baseline(violations, baseline_path)
        print(f'{_family(args)}: wrote '
              f'baseline with {len(violations)} violation(s) across '
              f'{len(counts)} (file, rule) key(s) to {baseline_path}')
        return 0

    baselined = 0
    if baselined_filter and not args.no_baseline:
        baseline = load_baseline(baseline_path)
        new = filter_new(violations, baseline)
        baselined = len(violations) - len(new)
        violations = new

    if args.format == 'json':
        print(format_json(violations, baselined=baselined,
                          suppressed=suppressed, extra=extra))
    else:
        print(format_text(violations, baselined=baselined,
                          suppressed=suppressed))
    # warnings (ML003 lane-reshape, ML006 near-budget, SL001
    # indivisible-dim, SL002 budget-slack) are advisory by design: they
    # surface in the output and the baseline but must not fail CI —
    # only error-severity violations gate
    return 1 if any(v.severity == 'error' for v in violations) else 0


def _main_tracelint(args, root):
    cfg = load_config(root)
    select = ([s.strip() for s in args.select.split(',') if s.strip()]
              if args.select else cfg.select)
    try:
        rules = all_rules(select or None)
    except KeyError as e:
        print(f'tracelint: {e.args[0]}', file=sys.stderr)
        return 2

    paths = args.paths or cfg.paths
    paths = [p if os.path.isabs(p) else os.path.join(root, p)
             for p in paths]
    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        print(f'tracelint: no such path(s): {missing}', file=sys.stderr)
        return 2

    violations = lint_paths(paths, rules=rules, root=root,
                            exclude=cfg.exclude)
    baseline_path = args.baseline or cfg.baseline
    if not os.path.isabs(baseline_path):
        baseline_path = os.path.join(root, baseline_path)
    return _finish(args, violations, baseline_path)


def _registry_main(args, root, name, cfg, all_rules_fn, entries_for_fn,
                   lint_fn, extra_key):
    """Shared mosaiclint/shardlint driver: both lint a REGISTRY of
    traced suites instead of a file tree, differing only in the
    registry, the rule set, and the per-suite detail blob (`vmem` vs
    `comm`) their JSON output carries."""
    select = ([s.strip() for s in args.select.split(',') if s.strip()]
              if args.select else cfg.select)
    try:
        rules = all_rules_fn(select or None)
    except KeyError as e:
        print(f'{name}: {e.args[0]}', file=sys.stderr)
        return 2

    paths = args.paths or cfg.paths
    try:
        entries = entries_for_fn(paths or None, root=root)
    except Exception as e:  # noqa: BLE001 - registry import failure
        print(f'{name}: registry failed to load: '
              f'{type(e).__name__}: {e}', file=sys.stderr)
        return 2
    if paths and not entries:
        print(f'{name}: no registered suites under {paths}',
              file=sys.stderr)
        return 2

    try:
        # one trace per suite covers both the rules and the detail blob
        violations, suppressed, detail = lint_fn(entries, rules=rules,
                                                 root=root)
    except ValueError as e:
        # a registry misconfiguration (reasonless suppression) is a
        # usage error, not a suite violation — rc 2, never rc 1
        print(f'{name}: {e}', file=sys.stderr)
        return 2
    baseline_path = args.baseline or cfg.baseline
    if not os.path.isabs(baseline_path):
        baseline_path = os.path.join(root, baseline_path)
    extra = {extra_key: detail} if args.format == 'json' else None
    return _finish(args, violations, baseline_path,
                   suppressed=len(suppressed), extra=extra)


def _main_mosaic(args, root):
    # imported here: mosaiclint needs jax, plain tracelint must not
    from .mosaic import lint_and_report
    from .mosaic.registry import entries_for
    from .mosaic.rules import all_rules as all_ml_rules

    return _registry_main(args, root, 'mosaiclint',
                          load_mosaic_config(root), all_ml_rules,
                          entries_for, lint_and_report, 'vmem')


def _main_shard(args, root):
    # imported here: shardlint needs jax (it compiles the registry on
    # the virtual mesh), plain tracelint must not
    from .shard import ensure_virtual_devices
    from .shard import lint_and_report
    from .shard.registry import entries_for
    from .shard.rules import all_rules as all_sl_rules

    # set the virtual-device flag BEFORE anything touches the backend;
    # refuse with a recipe (rc 2) when a too-small backend already won
    if not ensure_virtual_devices():
        import jax

        print(f'shardlint: needs 8 devices, found {jax.device_count()} '
              f'(backend initialised first?) — run with XLA_FLAGS='
              f'--xla_force_host_platform_device_count=8 '
              f'JAX_PLATFORMS=cpu', file=sys.stderr)
        return 2

    return _registry_main(args, root, 'shardlint',
                          load_shard_config(root), all_sl_rules,
                          entries_for, lint_and_report, 'comm')


def _main_hlo(args, root):
    # imported here: hlolint needs jax (it compiles the registry, and
    # its xcheck suites need the virtual mesh), plain tracelint must not
    from .hlo import (ensure_virtual_devices, fingerprint_report,
                      lint_and_report, write_fingerprints)
    from .hlo.registry import entries_for
    from .hlo.rules import all_rules as all_hl_rules

    # set the virtual-device flag BEFORE anything touches the backend;
    # refuse with a recipe (rc 2) when a too-small backend already won
    if not ensure_virtual_devices():
        import jax

        print(f'hlolint: needs 8 devices, found {jax.device_count()} '
              f'(backend initialised first?) — run with XLA_FLAGS='
              f'--xla_force_host_platform_device_count=8 '
              f'JAX_PLATFORMS=cpu', file=sys.stderr)
        return 2

    cfg = load_hlo_config(root)
    fp_path = cfg.fingerprints
    if not os.path.isabs(fp_path):
        fp_path = os.path.join(root, fp_path)
    if args.write_fingerprints:
        try:
            entries = entries_for(args.paths or cfg.paths or None,
                                  root=root)
            fps = fingerprint_report(entries, root=root)
        except Exception as e:  # noqa: BLE001 - a broken suite must not
            # be silently baselined around
            print(f'hlolint: --write-fingerprints failed: '
                  f'{type(e).__name__}: {e}', file=sys.stderr)
            return 2
        write_fingerprints(fps, fp_path)
        print(f'hlolint: wrote {len(fps)} fingerprint(s) across '
              f'{len(entries)} suite(s) to {fp_path}')
        return 0

    def lint_fn(entries, rules=None, root=None):
        return lint_and_report(entries, rules=rules, root=root,
                               fingerprint_path=fp_path)

    return _registry_main(args, root, 'hlolint', cfg, all_hl_rules,
                          entries_for, lint_fn, 'artifacts')


def _main_state(args, root):
    # imported here: statelint's live wire-schema extraction needs jax
    # (it instantiates tiny CPU engines), plain tracelint must not;
    # the registry/rules imports themselves stay stdlib-only
    from .state import lint_and_report
    from .state.registry import entries_for
    from .state.rules import all_rules as all_st_rules

    return _registry_main(args, root, 'statelint',
                          load_state_config(root), all_st_rules,
                          entries_for, lint_and_report, 'state')


def _main_all(args, root):
    """The unified runner: every family in sequence, one report.

    JSON schema: {"schema": 1, "rc": combined, "families": [{"family",
    "rc", <that family's own JSON report>}...]}; text mode prints each
    family's text report under a header plus a summary table.
    Combined rc: 1 if any family found new errors, else 2 if any
    family failed outright, else 0 — so one exit code gates CI."""
    import contextlib
    import io
    import json

    if (args.write_baseline or args.write_fingerprints or args.baseline
            or args.select or args.paths):
        print('tracelint: --all runs every family with its own config;'
              ' per-family flags (paths/--select/--baseline/--write-*)'
              ' need a single-family invocation', file=sys.stderr)
        return 2

    flags = ['--root', root, '--format', 'json']
    if args.no_baseline:
        flags.append('--no-baseline')
    rows, combined = [], []
    for family, flag in (('tracelint', None), ('mosaiclint', '--mosaic'),
                         ('shardlint', '--shard'), ('hlolint', '--hlo'),
                         ('statelint', '--state')):
        buf, err = io.StringIO(), io.StringIO()
        with contextlib.redirect_stdout(buf), \
                contextlib.redirect_stderr(err):
            try:
                rc = main(([flag] if flag else []) + flags)
            except SystemExit as e:  # argparse or a deep sys.exit
                rc = int(e.code or 0)
        try:
            report = json.loads(buf.getvalue())
        except ValueError:
            report = {'error': (err.getvalue() or buf.getvalue()).strip()}
        rows.append({'family': family, 'rc': rc, **report})
        combined.append(rc)
    rc = (1 if any(c == 1 for c in combined)
          else 2 if any(c not in (0, 1) for c in combined) else 0)
    if args.format == 'json':
        print(json.dumps({'schema': 1, 'rc': rc, 'families': rows},
                         indent=2))
    else:
        for row in rows:
            status = ('clean' if row['rc'] == 0
                      else 'GATE' if row['rc'] == 1 else 'ERROR')
            n_new = len(row.get('violations', []))
            print(f"{row['family']:<12} rc={row['rc']} [{status}] "
                  f"new={n_new} baselined={row.get('baselined', 0)} "
                  f"suppressed={row.get('suppressed', 0)}"
                  + (f" error={row['error']}" if 'error' in row else ''))
            for v in row.get('violations', []):
                print(f"  {v['path']}:{v['line']}: {v['rule']} "
                      f"[{v['severity']}] {v['message']}")
    return rc


def main(argv=None):
    args = _build_parser().parse_args(argv)
    picked = [f for f, on in (('--mosaic', args.mosaic),
                              ('--shard', args.shard),
                              ('--hlo', args.hlo),
                              ('--state', args.state),
                              ('--all', args.all)) if on]
    if len(picked) > 1:
        # one invocation = one analyzer family; last-flag-wins would
        # silently skip a whole family in CI
        print(f'tracelint: {" and ".join(picked)} are mutually '
              f'exclusive — pick one analyzer per invocation (--all '
              f'runs all five)', file=sys.stderr)
        return 2
    if args.list_rules:
        if args.mosaic:
            from .mosaic.rules import all_rules as all_ml_rules

            rules = all_ml_rules()
        elif args.shard:
            from .shard.rules import all_rules as all_sl_rules

            rules = all_sl_rules()
        elif args.hlo:
            from .hlo.rules import all_rules as all_hl_rules

            rules = all_hl_rules()
        elif args.state:
            from .state.rules import all_rules as all_st_rules

            rules = all_st_rules()
        else:
            rules = all_rules()
        for rule in rules:
            print(f'{rule.id} [{rule.severity}] {rule.name}: '
                  f'{rule.description}')
        return 0

    root = os.path.abspath(args.root or os.getcwd())
    if args.all:
        return _main_all(args, root)
    if args.mosaic:
        return _main_mosaic(args, root)
    if args.shard:
        return _main_shard(args, root)
    if args.hlo:
        return _main_hlo(args, root)
    if args.state:
        return _main_state(args, root)
    return _main_tracelint(args, root)


def mosaic_main(argv=None):
    """Entry point for the `mosaiclint` console script."""
    argv = list(sys.argv[1:] if argv is None else argv)
    return main(['--mosaic'] + argv)


def shard_main(argv=None):
    """Entry point for the `shardlint` console script."""
    argv = list(sys.argv[1:] if argv is None else argv)
    return main(['--shard'] + argv)


def hlo_main(argv=None):
    """Entry point for the `hlolint` console script."""
    argv = list(sys.argv[1:] if argv is None else argv)
    return main(['--hlo'] + argv)


def state_main(argv=None):
    """Entry point for the `statelint` console script."""
    argv = list(sys.argv[1:] if argv is None else argv)
    return main(['--state'] + argv)


if __name__ == '__main__':
    sys.exit(main())
